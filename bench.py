"""Headline benchmark: candidate acquisitions/sec/chip of the fused
on-device tuning engine.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

`vs_baseline` is value / 100_000 — the north-star floor from
BASELINE.json ("≥100k candidate acquisitions/sec on a v4-8"); the
reference generates proposals sequentially, one config per technique call
per instance (opentuner/search/driver.py:160-207), with per-proposal SQL
dedup, so its own throughput is O(100/s) per CPU core.

An acquisition here is the FULL per-candidate pipeline, not just RNG:
propose (technique operator kernels) -> hash -> dedup vs a 2^15-entry
history -> objective eval -> technique observe -> best update, all fused
into one lax.scan program.

Run on whatever platform JAX selects (TPU under the driver harness); pass
--cpu to force the virtual CPU platform.
"""
import json
import sys
import time


def main() -> None:
    if "--cpu" in sys.argv:
        import os
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "scripts"))
        import cpuenv  # noqa: F401
    import jax

    from uptune_tpu.engine import FusedEngine, default_arms
    from uptune_tpu.workloads import rosenbrock_device, rosenbrock_space

    # 16-D rosenbrock, arms scaled so each step acquires ~6k candidates:
    # big enough to fill the chip, small enough that dedup history (2^15)
    # holds several steps' worth
    quick = "--quick" in sys.argv
    space = rosenbrock_space(16, -5.0, 5.0)
    eng = FusedEngine(space, lambda v, p: rosenbrock_device(v),
                      arms=default_arms(scale=4 if quick else 64),
                      history_capacity=1 << (12 if quick else 15))

    steps = 20 if quick else 200
    state = eng.init(jax.random.PRNGKey(0))
    run = jax.jit(lambda s: eng.run(s, steps))
    state = run(state)                      # compile + warm
    jax.block_until_ready(state)

    best_t = float("inf")
    reps = 1 if quick else 3
    for _ in range(reps):
        s = eng.init(jax.random.PRNGKey(1))
        jax.block_until_ready(s)
        t0 = time.perf_counter()
        s = run(s)
        jax.block_until_ready(s)
        best_t = min(best_t, time.perf_counter() - t0)

    acqs = steps * eng.total_batch
    rate = acqs / best_t
    print(json.dumps({
        "metric": "candidate_acquisitions_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": "configs/s",
        "vs_baseline": round(rate / 100_000.0, 3),
    }))


if __name__ == "__main__":
    main()
