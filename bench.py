"""Headline benchmark: candidate acquisitions/sec/chip of the fused
on-device tuning engine.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
     "platform": "tpu"|"cpu"|"cpu:fallback", "quick": bool}

`vs_baseline` is value / 100_000 — the north-star floor from
BASELINE.json ("≥100k candidate acquisitions/sec on a v4-8"); the
reference generates proposals sequentially, one config per technique call
per instance (opentuner/search/driver.py:160-207), with per-proposal SQL
dedup, so its own throughput is O(100/s) per CPU core.

An acquisition here is the FULL per-candidate pipeline, not just RNG:
propose (technique operator kernels) -> hash -> dedup vs a 2^15-entry
history -> objective eval -> technique observe -> best update, all fused
into one lax.scan program.

Backend selection is defensive: the TPU tunnel on this machine can be
wedged (BENCH_r01 failed with "Unable to initialize backend 'axon'"), so
we probe the backend with a bounded retry and fall back to CPU with an
explicit `platform: "cpu"` label — a CPU number can never masquerade as
the TPU number.  Pass --cpu to force the virtual CPU platform.
"""
import json
import os
import sys
import time


def _probe_accelerator(timeout_s: float = 90.0) -> str:
    """Check in a SUBPROCESS whether the accelerator backend initializes.

    A wedged TPU tunnel makes jax.devices() hang (not raise) — exactly
    what killed BENCH_r01 — so the probe must be killable.  Returns the
    platform name on success, '' on failure/timeout.
    """
    import subprocess
    code = ("import jax; d = jax.devices()[0]; "
            "print('UT_PLATFORM=' + d.platform)")
    for attempt in range(2):
        try:
            out = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                text=True, timeout=timeout_s)
            for line in out.stdout.splitlines():
                if line.startswith("UT_PLATFORM="):
                    plat = line.split("=", 1)[1].strip()
                    if plat and plat != "cpu":
                        return plat
            print(f"bench: probe attempt {attempt + 1} got no accelerator "
                  f"(rc={out.returncode}): {out.stderr.strip()[-300:]}",
                  file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"bench: probe attempt {attempt + 1} hung "
                  f">{timeout_s:.0f}s (wedged TPU tunnel?)",
                  file=sys.stderr)
        time.sleep(2.0)
    return ""


def _init_backend(cpu_flag: bool):
    """Import jax and return (jax, platform_name).  Never hangs: the
    accelerator is probed in a killable subprocess first; on failure we
    fall back to CPU with an explicit label."""
    from uptune_tpu.utils.platform_guard import force_cpu

    if cpu_flag:
        force_cpu(8)
        import jax
        return jax, "cpu"

    plat = _probe_accelerator()
    if plat:
        import jax
        return jax, jax.devices()[0].platform
    print("bench: accelerator unavailable; falling back to CPU — result "
          "is labeled platform=cpu:fallback and does NOT stand in for "
          "the TPU number", file=sys.stderr)
    force_cpu(1)
    import jax
    return jax, "cpu:fallback"


def main() -> None:
    quick = "--quick" in sys.argv
    jax, platform = _init_backend(cpu_flag="--cpu" in sys.argv)
    if platform == "cpu:fallback":
        # the fallback number is explicitly labeled and never stands in
        # for the TPU result; run it at quick size so a wedged tunnel
        # can't also push the driver's bench step into a timeout
        quick = True

    from uptune_tpu.engine import FusedEngine, default_arms
    from uptune_tpu.workloads import rosenbrock_device, rosenbrock_space

    # 16-D rosenbrock, arms scaled so each step acquires ~6k candidates:
    # big enough to fill the chip, small enough that dedup history (2^15)
    # holds several steps' worth
    space = rosenbrock_space(16, -5.0, 5.0)
    eng = FusedEngine(space, lambda v, p: rosenbrock_device(v),
                      arms=default_arms(scale=4 if quick else 64),
                      history_capacity=1 << (12 if quick else 15))

    steps = 20 if quick else 200
    state = eng.init(jax.random.PRNGKey(0))
    run = jax.jit(lambda s: eng.run(s, steps))
    state = run(state)                      # compile + warm
    jax.block_until_ready(state)

    best_t = float("inf")
    reps = 1 if quick else 3
    for _ in range(reps):
        s = eng.init(jax.random.PRNGKey(1))
        jax.block_until_ready(s)
        t0 = time.perf_counter()
        s = run(s)
        jax.block_until_ready(s)
        best_t = min(best_t, time.perf_counter() - t0)

    acqs = steps * eng.total_batch
    rate = acqs / best_t
    print(json.dumps({
        "metric": "candidate_acquisitions_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": "configs/s",
        "vs_baseline": round(rate / 100_000.0, 3),
        "platform": platform,
        "quick": quick,
    }))


if __name__ == "__main__":
    main()
