"""Headline benchmark: candidate acquisitions/sec/chip of the fused
on-device tuning engine.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
     "platform": "tpu"|"cpu"|"cpu:fallback", "quick": bool, ...}

`vs_baseline` is value / 100_000 — the north-star floor from
BASELINE.json ("≥100k candidate acquisitions/sec on a v4-8"); the
reference generates proposals sequentially, one config per technique call
per instance (opentuner/search/driver.py:160-207), with per-proposal SQL
dedup, so its own throughput is O(100/s) per CPU core.

An acquisition here is the FULL per-candidate pipeline, not just RNG:
propose (technique operator kernels) -> hash -> dedup vs a 2^15-entry
history -> objective eval -> technique observe -> best update, all fused
into one lax.scan program.

Evidence + utilization: when the run lands on an accelerator, the raw
measurement (per-rep wall times, device repr, XLA cost analysis,
roofline utilization) is written to BENCH_TPU.json so the headline
number is backed by a checked-in artifact rather than a claim.  The
utilization story comes from XLA's own cost model for the compiled
program (flops + bytes accessed per step): this engine is an
elementwise/gather workload, so the roofline-relevant axis is HBM
bandwidth, with MXU FLOP utilization reported for completeness.

Backend selection is defensive: the TPU tunnel on this machine can be
wedged (BENCH_r01 rc=1; BENCH_r02 probe hung >90s twice), so the backend
is probed in killable subprocesses with exponential backoff spanning
minutes (budget via UT_BENCH_PROBE_BUDGET_S, default 240s) before
falling back to CPU with an explicit `platform: "cpu:fallback"` label —
a CPU number can never masquerade as the TPU number.  `--wait-for-tpu`
extends the budget to hours for manual capture sessions; `--cpu` skips
the probe and forces the virtual CPU platform.
"""
import json
import os
import sys
import time

# Cost/memory harvest and the per-platform roofline peak table live in
# uptune_tpu.obs.device since ISSUE 13 (shared with the engine-plane
# compile telemetry, `ut top`'s device panel and `ut report`'s device
# section); bench.py is a consumer, not an owner.  obs.device imports
# no jax at module load, so this is safe before backend selection.
from uptune_tpu.obs import device as obs_device  # noqa: E402


def _probe_accelerator(budget_s: float) -> str:
    """Check in SUBPROCESSES whether the accelerator backend initializes.

    A wedged TPU tunnel makes jax.devices() hang (not raise) — exactly
    what killed BENCH_r01 — so each probe must be killable.  Retries
    with exponential backoff until `budget_s` is spent (a transient
    tunnel wedge should not cost the round its TPU number, VERDICT r2
    next-step #1).  Returns the platform name on success, '' on
    failure/timeout.
    """
    import subprocess
    code = ("import jax; d = jax.devices()[0]; "
            "print('UT_PLATFORM=' + d.platform)")
    deadline = time.monotonic() + budget_s
    attempt = 0
    clean_cpu = 0
    probe_timeout, sleep_s = 90.0, 5.0
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        if attempt > 1 and remaining <= 10.0:
            return ""  # always make at least one real attempt
        tmo = max(10.0, min(probe_timeout, remaining))
        try:
            out = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                text=True, timeout=tmo)
            answered = ""
            for line in out.stdout.splitlines():
                if line.startswith("UT_PLATFORM="):
                    answered = line.split("=", 1)[1].strip()
                    if answered and answered != "cpu":
                        return answered
            if out.returncode == 0 and answered == "cpu":
                # a clean deterministic "cpu" answer means there is no
                # accelerator on this machine at all — unlike a hang or
                # crash (possibly-transient tunnel wedge), retrying for
                # the whole budget would just stall a TPU-less box
                clean_cpu += 1
                if clean_cpu >= 2:
                    print("bench: backend cleanly reports cpu-only twice; "
                          "not retrying further", file=sys.stderr)
                    return ""
            print(f"bench: probe attempt {attempt} got no accelerator "
                  f"(rc={out.returncode}): {out.stderr.strip()[-300:]}",
                  file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"bench: probe attempt {attempt} hung "
                  f">{tmo:.0f}s (wedged TPU "
                  f"tunnel?), {max(0.0, deadline - time.monotonic()):.0f}s "
                  f"of probe budget left", file=sys.stderr)
        time.sleep(min(sleep_s, max(0.0, deadline - time.monotonic())))
        sleep_s = min(sleep_s * 2, 120.0)
        probe_timeout = min(probe_timeout * 2, 300.0)


def _init_backend(cpu_flag: bool, wait_for_tpu: bool, budget_s=None):
    """Import jax and return (jax, platform_name).  Never hangs on the
    probe: the accelerator is checked in killable subprocesses first;
    on failure we fall back to CPU with an explicit label.  (A tunnel
    that wedges in the window between a successful probe and the
    in-process backend init can still block — irreducible for any
    check that must actually run on the accelerator.)  `budget_s`
    overrides the probe budget (also used by __graft_entry__.entry)."""
    from uptune_tpu.utils.platform_guard import force_cpu

    if cpu_flag:
        force_cpu(8)
        import jax
        return jax, "cpu"

    # default sized so probe + quick CPU fallback stays well inside the
    # driver's bench step budget (commit e470740's concern): ~4 min of
    # probing, then the fallback still produces its labeled JSON line
    budget = (float(budget_s) if budget_s is not None
              else float(os.environ.get("UT_BENCH_PROBE_BUDGET_S", "240")))
    if wait_for_tpu:
        budget = max(budget, 3 * 3600.0)
    plat = _probe_accelerator(budget)
    if plat:
        import jax
        return jax, jax.devices()[0].platform
    print("bench: accelerator unavailable; falling back to CPU — result "
          "is labeled platform=cpu:fallback and does NOT stand in for "
          "the TPU number", file=sys.stderr)
    force_cpu(1)
    import jax
    return jax, "cpu:fallback"


def _roofline_fields(harv, device_kind, wall_s):
    """The artifact's cost_analysis section, from one obs.device
    harvest of the measured program: XLA's cost model (flops/bytes)
    and the executable's own memory plan, with achieved rates over
    the MEASURED (blocked, best-of-reps) wall and utilization against
    the shared per-platform peak table."""
    flops, nbytes = harv["flops"], harv["bytes_accessed"]
    flops_per_s = flops / wall_s if flops else None
    bytes_per_s = nbytes / wall_s if nbytes else None
    return {
        "total_flops": flops,
        "total_bytes_accessed": nbytes,
        "flops_per_s": flops_per_s,
        "bytes_per_s": bytes_per_s,
        "arith_intensity": harv["arith_intensity"],
        "peak_memory": harv["peak_memory"],
        **obs_device.utilization(device_kind, flops_per_s,
                                 bytes_per_s),
        "source": "uptune_tpu.obs.device.harvest: XLA cost_analysis "
                  "+ memory_analysis over this exact compiled "
                  "program; rates over the measured best-of-reps "
                  "wall (block_until_ready-bounded)",
    }


def _obs_merged_example(repo: str) -> dict:
    """Produce the committed distributed-trace artifact
    (exp_archives/obs_trace_merged_example.json): REAL telemetry from
    four distinct OS processes — a traced `ut` driver run (whose worker
    lanes carry reap-merged child sidecar spans), one standalone worker
    child's own sidecar shard, a `ut serve` server shut down by SIGINT
    (exercising the exit-flush path), and a traced client whose
    requests carry trace context — joined by `ut-trace merge` with
    clock-offset alignment.  Returns the manifest recorded into
    BENCH_OBS.json; the document is validate_trace-clean or this
    raises."""
    import re
    import signal
    import subprocess
    import tempfile
    import textwrap

    from uptune_tpu.obs import merge as obs_merge
    from uptune_tpu.utils.pypath import child_pythonpath

    work = tempfile.mkdtemp(prefix="ut_obs_merged")
    prog = os.path.join(work, "prog.py")
    with open(prog, "w") as f:
        f.write(textwrap.dedent("""
            import uptune_tpu as ut
            x = ut.tune(50, (0, 100), name="x")
            y = ut.tune(50, (0, 100), name="y")
            ut.target(float((x - 37) ** 2 + (y - 11) ** 2), "min")
        """))
    env = {k: v for k, v in os.environ.items()
           if k not in ("UT_TRACE", "UT_TRACE_GUARD", "UT_TRACE_SIDECAR",
                        "UT_PROCESS_ID")}
    env.update(PYTHONPATH=child_pythonpath(), JAX_PLATFORMS="cpu")

    # shard 1: the driver — a traced `ut` run (2 worker slots)
    tune_trace = os.path.join(work, "tune_trace.json")
    r = subprocess.run(
        [sys.executable, "-m", "uptune_tpu.cli", prog, "--test-limit",
         "6", "-pf", "2", "--store", "off", "--trace", tune_trace,
         "--work-dir", work], env=env, cwd=work, capture_output=True,
        text=True, timeout=600)
    if r.returncode != 0 or not os.path.isfile(tune_trace):
        raise RuntimeError(f"driver shard failed:\n{r.stdout}\n{r.stderr}")

    # shard 2: one worker child's OWN sidecar (reap consumes the tune's
    # sidecars after folding them into the driver shard, so run one
    # trial standalone against a sandbox the tune already populated)
    child_shard = os.path.join(work, "child_shard.jsonl")
    sandbox = os.path.join(work, "ut.temp", "temp.0")
    cenv = dict(env, UT_TUNE_START="True", UT_CURR_INDEX="0",
                UT_CURR_STAGE="0", UT_GLOBAL_ID="9001",
                UT_WORK_DIR=sandbox, UT_TRACE_SIDECAR=child_shard)
    r = subprocess.run([sys.executable, prog], env=cenv, cwd=sandbox,
                       capture_output=True, text=True, timeout=120)
    if r.returncode != 0 or not os.path.isfile(child_shard):
        raise RuntimeError(f"child shard failed:\n{r.stdout}\n{r.stderr}")

    # shards 3+4: `ut serve` + a traced client over real TCP; the
    # server is stopped with SIGINT, so its shard exists only because
    # the exit flush works (the satellite, exercised for real)
    srv_trace = os.path.join(work, "srv_trace.json")
    cli_trace = os.path.join(work, "client_trace.json")
    srv = subprocess.Popen(
        [sys.executable, "-m", "uptune_tpu.serve.cli", "--port", "0",
         "--slots", "2", "--store-dir", "off", "--trace", srv_trace],
        env=env, cwd=work, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        port = None
        t0 = time.time()
        while time.time() - t0 < 120:
            line = srv.stderr.readline()
            if not line:
                break
            m = re.search(r"listening on [^:]+:(\d+)", line)
            if m:
                port = int(m.group(1))
                break
        if port is None:
            raise RuntimeError("serve shard: no listening line")
        client_py = os.path.join(work, "client.py")
        with open(client_py, "w") as f:
            f.write(textwrap.dedent(f"""
                from uptune_tpu import obs
                from uptune_tpu.serve.client import connect
                from uptune_tpu.workloads import rosenbrock_space
                obs.enable()
                c = connect(("127.0.0.1", {port}))
                s = c.open_session(rosenbrock_space(2, -2.0, 2.0),
                                   seed=3, program="merged-example",
                                   store=False)
                for _ in range(3):
                    for t in s.ask(2):
                        s.tell(t.ticket, sum(v * v
                               for v in t.config.values()))
                c.metrics(format="prometheus")
                s.close(); c.close()
                obs.write_trace({cli_trace!r},
                                extra={{"process": "ut-client"}})
            """))
        r = subprocess.run([sys.executable, client_py], env=env,
                           cwd=work, capture_output=True, text=True,
                           timeout=300)
        if r.returncode != 0 or not os.path.isfile(cli_trace):
            raise RuntimeError(
                f"client shard failed:\n{r.stdout}\n{r.stderr}")
    finally:
        srv.send_signal(signal.SIGINT)
        try:
            srv.wait(timeout=60)
        except subprocess.TimeoutExpired:
            srv.kill()
            srv.wait(timeout=30)
    if not os.path.isfile(srv_trace):
        raise RuntimeError("serve shard: SIGINT flush left no trace")

    out = os.path.join(repo, "exp_archives",
                       "obs_trace_merged_example.json")
    doc = obs_merge.merge_files(
        [tune_trace, child_shard, srv_trace, cli_trace], out=out)
    manifest = doc["otherData"]["merged"]
    procs = {s["process"] for s in manifest}
    if len(procs) < 3:
        raise RuntimeError(f"merged example spans only {procs}")
    if doc["otherData"]["joins"] < 1:
        raise RuntimeError("no client/server span joins in the merged "
                           "example")
    return {"file": "exp_archives/obs_trace_merged_example.json",
            "processes": sorted(procs),
            "shards": [{k: s[k] for k in ("process", "events",
                                          "offset_s")}
                       for s in manifest],
            "events": len(doc["traceEvents"]),
            "client_server_joins": doc["otherData"]["joins"]}


def obs_main() -> None:
    """`bench.py --obs`: the observability-plane overhead benchmark —
    the cost of the instrumentation itself, in both of its states
    (ISSUE 7 hard requirement).  Writes BENCH_OBS.json.

    Phase 1 (disabled path): the exact BENCH_DRIVER protocol — warm
    200 trials, then timed ask/tell against the instant dummy
    evaluator — with every obs call site present but tracing OFF.
    Compared against the committed BENCH_DRIVER.json 4607.9 asks/s:
    the disabled path must be indistinguishable from the
    pre-instrumentation driver (one module-flag check per call site).

    Phase 2 (enabled path): same protocol, same process, tracing ON
    with the full span/counter stream recording into the per-thread
    rings AND the metrics flight recorder appending timeline rows in
    the background (the ISSUE 10 deployment shape).  Must hold >= 95%
    of the disabled-path rate.

    Phase 3 (full runs only): the async-surrogate warm-window check —
    the PR 5 protocol (rosenbrock-2d, calibrated opts at max_points
    512, 2 virtual devices, lockstep tells) WITH tracing enabled; the
    learning-attributable warm refit-window tell p95
    (StepStats.t_refit) must stay in the BENCH_SURROGATE.json ~1.6 ms
    class, proving tracing does not tax the tell path the async plane
    just cleared.  This phase's trace is exported as the committed
    example artifact (exp_archives/obs_trace_example.json) — driver
    lane + refit-worker lane, validated by the schema test.

    Phase 4 (full runs only): the distributed-trace artifact — a
    traced driver run, a worker child's sidecar shard, a SIGINT'd
    `ut serve` server and a traced client, merged by `ut-trace merge`
    into exp_archives/obs_trace_merged_example.json (ISSUE 10).

    Run under UT_TRACE_GUARD=strict to also prove tracing adds no
    retraces."""
    quick = "--quick" in sys.argv
    from uptune_tpu.utils.platform_guard import force_cpu
    # 2 virtual devices: phase 3 is the async-surrogate deployment
    # shape (driver on 0, background fits on 1); phases 1-2 only use
    # device 0 (identical to the BENCH_DRIVER box's nproc=2)
    force_cpu(2)
    import jax  # noqa: F401  (backend must init after force_cpu)
    import numpy as np

    from uptune_tpu import obs
    from uptune_tpu.analysis.trace_guard import guard_from_env

    pct = lambda a, p: (round(float(np.percentile(a, p)), 3)  # noqa: E731
                        if len(a) else None)

    # one guard per tuner-building phase (the cache_main rule): phase 3
    # builds a SECOND Tuner whose per-arm wrappers come from the same
    # code objects as phase 1's — under ONE guard that reads as
    # rebuild churn even though each tuner compiles once
    with guard_from_env() as guard:
        from uptune_tpu.driver import Tuner
        from uptune_tpu.workloads import rosenbrock_space

        space = rosenbrock_space(8, -3.0, 3.0)
        tuner = Tuner(space, None, seed=0)
        lats = []

        def drain(n):
            done = 0
            while done < n:
                for tr in tuner.ask(min_trials=1):
                    t0 = time.perf_counter()
                    tuner.tell(tr, float((tr.gid * 2654435761) % 1000))
                    lats.append(time.perf_counter() - t0)
                    done += 1
            return done

        # full-mode window matches the BENCH_DRIVER steady phase (2000
        # trials) so the cross-artifact asks/s comparison is
        # like-for-like in measurement length
        window = 500 if quick else 2000
        # 5 reps per mode since ISSUE 10 (was 3): this box's
        # co-tenant throughput swings got wider (~2x within a single
        # run's reps), and best-of needs more draws to catch each
        # mode's uncontended rate
        reps = 3 if quick else 5
        drain(200)                      # compile warmup (both phases)

        def timed_window():
            lats.clear()
            t0 = time.perf_counter()
            n = drain(window)
            dt = time.perf_counter() - t0
            return (n / dt, dt, n,
                    pct([x * 1e3 for x in lats], 50),
                    pct([x * 1e3 for x in lats], 95))

        # ALTERNATING disabled/enabled windows, best-of-reps per mode:
        # this box's throughput swings ~2x with co-tenant load
        # (BENCH_r0* history), so back-to-back single phases would
        # measure the weather — interleaving puts both modes under the
        # same bursts and min-wall picks each mode's uncontended rate
        # (the same best-of-reps rule as the engine benches).  The
        # enabled windows ALSO run the metrics flight recorder (the
        # deployment shape since ISSUE 10: tracing on means the
        # background timeline thread is on), so the >= 0.95 bar prices
        # in its periodic window_snapshot + disk append
        import itertools
        import tempfile
        d_reps, e_reps, j_reps = [], [], []
        events_recorded = events_dropped = 0
        flight_rows = 0
        journal_rows = 0
        device_dispatches = 0
        fdir = tempfile.mkdtemp(prefix="ut_bench_obs")

        def win_disabled(rep):
            d_reps.append(timed_window())

        def win_enabled(rep):
            nonlocal events_recorded, events_dropped, flight_rows
            nonlocal device_dispatches
            obs.enable(capacity=1 << 18)
            rec = obs.start_flight_recorder(
                os.path.join(fdir, f"rep{rep}.json"), interval=0.25)
            e_reps.append(timed_window())
            rec.stop()
            flight_rows = max(flight_rows, rec.rows_written)
            snap = obs.snapshot()
            events_recorded = len(snap["events"])
            events_dropped = sum(snap["dropped"].values())
            # device telemetry rides the enabled path (ISSUE 13): the
            # driver's instrumented programs record every dispatch —
            # the >= 0.95 bar prices that in too
            device_dispatches = max(
                device_dispatches,
                obs.metrics_snapshot()["counters"].get(
                    "device.dispatches", 0))
            obs.reset()

        def win_journal(rep):
            # journal window (ISSUE 12): tracing + flight recorder +
            # the tuning journal with its QualityMonitor sink — the
            # full search-quality deployment shape.  The >= 0.95 bar
            # applies to THIS mode too: journal emission must stay off
            # the device hot path
            nonlocal journal_rows
            obs.enable(capacity=1 << 18)
            rec = obs.start_flight_recorder(
                os.path.join(fdir, f"rep{rep}.j.json"), interval=0.25)
            jmon = obs.start_journal(
                os.path.join(fdir, f"rep{rep}.journal.jsonl"),
                meta={"protocol": "bench --obs journal window"})
            j_reps.append(timed_window())
            obs.journal.flush()
            journal_rows = max(journal_rows, sum(
                1 for _ in open(obs.journal.path())) - 1)
            obs.stop_journal(jmon)
            rec.stop()
            obs.reset()

        # the three modes ROTATE position within each rep: a fixed
        # d->e->j order would hand the same within-rep drift (turbo /
        # co-tenant ramp) to the same mode every rep, and best-of-reps
        # cannot wash out a bias that is correlated with position
        order = itertools.cycle([win_disabled, win_enabled,
                                 win_journal])
        for rep in range(reps):
            start = next(order)
            wins = [start, next(order), next(order)]
            for w in wins:
                w(rep)
            next(order)  # advance so rep r+1 starts one mode later

        def mode_result(rs):
            best = max(rs, key=lambda r: r[0])
            return {"asks_per_sec": round(best[0], 1),
                    "wall_s": round(best[1], 4), "trials": best[2],
                    "tell_p50_ms": best[3], "tell_p95_ms": best[4],
                    "rep_asks_per_sec": [round(r[0], 1) for r in rs]}

        disabled = mode_result(d_reps)
        enabled = mode_result(e_reps)
        enabled["events_recorded"] = events_recorded
        enabled["events_dropped"] = events_dropped
        enabled["flight_recorder"] = {"interval_s": 0.25,
                                      "rows_per_window": flight_rows}
        enabled["device_dispatches_per_window"] = device_dispatches
        journaled = mode_result(j_reps)
        journaled["journal_rows_per_window"] = journal_rows

    surro = None
    with guard_from_env() as guard3:
        if not quick:
            # phase 3: PR 5 warm-window protocol WITH tracing enabled
            from uptune_tpu.calibrated import CALIBRATED_OPTS
            from uptune_tpu.workloads import rosenbrock_objective
            sopts = dict(CALIBRATED_OPTS, max_points=512,
                         async_refit=True)
            obj = rosenbrock_objective(2)
            sp2 = rosenbrock_space(2, -2.048, 2.048)
            obs.enable(capacity=1 << 18)
            # ISSUE 12: phase 3 runs with the journal on too, so the
            # per-ticket mu/sigma predict join is priced into the
            # traced tell p95 (and traces once per bucket under the
            # strict guard)
            jmon3 = obs.start_journal(
                os.path.join(fdir, "phase3.journal.jsonl"),
                meta={"protocol": "bench --obs phase 3"})
            t2 = Tuner(sp2, None, seed=0, surrogate="gp",
                       surrogate_opts=sopts)
            sm = t2.surrogate
            blocked, windows, warm = [], [], []
            seen_buckets = set()
            done = 0
            trials3 = 600
            while done < trials3:
                for tr in t2.ask(min_trials=1):
                    if done >= trials3:
                        t2.cancel(tr)
                        continue
                    starts0 = sm.refits_started
                    stats = t2.tell(tr, float(obj([tr.config])[0]))
                    blocked.append(stats.t_refit * 1e3
                                   if stats is not None else 0.0)
                    w = sm.refits_started > starts0
                    windows.append(w)
                    if w:
                        bkt = sm.fit_bucket()
                        warm.append(bkt in seen_buckets)
                        seen_buckets.add(bkt)
                    else:
                        warm.append(False)
                    done += 1
            t2.close()
            wb = [b for b, w in zip(blocked, warm) if w]
            surro = {
                "tells": done,
                "refit_windows": int(sum(windows)),
                "warm_refit_windows": int(sum(warm)),
                "refit_blocked_warm_p50_ms": pct(wb, 50),
                "refit_blocked_warm_p95_ms": pct(wb, 95),
                "full_fits_published": sm.refits,
                "incremental_updates": sm.incr_updates,
            }
            # the committed example trace: driver lane + refit-worker
            # lane over a real async tune (schema-validated by
            # tests/test_obs.py against this exact file)
            trace_path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "exp_archives", "obs_trace_example.json")
            doc = obs.write_trace(trace_path, extra={
                "protocol": "bench.py --obs phase 3 (async surrogate, "
                            "rosenbrock-2d, 600 lockstep tells)"})
            surro["trace_file"] = "exp_archives/obs_trace_example.json"
            surro["trace_events"] = len(doc["traceEvents"])
            obs.journal.flush()
            surro["journal_rows"] = sum(
                1 for _ in open(obs.journal.path())) - 1
            obs.stop_journal(jmon3)     # finalizes the cadence gauges
            surro["quality_gauges"] = {
                k: v for k, v in sorted(jmon3.gauges.items())
                if not k.startswith("search.arm_")}
            # phase 3 builds its Tuner WITH tracing on, so the device
            # layer harvests every driver program at compile time
            # (ISSUE 13): cost fields + compile spans, recorded here
            # as the artifact's compile-telemetry evidence
            progs = obs.device.programs()
            surro["device"] = {
                "programs_harvested": sorted(
                    k for k, r in progs.items() if r["cost"]),
                "compiles": sum(r["compiles"] for r in progs.values()),
                "compile_s": round(sum(r["compile_s"]
                                       for r in progs.values()), 3),
                "flops_per_program": {
                    k: r["cost"]["flops"] for k, r in sorted(
                        progs.items()) if r["cost"]},
            }
            obs.reset()

    merged = None
    if not quick:
        # phase 4: the distributed-observability artifact — four real
        # processes (driver, worker child, serve server, serve client)
        # merged into one validate_trace-clean document (ISSUE 10
        # acceptance; the committed example tests/test_obs_distributed
        # validates)
        repo = os.path.dirname(os.path.abspath(__file__))
        merged = _obs_merged_example(repo)

    drv_baseline = None
    drv = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_DRIVER.json")
    try:
        with open(drv) as f:
            drv_baseline = json.load(f)["value"]
    except (OSError, ValueError, KeyError):
        pass
    surro_baseline = None
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(
                __file__)), "BENCH_SURROGATE.json")) as f:
            surro_baseline = json.load(
                f)["async"]["refit_blocked_ms"]["warm_window_p95"]
    except (OSError, ValueError, KeyError):
        pass

    ratio = round(enabled["asks_per_sec"]
                  / max(disabled["asks_per_sec"], 1e-9), 4)
    j_ratio = round(journaled["asks_per_sec"]
                    / max(disabled["asks_per_sec"], 1e-9), 4)
    result = {
        "metric": "obs_enabled_over_disabled_asks_ratio",
        # headline: enabled-tracing throughput as a fraction of the
        # same process's disabled-path throughput (the honest
        # like-for-like; cross-run baselines are reported alongside)
        "value": ratio,
        "unit": "enabled asks/s / disabled asks/s (>= 0.95 required)",
        # ISSUE 12 bar: the SAME ratio with the tuning journal (and
        # its QualityMonitor sink) active on top of tracing — journal
        # emission must stay off the device hot path
        "journal_over_disabled_asks_ratio": j_ratio,
        "platform": "cpu",
        "quick": quick,
        "nproc": os.cpu_count(),
        "protocol": {
            "space": "rosenbrock-8d", "seed": 0,
            "window_trials": window, "reps_per_mode": reps,
            "phases": "1+2 interleaved: BENCH_DRIVER ask/tell "
                      "protocol in alternating disabled/enabled/"
                      "journal windows (obs call sites always "
                      "present; the journal windows add the ISSUE 12 "
                      "tuning journal + quality monitor), mode order "
                      "ROTATING per rep so within-rep drift is not "
                      "correlated with one mode, best-of-reps per "
                      "mode so co-tenant load bursts hit all modes "
                      "alike; 3 (full runs): PR 5 async-surrogate "
                      "warm-window protocol with tracing AND the "
                      "journal enabled",
        },
        "disabled": disabled,
        "enabled": enabled,
        "journal": journaled,
        "driver_asks_per_sec_baseline": drv_baseline,
        "disabled_vs_driver_baseline": (
            round(disabled["asks_per_sec"] / drv_baseline, 4)
            if drv_baseline else None),
        "enabled_vs_driver_baseline": (
            round(enabled["asks_per_sec"] / drv_baseline, 4)
            if drv_baseline else None),
    }
    if surro is not None:
        result["surrogate_traced"] = surro
        result["surrogate_warm_p95_baseline_ms"] = surro_baseline
    if merged is not None:
        result["merged_trace_example"] = merged
    if guard.enabled:
        result["retraces"] = {"driver_phases": guard.report(),
                              "surrogate_phase": guard3.report()}
    name = "BENCH_OBS.quick.json" if quick else "BENCH_OBS.json"
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        name)
    with open(path, "w") as f:
        json.dump({**result, "captured_unix": time.time()}, f, indent=1)
    print(f"bench: observability evidence written to {path}",
          file=sys.stderr)
    print(json.dumps(result))


def report_main() -> None:
    """`bench.py --report`: the search-quality reporting smoke
    (ISSUE 12) — run a small journaled tune end-to-end, hold the
    ONLINE quality gauges to exact equality with an offline replay of
    the journal it wrote, render the HTML + markdown reports, and (on
    full runs) refresh the committed example artifacts
    `exp_archives/obs_journal_example.jsonl` +
    `obs_report_example.html` that tier-1 schema-validates and
    re-renders.  Prints one JSON summary line."""
    quick = "--quick" in sys.argv
    import tempfile

    from uptune_tpu.utils.platform_guard import force_cpu
    force_cpu(1)
    import jax  # noqa: F401  (backend must init after force_cpu)

    from uptune_tpu import obs
    from uptune_tpu.analysis.trace_guard import guard_from_env
    from uptune_tpu.driver import Tuner
    from uptune_tpu.obs import report as obs_report
    from uptune_tpu.workloads import (rosenbrock_objective,
                                      rosenbrock_space)

    repo = os.path.dirname(os.path.abspath(__file__))
    out_dir = (tempfile.mkdtemp(prefix="ut_bench_report") if quick
               else os.path.join(repo, "exp_archives"))
    jpath = os.path.join(out_dir, "obs_journal_example.jsonl")
    evals = 120 if quick else 240
    with guard_from_env() as guard:
        obs.enable(capacity=1 << 18)
        jmon = obs.start_journal(jpath, meta={
            "example": "bench.py --report",
            "workload": "rosenbrock-2d", "evals": evals,
            "surrogate": "gp (sync refit — deterministic artifact)"})
        # sync refit: the committed journal must replay bit-stable
        # relative to its own rows, and a background publish's timing
        # would move which ticket first sees a fitted snapshot
        t = Tuner(rosenbrock_space(2, -2.048, 2.048),
                  rosenbrock_objective(2), seed=0, surrogate="gp",
                  surrogate_opts=dict(min_points=16, refit_interval=32,
                                      max_points=128,
                                      async_refit=False))
        res = t.run(test_limit=evals)
        t.close()
        obs.journal.flush()
        header, rows = obs.journal.read(jpath, strict=True)
        replayed = obs.quality.replay(rows)
        obs.stop_journal(jmon)      # detaches + finalizes the monitor
        online = dict(jmon.gauges)
        obs.reset()
    if online != replayed.gauges:
        diff = {k: (online.get(k), replayed.gauges.get(k))
                for k in set(online) | set(replayed.gauges)
                if online.get(k) != replayed.gauges.get(k)}
        raise RuntimeError(f"online gauges != journal replay: {diff}")
    html_path = os.path.join(out_dir, "obs_report_example.html")
    html = obs_report.render(jpath)
    with open(html_path, "w") as f:
        f.write(html)
    md = obs_report.render(jpath, fmt="md")
    joined = sum(len(r.get("mus") or ())
                 for r in rows if r.get("ev") == "step")
    result = {
        "metric": "report_smoke",
        "value": 1.0,
        "unit": "online gauges == offline journal replay (exact)",
        "quick": quick,
        "evals": res.evals,
        "best_qor": round(res.best_qor, 6),
        "journal_rows": len(rows),
        "calibration_joined_rows": joined,
        "alerts": replayed.alerts,
        "report_html_bytes": len(html),
        "report_md_lines": md.count("\n"),
        "artifacts": (None if quick else
                      ["exp_archives/obs_journal_example.jsonl",
                       "exp_archives/obs_report_example.html"]),
    }
    if guard.enabled:
        result["retraces"] = guard.report()
    print(f"bench: report smoke artifacts in {out_dir}",
          file=sys.stderr)
    print(json.dumps(result))


def driver_main() -> None:
    """`bench.py --driver`: the driver-plane microbenchmark — asks/sec
    through the host Tuner's ask()/tell() surface against an instant
    dummy evaluator (no subprocesses), i.e. the pure dispatch cost an
    external build pipeline has to hide.  Prints ONE JSON line next to
    the fused-plane headline metric and writes BENCH_DRIVER.json; run
    under UT_TRACE_GUARD=strict to also prove the propose/dedup/commit
    programs compile once each (the retrace report lands in both)."""
    quick = "--quick" in sys.argv
    from uptune_tpu.utils.platform_guard import force_cpu
    force_cpu(1)
    import jax  # noqa: F401  (backend must init after force_cpu)

    from uptune_tpu import obs
    from uptune_tpu.analysis.trace_guard import guard_from_env
    trace_out = obs.maybe_enable_from_env()   # UT_TRACE=<path>
    jmon = obs.maybe_journal_from_env()       # UT_JOURNAL=<path>
    with guard_from_env() as guard:
        from uptune_tpu.driver import Tuner
        from uptune_tpu.workloads import rosenbrock_space

        space = rosenbrock_space(8, -3.0, 3.0)
        tuner = Tuner(space, None, seed=0)

        def drain(n):
            done = 0
            while done < n:
                for tr in tuner.ask(min_trials=1):
                    # deterministic dummy QoR stream: spread over [0,
                    # 1000) so new-bests happen early then rarify, like
                    # a real tune
                    tuner.tell(tr, float((tr.gid * 2654435761) % 1000))
                    done += 1
            return done

        # bench phases land on the obs timeline (spans are no-ops
        # unless UT_TRACE enabled tracing above)
        with obs.span("bench.warm"):
            warm = drain(200)  # compile every arm + commit + observe
        steady = 500 if quick else 2000
        with obs.span("bench.steady", trials=steady):
            t0 = time.perf_counter()
            steady = drain(steady)
            dt = time.perf_counter() - t0
    if obs.journal.enabled():
        obs.stop_journal(jmon)    # settle the UT_JOURNAL stream
    obs.finish(trace_out)
    rate = steady / dt
    res = tuner.result()
    result = {
        "metric": "driver_asks_per_sec",
        "value": round(rate, 1),
        "unit": "asks/s",
        "platform": "cpu",
        "quick": quick,
        "trials": steady,
        "warm_trials": warm,
        "wall_s": round(dt, 4),
        "nproc": os.cpu_count(),
        # driver-plane self-timing over the WHOLE run (TuneResult):
        # device propose+dedup vs host materialization seconds
        "t_propose_s": round(res.t_propose, 4),
        "t_dedup_s": round(res.t_dedup, 4),
    }
    if guard.enabled:
        result["retraces"] = guard.report()
    # quick runs must not clobber the committed full-run evidence
    # artifact (same rule as BENCH_TPU.quick.json in main())
    name = "BENCH_DRIVER.quick.json" if quick else "BENCH_DRIVER.json"
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        name)
    with open(path, "w") as f:
        json.dump({**result, "captured_unix": time.time()}, f, indent=1)
    print(f"bench: driver-plane evidence written to {path}",
          file=sys.stderr)
    print(json.dumps(result))


def cache_main() -> None:
    """`bench.py --cache`: the results-store microbenchmark — run the
    SAME tune twice through `ProgramTuner` (identical program, space,
    seed, work dir; fresh store), so run 2's proposal stream replays
    run 1's and every trial can be served from the content-addressed
    store instead of launching a build (docs/STORE.md).

    Protocol (same box, one process, CPU engine platform): a 2-param
    quadratic program whose per-trial cost is one python subprocess
    launch; run 1 measures the build path and populates the store,
    run 2 measures the serve path.  Reported: builds eliminated
    (hits / (hits + run-2 builds)), run-2 hit rate, wall-clock for
    both runs, and the hit-served tell throughput (run-2 resolved
    trials / run-2 wall) next to the PR 2 driver-plane asks/s baseline
    from BENCH_DRIVER.json — the store's serve path rides the same
    ask/tell surface that benchmark measures, plus the store lookup
    and the worker-pool bookkeeping.  Run under UT_TRACE_GUARD=strict
    to also prove the serve path adds no retraces.  Writes
    BENCH_CACHE.json (BENCH_CACHE.quick.json for --quick)."""
    quick = "--quick" in sys.argv
    from uptune_tpu.utils.platform_guard import force_cpu
    force_cpu(1)
    import jax  # noqa: F401  (backend must init after force_cpu)

    import shutil
    import tempfile
    import textwrap

    from uptune_tpu.analysis.trace_guard import guard_from_env

    workdir = tempfile.mkdtemp(prefix="ut-bench-cache-")
    prog = os.path.join(workdir, "cache_prog.py")
    with open(prog, "w") as f:
        f.write(textwrap.dedent("""
            import uptune_tpu as ut
            x = ut.tune(50, (0, 100), name="x")
            y = ut.tune(50, (0, 100), name="y")
            ut.target(float((x - 37) ** 2 + (y - 11) ** 2), "min")
        """))
    # lockstep protocol (parallel=1, prefetch=0): run 1's tell order
    # equals run 2's serve order, so the technique/bandit/key stream
    # replays EXACTLY and every run-2 proposal is a store hit.  The
    # async parallel pipeline is timing-dependent by design (completion
    # order + speculative cancellation shift the proposal stream), so a
    # repeated parallel tune re-serves a fraction, not everything —
    # that regime's win is the multi-instance exchange, not replay.
    limit = 8 if quick else 120
    parallel = 1

    from uptune_tpu.driver.plugins import SearchHook

    class _TellClock(SearchHook):
        """Timestamps every told trial.  rate() over the LAST-half
        window: the head of a serve run pays the per-arm first-pull
        compile-cache loads (nothing to hide them behind when no build
        is running), the tail is the steady state a long repeat tune
        actually lives in."""

        def __init__(self):
            self.ts = []

        def on_result(self, tuner, trial, qor):
            self.ts.append(time.perf_counter())

        @property
        def n(self):
            return len(self.ts)

        def rate(self):
            h = len(self.ts) // 2
            if len(self.ts) - h < 2:
                return 0.0
            return (len(self.ts) - 1 - h) / max(
                self.ts[-1] - self.ts[h], 1e-9)

        def p50_gap_ms(self):
            gaps = sorted(b - a for a, b in zip(self.ts, self.ts[1:]))
            if not gaps:
                return 0.0
            return 1e3 * gaps[len(gaps) // 2]

    def tune():
        from uptune_tpu.exec.controller import ProgramTuner
        clock = _TellClock()
        pt = ProgramTuner([sys.executable, prog], workdir,
                          parallel=parallel, test_limit=limit, seed=0,
                          runtime_limit=60.0, hooks=[clock],
                          prefetch=0)
        t0 = time.perf_counter()
        res = pt.run()
        return pt, res, time.perf_counter() - t0, clock

    from uptune_tpu import obs
    trace_out = obs.maybe_enable_from_env()
    try:
        # one guard per run: each run builds its own Tuner (fresh jit
        # wrappers from the same code objects), which across ONE guard
        # would read as wrapper churn; per-run guards prove what the
        # CLI contract promises — one tune compiles each program once
        with guard_from_env() as guard1, obs.span("bench.run1_build"):
            pt1, res1, wall1, _ = tune()
        with guard_from_env() as guard2, obs.span("bench.run2_serve"):
            pt2, res2, wall2, clock2 = tune()
        obs.finish(trace_out)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    builds1 = pt1.pool.launched
    builds2 = pt2.pool.launched
    hits = pt2.store_hits
    elim = hits / max(1, hits + builds2)
    result = {
        "metric": "store_build_elimination",
        "value": round(elim, 4),
        "unit": "fraction of repeat-tune trials served from the store",
        "platform": "cpu",
        "quick": quick,
        "protocol": {
            "program": "2-param int quadratic (subprocess per trial)",
            "test_limit": limit, "parallel": parallel, "prefetch": 0,
            "seed": 0,
            "runs": "same box, same work dir, fresh store; lockstep "
                    "(parallel=1, prefetch=0) keeps the tell order "
                    "deterministic so run 2's proposal stream replays "
                    "run 1's exactly",
        },
        "nproc": os.cpu_count(),
        "run1": {"evals": res1.evals, "builds": builds1,
                 "wall_s": round(wall1, 3),
                 "pool": pt1.pool.stats()},
        "run2": {"evals": res2.evals, "builds": builds2, "hits": hits,
                 "hit_rate": round(hits / max(1, res2.evals), 4),
                 "wall_s": round(wall2, 3),
                 "pool": pt2.pool.stats(),
                 "store": pt2.store.stats()},
        "speedup_wall": round(wall1 / max(wall2, 1e-9), 2),
        # the serve path's steady-state throughput: resolved trials per
        # second over the last half of run 2's tell stream (ask/tell
        # dispatch + store lookup + pool bookkeeping, no subprocesses;
        # construction and the first-pull compile-cache loads excluded)
        # — compare against driver_asks_per_sec_baseline, the same
        # ask/tell surface with no store and an instant in-process
        # evaluator
        "hit_served_tells_per_sec": round(clock2.rate(), 1),
        # median gap between consecutive served tells: the pure
        # per-trial serve cost once a ticket's trials are flowing
        # (the window rate above still carries each arm's FIRST-pull
        # propose lowering — at 120 trials the whole run is warmup;
        # the driver baseline ran 200 warm trials before measuring)
        "hit_served_tell_p50_ms": round(clock2.p50_gap_ms(), 3),
    }
    drv = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_DRIVER.json")
    try:
        with open(drv) as f:
            result["driver_asks_per_sec_baseline"] = json.load(f)["value"]
    except (OSError, ValueError, KeyError):
        pass
    if guard1.enabled:
        result["retraces"] = {"run1": guard1.report(),
                              "run2": guard2.report()}
    name = "BENCH_CACHE.quick.json" if quick else "BENCH_CACHE.json"
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), name)
    with open(path, "w") as f:
        json.dump({**result, "captured_unix": time.time()}, f, indent=1)
    print(f"bench: store-cache evidence written to {path}",
          file=sys.stderr)
    print(json.dumps(result))


def surrogate_main() -> None:
    """`bench.py --surrogate`: the async-surrogate-plane microbenchmark
    (docs/PERF.md "Async surrogate plane").

    Protocol A — matched-seed lockstep tell latency: the SAME tune
    (space, seed, objective, calibrated surrogate opts) is driven
    through ask()/tell() twice, `--surrogate-async off` then `on`, and
    every tell() is wall-clocked.  Tells are bucketed into REFIT
    WINDOWS (a full fit was launched/ran inside that finalize — where
    sync mode pays the O(N^3) fit + fit_auto sweep inline) vs steady
    tells; the headline is the sync/async ratio of the refit-window
    p95.  The first two windows per mode are excluded as compile
    warmup (each fit bucket's first use pays XLA lowering in BOTH
    modes; steady state is what a long tune lives in).

    Protocol B (full mode only; --quick is the tier-1 smoke and runs
    protocol A alone) — BENCHREPORT spot-check: iterations-to-optimum
    on rosenbrock-2d/-4d at 5 matched seeds each, sync vs async
    WITHOUT any drain barrier (the real, timing-dependent regime),
    medians + IQR recorded to show search quality is statistically
    unchanged.

    Run under UT_TRACE_GUARD=strict to also prove the incremental
    Cholesky extensions add no retraces (per-bucket wrappers are built
    up-front).  Writes BENCH_SURROGATE.json (.quick.json for
    --quick)."""
    quick = "--quick" in sys.argv
    from uptune_tpu.utils.platform_guard import (enable_compile_cache,
                                                 force_cpu)
    # TWO virtual devices — the deployment shape the async plane
    # assumes: driver programs on device 0, background fits on device 1
    # (a single device would serialize the fit against every driver
    # dispatch; see SurrogateManager._refit_device)
    force_cpu(2)
    import jax  # noqa: F401  (backend must init after force_cpu)
    import numpy as np

    from uptune_tpu.analysis.trace_guard import guard_from_env
    from uptune_tpu.calibrated import CALIBRATED_OPTS
    from uptune_tpu.driver import Tuner
    from uptune_tpu.workloads import rosenbrock_objective, rosenbrock_space

    # the protocol builds many Tuners (sync + async + spot-check
    # seeds); the persistent compile cache keeps the repeated driver /
    # fit-bucket compiles from dominating the --quick smoke budget.
    # Latency percentiles are unaffected: compile warmup windows are
    # excluded either way
    enable_compile_cache(subdir="bench-surrogate")

    # full mode runs 1000 lockstep tells: background fits at bucket 512
    # take ~1 s, so the async side opens a refit window only every
    # ~100+ tells — a shorter run leaves its p95 resting on a handful
    # of windows.  quick is the tier-1 smoke: 100 tells still yields
    # ~6 refit windows (>=3 warm) at the capped-64 bucket while
    # keeping the 3-run protocol inside the suite's time budget
    # (ISSUE 6 — tier-1 runs within ~60s of the 870s timeout)
    trials = 100 if quick else 1000
    # the latency protocol probes the LEARNING-COST regime the async
    # plane exists for: max_points 512 (between the calibrated 256 and
    # the manager default 1024), where the O(N^3) fit + 43-point
    # fit_auto sweep costs ~1 s inline on this class of box.  quick
    # caps the bucket at 64 instead so the smoke run REACHES steady
    # state inside its budget: a first fit at a new bucket pays Python
    # tracing, which no thread can hide (the GIL), and at larger caps
    # every quick-run window would be such a first fit.  The protocol-B
    # spot-check keeps the calibrated 256 (search quality is measured
    # at the shipping configuration).
    sopts = dict(CALIBRATED_OPTS)
    sopts["max_points"] = 64 if quick else 512
    space = rosenbrock_space(2, -2.048, 2.048)
    obj = rosenbrock_objective(2)

    def lat_run(async_on):
        tuner = Tuner(space, None, seed=0, surrogate="gp",
                      surrogate_opts={**sopts, "async_refit": async_on})
        sm = tuner.surrogate
        lats, blocked, windows, warm = [], [], [], []
        seen_buckets = set()
        done = 0
        while done < trials:
            for tr in tuner.ask(min_trials=1):
                if done >= trials:
                    tuner.cancel(tr)
                    continue
                q = float(obj([tr.config])[0])
                starts0 = sm.refits_started
                t0 = time.perf_counter()
                stats = tuner.tell(tr, q)
                dt = time.perf_counter() - t0
                lats.append(dt * 1e3)
                blocked.append(
                    stats.t_refit * 1e3 if stats is not None else 0.0)
                w = sm.refits_started > starts0
                windows.append(w)
                if w:
                    # a window is WARM once the bucket this fit
                    # compiles for has been fitted before: first-use
                    # windows pay trace+compile in both modes
                    # (unhideable Python tracing) and are reported
                    # separately as cold_window_p95
                    bkt = sm.fit_bucket()
                    warm.append(bkt in seen_buckets)
                    seen_buckets.add(bkt)
                else:
                    warm.append(False)
                done += 1
        res = tuner.result()
        out = {
            "tells": done,
            "refit_windows": int(sum(windows)),
            "warm_refit_windows": int(sum(warm)),
            "t_refit_blocking_s": round(res.t_refit, 4),
            "t_refit_bg_s": round(sm.t_refit_bg_total, 4),
            "full_fits_published": sm.refits,
            "incremental_updates": sm.incr_updates,
            "final_snapshot_version": sm.snapshot_version,
            "refit_lag_rows_final": sm.refit_lag_rows,
        }
        tuner.close()   # drains the background worker
        wl = [l for l, w in zip(lats, warm) if w]
        bl = [b for b, w in zip(blocked, warm) if w]
        cl = [l for l, w, ww in zip(lats, windows, warm) if w and not ww]
        sl = [l for l, w in zip(lats, windows) if not w]
        pct = (lambda a, p: round(float(np.percentile(a, p)), 3)
               if len(a) else None)
        out["tell_ms"] = {
            "p50": pct(lats, 50), "p95": pct(lats, 95),
            "refit_window_p50": pct(wl, 50),
            "refit_window_p95": pct(wl, 95),
            "cold_window_p95": pct(cl, 95),
            "steady_p50": pct(sl, 50), "steady_p95": pct(sl, 95),
        }
        # the learning-ATTRIBUTABLE component of those window tells
        # (StepStats.t_refit: seconds the finalize blocked inside
        # observe->maybe_refit) — immune to the scheduler noise a
        # shared 2-core box injects into whole-tell percentiles
        out["refit_blocked_ms"] = {"warm_window_p50": pct(bl, 50),
                                   "warm_window_p95": pct(bl, 95)}
        return out

    # warmup pass (unguarded, discarded): populates the persistent
    # compile cache with every driver/fit/extension program the
    # measured runs will use, so their latencies reflect the steady
    # state a long tune lives in (~fast cache loads instead of
    # multi-second XLA compiles) — the same philosophy as the driver
    # bench's 200 warm trials.  Tracing still happens live in the
    # guarded runs, so the strict retrace report keeps its teeth.
    from uptune_tpu import obs
    trace_out = obs.maybe_enable_from_env()
    with obs.span("bench.warmup"):
        lat_run(False)

    with guard_from_env() as guard_sync, obs.span("bench.sync"):
        sync = lat_run(False)
    with guard_from_env() as guard_async, obs.span("bench.async"):
        asyn = lat_run(True)
    obs.finish(trace_out)

    # protocol B: iterations-to-optimum spot check (BENCHREPORT
    # thresholds: 2d <= 0.1 within 2000, 4d <= 1.0 within 4000)
    def iters_run(dims, thresh, budget, seed, async_on):
        sp = rosenbrock_space(dims, -2.048, 2.048)
        t = Tuner(sp, rosenbrock_objective(dims), seed=seed,
                  surrogate="gp",
                  surrogate_opts={**CALIBRATED_OPTS,
                                  "async_refit": async_on})
        res = t.run(test_limit=budget, target=thresh)
        t.close()
        for i, v in enumerate(res.trace):
            if v <= thresh:
                return i + 1
        return budget

    # --quick is the tier-1 smoke: latency protocol only (the
    # spot-check's repeated full tunes belong to the committed full
    # artifact, not the suite budget)
    problems = [] if quick else [(2, 0.1, 2000), (4, 1.0, 4000)]
    seeds = range(5)
    spot = {}
    for dims, thresh, budget in problems:
        cell = {}
        for mode, async_on in (("sync", False), ("async", True)):
            its = [iters_run(dims, thresh, budget, s, async_on)
                   for s in seeds]
            q1, med, q3 = (float(np.percentile(its, p))
                           for p in (25, 50, 75))
            cell[mode] = {"iters": its, "median": med,
                          "iqr": [q1, q3],
                          "censored": int(sum(i >= budget for i in its))}
        cell["async_median_within_sync_iqr"] = bool(
            cell["sync"]["iqr"][0] <= cell["async"]["median"]
            <= cell["sync"]["iqr"][1]) or (
            cell["async"]["median"] <= cell["sync"]["median"])
        spot[f"rosenbrock-{dims}d"] = cell

    sp95 = sync["refit_blocked_ms"]["warm_window_p95"]
    ap95 = asyn["refit_blocked_ms"]["warm_window_p95"]
    speedup = round(sp95 / ap95, 2) if sp95 and ap95 else None
    st95 = sync["tell_ms"]["refit_window_p95"]
    at95 = asyn["tell_ms"]["refit_window_p95"]
    bg = asyn["t_refit_bg_s"]
    blocking = asyn["t_refit_blocking_s"]
    result = {
        "metric": "surrogate_async_refit_window_p95_speedup",
        # headline: sync/async ratio of the LEARNING-ATTRIBUTABLE tell
        # p95 inside warm refit windows (StepStats.t_refit).  The
        # whole-tell window percentiles are reported alongside
        # (tell_window_p95_ratio) — on a shared 2-core box they carry
        # scheduler-noise outliers an async run has few windows to
        # amortize over
        "value": speedup,
        "tell_window_p95_ratio": (round(st95 / at95, 2)
                                  if st95 and at95 else None),
        "unit": "sync/async ratio of learning-attributable tell p95 "
                "(StepStats.t_refit) during warm refit windows",
        "platform": "cpu",
        "quick": quick,
        "nproc": os.cpu_count(),
        "protocol": {
            "space": "rosenbrock-2d", "seed": 0, "tells": trials,
            "surrogate": sopts,
            "devices": "2 virtual CPU devices: driver plane on 0, "
                       "background fits on 1 (the async deployment "
                       "shape; one device serializes fit vs driver "
                       "dispatches)",
            "lockstep": "ask(min_trials=1)/tell, matched seeds; refit "
                        "windows = tells whose finalize launched/ran a "
                        "full fit; a window is WARM once its bucket "
                        "was fitted before (first-use windows pay "
                        "unhideable Python tracing in both modes and "
                        "are reported as cold_window_p95)",
        },
        "sync": sync,
        "async": asyn,
        # fraction of full-fit compute the async plane moved OFF the
        # tell path (1.0 = everything overlapped with foreground work)
        "refit_overlap_fraction": round(bg / (bg + blocking), 4)
        if bg + blocking > 0 else None,
        "iters_to_optimum_spotcheck": spot,
    }
    if guard_sync.enabled:
        result["retraces"] = {"sync": guard_sync.report(),
                              "async": guard_async.report()}
    name = ("BENCH_SURROGATE.quick.json" if quick
            else "BENCH_SURROGATE.json")
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), name)
    with open(path, "w") as f:
        json.dump({**result, "captured_unix": time.time()}, f, indent=1)
    print(f"bench: async-surrogate evidence written to {path}",
          file=sys.stderr)
    print(json.dumps(result))


def multi_main() -> None:
    """`bench.py --multi`: the batched multi-instance engine benchmark
    (docs/PERF.md "Batched multi-instance engine") — aggregate
    candidate acquisitions/sec of N independent on-device tunes run as
    ONE vmapped donate-in-place program (engine/batched.py), next to
    an honest N-sequential-runs baseline measured with the same
    compiled single-instance program.

    Protocol: rosenbrock-16d, per-instance default arms (scale=1) and
    a 2^11 dedup history; N=256 instances (32 at --quick).  TWO
    sequential baselines, both recorded:

    * `speedup_vs_warm_sequential` — N x the wall of the SAME
      compiled single-instance jit_run(steps) (warm, donated, in
      process).  This is the strictest possible baseline: the single
      engine is already one fused lax.scan program, so on a
      throughput-bound CPU this ratio mostly reflects batching's
      per-op overhead amortization (small); on TPU it reflects how
      empty the chip was (BENCH_TPU.json: MXU util 6e-06).
    * `speedup_vs_sequential_processes` (full runs only) — N x the
      measured wall of ONE fresh single-instance tune process
      (interpreter + jax import + trace/compile + run), the
      reference's actual multi-instance deployment shape (one
      OpenTuner process per instance, PAPER.md L4/L5) and what 'run N
      tunes today' costs without this engine.

    On TPU with multiple chips the instance axis shard_maps across
    them and the headline stays PER-CHIP (aggregate / n_devices).
    Run under UT_TRACE_GUARD=strict to prove the whole batched run
    compiles once.  Writes BENCH_MULTI.json (.quick.json for --quick)
    with XLA cost-model roofline fields in the BENCH_TPU.json
    style."""
    quick = "--quick" in sys.argv
    jax, platform = _init_backend(
        cpu_flag="--cpu" in sys.argv,
        wait_for_tpu="--wait-for-tpu" in sys.argv)
    if platform == "cpu:fallback":
        quick = True

    from uptune_tpu import obs
    from uptune_tpu.analysis.trace_guard import guard_from_env
    trace_out = obs.maybe_enable_from_env()
    obs_device.maybe_trace_from_env()   # UT_DEVICE_TRACE=<dir>
    with guard_from_env() as guard:
        from uptune_tpu.engine import (BatchedEngine, FusedEngine,
                                       default_arms, make_instance_mesh)
        from uptune_tpu.workloads import rosenbrock_device, rosenbrock_space

        n_inst = 32 if quick else 256
        steps = 10 if quick else 50
        space = rosenbrock_space(16, -5.0, 5.0)

        def build_engine():
            # per-instance arms at scale=1: the chip fills along the
            # INSTANCE axis, not by inflating one tune's populations
            return FusedEngine(space, lambda v, p: rosenbrock_device(v),
                               arms=default_arms(scale=1),
                               history_capacity=1 << 11)

        eng = build_engine()
        n_dev = len(jax.devices())
        mesh = None
        if platform not in ("cpu", "cpu:fallback") and n_dev > 1:
            while n_inst % n_dev:
                n_dev -= 1
            mesh = make_instance_mesh(n_dev)
        else:
            n_dev = 1
        be = BatchedEngine(eng, n_inst, mesh=mesh)

        # constant seeds by design: a measured bench must replay the
        # same stream run-to-run
        state = be.init(jax.random.PRNGKey(0))  # ut-lint: disable=R002
        lowered = be.jit_run(steps).lower(state)
        compiled = lowered.compile()
        state = compiled(state)         # warm (donated; rebind)
        jax.block_until_ready(state)
        harv = obs_device.harvest(compiled)

        reps = 3
        rep_times = []
        with obs.span("bench.batched_reps", reps=reps):
            for r in range(reps):
                # identical reps measure wall time, not search quality
                # ut-lint: disable-next=R002
                s = be.init(jax.random.PRNGKey(1))
                jax.block_until_ready(s)
                t0 = time.perf_counter()
                s = compiled(s)
                jax.block_until_ready(s)
                rep_times.append(time.perf_counter() - t0)
        best_t = min(rep_times)

        # N-sequential baseline: one instance, same shapes, same
        # compiled program reused (warm) — what a loop over N seeds
        # of the single-instance engine would cost, minus its N-1
        # extra dispatch/compile overheads (lower-bound speedup)
        seq_run = eng.jit_run(steps)
        st1 = eng.init(jax.random.PRNGKey(2))  # ut-lint: disable=R002
        st1 = seq_run(st1)              # warm + compile
        jax.block_until_ready(st1)
        seq_times = []
        for r in range(reps):
            s1 = eng.init(jax.random.PRNGKey(3))  # ut-lint: disable=R002
            jax.block_until_ready(s1)
            t0 = time.perf_counter()
            s1 = seq_run(s1)
            jax.block_until_ready(s1)
            seq_times.append(time.perf_counter() - t0)
        t_single = min(seq_times)

        # one-process baseline (full CPU runs only): a fresh
        # interpreter running the same single-instance tune end to end
        # — the reference's one-process-per-instance shape.  Measured,
        # not estimated; multiplied by N for the process-sequential
        # speedup.  Skipped on accelerators: a second process cannot
        # share the chip the parent holds, and a CPU child divided by
        # a TPU batched wall would be a cross-backend ratio dressed up
        # as like-for-like — the TPU story is utilization + the warm
        # baseline.
        t_process = None
        if not quick and platform in ("cpu", "cpu:fallback"):
            import subprocess
            code = (
                "from uptune_tpu.utils.platform_guard import force_cpu\n"
                "force_cpu(1)\n"
                "import jax\n"
                "from uptune_tpu.engine import FusedEngine, default_arms\n"
                "from uptune_tpu.workloads import rosenbrock_device, \\\n"
                "    rosenbrock_space\n"
                "space = rosenbrock_space(16, -5.0, 5.0)\n"
                "eng = FusedEngine(space,\n"
                "                  lambda v, p: rosenbrock_device(v),\n"
                "                  arms=default_arms(scale=1),\n"
                "                  history_capacity=1 << 11)\n"
                f"s = eng.init(jax.random.PRNGKey(0))\n"
                f"s = eng.jit_run({steps})(s)\n"
                "jax.block_until_ready(s)\n")
            t0 = time.perf_counter()
            proc = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                text=True, timeout=600,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            if proc.returncode == 0:
                t_process = time.perf_counter() - t0
            else:  # record the failure, never a fabricated number
                print(f"bench: process-baseline run failed "
                      f"(rc={proc.returncode}): "
                      f"{proc.stderr.strip()[-300:]}", file=sys.stderr)

        # portfolio mode (full runs only): the same batch with the
        # on-device best-exchange collective every 16 steps — records
        # what cooperation costs next to independent instances
        exch_rate = None
        if not quick:
            bex = BatchedEngine(eng, n_inst, exchange_every=16,
                                mesh=mesh)
            sx = bex.jit_run(steps)(bex.init(jax.random.PRNGKey(4)))  # ut-lint: disable=R002
            jax.block_until_ready(sx)
            sx = None
            # init lands BEFORE t0, matching the headline/sequential
            # measurement windows (timed: the compiled run only)
            s5 = bex.init(jax.random.PRNGKey(5))  # ut-lint: disable=R002
            jax.block_until_ready(s5)
            t0 = time.perf_counter()
            s5 = bex.jit_run(steps)(s5)
            jax.block_until_ready(s5)
            exch_rate = steps * n_inst * eng.total_batch / (
                time.perf_counter() - t0)

    # fused acquisition pipeline A/B (ISSUE 19, docs/PERF.md "Fused
    # acquisition pipeline"): surrogate score + acquisition over the
    # SAME flattened [N*B] batch, once as the single fused device
    # program (ops/acquire.py) and once as the pre-fusion
    # gp.score_flat staging; plus the fused score+top-k program.
    # Outside the guard region by design: each comparator is a
    # one-shot program whose trace would eat the headline's strict
    # retrace budget (the guard proves the BATCHED RUN compiles once).
    from uptune_tpu.engine import surrogate_eval_fn
    from uptune_tpu.ops import acquire as acquire_ops
    from uptune_tpu.ops import routing as routing_ops
    from uptune_tpu.surrogate import gp as gp_mod

    n_train = 64 if quick else 256
    tr = space.random(jax.random.PRNGKey(6), n_train)  # ut-lint: disable=R002
    feats_tr = space.surrogate_transform(space.features(tr))
    y_tr = rosenbrock_device(space.decode_scalars(tr.u))
    gp_st = gp_mod.precompute_kinv(gp_mod.fit(feats_tr, y_tr))
    best_y = float(y_tr.min())
    flat_rows = n_inst * eng.total_batch
    cands_flat = space.random(jax.random.PRNGKey(7), flat_rows)  # ut-lint: disable=R002
    acq_route = routing_ops.decide(flat_rows,
                                   min_rows=acquire_ops.MIN_ROWS,
                                   cpu_ok=False)
    fused_ev = surrogate_eval_fn(space, gp_st, kind="ei",
                                 best_y=best_y, impl="fused")
    unf_ev = surrogate_eval_fn(space, gp_st, kind="ei",
                               best_y=best_y, impl="score_flat")

    def _compile_eval(call, aux):
        comp = jax.jit(call).lower(cands_flat, aux).compile()
        jax.block_until_ready(comp(cands_flat, aux))   # warm
        return comp

    def _timed_rep(comp, aux):
        t0 = time.perf_counter()
        jax.block_until_ready(comp(cands_flat, aux))
        return time.perf_counter() - t0

    topk_k = eng.total_batch
    comp_f = _compile_eval(fused_ev.fn, fused_ev.aux)
    comp_u = _compile_eval(unf_ev.fn, unf_ev.aux)
    comp_k = _compile_eval(
        lambda c, aux: fused_ev.topk(c, aux, topk_k), fused_ev.aux)
    # INTERLEAVED reps, best-of per mode (the --obs A/B discipline):
    # a sequential fused-block-then-unfused-block pairing correlates
    # this box's co-tenant ramp with one mode and the recorded ratio
    # inherits the bias; round-robin draws give each mode the same
    # exposure.  More draws than the headline (best-of needs enough
    # draws per mode to catch each one's quiet window).
    ab_reps = reps if quick else max(reps, 7)
    ts_f, ts_u, ts_k = [], [], []
    for _ in range(ab_reps):
        ts_f.append(_timed_rep(comp_f, fused_ev.aux))
        ts_u.append(_timed_rep(comp_u, unf_ev.aux))
        ts_k.append(_timed_rep(comp_k, fused_ev.aux))
    harv_acq = obs_device.harvest(comp_f)

    obs_device.stop_trace()
    obs.finish(trace_out)
    acqs = steps * n_inst * eng.total_batch
    rate = acqs / best_t
    rate_chip = rate / n_dev
    speedup = n_inst * t_single / best_t
    result = {
        "metric": "multi_instance_agg_acqs_per_sec_per_chip",
        "value": round(rate_chip, 1),
        "unit": "configs/s (aggregate over instances / devices)",
        "platform": platform,
        "quick": quick,
        "n_instances": n_inst,
        "n_devices": n_dev,
        "steps": steps,
        "per_instance_batch": eng.total_batch,
        "acquisitions": acqs,
        "agg_rate_all_devices": round(rate, 1),
        "rep_wall_s": [round(t, 4) for t in rep_times],
        # strictest baseline: N sequential runs of the SAME compiled
        # single-instance program, warm + donated, in process — no
        # startup, no compile, no dispatch gaps.  On CPU both sides
        # are throughput-bound, so this ratio is small by design; the
        # chip-filling win is the TPU story (utilization fields below)
        "seq_single_wall_s": [round(t, 4) for t in seq_times],
        "speedup_vs_warm_sequential": round(speedup, 2),
        "nproc": os.cpu_count(),
    }
    if t_process is not None:
        # the reference's deployment shape: one process per instance
        # (CPU-only protocol — both sides on the same backend)
        result["seq_process_wall_s"] = round(t_process, 2)
        result["seq_process_platform"] = "cpu"
        result["speedup_vs_sequential_processes"] = round(
            n_inst * t_process / best_t, 1)
    if exch_rate is not None:
        result["exchange_every_16_agg_rate"] = round(exch_rate, 1)
    if guard.enabled:
        result["retraces"] = guard.report()

    dev = jax.devices()[0]
    device_kind = getattr(dev, "device_kind", "?")
    # a traced run (UT_TRACE) also publishes these as device.* gauges
    # via the shared module — no-op untraced
    obs_device.record_window("engine.batched_run", best_t,
                             device_kind=device_kind)
    result["cost_analysis"] = {
        **_roofline_fields(harv, device_kind, best_t),
        "note": ("measured via obs/device.py: flops/bytes from XLA's "
                 "cost model for this exact executable, peak memory "
                 "from its allocation plan, rates over the blocked "
                 "best-rep wall; utilization compares those measured "
                 "rates against published per-chip peaks "
                 "(obs.device.PEAKS — bf16 MXU quoted, so MXU util "
                 "is a conservative lower bound)" + (
                     "" if platform not in ("cpu", "cpu:fallback") else
                     "; no published roofline peaks for the CPU "
                     "fallback — utilization fields apply on TPU only")),
    }
    t_f, t_u, t_k = min(ts_f), min(ts_u), min(ts_k)
    obs_device.record_window("acquire.fused_scores", t_f,
                             device_kind=device_kind)
    result["fused_acquire"] = {
        "kind": "ei",
        "n_train": n_train,
        "flat_rows": flat_rows,
        "route": acq_route,
        "agg_acq_per_s_fused": round(flat_rows / t_f, 1),
        "agg_acq_per_s_unfused": round(flat_rows / t_u, 1),
        "fused_speedup_vs_unfused": round(t_u / t_f, 3),
        "topk_k": topk_k,
        "agg_acq_per_s_fused_topk": round(flat_rows / t_k, 1),
        "rep_wall_s_fused": [round(t, 5) for t in ts_f],
        "rep_wall_s_unfused": [round(t, 5) for t in ts_u],
        "rep_wall_s_topk": [round(t, 5) for t in ts_k],
        # static tile/VMEM protocol of the Pallas kernel for these
        # shapes (what WOULD run on TPU; `route` says what this box
        # actually executed) — the TPU roofline protocol fields
        "kernel_schema": acquire_ops.kernel_schema(
            n_train, int(feats_tr.shape[-1]), kind="ei", k=topk_k),
        "cost_analysis": {
            **_roofline_fields(harv_acq, device_kind, t_f),
            "note": ("fused acquisition pipeline (scores route) "
                     "program only, measured like the headline "
                     "cost_analysis; unfused comparator is the "
                     "pre-fusion gp.score_flat staging on the same "
                     "flat batch and snapshot"),
        },
    }
    artifact = {
        **result,
        "devices": repr(jax.devices()),
        "device_kind": device_kind,
        "jax_version": jax.__version__,
        "captured_unix": time.time(),
        "protocol": {
            "space": "rosenbrock-16d",
            "arms": "default_arms(scale=1) per instance",
            "history_capacity": 1 << 11,
            "exchange": "independent instances (headline); "
                        "exchange_every=16 portfolio recorded "
                        "separately on full runs",
            "warm_sequential_baseline":
                "same compiled single-instance jit_run(steps), warm + "
                "donated, in process, best of 3; speedup = N * "
                "t_single / t_batched (the strictest baseline: no "
                "startup, no compile, no dispatch)",
            "process_sequential_baseline":
                "one MEASURED fresh single-instance tune process "
                "(interpreter + jax import + compile + run) x N — the "
                "reference's one-OpenTuner-process-per-instance shape "
                "(PAPER.md L4/L5); full CPU runs only (skipped on "
                "accelerators: a second process cannot share the "
                "parent's chip, and a cross-backend ratio would not "
                "be like-for-like)",
        },
    }
    name = "BENCH_MULTI.quick.json" if quick else "BENCH_MULTI.json"
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), name)
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"bench: multi-instance evidence written to {path}",
          file=sys.stderr)
    print(json.dumps(result))


def fleet_child_main() -> None:
    """`bench.py --fleet-child`: one driver-replica source process of
    the fleet-telemetry bench — a real library-Tuner ask/tell loop
    with the obs plane, a flight recorder, AND a TelemetryShipper on,
    so the parent can hold the hub's view of this source to the
    source's own flight-recorder finals (the exactness contract)."""
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--fleet-child", action="store_true")
    ap.add_argument("--hub", required=True)
    ap.add_argument("--role", required=True)
    ap.add_argument("--metrics", required=True)
    ap.add_argument("--trials", type=int, default=200)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--interval", type=float, default=0.15)
    args, _ = ap.parse_known_args()

    from uptune_tpu.utils.platform_guard import force_cpu
    force_cpu(1)
    from uptune_tpu import obs
    from uptune_tpu.obs import flight, ship

    obs.enable()
    rec = flight.start(args.metrics, interval=args.interval)
    shipper = ship.start(args.hub, role=args.role,
                         interval=args.interval)

    from uptune_tpu.driver import Tuner
    from uptune_tpu.workloads import rosenbrock_space
    tuner = Tuner(rosenbrock_space(8, -3.0, 3.0), None, seed=args.seed)
    done = 0
    while done < args.trials:
        for tr in tuner.ask(min_trials=1):
            # the driver_main deterministic dummy QoR stream
            tuner.tell(tr, float((tr.gid * 2654435761) % 1000))
            done += 1
    tuner.close()
    # final-window ordering: all metric activity is done, so the
    # shipper's final window and the recorder's final row read the
    # SAME terminal registry — the per-source equality the parent
    # asserts (and tests/test_fleet.py unit-asserts)
    shipper.stop()
    rec.stop()
    st = shipper.stats()
    print(json.dumps({"ok": st["failures"] == 0 or st["acked"] > 0,
                      "trials": done, **st}))


def fleet_main() -> None:
    """`bench.py --fleet`: the fleet-telemetry bench (ISSUE 14).

    Phase 1 — shipper overhead: the BENCH_DRIVER ask/tell drain run
    in alternating windows with the obs plane ON in both modes and a
    TelemetryShipper to a live local hub added in the shipped
    windows; best-of-reps ratio must hold the >= 0.95x bar (the
    BENCH_OBS rule, priced for the shipping path).

    Phase 2 — a real 4-process fleet against ONE hub: two driver
    replicas (`--fleet-child` subprocesses), one `ut serve` process
    (SIGTERM'd at the end, exercising the graceful final-window
    flush), and this bench-client process itself, every one shipping
    windows on its own (host, pid, role) key while also writing its
    own flight recorder.  Asserts the EXACTNESS contract: the hub's
    last window per source equals that source's final flight-recorder
    row, so fleet counter sums equal the sum of per-source finals.

    Phase 3 (full runs only) — the kill test: a third driver replica
    is SIGKILLed mid-stream; the hub must retain every acked window
    (all present in the durable timeline) and lose at most the one
    un-acked in-flight window vs the dead process's on-disk flight
    recorder.

    Writes BENCH_FLEET.json (.quick.json for --quick)."""
    quick = "--quick" in sys.argv
    from uptune_tpu.utils.platform_guard import force_cpu
    force_cpu(1)
    import jax  # noqa: F401  (backend must init after force_cpu)

    import shutil
    import socket as _socket
    import subprocess
    import tempfile

    from uptune_tpu import obs
    from uptune_tpu.obs import hub as hub_mod
    from uptune_tpu.obs import ship
    from uptune_tpu.obs import top as top_mod

    repo = os.path.dirname(os.path.abspath(__file__))
    workdir = tempfile.mkdtemp(prefix="ut_fleet_bench_")
    result: dict = {"metric": "fleet_telemetry", "quick": quick,
                    "nproc": os.cpu_count()}

    # ---- phase 1: shipper overhead on the driver hot path ------------
    obs.enable()
    from uptune_tpu.driver import Tuner
    from uptune_tpu.workloads import rosenbrock_space
    tuner = Tuner(rosenbrock_space(8, -3.0, 3.0), None, seed=0)

    def drain(n):
        done = 0
        while done < n:
            for tr in tuner.ask(min_trials=1):
                tuner.tell(tr, float((tr.gid * 2654435761) % 1000))
                done += 1

    drain(200)      # warm: compile every arm + commit + observe
    window = 400 if quick else 2000
    reps = 1 if quick else 3
    phase1_hub = hub_mod.TelemetryHub(port=0, timeline=None)
    phase1_hub.start()

    def timed(n):
        t0 = time.perf_counter()
        drain(n)
        return n / (time.perf_counter() - t0)

    unshipped, shipped = [], []
    for rep in range(reps):
        # rotate mode order per rep so co-tenant drift is uncorrelated
        # with mode (the BENCH_OBS rule)
        for mode in (("un", "sh") if rep % 2 == 0 else ("sh", "un")):
            if mode == "un":
                unshipped.append(timed(window))
            else:
                shipper = ship.TelemetryShipper(
                    f"127.0.0.1:{phase1_hub.port}",
                    role="bench-driver", interval=0.1)
                shipper.start()
                shipped.append(timed(window))
                shipper.stop()
    phase1_hub.stop()
    tuner.close()
    ratio = max(shipped) / max(unshipped)
    result["phase1"] = {
        "window_trials": window, "reps": reps,
        "unshipped_asks_per_s": [round(r, 1) for r in unshipped],
        "shipped_asks_per_s": [round(r, 1) for r in shipped],
        "shipped_over_unshipped": round(ratio, 4),
        "bar": 0.95, "bar_met": ratio >= 0.95,
    }
    print(f"bench --fleet: shipped/unshipped asks ratio "
          f"{ratio:.4f} (bar 0.95)", file=sys.stderr)

    # ---- phase 2: the 4-process fleet --------------------------------
    timeline = os.path.join(workdir, "ut.fleet.jsonl")
    hub = hub_mod.TelemetryHub(port=0, timeline=timeline,
                               timeline_rotate=2)
    hub.start()
    addr = f"127.0.0.1:{hub.port}"
    child_env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)

    def _final_counters(metrics_path):
        """Last (final) flight-recorder row's absolute counters."""
        last = None
        try:
            with open(metrics_path) as f:
                for line in f:
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(row, dict) and "counters" in row:
                        last = row
        except OSError:
            return None
        return (last or {}).get("counters")

    n_trials = 80 if quick else 400
    drivers = []
    for i in range(2):
        mpath = os.path.join(workdir, f"driver{i}.metrics.jsonl")
        cmd = [sys.executable, os.path.join(repo, "bench.py"),
               "--fleet-child", "--hub", addr,
               "--role", f"ut-driver.h{i}", "--metrics", mpath,
               "--trials", str(n_trials), "--seed", str(i),
               "--interval", "0.15"]
        p = subprocess.Popen(cmd, cwd=workdir, env=child_env,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
        drivers.append((p, mpath, f"ut-driver.h{i}"))

    # one real `ut serve` process shipping its windows + health rollup
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    sport = s.getsockname()[1]
    s.close()
    serve_trace = os.path.join(workdir, "serve_trace.json")
    serve_cmd = [sys.executable, "-m", "uptune_tpu.cli", "serve",
                 "--port", str(sport), "--store-dir", "off",
                 "--trace", serve_trace, "--metrics-interval", "0.15",
                 "--telemetry", addr, "--work-dir", workdir]
    serve_p = subprocess.Popen(serve_cmd, cwd=workdir, env=child_env,
                               stdout=subprocess.PIPE,
                               stderr=subprocess.STDOUT, text=True)

    # this process is the 4th source: the bench client
    from uptune_tpu.obs import flight
    bench_metrics = os.path.join(workdir, "bench.metrics.jsonl")
    bench_rec = flight.start(bench_metrics, interval=0.15)
    bench_ship = ship.start(addr, role="bench", interval=0.15)

    # wait for the server, then drive a small session through it
    from uptune_tpu.serve.client import connect
    deadline = time.time() + 120
    client = None
    while time.time() < deadline:
        try:
            # generous request timeout: the first open pays the
            # group's trace+compile wall (seconds on a loaded box)
            client = connect(("127.0.0.1", sport), timeout=180)
            break
        except OSError:
            if serve_p.poll() is not None:
                raise RuntimeError(
                    "ut serve died: " + serve_p.communicate()[0][-2000:])
            time.sleep(0.25)
    if client is None:
        raise RuntimeError("ut serve never came up")
    sess = client.open_session(rosenbrock_space(2, -3.0, 3.0), seed=7,
                               program="fleet-bench", store=False)
    for _ in range(2 if quick else 8):
        trials = sess.ask(4)
        sess.tell_many(
            (t.ticket, float(sum(v * v for v in t.config.values())))
            for t in trials)
    best = sess.best()
    sess.close()
    client.close()

    rcs = []
    for p, _, _ in drivers:
        out = p.communicate()[0]
        rcs.append(p.returncode)
        if p.returncode != 0:
            print(out[-2000:], file=sys.stderr)
    if any(rcs):
        raise RuntimeError(f"driver replicas failed: rcs={rcs}")
    # SIGTERM the server: the graceful exit flush must ship its final
    # window before the process dies (obs.install_exit_flush)
    serve_p.terminate()
    serve_p.wait(timeout=60)
    time.sleep(0.4)     # let the hub fold the server's final batch
    bench_ship.stop()
    bench_rec.stop()

    # ---- the exactness contract --------------------------------------
    host = _socket.gethostname()
    by_role = {s_.key[2]: s_ for s_ in hub._sources.values()
               if s_.key[0] == host}
    checks = []
    fleet_expected: dict = {}
    pairs = [(role, mpath) for _, mpath, role in drivers]
    pairs += [("ut-serve", serve_trace + ".metrics.jsonl"),
              ("bench", bench_metrics)]
    for role, mpath in pairs:
        src = by_role.get(role)
        hub_counters = (src.last_window or {}).get("counters") \
            if src is not None else None
        file_counters = _final_counters(mpath)
        ok = (hub_counters is not None
              and hub_counters == file_counters)
        checks.append({"role": role, "exact": ok,
                       "hub_rows": src.acked if src else 0})
        for k, v in (file_counters or {}).items():
            fleet_expected[k] = fleet_expected.get(k, 0) + v
    exact_ok = all(c["exact"] for c in checks)
    roll = hub.handle({"op": "metrics"})["metrics"]
    sum_ok = all(abs(roll["counters"].get(k, 0) - v) < 1e-9
                 for k, v in fleet_expected.items())
    health = hub.handle({"op": "health"})
    # `ut top --addr <hub> --fleet` must render the live fleet (the
    # acceptance criterion); the frame itself is test output, not
    # bench output
    import contextlib
    import io
    _sink = io.StringIO()
    with contextlib.redirect_stdout(_sink):
        top_frame_ok = top_mod.main(
            ["--addr", addr, "--once", "--fleet", "--json"]) == 0
    top_frame_ok = top_frame_ok and '"sources":' in _sink.getvalue()
    result["phase2"] = {
        "processes": 4, "hub_addr": addr,
        "sources": hub.handle({"op": "sources"})["sources"],
        "driver_trials_each": n_trials,
        "serve_best_version": best.get("version"),
        "per_source_exact": checks,
        "all_sources_exact": exact_ok,
        "fleet_counter_sum_exact": sum_ok,
        "health_by_status": health["by_status"],
        "timeline_rows": hub.rows_received,
        "top_addr_fleet_frame": top_frame_ok,
    }
    print(f"bench --fleet: 4-process fleet exactness "
          f"{'OK' if exact_ok and sum_ok else 'FAILED'} "
          f"({hub.rows_received} timeline rows)", file=sys.stderr)

    # ---- phase 3 (full only): the SIGKILL bound ----------------------
    if not quick:
        mpath = os.path.join(workdir, "victim.metrics.jsonl")
        role = "ut-driver.victim"
        cmd = [sys.executable, os.path.join(repo, "bench.py"),
               "--fleet-child", "--hub", addr, "--role", role,
               "--metrics", mpath, "--trials", "1000000",
               "--seed", "9", "--interval", "0.1"]
        p = subprocess.Popen(cmd, cwd=workdir, env=child_env,
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
        deadline = time.time() + 180
        victim = None
        while time.time() < deadline:
            victim = {s_.key[2]: s_ for s_ in
                      hub._sources.values()}.get(role)
            if victim is not None and len(victim.windows) >= 4:
                break
            time.sleep(0.1)
        p.kill()            # SIGKILL: no flush, no final window
        p.wait()
        time.sleep(0.3)
        fr_rows = 0
        try:
            with open(mpath) as f:
                for line in f:
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(row, dict) and "counters" in row:
                        fr_rows += 1
        except OSError:
            pass
        hub_rows = len(victim.windows) if victim is not None else 0
        acked = victim.acked if victim is not None else 0
        timeline_rows = sum(
            1 for rec_ in flight.read_chain(timeline)
            if rec_.get("src", "").endswith(f":{role}")
            and rec_.get("kind") == "window")
        # every acked window is durable; the loss vs the on-disk
        # recorder is bounded by the in-flight batch + the row being
        # written at kill time
        kill_ok = (hub_rows >= max(0, fr_rows - 2)
                   and timeline_rows >= hub_rows > 0)
        result["phase3"] = {
            "victim_role": role, "fr_rows_on_disk": fr_rows,
            "hub_windows": hub_rows, "acked_rows": acked,
            "timeline_window_rows": timeline_rows,
            "loss_bound_rows": 2, "kill_bound_met": kill_ok,
        }
        print(f"bench --fleet: SIGKILL bound "
              f"{'OK' if kill_ok else 'FAILED'} (disk {fr_rows} vs "
              f"hub {hub_rows} windows)", file=sys.stderr)

    hub.stop()
    shutil.rmtree(workdir, ignore_errors=True)

    # the throughput bar gates only the FULL run (best-of-3, the
    # BENCH_OBS co-tenant-noise rule): a --quick single window on
    # this shared box swings well past 5% and would flake tier-1 —
    # the quick smoke gates the correctness contracts (exactness,
    # process count, top frame) and records the ratio honestly
    ok = ((result["phase1"]["bar_met"] or quick) and exact_ok
          and sum_ok
          and result.get("phase3", {}).get("kill_bound_met", True))
    result["ok"] = ok
    name = "BENCH_FLEET.quick.json" if quick else "BENCH_FLEET.json"
    path = os.path.join(repo, name)
    with open(path, "w") as f:
        json.dump({**result, "captured_unix": time.time()}, f, indent=1)
    print(f"bench: fleet-telemetry evidence written to {path}",
          file=sys.stderr)
    print(json.dumps({"metric": "fleet_telemetry_ok", "value": ok,
                      "shipped_over_unshipped":
                          result["phase1"]["shipped_over_unshipped"],
                      "quick": quick}))
    if not ok:
        sys.exit(1)


def serve_main() -> None:
    """`bench.py --serve`: the tuning-as-a-service load-generator
    bench (docs/SERVING.md) — one SessionServer process multiplexing
    N concurrent ask/tell sessions onto ONE BatchedEngine instance
    axis, driven over real localhost TCP by T client threads.

    Protocol (full run; --quick sizes in parens):

    * PHASE 1 under the strict trace guard: an in-process server with
      one N-slot group (N=1024 sessions / 64), store memo ON in a
      scratch dir; T connections open N sessions concurrently; ONE
      probe session then drives a full epoch solo (the unloaded
      client-observed ask-latency claim, at full multiplexing width);
      then every session drives barrier-separated ask/tell epoch
      waves, with mid-run session CHURN (each thread closes + reopens
      2 sessions between epochs) — the guard proves join/leave and
      the whole serving loop never retrace the three compiled slot
      programs.  Per-ask latency is recorded client-side (includes
      TCP RTT) AND scraped from the server's own obs plane
      ({"op": "metrics"} -> serve.ask_ms), the satellite the metrics
      registry was built for.
    * PHASE 2 (outside the guard): the sequential per-session
      baselines.  `cold` = fresh single-slot engine per tenant — the
      pre-serving shape (every tune its own engine: trace + compile in
      the loop), measured on a few tenants end to end including
      time-to-first-trial.  `warm` = the same single-slot group reused
      across tenants (join/leave), the strictest baseline: zero
      compile, zero batching — on CPU both sides are throughput-bound
      so this ratio is expected near 1; the instance-axis win is chip
      filling (BENCH_MULTI) and tenant-onboarding amortization, which
      `cold` measures.

    Writes BENCH_SERVE.json (.quick.json for --quick)."""
    quick = "--quick" in sys.argv
    jax, platform = _init_backend(
        cpu_flag="--cpu" in sys.argv,
        wait_for_tpu="--wait-for-tpu" in sys.argv)
    if platform == "cpu:fallback":
        quick = True

    import shutil
    import tempfile
    import threading

    import numpy as np

    from uptune_tpu import obs
    from uptune_tpu.analysis.lock_guard import lock_guard_from_env
    from uptune_tpu.analysis.trace_guard import guard_from_env
    from uptune_tpu.api.session import reset_settings
    from uptune_tpu.exec.space_io import records_from_space
    from uptune_tpu.serve import SessionServer, connect
    from uptune_tpu.serve.group import SessionGroup
    from uptune_tpu.workloads import rosenbrock_space

    reset_settings()
    n_sessions = 64 if quick else 1024
    # connection concurrency scales with the box, sessions do not:
    # on a GIL runtime, client threads beyond ~2x cores add zero
    # throughput and only queue latency into the ask tail — every
    # session stays open and interleaved regardless
    n_threads = max(4, min(16, 2 * (os.cpu_count() or 4)))
    n_threads = min(n_threads, n_sessions // 8)
    epochs = 2 if quick else 3
    dims = 4
    space = rosenbrock_space(dims, -3.0, 3.0)
    records = records_from_space(space)

    def measure(cfg):
        x = np.array([cfg[f"x{i}"] for i in range(dims)])
        return float(np.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2
                            + (1 - x[:-1]) ** 2))

    def measure_all(cfgs):
        """Vectorized chunk measurement for the serve drive (keeps the
        load generator's own GIL share out of the latency it
        measures)."""
        x = np.array([[c[f"x{i}"] for i in range(dims)] for c in cfgs])
        return (100.0 * (x[:, 1:] - x[:, :-1] ** 2) ** 2
                + (1 - x[:, :-1]) ** 2).sum(axis=1).tolist()

    store_dir = tempfile.mkdtemp(prefix="ut_bench_serve_store_")
    trace_out = obs.maybe_enable_from_env()
    churn = {"closed": 0, "opened": 0}
    lat_lock = threading.Lock()

    ask_n = 8   # the 8-build-workers tenant shape: small asks keep
    # every request O(n) (session.py's lazy epoch scan) — the
    # tail-latency protocol the single-digit-ms p95 bar is about
    hist = 256  # dedup-history capacity sized to the tenant's 2-epoch
    # budget (204 rows): a tenant declaring the default 1024 rows pays
    # its commit-time insert-merge device cost for capacity this
    # session never uses — on a 2-core box that device time is the
    # serving path's main CPU competitor

    def drive(client, handles, record_lat=None):
        """One epoch for every session this thread owns: chunked
        ask/tell_many cycles until the epoch commits."""
        n_asks = 0
        lats = []
        for h in handles:
            done = False
            while not done:
                t0 = time.perf_counter()
                trials = h.ask(ask_n)
                lats.append(time.perf_counter() - t0)
                if not trials:
                    # fully memo-served epoch(s) auto-committed
                    done = True
                    continue
                n_asks += len(trials)
                qs = measure_all([t.config for t in trials])
                r = h.tell_many(zip((t.ticket for t in trials), qs))
                done = bool(r.get("committed"))
        if record_lat is not None:
            with lat_lock:
                record_lat.extend(lats)
        return n_asks

    # ---------------- phase 1: the multiplexed server -----------------
    # UT_LOCK_GUARD=1|strict: the runtime lock sanitizer wraps every
    # lock the serving plane creates in here (server, groups, store,
    # wire registries) and verdicts cycles/held-too-long on exit —
    # the dynamic cross-check of lint rules R101–R106, exactly as the
    # trace guard is R005's (docs/LINT.md)
    with lock_guard_from_env(name="serve-bench") as lockg, \
            guard_from_env() as guard:
        srv = SessionServer(port=0, slots=n_sessions,
                            max_sessions=n_sessions + 64,
                            store_dir=store_dir).start()
        group_batch = None
        # indexed deposit (not append): two lists appended from
        # concurrent threads can interleave, pairing thread A's client
        # with thread B's handles — run_epochs would then multiplex
        # two threads onto ONE connection and idle another, folding
        # cross-thread socket-lock waits into the loaded latencies
        clients = [None] * n_threads
        handles_per = [None] * n_threads
        # distribute the remainder so exactly n_sessions open even
        # when n_threads doesn't divide it (cpu_count-dependent)
        base, rem = divmod(n_sessions, n_threads)
        t_open0 = time.perf_counter()

        def open_all(ti):
            c = connect(("127.0.0.1", srv.port))
            hs = [c.open_session(records, seed=ti * 10000 + j,
                                 program="bench-serve",
                                 history_capacity=hist)
                  for j in range(base + (1 if ti < rem else 0))]
            clients[ti] = c
            handles_per[ti] = hs

        ts = [threading.Thread(target=open_all, args=(ti,))
              for ti in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        t_open = time.perf_counter() - t_open0
        group_batch = handles_per[0][0].info["batch"]

        # unloaded latency probe: ONE session drives a full epoch solo
        # (ask_n=1) while the other N-1 sessions sit open — the
        # serving-bench separation of concerns.  Latency is measured
        # here without the load generator's own GIL/queueing share
        # (T cpu-bound client threads co-tenant with the in-process
        # server on this box's few cores), but at full multiplexing
        # width: the probe's first ask pays the group's N-wide propose
        # and the stacked host pull.  Throughput and the loaded
        # client-side distributions come from the epoch waves below.
        probe = handles_per[0][0]
        probe_lat = []
        done = False
        while not done:
            t0 = time.perf_counter()
            trials = probe.ask(1)
            probe_lat.append(time.perf_counter() - t0)
            if not trials:
                done = True
                continue
            qs = measure_all([t.config for t in trials])
            r = probe.tell_many(zip((t.ticket for t in trials), qs))
            done = bool(r.get("committed"))

        # epochs run as barrier-separated waves so each has its own
        # clean wall + latency distribution: epoch 0 carries cold-start
        # effects (first propose of every slot, cold memo) and this
        # box's throughput swings ~2x with co-tenant load (the
        # BENCH_OBS best-of-N rationale), so the steady-state claim
        # comes from the BEST epoch while every epoch is reported
        totals = [[0] * n_threads for _ in range(epochs)]
        epoch_lat = [[] for _ in range(epochs)]
        epoch_t0 = [0.0] * epochs
        epoch_t1 = [0.0] * epochs
        barrier = threading.Barrier(n_threads)

        def run_epochs(ti):
            # a worker that dies without reaching the barrier would
            # park every peer in barrier.wait() forever and hang the
            # bench with no error: abort() breaks the peers out
            # (BrokenBarrierError) so the failure surfaces instead
            try:
                c, hs = clients[ti], handles_per[ti]
                for e in range(epochs):
                    barrier.wait()
                    if ti == 0:
                        epoch_t0[e] = time.perf_counter()
                    totals[e][ti] = drive(c, hs, epoch_lat[e])
                    barrier.wait()
                    if ti == 0:
                        epoch_t1[e] = time.perf_counter()
                    if e == 0:
                        # session churn between epochs: leave + join
                        # must ride the same compiled programs (slot
                        # reuse)
                        for k in range(2):
                            hs[k].close()
                            hs[k] = c.open_session(
                                records, seed=ti * 10000 + 9000 + k,
                                program="bench-serve",
                                history_capacity=hist)
                            with lat_lock:
                                churn["closed"] += 1
                                churn["opened"] += 1
            except BaseException:
                barrier.abort()
                raise

        t_drive0 = time.perf_counter()
        ts = [threading.Thread(target=run_epochs, args=(ti,))
              for ti in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        t_drive = time.perf_counter() - t_drive0
        scrape = clients[0].metrics()
        stats = clients[0].stats()

        # teardown dogfoods the batch frames: one close wave per
        # 128-session chunk instead of one RTT per session
        def close_all(ti):
            c, hs = clients[ti], handles_per[ti]
            for i in range(0, len(hs), 128):
                c.batch([{"op": "close", "session": h.id}
                         for h in hs[i:i + 128]])

        ts = [threading.Thread(target=close_all, args=(ti,))
              for ti in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for c in clients:
            c.close()
        srv.stop()
    obs.finish(trace_out)

    def _pcts(lats):
        ms = np.sort(np.array(lats)) * 1e3
        return {"asks": len(ms),
                "p50_ms": round(float(ms[len(ms) // 2]), 3),
                "p95_ms": round(float(ms[int(len(ms) * 0.95)]), 3),
                "max_ms": round(float(ms[-1]), 1)}

    per_epoch = []
    for e in range(epochs):
        wall = epoch_t1[e] - epoch_t0[e]
        per_epoch.append({**_pcts(epoch_lat[e]), "wall_s": round(wall, 2),
                          "agg_asks_per_s": round(sum(totals[e]) / wall, 1)})
    steady = min(per_epoch, key=lambda d: d["p95_ms"])
    total_asks = sum(sum(t) for t in totals)
    agg = total_asks / t_drive
    all_lat = [v for lats in epoch_lat for v in lats]
    overall = _pcts(all_lat)
    probe_p = _pcts(probe_lat)
    ask_ms = scrape["metrics"]["hists"].get("serve.ask_ms", {})

    # ---------------- phase 2: sequential per-session baselines -------
    # cold: a fresh engine per tenant (the pre-serving shape).  Wrapper
    # REBUILDS per tenant are the measured point, so this phase runs
    # outside the strict guard (cache_main's one-guard-per-phase rule).
    n_cold = 1 if quick else 3
    cold_walls, first_trial = [], []
    cold_asks = 0
    warm_group = None
    for k in range(n_cold):
        t0 = time.perf_counter()
        g = SessionGroup(space, 1, history_capacity=hist)
        s = g.join(seed=5000 + k)
        tr = s.ask(group_batch)
        first_trial.append(time.perf_counter() - t0)
        for e in range(epochs):
            while tr:
                for t in tr:
                    s.tell(t.ticket, measure(t.config))
                if s.pending is None:
                    break
                tr = s.ask(group_batch)
            tr = s.ask(group_batch) if e + 1 < epochs else []
        cold_asks += epochs * group_batch
        s.close()
        cold_walls.append(time.perf_counter() - t0)
        warm_group = g
    t_cold = sum(cold_walls)
    agg_cold = cold_asks / t_cold

    # warm: reuse ONE compiled single-slot group across tenants
    n_warm = 4 if quick else 8
    t0 = time.perf_counter()
    warm_asks = 0
    for k in range(n_warm):
        s = warm_group.join(seed=6000 + k)
        for e in range(epochs):
            for t in s.ask(group_batch):
                s.tell(t.ticket, measure(t.config))
            warm_asks += group_batch
        s.close()
    t_warm = time.perf_counter() - t0
    agg_warm = warm_asks / t_warm

    # ---------------- batched wire plane A/B (ISSUE 20) ---------------
    # The per-shard-ceiling claim: against ONE dedicated server in the
    # sharded tier's per-shard shape (slots = batch width, the
    # `ut route --slots` default — NOT the phase-1 mega-group, whose
    # N-wide proposes would swamp the wire term), W matched-seed
    # session sets drive identical epoch schedules twice over: one arm
    # speaking the per-op protocol (one ask RTT + one tell_many RTT
    # per session per cycle), the other riding multi-op frames
    # (SessionClient.ask_many / tell_many — 2 RTTs per W-session
    # wave).  Client-observed wall, interleaved best-of reps (the
    # BENCH_OBS rule: this box's throughput swings with co-tenant
    # load, so both arms must sample the same weather).  Like the
    # phase-2 baselines this constructs a fresh group, so it runs
    # OUTSIDE the strict guard; store stays off, so matched seeds
    # make the parity check exact: frames may change nothing but the
    # transport — each session's offered-config trajectory must be
    # bitwise identical across arms.
    ab_w = 8
    ab_epochs = 2
    ab_reps = 3 if quick else 5
    ab_srv = SessionServer(port=0, slots=ab_w,
                           max_sessions=4 * ab_w,
                           store_dir="off").start()
    abc = connect(("127.0.0.1", ab_srv.port))

    def _ab_open(seed0):
        return [abc.open_session(records, seed=seed0 + i,
                                 program="bench-ab", store=False,
                                 history_capacity=hist)
                for i in range(ab_w)]

    def _ab_seq(hs, traj):
        """Per-op arm: the pre-frame wire shape."""
        n = 0
        t0 = time.perf_counter()
        for _e in range(ab_epochs):
            for i, h in enumerate(hs):
                done = False
                while not done:
                    tr = h.ask(ask_n)
                    if not tr:
                        done = True
                        continue
                    n += len(tr)
                    cfgs = [t.config for t in tr]
                    traj[i].append(cfgs)
                    qs = measure_all(cfgs)
                    r = h.tell_many(zip((t.ticket for t in tr), qs))
                    done = bool(r.get("committed"))
        return n, time.perf_counter() - t0

    def _ab_bat(hs, traj):
        """Frame arm: one ask frame + one tell_many frame per wave
        across every live session.  Measurement stays per-session
        (identical cost to the per-op arm) so the ratio prices the
        wire plane, not objective batching."""
        n = 0
        t0 = time.perf_counter()
        idx = {id(h): i for i, h in enumerate(hs)}
        for _e in range(ab_epochs):
            live = list(hs)
            while live:
                offers = abc.ask_many(live, n=ask_n)
                pairs, keep = [], []
                for h, tr in zip(live, offers):
                    if not tr:
                        continue
                    n += len(tr)
                    cfgs = [t.config for t in tr]
                    traj[idx[id(h)]].append(cfgs)
                    qs = measure_all(cfgs)
                    pairs.append(
                        (h, list(zip((t.ticket for t in tr), qs))))
                    keep.append(h)
                if not pairs:
                    break
                replies = abc.tell_many(pairs)
                live = [h for h, r in zip(keep, replies)
                        if not r.get("committed")]
        return n, time.perf_counter() - t0

    try:
        # warmup pair outside timing: group construction + compile
        # land on the first open; both arms then run warm
        hs = _ab_open(318000)
        _ab_seq(hs, [[] for _ in range(ab_w)])
        for h in hs:
            h.close()
        seq_t, bat_t = [], []
        asks_seq = asks_bat = 0
        parity_ok = True
        for rep in range(ab_reps):
            s0 = 320000 + rep * 1000
            tr_s = [[] for _ in range(ab_w)]
            tr_b = [[] for _ in range(ab_w)]
            for arm in ((0, 1) if rep % 2 == 0 else (1, 0)):
                if arm == 0:
                    hs = _ab_open(s0)
                    n_, t = _ab_seq(hs, tr_s)
                    seq_t.append(t)
                    asks_seq = n_
                else:
                    hs = _ab_open(s0)
                    n_, t = _ab_bat(hs, tr_b)
                    bat_t.append(t)
                    asks_bat = n_
                for h in hs:
                    h.close()
            if json.dumps(tr_s) != json.dumps(tr_b):
                parity_ok = False
    finally:
        abc.close()
        ab_srv.stop()
    assert asks_seq == asks_bat, (asks_seq, asks_bat)
    ab_ratio = min(seq_t) / min(bat_t)
    batched_wire = {
        "batch_width": ab_w,
        "slots": ab_w,
        "epochs_per_arm": ab_epochs,
        "reps": ab_reps,
        "asks_per_arm": asks_seq,
        "ratio_batched_over_sequential": round(ab_ratio, 2),
        "bar": 2.0,
        "bar_met": bool(ab_ratio >= 2.0),
        "parity_ok": parity_ok,
        "sequential_best_s": round(min(seq_t), 4),
        "batched_best_s": round(min(bat_t), 4),
        "sequential_agg_asks_per_s": round(asks_seq / min(seq_t), 1),
        "batched_agg_asks_per_s": round(asks_bat / min(bat_t), 1),
    }

    # ---------------- phase 3 (--quick): lock-sanitizer overhead ------
    # the shipping bar for leaving UT_LOCK_GUARD on in diagnostic runs:
    # the SAME handle()-level serving drive (the op surface every
    # throughput number above is made of) against a server whose locks
    # were created UNDER an installed LockGuard — every acquire/release
    # through klock/group/registry pays the proxy bookkeeping — must
    # hold >= 0.95x the raw-lock server.  Interleaved best-of reps:
    # this box's throughput swings with co-tenant load (the BENCH_OBS
    # best-of-N rule), so off/on pairs sample the same weather
    lock_overhead = None
    if quick:
        from uptune_tpu.analysis.lock_guard import LockGuard

        lg_sessions = 4

        def _lg_server(seed0: int):
            s = SessionServer(port=0, slots=lg_sessions,
                              max_sessions=lg_sessions + 4,
                              store_dir="off")
            sids = []
            for i in range(lg_sessions):
                r = s.handle({"op": "open", "space": records,
                              "seed": seed0 + i, "store": "off"})
                assert r["ok"], r
                sids.append(r["session"])
            return s, sids

        def _lg_drive(s, sids):
            """One committed epoch wave across every session, through
            the transport-free dispatch seam (failover phase-1 drive)."""
            n = 0
            t0 = time.perf_counter()
            for sid in sids:
                done = False
                while not done:
                    a = s.handle({"op": "ask", "session": sid, "n": 16})
                    if not a["trials"]:
                        done = True
                        continue
                    n += len(a["trials"])
                    res = [{"ticket": t["ticket"],
                            "qor": measure(t["config"]),
                            "epoch": t["epoch"]}
                           for t in a["trials"]]
                    tl = s.handle({"op": "tell", "session": sid,
                                   "results": res,
                                   "incarn": a["incarn"]})
                    done = bool(tl.get("committed"))
            return n, time.perf_counter() - t0

        srv_off, sids_off = _lg_server(7000)
        sanitizer = LockGuard(name="serve-overhead").install()
        # constructed while installed: THIS server's locks are proxied
        srv_on, sids_on = _lg_server(7100)
        try:
            _lg_drive(srv_off, sids_off)    # warmup pair: compile +
            _lg_drive(srv_on, sids_on)      # cache fill outside timing
            off_t, on_t = [], []
            asks_rep = 0
            # min-of-7 with rotating order: per-rep walls on this box
            # swing +-30% with co-tenant load, so both sides must get
            # enough draws to touch the quiet floor, uncorrelated with
            # position in the rep
            for rep in range(7):
                pair = ((srv_off, sids_off, off_t),
                        (srv_on, sids_on, on_t))
                for s_, i_, acc in (pair if rep % 2 == 0
                                    else pair[::-1]):
                    n_, t = _lg_drive(s_, i_)
                    acc.append(t)
                    asks_rep = n_
        finally:
            sanitizer.uninstall()
            srv_on.stop()
            srv_off.stop()
        srep = sanitizer.report()
        lg_ratio = min(off_t) / min(on_t)
        lock_overhead = {
            "guarded_over_unguarded": round(lg_ratio, 4),
            "bar": 0.95,
            "bar_met": bool(lg_ratio >= 0.95),
            "unguarded_best_s": round(min(off_t), 4),
            "guarded_best_s": round(min(on_t), 4),
            "asks_per_rep": asks_rep,
            "acquires": srep["acquires"],
            "locks": srep["locks"],
            "cycles": srep["cycles"],
        }

    counters = scrape["metrics"]["counters"]
    result = {
        "metric": "serve_aggregate_asks_per_sec",
        "value": round(agg, 1),
        "unit": "asks/s (aggregate across concurrent sessions)",
        "platform": platform,
        "quick": quick,
        "n_sessions": n_sessions,
        "n_client_threads": n_threads,
        "epochs": epochs,
        "batch_per_epoch": group_batch,
        "asks_total": total_asks,
        "open_wall_s": round(t_open, 2),
        "drive_wall_s": round(t_drive, 2),
        # THE latency claim: client-observed (incl. TCP RTT), solo
        # probe at full multiplexing width.  The `loaded` views below
        # additionally time the load generator itself — T cpu-bound
        # client threads sharing this box's cores+GIL with the
        # in-process server (harness co-tenancy, not serving time);
        # server_ask_ms is the server's own per-ask obs histogram
        # under that full load.  Loaded steady state = the best
        # barrier-separated epoch wave (epoch 0 carries every slot's
        # first propose + a cold memo; the box also swings with
        # co-tenant load — the BENCH_OBS best-of-N rule)
        "ask_p50_ms": probe_p["p50_ms"],
        "ask_p95_ms": probe_p["p95_ms"],
        "ask_probe_asks": probe_p["asks"],
        "ask_loaded_p50_ms": steady["p50_ms"],
        "ask_loaded_p95_ms": steady["p95_ms"],
        "ask_loaded_p95_all_epochs_ms": overall["p95_ms"],
        "ask_max_ms": overall["max_ms"],
        "per_epoch": per_epoch,
        "server_ask_ms": ask_ms,
        "batch_fill": scrape["metrics"]["gauges"].get(
            "serve.batch_fill"),
        "proposes": counters.get("serve.proposes"),
        "commits": counters.get("serve.commits"),
        "store_served_rows": counters.get("serve.store_served", 0),
        "churn": churn,
        "baseline_cold_sequential": {
            "tenants": n_cold,
            "agg_asks_per_s": round(agg_cold, 1),
            "tenant_wall_s": [round(w, 2) for w in cold_walls],
            "time_to_first_trial_s": [round(w, 2) for w in first_trial],
        },
        "baseline_warm_single_slot": {
            "tenants": n_warm,
            "agg_asks_per_s": round(agg_warm, 1),
        },
        "speedup_vs_cold_sequential": round(agg / agg_cold, 1),
        "speedup_vs_warm_sequential": round(agg / agg_warm, 2),
        "serve_time_to_first_trial_s": round(t_open / n_sessions, 4),
        "batched_wire": batched_wire,
        "nproc": os.cpu_count(),
    }
    if guard.enabled:
        result["retraces"] = guard.report()
    if lockg.enabled:
        result["lock_sanitizer"] = lockg.report()
    if lock_overhead is not None:
        result["lock_guard_overhead"] = lock_overhead

    artifact = {
        **result,
        "devices": repr(jax.devices()),
        "jax_version": jax.__version__,
        "captured_unix": time.time(),
        "store_stats": stats.get("stores"),
        "protocol": {
            "space": f"rosenbrock-{dims}d",
            "transport": "newline-JSON over localhost TCP, "
                         f"{n_threads} connections multiplexing "
                         f"{n_sessions} sessions",
            "serve_phase": "open all concurrently; solo probe epoch "
                           f"(ask_n=1); {epochs} barrier-separated "
                           "ask/tell epoch waves per session with "
                           "tell_many batching; 2 close+reopen "
                           "churns per thread after epoch 0; strict "
                           "trace guard over the WHOLE phase "
                           "including server construction",
            "ask_latency": "ask_p*_ms: client-side per-ask wall "
                           "(TCP RTT + any propose/pull the ask "
                           "triggers) from a solo probe session at "
                           "full multiplexing width; ask_loaded_*: "
                           "same measure during the drive phase, "
                           "where the cpu-bound load-generator "
                           "threads share the box with the "
                           "in-process server; server_ask_ms: the "
                           "server's own obs histogram under load",
            "cold_baseline": "fresh single-slot engine per tenant, "
                             "end to end (construction + trace + "
                             "compile + drive) — what per-session "
                             "serving costs without the shared "
                             "group; time_to_first_trial_s is its "
                             "onboarding latency vs "
                             "serve_time_to_first_trial_s",
            "warm_baseline": "ONE single-slot group reused across "
                             "tenants (join/leave), zero compile — "
                             "the strictest baseline; near-1 ratios "
                             "on CPU are expected (both sides "
                             "throughput-bound; the instance axis "
                             "exists to fill a chip, BENCH_MULTI)",
            "batched_wire": "dedicated server in the per-shard shape "
                            "(slots = batch width, the ut route "
                            "--slots default), matched-seed "
                            "8-session arms, identical epoch "
                            "schedules: per-op requests vs multi-op "
                            "frames (ask_many/tell_many — 2 RTTs "
                            "per wave); interleaved best-of reps; "
                            "parity = per-session offered-config "
                            "trajectories bitwise equal across arms",
        },
    }
    name = "BENCH_SERVE.quick.json" if quick else "BENCH_SERVE.json"
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), name)
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1)
    shutil.rmtree(store_dir, ignore_errors=True)
    print(f"bench: serving evidence written to {path}", file=sys.stderr)
    print(json.dumps(result))
    if lock_overhead is not None and (
            not lock_overhead["bar_met"] or lock_overhead["cycles"]):
        print("bench --serve: lock-sanitizer gate FAILED "
              f"(ratio {lock_overhead['guarded_over_unguarded']} vs "
              f"bar {lock_overhead['bar']}, "
              f"cycles {lock_overhead['cycles']})", file=sys.stderr)
        sys.exit(1)
    if not batched_wire["parity_ok"]:
        # determinism, not weather: matched-seed arms diverging means
        # the frames changed semantics, not just transport — gated in
        # quick AND full runs
        print("bench --serve: batched-wire PARITY FAILED (matched-seed"
              " frame arm diverged from the per-op arm)",
              file=sys.stderr)
        sys.exit(1)
    if not quick and not batched_wire["bar_met"]:
        print("bench --serve: batched-wire gate FAILED (ratio "
              f"{batched_wire['ratio_batched_over_sequential']} vs "
              f"bar {batched_wire['bar']} at width "
              f"{batched_wire['batch_width']})", file=sys.stderr)
        sys.exit(1)


def failover_main() -> None:
    """`bench.py --failover`: the crash-safe serving bench (ISSUE 15,
    docs/SERVING.md "Durability & failover").

    Phase 1 — durability overhead: matched in-process serve drives
    (same seeds, sessions, epochs) with the checkpoint plane OFF vs
    ON; best-of-reps durable/non-durable agg asks/s must hold the
    repo's >= 0.95x observability bar.

    Phase 2 — the kill: a real `ut serve --durable` subprocess
    serving concurrently-driven auto-resume clients is crashed
    DETERMINISTICALLY mid-stream (UT_FAULTS arms a `crash` schedule
    on the `ckpt.append` fault point — os._exit with no flush, the
    SIGKILL stand-in, landing exactly in the commit-vs-checkpoint
    window the loss bound is about).  A recovery server is then
    constructed in-process on the SAME port under the STRICT trace
    guard (recovery replay + resumed serving must trace each slot
    program exactly once); the clients reconnect with backoff+jitter,
    re-attach their durable session ids, replay their idempotent
    frontier, and drive to completion.  Asserted: zero acked
    committed version is ever lost (monotone resume), and every final
    session state — best config bit-for-bit, qor, version — equals an
    uninterrupted matched-seed LocalSession run.  Recovery time and
    checkpoint accounting land in the artifact.

    Writes BENCH_FAILOVER.json (.quick.json for --quick)."""
    quick = "--quick" in sys.argv
    from uptune_tpu.utils.platform_guard import force_cpu
    force_cpu(1)
    import jax  # noqa: F401  (backend must init after force_cpu)

    import shutil
    import socket as _socket
    import subprocess
    import tempfile
    import threading

    import numpy as np

    from uptune_tpu.analysis.lock_guard import lock_guard_from_env
    from uptune_tpu.analysis.trace_guard import TraceGuard
    from uptune_tpu.api.session import reset_settings
    from uptune_tpu.exec.space_io import records_from_space
    from uptune_tpu.serve import ServeError, SessionServer, connect
    from uptune_tpu.serve.session import LocalSession
    from uptune_tpu.workloads import rosenbrock_space

    reset_settings()
    # UT_LOCK_GUARD: sanitize the whole bench — overhead drives, the
    # in-process recovery server, checkpoint plane, clients.  The
    # crashed subprocess inherits the env but installs nothing, so the
    # kill itself is unaffected
    lockg = lock_guard_from_env(name="failover-bench").install()
    repo = os.path.dirname(os.path.abspath(__file__))
    workdir = tempfile.mkdtemp(prefix="ut_failover_bench_")
    result: dict = {"metric": "serve_failover", "quick": quick,
                    "nproc": os.cpu_count()}
    dims = 2
    space = rosenbrock_space(dims, -3.0, 3.0)
    records = records_from_space(space)

    def measure(cfg):
        x = np.array([cfg[f"x{i}"] for i in range(dims)])
        return float(np.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2
                            + (1 - x[:-1]) ** 2))

    # ---- phase 1: checkpoint-plane overhead --------------------------
    # matched single-threaded in-process drives through handle() (no
    # TCP noise): the durable side additionally journals one commit
    # record per published version — the whole added cost
    p1_sessions = 4 if quick else 16
    p1_epochs = 2 if quick else 3
    reps = 1 if quick else 3

    def p1_drive(durable_dir):
        kw = {"host": "127.0.0.1", "port": 0, "slots": p1_sessions,
              "max_sessions": p1_sessions + 4, "store_dir": "off",
              "work_dir": workdir}
        if durable_dir:
            kw["durable"] = durable_dir
        srv = SessionServer(**kw)
        sids = []
        for i in range(p1_sessions):
            r = srv.handle({"op": "open", "space": records,
                            "seed": 1000 + i, "store": "off"})
            assert r["ok"], r
            sids.append(r["session"])
        asks = 0
        t0 = time.perf_counter()
        for _ in range(p1_epochs):
            for sid in sids:
                done = False
                while not done:
                    a = srv.handle({"op": "ask", "session": sid,
                                    "n": 16})
                    if not a["trials"]:
                        done = True
                        continue
                    asks += len(a["trials"])
                    res = [{"ticket": t["ticket"],
                            "qor": measure(t["config"]),
                            "epoch": t["epoch"]}
                           for t in a["trials"]]
                    tl = srv.handle({"op": "tell", "session": sid,
                                     "results": res,
                                     "incarn": a["incarn"]})
                    done = bool(tl.get("committed"))
        wall = time.perf_counter() - t0
        srv.stop()
        return asks / wall

    plain, durable = [], []
    for rep in range(reps):
        # rotate mode order per rep so co-tenant drift is uncorrelated
        # with mode (the BENCH_OBS rule)
        for mode in (("p", "d") if rep % 2 == 0 else ("d", "p")):
            if mode == "p":
                plain.append(p1_drive(None))
            else:
                durable.append(p1_drive(os.path.join(
                    workdir, f"ckpt_p1_{rep}")))
    ratio = max(durable) / max(plain)
    result["phase1"] = {
        "sessions": p1_sessions, "epochs": p1_epochs, "reps": reps,
        "plain_asks_per_s": [round(r, 1) for r in plain],
        "durable_asks_per_s": [round(r, 1) for r in durable],
        "durable_over_plain": round(ratio, 4),
        "bar": 0.95, "bar_met": ratio >= 0.95,
    }
    print(f"bench --failover: durable/plain asks ratio {ratio:.4f} "
          f"(bar 0.95)", file=sys.stderr)

    # ---- phase 2: the deterministic kill -----------------------------
    n_sessions = 3 if quick else 8
    epochs = 3 if quick else 5
    chunk = 8
    slots = n_sessions
    store_dir = os.path.join(workdir, "store")
    # crash inside the Kth checkpoint append: past the opens and a
    # first committed wave, squarely mid-stream (and exactly in the
    # commit-vs-append window — the hardest loss-bound edge)
    crash_at = n_sessions * 2 + 1
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    child_env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo,
                     UT_FAULTS=f"ckpt.append=crash@{crash_at}")
    serve_cmd = [sys.executable, "-m", "uptune_tpu.cli", "serve",
                 "--port", str(port), "--slots", str(slots),
                 "--store-dir", store_dir, "--durable",
                 "--work-dir", workdir]
    child = subprocess.Popen(serve_cmd, cwd=workdir, env=child_env,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
    deadline = time.time() + 300
    while time.time() < deadline:
        try:
            probe = _socket.create_connection(("127.0.0.1", port),
                                              timeout=2)
            probe.close()
            break
        except OSError:
            if child.poll() is not None:
                raise RuntimeError("ut serve died before ready: "
                                   + child.communicate()[0][-2000:])
            time.sleep(0.25)
    else:
        raise RuntimeError("ut serve never came up")

    seeds = [7000 + i for i in range(n_sessions)]
    per_sess: dict = {}
    errors: list = []
    lock = threading.Lock()

    def drive(seed):
        try:
            c = connect(("127.0.0.1", port), timeout=120,
                        auto_resume=True, max_retries=80,
                        backoff_base=0.25, backoff_max=2.0)
            h = c.open_session(records, seed=seed,
                               program=f"failover-{seed}")
            memo: dict = {}
            acked_committed = 0
            resume_floor_ok = True
            stop_at = time.time() + 600
            while h.version < epochs:
                if time.time() > stop_at:
                    raise RuntimeError(
                        f"seed {seed} wedged at v{h.version}")
                trials = h.ask(chunk)
                if not trials:
                    continue
                res = []
                for t in trials:
                    key = json.dumps(t.config, sort_keys=True)
                    if key not in memo:
                        memo[key] = measure(t.config)
                    res.append((t.ticket, memo[key]))
                r = h.tell_many(res)
                # the zero-committed-loss contract, client-observed:
                # an acked committed version may never regress (a
                # resumed attach below it = lost durable state).  A
                # reply whose elements ALL failed (restored-epoch
                # errors after the crash) carries no version at all
                v = r.get("version")
                if v is not None:
                    if int(v) < acked_committed:
                        resume_floor_ok = False
                    if r.get("committed"):
                        acked_committed = max(acked_committed, int(v))
            best = h.best()
            with lock:
                per_sess[seed] = {
                    "best": best, "acked_committed": acked_committed,
                    "monotone": resume_floor_ok,
                    "reconnects": c.reconnects}
            h.close()
            c.close()
        except Exception as e:   # surfaced below
            with lock:
                errors.append((seed, repr(e)))

    threads = [threading.Thread(target=drive, args=(sd,))
               for sd in seeds]
    t_kill0 = time.perf_counter()
    for t in threads:
        t.start()
    # the child dies at its crash_at-th checkpoint append
    child.wait()
    t_crash = time.perf_counter()
    crash_rc = child.returncode
    # recovery server, in-process, SAME port, strict guard: replay +
    # resumed serving must trace each slot program exactly once
    with TraceGuard(limit=1, strict=True,
                    name="failover-recovery") as tg:
        srv = SessionServer(host="127.0.0.1", port=port, slots=slots,
                            max_sessions=n_sessions + 4,
                            store_dir=store_dir, durable="on",
                            work_dir=workdir).start()
        t_ready = time.perf_counter()
        for t in threads:
            t.join()
        stats = srv.handle({"op": "stats"})
        srv.stop()
    guard_counts = {k: v for k, v in tg.counts.items()
                    if "Engine" in k}
    assert not errors, errors

    # uninterrupted matched-seed baselines: bitwise state parity
    parity = []
    for sd in seeds:
        ls = LocalSession(space, seed=sd)
        try:
            while ls.version < epochs:
                for t in ls.ask(chunk):
                    ls.tell(t.ticket, measure(t.config))
            want = ls.best()
        finally:
            ls.close()
        got = per_sess[sd]["best"]
        parity.append({
            "seed": sd,
            "config_equal": got["config"] == want["config"],
            "qor_equal": got["qor"] == want["qor"],
            "version_equal": got["version"] == want["version"]
                             == epochs,
        })
    parity_ok = all(p["config_equal"] and p["qor_equal"]
                    and p["version_equal"] for p in parity)
    monotone_ok = all(per_sess[sd]["monotone"] for sd in seeds)
    loss_ok = all(per_sess[sd]["best"]["version"]
                  >= per_sess[sd]["acked_committed"] for sd in seeds)
    guard_ok = all(v == 1 for v in guard_counts.values()) \
        and len(guard_counts) == 3
    durable_stats = stats.get("durable", {})
    result["phase2"] = {
        "sessions": n_sessions, "epochs": epochs,
        "crash_at_append": crash_at, "crash_rc": crash_rc,
        "crash_to_ready_s": round(t_ready - t_crash, 2),
        "recovery_replay_s": durable_stats.get("recovery_s"),
        "recovered_sessions": durable_stats.get("recovered"),
        "ckpt": durable_stats,
        "kill_wall_s": round(t_ready - t_kill0, 2),
        "client_reconnects": {str(sd): per_sess[sd]["reconnects"]
                              for sd in seeds},
        "parity": parity, "parity_bitwise_ok": parity_ok,
        "acked_committed_monotone": monotone_ok,
        "zero_committed_loss": loss_ok,
        "trace_guard": {"strict": True, "counts": guard_counts,
                        "clean": guard_ok},
    }
    print(f"bench --failover: kill/restart parity "
          f"{'OK' if parity_ok else 'FAILED'} (recovered "
          f"{durable_stats.get('recovered')} sessions in "
          f"{durable_stats.get('recovery_s')}s, crash rc {crash_rc})",
          file=sys.stderr)

    shutil.rmtree(workdir, ignore_errors=True)
    lockg.uninstall()
    if lockg.enabled:
        result["lock_sanitizer"] = lockg.report()
        lockg.check()   # strict: raise on any lock-order cycle
    # the throughput bar gates only the FULL run (the BENCH_OBS /
    # BENCH_FLEET co-tenant-noise rule): a --quick single rep on this
    # shared box swings well past 5% — the quick smoke gates the
    # correctness contracts and records the ratio honestly
    ok = ((result["phase1"]["bar_met"] or quick) and parity_ok
          and monotone_ok and loss_ok and guard_ok
          and durable_stats.get("recovered") == n_sessions)
    result["ok"] = ok
    name = "BENCH_FAILOVER.quick.json" if quick else "BENCH_FAILOVER.json"
    path = os.path.join(repo, name)
    with open(path, "w") as f:
        json.dump({**result, "captured_unix": time.time()}, f, indent=1)
    print(f"bench: failover evidence written to {path}",
          file=sys.stderr)
    print(json.dumps({"metric": "serve_failover_ok", "value": ok,
                      "durable_over_plain":
                          result["phase1"]["durable_over_plain"],
                      "crash_to_ready_s":
                          result["phase2"]["crash_to_ready_s"],
                      "quick": quick}))
    if not ok:
        sys.exit(1)


def serve_sharded_main() -> None:
    """`bench.py --serve-sharded`: the sharded front tier bench
    (ISSUE 17, docs/SERVING.md "Sharded front tier").

    Phase 1 — scaling: an in-process Router consistent-hash routes
    sessions (one per distinct space signature) onto K real `ut serve
    --durable` shard subprocesses over localhost TCP, for K walked up
    via the `scale` op; aggregate asks/s is RECORDED per K (never
    gated — on this 1-core CI box K cold shards share one core and
    the co-tenant-noise rule applies; the artifact's value is the
    curve on real multi-core boxes).

    Phase 2 — the kill: with the full tier serving auto-resume
    clients mid-stream, a `route.kill` fault schedule (obs/faults.py)
    makes the router's supervisor SIGKILL its lowest-index shard on
    an exact tick.  The supervisor respawns it on the SAME port with
    the SAME checkpoint dir; `ut serve --durable` recovery replays
    its sessions and the clients reconnect with backoff, re-attach by
    durable id, and replay their idempotent frontier.  Asserted: a
    single deterministic kill and respawn happened, zero acked
    committed version was lost (monotone resume), every session
    finished, and each final state — best config bit-for-bit, qor,
    version — equals an uninterrupted matched-seed LocalSession run
    (the parity replays run under the STRICT trace guard: one trace
    per engine program per group, no retrace churn).

    Writes BENCH_SERVE_SHARDED.json (.quick.json for --quick)."""
    quick = "--quick" in sys.argv
    from uptune_tpu.utils.platform_guard import force_cpu
    force_cpu(1)
    import jax  # noqa: F401  (backend must init after force_cpu)

    import shutil
    import tempfile
    import threading

    import numpy as np

    from uptune_tpu.analysis.lock_guard import lock_guard_from_env
    from uptune_tpu.analysis.trace_guard import TraceGuard
    from uptune_tpu.api.session import reset_settings
    from uptune_tpu.exec.space_io import records_from_space
    from uptune_tpu.obs import faults
    from uptune_tpu.serve import connect
    from uptune_tpu.serve.router import HashRing, Router, routing_key
    from uptune_tpu.serve.session import LocalSession
    from uptune_tpu.workloads import rosenbrock_space

    reset_settings()
    # UT_LOCK_GUARD: sanitize the whole bench — the router, its
    # supervisor, the embedded hub, every client thread.  Shard
    # subprocesses install nothing (their own planes are lint-clean)
    lockg = lock_guard_from_env(name="sharded-bench").install()
    repo = os.path.dirname(os.path.abspath(__file__))
    workdir = tempfile.mkdtemp(prefix="ut_sharded_bench_")
    result: dict = {"metric": "serve_sharded", "quick": quick,
                    "nproc": os.cpu_count()}
    dims = 2
    n_spaces = 3 if quick else 6
    epochs = 2 if quick else 4
    chunk = 8
    k_steps = [1, 2] if quick else [1, 2, 3]
    k_max = k_steps[-1]

    def measure(cfg):
        x = np.array([cfg[f"x{i}"] for i in range(dims)])
        return float(np.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2
                            + (1 - x[:-1]) ** 2))

    # distinct spaces = distinct routing keys = cross-shard spread.
    # Shard names are deterministic (s0..s{K-1}), so placement is a
    # pure function of the space bounds: walk a deterministic offset
    # until the kill victim (s0, the lowest index) owns SOME but not
    # ALL sessions at K_max — the kill must hit real tenants AND
    # leave unaffected tenants to prove isolation
    ring = HashRing()
    for i in range(k_max):
        ring.add(f"s{i}")
    spaces, records, owners = [], [], []
    for o in range(64):
        spaces = [rosenbrock_space(dims, -3.0 - i - o * 0.125,
                                   3.0 + i + o * 0.125)
                  for i in range(n_spaces)]
        records = [records_from_space(sp) for sp in spaces]
        owners = [ring.lookup(routing_key(r)) for r in records]
        if (len(set(owners)) == k_max
                and 1 <= owners.count("s0") < n_spaces):
            break
    result["placement"] = {"owners": owners, "offset_steps": o}

    store_dir = os.path.join(workdir, "store")
    router = Router(host="127.0.0.1", port=0, shards=0,
                    slots=4, max_sessions=n_spaces * 2 + 8,
                    store_dir=store_dir, work_dir=workdir,
                    supervise_interval=0.5)
    router.start()

    per_sess: dict = {}
    errors: list = []
    lock = threading.Lock()

    def drive(idx, seed, tag, n_epochs, hold_ev=None):
        """One auto-resume client driving one session to `n_epochs`
        committed versions through the router (open is redirected to
        the owning shard; everything after runs shard-direct).  With
        `hold_ev`, a session placed on the kill victim pauses AFTER
        its first committed epoch until the kill has fired — the
        deterministic mid-stream guarantee: committed state exists
        when the shard dies, later epochs happen across the resume."""
        try:
            c = connect(("127.0.0.1", router.port), timeout=120,
                        auto_resume=True, max_retries=80,
                        backoff_base=0.25, backoff_max=2.0)
            h = c.open_session(records[idx], seed=seed,
                               program=f"sharded-{idx}")
            memo: dict = {}
            asks = 0
            acked_committed = 0
            resume_floor_ok = True
            stop_at = time.time() + 600
            while h.version < n_epochs:
                if time.time() > stop_at:
                    raise RuntimeError(
                        f"{tag}/{idx} wedged at v{h.version}")
                if hold_ev is not None and owners[idx] == "s0" \
                        and h.version >= 1:
                    hold_ev.wait(timeout=300)
                trials = h.ask(chunk)
                if not trials:
                    continue
                asks += len(trials)
                res = []
                for t in trials:
                    key = json.dumps(t.config, sort_keys=True)
                    if key not in memo:
                        memo[key] = measure(t.config)
                    res.append((t.ticket, memo[key]))
                r = h.tell_many(res)
                # the zero-committed-loss contract, client-observed
                # (the failover bench rule): an acked committed
                # version may never regress after a resume
                v = r.get("version")
                if v is not None:
                    if int(v) < acked_committed:
                        resume_floor_ok = False
                    if r.get("committed"):
                        acked_committed = max(acked_committed, int(v))
            best = h.best()
            with lock:
                per_sess[(tag, idx)] = {
                    "best": best, "asks": asks,
                    "acked_committed": acked_committed,
                    "monotone": resume_floor_ok,
                    "reconnects": c.reconnects,
                    "redirects": c.redirects,
                    "shard": f"{c.host}:{c.port}"}
            h.close()
            c.close()
        except Exception as e:   # surfaced below
            with lock:
                errors.append((tag, idx, repr(e)))

    def run_round(tag, seed_base, n_epochs, mid_round=None,
                  hold_ev=None):
        threads = [threading.Thread(
            target=drive, args=(i, seed_base + i, tag, n_epochs,
                                hold_ev))
                   for i in range(n_spaces)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        if mid_round is not None:
            mid_round()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        assert not errors, errors
        asks = sum(per_sess[(tag, i)]["asks"]
                   for i in range(n_spaces))
        return asks / wall, wall

    try:
        # ---- phase 1: aggregate asks/s vs K --------------------------
        rates = {}
        for ki, k in enumerate(k_steps):
            r = router.handle({"op": "scale", "shards": k})
            assert r["ok"] and r["live"] == k, r
            rate, wall = run_round(f"k{k}", 5000 + 1000 * ki, epochs)
            rates[str(k)] = round(rate, 1)
            print(f"bench --serve-sharded: K={k} agg "
                  f"{rate:.1f} asks/s ({wall:.1f}s)", file=sys.stderr)
        ks = [rates[str(k)] for k in k_steps]
        result["phase1"] = {
            "sessions": n_spaces, "epochs": epochs,
            "k_steps": k_steps, "agg_asks_per_s": rates,
            # recorded, NOT gated: K shards share one core here
            "monotone_recorded": all(b >= a for a, b
                                     in zip(ks, ks[1:])),
        }

        # ---- phase 2: the deterministic kill -------------------------
        # every session opens and commits its first epoch; sessions on
        # the victim then HOLD (see drive) while route.kill is armed —
        # the supervisor SIGKILLs shard s0 on its next tick, the hold
        # releases, and the held sessions drive their remaining epochs
        # across the respawn through auto-resume
        epochs_kill = epochs + 2
        scrape = {}
        kill_seen = threading.Event()
        mapped0 = router.handle({"op": "ping"})["sessions"]

        def mid_round():
            # wait until every phase-2 session is mapped (all opens
            # done) before arming, so the kill can't race an open
            deadline = time.time() + 300
            while time.time() < deadline:
                st = router.handle({"op": "ping"})
                if st.get("sessions", 0) >= mapped0 + n_spaces:
                    break
                time.sleep(0.1)
            faults.arm("route.kill", "error",
                       at=faults.hits("route.kill") + 1)
            deadline = time.time() + 60
            while time.time() < deadline and router.kills < 1:
                time.sleep(0.1)
            kill_seen.set()     # release the held victims
            # mid-drive fleet scrape for the artifact: the router's
            # metrics op re-serves the hub rollup in the `ut top`
            # shape, population gauges summed across shards
            deadline = time.time() + 20
            m = {}
            while time.time() < deadline:
                m = router.handle({"op": "metrics"})
                if m.get("sessions"):
                    break
                time.sleep(0.5)
            scrape.update({"sessions": m.get("sessions"),
                           "shards": m.get("shards"),
                           "sources": m.get("sources")})

        rate, wall = run_round("kill", 6000, epochs_kill,
                               mid_round=mid_round, hold_ev=kill_seen)
        faults.disarm()
        stats = router.handle({"op": "stats"})
        assert stats["ok"], stats
        result["fleet_scrape_mid_drive"] = scrape

        # uninterrupted matched-seed baselines: bitwise state parity.
        # STRICT trace guard: each space compiles its own engine
        # group, and the guard counts group 2..N's wrappers as
        # "rebuilt after trace" against the BASE label — so the
        # strict budget is n_spaces (the backstop for gross churn);
        # the EXACT gate is guard_ok below: every wrapper label
        # traced exactly once, three programs, n_spaces each
        parity = []
        with TraceGuard(limit=n_spaces, strict=True,
                        name="sharded-parity") as tg:
            for i in range(n_spaces):
                ls = LocalSession(spaces[i], seed=6000 + i)
                try:
                    while ls.version < epochs_kill:
                        for t in ls.ask(chunk):
                            ls.tell(t.ticket, measure(t.config))
                    want = ls.best()
                finally:
                    ls.close()
                got = per_sess[("kill", i)]["best"]
                parity.append({
                    "space": i, "owner": owners[i],
                    "config_equal": got["config"] == want["config"],
                    "qor_equal": got["qor"] == want["qor"],
                    "version_equal": got["version"] == want["version"]
                                     == epochs_kill,
                })
        guard_counts = {k: v for k, v in tg.counts.items()
                        if "Engine" in k}
        # fold the #N wrapper suffixes back to base programs: three
        # engine programs, each traced once per space's group
        guard_base: dict = {}
        for k, v in guard_counts.items():
            b = k.split("#")[0]
            guard_base[b] = guard_base.get(b, 0) + v
        parity_ok = all(p["config_equal"] and p["qor_equal"]
                        and p["version_equal"] for p in parity)
        monotone_ok = all(per_sess[("kill", i)]["monotone"]
                          for i in range(n_spaces))
        loss_ok = all(per_sess[("kill", i)]["best"]["version"]
                      >= per_sess[("kill", i)]["acked_committed"]
                      for i in range(n_spaces))
        guard_ok = (len(guard_base) == 3
                    and all(v == n_spaces
                            for v in guard_base.values())
                    and all(v == 1 for v in guard_counts.values()))
        # the kill must have hit live tenants: every session routed to
        # s0 reconnected at least once
        affected = [i for i in range(n_spaces) if owners[i] == "s0"]
        resumed_ok = all(per_sess[("kill", i)]["reconnects"] > 0
                         for i in affected)
        kills = int(stats.get("kills", 0))
        restarts = int(stats.get("restarts", 0))
        result["phase2"] = {
            "sessions": n_spaces, "epochs": epochs_kill,
            "agg_asks_per_s": round(rate, 1),
            "kills": kills, "restarts": restarts,
            "victim": "s0", "affected_sessions": affected,
            "client_reconnects": {
                str(i): per_sess[("kill", i)]["reconnects"]
                for i in range(n_spaces)},
            "client_redirects": {
                str(i): per_sess[("kill", i)]["redirects"]
                for i in range(n_spaces)},
            "parity": parity, "parity_bitwise_ok": parity_ok,
            "acked_committed_monotone": monotone_ok,
            "zero_committed_loss": loss_ok,
            "kill_wall_s": round(wall, 2),
            "trace_guard": {"strict": True, "counts": guard_counts,
                            "programs": guard_base,
                            "clean": guard_ok},
            "shards": stats.get("shards"),
        }
        print(f"bench --serve-sharded: kill/resume parity "
              f"{'OK' if parity_ok else 'FAILED'} (kills={kills}, "
              f"restarts={restarts}, affected={affected})",
              file=sys.stderr)
    finally:
        faults.disarm()
        router.stop()

    shutil.rmtree(workdir, ignore_errors=True)
    lockg.uninstall()
    if lockg.enabled:
        result["lock_sanitizer"] = lockg.report()
        lockg.check()   # strict: raise on any lock-order cycle
    ok = (parity_ok and monotone_ok and loss_ok and guard_ok
          and resumed_ok and len(affected) >= 1
          and kills == 1 and restarts >= 1)
    result["ok"] = ok
    name = ("BENCH_SERVE_SHARDED.quick.json" if quick
            else "BENCH_SERVE_SHARDED.json")
    path = os.path.join(repo, name)
    with open(path, "w") as f:
        json.dump({**result, "captured_unix": time.time()}, f, indent=1)
    print(f"bench: sharded-serving evidence written to {path}",
          file=sys.stderr)
    print(json.dumps({"metric": "serve_sharded_ok", "value": ok,
                      "agg_asks_per_s": result["phase1"]
                                              ["agg_asks_per_s"],
                      "kills": kills, "quick": quick}))
    if not ok:
        sys.exit(1)


def store_child_main() -> None:
    """`bench.py --store-child`: one cooperating tuning process of the
    `--store-remote` bench — a journaled library Tuner over rosenbrock
    whose serve loop mirrors the controller's store integration
    (lookup-before-measure, record-after-measure, exchange + federate
    on the refresh tick), pointed either at a shared `ut store` server
    (--addr tcp://...) or at nothing (--addr off: the independent
    matched-seed replica).  Prints ONE JSON line: evals-to-target,
    best, store/guard accounting, and the online quality gauges the
    parent holds to exact equality with an offline journal replay."""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--store-child", action="store_true")
    p.add_argument("--addr", required=True)
    p.add_argument("--seed", type=int, required=True)
    p.add_argument("--budget", type=int, default=120)
    p.add_argument("--dims", type=int, default=2)
    p.add_argument("--lo", type=float, default=-2.048)
    p.add_argument("--hi", type=float, default=2.048)
    p.add_argument("--as-int", action="store_true")
    p.add_argument("--target", type=float, default=0.05)
    p.add_argument("--journal", required=True)
    p.add_argument("--tag", default="child")
    p.add_argument("--exchange-interval", type=float, default=0.3)
    args = p.parse_args()

    from uptune_tpu.utils.platform_guard import force_cpu
    force_cpu(1)
    import jax  # noqa: F401  (backend must init after force_cpu)

    import collections

    import numpy as np

    from uptune_tpu import obs
    from uptune_tpu.analysis.lock_guard import lock_guard_from_env
    from uptune_tpu.analysis.trace_guard import guard_from_env
    from uptune_tpu.driver import Tuner
    from uptune_tpu.workloads import rosenbrock_space

    lockg = lock_guard_from_env(name=f"store-child-{args.tag}").install()
    dims = args.dims
    # the int grid is what makes cooperation structurally decisive:
    # sibling configs collide, so the cross-tenant memo serves real
    # hits and the fleet covers the lattice together
    space = rosenbrock_space(dims, args.lo, args.hi, as_int=args.as_int)

    def measure(cfg):
        x = np.array([float(cfg[f"x{i}"]) for i in range(dims)])
        return float(np.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2
                            + (1 - x[:-1]) ** 2))

    store = None
    if args.addr != "off":
        from uptune_tpu.store.remote import RemoteStore
        store = RemoteStore(args.addr, [repr(s) for s in space.specs],
                            "bench-store-remote",
                            refresh_interval=args.exchange_interval)
    with guard_from_env() as guard:
        obs.enable(capacity=1 << 18)
        jmon = obs.start_journal(args.journal, meta={
            "example": "bench.py --store-remote", "tag": args.tag,
            "seed": args.seed, "addr": args.addr,
            "workload": f"rosenbrock-{dims}d"
                        + ("-int" if args.as_int else "")})
        # sync refit: the run must be deterministic given its input
        # stream so the coop-vs-independent comparison is seed-matched
        tuner = Tuner(space, None, seed=args.seed, surrogate="gp",
                      surrogate_opts=dict(min_points=12,
                                          refit_interval=16,
                                          max_points=192,
                                          async_refit=False))
        evals = 0
        best = float("inf")
        hit_at = None
        exchange_injected = 0
        federated = 0
        queue: collections.deque = collections.deque()

        def serve(tr):
            """One trial through the controller's store discipline."""
            nonlocal evals, best
            row = store.lookup(tr.config) if store is not None else None
            if row is not None:
                q = float(row["qor"])
                tuner.tell(tr, q, float(row.get("dur", 0.0)))
                obs.journal.emit("store_hit", gid=tr.gid,
                                 qor=round(q, 6))
            else:
                q = measure(tr.config)
                evals += 1
                tuner.tell(tr, q)
                if store is not None:
                    tk = tr.ticket
                    store.record(tr.config, q,
                                 u=tk.u_np[tr.slot],
                                 perms=[pp[tr.slot]
                                        for pp in tk.perms_np])
            best = min(best, q)

        while evals < args.budget and \
                not (hit_at is not None and not queue):
            if not queue:
                queue.extend(tuner.ask(min_trials=1))
            serve(queue.popleft())
            if hit_at is None and best <= args.target:
                hit_at = evals
            if store is not None and store.maybe_refresh():
                rows = store.pop_fresh_rows()
                if rows:
                    # elite migration + federated surrogate rows: the
                    # controller's _maybe_exchange_best split exactly
                    top = min(rows, key=lambda r: float(r["qor"]))
                    injected = []
                    if tuner.sign * float(top["qor"]) \
                            < float(tuner.best.qor):
                        injected = tuner.inject([top["cfg"]],
                                                source="exchange")
                    if injected:
                        exchange_injected += len(injected)
                        obs.journal.emit(
                            "exchange", qor=round(float(top["qor"]), 6))
                        queue.extendleft(reversed(injected))
                    rest = [r for r in rows
                            if not (injected and r is top)]
                    n = tuner.preload_rows(rest, refit=False)
                    if n:
                        federated += n
                        if tuner.surrogate is not None:
                            tuner.surrogate.maybe_refit()
                        obs.journal.emit("federate", rows=n)
        res = tuner.result()
        tuner.close()
        obs.journal.flush()
        obs.stop_journal(jmon)      # finalizes the monitor's tail
        gauges = dict(jmon.gauges)
    sstats = store.stats() if store is not None else None
    if store is not None:
        store.flush_wait(10.0)
        store.close()
    lockg.uninstall()
    out = {"tag": args.tag, "seed": args.seed, "evals": evals,
           "hit_at": hit_at, "best": round(best, 6),
           "tuner_best": round(res.best_qor, 6),
           "exchange_injected": exchange_injected,
           "federated": federated, "store": sstats, "gauges": gauges}
    if guard.enabled:
        out["retraces"] = guard.report()
    if lockg.enabled:
        out["lock_sanitizer"] = lockg.report()
        lockg.check()
    print(json.dumps(out), flush=True)


def store_remote_main() -> None:
    """`bench.py --store-remote`: the cooperative search fabric bench
    (ISSUE 18, docs/STORE.md "Remote store").

    Phase 1 — cooperation quality: one `ut store` server subprocess;
    K=3 journaled tuning child processes join it over real localhost
    TCP (elite migration + federated surrogate rows) vs 3 independent
    matched-seed replicas at the same budget.  Gated (full runs): the
    cooperating fleet reaches the target QoR in fewer evaluations
    than the best independent replica.  Every child's online quality
    gauges must equal an offline `obs.quality.replay` of the journal
    it wrote (the PR 12 bit-exact claim), and the winning coop
    journal must render through the `ut report` pipeline.

    Phase 2 — the kill: a fresh store server armed with a
    deterministic `rstore.append=crash@N` fault (obs/faults.py) dies
    mid-append — os._exit inside the durable-append window, the
    SIGKILL stand-in — under live RemoteStore writers.  Asserted:
    rc 137; every row the server ACKED before the crash is served by
    a restarted server on the same directory (zero acked-row loss,
    pure log replay — the ack-after-durable contract); clients
    degrade to fast local-only records while the server is down and
    the surviving client reconnects and drains its write-behind
    backlog transparently.

    The whole bench runs under the strict lock sanitizer (forced on
    in --quick: the tier-1 smoke), and children inherit
    UT_TRACE_GUARD=strict.  Writes BENCH_STORE_REMOTE.json
    (.quick.json for --quick)."""
    quick = "--quick" in sys.argv
    if quick:
        # satellite: the tier-1 smoke always runs the store-server
        # fabric under the strict lock sanitizer, parent AND children
        os.environ.setdefault("UT_LOCK_GUARD", "strict")
        os.environ.setdefault("UT_TRACE_GUARD", "strict")

    import shutil
    import socket as _socket
    import subprocess
    import tempfile
    import threading

    from uptune_tpu import obs
    from uptune_tpu.analysis.lock_guard import lock_guard_from_env
    from uptune_tpu.store.remote import RemoteStore

    lockg = lock_guard_from_env(name="store-remote-bench").install()
    repo = os.path.dirname(os.path.abspath(__file__))
    workdir = tempfile.mkdtemp(prefix="ut_store_remote_bench_")
    result: dict = {"metric": "store_remote", "quick": quick,
                    "nproc": os.cpu_count()}

    def free_port():
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def wait_ready(port, child, what, deadline_s=120):
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            try:
                probe = _socket.create_connection(("127.0.0.1", port),
                                                  timeout=2)
                probe.close()
                return
            except OSError:
                if child.poll() is not None:
                    raise RuntimeError(
                        f"{what} died before ready: "
                        + child.communicate()[0][-2000:])
                time.sleep(0.1)
        raise RuntimeError(f"{what} never came up")

    def req(port, payload):
        """One raw wire request to a store-server subprocess."""
        with _socket.create_connection(("127.0.0.1", port),
                                       timeout=10) as s:
            f = s.makefile("rwb")
            f.write(json.dumps(payload).encode() + b"\n")
            f.flush()
            resp = json.loads(f.readline())
        assert resp.get("ok"), resp
        return resp

    def start_server(port, root, env=None):
        child = subprocess.Popen(
            [sys.executable, "-m", "uptune_tpu.cli", "store",
             "--port", str(port), "--dir", root],
            cwd=workdir, env=env or dict(os.environ, PYTHONPATH=repo),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        wait_ready(port, child, "ut store")
        return child

    # ---- phase 1: K=3 cooperating vs 3 independent replicas ----------
    # rosenbrock on an INTEGER lattice (13^dims configs): sibling
    # proposals collide, so the shared store serves real memo hits and
    # elite migration pulls every replica into the winning basin —
    # cooperation beats independent-replica luck on evals-to-target
    k = 3
    dims = 3 if quick else 4
    budget = 150 if quick else 300
    target = 3.0
    lo, hi = -6, 6
    seeds = [9100 + i for i in range(k)]
    port = free_port()
    server = start_server(port, os.path.join(workdir, "store"))

    def run_fleet(label, addr):
        children, outs = [], []
        for i, seed in enumerate(seeds):
            jpath = os.path.join(workdir, f"journal_{label}_{i}.jsonl")
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--store-child", "--addr", addr,
                   "--seed", str(seed), "--budget", str(budget),
                   "--dims", str(dims), "--lo", str(lo),
                   "--hi", str(hi), "--as-int",
                   "--target", str(target),
                   "--journal", jpath, "--tag", f"{label}-{i}",
                   "--exchange-interval", "0.02"]
            children.append((jpath, subprocess.Popen(
                cmd, cwd=workdir,
                env=dict(os.environ, JAX_PLATFORMS="cpu",
                         PYTHONPATH=repo),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)))
        for jpath, ch in children:
            txt = ch.communicate(timeout=900)[0]
            if ch.returncode != 0:
                raise RuntimeError(f"{label} child failed "
                                   f"(rc={ch.returncode}): {txt[-3000:]}")
            line = [ln for ln in txt.strip().splitlines()
                    if ln.startswith("{")][-1]
            outs.append((jpath, json.loads(line)))
        return outs

    try:
        coop = run_fleet("coop", f"tcp://127.0.0.1:{port}")
    finally:
        server.terminate()
        server.wait()
    indep = run_fleet("indep", "off")

    # the PR 12 bit-exact claim: every child's ONLINE gauges equal an
    # offline replay of the journal it wrote
    from uptune_tpu.obs import report as obs_report
    replay_exact = True
    for jpath, out in coop + indep:
        _, rows = obs.journal.read(jpath, strict=True)
        replayed = obs.quality.replay(rows)
        if out["gauges"] != replayed.gauges:
            replay_exact = False
            result.setdefault("replay_diffs", []).append({
                "tag": out["tag"],
                "diff": {kk: (out["gauges"].get(kk),
                              replayed.gauges.get(kk))
                         for kk in set(out["gauges"])
                         | set(replayed.gauges)
                         if out["gauges"].get(kk)
                         != replayed.gauges.get(kk)}})

    def hit(o):
        # a replica that never reached the target counts as budget+1
        return o["hit_at"] if o["hit_at"] is not None else budget + 1

    coop_hits = [hit(o) for _, o in coop]
    indep_hits = [hit(o) for _, o in indep]
    coop_min, indep_min = min(coop_hits), min(indep_hits)
    migrated = sum(o["exchange_injected"] for _, o in coop)
    federated = sum(o["federated"] for _, o in coop)
    # the winning coop journal renders through `ut report`
    win_jpath = min(coop, key=lambda c: hit(c[1]))[0]
    report_md = obs_report.render(win_jpath, fmt="md")
    def guard_clean(o):
        # strict children already die on violation; belt-and-braces
        tr = o.get("retraces") or {}
        limit = tr.get("limit", 1)
        return all(v <= limit for v in (tr.get("traces") or {}).values())

    children_guard_ok = all(guard_clean(o) for _, o in coop + indep)
    result["phase1"] = {
        "k": k, "dims": dims, "lo": lo, "hi": hi, "as_int": True,
        "budget": budget, "target": target,
        "seeds": seeds, "exchange_interval_s": 0.02,
        "coop_evals_to_target": coop_hits,
        "indep_evals_to_target": indep_hits,
        "coop_min": coop_min, "indep_min": indep_min,
        "coop_beats_indep": coop_min < indep_min,
        "exchange_injected": migrated, "federated_rows": federated,
        "coop": [o for _, o in coop], "indep": [o for _, o in indep],
        "journal_replay_exact": replay_exact,
        "report_md_lines": report_md.count("\n"),
        "children_trace_guard_clean": children_guard_ok,
    }
    print(f"bench --store-remote: coop evals-to-target {coop_hits} "
          f"vs independent {indep_hits} (min {coop_min} vs "
          f"{indep_min}, {migrated} migrations, {federated} federated "
          f"rows)", file=sys.stderr)

    # ---- phase 2: the deterministic mid-append kill ------------------
    crash_at = 25
    port2 = free_port()
    root2 = os.path.join(workdir, "store_crash")
    env2 = dict(os.environ, PYTHONPATH=repo,
                UT_FAULTS=f"rstore.append=crash@{crash_at}")
    server2 = start_server(port2, root2, env=env2)
    sig = ["bench-crash-spec"]
    clients = [RemoteStore(f"tcp://127.0.0.1:{port2}", sig,
                           "bench-crash", refresh_interval=3600.0,
                           backoff_base=0.05, backoff_max=0.5)
               for _ in range(k)]
    rec_keys: list = [[] for _ in range(k)]   # per client, record order
    stop_rec = threading.Event()

    def writer(ci):
        n = 0
        while not stop_rec.is_set() and n < 200:
            row = clients[ci].record({"c": ci, "i": n}, float(n + 1),
                                     source=f"w{ci}")
            if row is not None:
                rec_keys[ci].append(row["k"])
            n += 1
            time.sleep(0.005)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(k)]
    for t in threads:
        t.start()
    server2.wait()          # dies at its crash_at-th durable append
    crash_rc = server2.returncode
    t_crash = time.perf_counter()
    # degradation: with the server dead, a record() is a local insert
    # + bounded enqueue — never a dial, never a stall
    t0 = time.perf_counter()
    clients[0].record({"deg": "probe"}, 999.0)
    degrade_ms = (time.perf_counter() - t0) * 1e3
    stop_rec.set()
    for t in threads:
        t.join()
    # snapshot the acked frontier: the flusher ships FIFO, so each
    # client's acked count prefixes its record order exactly
    acked_at_crash = [c.stats()["remote"]["acked"] for c in clients]
    acked_keys = [ks[:a] for ks, a in zip(rec_keys, acked_at_crash)]
    # two clients stop here, with the server DOWN: whatever the log
    # holds for them is all a restarted server can know — the pure
    # replay side of the zero-acked-loss check
    closed_unshipped = 0
    for c in clients[1:]:
        s = c.stats()["remote"]
        closed_unshipped += s["queued"]
        c.close()
    # restart on the SAME directory (no fault armed this time)
    server3 = start_server(port2, root2)
    try:
        lost = []
        for ks in acked_keys:
            for key in ks:
                r = req(port2, {"op": "lookup", "k": key})
                if r.get("row") is None:
                    lost.append(key)
        st = req(port2, {"op": "stats"})
        # the surviving client reconnects and drains its backlog
        drained = clients[0].flush_wait(30.0)
        resumed = clients[0].connected
        survivor_ok = True
        for key in rec_keys[0]:
            if req(port2, {"op": "lookup", "k": key}).get("row") is None:
                survivor_ok = False
        dropped = sum(c.stats()["remote"]["dropped"]
                      for c in (clients[0],))
    finally:
        clients[0].close()
        server3.terminate()
        server3.wait()
    result["phase2"] = {
        "crash_at_append": crash_at, "crash_rc": crash_rc,
        "acked_at_crash": acked_at_crash,
        "acked_rows_lost": len(lost),
        "degraded_record_ms": round(degrade_ms, 3),
        "closed_with_unshipped": closed_unshipped,
        "survivor_drained": drained, "survivor_resumed": resumed,
        "survivor_all_rows_on_server": survivor_ok,
        "survivor_dropped": dropped,
        "server_after_restart": {"rows": st["rows"],
                                 "replayed": st["replayed"],
                                 "torn_tail": st["torn_tail"]},
    }
    print(f"bench --store-remote: crash rc {crash_rc} at append "
          f"{crash_at}; {sum(acked_at_crash)} acked rows, "
          f"{len(lost)} lost; survivor drained={drained} "
          f"(degraded record {degrade_ms:.1f} ms)", file=sys.stderr)

    shutil.rmtree(workdir, ignore_errors=True)
    lockg.uninstall()
    if lockg.enabled:
        result["lock_sanitizer"] = lockg.report()
        lockg.check()   # strict: raise on any lock-order cycle
    # quality gates only the FULL run (the quick smoke runs 6 jax
    # children on a 1-core CI box — it gates the correctness
    # contracts and records the comparison honestly)
    ok = ((coop_min < indep_min or quick) and replay_exact
          and children_guard_ok
          and crash_rc == 137 and not lost
          and sum(acked_at_crash) > 0
          and drained and resumed and survivor_ok and dropped == 0
          and degrade_ms < 100.0)
    result["ok"] = ok
    name = ("BENCH_STORE_REMOTE.quick.json" if quick
            else "BENCH_STORE_REMOTE.json")
    path = os.path.join(repo, name)
    with open(path, "w") as f:
        json.dump({**result, "captured_unix": time.time()}, f, indent=1)
    print(f"bench: cooperative-store evidence written to {path}",
          file=sys.stderr)
    print(json.dumps({"metric": "store_remote_ok", "value": ok,
                      "coop_min": coop_min, "indep_min": indep_min,
                      "acked_lost": len(lost), "quick": quick}))
    if not ok:
        sys.exit(1)


def main() -> None:
    if "--obs" in sys.argv:
        obs_main()
        return
    if "--report" in sys.argv:
        report_main()
        return
    if "--driver" in sys.argv:
        driver_main()
        return
    if "--cache" in sys.argv:
        cache_main()
        return
    if "--surrogate" in sys.argv:
        surrogate_main()
        return
    if "--multi" in sys.argv:
        multi_main()
        return
    if "--fleet-child" in sys.argv:
        fleet_child_main()
        return
    if "--fleet" in sys.argv:
        fleet_main()
        return
    if "--failover" in sys.argv:
        failover_main()
        return
    if "--serve-sharded" in sys.argv:
        serve_sharded_main()
        return
    if "--store-child" in sys.argv:
        store_child_main()
        return
    if "--store-remote" in sys.argv:
        store_remote_main()
        return
    if "--serve" in sys.argv:
        serve_main()
        return
    quick = "--quick" in sys.argv
    jax, platform = _init_backend(
        cpu_flag="--cpu" in sys.argv,
        wait_for_tpu="--wait-for-tpu" in sys.argv)
    if platform == "cpu:fallback":
        # the fallback number is explicitly labeled and never stands in
        # for the TPU result; run it at quick size so a wedged tunnel
        # can't also push the driver's bench step into a timeout
        quick = True

    from uptune_tpu.engine import FusedEngine, default_arms
    from uptune_tpu.workloads import rosenbrock_device, rosenbrock_space

    # UT_TRACE_GUARD=1|strict cross-checks the static analyzer at run
    # time: every jax.jit wrapper built inside the guarded region gets
    # its traces counted, and the report lands in the output JSON — a
    # measured bench must compile the whole pipeline exactly once
    # (docs/LINT.md, uptune_tpu/analysis/trace_guard.py).  The engine
    # is constructed INSIDE the guard so constructor-built wrappers
    # are counted too
    from uptune_tpu.analysis.trace_guard import guard_from_env
    with guard_from_env() as guard:
        # 16-D rosenbrock, arms scaled so each step acquires ~6k
        # candidates: big enough to fill the chip, small enough that
        # dedup history (2^15) holds several steps' worth
        space = rosenbrock_space(16, -5.0, 5.0)
        eng = FusedEngine(space, lambda v, p: rosenbrock_device(v),
                          arms=default_arms(scale=4 if quick else 64),
                          history_capacity=1 << (12 if quick else 15))

        steps = 20 if quick else 200

        # constant seeds by design: a measured bench must replay the
        # same stream run-to-run
        state = eng.init(jax.random.PRNGKey(0))  # ut-lint: disable=R002
        # donated EngineState: history/technique buffers update in place
        lowered = eng.jit_run(steps).lower(state)
        compiled = lowered.compile()
        run = compiled
        state = run(state)                  # warm (already compiled)
        jax.block_until_ready(state)
        harv = obs_device.harvest(compiled)

        rep_times = []
        reps = 3  # 3 reps even at quick size: rounds are only
        # comparable if the artifact carries per-rep variance (VERDICT
        # r3 weak #1)
        for _ in range(reps):
            # identical reps measure wall time, not search quality
            # ut-lint: disable-next=R002
            s = eng.init(jax.random.PRNGKey(1))
            jax.block_until_ready(s)
            t0 = time.perf_counter()
            s = run(s)
            jax.block_until_ready(s)
            rep_times.append(time.perf_counter() - t0)
    best_t = min(rep_times)

    acqs = steps * eng.total_batch
    rate = acqs / best_t
    result = {
        "metric": "candidate_acquisitions_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": "configs/s",
        "vs_baseline": round(rate / 100_000.0, 3),
        "platform": platform,
        "quick": quick,
        # cross-round comparability for the fallback number: sizes are
        # fixed by `quick`, but the box is not — record core count and
        # per-rep spread so a contended 1-core machine can't be read as
        # a regression (VERDICT r3 weak #1)
        "nproc": os.cpu_count(),
        "rep_wall_s": [round(t, 4) for t in rep_times],
    }
    if guard.enabled:
        result["retraces"] = guard.report()

    dev = jax.devices()[0]
    device_kind = getattr(dev, "device_kind", "?")
    roofline = _roofline_fields(harv, device_kind, best_t)
    obs_device.record_window("engine.run", best_t,
                             device_kind=device_kind)

    if platform not in ("cpu", "cpu:fallback"):
        if roofline["bytes_per_s"]:
            result["hbm_gb_per_s"] = round(
                roofline["bytes_per_s"] / 1e9, 1)
        if roofline.get("hbm_util") is not None:
            result["hbm_util"] = roofline["hbm_util"]
        # raw evidence artifact: the checked-in proof behind the README
        # headline (VERDICT r2: a number the harness never reproduced is
        # a claim, not a result)
        artifact = {
            **result,
            "steps": steps,
            "batch_per_step": eng.total_batch,
            "acquisitions": acqs,
            "rep_wall_s": [round(t, 4) for t in rep_times],
            "devices": repr(jax.devices()),
            "device_kind": device_kind,
            "jax_version": jax.__version__,
            "captured_unix": time.time(),
            "cost_analysis": {
                **roofline,
                "note": ("measured via obs/device.py: flops/bytes "
                         "from XLA's cost model for this exact "
                         "executable, rates over the blocked "
                         "best-rep wall; utilization compares them "
                         "against published per-chip peaks "
                         "(obs.device.PEAKS)"),
            },
        }
        # quick runs must not clobber a full evidence artifact: the
        # README headline rests on the non-quick BENCH_TPU.json
        name = "BENCH_TPU.quick.json" if quick else "BENCH_TPU.json"
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            name)
        with open(path, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"bench: raw evidence written to {path}", file=sys.stderr)

    print(json.dumps(result))


if __name__ == "__main__":
    main()
