// uptune C++ client — the intrusive tuning API for native workloads.
//
// The complete counterpart of the reference's unfinished header
// (/root/reference/src/uptune.h:14-47, whose ANALYSIS branch was a
// skeleton and whose TUNE branch was absent): this client implements the
// whole four-mode env/JSON protocol of uptune_tpu/api/state.py, so a C++
// program can be tuned by the same controller as a Python one —
//
//   ANALYSIS (UT_BEFORE_RUN_PROFILE): uptune::tune() records the search
//     space; uptune::target() flushes ut.params.json + ut.default_qor.json
//     and closes the stage.
//   TUNE (UT_TUNE_START): tune() serves values from the proposal published
//     at configs/ut.dr_stage{S}_index{I}.json — name-first lookup with the
//     positional-counter fallback (template/types.py:132-134 semantics);
//     target() appends [index, val, trend] to ut.qor_stage{S}.json (and
//     acts as the multi-stage breakpoint: exit(0) at the tuned stage).
//   BEST (BEST): tune() serves values from best.json.
//   DEFAULT (no env): tune() returns its origin value.
//
// Header-only, C++11, no dependencies beyond the bundled json.hpp.
#pragma once

#include <cstdlib>
#include <fstream>
#include <sstream>
#include <string>
#include <type_traits>
#include <utility>
#include <vector>

#include "json.hpp"

namespace uptune {

enum class Mode { Default, Analysis, Tune, Best };

namespace detail {

inline bool truthy(const char* v) {
  if (v == nullptr) return false;
  std::string s(v);
  for (auto& c : s) c = static_cast<char>(std::tolower(c));
  return !(s.empty() || s == "0" || s == "false" || s == "off");
}

inline std::string read_file(const std::string& path) {
  std::ifstream f(path);
  if (!f) throw std::runtime_error("cannot open " + path);
  std::ostringstream os;
  os << f.rdbuf();
  return os.str();
}

inline void write_file(const std::string& path, const std::string& text) {
  std::ofstream f(path);
  if (!f) throw std::runtime_error("cannot write " + path);
  f << text;
}

// Per-process protocol state (mirror of api/state.py _ProtocolState).
class Client {
 public:
  static Client& instance() {
    static Client c;
    return c;
  }

  Mode mode() const { return mode_; }
  int index() const { return index_; }
  int stage() const { return stage_; }
  long long global_id() const { return global_id_; }
  const std::string& work_dir() const { return work_dir_; }

  // ---------------------------------------------------------- ANALYSIS
  void record_param(json::Object rec) {
    while (recorded_.size() <= static_cast<size_t>(cur_stage_))
      recorded_.push_back(json::Array{});
    auto& stage = recorded_[cur_stage_].as_array();
    if (!rec.count("name") || rec["name"].as_string().empty()) {
      rec["name"] = "v" + std::to_string(cur_stage_) + "_" +
                    std::to_string(stage.size());
    }
    const std::string& name = rec["name"].as_string();
    for (const auto& st : recorded_)
      for (const auto& r : st.as_array())
        if (r.at("name").as_string() == name)
          throw std::runtime_error("duplicate tunable parameter name: " +
                                   name);
    stage.push_back(json::Value(std::move(rec)));
  }

  void flush_params() {
    write_file(work_dir_ + "/ut.params.json",
               json::Value(recorded_).dump());
  }

  size_t recorded_stages() const { return recorded_.size(); }

  // -------------------------------------------------------------- TUNE
  // Serve the value for the next tune() call: name-first, positional
  // fallback against ut.params.json (state.py next_value).
  json::Value next_value(const std::string& name,
                         const json::Value& dflt) {
    if (!loaded_) {
      loaded_ = true;
      try {
        if (mode_ == Mode::Best) {
          load_best();
        } else {
          load_proposal();
        }
      } catch (const std::exception&) {
        proposal_ok_ = false;  // no/bad published config: run as default
      }
    }
    if (!proposal_ok_) {
      ++count_;
      return dflt;
    }
    std::string key;
    if (!name.empty() && proposal_.count(name)) {
      key = name;
    } else if (params_meta_.is_array() &&
               static_cast<size_t>(cur_stage_) < params_meta_.size()) {
      const auto& stage_params = params_meta_.at(cur_stage_).as_array();
      if (static_cast<size_t>(count_) < stage_params.size())
        key = stage_params[count_].at("name").as_string();
    }
    ++count_;
    if (key.empty() || !proposal_.count(key)) return dflt;
    return proposal_.at(key);
  }

  size_t n_stages() const {
    if (params_meta_.is_array() && params_meta_.size() > 0)
      return params_meta_.size();
    return recorded_.empty() ? 1 : recorded_.size();
  }

  // --------------------------------------------------------------- QoR
  void write_qor_row(double val, const std::string& trend) {
    std::string path = work_dir_ + "/ut.qor_stage" +
                       std::to_string(cur_stage_) + ".json";
    json::Array rows;
    try {
      json::Value prev = json::parse(read_file(path));
      if (prev.is_array()) rows = prev.as_array();
    } catch (const std::exception&) {
    }
    rows.push_back(json::Value(json::Array{
        json::Value(index_), json::Value(val), json::Value(trend)}));
    write_file(path, json::Value(rows).dump());
  }

  void write_default_qor(double val, const std::string& trend) {
    json::Object o;
    o["qor"] = val;
    o["trend"] = trend;
    o["stage"] = cur_stage_;
    write_file(work_dir_ + "/ut.default_qor.json",
               json::Value(std::move(o)).dump());
  }

  // target() bookkeeping (report.py target): returns true when the
  // caller must exit(0) — the multi-stage TUNE breakpoint.
  bool on_target(double val, const std::string& trend) {
    if (mode_ == Mode::Analysis) {
      flush_params();
      write_default_qor(val, trend);
      ++cur_stage_;
      count_ = 0;
      return false;
    }
    if (mode_ == Mode::Tune) {
      if (n_stages() <= 1) {
        write_qor_row(val, trend);
        return false;
      }
      if (cur_stage_ == stage_) {
        write_qor_row(val, trend);
        return true;  // breakpoint: the tuned stage is done
      }
      if (cur_stage_ > stage_)
        throw std::runtime_error("breakpoint past the tuned stage");
      ++cur_stage_;
      count_ = 0;
      return false;
    }
    if (mode_ == Mode::Best) {
      ++cur_stage_;
      count_ = 0;
    }
    return false;
  }

 private:
  Client() {
    const char* wd = std::getenv("UT_WORK_DIR");
    work_dir_ = wd != nullptr && *wd ? wd : ".";
    if (truthy(std::getenv("UT_BEFORE_RUN_PROFILE"))) {
      mode_ = Mode::Analysis;
    } else if (truthy(std::getenv("UT_TUNE_START"))) {
      mode_ = Mode::Tune;
    } else if (truthy(std::getenv("BEST"))) {
      mode_ = Mode::Best;
    } else {
      mode_ = Mode::Default;
    }
    const char* s = std::getenv("UT_CURR_STAGE");
    stage_ = s != nullptr ? std::atoi(s) : 0;
    const char* i = std::getenv("UT_CURR_INDEX");
    index_ = i != nullptr ? std::atoi(i) : 0;
    const char* g = std::getenv("UT_GLOBAL_ID");
    global_id_ = g != nullptr ? std::atoll(g) : 0;
  }

  void load_params_meta() {
    try {
      params_meta_ = json::parse(read_file(work_dir_ + "/ut.params.json"));
    } catch (const std::exception&) {
      params_meta_ = json::Value();
    }
  }

  void load_proposal() {
    std::string path = work_dir_ + "/configs/ut.dr_stage" +
                       std::to_string(stage_) + "_index" +
                       std::to_string(index_) + ".json";
    json::Value v = json::parse(read_file(path));
    proposal_ = v.as_object();
    load_params_meta();
    // merge best configs of earlier pipeline stages (state.py:121-127)
    for (int s = 0; s < stage_; ++s) {
      try {
        json::Value prev = json::parse(read_file(
            work_dir_ + "/configs/" + std::to_string(s) + "-best.json"));
        for (const auto& kv : prev.as_object())
          if (!proposal_.count(kv.first)) proposal_[kv.first] = kv.second;
      } catch (const std::exception&) {
      }
    }
    proposal_ok_ = true;
  }

  void load_best() {
    json::Value v = json::parse(read_file(work_dir_ + "/best.json"));
    if (v.is_object()) {
      proposal_ = v.contains("config") ? v.at("config").as_object()
                                       : v.as_object();
    } else if (v.is_array() && v.size() == 2 && v.at(0).is_object()) {
      proposal_ = v.at(0).as_object();
    } else {
      throw std::runtime_error("unrecognized best.json payload");
    }
    load_params_meta();
    proposal_ok_ = true;
  }

  Mode mode_;
  std::string work_dir_;
  int index_ = 0;
  int stage_ = 0;
  long long global_id_ = 0;
  int cur_stage_ = 0;
  int count_ = 0;
  bool loaded_ = false;
  bool proposal_ok_ = false;
  json::Array recorded_;     // per-stage arrays of param records
  json::Object proposal_;
  json::Value params_meta_;
};

}  // namespace detail

// ======================================================================
// Public API
// ======================================================================

// tune(origin, {lo, hi}[, name]) — integer range parameter.
template <typename T,
          typename std::enable_if<std::is_integral<T>::value &&
                                      !std::is_same<T, bool>::value,
                                  int>::type = 0>
T tune(T origin, std::pair<T, T> range, const std::string& name = "") {
  auto& c = detail::Client::instance();
  switch (c.mode()) {
    case Mode::Analysis: {
      json::Object rec;
      rec["name"] = name;
      rec["type"] = "int";
      rec["default"] = static_cast<long long>(origin);
      rec["lo"] = static_cast<long long>(range.first);
      rec["hi"] = static_cast<long long>(range.second);
      c.record_param(std::move(rec));
      return origin;
    }
    case Mode::Tune:
    case Mode::Best: {
      json::Value v = c.next_value(
          name, json::Value(static_cast<long long>(origin)));
      return v.is_number() ? static_cast<T>(v.as_int()) : origin;
    }
    default:
      return origin;
  }
}

// Reference-style call with a brace range: tune<int>(2, {1, 8})
// (/root/reference/tests/cpp/test_basic.cc:5-8 treats {lo, hi} as the
// inclusive range).
template <typename T,
          typename std::enable_if<std::is_integral<T>::value &&
                                      !std::is_same<T, bool>::value,
                                  int>::type = 0>
T tune(T origin, std::initializer_list<T> range,
       const std::string& name = "") {
  if (range.size() != 2)
    throw std::invalid_argument("tune: range must be {lo, hi}");
  auto it = range.begin();
  T lo = *it++;
  T hi = *it;
  return tune(origin, std::make_pair(lo, hi), name);
}

// tune(origin, {lo, hi}[, name]) — float range parameter.
template <typename T,
          typename std::enable_if<std::is_floating_point<T>::value,
                                  int>::type = 0>
T tune(T origin, std::pair<T, T> range, const std::string& name = "") {
  auto& c = detail::Client::instance();
  switch (c.mode()) {
    case Mode::Analysis: {
      json::Object rec;
      rec["name"] = name;
      rec["type"] = "float";
      rec["default"] = static_cast<double>(origin);
      rec["lo"] = static_cast<double>(range.first);
      rec["hi"] = static_cast<double>(range.second);
      c.record_param(std::move(rec));
      return origin;
    }
    case Mode::Tune:
    case Mode::Best: {
      json::Value v =
          c.next_value(name, json::Value(static_cast<double>(origin)));
      return v.is_number() ? static_cast<T>(v.as_double()) : origin;
    }
    default:
      return origin;
  }
}

template <typename T,
          typename std::enable_if<std::is_floating_point<T>::value,
                                  int>::type = 0>
T tune(T origin, std::initializer_list<T> range,
       const std::string& name = "") {
  if (range.size() != 2)
    throw std::invalid_argument("tune: range must be {lo, hi}");
  auto it = range.begin();
  T lo = *it++;
  T hi = *it;
  return tune(origin, std::make_pair(lo, hi), name);
}

// tune(origin[, name]) — boolean flag.
inline bool tune(bool origin, const std::string& name = "") {
  auto& c = detail::Client::instance();
  switch (c.mode()) {
    case Mode::Analysis: {
      json::Object rec;
      rec["name"] = name;
      rec["type"] = "bool";
      rec["default"] = origin;
      c.record_param(std::move(rec));
      return origin;
    }
    case Mode::Tune:
    case Mode::Best: {
      json::Value v = c.next_value(name, json::Value(origin));
      if (v.is_bool()) return v.as_bool();
      if (v.is_number()) return v.as_double() != 0.0;
      return origin;
    }
    default:
      return origin;
  }
}

// tune(origin, options[, name]) — enum over strings.
inline std::string tune(const std::string& origin,
                        const std::vector<std::string>& options,
                        const std::string& name = "") {
  auto& c = detail::Client::instance();
  switch (c.mode()) {
    case Mode::Analysis: {
      bool found = false;
      json::Array opts;
      for (const auto& o : options) {
        opts.push_back(json::Value(o));
        if (o == origin) found = true;
      }
      if (!found)
        throw std::invalid_argument("tune: default \"" + origin +
                                    "\" not in options");
      json::Object rec;
      rec["name"] = name;
      rec["type"] = "enum";
      rec["default"] = origin;
      rec["options"] = std::move(opts);
      c.record_param(std::move(rec));
      return origin;
    }
    case Mode::Tune:
    case Mode::Best: {
      json::Value v = c.next_value(name, json::Value(origin));
      return v.is_string() ? v.as_string() : origin;
    }
    default:
      return origin;
  }
}

inline std::string tune(const char* origin,
                        const std::vector<std::string>& options,
                        const std::string& name = "") {
  return tune(std::string(origin), options, name);
}

// tune_enum(origin, choices[, name]) — enum over numeric choices
// (distinct from the {lo, hi} range overloads above).
template <typename T,
          typename std::enable_if<std::is_arithmetic<T>::value,
                                  int>::type = 0>
T tune_enum(T origin, const std::vector<T>& choices,
            const std::string& name = "") {
  auto& c = detail::Client::instance();
  switch (c.mode()) {
    case Mode::Analysis: {
      bool found = false;
      json::Array opts;
      for (const auto& o : choices) {
        opts.push_back(json::Value(static_cast<double>(o)));
        if (o == origin) found = true;
      }
      if (!found)
        throw std::invalid_argument("tune_enum: default not in choices");
      json::Object rec;
      rec["name"] = name;
      rec["type"] = "enum";
      rec["default"] = static_cast<double>(origin);
      rec["options"] = std::move(opts);
      c.record_param(std::move(rec));
      return origin;
    }
    case Mode::Tune:
    case Mode::Best: {
      json::Value v =
          c.next_value(name, json::Value(static_cast<double>(origin)));
      return v.is_number() ? static_cast<T>(
                                 std::is_integral<T>::value
                                     ? static_cast<double>(v.as_int())
                                     : v.as_double())
                           : origin;
    }
    default:
      return origin;
  }
}

// target(value[, trend]) — report the QoR of this run; in multi-stage
// TUNE mode this is the stage breakpoint (the process exits 0 when the
// tuned stage completes, exactly like report.py:69-79).
inline double target(double value, const std::string& trend = "min") {
  if (trend != "min" && trend != "max")
    throw std::invalid_argument("target: trend must be 'min' or 'max'");
  if (detail::Client::instance().on_target(value, trend)) std::exit(0);
  return value;
}

inline Mode mode() { return detail::Client::instance().mode(); }

// Global trial id under tuning; -1 outside a tuning run
// (report.py:141-145 get_global_id returns 'base' — C++ callers get -1).
inline long long get_global_id() {
  return detail::truthy(std::getenv("UT_TUNE_START"))
             ? detail::Client::instance().global_id()
             : -1;
}

// Worker-slot index under tuning; -1 outside a tuning run.
inline int get_local_id() {
  return detail::truthy(std::getenv("UT_TUNE_START"))
             ? detail::Client::instance().index()
             : -1;
}

}  // namespace uptune
