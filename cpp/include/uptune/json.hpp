// Minimal JSON value model + parser/serializer for the uptune C++ client.
//
// The client only needs the protocol subset the controller emits
// (objects, arrays, strings, numbers, bools, null) — see
// uptune_tpu/api/state.py for the files exchanged.  Dependency-free by
// design: the reference's C++ API (src/uptune.h:14-47) left its JSON
// handling unimplemented; this completes it without pulling a library
// into user build systems.
#pragma once

#include <cctype>
#include <cmath>
#include <cstdint>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace uptune {
namespace json {

class Value;
using Array = std::vector<Value>;
using Object = std::map<std::string, Value>;

class Value {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Value() : type_(Type::Null) {}
  Value(std::nullptr_t) : type_(Type::Null) {}
  Value(bool b) : type_(Type::Bool), bool_(b) {}
  Value(int v) : type_(Type::Number), num_(v) {}
  Value(long v) : type_(Type::Number), num_(static_cast<double>(v)) {}
  Value(long long v) : type_(Type::Number), num_(static_cast<double>(v)) {}
  Value(double v) : type_(Type::Number), num_(v) {}
  Value(const char* s) : type_(Type::String), str_(s) {}
  Value(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Value(Array a) : type_(Type::Array), arr_(std::move(a)) {}
  Value(Object o) : type_(Type::Object), obj_(std::move(o)) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_bool() const { return type_ == Type::Bool; }
  bool is_number() const { return type_ == Type::Number; }
  bool is_string() const { return type_ == Type::String; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_object() const { return type_ == Type::Object; }

  bool as_bool() const { require(Type::Bool); return bool_; }
  double as_double() const { require(Type::Number); return num_; }
  long long as_int() const {
    require(Type::Number);
    return static_cast<long long>(std::llround(num_));
  }
  const std::string& as_string() const { require(Type::String); return str_; }
  const Array& as_array() const { require(Type::Array); return arr_; }
  Array& as_array() { require(Type::Array); return arr_; }
  const Object& as_object() const { require(Type::Object); return obj_; }
  Object& as_object() { require(Type::Object); return obj_; }

  bool contains(const std::string& key) const {
    return is_object() && obj_.count(key) > 0;
  }
  const Value& at(const std::string& key) const {
    require(Type::Object);
    auto it = obj_.find(key);
    if (it == obj_.end()) throw std::out_of_range("json: no key " + key);
    return it->second;
  }
  const Value& at(size_t i) const {
    require(Type::Array);
    return arr_.at(i);
  }
  size_t size() const {
    if (is_array()) return arr_.size();
    if (is_object()) return obj_.size();
    return 0;
  }

  std::string dump() const {
    std::ostringstream os;
    write(os);
    return os.str();
  }

 private:
  void require(Type t) const {
    if (type_ != t) throw std::runtime_error("json: wrong type access");
  }

  void write(std::ostringstream& os) const {
    switch (type_) {
      case Type::Null: os << "null"; break;
      case Type::Bool: os << (bool_ ? "true" : "false"); break;
      case Type::Number: {
        if (std::isfinite(num_) &&
            num_ == std::floor(num_) && std::fabs(num_) < 1e15) {
          os << static_cast<long long>(num_);
        } else if (std::isnan(num_)) {
          os << "NaN";            // python json reads this back
        } else if (std::isinf(num_)) {
          os << (num_ > 0 ? "Infinity" : "-Infinity");
        } else {
          os.precision(17);
          os << num_;
        }
        break;
      }
      case Type::String: write_string(os, str_); break;
      case Type::Array: {
        os << '[';
        for (size_t i = 0; i < arr_.size(); ++i) {
          if (i) os << ", ";
          arr_[i].write(os);
        }
        os << ']';
        break;
      }
      case Type::Object: {
        os << '{';
        bool first = true;
        for (const auto& kv : obj_) {
          if (!first) os << ", ";
          first = false;
          write_string(os, kv.first);
          os << ": ";
          kv.second.write(os);
        }
        os << '}';
        break;
      }
    }
  }

  static void write_string(std::ostringstream& os, const std::string& s) {
    os << '"';
    for (char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\n': os << "\\n"; break;
        case '\t': os << "\\t"; break;
        case '\r': os << "\\r"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof(buf), "\\u%04x", c);
            os << buf;
          } else {
            os << c;
          }
      }
    }
    os << '"';
  }

  Type type_;
  bool bool_ = false;
  double num_ = 0.0;
  std::string str_;
  Array arr_;
  Object obj_;
};

// ---------------------------------------------------------------- parser
class Parser {
 public:
  explicit Parser(const std::string& text) : s_(text) {}

  Value parse() {
    Value v = value();
    skip_ws();
    if (pos_ != s_.size()) fail("trailing characters");
    return v;
  }

 private:
  [[noreturn]] void fail(const std::string& why) {
    throw std::runtime_error("json parse error at byte " +
                             std::to_string(pos_) + ": " + why);
  }

  void skip_ws() {
    while (pos_ < s_.size() &&
           std::isspace(static_cast<unsigned char>(s_[pos_]))) {
      ++pos_;
    }
  }

  char peek() {
    if (pos_ >= s_.size()) fail("unexpected end");
    return s_[pos_];
  }

  bool consume(const std::string& word) {
    if (s_.compare(pos_, word.size(), word) == 0) {
      pos_ += word.size();
      return true;
    }
    return false;
  }

  Value value() {
    skip_ws();
    char c = peek();
    if (c == '{') return object();
    if (c == '[') return array();
    if (c == '"') return Value(string());
    if (consume("true")) return Value(true);
    if (consume("false")) return Value(false);
    if (consume("null")) return Value(nullptr);
    // python's json emits these for non-finite floats
    if (consume("NaN")) return Value(std::nan(""));
    if (consume("Infinity")) return Value(HUGE_VAL);
    if (consume("-Infinity")) return Value(-HUGE_VAL);
    return number();
  }

  Value object() {
    ++pos_;  // {
    Object out;
    skip_ws();
    if (peek() == '}') { ++pos_; return Value(std::move(out)); }
    while (true) {
      skip_ws();
      std::string key = string();
      skip_ws();
      if (peek() != ':') fail("expected ':'");
      ++pos_;
      out[key] = value();
      skip_ws();
      char c = peek();
      ++pos_;
      if (c == '}') break;
      if (c != ',') fail("expected ',' or '}'");
    }
    return Value(std::move(out));
  }

  Value array() {
    ++pos_;  // [
    Array out;
    skip_ws();
    if (peek() == ']') { ++pos_; return Value(std::move(out)); }
    while (true) {
      out.push_back(value());
      skip_ws();
      char c = peek();
      ++pos_;
      if (c == ']') break;
      if (c != ',') fail("expected ',' or ']'");
    }
    return Value(std::move(out));
  }

  std::string string() {
    if (peek() != '"') fail("expected string");
    ++pos_;
    std::string out;
    while (true) {
      if (pos_ >= s_.size()) fail("unterminated string");
      char c = s_[pos_++];
      if (c == '"') break;
      if (c == '\\') {
        if (pos_ >= s_.size()) fail("bad escape");
        char e = s_[pos_++];
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            if (pos_ + 4 > s_.size()) fail("bad \\u escape");
            unsigned cp = std::stoul(s_.substr(pos_, 4), nullptr, 16);
            pos_ += 4;
            // encode BMP code point as UTF-8 (enough for the protocol)
            if (cp < 0x80) {
              out += static_cast<char>(cp);
            } else if (cp < 0x800) {
              out += static_cast<char>(0xC0 | (cp >> 6));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else {
              out += static_cast<char>(0xE0 | (cp >> 12));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            }
            break;
          }
          default: fail("bad escape");
        }
      } else {
        out += c;
      }
    }
    return out;
  }

  Value number() {
    size_t start = pos_;
    if (peek() == '-') ++pos_;
    while (pos_ < s_.size() &&
           (std::isdigit(static_cast<unsigned char>(s_[pos_])) ||
            s_[pos_] == '.' || s_[pos_] == 'e' || s_[pos_] == 'E' ||
            s_[pos_] == '+' || s_[pos_] == '-')) {
      ++pos_;
    }
    if (pos_ == start) fail("expected number");
    return Value(std::stod(s_.substr(start, pos_ - start)));
  }

  const std::string& s_;
  size_t pos_ = 0;
};

inline Value parse(const std::string& text) { return Parser(text).parse(); }

}  // namespace json
}  // namespace uptune
