// Demo workload for end-to-end C++ tuning (the gcc-options shape in
// miniature: block size + unroll + opt level + a continuous knob).
//
// Deterministic synthetic cost surface with a unique optimum at
// block=32, alpha=0.8, unroll=true, opt="O3" (cost 0), so tests can
// assert convergence without timing noise.  Tuned through the same
// subprocess plane as Python workloads (uptune_tpu/exec/controller.py);
// the reference's equivalent demo never existed (src/uptune.h was a
// skeleton).

#include <cstdio>
#include <string>

#include "uptune/uptune.hpp"

int main() {
  int block = uptune::tune(16, {1, 64}, "block");
  double alpha = uptune::tune(0.5, std::make_pair(0.0, 1.0), "alpha");
  bool unroll = uptune::tune(false, "unroll");
  std::string opt = uptune::tune("O1", {"O0", "O1", "O2", "O3"}, "opt");

  double cost = (block - 32) * (block - 32) / 64.0 +
                (alpha - 0.8) * (alpha - 0.8) * 10.0 +
                (unroll ? 0.0 : 1.5);
  if (opt == "O0") cost += 2.0;
  else if (opt == "O1") cost += 1.0;
  else if (opt == "O2") cost += 0.5;

  uptune::target(cost, "min");
  std::printf("block=%d alpha=%.3f unroll=%d opt=%s cost=%.4f\n", block,
              alpha, unroll ? 1 : 0, opt.c_str(), cost);
  return 0;
}
