// Unit tests for the uptune C++ client (cpp/include/uptune/uptune.hpp).
//
// Mirrors the reference's lone C++ test — default mode returns the origin
// (/root/reference/tests/cpp/test_basic.cc:5-8) — and adds the tune-mode
// coverage the reference never wrote: ANALYSIS records the space, TUNE
// serves published proposals (name-keyed and positional) and writes QoR
// rows, BEST serves best.json.
//
// The protocol mode is fixed per process (env is read once), so the
// binary re-executes itself once per phase: with no argument it
// orchestrates; with a phase argument it runs that phase's assertions.
// Plain asserts — no gtest dependency.

#include <sys/wait.h>

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <fstream>
#include <string>

#include "uptune/json.hpp"
#include "uptune/uptune.hpp"

// assert() vanishes under NDEBUG (CMake Release); CHECK never does.
#define CHECK(cond)                                                      \
  do {                                                                 \
    if (!(cond)) {                                                     \
      std::fprintf(stderr, "CHECK failed %s:%d: %s\n", __FILE__,       \
                   __LINE__, #cond);                                   \
      std::abort();                                                    \
    }                                                                  \
  } while (0)

namespace {

std::string g_dir;

std::string read_all(const std::string& path) {
  std::ifstream f(path);
  CHECK(f && "missing file");
  std::string s((std::istreambuf_iterator<char>(f)),
                std::istreambuf_iterator<char>());
  return s;
}

void write_all(const std::string& path, const std::string& text) {
  std::ofstream f(path);
  CHECK(f && "cannot write");
  f << text;
}

int run_phase(const std::string& self, const std::string& env,
              const std::string& phase) {
  std::string cmd = "env " + env + " UT_WORK_DIR=" + g_dir + " " + self +
                    " " + phase;
  int rc = std::system(cmd.c_str());
  return rc == -1 ? -1 : WEXITSTATUS(rc);
}

// ---------------------------------------------------------------- phases

void phase_default() {
  CHECK(uptune::tune(2, {1, 8}) == 2);
  CHECK(uptune::tune(0.5, {0.0, 1.0}) == 0.5);
  CHECK(uptune::tune(true) == true);
  CHECK(uptune::tune("a", {"a", "b"}) == "a");
  CHECK(uptune::tune_enum(4, std::vector<int>{2, 4, 8}) == 4);
  CHECK(uptune::mode() == uptune::Mode::Default);
  CHECK(uptune::get_global_id() == -1);
}

void phase_analysis() {
  CHECK(uptune::mode() == uptune::Mode::Analysis);
  CHECK(uptune::tune(2, {1, 8}, "bs") == 2);
  CHECK(uptune::tune(0.5, std::make_pair(0.0, 1.0), "alpha") == 0.5);
  CHECK(uptune::tune(false, "flag") == false);
  CHECK(uptune::tune("O1", {"O0", "O1", "O2"}, "opt") == "O1");
  CHECK(uptune::tune(7, {0, 100}) == 7);  // unnamed -> auto v0_4
  uptune::target(42.0, "min");
}

void phase_tune() {
  CHECK(uptune::mode() == uptune::Mode::Tune);
  CHECK(uptune::get_local_id() == 3);
  CHECK(uptune::get_global_id() == 99);
  CHECK(uptune::tune(2, {1, 8}, "bs") == 5);
  CHECK(std::fabs(uptune::tune(0.5, std::make_pair(0.0, 1.0), "alpha") -
                   0.25) < 1e-12);
  CHECK(uptune::tune(false, "flag") == true);
  CHECK(uptune::tune("O1", {"O0", "O1", "O2"}, "opt") == "O2");
  // unnamed call binds positionally via ut.params.json (types.py:132-134)
  CHECK(uptune::tune(7, {0, 100}) == 63);
  uptune::target(3.5, "min");
  uptune::target(4.5, "min");  // second report appends a second row
}

void phase_best() {
  CHECK(uptune::mode() == uptune::Mode::Best);
  CHECK(uptune::tune(2, {1, 8}, "bs") == 6);
  // unnamed: positional binding must work in BEST mode too (the ADVICE
  // round-1 finding on _load_best)
  CHECK(uptune::tune(0.5, std::make_pair(0.0, 1.0), "alpha") == 0.5);
  CHECK(uptune::tune(false, "flag") == false);
  CHECK(uptune::tune("O1", {"O0", "O1", "O2"}, "opt") == "O1");
  CHECK(uptune::tune(7, {0, 100}) == 31);
}

void phase_tune_missing() {
  // no proposal published: every call falls back to its origin
  CHECK(uptune::mode() == uptune::Mode::Tune);
  CHECK(uptune::tune(2, {1, 8}, "bs") == 2);
  CHECK(uptune::tune("O1", {"O0", "O1", "O2"}, "opt") == "O1");
}

// ------------------------------------------------------------ orchestrate

int orchestrate(const std::string& self) {
  char tmpl[] = "/tmp/utcpp.XXXXXX";
  CHECK(mkdtemp(tmpl) != nullptr);
  g_dir = tmpl;
  CHECK(std::system(("mkdir -p " + g_dir + "/configs").c_str()) == 0);

  CHECK(run_phase(self, "", "default") == 0);

  // ANALYSIS writes the space + default QoR
  CHECK(run_phase(self, "UT_BEFORE_RUN_PROFILE=On", "analysis") == 0);
  auto params = uptune::json::parse(read_all(g_dir + "/ut.params.json"));
  CHECK(params.size() == 1 && params.at(0).size() == 5);
  const auto& bs = params.at(0).at(0);
  CHECK(bs.at("name").as_string() == "bs");
  CHECK(bs.at("type").as_string() == "int");
  CHECK(bs.at("lo").as_int() == 1 && bs.at("hi").as_int() == 8);
  CHECK(bs.at("default").as_int() == 2);
  CHECK(params.at(0).at(1).at("type").as_string() == "float");
  CHECK(params.at(0).at(2).at("type").as_string() == "bool");
  CHECK(params.at(0).at(3).at("type").as_string() == "enum");
  CHECK(params.at(0).at(3).at("options").size() == 3);
  CHECK(params.at(0).at(4).at("name").as_string() == "v0_4");
  auto dq = uptune::json::parse(read_all(g_dir + "/ut.default_qor.json"));
  CHECK(dq.at("qor").as_double() == 42.0);
  CHECK(dq.at("trend").as_string() == "min");

  // TUNE serves the published proposal and writes QoR rows
  write_all(g_dir + "/configs/ut.dr_stage0_index3.json",
            "{\"bs\": 5, \"alpha\": 0.25, \"flag\": true, "
            "\"opt\": \"O2\", \"v0_4\": 63}");
  CHECK(run_phase(self,
                   "UT_TUNE_START=True UT_CURR_INDEX=3 UT_GLOBAL_ID=99",
                   "tune") == 0);
  auto qor = uptune::json::parse(read_all(g_dir + "/ut.qor_stage0.json"));
  CHECK(qor.size() == 2);
  CHECK(qor.at(0).at(0).as_int() == 3);
  CHECK(qor.at(0).at(1).as_double() == 3.5);
  CHECK(qor.at(0).at(2).as_string() == "min");
  CHECK(qor.at(1).at(1).as_double() == 4.5);

  // BEST serves best.json ({"config": ..., "qor": ...} shape)
  write_all(g_dir + "/best.json",
            "{\"config\": {\"bs\": 6, \"v0_4\": 31}, \"qor\": 1.0}");
  CHECK(run_phase(self, "BEST=True", "best") == 0);

  // TUNE with no published config degrades to defaults
  CHECK(std::system(("rm " + g_dir +
                      "/configs/ut.dr_stage0_index3.json").c_str()) == 0);
  CHECK(run_phase(self, "UT_TUNE_START=True UT_CURR_INDEX=3",
                   "tune_missing") == 0);

  CHECK(std::system(("rm -rf " + g_dir).c_str()) == 0);
  std::printf("cpp client: all phases passed\n");
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) return orchestrate(argv[0]);
  std::string phase = argv[1];
  g_dir = std::getenv("UT_WORK_DIR") ? std::getenv("UT_WORK_DIR") : ".";
  if (phase == "default") phase_default();
  else if (phase == "analysis") phase_analysis();
  else if (phase == "tune") phase_tune();
  else if (phase == "best") phase_best();
  else if (phase == "tune_missing") phase_tune_missing();
  else return 2;
  return 0;
}
