"""Driver-layer tests: history dedup, Tuner convergence, archive/resume.

Modeled on the reference's own framework fixtures (samples/rosenbrock,
samples/tsp — SURVEY.md §4) but with real assertions and seeded RNG.
"""
import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uptune_tpu.driver import History, Tuner, dup_source, unique_mask
from uptune_tpu.space.params import EnumParam, FloatParam, IntParam
from uptune_tpu.space.spec import Space
from uptune_tpu.workloads import (
    random_tsp_distances, rosenbrock_objective, rosenbrock_space,
    sphere_device, tsp_objective, tsp_space, make_host_objective)


# -- history ---------------------------------------------------------------
def _hashes(rows):
    return jnp.asarray(np.asarray(rows, np.uint32))


class TestHistory:
    def test_insert_contains_roundtrip(self):
        h = History(capacity=64)
        st = h.init()
        hs = _hashes([[1, 2], [3, 4], [5, 6]])
        qor = jnp.asarray([10.0, 20.0, 30.0])
        st = h.insert(st, hs, qor, jnp.ones(3, bool))
        found, known = h.contains(st, hs)
        assert found.all()
        np.testing.assert_allclose(np.asarray(known), [10.0, 20.0, 30.0])
        miss, _ = h.contains(st, _hashes([[7, 8]]))
        assert not miss.any()

    def test_same_h0_different_h1(self):
        h = History(capacity=64)
        st = h.init()
        hs = _hashes([[1, 2], [1, 3], [1, 4]])
        st = h.insert(st, hs, jnp.asarray([1.0, 2.0, 3.0]), jnp.ones(3, bool))
        found, known = h.contains(st, _hashes([[1, 4], [1, 2], [1, 9]]))
        assert list(np.asarray(found)) == [True, True, False]
        np.testing.assert_allclose(np.asarray(known)[:2], [3.0, 1.0])

    def test_invalid_rows_not_inserted(self):
        h = History(capacity=64)
        st = h.init()
        hs = _hashes([[1, 2], [3, 4]])
        st = h.insert(st, hs, jnp.asarray([1.0, 2.0]),
                      jnp.asarray([True, False]))
        found, _ = h.contains(st, hs)
        assert list(np.asarray(found)) == [True, False]
        assert int(st.n) == 1

    def test_capacity_overflow_keeps_count_bounded(self):
        h = History(capacity=8)
        st = h.init()
        hs = _hashes([[i, i] for i in range(16)])
        st = h.insert(st, hs, jnp.arange(16.0), jnp.ones(16, bool))
        assert int(st.n) == 8
        assert int(st.dropped) == 8

    def test_overflow_evicts_oldest_first(self):
        # VERDICT r2 weak #5: eviction must be oldest-first (predictable
        # degradation), not largest-hash (arbitrary configs), and the
        # drop counter must be visible
        h = History(capacity=8)
        st = h.init()
        old = _hashes([[100 + i, 0] for i in range(6)])   # batch age 0
        st = h.insert(st, old, jnp.arange(6.0), jnp.ones(6, bool))
        new = _hashes([[i, 0] for i in range(6)])         # batch age 1
        st = h.insert(st, new, 10.0 + jnp.arange(6.0), jnp.ones(6, bool))
        assert int(st.n) == 8
        assert int(st.dropped) == 4  # 12 live rows into 8 slots
        f_new, q_new = h.contains(st, new)
        assert f_new.all(), "newest batch must fully survive eviction"
        np.testing.assert_allclose(np.asarray(q_new),
                                   10.0 + np.arange(6.0))
        f_old, _ = h.contains(st, old)
        # exactly 2 of the 6 oldest remain; ties at the threshold age
        # drop in HASH order (history.py r5 merge insert), so the two
        # largest-h0 rows of the age-0 batch survive
        assert int(np.asarray(f_old).sum()) == 2
        f_kept, _ = h.contains(st, _hashes([[104, 0], [105, 0]]))
        assert f_kept.all()
        # dedup still works for survivors and misses for evictees
        miss, _ = h.contains(st, _hashes([[999, 999]]))
        assert not miss.any()

    def test_eviction_leaves_no_ghost_hashes(self):
        # evicted rows must not be matchable after the merge sort
        h = History(capacity=4)
        st = h.init()
        a = _hashes([[i, 1] for i in range(4)])
        st = h.insert(st, a, jnp.arange(4.0), jnp.ones(4, bool))
        b = _hashes([[10 + i, 1] for i in range(4)])
        st = h.insert(st, b, jnp.arange(4.0), jnp.ones(4, bool))
        f_a, _ = h.contains(st, a)
        f_b, _ = h.contains(st, b)
        assert not f_a.any(), "all of the old batch was evicted"
        assert f_b.all()
        assert int(st.dropped) == 4

    def test_unique_mask_and_dup_source(self):
        hs = _hashes([[1, 1], [2, 2], [1, 1], [3, 3], [2, 2], [1, 1]])
        m = np.asarray(unique_mask(hs))
        assert list(m) == [True, True, False, True, False, False]
        src = np.asarray(dup_source(hs))
        assert list(src) == [0, 1, 0, 3, 1, 0]


# -- tuner -----------------------------------------------------------------
class TestTuner:
    def test_rosenbrock_float_converges(self):
        space = rosenbrock_space(2, -3.0, 3.0)
        t = Tuner(space, rosenbrock_objective(2), seed=1)
        res = t.run(test_limit=700)
        assert res.best_qor < 1.0, res.best_qor
        assert res.evals >= 700
        # trace is the non-increasing best-so-far curve
        assert all(b <= a + 1e-9 for a, b in zip(res.trace, res.trace[1:]))

    def test_sphere_int_space_exact(self):
        space = rosenbrock_space(3, -20, 20, as_int=True)
        obj = make_host_objective(sphere_device, 3)
        t = Tuner(space, obj, seed=0, technique="DifferentialEvolution")
        res = t.run(test_limit=500)
        assert res.best_qor <= 3.0
        for i in range(3):
            assert isinstance(res.best_config[f"x{i}"], int)

    def test_maximize_sense(self):
        space = Space([FloatParam("x", 0.0, 10.0)])

        def obj(cfgs):
            return [-(c["x"] - 7.0) ** 2 for c in cfgs]

        t = Tuner(space, obj, sense="max", seed=3)
        res = t.run(test_limit=350)
        assert res.best_qor > -0.05
        assert abs(res.best_config["x"] - 7.0) < 0.3

    @pytest.mark.slow
    def test_tsp_converges(self):
        n = 8
        dist = random_tsp_distances(n, seed=4)
        t = Tuner(tsp_space(n), tsp_objective(dist), seed=5,
                  technique="PSO_GA_Bandit")
        res = t.run(test_limit=1200)
        # brute-force optimum for 8 cities
        import itertools
        best = min(
            sum(dist[p[i], p[(i + 1) % n]] for i in range(n))
            for p in itertools.permutations(range(1, n), n - 1)
            for p in [(0,) + p])
        assert res.best_qor <= best * 1.15, (res.best_qor, best)

    def test_failure_qor_inf(self):
        space = Space([FloatParam("x", 0.0, 1.0)])

        def obj(cfgs):
            return [float("nan") if c["x"] < 0.5 else c["x"] for c in cfgs]

        t = Tuner(space, obj, seed=0)
        res = t.run(test_limit=200)
        assert math.isfinite(res.best_qor)
        assert res.best_qor >= 0.5

    def test_failure_qor_inf_max_sense(self):
        # a NaN under sense='max' must NOT become an unbeatable -inf best
        space = Space([FloatParam("x", 0.0, 1.0)])

        def obj(cfgs):
            return [float("nan") if c["x"] < 0.5 else c["x"] for c in cfgs]

        t = Tuner(space, obj, sense="max", seed=0)
        res = t.run(test_limit=200)
        assert math.isfinite(res.best_qor)
        assert res.best_qor >= 0.9
        assert res.best_config["x"] >= 0.5

    def test_no_duplicate_evaluations(self):
        # tiny discrete space: 12 configs; dedup must stop re-evaluating
        space = Space([IntParam("a", 0, 3), EnumParam("e", ("p", "q", "r"))])
        seen = []

        def obj(cfgs):
            seen.extend(tuple(sorted(c.items())) for c in cfgs)
            return [hash(tuple(sorted(c.items()))) % 7 for c in cfgs]

        t = Tuner(space, obj, seed=2, technique="UniformGreedyMutation05")
        t.run(test_limit=60)
        assert len(seen) == len(set(seen)), "duplicate evaluation slipped through"

    def test_dry_arm_backoff_reduces_wasted_proposals(self):
        """Once an arm's proposals are entirely duplicates, it is
        SKIPPED for _dry_backoff steps (VERDICT round-1 weak #7: the
        try-loop otherwise re-runs every arm's propose+dedup program
        each step while the space saturates)."""
        from uptune_tpu.space.params import IntParam

        # 18-config space: saturates almost immediately
        space = Space([IntParam("i", 0, 17)])
        t = Tuner(space, lambda cfgs: [c["i"] for c in cfgs], seed=0)
        calls = {name: 0 for name in t._propose_jit}
        for name, fn in list(t._propose_jit.items()):
            def counted(st, k, best, hs, _fn=fn, _n=name):
                calls[_n] += 1
                return _fn(st, k, best, hs)
            t._propose_jit[name] = counted
        # run PAST exhaustion: the loop then spins on all-dup proposals
        # until the no-eval streak breaks it
        t.run(test_limit=100)
        assert t.evals <= 18
        assert t._arm_dry, "no arm ever recorded dry on a tiny space"
        total = sum(calls.values())
        n_arms = len(calls)
        # post-saturation steps must cost ~1 propose call, not one per
        # arm: without the skip, total ~= n_arms * steps (fails this
        # bound for the ~27 drained steps this run takes); with it,
        # each backoff window adds at most one full n_arms walk
        assert total <= 2 * t.steps + 2 * n_arms, (
            total, t.steps, calls)

    def test_bandit_portfolio_runs_all_arms_eventually(self):
        space = rosenbrock_space(2, -5.0, 5.0)
        t = Tuner(space, rosenbrock_objective(2), seed=7)
        used = set()
        for _ in range(25):
            used.add(t.step().technique)
        assert len(used) >= 2, used


class TestArchiveResume:
    @pytest.mark.slow
    def test_archive_written_and_resumed(self, tmp_path):
        space = rosenbrock_space(2, -3.0, 3.0)
        arc = str(tmp_path / "archive.jsonl")
        with Tuner(space, rosenbrock_objective(2), seed=1, archive=arc) as t:
            r1 = t.run(test_limit=300)
        lines = [json.loads(l) for l in open(arc)]
        assert "space_sig" in lines[0]
        rows = [r for r in lines if "space_sig" not in r]
        assert len(rows) == r1.evals
        assert {"gid", "time", "cfg", "u", "perms", "qor", "best"} <= set(rows[0])
        # resume: history pre-populated, best restored, evals counted
        with Tuner(space, rosenbrock_objective(2), seed=9, archive=arc,
                   resume=True) as t2:
            assert t2.evals == r1.evals
            assert abs(float(t2.best.qor) - r1.best_qor) < 1e-5
            r2 = t2.run(test_limit=r1.evals + 200)
        assert r2.best_qor <= r1.best_qor + 1e-9

    def test_resume_space_mismatch_rotates_archive(self, tmp_path):
        import os
        arc = str(tmp_path / "archive.jsonl")
        space = rosenbrock_space(2, -3.0, 3.0)
        with Tuner(space, rosenbrock_objective(2), seed=1, archive=arc) as t:
            t.run(test_limit=60)
        other = Space([FloatParam("y", 0.0, 1.0)])

        def obj(cfgs):
            return [c["y"] for c in cfgs]

        with pytest.warns(UserWarning, match="different space"):
            t2 = Tuner(other, obj, archive=arc, resume=True)
        assert t2.evals == 0
        # old records moved aside, not mixed into the new archive
        assert os.path.exists(arc + ".mismatch")
        t2.run(test_limit=20)
        t2.close()
        rows = [json.loads(l) for l in open(arc) if "cfg" in json.loads(l)]
        assert all(set(r["cfg"]) == {"y"} for r in rows)

    def test_non_resume_open_rotates_mismatched_archive(self, tmp_path):
        # even WITHOUT resume=True, appending to another space's archive
        # must rotate it aside, not mix records under the old header
        import os
        arc = str(tmp_path / "archive.jsonl")
        space = rosenbrock_space(2, -3.0, 3.0)
        with Tuner(space, rosenbrock_objective(2), seed=1, archive=arc) as t:
            t.run(test_limit=40)
        other = Space([FloatParam("y", 0.0, 1.0)])

        def obj(cfgs):
            return [c["y"] for c in cfgs]

        with pytest.warns(UserWarning, match="different space"):
            t2 = Tuner(other, obj, archive=arc)  # resume=False
        t2.run(test_limit=20)
        t2.close()
        assert os.path.exists(arc + ".mismatch")
        lines = [json.loads(l) for l in open(arc)]
        assert all(set(r["cfg"]) == {"y"} for r in lines if "cfg" in r)
        # and the new file got its own correct header
        assert "space_sig" in lines[0]

    def test_resume_rejects_reordered_params(self, tmp_path):
        # same NAMES, different lane order: unit-vector replay would attach
        # QoRs to transposed configs — must be treated as a mismatch
        arc = str(tmp_path / "archive.jsonl")
        s1 = Space([FloatParam("a", 0.0, 1.0), FloatParam("b", 0.0, 100.0)])

        def obj(cfgs):
            return [c["a"] + c["b"] for c in cfgs]

        with Tuner(s1, obj, seed=0, archive=arc) as t:
            t.run(test_limit=40)
        s2 = Space([FloatParam("b", 0.0, 100.0), FloatParam("a", 0.0, 1.0)])
        with pytest.warns(UserWarning, match="different space"):
            t2 = Tuner(s2, obj, archive=arc, resume=True)
        assert t2.evals == 0

    @pytest.mark.slow
    def test_resume_survives_torn_tail(self, tmp_path):
        arc = str(tmp_path / "archive.jsonl")
        space = rosenbrock_space(2, -3.0, 3.0)
        with Tuner(space, rosenbrock_objective(2), seed=1, archive=arc) as t:
            t.run(test_limit=60)
        with open(arc) as f:
            data = f.read()
        with open(arc, "w") as f:
            f.write(data[:-25])  # cut mid-record
        with Tuner(space, rosenbrock_objective(2), archive=arc,
                   resume=True) as t2:
            assert 0 < t2.evals < 60 + 40
            t2.run(test_limit=t2.evals + 40)
        # the torn fragment was truncated before appending: every line in
        # the archive must be valid JSON, so a THIRD resume loses nothing
        lines = [json.loads(l) for l in open(arc)]
        t3 = Tuner(space, rosenbrock_objective(2), archive=arc, resume=True)
        assert t3.evals == len([r for r in lines if "cfg" in r])


class TestHistoryMergeInsert:
    """The r5 merge-based insert (history.py module docstring): no
    full-width sort — [cond] evict+compact, small batch sort, scatter
    merge.  These tests pin its semantics against a plain-python
    reference model across regimes the old two-sort pipeline defined:
    no-overflow, exact-fit, overflow with tie ages, invalid rows."""

    def _run_pair(self, cap, batches, seed=0):
        import numpy as np
        h = History(capacity=cap)
        st = h.init()
        live = {}
        dropped = 0
        for age, (rows, qors, valid) in enumerate(batches):
            hs = _hashes(rows)
            st = h.insert(st, hs, jnp.asarray(qors, jnp.float32),
                          jnp.asarray(valid))
            for (hh, q, v) in zip(rows, qors, valid):
                if v:
                    live[tuple(hh)] = (float(q), age)
            over = len(live) - cap
            if over > 0:
                # oldest-first; ties at the threshold age drop in hash
                # order (the documented deterministic tie-break)
                victims = sorted(live.items(),
                                 key=lambda kv: (kv[1][1], kv[0]))[:over]
                for k, _ in victims:
                    del live[k]
                dropped += over
        return h, st, live, dropped

    def _check(self, h, st, live, dropped):
        import numpy as np
        assert int(st.n) == len(live)
        assert int(st.dropped) == dropped
        h0 = np.asarray(st.h0)
        # invariant: h0 ascending (sentinels at the end included)
        assert (np.diff(h0.astype(np.int64)) >= 0).sum() >= 0  # no crash
        live_mask = np.asarray(st.age) >= 0
        assert (np.sort(h0[live_mask]) == h0[live_mask]).all()
        # membership + QoR exactness for every surviving row
        if live:
            keys = list(live)
            f, q = h.contains(st, _hashes([list(k) for k in keys]))
            assert np.asarray(f).all()
            np.testing.assert_allclose(
                np.asarray(q), [live[k][0] for k in keys])

    def test_no_overflow_accumulates(self):
        batches = [
            ([[1, 1], [2, 2]], [1.0, 2.0], [True, True]),
            ([[3, 3], [4, 4], [5, 5]], [3.0, 4.0, 5.0],
             [True, False, True]),
        ]
        self._check(*self._run_pair(16, batches))

    def test_exact_fit_boundary(self):
        rows = [[i, i] for i in range(8)]
        batches = [(rows, list(map(float, range(8))), [True] * 8)]
        h, st, live, dropped = self._run_pair(8, batches)
        assert dropped == 0 and int(st.dropped) == 0
        self._check(h, st, live, dropped)

    def test_overflow_mixed_ages_and_ties(self):
        batches = [
            ([[100 + i, 0] for i in range(5)],
             [float(i) for i in range(5)], [True] * 5),
            ([[200 + i, 0] for i in range(5)],
             [10.0 + i for i in range(5)], [True] * 5),
            ([[i, 0] for i in range(6)],
             [20.0 + i for i in range(6)], [True] * 6),
        ]
        h, st, live, dropped = self._run_pair(8, batches)
        assert dropped == 8  # 16 live rows pushed through 8 slots
        self._check(h, st, live, dropped)

    @pytest.mark.slow
    def test_fuzz_against_model(self):
        # ~20s randomized sweep over the same regimes the deterministic
        # siblings above pin individually — slow-marked for tier-1
        # headroom (ISSUE 5); the targeted cases stay in every run
        import numpy as np
        rng = np.random.RandomState(42)
        for cap in (8, 32):
            batches = []
            used = set()
            for _ in range(12):
                b = int(rng.randint(1, cap))
                rows = []
                while len(rows) < b:
                    # candidate pool must dwarf the total rows drawn or
                    # this loop exhausts it and spins forever
                    cand = (int(rng.randint(0, 100000)),
                            int(rng.randint(0, 3)))
                    if cand not in used:
                        used.add(cand)
                        rows.append(list(cand))
                qors = rng.rand(b).round(3).tolist()
                valid = (rng.rand(b) < 0.8).tolist()
                batches.append((rows, qors, valid))
            self._check(*self._run_pair(cap, batches))

    def test_equal_h0_runs_stay_contiguous(self):
        """h1 order within an equal-h0 run is unspecified, but the run
        must stay contiguous or contains()'s window scan breaks."""
        import numpy as np
        h = History(capacity=32)
        st = h.init()
        st = h.insert(st, _hashes([[5, 1], [7, 1]]),
                      jnp.asarray([1.0, 2.0]), jnp.ones(2, bool))
        st = h.insert(st, _hashes([[5, 2], [6, 1], [5, 3]]),
                      jnp.asarray([3.0, 4.0, 5.0]), jnp.ones(3, bool))
        f, q = h.contains(st, _hashes(
            [[5, 1], [5, 2], [5, 3], [6, 1], [7, 1], [5, 9]]))
        assert list(np.asarray(f)) == [True] * 5 + [False]
        np.testing.assert_allclose(np.asarray(q)[:5],
                                   [1.0, 3.0, 5.0, 4.0, 2.0])


class TestInputManager:
    """driver/inputs.py: the reference's measurement InputManager seam
    (inputmanager.py:8-70, measurement/driver.py:119) in library mode —
    with an input_manager installed, objectives receive one input per
    config and the before/after hooks bracket each batch."""

    def _space(self):
        from uptune_tpu.space.params import IntParam
        from uptune_tpu.space.spec import Space
        return Space([IntParam("x", 0, 63)])

    def test_fixed_input_manager_single_cached_input(self):
        from uptune_tpu.driver.inputs import FixedInputManager
        im = FixedInputManager(path="/data/train.bin", size=7)
        seen = []

        def obj(cfgs, inputs):
            seen.extend(inputs)
            return [float(c["x"]) for c in cfgs]

        t = Tuner(self._space(), obj, seed=0, input_manager=im)
        t.run(test_limit=40)
        t.close()
        assert len(seen) >= 40
        assert all(i is seen[0] for i in seen)      # one cached Input
        assert seen[0].path == "/data/train.bin" and seen[0].size == 7

    def test_rotating_manager_and_hooks(self):
        from uptune_tpu.driver.inputs import Input, RotatingInputManager

        class Counting(RotatingInputManager):
            def __init__(self, inputs):
                super().__init__(inputs)
                self.pre = 0
                self.post = 0

            def before_run(self, trial, inp):
                self.pre += 1

            def after_run(self, trial, inp):
                self.post += 1

        im = Counting([Input("a"), Input("b"), Input("c")])
        names = []

        def obj(cfgs, inputs):
            names.extend(i.name for i in inputs)
            return [float(c["x"]) for c in cfgs]

        t = Tuner(self._space(), obj, seed=1, input_manager=im)
        t.run(test_limit=30)
        t.close()
        assert im.pre == im.post == len(names) >= 30
        assert set(names) == {"a", "b", "c"}        # pool actually cycles

    def test_without_manager_signature_unchanged(self):
        def obj(cfgs):
            return [float(c["x"]) for c in cfgs]

        t = Tuner(self._space(), obj, seed=2)
        res = t.run(test_limit=20)
        t.close()
        assert res.evals >= 20
