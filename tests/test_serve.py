"""Tuning-as-a-service tests (uptune_tpu/serve, docs/SERVING.md).

Coverage map:
* wire bridge round trip (records_from_space <-> space_from_params)
* `serve-*` ut.config keys and the flags > ut.config > DEFAULTS
  precedence contract (mirrors the store/trace key tests)
* session mechanics on the offline single-slot group: versioned
  epochs, lazy memo/dedup scan, stale tickets, failure QoRs
* server protocol: transport-free handle() dispatch, real TCP
  client, metrics scrape (the obs plane's serving seam), admission
* ISOLATION + PARITY: concurrently driven server sessions bitwise
  equal to sequential offline sessions at matched seeds (the
  multiplexing contract; soak version with churn slow-marked)
* cross-tenant memo: one tenant's recorded builds serve another's
  ask; program tokens scope the sharing
* batched wire plane (ISSUE 20): cross-session ask_many/tell_many
  frames bitwise equal to the per-op drive at matched seeds,
  duplicate replay squashed through the vectorized tell_many op,
  down-level server compat fallback (kernel-level frame semantics
  live in test_wire_batch.py)
* strict no-retrace: join/leave/ask/tell churn rides three compiled
  programs, each traced exactly once
* `bench.py --serve --quick` tier-1 smoke

Engine groups compile three programs each (~seconds), so the suite
shares ONE server (module scope) and ONE offline single-slot group;
the offline group is reused across seeds via join/leave, which is
exactly LocalSession's machinery (their identity is asserted in the
slow soak).
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from uptune_tpu.api import session as api_session  # noqa: E402
from uptune_tpu.exec.space_io import (  # noqa: E402
    records_from_space, space_from_params)
from uptune_tpu.serve import (  # noqa: E402
    LocalSession, ServeError, SessionServer, connect)
from uptune_tpu.serve.cli import build_parser, resolve_config  # noqa: E402
from uptune_tpu.serve.group import SessionGroup, group_key  # noqa: E402
from uptune_tpu.serve.session import StaleTicketError  # noqa: E402
from uptune_tpu.workloads import rosenbrock_space  # noqa: E402

DIMS = 2


def _space():
    return rosenbrock_space(DIMS, -3.0, 3.0)


def _measure(cfg):
    x = np.array([cfg[f"x{i}"] for i in range(DIMS)])
    return float(np.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2
                        + (1 - x[:-1]) ** 2))


def _drive_epochs(sess, epochs, chunk=7):
    """Interleaved chunked ask/tell until the session advances `epochs`
    versions past where it started; returns the full offered-config
    trajectory (the bitwise parity evidence).  Progress is measured on
    ``sess.version``, not on commits observed via tell: a fully
    memo-served epoch auto-commits with ZERO tells (ask returns [] and
    the version jumps), and counting tell-side commits would overdrive
    the session past the target."""
    offered = []
    target = sess.version + epochs
    while sess.version < target:
        trials = sess.ask(chunk)
        if not trials:      # memo auto-committed; version re-checked
            continue
        offered.extend(t.config for t in trials)
        for t in trials:
            sess.tell(t.ticket, _measure(t.config))
    return offered


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    """One shared server: 8-slot groups, memo store on."""
    store = str(tmp_path_factory.mktemp("serve_store"))
    srv = SessionServer(host="127.0.0.1", port=0, slots=8,
                        max_sessions=64, store_dir=store).start()
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def offline():
    """One shared single-slot group, reused across seeds via
    join/leave — the sequential offline baseline."""
    return SessionGroup(_space(), 1)


class TestWireBridge:
    def test_records_roundtrip_signature(self):
        sp = _space()
        recs = records_from_space(sp)
        assert json.loads(json.dumps(recs)) == recs   # JSON-clean
        sp2 = space_from_params(recs)
        assert sp2.signature() == sp.signature()

    def test_roundtrip_covers_param_kinds(self):
        from uptune_tpu.space import params as P
        from uptune_tpu.space.spec import Space
        sp = Space([
            P.IntParam("i", 1, 9), P.FloatParam("f", 0.0, 1.0),
            P.BoolParam("b"), P.Pow2Param("p", 1, 16),
            P.EnumParam("e", ["a", "c"]),
            P.PermParam("perm", [0, 1, 2]),
        ])
        sp2 = space_from_params(records_from_space(sp))
        assert sp2.signature() == sp.signature()


class TestConfigKeys:
    def test_defaults_have_serve_keys(self):
        for k in ("serve-host", "serve-port", "serve-slots",
                  "serve-max-sessions", "serve-store-dir"):
            assert k in api_session.DEFAULTS

    def test_precedence_flags_over_config_over_defaults(self):
        """CLI flags > ut.config > DEFAULTS for the new subcommand
        (same contract, same test shape as the store/trace keys)."""
        import uptune_tpu as ut
        try:
            # default layer
            args = build_parser().parse_args([])
            assert resolve_config(args)["port"] == \
                api_session.DEFAULTS["serve-port"]
            # ut.config layer overrides the default
            ut.config({"serve-port": 9100, "serve-slots": 3})
            cfg = resolve_config(build_parser().parse_args([]))
            assert cfg["port"] == 9100 and cfg["slots"] == 3
            # explicit flag beats ut.config
            cfg = resolve_config(build_parser().parse_args(
                ["--port", "9200", "--store-dir", "off"]))
            assert cfg["port"] == 9200
            assert cfg["slots"] == 3
            assert cfg["store_dir"] == "off"
        finally:
            api_session.reset_settings()

    def test_server_constructor_reads_settings(self):
        try:
            api_session.settings["serve-slots"] = 5
            api_session.settings["serve-max-sessions"] = 7
            api_session.settings["serve-store-dir"] = "off"
            srv = SessionServer(port=0)     # not started: no sockets
            assert srv.slots == 5 and srv.max_sessions == 7
            assert srv.store_dir is None
        finally:
            api_session.reset_settings()

    def test_bad_slots_rejected(self):
        with pytest.raises(ValueError):
            SessionServer(port=0, slots=0)

    def test_ut_cli_dispatches_serve(self):
        """`ut serve ...` routes to the serve subcommand's own parser
        (argparse --help exits 0 before any server is constructed)."""
        from uptune_tpu import cli
        with pytest.raises(SystemExit) as e:
            cli.main(["serve", "--help"])
        assert e.value.code == 0


class TestSessionMechanics:
    def test_versioned_epochs_and_dedup(self, offline):
        s = offline.join(seed=11)
        try:
            assert s.version == 0
            seen = {}
            told = 0
            while True:
                trials = s.ask(5)
                if not trials:
                    assert told > 0
                    break
                for t in trials:
                    key = json.dumps(t.config, sort_keys=True)
                    # in-epoch duplicates never get a second ticket
                    assert key not in seen
                    seen[key] = t.ticket
                    r = s.tell(t.ticket, _measure(t.config))
                    told += 1
                    if r["committed"]:
                        break
                if s.version:
                    break
            assert s.version == 1
            # a ticket from the published-over epoch is stale
            with pytest.raises(StaleTicketError):
                s.tell(next(iter(seen.values())), 1.0)
        finally:
            s.close()

    def test_failure_qor_never_becomes_best(self, offline):
        s = offline.join(seed=12)
        try:
            trials = s.ask(4)
            s.tell(trials[0].ticket, None)          # build failure
            s.tell(trials[1].ticket, float("inf"))  # unbounded
            assert s.best()["qor"] is None
            s.tell(trials[2].ticket, 3.25)
            assert s.best()["qor"] == 3.25
        finally:
            s.close()

    def test_malformed_qor_leaves_ticket_live(self, offline):
        """A non-numeric qor must fail WITHOUT consuming the ticket:
        popping first would strand the epoch one row short of settled
        forever (the session could never commit or ask again)."""
        s = offline.join(seed=14)
        try:
            t = s.ask(1)[0]
            with pytest.raises((TypeError, ValueError)):
                s.tell(t.ticket, "oops")
            r = s.tell(t.ticket, 1.5)       # retry succeeds
            assert s.best()["qor"] == 1.5
            assert r["version"] == s.version
        finally:
            s.close()

    def test_closed_session_rejects_ops(self, offline):
        s = offline.join(seed=13)
        s.close()
        with pytest.raises(StaleTicketError):
            s.ask(1)
        # slot is back in the pool
        assert offline.n_free == 1

    def test_group_key_identity(self):
        sp = _space()
        assert group_key(sp, None, "min", 64) == \
            group_key(space_from_params(records_from_space(sp)),
                      None, "min", 64)
        assert group_key(sp, None, "min", 64) != \
            group_key(sp, None, "max", 64)


class TestServerProtocol:
    def test_handle_rejects_garbage(self, server):
        assert server.handle(["nope"])["ok"] is False
        assert "unknown op" in server.handle({"op": "zap"})["error"]
        r = server.handle({"op": "ask", "session": "missing"})
        assert r["ok"] is False and "unknown session" in r["error"]
        r = server.handle({"op": "open", "space": []})
        assert r["ok"] is False
        r = server.handle({"op": "open",
                           "space": [{"name": "x", "type": "wat"}],
                           "id": 7})
        assert r["ok"] is False and r["id"] == 7
        recs = records_from_space(_space())
        r = server.handle({"op": "open", "space": recs,
                           "sense": "sideways"})
        assert r["ok"] is False and "sense" in r["error"]

    def test_admission_limit(self, server):
        old = server.max_sessions
        server.max_sessions = server.n_sessions
        try:
            r = server.handle({"op": "open",
                               "space": records_from_space(_space())})
            assert r["ok"] is False and "full" in r["error"]
        finally:
            server.max_sessions = old

    def test_tcp_open_ask_tell_best_close(self, server):
        with connect(("127.0.0.1", server.port)) as c:
            assert c.ping()["ok"]
            with c.open_session(_space(), seed=21, program="tcp-e2e",
                                store=False) as h:
                trials = h.ask(6)
                assert len(trials) == 6
                qs = [_measure(t.config) for t in trials]
                r = h.tell_many(zip((t.ticket for t in trials), qs))
                assert r["told"] == 6
                b = h.best()
                assert b["qor"] == min(qs)
                # stale/bogus ticket is an error, not a crash
                with pytest.raises(ServeError):
                    h.tell(10 ** 9, 1.0)

    def test_dead_connection_reaps_its_sessions(self, server):
        """A client that crashes without op:close must not hold its
        slot + admission unit forever: session lifetime is
        connection-scoped, the server reaps on disconnect."""
        before = server.n_sessions
        c = connect(("127.0.0.1", server.port))
        c.open_session(_space(), seed=24, store=False)
        assert server.n_sessions == before + 1
        c.close()   # socket drop, no {"op": "close"} sent
        deadline = time.time() + 5.0
        while server.n_sessions > before and time.time() < deadline:
            time.sleep(0.02)
        assert server.n_sessions == before

    def test_metrics_scrape_is_obs_snapshot(self, server):
        """The `metrics` op serves obs.metrics.snapshot() — the seam
        PR 7 left open — including the server's own gauges/hists."""
        with connect(("127.0.0.1", server.port)) as c:
            with c.open_session(_space(), seed=22, store=False) as h:
                for t in h.ask(3):
                    h.tell(t.ticket, _measure(t.config))
                m = c.metrics()
        snap = m["metrics"]
        assert m["sessions"] >= 1
        assert snap["counters"]["serve.asks"] >= 3
        assert snap["counters"]["serve.tells"] >= 3
        assert snap["gauges"]["serve.sessions.active"] >= 1
        assert snap["hists"]["serve.ask_ms"]["count"] >= 1
        assert "p95" in snap["hists"]["serve.ask_ms"]

    def test_stats_op(self, server):
        st = server.handle({"op": "stats"})
        assert st["ok"] and isinstance(st["groups"], list)

    def test_unhashable_op_is_an_error_reply(self, server):
        r = server.handle({"op": ["ask"]})
        assert r["ok"] is False and "unknown op" in r["error"]

    def test_batch_tell_applies_elementwise(self, server):
        """One bad ticket in a `results` batch must not strand the
        good elements: they are told server-side, the failure comes
        back in `errors`, and the epoch can still settle."""
        with connect(("127.0.0.1", server.port)) as c:
            with c.open_session(_space(), seed=23, store=False) as h:
                trials = h.ask(3)
                r = h.tell_many([(trials[0].ticket,
                                  _measure(trials[0].config)),
                                 (10 ** 9, 1.0)])
                assert r["told"] == 1
                assert r["errors"][0]["ticket"] == 10 ** 9
                for t in trials[1:]:
                    h.tell(t.ticket, _measure(t.config))


class TestDistributedObs:
    """ISSUE 10 serve-plane halves: trace-context propagation over the
    wire and the Prometheus scrape format."""

    def test_prometheus_scrape_format(self, server):
        with connect(("127.0.0.1", server.port)) as c:
            with c.open_session(_space(), seed=31, store=False) as h:
                for t in h.ask(2):
                    h.tell(t.ticket, _measure(t.config))
            m = c.metrics(format="prometheus")
        text = m["metrics_text"]
        assert "metrics" not in m          # text replaces the snapshot
        assert "# TYPE ut_serve_asks counter" in text
        assert "ut_serve_sessions_active" in text
        # histogram summaries: quantile series + _sum/_count
        assert 'ut_serve_ask_ms{quantile="0.5"}' in text
        assert "ut_serve_ask_ms_count" in text
        r = server.handle({"op": "metrics", "format": "nope"})
        assert r["ok"] is False and "format" in r["error"]

    def test_ctx_joins_client_and_handler_spans(self, server):
        """A traced client's requests carry ctx span ids; the server's
        serve.handle spans carry them back as `parent` — the join
        `ut-trace merge` annotates.  In-process here, so both sides
        land in one ring set and the pairing is directly assertable."""
        from uptune_tpu import obs
        if not obs.enabled():
            obs.enable()
        with connect(("127.0.0.1", server.port)) as c:
            with c.open_session(_space(), seed=32, store=False) as h:
                for t in h.ask(2):
                    h.tell(t.ticket, _measure(t.config))
        evs = obs.snapshot()["events"]
        ctxs = {(e["attrs"] or {}).get("ctx") for e in evs
                if e["name"] == "client.request"}
        pairs = [(e["attrs"] or {}) for e in evs
                 if e["name"] == "serve.handle"
                 and (e["attrs"] or {}).get("parent")]
        assert ctxs and pairs
        assert {p["parent"] for p in pairs} <= ctxs
        # ops are tagged on both sides of the join
        assert {p["op"] for p in pairs} >= {"open", "ask", "tell"}

    def test_scrape_carries_device_family(self, server):
        """Device telemetry rides the metrics registry like every
        other family (ISSUE 13): a traced server's engine-program
        dispatches land in the `{"op": "metrics"}` scrape as
        device.* counters with zero serve-plane plumbing."""
        from uptune_tpu import obs
        if not obs.enabled():
            obs.enable()
        with connect(("127.0.0.1", server.port)) as c:
            with c.open_session(_space(), seed=34, store=False) as h:
                for t in h.ask(2):
                    h.tell(t.ticket, _measure(t.config))
            m = c.metrics()
        counters = m["metrics"]["counters"]
        # join (init_slot) + ask (propose_all) + tell (commit_slot)
        # all dispatch instrumented engine programs
        assert counters.get("device.dispatches", 0) > 0
        assert "device.dispatch_ms" in m["metrics"]["hists"]

    def test_untraced_client_sends_no_ctx(self, server):
        """The wire stays minimal for untraced clients: no ctx field
        leaves the process (asserted at the payload level)."""
        from uptune_tpu import obs
        was = obs.enabled()
        obs.disable()
        try:
            captured = {}

            with connect(("127.0.0.1", server.port)) as c:
                import uptune_tpu.serve.client as mod
                real = mod._ENC

                def spy(payload):
                    if isinstance(payload, dict) and "op" in payload:
                        captured.setdefault(payload["op"], payload)
                    return real(payload)

                old = mod._ENC
                mod._ENC = spy
                try:
                    c.ping()
                finally:
                    mod._ENC = old
            assert "ctx" not in captured["ping"]
        finally:
            if was:
                obs.enable()


class TestIsolationParity:
    SEEDS = (101, 202, 303, 404)

    def test_threaded_server_matches_sequential_offline(self, server,
                                                        offline):
        """THE multiplexing contract: N sessions driven CONCURRENTLY
        over TCP (interleaved chunked ask/tell, one group, shared
        epochs) produce per-session trajectories and incumbents
        bitwise equal to the same seeds driven sequentially on the
        offline single-slot group."""
        results = {}
        errors = []

        def run(seed):
            try:
                with connect(("127.0.0.1", server.port)) as c:
                    with c.open_session(_space(), seed=seed,
                                        store=False) as h:
                        offered = _drive_epochs(h, epochs=2)
                        results[seed] = (offered, h.best())
            except Exception as e:   # surfaced below
                errors.append((seed, repr(e)))

        ts = [threading.Thread(target=run, args=(s,))
              for s in self.SEEDS]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errors, errors

        for seed in self.SEEDS:
            s = offline.join(seed=seed)
            try:
                offered = _drive_epochs(s, epochs=2)
                best = s.best()
            finally:
                s.close()
            got_offered, got_best = results[seed]
            assert got_offered == offered, f"seed {seed} diverged"
            assert got_best["qor"] == best["qor"]
            assert got_best["config"] == best["config"]
            assert got_best["version"] == best["version"] == 2

    @pytest.mark.slow
    def test_soak_parity_with_churn_and_memo(self, tmp_path):
        """Soak: 12 sessions on a fresh server, 3 epochs, mid-run
        close/reopen churn, memo ON — per-seed bests still bitwise
        equal to LocalSession (same seeds, memo changes who BUILDS a
        row, never its value), and LocalSession is the same machinery
        as the shared offline group."""
        srv = SessionServer(host="127.0.0.1", port=0, slots=4,
                            max_sessions=64,
                            store_dir=str(tmp_path / "memo")).start()
        try:
            seeds = list(range(500, 512))
            results = {}
            lock = threading.Lock()

            def run(my):
                with connect(("127.0.0.1", srv.port)) as c:
                    for i, seed in enumerate(my):
                        h = c.open_session(_space(), seed=seed,
                                           program="soak")
                        _drive_epochs(h, epochs=1)
                        if i % 2:       # churn: leave + rejoin
                            h.close()
                            h = c.open_session(_space(), seed=seed,
                                               program="soak")
                            # memo replays epoch 1; drive to epoch 3
                            _drive_epochs(h, epochs=3)
                        else:
                            _drive_epochs(h, epochs=2)
                        with lock:
                            results[seed] = h.best()
                        h.close()

            ts = [threading.Thread(target=run,
                                   args=(seeds[i::3],))
                  for i in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert len(results) == len(seeds)
        finally:
            srv.stop()

        ref = LocalSession(_space(), seed=seeds[0])
        try:
            _drive_epochs(ref, epochs=3)
            b = ref.best()
        finally:
            ref.close()
        assert results[seeds[0]]["qor"] == b["qor"]
        assert results[seeds[0]]["config"] == b["config"]
        for seed in seeds[1:]:
            s = LocalSession(_space(), seed=seed)
            try:
                _drive_epochs(s, epochs=3)
                assert results[seed]["qor"] == s.best()["qor"], seed
            finally:
                s.close()


class TestCrossTenantMemo:
    def test_memo_serves_other_tenants_rows(self, server):
        """Tenant A measures an epoch; tenant B (same space, same
        program, same seed => same proposals) is served every row
        from the memo: epoch commits with ZERO tells."""
        with connect(("127.0.0.1", server.port)) as c:
            with c.open_session(_space(), seed=42,
                                program="memo-shared") as a:
                _drive_epochs(a, epochs=1)
                best_a = a.best()
            with c.open_session(_space(), seed=42,
                                program="memo-shared") as b:
                trials = b.ask(4)
                bb = b.best()
                # epoch 1 auto-committed from the memo; any offers are
                # epoch 2 (which nobody measured yet)
                assert bb["version"] >= 1
                assert bb["tells"] == 0
                assert bb["store_served"] > 0
                assert bb["qor"] == best_a["qor"]
                assert bb["config"] == best_a["config"]
                if trials:
                    assert b.version >= 1

    def test_program_token_scopes_the_memo(self, server):
        """Same space + seed under a DIFFERENT program token shares
        nothing: every row needs a build."""
        with connect(("127.0.0.1", server.port)) as c:
            with c.open_session(_space(), seed=42,
                                program="memo-other") as d:
                trials = d.ask(5)
                assert len(trials) == 5
                assert d.best()["store_served"] == 0


class TestBatchedWirePlane:
    """ISSUE 20 on the session server: cross-session frames and the
    vectorized tell_many op.  Kernel-level frame semantics (error
    entries, nesting, oversize, encode fast path) are nailed down in
    test_wire_batch.py; here the engine-backed server proves the
    frames change the transport and nothing else."""

    SEEDS = (611, 622, 633)

    def test_frame_drive_matches_sequential_offline(self, server,
                                                    offline):
        """Bitwise matched-seed parity: sessions driven through
        cross-session frames (SessionClient.ask_many / tell_many —
        2 RTTs per wave) yield offered-config trajectories and
        incumbents equal to the per-op offline drive."""
        with connect(("127.0.0.1", server.port)) as c:
            hs = [c.open_session(_space(), seed=s, store=False)
                  for s in self.SEEDS]
            offered = {h.id: [] for h in hs}
            target = {h.id: h.version + 2 for h in hs}
            live = list(hs)
            while live:
                offers = c.ask_many(live, n=7)
                pairs = []
                for h, tr in zip(live, offers):
                    if tr:
                        offered[h.id].extend(t.config for t in tr)
                        pairs.append(
                            (h, [(t.ticket, _measure(t.config))
                                 for t in tr]))
                if pairs:
                    c.tell_many(pairs)
                live = [h for h in hs if h.version < target[h.id]]
            bests = {h.id: h.best() for h in hs}
            for h in hs:
                h.close()
        assert c._batch_ok is True       # frames actually rode
        for h, seed in zip(hs, self.SEEDS):
            s = offline.join(seed=seed)
            try:
                want = _drive_epochs(s, epochs=2)
                wb = s.best()
            finally:
                s.close()
            assert offered[h.id] == want, f"seed {seed} diverged"
            assert bests[h.id]["qor"] == wb["qor"]
            assert bests[h.id]["config"] == wb["config"]
            assert bests[h.id]["version"] == 2

    def test_tell_many_replay_squashes_duplicates(self, server):
        """At-least-once retries through the vectorized op: replaying
        an already-told batch (the ack was lost) squashes every row —
        told=0, duplicates=n, no errors, version unchanged (PR 15's
        epoch-tag matrix, through the ISSUE 20 op) — including when
        the replay rides a batch frame, the client-resume shape."""
        recs = records_from_space(_space())
        r = server.handle({"op": "open", "space": recs,
                           "store": "off", "seed": 71})
        assert r["ok"], r
        sid = r["session"]
        try:
            a = server.handle({"op": "ask", "session": sid, "n": 4})
            rows = [{"ticket": t["ticket"],
                     "qor": _measure(t["config"]),
                     "epoch": t["epoch"]} for t in a["trials"]]
            req = {"op": "tell_many", "session": sid,
                   "results": rows, "incarn": a["incarn"]}
            r1 = server.handle(req)
            assert r1["ok"] and r1["told"] == len(rows)
            assert r1["duplicates"] == 0 and "errors" not in r1
            r2 = server.handle(dict(req))        # the replay
            assert r2["ok"] and r2["told"] == 0
            assert r2["duplicates"] == len(rows)
            assert "errors" not in r2
            assert r2["version"] == r1["version"]
            fr = server.handle({"op": "batch", "ops": [dict(req)]})
            assert fr["ok"] and fr["failed"] == 0
            assert fr["replies"][0]["duplicates"] == len(rows)
        finally:
            server.handle({"op": "close", "session": sid})

    def test_tell_many_bad_row_stays_element_wise(self, server):
        """One malformed row in a tell_many batch becomes an `errors`
        entry and leaves ITS ticket live for retry; the siblings
        apply — nothing is stranded."""
        recs = records_from_space(_space())
        r = server.handle({"op": "open", "space": recs,
                           "store": "off", "seed": 72})
        sid = r["session"]
        try:
            a = server.handle({"op": "ask", "session": sid, "n": 3})
            t0, t1, t2 = a["trials"]
            out = server.handle({
                "op": "tell_many", "session": sid, "incarn":
                a["incarn"], "results": [
                    {"ticket": t0["ticket"],
                     "qor": _measure(t0["config"]),
                     "epoch": t0["epoch"]},
                    {"ticket": t1["ticket"], "qor": 1.0,
                     "dur": "not-a-float",
                     "epoch": t1["epoch"]},
                    {"ticket": 10 ** 9, "qor": 1.0},
                ]})
            assert out["ok"] and out["told"] == 1
            assert len(out["errors"]) == 2
            assert out["errors"][1]["ticket"] == 10 ** 9
            # the malformed row's ticket is still live: a clean
            # retry applies it
            ok2 = server.handle({
                "op": "tell_many", "session": sid, "incarn":
                a["incarn"], "results": [
                    {"ticket": t1["ticket"],
                     "qor": _measure(t1["config"]),
                     "epoch": t1["epoch"]},
                    {"ticket": t2["ticket"],
                     "qor": _measure(t2["config"]),
                     "epoch": t2["epoch"]}]})
            assert ok2["told"] == 2 and "errors" not in ok2
        finally:
            server.handle({"op": "close", "session": sid})

    def test_downlevel_server_compat_fallback(self, tmp_path):
        """Against a server predating ISSUE 20 (no batch intercept,
        no tell_many op) the client sniffs the unknown-op reply ONCE
        and degrades: frames go sequential, handle.tell_many rides
        the legacy tell+results spelling — same results, more RTTs."""
        srv = SessionServer(host="127.0.0.1", port=0, slots=2,
                            max_sessions=8, store_dir="off")
        real = srv.handle

        def old_handle(req):
            op = req.get("op") if isinstance(req, dict) else None
            if op in ("batch", "tell_many"):
                return {"ok": False,
                        "error": f"unknown op {op!r}; valid: [...]"}
            return real(req)

        srv.handle = old_handle
        srv.start()
        try:
            with connect(("127.0.0.1", srv.port)) as c:
                h = c.open_session(_space(), seed=81, store=False)
                trials = c.ask_many([h], n=3)[0]
                assert len(trials) == 3
                assert c._batch_ok is False      # sniffed + degraded
                r = h.tell_many([(t.ticket, _measure(t.config))
                                 for t in trials])
                assert r["told"] == 3
                assert c._tell_many_ok is False
                # the fallback path keeps working quietly
                trials = c.ask_many([h], n=2)[0]
                r = c.tell_many(
                    [(h, [(t.ticket, _measure(t.config))
                          for t in trials])])[0]
                assert r["told"] == 2
                h.close()
        finally:
            srv.stop()


class TestNoRetrace:
    def test_join_leave_churn_traces_each_program_once(self):
        """Strict trace-guard over a FRESH group's whole lifetime:
        construction warmup, joins, interleaved epochs, leave, slot
        reuse — three programs, each traced exactly once."""
        from uptune_tpu.analysis.trace_guard import TraceGuard
        with TraceGuard(limit=1, strict=True,
                        name="serve-slot-programs") as tg:
            g = SessionGroup(_space(), 2)
            s1 = g.join(seed=1)
            s2 = g.join(seed=2)
            for t in s1.ask(3):
                s1.tell(t.ticket, _measure(t.config))
            for t in s2.ask(3):
                s2.tell(t.ticket, _measure(t.config))
            s1.close()
            s3 = g.join(seed=3)     # slot reuse
            for t in s3.ask(2):
                s3.tell(t.ticket, _measure(t.config))
            s3.close()
            s2.close()
        counts = {k: v for k, v in tg.counts.items() if "Engine" in k}
        assert len(counts) == 3, counts
        assert all(v == 1 for v in counts.values()), counts


class TestBenchSmoke:
    @pytest.mark.slow
    def test_serve_bench_quick_smoke(self, tmp_path):
        """`bench.py --serve --quick` keeps producing its evidence
        JSON: concurrent multiplexed sessions, both sequential
        baselines, and a clean strict retrace report.  Slow-marked for
        suite-budget headroom (ISSUE 10, the ~34 s heaviest tier-1
        item; same rule as the PR 7 `--surrogate --quick` slow-mark):
        the serving plane keeps dense tier-1 coverage above — TCP e2e,
        isolation+parity, memo sharing, strict no-retrace churn — and
        the full bench runs out-of-band like every other BENCH_*
        artifact."""
        env = {**os.environ, "UT_TRACE_GUARD": "strict",
               "JAX_PLATFORMS": "cpu"}
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--serve", "--quick", "--cpu"],
            capture_output=True, text=True, env=env,
            cwd=str(tmp_path), timeout=420)
        assert r.returncode == 0, r.stdout + r.stderr
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["metric"] == "serve_aggregate_asks_per_sec"
        assert out["n_sessions"] >= 64
        assert out["commits"] >= out["n_sessions"]
        assert out["churn"]["opened"] > 0
        assert out["retraces"]["excess"] == {}
        assert out["baseline_cold_sequential"]["agg_asks_per_s"] > 0
        # the batched wire plane A/B (ISSUE 20): schema only — the
        # ratio is recorded, not gated, in --quick (the 2.0x bar is
        # the full run's gate); parity is determinism, so it IS a
        # hard assert here
        bw = out["batched_wire"]
        assert bw["batch_width"] == 8 and bw["bar"] == 2.0
        assert bw["parity_ok"] is True
        assert bw["asks_per_arm"] > 0
        assert bw["ratio_batched_over_sequential"] > 0
        assert bw["sequential_agg_asks_per_s"] > 0
        assert bw["batched_agg_asks_per_s"] > 0
        assert os.path.exists(os.path.join(REPO,
                                           "BENCH_SERVE.quick.json"))
