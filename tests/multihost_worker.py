"""Worker for the REAL multi-process DCN test (run via subprocess by
tests/test_multihost.py::TestTwoProcess).

Each of the 2 processes owns 2 virtual CPU devices; jax.distributed
wires them through the coordination service exactly as real multi-host
TPU pods do over DCN (SURVEY §4: "multi-host tests via JAX multi-process
simulation on CPU").  The worker builds the hybrid ('search','eval')
mesh — eval inside the host, search spanning hosts — runs sharded-engine
steps, and prints the global best it computed so the parent can assert
both processes agree.
"""
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# JAX_PLATFORMS=cpu alone is NOT enough on this machine: the axon
# TPU-tunnel backend factory dials out during backends() init and hangs
# when the tunnel is wedged — drop it like tests/conftest.py does
from uptune_tpu.utils.platform_guard import force_cpu  # noqa: E402

force_cpu(2)


def main() -> int:
    from uptune_tpu.parallel import (initialize, is_coordinator,
                                     make_multihost_mesh)
    cfg = initialize()           # from UT_COORDINATOR / UT_* env
    import jax
    import numpy as np
    from jax.experimental import multihost_utils

    assert jax.process_count() == cfg["num_processes"], (
        jax.process_count(), cfg)
    assert jax.local_device_count() == 2
    n_global = len(jax.devices())
    assert n_global == 2 * cfg["num_processes"]

    mesh = make_multihost_mesh(n_eval_per_host=2)
    assert dict(mesh.shape) == {"search": cfg["num_processes"],
                                "eval": 2}, dict(mesh.shape)

    from uptune_tpu.engine import FusedEngine, default_arms
    from uptune_tpu.parallel.sharded import ShardedEngine
    from uptune_tpu.workloads import rosenbrock_space, sphere_device

    space = rosenbrock_space(4, -3.0, 3.0)
    eng = FusedEngine(space, lambda v, p: sphere_device(v),
                      arms=default_arms(1), history_capacity=1 << 10)
    se = ShardedEngine(eng, mesh)
    state = se.init(jax.random.PRNGKey(0))
    state = se.run(state, 25)
    jax.block_until_ready(state)

    # per-replica bests live sharded across hosts: allgather to every
    # process, then each computes the same global answer
    qors = multihost_utils.process_allgather(state.best.qor, tiled=True)
    qors = np.asarray(qors).reshape(-1)
    gbest = float(qors.min())
    # every replica already holds the exchanged global best (the
    # per-step _exchange collective), so all replica bests must agree
    spread = float(qors.max() - qors.min())
    print(f"UT_MH pid={cfg['process_id']} coord={is_coordinator()} "
          f"replicas={qors.shape[0]} global_best={gbest:.9f} "
          f"spread={spread:.3e}", flush=True)
    assert spread < 1e-6, f"replicas disagree after exchange: {qors}"
    assert gbest < 1.0, f"sharded engine failed to descend: {gbest}"
    return 0


if __name__ == "__main__":
    sys.exit(main())
