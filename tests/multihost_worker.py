"""Worker for the REAL multi-process DCN test (run via subprocess by
tests/test_multihost.py::TestTwoProcess).

Each of the 2 processes owns 2 virtual CPU devices; jax.distributed
wires them through the coordination service exactly as real multi-host
TPU pods do over DCN (SURVEY §4: "multi-host tests via JAX multi-process
simulation on CPU").  The worker builds the hybrid ('search','eval')
mesh — eval inside the host, search spanning hosts — runs sharded-engine
steps, and prints the global best it computed so the parent can assert
both processes agree.
"""
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# JAX_PLATFORMS=cpu alone is NOT enough on this machine: the axon
# TPU-tunnel backend factory dials out during backends() init and hangs
# when the tunnel is wedged — drop it like tests/conftest.py does
from uptune_tpu.utils.platform_guard import force_cpu  # noqa: E402

force_cpu(2)


def main() -> int:
    from uptune_tpu.parallel import (initialize, is_coordinator,
                                     make_multihost_mesh)
    cfg = initialize()           # from UT_COORDINATOR / UT_* env
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    steps = int(os.environ.get("UT_MH_STEPS", "25"))
    ckpt = os.environ.get("UT_MH_CKPT")          # write best here
    resume = os.environ.get("UT_MH_RESUME") == "1"   # ...or restore it
    start_file = os.environ.get("UT_MH_START_FILE")  # liveness beacon

    assert jax.process_count() == cfg["num_processes"], (
        jax.process_count(), cfg)
    assert jax.local_device_count() == 2
    n_global = len(jax.devices())
    assert n_global == 2 * cfg["num_processes"]

    mesh = make_multihost_mesh(n_eval_per_host=2)
    assert dict(mesh.shape) == {"search": cfg["num_processes"],
                                "eval": 2}, dict(mesh.shape)

    from uptune_tpu.engine import FusedEngine, default_arms
    from uptune_tpu.parallel.sharded import ShardedEngine
    from uptune_tpu.workloads import rosenbrock_space, sphere_device

    space = rosenbrock_space(4, -3.0, 3.0)
    eng = FusedEngine(space, lambda v, p: sphere_device(v),
                      arms=default_arms(1), history_capacity=1 << 10)
    se = ShardedEngine(eng, mesh)
    # the seed must be IDENTICAL on every process: ShardedEngine.init
    # builds one global sharded state, and multihost device_put asserts
    # the same global value on each process.  Per-REPLICA divergence
    # (the uneven best distribution the exchange collective must
    # reconcile) comes from the jax.random.split over the search axis
    # inside init() — each of the n_search replicas draws its own key.
    state = se.init(jax.random.PRNGKey(1000))

    restored = None
    if resume and ckpt and os.path.exists(ckpt):
        # pod-preemption recovery, the TPU-native failure model: the job
        # died as a unit (a host was SIGKILLed), restarted, and resumes
        # from the checkpointed global best instead of from scratch
        with open(ckpt) as f:
            saved = json.load(f)
        restored = float(saved["qor"])
        n_search = mesh.shape["search"]
        u = jnp.asarray(saved["u"], jnp.float32)
        best = state.best.__class__(
            jnp.tile(u[None, :], (n_search, 1)),
            state.best.perms,
            jnp.full((n_search,), restored, jnp.float32))
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("search"))
        best = jax.tree.map(lambda x: jax.device_put(x, sharding), best)
        state = state._replace(best=best)

    if start_file:       # tell the parent we are alive and mid-phase
        with open(start_file, "w") as f:
            f.write(str(os.getpid()))

    state = se.run(state, steps)
    jax.block_until_ready(state)

    # per-replica bests live sharded across hosts: allgather to every
    # process, then each computes the same global answer
    qors = multihost_utils.process_allgather(state.best.qor, tiled=True)
    qors = np.asarray(qors).reshape(-1)
    us = np.asarray(multihost_utils.process_allgather(
        state.best.u, tiled=True)).reshape(qors.shape[0], -1)
    gbest = float(qors.min())
    # every replica already holds the exchanged global best (the
    # per-step _exchange collective), so all replica bests must agree
    spread = float(qors.max() - qors.min())
    if ckpt and not resume and is_coordinator():
        tmp = ckpt + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"qor": gbest,
                       "u": us[int(qors.argmin())].tolist()}, f)
        os.replace(tmp, ckpt)
    print(f"UT_MH pid={cfg['process_id']} coord={is_coordinator()} "
          f"replicas={qors.shape[0]} global_best={gbest:.9f} "
          f"spread={spread:.3e} restored="
          f"{'-' if restored is None else f'{restored:.9f}'}", flush=True)
    assert spread < 1e-6, f"replicas disagree after exchange: {qors}"
    assert gbest < 1.0, f"sharded engine failed to descend: {gbest}"
    if restored is not None:
        # resumed search must start from (and never regress past) the
        # checkpointed best
        assert gbest <= restored + 1e-9, (gbest, restored)
    return 0


if __name__ == "__main__":
    sys.exit(main())
