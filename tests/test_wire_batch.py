"""Batched wire plane (ISSUE 20): multi-op frames + the encode fast
path, tested at the kernel seam (serve/wire.py) with a plain
WireServer subclass — no engine, no jax, fast.

What is nailed down here:

* a ``{"op": "batch", "ops": [...]}`` frame dispatches every sub-op
  through the SAME per-op error wall a lone request gets: malformed
  sub-ops come back as error ENTRIES in the ordered reply list, never
  a poisoned frame or connection;
* frame-level validation (non-list / empty ops, the max_batch_ops
  amplification cap, no nesting) fails the FRAME, cleanly;
* the text/dict equivalence contract of WireReply: a preserialized
  ``wire_text`` must decode to exactly the dict the in-process caller
  sees, including after an ``id`` echo splice;
* the ``_on_response`` hook fans out per sub-op (connection-scoped
  ownership tracking must observe every sub-request, never the
  opaque frame);
* over TCP: one frame in, ONE coalesced reply line out, and the
  ``max_line`` cap applies to the frame exactly as to a single
  request (one clean oversize error, then close).
"""
import json
import socket

import pytest

from uptune_tpu.serve.wire import (RequestError, WireReply, WireServer,
                                   encode_reply, _set_id)


class _EchoServer(WireServer):
    WIRE_NAME = "ut-test-batch"

    def _op_ping(self, req):
        return {"t": 1}

    def _op_echo(self, req):
        return {"v": req.get("v")}

    def _op_ctx(self, req):
        return {"ctx_seen": req.get("ctx")}

    def _op_bad(self, req):
        raise RequestError("told you so")

    def _op_boom(self, req):
        raise RuntimeError("kaboom")

    def _op_fast(self, req):
        out = WireReply(ok=True, v=req.get("v"))
        out.wire_text = '{"ok":true,"v":%s}' % json.dumps(req.get("v"))
        return out

    _OPS = {"ping": _op_ping, "echo": _op_echo, "ctx": _op_ctx,
            "bad": _op_bad, "boom": _op_boom, "fast": _op_fast}


class _HookServer(_EchoServer):
    """Records every (op, ok) pair `_on_response` observes."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.seen = []

    def _on_response(self, state, req, resp):
        self.seen.append((req.get("op"), bool(resp.get("ok"))))


@pytest.fixture()
def srv():
    return _EchoServer("127.0.0.1", 0)


# ---------------------------------------------------------------------
class TestBatchDispatch:
    def test_ordered_replies_one_frame(self, srv):
        out = srv.handle({"op": "batch", "ops": [
            {"op": "echo", "v": 1}, {"op": "ping"},
            {"op": "echo", "v": "x"}]})
        assert out["ok"] is True
        assert out["n"] == 3 and out["failed"] == 0
        assert [r.get("v", r.get("t")) for r in out["replies"]] \
            == [1, 1, "x"]
        assert all(r["ok"] for r in out["replies"])

    def test_partial_failure_stays_element_wise(self, srv):
        """One bad sub-op = one error ENTRY; its siblings' results
        survive in order — the frame itself stays ok=True."""
        out = srv.handle({"op": "batch", "ops": [
            {"op": "echo", "v": 1},
            {"op": "nope"},                 # unknown op
            "not a dict",                   # malformed sub-op
            {"op": "bad"},                  # handler RequestError
            {"op": "boom"},                 # handler crash -> wall
            {"op": "echo", "v": 2}]})
        assert out["ok"] is True
        assert out["n"] == 6 and out["failed"] == 4
        r = out["replies"]
        assert r[0] == {"ok": True, "v": 1}
        assert not r[1]["ok"] and "unknown op" in r[1]["error"]
        assert not r[2]["ok"] and "JSON object" in r[2]["error"]
        assert not r[3]["ok"] and r[3]["error"] == "told you so"
        assert not r[4]["ok"] and r[4]["error"].startswith("internal:")
        assert r[5] == {"ok": True, "v": 2}

    def test_frames_do_not_nest(self, srv):
        out = srv.handle({"op": "batch", "ops": [
            {"op": "batch", "ops": [{"op": "ping"}]},
            {"op": "ping"}]})
        assert out["ok"] is True and out["failed"] == 1
        assert "nest" in out["replies"][0]["error"]
        assert out["replies"][1]["ok"]

    def test_frame_level_validation(self, srv):
        for ops in (None, [], "ping", {"op": "ping"}):
            out = srv.handle({"op": "batch", "ops": ops})
            assert out["ok"] is False
            assert "non-empty list" in out["error"]

    def test_amplification_cap(self, srv):
        srv.max_batch_ops = 4
        out = srv.handle(
            {"op": "batch", "ops": [{"op": "ping"}] * 5})
        assert out["ok"] is False and "caps frames at 4" in out["error"]
        # at the cap is fine
        out = srv.handle(
            {"op": "batch", "ops": [{"op": "ping"}] * 4})
        assert out["ok"] is True and out["n"] == 4

    def test_frame_ctx_covers_bare_sub_ops(self, srv):
        """The frame's trace context flows into sub-ops that carry
        none of their own — and never overwrites one they do."""
        out = srv.handle({"op": "batch", "ctx": {"span": "abc"},
                          "ops": [{"op": "ctx"},
                                  {"op": "ctx",
                                   "ctx": {"span": "own"}}]})
        assert out["replies"][0]["ctx_seen"] == {"span": "abc"}
        assert out["replies"][1]["ctx_seen"] == {"span": "own"}

    def test_id_echo_on_frame(self, srv):
        out = srv.handle({"op": "batch", "id": 7,
                          "ops": [{"op": "ping"}]})
        assert out["id"] == 7
        assert json.loads(encode_reply(out))["id"] == 7


# ---------------------------------------------------------------------
class TestEncodeFastPath:
    def test_wire_reply_text_dict_equivalence(self, srv):
        """THE contract: the preserialized text decodes to exactly
        the dict an in-process caller sees."""
        out = srv.handle({"op": "fast", "v": [1, "x", None]})
        assert type(out) is WireReply
        assert json.loads(out.wire_text) == dict(out)
        assert encode_reply(out) is out.wire_text

    def test_set_id_patches_text_and_dict(self):
        r = WireReply(ok=True, v=1)
        r.wire_text = '{"ok":true,"v":1}'
        _set_id(r, "a-b")
        assert r["id"] == "a-b"
        assert json.loads(r.wire_text) == dict(r)

    def test_plain_dict_uses_cached_encoder(self):
        assert json.loads(encode_reply({"ok": True, "v": 2})) \
            == {"ok": True, "v": 2}

    def test_batch_frame_splices_sub_reply_texts(self, srv):
        """The frame's own wire_text is the spliced sub-reply texts —
        decode it and the dict view must agree, fast-path sub-ops
        included."""
        out = srv.handle({"op": "batch", "ops": [
            {"op": "fast", "v": 3}, {"op": "nope"},
            {"op": "echo", "v": {"k": [1.5]}}]})
        assert type(out) is WireReply
        assert json.loads(out.wire_text) == json.loads(
            json.dumps(out))

    def test_handler_wire_reply_survives_id_echo(self, srv):
        out = srv.handle({"op": "fast", "v": 9, "id": 4})
        assert out["id"] == 4 and out["v"] == 9
        assert json.loads(out.wire_text) == dict(out)


# ---------------------------------------------------------------------
class TestHookFanOut:
    def test_on_response_sees_sub_ops_not_the_frame(self):
        s = _HookServer("127.0.0.1", 0)
        s._dispatch(None, {"op": "batch", "ops": [
            {"op": "ping"}, {"op": "nope"}, {"op": "echo", "v": 1}]})
        assert s.seen == [("ping", True), ("nope", False),
                          ("echo", True)]

    def test_single_request_hook_unchanged(self):
        s = _HookServer("127.0.0.1", 0)
        s._dispatch(None, {"op": "ping"})
        assert s.seen == [("ping", True)]

    def test_failed_frame_hook_sees_the_frame(self):
        """A frame that fails validation produced no sub-replies —
        the hook observes the frame itself, exactly once."""
        s = _HookServer("127.0.0.1", 0)
        s._dispatch(None, {"op": "batch", "ops": []})
        assert s.seen == [("batch", False)]


# ---------------------------------------------------------------------
class TestBatchTCP:
    def test_one_frame_one_reply_line(self, srv):
        srv.start()
        try:
            with socket.create_connection(
                    ("127.0.0.1", srv.port), timeout=5) as c:
                f = c.makefile("rb")
                frame = {"op": "batch", "id": 1, "ops": [
                    {"op": "echo", "v": i} for i in range(5)]}
                c.sendall(json.dumps(frame).encode() + b"\n")
                resp = json.loads(f.readline())
                assert resp["ok"] and resp["n"] == 5
                assert resp["id"] == 1
                assert [r["v"] for r in resp["replies"]] \
                    == list(range(5))
                # the connection survives a partial-failure frame
                frame = {"op": "batch", "ops": [
                    {"op": "nope"}, {"op": "ping"}]}
                c.sendall(json.dumps(frame).encode() + b"\n")
                resp = json.loads(f.readline())
                assert resp["ok"] and resp["failed"] == 1
                assert resp["replies"][1]["ok"]
        finally:
            srv.stop()

    def test_oversize_frame_error_then_close(self, srv):
        """max_line bounds the FRAME exactly as a single request:
        one clean oversize error, then close."""
        srv.max_line = 512
        srv.start()
        try:
            with socket.create_connection(
                    ("127.0.0.1", srv.port), timeout=5) as c:
                ops = [{"op": "echo", "v": "x" * 64}
                       for _ in range(32)]
                c.sendall(json.dumps(
                    {"op": "batch", "ops": ops}).encode() + b"\n")
                f = c.makefile("rb")
                resp = json.loads(f.readline())
                assert resp["ok"] is False
                assert "exceeds" in resp["error"]
                assert f.readline() == b""
        finally:
            srv.stop()
