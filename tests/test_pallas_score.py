"""Pallas fused GP-scoring kernel tests (interpret mode on the CPU
mesh; the compiled path runs on real TPU where it measured 32ms vs
XLA's 37ms for 1M candidates x 1024 history rows without the 4GB
cross-kernel intermediate)."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from uptune_tpu.surrogate import gp  # noqa: E402
from uptune_tpu.surrogate.pallas_score import TILE, gp_mean_scores  # noqa: E402


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(96, 12), jnp.float32)
    y = jnp.asarray((np.sin(3 * rng.rand(96)) + 0.1 * rng.randn(96)),
                    jnp.float32)
    return gp.fit(x, y, 0.4, 1e-2)


class TestFusedMeanScores:
    def test_matches_xla_predict(self, fitted):
        rng = np.random.RandomState(1)
        xq = jnp.asarray(rng.rand(TILE, 12), jnp.float32)
        mu_ref, _ = gp.predict(fitted, xq)
        mu = gp_mean_scores(fitted, xq, interpret=True)
        np.testing.assert_allclose(np.asarray(mu), np.asarray(mu_ref),
                                   rtol=1e-4, atol=1e-5)

    def test_ragged_batch_padding(self, fitted):
        """B not a multiple of the tile: padded rows must not leak."""
        rng = np.random.RandomState(2)
        xq = jnp.asarray(rng.rand(37, 12), jnp.float32)
        mu_ref, _ = gp.predict(fitted, xq)
        mu = gp_mean_scores(fitted, xq, interpret=True)
        assert mu.shape == (37,)
        np.testing.assert_allclose(np.asarray(mu), np.asarray(mu_ref),
                                   rtol=1e-4, atol=1e-5)

    def test_masked_state(self):
        """A bucket-padded GPState (masked rows) scores identically to
        the unpadded fit."""
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.rand(40, 6), jnp.float32)
        y = jnp.asarray(rng.randn(40), jnp.float32)
        xq = jnp.asarray(rng.rand(16, 6), jnp.float32)
        s0 = gp.fit(x, y, 0.5, 1e-2)
        xp = jnp.concatenate([x, jnp.zeros((24, 6))])
        yp = jnp.concatenate([y, jnp.zeros(24)])
        mask = jnp.concatenate([jnp.ones(40), jnp.zeros(24)])
        s1 = gp.fit(xp, yp, 0.5, 1e-2, mask)
        m0 = gp_mean_scores(s0, xq, interpret=True)
        m1 = gp_mean_scores(s1, xq, interpret=True)
        np.testing.assert_allclose(np.asarray(m0), np.asarray(m1),
                                   rtol=1e-4, atol=1e-5)


class TestMixedKernelScores:
    def test_mixed_matches_xla_predict(self):
        """Mixed continuous×categorical state: the two-block pallas
        kernel must reproduce gp.predict with the n_cont/n_cat split
        (r4 review: scoring a mixed state through the pure-Matérn path
        silently drops ls_cat)."""
        rng = np.random.RandomState(3)
        n_cont, n_cat, K = 5, 4, 3
        f = n_cont + n_cat * K
        codes = rng.randint(K, size=(96, n_cat))
        oh = np.zeros((96, n_cat, K), np.float32)
        np.put_along_axis(oh, codes[:, :, None], 1.0, axis=2)
        x = np.concatenate(
            [rng.rand(96, n_cont).astype(np.float32),
             oh.reshape(96, -1) / np.sqrt(2)], axis=1)
        y = (x[:, 0] * 2 + 3.0 * (codes[:, 1] == 0)
             + 0.1 * rng.randn(96)).astype(np.float32)
        st = gp.fit(jnp.asarray(x), jnp.asarray(y), 0.4, 1e-2,
                    n_cont=n_cont, n_cat=n_cat, ls_cat=0.2)
        xq = jnp.asarray(x[:64])
        mu_ref, _ = gp.predict(st, xq, n_cont, n_cat)
        mu = gp_mean_scores(st, xq, interpret=True,
                            n_cont=n_cont, n_cat=n_cat)
        np.testing.assert_allclose(np.asarray(mu), np.asarray(mu_ref),
                                   rtol=1e-4, atol=1e-5)
        # and the pure path would NOT have matched (the split matters)
        mu_wrong = gp_mean_scores(st, xq, interpret=True)
        assert not np.allclose(np.asarray(mu_wrong), np.asarray(mu_ref),
                               rtol=1e-3)

    def test_all_categorical_space(self):
        """n_cont == 0 (pure flag space): the pure exponential-Hamming
        kernel path must match gp.predict — a zero-width continuous
        BlockSpec would not lower on TPU (r4 review)."""
        rng = np.random.RandomState(4)
        n_cat, K = 6, 3
        codes = rng.randint(K, size=(80, n_cat))
        oh = np.zeros((80, n_cat, K), np.float32)
        np.put_along_axis(oh, codes[:, :, None], 1.0, axis=2)
        x = oh.reshape(80, -1) / np.sqrt(2)
        y = (3.0 * (codes[:, 0] == 1) - 2.0 * (codes[:, 3] == 2)
             + 0.05 * rng.randn(80)).astype(np.float32)
        st = gp.fit(jnp.asarray(x), jnp.asarray(y), 0.4, 1e-2,
                    n_cont=0, n_cat=n_cat, ls_cat=0.3)
        xq = jnp.asarray(x[:48])
        mu_ref, _ = gp.predict(st, xq, 0, n_cat)
        mu = gp_mean_scores(st, xq, interpret=True, n_cont=0,
                            n_cat=n_cat)
        np.testing.assert_allclose(np.asarray(mu), np.asarray(mu_ref),
                                   rtol=1e-4, atol=1e-5)


from uptune_tpu.surrogate.pallas_score import (  # noqa: E402
    PALLAS_MIN_POOL, VTILE, gp_mean_var_scores)


def _mixed_data(rng, n, n_cont, n_cat, K):
    codes = rng.randint(K, size=(n, n_cat))
    oh = np.zeros((n, n_cat, K), np.float32)
    np.put_along_axis(oh, codes[:, :, None], 1.0, axis=2)
    x = np.concatenate([rng.rand(n, n_cont).astype(np.float32),
                        oh.reshape(n, -1) / np.sqrt(2)], axis=1)
    y = (x[:, 0] * 2 + 3.0 * (codes[:, 1] == 0)
         + 0.1 * rng.randn(n)).astype(np.float32)
    return x, y


class TestMeanVarScores:
    """The fused mean+VARIANCE path (K^-1 quadratic-form tiling): EI
    and LCB become exact in the Pallas regime, not just the mean."""

    def _check(self, st, xq, n_cont=None, n_cat=0):
        mu_ref, sd_ref = gp.predict(st, xq, n_cont, n_cat)
        mu, sd = gp_mean_var_scores(st, xq, interpret=True,
                                    n_cont=n_cont, n_cat=n_cat)
        np.testing.assert_allclose(np.asarray(mu), np.asarray(mu_ref),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(sd), np.asarray(sd_ref),
                                   rtol=1e-3, atol=1e-5)

    def test_matches_xla_predict(self, fitted):
        rng = np.random.RandomState(11)
        self._check(fitted, jnp.asarray(rng.rand(VTILE, 12), jnp.float32))

    def test_ragged_batch(self, fitted):
        rng = np.random.RandomState(12)
        self._check(fitted, jnp.asarray(rng.rand(53, 12), jnp.float32))

    def test_masked_state_matches_unpadded(self):
        """The premasked K^-1 must make padded training rows inert in
        BOTH moments (block-diagonal argument, module docstring)."""
        rng = np.random.RandomState(13)
        x = jnp.asarray(rng.rand(40, 6), jnp.float32)
        y = jnp.asarray(rng.randn(40), jnp.float32)
        xq = jnp.asarray(rng.rand(16, 6), jnp.float32)
        s0 = gp.fit(x, y, 0.5, 1e-2)
        xp = jnp.concatenate([x, jnp.zeros((24, 6))])
        yp = jnp.concatenate([y, jnp.zeros(24)])
        mask = jnp.concatenate([jnp.ones(40), jnp.zeros(24)])
        s1 = gp.fit(xp, yp, 0.5, 1e-2, mask)
        m0, v0 = gp_mean_var_scores(s0, xq, interpret=True)
        m1, v1 = gp_mean_var_scores(s1, xq, interpret=True)
        np.testing.assert_allclose(np.asarray(m0), np.asarray(m1),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(v0), np.asarray(v1),
                                   rtol=1e-3, atol=1e-5)

    def test_mixed_kernel(self):
        rng = np.random.RandomState(14)
        x, y = _mixed_data(rng, 96, 5, 4, 3)
        st = gp.fit(jnp.asarray(x), jnp.asarray(y), 0.4, 1e-2,
                    n_cont=5, n_cat=4, ls_cat=0.2)
        self._check(st, jnp.asarray(x[:64]), n_cont=5, n_cat=4)

    def test_all_categorical(self):
        rng = np.random.RandomState(15)
        x, y = _mixed_data(rng, 80, 0, 6, 3)
        st = gp.fit(jnp.asarray(x), jnp.asarray(y), 0.4, 1e-2,
                    n_cont=0, n_cat=6, ls_cat=0.3)
        self._check(st, jnp.asarray(x[:48]), n_cont=0, n_cat=6)


class TestManagerPallasRegime:
    """r4 verdict next-step #2 'done' bar: via the PUBLIC manager API,
    on a >= 4096-candidate pool, the Pallas-scored top-k equals the
    plain-XLA top-k."""

    def test_pool_topk_matches_xla(self, monkeypatch):
        import uptune_tpu.surrogate.pallas_score as ps
        from uptune_tpu.surrogate import SurrogateManager
        from uptune_tpu.workloads import (rosenbrock_device,
                                          rosenbrock_space)

        space = rosenbrock_space(4, -2.0, 2.0)

        def fitted_manager():
            # propose_batch 64 x pool_mult 64 = 4096-candidate pool
            m = SurrogateManager(space, "gp", min_points=48,
                                 propose_batch=64, pool_mult=64,
                                 score="ei", seed=3)
            cands = space.random(jax.random.PRNGKey(3), 64)
            qor = np.asarray(
                rosenbrock_device(space.decode_scalars(cands.u)))
            m.observe(np.asarray(space.features(cands)), qor)
            assert m.maybe_refit()
            return m, float(qor.min()), cands

        m_pl, best, cands = fitted_manager()
        assert 64 * m_pl.pool_mult >= PALLAS_MIN_POOL
        out_pl = m_pl.propose_pool(jax.random.PRNGKey(7), cands.u[0],
                                   (), best)
        # identical manager, Pallas regime disabled
        monkeypatch.setattr(ps, "PALLAS_MIN_POOL", 1 << 30)
        m_xla, best2, cands2 = fitted_manager()
        assert best2 == best
        out_xla = m_xla.propose_pool(jax.random.PRNGKey(7),
                                     cands2.u[0], (), best)
        np.testing.assert_allclose(np.asarray(out_pl.u),
                                   np.asarray(out_xla.u),
                                   rtol=1e-6, atol=1e-6)


class TestShardedPallasRegime:
    @pytest.mark.slow
    def test_sharded_pallas_matches_xla(self):
        """parallel/surrogate_shard.py: forcing the per-shard Pallas
        path must reproduce the XLA scores for mean/ei/lcb.  Slow-
        marked (~14s; ISSUE 5 tier-1 headroom): the sharded×Pallas
        cross product — its two axes stay tier-1 separately via
        TestManagerPallasRegime (Pallas vs XLA) and
        test_surrogate_shard's sharded-vs-dense equalities."""
        from uptune_tpu.parallel import make_mesh
        from uptune_tpu.parallel.surrogate_shard import sharded_gp_score

        rng = np.random.RandomState(21)
        x = jnp.asarray(rng.rand(64, 8), jnp.float32)
        y = jnp.asarray(rng.randn(64), jnp.float32)
        st = gp.fit(x, y, 0.4, 1e-2)
        pool = jnp.asarray(rng.rand(128, 8), jnp.float32)
        mesh = make_mesh(n_search=1, n_eval=8)
        for kind in ("mean", "ei", "lcb"):
            a = sharded_gp_score(mesh, "eval", st, pool, kind=kind,
                                 best_y=0.0, use_pallas=False)
            b = sharded_gp_score(mesh, "eval", st, pool, kind=kind,
                                 best_y=0.0, use_pallas=True)
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-5)
