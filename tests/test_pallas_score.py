"""Pallas fused GP-scoring kernel tests (interpret mode on the CPU
mesh; the compiled path runs on real TPU where it measured 32ms vs
XLA's 37ms for 1M candidates x 1024 history rows without the 4GB
cross-kernel intermediate)."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from uptune_tpu.surrogate import gp  # noqa: E402
from uptune_tpu.surrogate.pallas_score import TILE, gp_mean_scores  # noqa: E402


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(96, 12), jnp.float32)
    y = jnp.asarray((np.sin(3 * rng.rand(96)) + 0.1 * rng.randn(96)),
                    jnp.float32)
    return gp.fit(x, y, 0.4, 1e-2)


class TestFusedMeanScores:
    def test_matches_xla_predict(self, fitted):
        rng = np.random.RandomState(1)
        xq = jnp.asarray(rng.rand(TILE, 12), jnp.float32)
        mu_ref, _ = gp.predict(fitted, xq)
        mu = gp_mean_scores(fitted, xq, interpret=True)
        np.testing.assert_allclose(np.asarray(mu), np.asarray(mu_ref),
                                   rtol=1e-4, atol=1e-5)

    def test_ragged_batch_padding(self, fitted):
        """B not a multiple of the tile: padded rows must not leak."""
        rng = np.random.RandomState(2)
        xq = jnp.asarray(rng.rand(37, 12), jnp.float32)
        mu_ref, _ = gp.predict(fitted, xq)
        mu = gp_mean_scores(fitted, xq, interpret=True)
        assert mu.shape == (37,)
        np.testing.assert_allclose(np.asarray(mu), np.asarray(mu_ref),
                                   rtol=1e-4, atol=1e-5)

    def test_masked_state(self):
        """A bucket-padded GPState (masked rows) scores identically to
        the unpadded fit."""
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.rand(40, 6), jnp.float32)
        y = jnp.asarray(rng.randn(40), jnp.float32)
        xq = jnp.asarray(rng.rand(16, 6), jnp.float32)
        s0 = gp.fit(x, y, 0.5, 1e-2)
        xp = jnp.concatenate([x, jnp.zeros((24, 6))])
        yp = jnp.concatenate([y, jnp.zeros(24)])
        mask = jnp.concatenate([jnp.ones(40), jnp.zeros(24)])
        s1 = gp.fit(xp, yp, 0.5, 1e-2, mask)
        m0 = gp_mean_scores(s0, xq, interpret=True)
        m1 = gp_mean_scores(s1, xq, interpret=True)
        np.testing.assert_allclose(np.asarray(m0), np.asarray(m1),
                                   rtol=1e-4, atol=1e-5)
