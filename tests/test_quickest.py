"""QuickEst estimator tests (the reference pipeline, /root/reference/
python/uptune/quickest/, had no automated tests — train/test were CLI
entry points over private CSV data)."""
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from uptune_tpu.quickest import QuickEst, load_csv, preprocess  # noqa: E402
from uptune_tpu.quickest import predict as q_predict  # noqa: E402
from uptune_tpu.quickest import test as q_test  # noqa: E402
from uptune_tpu.quickest import train as q_train  # noqa: E402
from uptune_tpu.quickest.pipeline import (_lasso_fit, apply_preprocess,  # noqa: E402
                                          r2_score, rae)


def _dataset(seed=0, n=400, f=40):
    """Sparse nonlinear multi-target surface: only 8 features matter."""
    rng = np.random.RandomState(seed)
    x = rng.rand(n, f).astype(np.float32)
    lut = (3.0 * x[:, 0] + 2.0 * x[:, 1] * x[:, 2] - x[:, 3]
           + 0.5 * np.sin(3 * x[:, 4]) + 0.05 * rng.randn(n))
    ff = (1.5 * x[:, 5] + x[:, 6] ** 2 + 0.4 * x[:, 7]
          + 0.05 * rng.randn(n))
    return x, np.stack([lut, ff], 1).astype(np.float32)


class TestPreprocess:
    def test_impute_and_drop(self):
        x = np.asarray([[1.0, np.nan, 5.0],
                        [2.0, np.nan, 5.0],
                        [3.0, np.nan, 5.0]], np.float32)
        out, meta = preprocess(x)
        # col1 imputed to its (empty->0) median then dropped as constant,
        # col2 constant -> dropped
        assert out.shape == (3, 1)
        assert meta["kept"] == [0]
        x2 = apply_preprocess(
            np.asarray([[9.0, 1.0, 2.0]], np.float32), meta)
        assert x2.shape == (1, 1) and x2[0, 0] == 9.0


class TestLasso:
    def test_sparse_recovery(self):
        import jax.numpy as jnp
        rng = np.random.RandomState(0)
        x = rng.randn(300, 20).astype(np.float32)
        y = 2.0 * x[:, 3] - 1.0 * x[:, 7] + 0.02 * rng.randn(300)
        w, b = _lasso_fit(jnp.asarray(x), jnp.asarray(y), lam=0.05)
        w = np.asarray(w)
        top = set(np.argsort(-np.abs(w))[:2].tolist())
        assert top == {3, 7}
        # most other coefficients shrunk to (near) zero
        rest = np.delete(np.abs(w), [3, 7])
        assert (rest < 0.05).mean() > 0.9


class TestQuickEst:
    @pytest.fixture(scope="class")
    def fitted(self):
        x, y = _dataset()
        return QuickEst().fit(x, y, ["LUT_impl", "FF_impl"]), _dataset(1)

    def test_accuracy(self, fitted):
        est, (xt, yt) = fitted
        scores = est.score(xt, yt, ["LUT_impl", "FF_impl"])
        assert scores["LUT_impl"]["r2"] > 0.85, scores
        assert scores["FF_impl"]["r2"] > 0.85, scores
        assert scores["LUT_impl"]["rae"] < 0.35, scores

    def test_feature_selection_found_signal(self, fitted):
        est, _ = fitted
        sel = set(est.models["LUT_impl"].sel.tolist())
        assert {0, 1, 3}.issubset(sel)   # strongest LUT drivers

    def test_predict_single_row(self, fitted):
        est, (xt, yt) = fitted
        p = est.predict(xt[0], "LUT_impl")
        assert p.shape == (1,)
        assert abs(float(p[0]) - yt[0, 0]) < 1.5

    def test_unknown_target(self, fitted):
        est, _ = fitted
        with pytest.raises(KeyError):
            est.predict(np.zeros(40), "BRAM_impl")

    def test_save_load_round_trip(self, fitted, tmp_path):
        est, (xt, _) = fitted
        d = str(tmp_path / "models")
        est.save(d)
        est2 = QuickEst.load(d)
        np.testing.assert_allclose(
            est.predict(xt[:16], "FF_impl"),
            est2.predict(xt[:16], "FF_impl"), rtol=1e-5)


class TestModuleFacade:
    def test_train_test_predict(self, tmp_path):
        x, y = _dataset()
        xt, yt = _dataset(2)
        d = str(tmp_path / "db")
        q_train(x, y[:, 0], ["LUT_impl"], save_dir=d, mlp_steps=200)
        scores = q_test(xt, yt[:, 0], ["LUT_impl"], model_dir=d)
        assert scores["LUT_impl"]["r2"] > 0.8
        p = q_predict(xt[:4], "LUT_impl", model_dir=d)
        assert p.shape == (4,)


class TestRegressions:
    def test_tiny_dataset_clear_error(self):
        x, y = _dataset(n=8)
        with pytest.raises(ValueError, match="16 training rows"):
            QuickEst().fit(x, y[:, 0], ["T"])

    def test_seed_option_accepted(self):
        x, y = _dataset(n=60)
        est = QuickEst(seed=3, mlp_steps=50).fit(x, y, ["A", "B"])
        assert est.models["A"].seed == 3 and est.models["B"].seed == 4

    def test_blank_csv_lines(self, tmp_path):
        p = tmp_path / "b.csv"
        p.write_text("f0,LUT_impl\n1,10\n\n2,20\n")
        x, y, _, _ = load_csv(str(p), ["LUT_impl"])
        assert x.shape == (2, 1)


class TestCSV:
    def test_load_csv(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("f0,f1,LUT_impl\n1,2,10\n3,x,30\n")
        x, y, fn, tn = load_csv(str(p), ["LUT_impl"])
        assert fn == ["f0", "f1"] and tn == ["LUT_impl"]
        assert x.shape == (2, 2) and np.isnan(x[1, 1])
        np.testing.assert_array_equal(y[:, 0], [10.0, 30.0])

    def test_metrics(self):
        y = np.asarray([1.0, 2.0, 3.0])
        assert r2_score(y, y) == pytest.approx(1.0)
        assert rae(y, y) == pytest.approx(0.0)
