"""QuickEst estimator tests (the reference pipeline, /root/reference/
python/uptune/quickest/, had no automated tests — train/test were CLI
entry points over private CSV data)."""
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from uptune_tpu.quickest import QuickEst, load_csv, preprocess  # noqa: E402
from uptune_tpu.quickest import predict as q_predict  # noqa: E402
from uptune_tpu.quickest import test as q_test  # noqa: E402
from uptune_tpu.quickest import train as q_train  # noqa: E402
from uptune_tpu.quickest.pipeline import (_lasso_fit, apply_preprocess,  # noqa: E402
                                          r2_score, rae)


def _dataset(seed=0, n=400, f=40):
    """Sparse nonlinear multi-target surface: only 8 features matter."""
    rng = np.random.RandomState(seed)
    x = rng.rand(n, f).astype(np.float32)
    lut = (3.0 * x[:, 0] + 2.0 * x[:, 1] * x[:, 2] - x[:, 3]
           + 0.5 * np.sin(3 * x[:, 4]) + 0.05 * rng.randn(n))
    ff = (1.5 * x[:, 5] + x[:, 6] ** 2 + 0.4 * x[:, 7]
          + 0.05 * rng.randn(n))
    return x, np.stack([lut, ff], 1).astype(np.float32)


class TestPreprocess:
    def test_impute_and_drop(self):
        x = np.asarray([[1.0, np.nan, 5.0],
                        [2.0, np.nan, 5.0],
                        [3.0, np.nan, 5.0]], np.float32)
        out, meta = preprocess(x)
        # col1 imputed to its (empty->0) median then dropped as constant,
        # col2 constant -> dropped
        assert out.shape == (3, 1)
        assert meta["kept"] == [0]
        x2 = apply_preprocess(
            np.asarray([[9.0, 1.0, 2.0]], np.float32), meta)
        assert x2.shape == (1, 1) and x2[0, 0] == 9.0


class TestLasso:
    def test_sparse_recovery(self):
        import jax.numpy as jnp
        rng = np.random.RandomState(0)
        x = rng.randn(300, 20).astype(np.float32)
        y = 2.0 * x[:, 3] - 1.0 * x[:, 7] + 0.02 * rng.randn(300)
        w, b = _lasso_fit(jnp.asarray(x), jnp.asarray(y), lam=0.05)
        w = np.asarray(w)
        top = set(np.argsort(-np.abs(w))[:2].tolist())
        assert top == {3, 7}
        # most other coefficients shrunk to (near) zero
        rest = np.delete(np.abs(w), [3, 7])
        assert (rest < 0.05).mean() > 0.9


class TestQuickEst:
    @pytest.fixture(scope="class")
    def fitted(self):
        x, y = _dataset()
        return QuickEst().fit(x, y, ["LUT_impl", "FF_impl"]), _dataset(1)

    def test_accuracy(self, fitted):
        est, (xt, yt) = fitted
        scores = est.score(xt, yt, ["LUT_impl", "FF_impl"])
        assert scores["LUT_impl"]["r2"] > 0.85, scores
        assert scores["FF_impl"]["r2"] > 0.85, scores
        assert scores["LUT_impl"]["rae"] < 0.35, scores

    def test_feature_selection_found_signal(self, fitted):
        est, _ = fitted
        sel = set(est.models["LUT_impl"].sel.tolist())
        assert {0, 1, 3}.issubset(sel)   # strongest LUT drivers

    def test_predict_single_row(self, fitted):
        est, (xt, yt) = fitted
        p = est.predict(xt[0], "LUT_impl")
        assert p.shape == (1,)
        assert abs(float(p[0]) - yt[0, 0]) < 1.5

    def test_unknown_target(self, fitted):
        est, _ = fitted
        with pytest.raises(KeyError):
            est.predict(np.zeros(40), "BRAM_impl")

    def test_save_load_round_trip(self, fitted, tmp_path):
        est, (xt, _) = fitted
        d = str(tmp_path / "models")
        est.save(d)
        est2 = QuickEst.load(d)
        np.testing.assert_allclose(
            est.predict(xt[:16], "FF_impl"),
            est2.predict(xt[:16], "FF_impl"), rtol=1e-5)


class TestModuleFacade:
    def test_train_test_predict(self, tmp_path):
        x, y = _dataset()
        xt, yt = _dataset(2)
        d = str(tmp_path / "db")
        q_train(x, y[:, 0], ["LUT_impl"], save_dir=d, mlp_steps=200)
        scores = q_test(xt, yt[:, 0], ["LUT_impl"], model_dir=d)
        assert scores["LUT_impl"]["r2"] > 0.8
        p = q_predict(xt[:4], "LUT_impl", model_dir=d)
        assert p.shape == (4,)


class TestRegressions:
    def test_tiny_dataset_clear_error(self):
        x, y = _dataset(n=8)
        with pytest.raises(ValueError, match="16 training rows"):
            QuickEst().fit(x, y[:, 0], ["T"])

    @pytest.mark.slow   # suite-budget (ISSUE 8): seed plumbing only,
    # but pays two full fits; fit behavior stays tier-1 in TestQuickEst
    def test_seed_option_accepted(self):
        x, y = _dataset(n=60)
        est = QuickEst(seed=3, mlp_steps=50).fit(x, y, ["A", "B"])
        assert est.models["A"].seed == 3 and est.models["B"].seed == 4

    def test_blank_csv_lines(self, tmp_path):
        p = tmp_path / "b.csv"
        p.write_text("f0,LUT_impl\n1,10\n\n2,20\n")
        x, y, _, _ = load_csv(str(p), ["LUT_impl"])
        assert x.shape == (2, 1)


class TestAnalyze:
    """The analysis stage (reference quickest/analyze.py:149-498)."""

    @pytest.fixture(scope="class")
    def fitted(self):
        x, y = _dataset()
        names = [f"feat{i}" for i in range(x.shape[1])]
        est = QuickEst(mlp_steps=150).fit(
            x, y, ["LUT_impl", "FF_impl"], feature_names=names)
        return est, _dataset(1)

    def test_scores_table(self, fitted, tmp_path):
        from uptune_tpu.quickest import scores
        est, (xt, yt) = fitted
        out = scores(est, xt, yt, ["LUT_impl", "FF_impl"],
                     save_dir=str(tmp_path))
        assert out["LUT_impl"]["R2"] > 0.8
        assert 0.0 <= out["LUT_impl"]["RRSE"] < 0.6
        assert (tmp_path / "scores.csv").exists()

    def test_feature_importance_finds_signal(self, fitted, tmp_path):
        from uptune_tpu.quickest import feature_importance
        est, _ = fitted
        imp = feature_importance(est, save_dir=str(tmp_path))
        lut = imp["LUT_impl"]
        ranked = [f for f in lut if f != "__selected__"]
        # feat0 (weight 3.0) must rank above every noise feature
        assert ranked.index("feat0") < 5
        assert "feat0" in lut["__selected__"]
        assert (tmp_path / "feature_importance.csv").exists()

    @pytest.mark.slow   # suite-budget (ISSUE 8): statistical trend on
    # repeated fits; model quality stays tier-1 via TestQuickEst::
    # test_accuracy and TestAnalyze's scores/feature-importance cases
    def test_learning_curve_improves_with_data(self, tmp_path):
        from uptune_tpu.quickest import learning_curve
        x, y = _dataset(n=160)
        xt, yt = _dataset(1, n=80)
        out = learning_curve(x, y[:, 0], xt, yt[:, 0], ["LUT_impl"],
                             points=2, mlp_steps=100,
                             save_dir=str(tmp_path))
        d = out["LUT_impl"]
        assert len(d["nums"]) == 2 and d["nums"][-1] == 160
        # more data must not make the held-out fit dramatically worse,
        # and the full-data model must genuinely fit (RRSE < 0.7)
        assert d["test"][-1] < max(d["test"][0] * 1.5, 0.7)
        assert (tmp_path / "learning_curve.csv").exists()

    def test_hls_scores_direct_baseline(self):
        from uptune_tpu.quickest import hls_scores
        rng = np.random.RandomState(0)
        early = rng.rand(50, 2).astype(np.float32) * 100
        impl = np.stack([early[:, 0] * 1.1 + 3,
                         rng.rand(50) * 100], 1).astype(np.float32)
        out = hls_scores(early, impl,
                         [("Registers", "Registers_used"),
                          ("DSP", "Registers_used")],
                         ["Registers", "DSP"],
                         ["Registers_used", "DSP_used"])
        # keyed by (feature, target): two early features scored against
        # the same target both survive (ADVICE r3)
        assert out[("Registers", "Registers_used")]["R2"] > 0.9
        assert ("DSP", "Registers_used") in out

    def test_analyze_dispatch(self, fitted):
        import uptune_tpu as ut
        est, (xt, yt) = fitted
        out = ut.analyze("sc", est=est, x=xt, y=yt,
                         target_names=["LUT_impl", "FF_impl"])
        assert "LUT_impl" in out
        with pytest.raises(ValueError, match="unknown analysis"):
            ut.analyze("nope")


class TestExtract:
    """LegUp-shaped HLS report scraping (funcs.py:270-447)."""

    @staticmethod
    def _make_tree(root, design="fir", cp=10, with_fit=True):
        d = root / design / f"{design}CP_{cp}"
        d.mkdir(parents=True)
        (d / "scheduling.legup.rpt").write_text(
            "Some header\nClock period constraint: 10.00ns\n")
        (d / "resources.legup.rpt").write_text(
            "Logic Elements: 1200\n"
            "Combinational: 800\n"
            "Registers: 450\n"
            "DSP Elements: 6\n"
            'Operation "signed_add_32" x 14\n'
            'Operation "signed_multiply_32" x 3\n')
        (d / "timingReport.legup.rpt").write_text(
            "-----------------Delay of path:5.10 ns-----\n"
            "-----------------Delay of path:7.90 ns-----\n"
            "-----------------Delay of path:6.00 ns-----\n")
        (d / "top.v").write_text(
            "// Number of RAM elements: 4\nmodule top(); endmodule\n")
        if with_fit:
            (d / "top.fit.rpt").write_text(
                "; Total registers : ; 512 ;\n"
                "; Total block memory bits ; 2,048 / 4,096 ;\n"
                "; Total RAM Blocks ; 2 / 8 ;\n"
                "; Total DSP Blocks ; 6 / 112 ;\n"
                "; Combinational ALUT usage for logic ; 900 ;\n"
                "; Combinational ALUT usage for route-throughs ; 30 ;\n"
                "; Memory ALUT usage ; 12 ;\n")
        return d

    def test_scrape_and_extract(self, tmp_path):
        from uptune_tpu.quickest import extract as q_extract
        from uptune_tpu.quickest.hlsreport import TARGETS
        self._make_tree(tmp_path, "fir", 10)
        self._make_tree(tmp_path, "matmul", 20)
        out = tmp_path / "feats.csv"
        n = q_extract([str(tmp_path / "fir"), str(tmp_path / "matmul")],
                      str(out))
        assert n == 2
        x, y, fn, tn = load_csv(str(out), TARGETS)
        assert tn == TARGETS
        # early features present with the scraped values
        row = dict(zip(fn, x[0]))
        assert row["Registers"] == 450
        assert row["Clock Period"] == pytest.approx(10.0)
        assert row["Delay_of_path_max"] == pytest.approx(7.9)
        assert row["Delay_of_path_med"] == pytest.approx(6.0)
        assert row["RAM Elements"] == 4
        assert row["signed_add_32"] == 14
        # targets scraped from the fit report (ALUT = 900+30+12)
        ty = dict(zip(tn, y[0]))
        assert ty["Registers_used"] == 512
        assert ty["ALUT_used"] == 942
        assert ty["Block_memory_bits_used"] == 2048

    def test_rows_without_fit_report_skipped(self, tmp_path):
        from uptune_tpu.quickest import extract as q_extract
        self._make_tree(tmp_path, "a", 1, with_fit=True)
        self._make_tree(tmp_path, "b", 2, with_fit=False)
        out = tmp_path / "feats.csv"
        n = q_extract([str(tmp_path / "a"), str(tmp_path / "b")],
                      str(out))
        assert n == 1  # funcs.py:438-439 skips unimplemented rows
        n2 = q_extract([str(tmp_path / "a"), str(tmp_path / "b")],
                       str(out), require_targets=False)
        assert n2 == 2

    def test_discover_operations(self, tmp_path):
        from uptune_tpu.quickest import discover_operations
        self._make_tree(tmp_path, "fir", 3)
        ops = discover_operations([str(tmp_path / "fir")])
        assert ops == ["signed_add_32", "signed_multiply_32"]

    def test_extract_to_train_round_trip(self, tmp_path):
        """End-to-end: report tree -> CSV -> ut.train -> predict."""
        from uptune_tpu.quickest import extract as q_extract
        from uptune_tpu.quickest.hlsreport import TARGETS
        rng = np.random.RandomState(0)
        dirs = []
        for i in range(24):
            regs = int(rng.randint(100, 2000))
            d = tmp_path / f"d{i}" / f"d{i}CP_{i}"
            d.mkdir(parents=True)
            (d / "resources.legup.rpt").write_text(
                f"Registers: {regs}\nLogic Elements: {regs * 3}\n")
            # implementation register count tracks the HLS estimate
            (d / "top.fit.rpt").write_text(
                f"; Total registers : ; {int(regs * 1.2) + 7} ;\n"
                "; Total DSP Blocks ; 1 / 112 ;\n")
            dirs.append(str(tmp_path / f"d{i}"))
        out = tmp_path / "f.csv"
        two = ["Registers_used", "DSP_blocks_used"]
        assert q_extract(dirs, str(out), targets=two) == 24
        x, y, fn, tn = load_csv(str(out), two)
        # drop the non-numeric path column via preprocess-side NaN impute
        est = QuickEst(mlp_steps=100, top_k=4).fit(
            x, y[:, tn.index("Registers_used")], ["Registers_used"],
            feature_names=fn)
        pred = est.predict(x[:5], "Registers_used")
        assert np.abs(pred - y[:5, tn.index("Registers_used")]).mean() \
            < 250


class TestCSV:
    def test_load_csv(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("f0,f1,LUT_impl\n1,2,10\n3,x,30\n")
        x, y, fn, tn = load_csv(str(p), ["LUT_impl"])
        assert fn == ["f0", "f1"] and tn == ["LUT_impl"]
        assert x.shape == (2, 2) and np.isnan(x[1, 1])
        np.testing.assert_array_equal(y[:, 0], [10.0, 30.0])

    def test_metrics(self):
        y = np.asarray([1.0, 2.0, 3.0])
        assert r2_score(y, y) == pytest.approx(1.0)
        assert rae(y, y) == pytest.approx(0.0)
