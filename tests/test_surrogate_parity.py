"""Surrogate ranking-quality parity vs a gradient-boosted-tree oracle
(SURVEY §7.5 bar: the JAX surrogate must match the reference's
XGBoost-300-tree ranking quality on 94-feature EDA-style data,
/root/reference/python/uptune/plugins/xgbregressor.py:35-44,55 — here the
oracle is sklearn GBT with the reference's hyperparameters, since
xgboost is not in the image), plus MLL hyperparameter selection and
masked-padding invariance checks."""
import os
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from uptune_tpu.surrogate import gp, mlp  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))
from surrogate_bench import (make_eda_dataset, precision_at, run,  # noqa: E402
                             spearman)


@pytest.fixture(scope="module")
def results():
    # quick=False: the oracle must be the REFERENCE configuration
    # (300 trees / depth 10 / lr 0.015) — a weaker quick-mode oracle
    # would let a GP regression below the real bar pass
    return run(n=400, n_test=200, quick=False)


@pytest.mark.slow
class TestParity:
    """Ranking-quality parity vs the 300-tree oracle: a ~34s
    module-fixture benchmark (the reference GBT fit dominates), slow-
    marked with the other convergence/bench gates (ISSUE 5 tier-1
    headroom); the cheap TestMLL/TestKernelNumerics/TestMaskedFit
    correctness checks below stay tier-1."""

    def test_gp_mll_beats_tree_oracle(self, results):
        """The headline: marginal-likelihood-fitted GP must be within
        0.05 Spearman of the tree oracle (measured: GP 0.89 vs GBT
        0.64 — it wins outright)."""
        assert results["gp_mll"]["spearman"] >= \
            results["oracle_gbt"]["spearman"] - 0.05
        assert results["gp_mll"]["p_at_10"] >= \
            results["oracle_gbt"]["p_at_10"] - 0.1

    def test_gp_mll_absolute_quality(self, results):
        assert results["gp_mll"]["spearman"] > 0.7
        assert results["gp_mll"]["p_at_10"] > 0.4

    def test_mll_fitting_improves_on_fixed(self, results):
        """Round-1's fixed (0.3, 1e-3) was the VERDICT's weak #5; the
        fitted GP must clearly beat it on the EDA surface."""
        assert results["gp_mll"]["spearman"] > \
            results["gp_fixed"]["spearman"] + 0.1

    def test_mlp_ensemble_competitive(self, results):
        assert results["mlp_ens"]["spearman"] >= \
            results["oracle_gbt"]["spearman"] - 0.1


class TestMLL:
    def test_mll_selects_sensible_lengthscale(self):
        """On a smooth 1-feature surface sampled densely, the evidence
        must prefer a long lengthscale over a tiny one."""
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.rand(80, 1), jnp.float32)
        y = jnp.sin(2 * x[:, 0]) + 0.01 * jnp.asarray(rng.randn(80))
        mll_long = gp.log_marginal_likelihood(x, y, 1.0, 1e-3)
        mll_short = gp.log_marginal_likelihood(x, y, 0.01, 1e-3)
        assert float(mll_long) > float(mll_short)

    def test_mll_mask_invariance(self):
        """Padded rows must contribute exactly zero evidence."""
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.rand(32, 3), jnp.float32)
        y = jnp.asarray(rng.randn(32), jnp.float32)
        base = gp.log_marginal_likelihood(x, y, 0.5, 1e-2)
        xp = jnp.concatenate([x, jnp.zeros((16, 3))])
        yp = jnp.concatenate([y, jnp.zeros(16)])
        mask = jnp.concatenate([jnp.ones(32), jnp.zeros(16)])
        padded = gp.log_marginal_likelihood(xp, yp, 0.5, 1e-2, mask)
        assert float(base) == pytest.approx(float(padded), rel=1e-4)


class TestKernelNumerics:
    def test_self_diagonal_is_one_at_small_lengthscale(self):
        """The matmul-identity distance must not lose the |a-b|=0
        cancellation: K(x, x) diagonal stays 1.0 even when |x/ls|^2 is
        large.  (On TPU this requires precision='highest' — default
        bf16 matmul passes collapsed the diagonal to ~0.0002 at
        ls=0.05; CPU f32 hides the bug, but the assertion documents
        the contract wherever the suite runs.)"""
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.rand(256, 94), jnp.float32)
        for ls in (0.05, 0.3, 2.0):
            k = gp._matern52(x, x, jnp.float32(ls))
            diag = np.asarray(jnp.diagonal(k))
            assert diag.min() > 0.99, (ls, diag.min())


class TestMaskedFit:
    def test_gp_padding_exact(self):
        """fit() on padded+masked data must produce the same predictions
        (mean AND variance) as the unpadded fit."""
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.rand(40, 4), jnp.float32)
        y = jnp.asarray(rng.randn(40), jnp.float32)
        xq = jnp.asarray(rng.rand(16, 4), jnp.float32)
        s0 = gp.fit(x, y, 0.4, 1e-2)
        mu0, sd0 = gp.predict(s0, xq)
        xp = jnp.concatenate([x, jnp.zeros((24, 4))])
        yp = jnp.concatenate([y, jnp.zeros(24)])
        mask = jnp.concatenate([jnp.ones(40), jnp.zeros(24)])
        s1 = gp.fit(xp, yp, 0.4, 1e-2, mask)
        mu1, sd1 = gp.predict(s1, xq)
        np.testing.assert_allclose(np.asarray(mu0), np.asarray(mu1),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(sd0), np.asarray(sd1),
                                   rtol=1e-4, atol=1e-5)

    def test_mlp_padding_close(self):
        """Masked MLP training must match unpadded training (identical
        normalization + loss; same RNG -> same parameters)."""
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.rand(50, 4), jnp.float32)
        y = jnp.asarray(rng.randn(50), jnp.float32)
        xq = jnp.asarray(rng.rand(8, 4), jnp.float32)
        key = jax.random.PRNGKey(0)
        m0, _ = mlp.predict(mlp.fit(key, x, y, steps=50), xq)
        xp = jnp.concatenate([x, jnp.zeros((14, 4))])
        yp = jnp.concatenate([y, jnp.zeros(14)])
        mask = jnp.concatenate([jnp.ones(50), jnp.zeros(14)])
        m1, _ = mlp.predict(mlp.fit(key, xp, yp, steps=50, mask=mask), xq)
        np.testing.assert_allclose(np.asarray(m0), np.asarray(m1),
                                   rtol=1e-3, atol=1e-4)


class TestTopKSelect:
    @pytest.fixture(params=["gp", "mlp"])
    def mgr(self, request):
        from uptune_tpu.space.params import FloatParam
        from uptune_tpu.space.spec import Space
        from uptune_tpu.surrogate.manager import SurrogateManager

        space = Space([FloatParam("x", 0.0, 1.0),
                       FloatParam("y", 0.0, 1.0)])
        m = SurrogateManager(space, request.param, min_points=32,
                             refit_interval=32, select="topk",
                             keep_frac=0.25, explore_frac=0.0, seed=0,
                             n_members=2)
        rng = np.random.RandomState(0)
        pts = rng.rand(64, 2).astype(np.float32)
        qor = (pts ** 2).sum(1)   # minimize: best near origin
        cands = space.from_configs(
            [{"x": float(a), "y": float(b)} for a, b in pts])
        m.observe(np.asarray(space.features(cands)), qor)
        assert m.maybe_refit()
        return space, m

    def test_exactly_k_survive(self, mgr):
        space, m = mgr
        rng = np.random.RandomState(1)
        pts = rng.rand(40, 2).astype(np.float32)
        cands = space.from_configs(
            [{"x": float(a), "y": float(b)} for a, b in pts])
        keep = m.keep_mask(cands)
        assert keep.sum() == 10   # 25% of 40

    def test_orientation_prefers_predicted_best(self, mgr):
        """Candidates near the origin (true minimum) must dominate the
        kept set."""
        space, m = mgr
        good = np.full((20, 2), 0.05, np.float32) \
            + np.random.RandomState(2).rand(20, 2).astype(np.float32) * 0.1
        bad = np.full((20, 2), 0.9, np.float32)
        pts = np.concatenate([good, bad])
        cands = space.from_configs(
            [{"x": float(a), "y": float(b)} for a, b in pts])
        keep = m.keep_mask(cands)
        assert keep[:20].sum() >= 8 and keep[20:].sum() <= 2

    def test_candidate_mask_restricts_ranking(self, mgr):
        """Ineligible (duplicate) rows must never occupy top-k slots,
        even when their predicted scores are the best in the batch."""
        space, m = mgr
        good = np.full((8, 2), 0.05, np.float32)    # predicted-best rows
        ok = np.full((32, 2), 0.5, np.float32) \
            + np.random.RandomState(3).rand(32, 2).astype(np.float32) * 0.2
        pts = np.concatenate([good, ok])
        cands = space.from_configs(
            [{"x": float(a), "y": float(b)} for a, b in pts])
        elig = np.concatenate([np.zeros(8, bool), np.ones(32, bool)])
        keep = m.keep_mask(cands, elig)
        assert not keep[:8].any()
        assert keep[8:].sum() == 8   # 25% of the 32 eligible


class TestDatasetSanity:
    def test_train_test_share_function(self):
        """Regression guard for the benchmark itself: different sample
        seeds must share the response function."""
        x1, y1 = make_eda_dataset(0, 50)
        x2, y2 = make_eda_dataset(1, 50)
        assert not np.allclose(x1, x2)
        # same x -> same y (up to noise): re-draw with same seed
        x3, y3 = make_eda_dataset(0, 50)
        np.testing.assert_allclose(y1, y3)

    def test_metrics(self):
        a = np.arange(10.0)
        assert spearman(a, a) == pytest.approx(1.0)
        assert spearman(a, -a) == pytest.approx(-1.0)
        assert precision_at(a, a, 0.2) == 1.0
