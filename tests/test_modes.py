"""CLI, template-mode, decouple-mode and multi-stage-mode tests
(reference parity: on.py:8-55, src/codegen.py:153-196,
async_task_scheduler.py:106-238, src/multi_stage.py:50-165)."""
import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

import uptune_tpu
from uptune_tpu.api import constraint as C
from uptune_tpu.api import session
from uptune_tpu.exec.controller import ProgramTuner
from uptune_tpu.exec.multistage import (DecoupledTuner, MultiStageTuner,
                                        run_auto, select_mode)
from uptune_tpu.exec.template import TemplateProgram, detect_template

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    uptune_tpu.__file__)))
ENV = {"PYTHONPATH": REPO}
SAMPLES = os.path.join(REPO, "samples")


@pytest.fixture(autouse=True)
def clean_registry(monkeypatch):
    for v in ("UT_BEFORE_RUN_PROFILE", "UT_TUNE_START", "BEST",
              "UT_WORK_DIR", "UT_MULTI_STAGE_SAMPLE"):
        monkeypatch.delenv(v, raising=False)
    C.REGISTRY.clear()
    session.reset_settings()
    yield


# ---------------------------------------------------------------------
class TestTemplate:
    TPL = textwrap.dedent("""\
        import uptune_tpu as ut
        a = 5           # {% a = TuneInt(5, (0, 50)) %}
        opt = '-O1'     # {% opt = TuneEnum('-O1', ['-O1','-O2','-O3'], 'level') %}
        flag = False    # {% flag = TuneBool(False) %}
        ut.target(float(a + (10 if opt == '-O1' else 0)), "min")
    """)

    def test_extract_records(self, tmp_path):
        p = tmp_path / "prog.py"
        p.write_text(self.TPL)
        tp = TemplateProgram(str(p))
        assert [r["name"] for r in tp.records] == ["a", "level", "flag"]
        assert tp.records[0] == {"name": "a", "type": "int", "default": 5,
                                 "lo": 0, "hi": 50}
        assert tp.records[1]["options"] == ["-O1", "-O2", "-O3"]

    def test_render_applies_config(self, tmp_path):
        p = tmp_path / "prog.py"
        p.write_text(self.TPL)
        tp = TemplateProgram(str(p))
        out = tp.render({"a": 7, "level": "-O3", "flag": True})
        assert "a = 7\n" in out
        assert "opt = '-O3'" in out
        assert "flag = True" in out
        # defaults fill unspecified values
        out2 = tp.render({"a": 9})
        assert "opt = '-O1'" in out2

    def test_non_template_detection(self, tmp_path):
        p = tmp_path / "plain.py"
        p.write_text("print('no annotations')\n")
        assert detect_template(str(p)) is None

    @pytest.mark.slow
    def test_template_end_to_end(self, tmp_path):
        p = tmp_path / "prog.py"
        p.write_text(self.TPL)
        pt = ProgramTuner([sys.executable, str(p)], str(tmp_path),
                          parallel=2, env=ENV, runtime_limit=30.0,
                          test_limit=20, seed=11,
                          template=TemplateProgram(str(p)))
        res = pt.run()
        # optimum: a=0, opt != -O1 -> qor 0
        assert res.best_qor < 15.0   # default is 15


# ---------------------------------------------------------------------
class TestDecouple:
    @pytest.mark.slow
    def test_mode_detection_and_run(self, tmp_path):
        shutil.copy(os.path.join(SAMPLES, "decomposed", "decomposed.py"),
                    tmp_path / "decomposed.py")
        pt = ProgramTuner(
            [sys.executable, str(tmp_path / "decomposed.py")],
            str(tmp_path), parallel=2, env=ENV, runtime_limit=30.0,
            test_limit=15, seed=13)
        pt.analyze()
        assert select_mode(pt) == "decouple"
        assert len(pt.params) == 2
        assert pt.params[0][0]["name"] == "scale"
        assert pt.params[1][0]["name"] == "unroll"
        res = DecoupledTuner(pt).run()
        assert set(res.best_config) == {"scale", "unroll"}
        # stage-0 best was published for stage-1 replay
        assert os.path.isfile(tmp_path / "configs" / "0-best.json")
        # both stage archives exist with attribution
        for s in range(2):
            rows = [json.loads(l) for l in
                    open(tmp_path / f"ut.archive_stage{s}.jsonl")][1:]
            assert rows and all("tech" in r for r in rows)
        # default pipeline cost: err0(8)=0.666, cost=0.666+|8-96|/96
        assert res.best_qor < 1.58


# ---------------------------------------------------------------------
MULTI_PROG = textwrap.dedent("""\
    import uptune_tpu as ut
    x = ut.tune(0, (0, 100), name="x")
    y = ut.tune(0, (0, 100), name="y")
    ut.interm([float(x), float(y)])
    ut.target(float((x - 60) ** 2 + (y - 20) ** 2), "min")
""")


class TestMultiStage:
    @pytest.mark.slow
    def test_pre_post_epochs(self, tmp_path):
        p = tmp_path / "prog.py"
        p.write_text(MULTI_PROG)
        pt = ProgramTuner([sys.executable, str(p)], str(tmp_path),
                          parallel=2, env=ENV, runtime_limit=30.0,
                          test_limit=16, seed=17)
        pt.analyze()
        assert select_mode(pt) == "multistage"
        ms = MultiStageTuner(pt, cand_factor=3, retrain_interval=1)
        res = ms.run()
        assert res.evals >= 16
        # the pre-phase pool saw cand_factor x more trials than evals
        assert ms.surrogate._ys    # online (features, qor) pairs recorded
        assert res.best_qor < (60 ** 2 + 20 ** 2)  # beat the default


# ---------------------------------------------------------------------
class TestCLI:
    def _run(self, args, cwd):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        return subprocess.run(
            [sys.executable, "-m", "uptune_tpu.cli"] + args,
            capture_output=True, text=True, cwd=cwd, env=env, timeout=300)

    def test_list_techniques(self, tmp_path):
        out = self._run(["--list-techniques"], str(tmp_path))
        assert out.returncode == 0
        names = out.stdout.split()
        assert "de" in names or any("de" in n for n in names)
        assert len(names) >= 30

    @pytest.mark.slow
    def test_tune_and_apply_best(self, tmp_path):
        """Slow-marked for suite-budget headroom (ISSUE 10, ~21 s):
        the CLI tune loop stays tier-1 via test_store's full `ut`
        strict-guard e2e and the seed-config CLI runs below, and
        --apply-best keeps the fast tier-1 sibling
        test_apply_best_serves_stored_best."""
        shutil.copy(os.path.join(SAMPLES, "hash", "single_stage.py"),
                    tmp_path / "prog.py")
        out = self._run(["prog.py", "-pf", "2", "--test-limit", "15",
                         "--seed", "3"], str(tmp_path))
        assert out.returncode == 0, out.stderr[-800:]
        last = json.loads(out.stdout.strip().splitlines()[-1])
        assert "best_config" in last and last["evals"] >= 15
        assert (tmp_path / "best.json").is_file()
        # --apply-best re-runs the program with the stored best
        out2 = self._run(["prog.py", "--apply-best"], str(tmp_path))
        assert out2.returncode == 0, out2.stderr[-800:]

    def test_apply_best_serves_stored_best(self, tmp_path):
        """Fast --apply-best sibling: a hand-written best.json is
        served to the program (BEST mode) without any prior tune —
        one subprocess instead of a 15-trial run."""
        prog = tmp_path / "prog.py"
        prog.write_text(
            "import uptune_tpu as ut\n"
            "x = ut.tune(1, (0, 100), name='x')\n"
            "print('SERVED', x)\n"
            "ut.target(float(x), 'min')\n")
        (tmp_path / "best.json").write_text(
            json.dumps({"config": {"x": 73}, "qor": 73.0}))
        out = self._run(["prog.py", "--apply-best"], str(tmp_path))
        assert out.returncode == 0, out.stderr[-800:]
        assert "SERVED 73" in out.stdout

    def test_learning_model_session_fallback(self, tmp_path):
        """ProgramTuner honors ut.config({'learning-model': ...}) when
        no explicit surrogate is passed (the documented settings
        fallback, same layering as its sibling parameters)."""
        from uptune_tpu.api.session import settings
        from uptune_tpu.calibrated import CALIBRATED_OPTS
        from uptune_tpu.exec.controller import ProgramTuner
        old = settings["learning-model"]
        settings["learning-model"] = ["gp"]
        try:
            pt = ProgramTuner(["true"], str(tmp_path))
            assert pt.surrogate == "gp"
            # calibrated defaults plus the async surrogate plane, ON by
            # default in program mode (docs/PERF.md; --surrogate-async
            # off / ut.config {'surrogate-async': 'off'} restore sync)
            assert pt.surrogate_opts == {**CALIBRATED_OPTS,
                                         "async_refit": True}
            # explicit surrogate still wins over the setting
            pt2 = ProgramTuner(["true"], str(tmp_path),
                               surrogate="mlp",
                               surrogate_opts={"keep_frac": 0.5})
            assert pt2.surrogate == "mlp"
            assert pt2.surrogate_opts["keep_frac"] == 0.5
        finally:
            settings["learning-model"] = old

    @pytest.mark.slow
    def test_learning_models_flag(self, tmp_path):
        """--learning-models gp enables the surrogate plane with the
        calibrated defaults (the reference's --learning-models,
        api.py:39-40); trials past min_points are surrogate-guided and
        the run still completes.  Slow-marked for suite-budget headroom
        (ISSUE 6): the CLI loop stays tier-1 via the other TestCLI
        runs, and the calibrated surrogate plane itself via
        test_surrogate* / the bench smoke."""
        shutil.copy(os.path.join(SAMPLES, "hash", "single_stage.py"),
                    tmp_path / "prog.py")
        out = self._run(["prog.py", "-pf", "2", "--test-limit", "24",
                         "--seed", "3", "--learning-models", "gp"],
                        str(tmp_path))
        assert out.returncode == 0, out.stderr[-800:]
        last = json.loads(out.stdout.strip().splitlines()[-1])
        assert last["evals"] >= 24

    def test_print_search_space_size(self, tmp_path):
        shutil.copy(os.path.join(SAMPLES, "hash", "single_stage.py"),
                    tmp_path / "prog.py")
        out = self._run(["prog.py", "--print-search-space-size"],
                        str(tmp_path))
        assert out.returncode == 0, out.stderr[-800:]
        assert "log10(size)" in out.stdout


# ---------------------------------------------------------------------
class TestSeedConfiguration:
    """--seed-configuration parity (r4 verdict next-step #7): the
    reference loads known-good config files at startup
    (/root/reference/python/uptune/opentuner/search/driver.py:37-42 via
    ConfigurationManipulator.load_from_file); here they are injected
    through the Tuner.inject seed path and EVALUATED first."""

    PROG = textwrap.dedent("""\
        import uptune_tpu as ut
        x = ut.tune(40, (0, 100), name='x')
        y = ut.tune(40, (0, 100), name='y')
        ut.target(float((x - 7) ** 2 + (y - 93) ** 2), "min")
    """)

    @pytest.mark.slow
    def test_seed_config_archived_and_evaluated(self, tmp_path):
        p = tmp_path / "prog.py"
        p.write_text(self.PROG)
        pt = ProgramTuner([sys.executable, str(p)], str(tmp_path),
                          parallel=2, env=ENV, runtime_limit=30.0,
                          test_limit=8, seed=19,
                          seed_configs=[{"x": 7, "y": 93}])
        res = pt.run()
        # the injected known-good config is the optimum: it must have
        # been evaluated (trace contains 0) and won
        assert res.best_qor == 0.0
        rows = [json.loads(line) for line in
                open(os.path.join(str(tmp_path), "ut.archive.jsonl"))]
        seeded = [r for r in rows if r.get("tech") == "seed"
                  and r.get("cfg", {}).get("x") == 7
                  and r.get("cfg", {}).get("y") == 93]
        assert seeded and seeded[0]["qor"] == 0.0

    @pytest.mark.slow
    def test_partial_seed_config_merged_over_defaults(self, tmp_path):
        p = tmp_path / "prog.py"
        p.write_text(self.PROG)
        pt = ProgramTuner([sys.executable, str(p)], str(tmp_path),
                          parallel=2, env=ENV, runtime_limit=30.0,
                          test_limit=8, seed=23,
                          seed_configs=[{"y": 93, "zzz_unknown": 1}])
        pt.run()
        rows = [json.loads(line) for line in
                open(os.path.join(str(tmp_path), "ut.archive.jsonl"))]
        # partial file: x fell back to the declared default (40),
        # unknown keys were dropped with a warning
        seeded = [r for r in rows if r.get("tech") == "seed"
                  and r.get("cfg", {}).get("y") == 93]
        assert seeded and seeded[0]["cfg"]["x"] == 40
        assert all("zzz_unknown" not in r.get("cfg", {}) for r in rows)

    def test_cli_flag_parses_files(self, tmp_path):
        """ut --seed-configuration accepts a dict file and a list file;
        a malformed file is a clean argv error, not a traceback."""
        from uptune_tpu import cli
        good = tmp_path / "one.json"
        good.write_text(json.dumps({"x": 1}))
        lst = tmp_path / "many.json"
        lst.write_text(json.dumps([{"x": 2}, {"y": 3}]))
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        prog = tmp_path / "prog.py"
        prog.write_text(self.PROG)
        rc = cli.main([str(prog), "--test-limit", "0",
                       "--seed-configuration", str(bad)])
        assert rc == 2
