"""NOTEARS causal-discovery tests (the reference never had any — its full
version depended on an absent C++ extension, /root/reference/python/
uptune/plugins/notears.py:19, and the simple one was exercised only by a
__main__ block)."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from uptune_tpu.plugins.notears import (_break_cycles, covariate_graph,  # noqa: E402
                                        h_func, notears, simulate_dag)


class TestHFunc:
    def test_dag_is_zero(self):
        w = jnp.asarray([[0.0, 1.5, 0.0],
                         [0.0, 0.0, -2.0],
                         [0.0, 0.0, 0.0]])
        assert float(h_func(w)) == pytest.approx(0.0, abs=1e-5)

    def test_cycle_is_positive(self):
        w = jnp.asarray([[0.0, 1.0], [1.0, 0.0]])
        assert float(h_func(w)) > 0.5


class TestBreakCycles:
    def test_removes_weakest_cycle_edge(self):
        w = np.asarray([[0.0, 1.0, 0.0],
                        [0.0, 0.0, 0.8],
                        [0.2, 0.0, 0.0]])   # 3-cycle; 0.2 is weakest
        out = _break_cycles(w)
        assert out[2, 0] == 0.0
        assert out[0, 1] == 1.0 and out[1, 2] == 0.8

    def test_dag_untouched(self):
        w = np.triu(np.ones((4, 4)), 1)
        np.testing.assert_array_equal(_break_cycles(w), w)

    def test_weak_acyclic_edge_survives(self):
        """A true weak edge outside the cycle must NOT be sacrificed for
        a strong 2-cycle elsewhere."""
        w = np.zeros((3, 3))
        w[0, 1] = 0.15              # weak, acyclic
        w[1, 2], w[2, 1] = 0.9, 1.0  # strong 2-cycle
        out = _break_cycles(w)
        assert out[0, 1] == 0.15
        assert out[1, 2] == 0.0 and out[2, 1] == 1.0


class TestRecovery:
    def test_exact_recovery_small(self):
        w_true, x = simulate_dag(jax.random.PRNGKey(0), d=6, n_edges=6,
                                 n_samples=800)
        w = notears(x, lambda1=0.05)
        assert ((w_true != 0) == (w != 0)).all(), (w_true, w)
        # refit magnitudes close to truth
        err = np.abs(w - w_true)[w_true != 0]
        assert err.max() < 0.25

    def test_aggregate_f1_medium(self):
        """Across seeds on d=10/12-edge graphs, structure F1 must stay
        high (measured ~0.9 median)."""
        f1s = []
        for seed in (1, 2, 3):
            w_true, x = simulate_dag(jax.random.PRNGKey(seed), d=10,
                                     n_edges=12, n_samples=1500)
            w = notears(x, lambda1=0.05)
            tp = float(((w_true != 0) & (w != 0)).sum())
            fp = float(((w_true == 0) & (w != 0)).sum())
            fn = float(((w_true != 0) & (w == 0)).sum())
            f1s.append(2 * tp / max(2 * tp + fp + fn, 1.0))
        assert np.median(f1s) >= 0.8, f1s

    def test_forbidden_mask(self):
        w_true, x = simulate_dag(jax.random.PRNGKey(0), d=6, n_edges=6,
                                 n_samples=800)
        forbid = np.zeros((6, 6), bool)
        forbid[0, :] = True         # node 0 may have no outgoing edges
        w = notears(x, lambda1=0.05, forbidden=forbid)
        assert (w[0, :] == 0).all()


class TestCovariateGraph:
    def test_drivers_found(self):
        """QoR driven by covariate 'a' (directly) and 'b' (through a);
        'c' is independent noise — only direct parents of qor count."""
        rng = np.random.RandomState(0)
        n = 600
        b = rng.randn(n)
        a = 1.6 * b + 0.5 * rng.randn(n)
        c = rng.randn(n)
        q = 2.0 * a + 0.4 * rng.randn(n)
        covars = [{"a": a[i], "b": b[i], "c": c[i]} for i in range(n)]
        out = covariate_graph(covars, q.tolist(), lambda1=0.05)
        assert out["names"] == ["a", "b", "c", "qor"]
        assert "a" in out["drivers"]
        assert "c" not in out["drivers"]

    def test_needs_enough_rows(self):
        with pytest.raises(ValueError):
            covariate_graph([{"a": 1.0}] * 5, [1.0] * 5)
