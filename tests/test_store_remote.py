"""Cooperative search fabric tests (ISSUE 18): the networked
ResultStore server (store/server.py) and its RemoteStore client
(store/remote.py).

TestParseAddr/TestOpenStoreFactory cover the address grammar and the
``--store tcp://`` routing seam.  TestStoreServerOps drives the server
transport-free through WireServer.handle(): content-key idempotency,
the per-requester delta cursor (scope/src/incarnation semantics), and
torn-tail log replay.  TestRemoteStoreFailureModes uses real localhost
TCP for the degradation contract: dead-server-at-open loud fallback,
bounded write-behind under a mid-run disconnect, and idempotent
re-delivery after reconnect.  TestStoreRemoteBenchSmoke runs the
`bench.py --store-remote --quick` fabric bench end-to-end (tier-1, the
ISSUE 18 smoke).  No jax anywhere on the client/server path."""
import json
import logging
import os
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from uptune_tpu.store import is_remote_addr, open_store  # noqa: E402
from uptune_tpu.store.remote import RemoteStore, parse_addr  # noqa: E402
from uptune_tpu.store.server import StoreServer  # noqa: E402
from uptune_tpu.store.store import ResultStore  # noqa: E402

SIG = ["spec-a", "spec-b"]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------
class TestParseAddr:
    def test_grammar(self):
        assert parse_addr("tcp://10.1.2.3:8791") == ("10.1.2.3", 8791)
        assert parse_addr("tcp://localhost:80") == ("localhost", 80)
        assert parse_addr("127.0.0.1:9") == ("127.0.0.1", 9)

    @pytest.mark.parametrize("bad", [
        "tcp://", "tcp://host", "host", "tcp://host:nan",
        "tcp://host:0", "tcp://:123", "http://h:1"])
    def test_rejects(self, bad):
        with pytest.raises(ValueError):
            parse_addr(bad)


class TestOpenStoreFactory:
    def test_routes_by_prefix(self, tmp_path):
        assert is_remote_addr("tcp://h:1") and not is_remote_addr(
            str(tmp_path))
        st = open_store(str(tmp_path / "s"), SIG, ["cmd"])
        try:
            assert isinstance(st, ResultStore)
        finally:
            st.close()
        port = _free_port()
        srv = StoreServer("127.0.0.1", port,
                          str(tmp_path / "srv")).start()
        try:
            rt = open_store(f"tcp://127.0.0.1:{port}", SIG, ["cmd"])
            try:
                assert isinstance(rt, RemoteStore) and rt.connected
            finally:
                rt.close()
        finally:
            srv.stop()

    def test_empty_store_is_truthy(self, tmp_path):
        # ``if store:`` call sites must not silently disable an
        # open-but-empty store (it defines __len__)
        st = open_store(str(tmp_path / "s"), SIG, ["cmd"])
        try:
            assert len(st) == 0 and bool(st)
        finally:
            st.close()


# ---------------------------------------------------------------------
class TestStoreServerOps:
    """Transport-free op semantics through WireServer.handle()."""

    def _row(self, i=0, qor=1.0, scope="sc", src="w0"):
        return {"k": f"k{i}", "scope": scope, "cfg": {"i": i},
                "qor": qor, "src": src}

    def test_record_is_content_key_idempotent(self, tmp_path):
        srv = StoreServer("127.0.0.1", 0, str(tmp_path))
        r1 = srv.handle({"op": "record", "row": self._row()})
        assert r1["ok"] and r1["acked"] and not r1["dup"]
        # the replayed duplicate (ack lost, client re-sent): ACKED
        # again but NOT re-appended — restart-safe dedup
        r2 = srv.handle({"op": "record", "row": self._row()})
        assert r2["ok"] and r2["acked"] and r2["dup"]
        assert srv.appends == 1 and srv.dups == 1
        srv.stop()

    def test_failure_rows_recorded_but_never_served(self, tmp_path):
        srv = StoreServer("127.0.0.1", 0, str(tmp_path))
        srv.handle({"op": "record", "row": self._row(qor=None)})
        miss = srv.handle({"op": "lookup", "k": "k0"})
        assert miss["ok"] and miss["row"] is None
        d = srv.handle({"op": "delta", "scope": "sc", "cursor": 0,
                        "src": "other"})
        assert d["rows"] == []          # delta feeds finite rows only
        # a later finite result for the same key upgrades the row
        srv.handle({"op": "record", "row": self._row(qor=7.5)})
        hit = srv.handle({"op": "lookup", "k": "k0"})
        assert hit["row"]["qor"] == 7.5
        srv.stop()

    def test_delta_cursor_scope_src_semantics(self, tmp_path):
        srv = StoreServer("127.0.0.1", 0, str(tmp_path))
        for i in range(3):
            srv.handle({"op": "record", "row": self._row(i, 1.0 + i,
                                                         src="wa")})
        srv.handle({"op": "record",
                    "row": self._row(9, 0.5, scope="other",
                                     src="wb")})
        # src filter: wa never gets its own rows back
        d = srv.handle({"op": "delta", "scope": "sc", "cursor": 0,
                        "incarn": srv.incarn, "src": "wa"})
        assert d["ok"] and d["rows"] == []
        # a sibling sees exactly the in-scope rows, cursor advances
        d = srv.handle({"op": "delta", "scope": "sc", "cursor": 0,
                        "incarn": srv.incarn, "src": "wb"})
        assert [r["k"] for r in d["rows"]] == ["k0", "k1", "k2"]
        assert d["cursor"] == 4 and not d["more"]
        d2 = srv.handle({"op": "delta", "scope": "sc",
                         "cursor": d["cursor"],
                         "incarn": srv.incarn, "src": "wb"})
        assert d2["rows"] == []
        # a stale incarnation (client survived a server restart):
        # the cursor is meaningless — the feed restarts from 0
        d3 = srv.handle({"op": "delta", "scope": "sc", "cursor": 99,
                         "incarn": "someone-else", "src": "wb"})
        assert len(d3["rows"]) == 3 and d3["incarn"] == srv.incarn
        srv.stop()

    def test_torn_tail_replay(self, tmp_path):
        srv = StoreServer("127.0.0.1", 0, str(tmp_path))
        srv.handle({"op": "record", "row": self._row(0, 1.0)})
        srv.handle({"op": "record", "row": self._row(1, 2.0)})
        srv.stop()
        with open(srv.log_path, "ab") as f:
            f.write(b'{"k": "torn", "scope": "sc", "cfg"')   # no \n
        srv2 = StoreServer("127.0.0.1", 0, str(tmp_path))
        assert srv2.replayed == 2 and srv2.torn_tail
        assert srv2.handle({"op": "lookup", "k": "k1"})["row"][
            "qor"] == 2.0
        # the server stays writable past a torn tail
        r = srv2.handle({"op": "record", "row": self._row(2, 3.0)})
        assert r["acked"] and not r["dup"]
        srv2.stop()

    def test_batch_frame_inherited_from_kernel(self, tmp_path):
        """`ut store` speaks multi-op frames with no op-table change
        (the ISSUE 20 kernel seam): record + lookup in ONE frame,
        ordered replies, failures element-wise."""
        srv = StoreServer("127.0.0.1", 0, str(tmp_path))
        out = srv.handle({"op": "batch", "ops": [
            {"op": "record", "row": self._row(0, 2.5)},
            {"op": "lookup", "k": "k0"},
            {"op": "nope"}]})
        assert out["ok"] and out["n"] == 3 and out["failed"] == 1
        r = out["replies"]
        assert r[0]["acked"] and not r[0]["dup"]
        assert r[1]["row"]["qor"] == 2.5   # sees the sub-op before it
        assert "unknown op" in r[2]["error"]
        srv.stop()

    def test_health_and_metrics_shapes(self, tmp_path):
        srv = StoreServer("127.0.0.1", 0, str(tmp_path))
        h = srv.handle({"op": "health"})
        assert h["role"] == "ut-store" and h["status"] == "cold"
        assert h["by_status"] == {"cold": 1}
        srv.handle({"op": "record", "row": self._row()})
        assert srv.handle({"op": "health"})["status"] == "ok"
        m = srv.handle({"op": "metrics"})
        assert "metrics" in m and "uptime_s" in m
        t = srv.handle({"op": "metrics", "format": "prometheus"})
        assert "metrics_text" in t
        srv.stop()


# ---------------------------------------------------------------------
class TestRemoteStoreFailureModes:
    """The degradation contract over real localhost TCP."""

    def test_dead_server_at_open_degrades_loudly(self, caplog):
        port = _free_port()     # nobody listening
        with caplog.at_level(logging.WARNING, logger="uptune_tpu"):
            st = RemoteStore(f"tcp://127.0.0.1:{port}", SIG, "cmd",
                             backoff_base=0.01, backoff_max=0.05)
        try:
            assert any("unreachable at open" in r.message
                       for r in caplog.records)
            assert not st.connected
            # local-only service continues: record + lookup work
            row = st.record({"p": 1}, 4.0)
            assert row is not None
            assert st.lookup({"p": 1})["qor"] == 4.0
            assert st.stats()["remote"]["queued"] >= 1
        finally:
            st.close()

    def test_mid_run_disconnect_bounds_write_behind(self, tmp_path):
        port = _free_port()
        srv = StoreServer("127.0.0.1", port, str(tmp_path)).start()
        st = RemoteStore(f"tcp://127.0.0.1:{port}", SIG, "cmd",
                         queue_max=8, batch_max=4, backoff_base=0.01,
                         backoff_max=0.05)
        try:
            assert st.record({"i": -1}, 1.0) is not None
            assert st.flush_wait(10.0)
            srv.stop()          # mid-run death
            for i in range(50):
                assert st.record({"i": i}, float(i)) is not None
            s = st.stats()["remote"]
            # bounded write-behind: the queue sheds oldest (plus at
            # most one in-flight ack-gated batch), and counts it
            assert s["queued"] <= 8 + 4
            assert s["dropped"] >= 50 - (8 + 4)
            assert len(st) == 51        # local table keeps everything
            # refresh on a dead wire is a cheap no-op, never a dial
            assert st.refresh() == 0
        finally:
            st.close()

    def test_reconnect_and_idempotent_redelivery(self, tmp_path):
        port = _free_port()
        root = str(tmp_path / "store")
        srv = StoreServer("127.0.0.1", port, root).start()
        st = RemoteStore(f"tcp://127.0.0.1:{port}", SIG, "cmd",
                         backoff_base=0.01, backoff_max=0.05)
        try:
            st.record({"i": 0}, 1.0)
            assert st.flush_wait(10.0)
            srv.stop()
            st.record({"i": 1}, 2.0)    # queues while down
            # the same server identity comes back on the same log
            srv2 = StoreServer("127.0.0.1", port, root).start()
            try:
                assert srv2.replayed == 1
                assert st.flush_wait(10.0)      # flusher re-dialed
                assert st.connected
                with srv2._lock:
                    assert len(srv2._rows) == 2
                # duplicate delivery (ack lost → client re-sends) is
                # absorbed by the content key, not re-appended
                k = st.lookup({"i": 0})["k"]
                before = srv2.appends
                r = srv2.handle({"op": "record",
                                 "row": {"k": k, "scope": st.scope,
                                         "cfg": {"i": 0}, "qor": 1.0,
                                         "src": "replayer"}})
                assert r["acked"] and r["dup"]
                assert srv2.appends == before
            finally:
                srv2.stop()
        finally:
            st.close()

    def test_exchange_survives_server_restart(self, tmp_path):
        """The delta cursor resets across a server incarnation change
        and the feed replays from 0 without duplicating rows the
        client already holds."""
        port = _free_port()
        root = str(tmp_path / "store")
        srv = StoreServer("127.0.0.1", port, root).start()
        a = RemoteStore(f"tcp://127.0.0.1:{port}", SIG, "cmd",
                        backoff_base=0.01, backoff_max=0.05)
        b = RemoteStore(f"tcp://127.0.0.1:{port}", SIG, "cmd",
                        backoff_base=0.01, backoff_max=0.05)
        try:
            a.record({"i": 0}, 1.0)
            assert a.flush_wait(10.0)
            assert b.refresh() == 1
            assert len(b.pop_fresh_rows()) == 1
            srv.stop()
            srv2 = StoreServer("127.0.0.1", port, root).start()
            try:
                # reconnect b (the flusher dials on queued work; a
                # bare refresh must also survive the new incarnation)
                b.record({"j": 9}, 9.0)
                assert b.flush_wait(10.0)
                # replayed rows re-arrive under the new incarnation
                # but merge as already-known: nothing fresh to pop
                b.refresh()
                assert b.pop_fresh_rows() == []
                assert len(b) == 2
            finally:
                srv2.stop()
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------
class TestStoreRemoteBenchSmoke:
    def test_store_remote_bench_quick_smoke(self, tmp_path):
        """`bench.py --store-remote --quick` (the ISSUE 18 tier-1
        smoke): a real `ut store` server under K=3 cooperating jax
        children over localhost TCP, bit-exact journal replay, then
        the deterministic mid-append SIGKILL with zero acked-row loss
        — all under the strict lock sanitizer and trace guard."""
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--store-remote", "--quick", "--cpu"],
            capture_output=True, text=True, env=env,
            cwd=str(tmp_path), timeout=840)
        assert r.returncode == 0, r.stdout + r.stderr
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["metric"] == "store_remote_ok"
        assert out["value"] is True
        art = json.load(open(os.path.join(
            REPO, "BENCH_STORE_REMOTE.quick.json")))
        assert art["phase1"]["journal_replay_exact"]
        assert art["phase1"]["children_trace_guard_clean"]
        assert art["phase1"]["exchange_injected"] > 0
        assert art["phase1"]["federated_rows"] > 0
        assert art["phase2"]["crash_rc"] == 137
        assert art["phase2"]["acked_rows_lost"] == 0
        assert sum(art["phase2"]["acked_at_crash"]) > 0
        assert art["phase2"]["survivor_drained"]
        assert art["phase2"]["survivor_resumed"]
        assert art["phase2"]["survivor_dropped"] == 0
