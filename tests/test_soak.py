"""Long-run soak (VERDICT r3 next-step #4): one 10^5-eval run through
the real Tuner exercising oldest-first History eviction, the surfaced
`dropped` counter, archive growth, torn-tail kill + resume, the dedup
floor past 2× capacity, and `ut-stats --compact` (the compactdb.py
equivalent) — end to end on one archive file.
"""
import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from uptune_tpu.driver.driver import Tuner  # noqa: E402
from uptune_tpu.space.params import IntParam  # noqa: E402
from uptune_tpu.space.spec import Space  # noqa: E402
from uptune_tpu.utils.stats import (FollowAccumulator,  # noqa: E402
                                    compact_archive, load_archive,
                                    technique_report)

CAP = 1 << 12          # history capacity: 4096 << eval count


def _space():
    return Space([IntParam(f"x{i}", 0, 31) for i in range(8)])


def _objective(cfgs):
    # cheap separable bowl with a known optimum at x=7: keeps the run
    # improving slowly enough that techniques stay active all soak
    out = []
    for c in cfgs:
        out.append(float(sum((c[f"x{i}"] - 7) ** 2 for i in range(8))))
    return np.asarray(out)


@pytest.mark.slow
class TestSoak:
    def test_soak_eviction_resume_compact(self, tmp_path):
        arch = str(tmp_path / "soak.jsonl")

        # phase 1: 50k evals, then die WITHOUT close() — plus a torn
        # half-line, the on-disk state a SIGKILL mid-write leaves
        t = Tuner(_space(), _objective, seed=0, capacity=CAP,
                  archive=arch)
        t.run(test_limit=50_000)
        evals1 = t.evals
        best1 = t.result().best_qor
        dropped1 = int(t.hist_state.dropped)
        assert evals1 >= 50_000
        # 50k novel evals through a 4k history => eviction MUST have
        # happened and the counter must surface it (>= evals - capacity
        # would be exact if every insert was novel; stay conservative)
        assert dropped1 > 2 * CAP, dropped1
        t._flush_archive()
        t._archive_f.write('{"gid": 99999999, "tech": "torn')  # no \n
        t._archive_f.flush()
        del t

        # phase 2: resume repairs the torn tail and replays 50k rows
        t2 = Tuner(_space(), _objective, seed=1, capacity=CAP,
                   archive=arch, resume=True)
        assert t2.evals == evals1, (t2.evals, evals1)
        assert t2.result().best_qor <= best1 + 1e-9
        t2.run(test_limit=100_000)
        assert t2.evals >= 100_000
        assert int(t2.hist_state.dropped) > dropped1
        t2.close()

        rows = load_archive(arch)
        assert len(rows) >= 100_000
        # dedup floor past 2x capacity: the archive stays dominated by
        # distinct configs (re-evals of evicted configs are allowed,
        # wholesale duplicate churn is not)
        uniq = len({json.dumps([r["u"], r["perms"]]) for r in rows})
        assert uniq / len(rows) > 0.8, (uniq, len(rows))

        # incremental --follow fold at 10^5 rows (VERDICT r3 weak #6):
        # chunked folding must agree with the full recompute
        acc = FollowAccumulator("min")
        for i in range(0, len(rows), 4096):
            acc.update(rows[i:i + 4096])
        assert acc.snapshot() == technique_report(rows)

        # compaction drops only duplicate-config rows, atomically
        st = compact_archive(arch)
        assert st["rows_after"] == uniq
        assert st["rows_before"] == len(rows)

        # a tuner resumed from the COMPACTED archive reconstructs the
        # same best (replay only needed each config once)
        t3 = Tuner(_space(), _objective, seed=2, capacity=CAP,
                   archive=arch, resume=True)
        assert abs(t3.result().best_qor - t2.result().best_qor) < 1e-9
        t3.close()
