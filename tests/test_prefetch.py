"""Driver-plane prefetch + dispatch-elimination tests: ask/tell
interleaving under pool saturation, speculative-ticket cancellation
semantics (no observe, no bandit credit), in-flight dedup, buffer
donation (in-place history/technique-state updates), and the
one-trace-per-program guarantee over a full tune."""
import jax
import numpy as np
import pytest

from uptune_tpu.analysis.trace_guard import TraceGuard
from uptune_tpu.driver import Tuner
from uptune_tpu.space.params import IntParam
from uptune_tpu.space.spec import Space
from uptune_tpu.workloads import rosenbrock_objective, rosenbrock_space


def _cfg_key(cfg):
    return tuple(sorted(cfg.items()))


class TestAskTellInterleave:
    def test_overlapping_asks_never_duplicate_inflight(self):
        """The dedup satellite: while one batch is out for evaluation
        (pool saturated), further ask()s must not re-propose any
        in-flight config — _pending masks them on device-dedup's
        novelty output."""
        space = Space([IntParam("a", 0, 200), IntParam("b", 0, 200)])
        t = Tuner(space, None, seed=3)
        first = t.ask(min_trials=4)
        inflight = {_cfg_key(tr.config) for tr in first}
        second = t.ask(min_trials=4)
        overlap = inflight & {_cfg_key(tr.config) for tr in second}
        assert not overlap, overlap
        # resolve out of order: second batch first, then the first
        for tr in second:
            t.tell(tr, float(tr.config["a"]))
        for tr in first:
            t.tell(tr, float(tr.config["a"]))
        assert t.told == len(first) + len(second)
        assert t.evals == t.told
        # every config entered history exactly once: re-injecting one
        # serves the recorded result instead of opening a trial
        assert t.inject([first[0].config]) == []

    def test_interleaved_tell_midstream_keeps_budget_counters(self):
        space = rosenbrock_space(4, -3.0, 3.0)
        t = Tuner(space, None, seed=5)
        a = t.ask(min_trials=2)
        # tell only half of a, then ask again with the rest in flight
        for tr in a[: len(a) // 2]:
            t.tell(tr, 1.0 + tr.gid)
        b = t.ask(min_trials=2)
        for tr in a[len(a) // 2:] + b:
            t.tell(tr, 1.0 + tr.gid)
        assert t.told == len(a) + len(b) == t.evals


class TestSpeculativeCancel:
    def test_fully_cancelled_ticket_skips_credit(self):
        """A prefetched ticket invalidated before any of its trials ran
        is an UNKNOWN outcome: the bandit must get no credit event for
        the pull (vs. a zero-trial dup-serving ticket, whose negative
        credit is load-bearing)."""
        space = rosenbrock_space(4, -3.0, 3.0)
        t = Tuner(space, None, seed=9)
        first = t.ask(min_trials=1)
        for tr in first:
            t.tell(tr, 100.0 + tr.gid)  # land an incumbent
        evals0 = t.evals
        spec = t.ask(min_trials=1)
        assert spec[0].ticket.arm is not None
        credits = []
        orig_credit = t.root.credit
        t.root.credit = lambda *a, **k: credits.append(a)
        try:
            for tr in spec:
                t.cancel(tr)
        finally:
            t.root.credit = orig_credit
        assert credits == [], "withdrawn pull must not earn/lose credit"
        # nothing was archived/evaluated, and the configs may come back
        assert t.evals == evals0
        again = t.inject([spec[0].config])
        assert len(again) == 1, "cancelled config must be re-proposable"
        t.tell(again[0], 5.0)

    def test_fully_cancelled_ticket_skips_observe(self):
        # DE is the stateful arm (GreedyMutation state is the interned
        # empty tuple, useless for identity checks)
        space = rosenbrock_space(4, -3.0, 3.0)
        t = Tuner(space, None, seed=9,
                  technique="DifferentialEvolutionAlt")
        spec = t.ask(min_trials=1)
        name = spec[0].ticket.arm.name
        state_before = t._tstates[name]
        for tr in spec:
            t.cancel(tr)
        assert t._tstates[name] is state_before, \
            "withdrawn pull must not touch the arm's device state"

    def test_partial_cancel_still_observes_live_trials(self):
        space = rosenbrock_space(4, -3.0, 3.0)
        t = Tuner(space, None, seed=9,
                  technique="DifferentialEvolutionAlt")
        trials = t.ask(min_trials=2)
        tk = trials[0].ticket
        same = [tr for tr in trials if tr.ticket is tk]
        state_before = t._tstates[tk.arm.name]
        stats = None
        t.tell(same[0], 1.0)          # one real result -> new best
        for tr in same[1:]:
            stats = t.cancel(tr)
        for tr in trials:             # resolve any other ticket
            if tr.ticket is not tk and tr.qor is None:
                t.tell(tr, 2.0)
        assert stats is not None and stats.evaluated == 1
        assert stats.was_new_best
        assert t._tstates[tk.arm.name] is not state_before, \
            "a ticket with live results must still observe()"


class TestDonation:
    def test_commit_donates_history_and_best(self):
        """The _commit program updates the [cap] history buffers in
        place (donate_argnums): after a step, the pre-step HistState
        and Best buffers are dead — the dispatch-cost the tentpole
        eliminates is exactly this per-step full-capacity copy."""
        space = rosenbrock_space(2, -3.0, 3.0)
        t = Tuner(space, rosenbrock_objective(2), seed=1,
                  capacity=1 << 10)
        old_hist = t.hist_state
        old_best = t.best
        t.step()
        assert old_hist.h0.is_deleted()
        assert old_hist.qor.is_deleted()
        assert old_best.u.is_deleted()
        # the new state is live and the tuner keeps working
        assert int(t.hist_state.n) > 0
        t.step()

    def test_observe_donates_ticket_state(self):
        space = rosenbrock_space(2, -3.0, 3.0)
        t = Tuner(space, None, seed=2,
                  technique="DifferentialEvolutionAlt")
        trials = t.ask(min_trials=1)
        tk = trials[0].ticket
        leaves_before = [x for x in jax.tree_util.tree_leaves(tk.tstate)
                         if hasattr(x, "is_deleted")]
        for tr in trials:
            t.tell(tr, float(tr.gid))
        assert any(x.is_deleted() for x in leaves_before), \
            "ticket tstate must be donated into observe()"

    def test_forwarding_technique_survives_inflight_donation(self):
        """A technique whose propose() forwards its state unchanged
        makes every in-flight ticket alias ONE buffer (jit input-output
        forwarding): the driver must detect this on the first pull and
        observe WITHOUT donation, or finalizing ticket A would delete
        ticket B's state."""
        import jax.numpy as jnp

        from uptune_tpu.techniques.base import Technique

        class Forwarding(Technique):
            def natural_batch(self, space):
                return 8

            def init_state(self, space, key):
                return (jnp.zeros((4,), jnp.float32),)

            def propose(self, space, state, key, best):
                return state, space.random(key, 8)  # state FORWARDED

            def observe(self, space, state, cands, qor, best):
                return (state[0] + 1.0,)

        space = rosenbrock_space(4, -3.0, 3.0)
        t = Tuner(space, None, seed=7, technique=Forwarding("fwd"))
        # this jax version (0.4.37) copies passthrough outputs, so make
        # the forwarding OBSERVABLE the way newer jax does it: return
        # the input state object itself from the propose wrapper
        orig = t._propose_jit["fwd"]

        def forwarding_propose(st, k, best, hs):
            out = orig(st, k, best, hs)
            return (st,) + tuple(out[1:])

        t._propose_jit["fwd"] = forwarding_propose
        a = t.ask(min_trials=1)
        b = t.ask(min_trials=1)   # same arm: both tickets alias st
        assert "fwd" in t._arm_forwards
        assert a[0].ticket.tstate is b[0].ticket.tstate
        for tr in a + b:
            t.tell(tr, float(tr.gid))  # donation here would crash B
        # both observes ran from the shared snapshot without a deleted-
        # buffer error (each observed +1 over the same base state)
        assert float(t._tstates["fwd"][0][0]) == 1.0

    def test_padding_rows_never_become_trials(self):
        """Arm batches are padded to one common bucket for aval
        stability; padded rows are in-batch duplicates of row 0 and
        must never be proposed as trials nor enter the history."""
        space = rosenbrock_space(2, -3.0, 3.0)
        t = Tuner(space, None, seed=4)
        trials = t.ask(min_trials=1)
        tk = trials[0].ticket
        assert tk.cands.batch == t._bucket
        rows = [tr.row for tr in tk.trials]
        assert len(rows) == len(set(rows))
        src = np.asarray(tk.src)
        for tr in tk.trials:
            assert src[tr.row] == tr.row, "a trial row must be a first occurrence"
        for tr in trials:
            t.tell(tr, float(tr.gid))
        assert int(t.hist_state.n) <= t._bucket


class TestTraceOnce:
    def test_full_tune_compiles_each_program_once(self):
        """The PR 1 finding (3 traces/tune for _dedup/_commit) stays
        fixed: a full in-process tune under a strict limit=1 TraceGuard
        — every driver program (per-arm propose+dedup, commit, observe)
        traces exactly once."""
        with TraceGuard(limit=1, strict=True, name="driver-plane"):
            space = rosenbrock_space(4, -3.0, 3.0)
            t = Tuner(space, rosenbrock_objective(4), seed=0)
            t.run(test_limit=150)
        # reaching here means check() raised nothing

    def test_timing_fields_populated(self):
        space = rosenbrock_space(2, -3.0, 3.0)
        t = Tuner(space, rosenbrock_objective(2), seed=6)
        stats = t.step()
        assert stats.t_propose > 0.0
        assert stats.t_eval_wait > 0.0
        res = t.result()
        assert res.t_propose >= stats.t_propose
        assert res.t_eval_wait >= stats.t_eval_wait
