"""Cross-payload feature screening (surrogate/screen.py): per-lane
sensitivity transfer from archives of other payloads over the same
space, restricting the SURROGATE's view (never the techniques') to the
lanes that measurably moved QoR — the r4-verdict attack on the
prior-dominated-GP regime (80 evals over ~1,100 one-hot lanes).
Reference analogue: none — its XGBoost plugin relied on tree splits to
ignore dead features and archives were only replayed for resume
(/root/reference/python/uptune/api.py:328-363)."""
import os

import jax
import numpy as np
import pytest

from uptune_tpu.driver import Tuner
from uptune_tpu.space.params import BoolParam, EnumParam, FloatParam
from uptune_tpu.space.spec import Space
from uptune_tpu.surrogate import SurrogateManager
from uptune_tpu.surrogate.screen import (FeatureScreen, archive_rows,
                                         build_screen, lane_sensitivity,
                                         screen_from_archives)


def _space(n_float=4, n_bool=12, n_enum=3):
    return Space([FloatParam(f"x{i}", 0.0, 1.0) for i in range(n_float)]
                 + [BoolParam(f"f{i}") for i in range(n_bool)]
                 + [EnumParam(f"e{i}", ("a", "b", "c"))
                    for i in range(n_enum)])


def _payload_data(space, n=200, seed=0, live_f=(0, 3), live_x=(1,)):
    """(surrogate feats, qor) where only the named params move QoR."""
    cands = space.random(jax.random.PRNGKey(seed), n)
    feats = np.asarray(space.surrogate_transform(space.features(cands)))
    cfgs = space.to_configs(cands)
    qor = np.zeros(n)
    for r, c in enumerate(cfgs):
        qor[r] = (sum((2.0 + i) * float(c[f"f{i}"]) for i in live_f)
                  + sum(3.0 * c[f"x{i}"] for i in live_x)
                  + 0.01 * np.random.RandomState(seed * 1000 + r).rand())
    return feats, qor


class TestSensitivity:
    def test_live_lanes_outrank_dead(self):
        space = _space()
        feats, qor = _payload_data(space)
        s = lane_sensitivity(feats, qor)
        assert s.shape == (space.n_surrogate_features,)
        nc, w = space.n_cont_features, space.cat_max_codes
        # group scores: live flags f0/f3 (groups 0 and 3) beat all the
        # dead flags and enums
        gs = s[nc:].reshape(space.n_cat, w).max(axis=1)
        dead = [g for g in range(space.n_cat) if g not in (0, 3)]
        assert gs[0] > max(gs[d] for d in dead)
        assert gs[3] > max(gs[d] for d in dead)
        # live numeric lane x1 beats the dead numeric lanes
        assert s[1] > max(s[0], s[2], s[3])

    def test_nonfinite_rows_dropped(self):
        space = _space()
        feats, qor = _payload_data(space)
        qor[::3] = np.inf
        s = lane_sensitivity(feats, qor)
        assert np.isfinite(s).all()


class TestBuildScreen:
    def test_layout_and_selection(self):
        space = _space()
        sources = [_payload_data(space, seed=s) for s in range(3)]
        sc = build_screen(space, sources, top_cont=2, top_cat=4)
        assert isinstance(sc, FeatureScreen)
        assert sc.n_cont == 2 and sc.n_cat == 4
        nc, w = space.n_cont_features, space.cat_max_codes
        assert len(sc.idx) == 2 + 4 * w
        # cont block first (indices < n_cont), then whole one-hot
        # groups, everything strictly increasing within its block
        cont, cat = sc.idx[:2], sc.idx[2:]
        assert (cont < nc).all() and (cat >= nc).all()
        assert (np.diff(cont) > 0).all() and (np.diff(cat) > 0).all()
        # the live lanes made the cut
        assert 1 in cont                       # x1
        groups = sorted(set((cat - nc) // w))
        assert 0 in groups and 3 in groups     # f0, f3
        # flip weights live only on kept categorical scalar lanes
        lanes = np.asarray(space.cat_lane_idx)[groups]
        assert (sc.cat_weight[lanes] > 0).any()
        off = np.ones(space.n_scalar, bool)
        off[lanes] = False
        assert (sc.cat_weight[off] == 0).all()

    def test_apply_projects(self):
        space = _space()
        sc = build_screen(space, [_payload_data(space)], top_cont=2,
                          top_cat=4)
        feats, _ = _payload_data(space, seed=9)
        assert sc.apply(feats).shape == (feats.shape[0], len(sc.idx))


class TestManagerIntegration:
    def test_screened_manager_fit_prune_propose(self):
        space = _space()
        sc = build_screen(space, [_payload_data(space, seed=s)
                                  for s in range(2)],
                          top_cont=2, top_cat=4)
        m = SurrogateManager(space, "gp", min_points=32,
                             propose_batch=8, pool_mult=8, screen=sc,
                             select="topk", score="ei")
        cands = space.random(jax.random.PRNGKey(5), 64)
        feats, qor = _payload_data(space, seed=5, n=64)
        m.observe(np.asarray(space.features(cands)), qor[:64])
        assert m.maybe_refit()
        # the GP was fitted on the SCREENED width
        assert m._state.x.shape[1] == len(sc.idx)
        keep = m.keep_mask(cands)
        assert keep is not None and keep.shape == (64,)
        pool = m.propose_pool(jax.random.PRNGKey(6), cands.u[0], (),
                              float(qor.min()))
        assert pool is not None and pool.batch == 8

    def test_screen_dict_form_builds_from_archives(self, tmp_path):
        """The CLI hands {'archives': [...]} through surrogate_opts;
        the manager builds the screen once the space exists."""
        space = _space()
        arch = str(tmp_path / "src.jsonl")
        cfg_live = [0, 3]

        def obj(cfgs):
            return [sum((2.0 + i) * float(c[f"f{i}"]) for i in cfg_live)
                    + 3.0 * c["x1"] for c in cfgs]

        t = Tuner(space, obj, seed=0, archive=arch)
        t.run(test_limit=120)
        t.close()
        m = SurrogateManager(space, "gp",
                             screen={"archives": [arch],
                                     "top_cont": 2, "top_cat": 4})
        assert m.screen is not None
        assert m.screen.n_cont == 2 and m.screen.n_cat == 4
        # missing/empty archives -> unscreened, not an error
        m2 = SurrogateManager(space, "gp",
                              screen={"archives":
                                      [str(tmp_path / "nope.jsonl")]})
        assert m2.screen is None

    def test_archive_space_mismatch_raises(self, tmp_path):
        space = _space()
        other = _space(n_float=3)
        arch = str(tmp_path / "a.jsonl")
        t = Tuner(space, lambda cfgs: [0.0] * len(cfgs), seed=0,
                  archive=arch)
        t.run(test_limit=20)
        t.close()
        with pytest.raises(ValueError, match="different space"):
            archive_rows(other, arch)

    @pytest.mark.slow
    def test_screened_beats_unscreened_ranking(self):
        """On a mostly-dead space with 48 observations, the screened
        GP's posterior mean must rank a large candidate set better
        than the unscreened one (the whole point of the transfer).
        Slow-marked for suite-budget headroom (ISSUE 10, ~15 s — the
        two full-width fit_auto sweeps dominate): the screen mechanics
        keep tier-1 coverage via the manager-integration and
        soft-screen tests in this file, and the measured
        screened-vs-unscreened claim is pinned on gcc-real in
        BENCHREPORT.md."""
        from uptune_tpu.surrogate import gp as gp_mod

        space = _space(n_float=4, n_bool=24, n_enum=6)
        sources = [_payload_data(space, seed=s) for s in range(3)]
        sc = build_screen(space, sources, top_cont=2, top_cat=4)
        feats, qor = _payload_data(space, seed=7, n=48)
        test_f, test_q = _payload_data(space, seed=8, n=256)

        def spearman(a, b):
            ra = np.argsort(np.argsort(a)).astype(float)
            rb = np.argsort(np.argsort(b)).astype(float)
            return np.corrcoef(ra, rb)[0, 1]

        rhos = {}
        for name, idx, ncont, ncat in (
                ("screened", sc.idx, sc.n_cont, sc.n_cat),
                ("full", np.arange(space.n_surrogate_features),
                 space.n_cont_features, space.n_cat)):
            st = gp_mod.fit_auto(feats[:, idx], qor, n_cont=ncont,
                                 n_cat=ncat)
            mu, _ = gp_mod.predict(st, test_f[:, idx], n_cont=ncont,
                                   n_cat=ncat)
            rhos[name] = spearman(np.asarray(mu), test_q)
        assert rhos["screened"] > rhos["full"] - 1e-9, rhos
        assert rhos["screened"] > 0.5, rhos


class TestSoftScreen:
    """screen_mode='soft': full-width per-lane ARD scaling from
    transferred sensitivities (lane_weight), vs the hard top-k
    restriction.  Measured on gcc-real in BENCHREPORT.md; these tests
    pin the mechanics."""

    def test_lane_weight_shape_and_bounds(self):
        space = _space()
        sc = build_screen(space, [_payload_data(space)], top_cont=2,
                          top_cat=4)
        w = sc.lane_weight
        assert w.shape == (space.n_surrogate_features,)
        assert (w >= 0.1 - 1e-9).all() and (w <= 1.0 + 1e-9).all()
        # one-hot columns of the same flag share their group weight
        nc, k = space.n_cont_features, space.cat_max_codes
        gw = w[nc:].reshape(space.n_cat, k)
        assert np.allclose(gw, gw[:, :1])

    def test_soft_manager_full_width_scaled(self):
        space = _space()
        sc = build_screen(space, [_payload_data(space, seed=s)
                                  for s in range(2)],
                          top_cont=2, top_cat=4)
        m = SurrogateManager(space, "gp", min_points=32,
                             propose_batch=8, pool_mult=8, screen=sc,
                             screen_mode="soft")
        cands = space.random(jax.random.PRNGKey(5), 64)
        _, qor = _payload_data(space, seed=5, n=64)
        m.observe(np.asarray(space.features(cands)), qor)
        assert m.maybe_refit()
        # full width (no restriction), but the representation is scaled
        assert m._state.x.shape[1] == space.n_surrogate_features
        feats = np.asarray(m._sx(space.features(cands)))
        raw = np.asarray(space.surrogate_transform(
            space.features(cands)))
        np.testing.assert_allclose(feats, raw * sc.lane_weight,
                                   rtol=1e-6)
        pool = m.propose_pool(jax.random.PRNGKey(6), cands.u[0], (),
                              float(qor.min()))
        assert pool is not None and pool.batch == 8

    def test_bad_mode_rejected(self):
        space = _space()
        with pytest.raises(ValueError, match="screen_mode"):
            SurrogateManager(space, "gp", screen_mode="fuzzy")


class TestOnlineFlipBias:
    """flip_bias='online' (manager): per-group |corr| over the run's
    OWN observations re-weights the pool's flip moves at each refit —
    no transfer, no model narrowing."""

    def test_online_weights_track_live_flags(self):
        space = _space()
        m = SurrogateManager(space, "gp", min_points=32,
                             refit_interval=32,
                             propose_batch=8, pool_mult=8,
                             flip_bias="online")
        cands = space.random(jax.random.PRNGKey(3), 96)
        _, qor = _payload_data(space, seed=3, n=96)
        m.observe(np.asarray(space.features(cands)), qor)
        assert m.maybe_refit()
        w = m._online_cat_w
        assert w is not None and w.shape == (space.n_scalar,)
        lanes = np.asarray(space.cat_lane_idx)
        live = [lanes[0], lanes[3]]          # f0, f3 move QoR
        dead = [l for l in lanes if l not in live]
        assert min(w[l] for l in live) > max(w[l] for l in dead)
        fp = np.asarray(m._flip_probs())
        assert fp.shape == (space.n_scalar,)
        # numeric lanes never flip; every cat lane keeps a floor share
        num = [i for i in range(space.n_scalar) if i not in lanes]
        assert all(fp[i] == 0 for i in num)
        assert all(fp[l] > 0 for l in lanes)
        # pool still proposes (flip_p is an argument, not a retrace)
        pool = m.propose_pool(jax.random.PRNGKey(4), cands.u[0], (),
                              float(qor.min()))
        assert pool is not None and pool.batch == 8
        # a second refit updates the weights without rebuilding the jit
        cands2 = space.random(jax.random.PRNGKey(5), 32)
        _, q2 = _payload_data(space, seed=5, n=32)
        m.observe(np.asarray(space.features(cands2)), q2)
        assert m.maybe_refit()      # 32 new rows >= refit_interval
        assert m.propose_pool(jax.random.PRNGKey(6), cands.u[0], (),
                              float(qor.min())) is not None

    def test_uniform_without_bias(self):
        space = _space()
        m = SurrogateManager(space, "gp")
        fp = np.asarray(m._flip_probs())
        lanes = np.asarray(space.cat_lane_idx)
        np.testing.assert_allclose(fp[lanes], 1.0 / space.n_cat)

    def test_bad_value_rejected(self):
        with pytest.raises(ValueError, match="flip_bias"):
            SurrogateManager(_space(), "gp", flip_bias="upstream")
