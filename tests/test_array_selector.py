"""Selector + array parameter types (the reference's
SelectorParameter / ParameterArray / BooleanArray / FloatArray,
manipulator.py:1448-1732, redesigned as ordered INT lanes and
lane-expanded composites)."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")

from uptune_tpu.exec.space_io import space_from_params  # noqa: E402
from uptune_tpu.space import (BoolArrayParam, FloatArrayParam,  # noqa: E402
                              FloatParam, IntArrayParam, SelectorParam,
                              Space)


class TestSelector:
    def test_choice_mapping_ordered(self):
        s = SelectorParam("s", ("a", "b", "c"), max_cutoff=9)
        # positions 0-2 -> a, 3-5 -> b, 6-8 -> c
        assert [s.choice_of(p) for p in range(9)] == \
            ["a", "a", "a", "b", "b", "b", "c", "c", "c"]

    def test_round_trip(self):
        space = Space([SelectorParam("s", ("x", "y", "z"), 12)])
        cands = space.from_configs([{"s": "y"}, {"s": "z"}, {"s": "x"}])
        cfgs = space.to_configs(cands)
        assert [c["s"] for c in cfgs] == ["y", "z", "x"]

    def test_locality_under_mutation(self):
        """Small unit-space steps move to the same or a neighboring
        choice (the property an ENUM lane does not have)."""
        s = SelectorParam("s", ("a", "b", "c", "d"), 16)
        space = Space([s])
        import jax.numpy as jnp
        u = jnp.linspace(0.02, 0.98, 50)[:, None]
        vals = space.decode_scalars_np(np.asarray(u))[:, 0]
        seq = [s.choice_of(int(round(v))) for v in vals]
        order = [seq[0]]
        for c in seq[1:]:
            if c != order[-1]:
                order.append(c)
        assert order == ["a", "b", "c", "d"]   # monotone sweep

    def test_tunes(self):
        from uptune_tpu.driver.driver import Tuner
        space = Space([SelectorParam("alg", ("slow", "ok", "fast"), 9),
                       FloatParam("x", 0.0, 1.0)])
        cost = {"slow": 2.0, "ok": 1.0, "fast": 0.0}

        def obj(cfgs):
            return [cost[c["alg"]] + (c["x"] - 0.5) ** 2 for c in cfgs]

        t = Tuner(space, obj, seed=0)
        res = t.run(test_limit=180)
        t.close()
        assert res.best_config["alg"] == "fast"


class TestArrays:
    def test_expansion_and_round_trip(self):
        space = Space([BoolArrayParam("flags", 4),
                       IntArrayParam("tiles", 3, 1, 8),
                       FloatArrayParam("w", 2, -1.0, 1.0)])
        assert space.n_scalar == 9
        cfg = {"flags": [True, False, True, False],
               "tiles": [2, 8, 1], "w": [0.25, -0.5]}
        out = space.to_configs(space.from_configs([cfg]))[0]
        assert out["flags"] == cfg["flags"]
        assert out["tiles"] == cfg["tiles"]
        np.testing.assert_allclose(out["w"], cfg["w"], atol=1e-3)

    def test_wrong_length_rejected(self):
        space = Space([BoolArrayParam("f", 3)])
        with pytest.raises(ValueError, match="3 elements"):
            space.from_configs([{"f": [True]}])

    def test_expansion_name_collision_rejected(self):
        from uptune_tpu.space import IntParam
        with pytest.raises(ValueError, match="collide"):
            Space([IntParam("x[0]", 0, 5), IntArrayParam("x", 2, 0, 5)])

    def test_search_space_size(self):
        assert BoolArrayParam("f", 5).search_space_size() == 32.0
        assert IntArrayParam("t", 2, 0, 9).search_space_size() == 100.0

    def test_random_and_hash(self):
        space = Space([BoolArrayParam("f", 4),
                       FloatParam("x", 0.0, 1.0)])
        cands = space.random(jax.random.PRNGKey(0), 16)
        h = space.hash_batch(cands)
        assert h.shape[0] == 16
        cfgs = space.to_configs(cands)
        assert all(len(c["f"]) == 4 for c in cfgs)

    def test_tunes(self):
        from uptune_tpu.driver.driver import Tuner
        space = Space([BoolArrayParam("f", 6)])
        want = [True, False, True, True, False, True]

        def obj(cfgs):
            return [sum(a != b for a, b in zip(c["f"], want))
                    for c in cfgs]

        t = Tuner(space, obj, seed=0)
        res = t.run(test_limit=250)
        t.close()
        assert res.best_qor == 0.0
        assert res.best_config["f"] == want


class TestSpaceIO:
    def test_records(self):
        space = space_from_params([
            {"name": "s", "type": "selector", "choices": ["a", "b"],
             "max_cutoff": 6},
            {"name": "f", "type": "bool_array", "n": 3},
            {"name": "t", "type": "int_array", "n": 2, "lo": 0, "hi": 7},
            {"name": "w", "type": "float_array", "n": 2, "lo": 0.0,
             "hi": 1.0},
        ])
        assert space.n_scalar == 1 + 3 + 2 + 2
        cfg = space.to_configs(space.random(jax.random.PRNGKey(1), 2))[0]
        assert cfg["s"] in ("a", "b") and len(cfg["f"]) == 3
