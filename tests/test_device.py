"""Device-plane telemetry (uptune_tpu/obs/device.py, ISSUE 13):
cost/memory harvest on the CPU backend, peak-table resolution by
device_kind substring with unknown-device fallback, the instrument
seam's AOT harvest + disabled-path no-op contract, persistent
compile-cache hit/miss attribution, driver StepStats compile fields,
the `ut top` device panel, and the `ut report` "Device & compile"
section."""
import json
import os

import jax
import jax.numpy as jnp
import pytest

import uptune_tpu
from uptune_tpu import obs
from uptune_tpu.obs import device

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    uptune_tpu.__file__)))


@pytest.fixture(autouse=True)
def obs_clean():
    obs.reset()
    yield
    obs.reset()


# --------------------------------------------------------- peak table
class TestPeakTable:
    def test_resolution_by_substring(self):
        assert device.resolve_peaks("TPU v4") == (275e12, 1200e9)
        assert device.resolve_peaks("TPU v5 lite") == (197e12, 819e9)
        # case-insensitive, anywhere in the kind string
        assert device.resolve_peaks("Cloud TPU V5P pod") == \
            (459e12, 2765e9)

    def test_unknown_device_fallback(self):
        """A device the table doesn't know gets NO roofline claims —
        not a made-up estimate (the CPU-fallback honesty rule)."""
        assert device.resolve_peaks("Banana 9000") is None
        assert device.resolve_peaks("") is None
        assert device.resolve_peaks(None) is None
        assert device.utilization("cpu", 1e9, 1e9) == {}

    def test_utilization_fields(self):
        u = device.utilization("TPU v4", 275e11, 120e9)
        assert u["peak_flops_per_s"] == 275e12
        assert u["peak_hbm_bytes_per_s"] == 1200e9
        assert u["mxu_util"] == pytest.approx(0.1)
        assert u["hbm_util"] == pytest.approx(0.1)
        # peaks present, rates absent: utilization keys omitted
        u2 = device.utilization("TPU v4")
        assert "mxu_util" not in u2 and "peak_flops_per_s" in u2


# ------------------------------------------------------------ harvest
class TestHarvest:
    def test_cpu_backend_fields_present(self):
        """XLA exposes cost_analysis AND memory_analysis on the CPU
        backend: the full schema must come back populated."""
        fn = jax.jit(lambda x: jnp.sin(x) @ x.T)
        rec = device.harvest(fn.lower(jnp.ones((16, 16))).compile())
        device.validate_record(rec)
        assert rec["flops"] > 0
        assert rec["bytes_accessed"] > 0
        assert rec["arith_intensity"] == pytest.approx(
            rec["flops"] / rec["bytes_accessed"], rel=1e-3)
        pm = rec["peak_memory"]
        assert pm["argument_bytes"] == 16 * 16 * 4
        assert pm["output_bytes"] == 16 * 16 * 4

    def test_schema_rejects_malformed(self):
        ok = {"flops": 1.0, "bytes_accessed": 2.0,
              "transcendentals": None, "arith_intensity": 0.5,
              "peak_memory": None}
        device.validate_record(ok)
        with pytest.raises(ValueError):
            device.validate_record({**ok, "flops": -1.0})
        with pytest.raises(ValueError):
            device.validate_record(
                {k: v for k, v in ok.items() if k != "bytes_accessed"})
        with pytest.raises(ValueError):
            device.validate_record(
                {**ok, "peak_memory": {"temp_bytes": "big"}})
        with pytest.raises(ValueError):
            device.validate_record([ok])

    def test_harvest_tolerates_opaque_object(self):
        """A backend without the analyses yields the all-None schema,
        never a raise."""
        rec = device.harvest(object())
        device.validate_record(rec)
        assert rec["flops"] is None and rec["peak_memory"] is None


# --------------------------------------------------------- instrument
class TestInstrument:
    def test_disabled_path_is_noop(self):
        """With tracing off the wrapper calls through: no spans, no
        metrics, no registry entry — and the span layer underneath is
        the shared no-op singleton."""
        assert not obs.enabled()
        f = obs.instrument_device_fn(
            jax.jit(lambda x: x.sum()), "dev.off")
        assert float(f(jnp.ones((8,)))) == 8.0
        assert obs.span("x") is obs.device_span("y")   # shared NOOP
        assert device.programs() == {}
        assert obs.metrics_snapshot()["counters"] == {}
        assert obs.snapshot()["events"] == []

    def test_enabled_harvests_at_compile_time(self):
        """First traced call: ONE engine.compile span, the cost model
        harvested into the registry, device.* gauges published; later
        calls reuse the AOT executable (no second compile)."""
        obs.enable()
        f = obs.instrument_device_fn(
            jax.jit(lambda x: jnp.cos(x).sum()), "dev.fresh")
        x = jnp.ones((32,))
        r1, r2 = float(f(x)), float(f(x))
        assert r1 == r2
        rec = device.programs()["dev.fresh"]
        device.validate_record(rec["cost"])
        assert rec["compiles"] == 1 and rec["dispatches"] == 2
        m = obs.metrics_snapshot()
        assert m["counters"]["device.compiles"] == 1
        assert m["counters"]["device.dispatches"] == 2
        assert m["gauges"]["device.flops.dev.fresh"] > 0
        assert m["gauges"]["device.programs"] == 1
        spans = [e for e in obs.snapshot()["events"]
                 if e["name"] == "engine.compile"]
        assert len(spans) == 1
        assert spans[0]["attrs"]["program"] == "dev.fresh"
        assert spans[0]["attrs"]["cache"] in ("hit", "miss", "off")
        assert device.compile_totals()[0] == 1
        assert device.compile_totals()[1] > 0

    def test_warm_program_is_not_relowered(self):
        """A program first called while tracing was OFF must never be
        lowered again on enable (a second trace would break the strict
        trace-guard contract): dispatch telemetry only."""
        f = obs.instrument_device_fn(
            jax.jit(lambda x: x * 3.0), "dev.warm")
        x = jnp.ones((4,))
        f(x)                        # warm, untraced
        obs.enable()
        f(x)
        rec = device.programs()["dev.warm"]
        assert rec["cost"] is None and rec["compiles"] == 0
        assert rec["dispatches"] == 1
        assert not any(e["name"] == "engine.compile"
                       for e in obs.snapshot()["events"])

    def test_donation_preserved_through_aot_path(self):
        obs.enable()
        f = obs.instrument_device_fn(
            jax.jit(lambda s: s + 1.0, donate_argnums=(0,)),
            "dev.donate")
        x = jnp.ones((8,))
        y = f(x)
        assert float(y[0]) == 2.0
        assert x.is_deleted(), "donated input must be consumed"

    def test_aval_drift_falls_back_to_jit(self):
        """The engine plane's avals are fixed by design, but a caller
        that does vary shapes must get correct results: the AOT
        executable's TypeError routes back to the jit wrapper."""
        obs.enable()
        f = obs.instrument_device_fn(
            jax.jit(lambda x: x * 2.0), "dev.drift")
        assert float(f(jnp.ones((4,))).sum()) == 8.0
        assert float(f(jnp.ones((6,))).sum()) == 12.0
        assert float(f(jnp.ones((6,))).sum()) == 12.0
        assert device.programs()["dev.drift"]["dispatches"] == 3

    def test_lower_is_forwarded(self):
        f = obs.instrument_device_fn(
            jax.jit(lambda x: x - 1.0), "dev.lower")
        compiled = f.lower(jnp.ones((4,))).compile()
        rec = device.harvest(compiled)
        device.validate_record(rec)

    def test_record_window_publishes_roofline_gauges(self):
        obs.enable()
        f = obs.instrument_device_fn(
            jax.jit(lambda x: jnp.sin(x) @ x.T), "dev.win")
        jax.block_until_ready(f(jnp.ones((32, 32))))
        out = device.record_window("dev.win", 1e-3,
                                   device_kind="TPU v4")
        assert out["achieved_flops_per_s"] > 0
        assert out["peak_flops_per_s"] == 275e12
        assert "mxu_util" in out and "hbm_util" in out
        g = obs.metrics_snapshot()["gauges"]
        assert g["device.achieved_flops_per_s.dev.win"] == \
            out["achieved_flops_per_s"]
        # aggregate (last-window) copies ride alongside for `ut top`
        assert g["device.achieved_flops_per_s"] == \
            out["achieved_flops_per_s"]
        # unknown program / untraced: inert
        assert device.record_window("nope", 1.0) == {}
        obs.reset()
        assert device.record_window("dev.win", 1.0) == {}


class TestCompileCacheAttribution:
    @pytest.fixture
    def cache_dir(self, tmp_path):
        cfg = jax.config
        old = (cfg.jax_compilation_cache_dir,
               cfg.jax_persistent_cache_min_compile_time_secs,
               cfg.jax_persistent_cache_min_entry_size_bytes)
        cfg.update("jax_compilation_cache_dir", str(tmp_path))
        cfg.update("jax_persistent_cache_min_compile_time_secs", 0)
        cfg.update("jax_persistent_cache_min_entry_size_bytes", 0)
        yield tmp_path
        cfg.update("jax_compilation_cache_dir", old[0])
        cfg.update("jax_persistent_cache_min_compile_time_secs", old[1])
        cfg.update("jax_persistent_cache_min_entry_size_bytes", old[2])

    def test_miss_then_hit(self, cache_dir):
        """Two instrumented wrappers over the SAME computation: the
        first compile MISSES the (fresh) persistent cache and writes
        it, the second is served from disk — attributed per program
        and in the device.* counters."""
        obs.enable()
        x = jnp.ones((64,))
        fa = obs.instrument_device_fn(
            jax.jit(lambda x: jnp.tanh(x) * 1.5), "dev.cache.a")
        fa(x)
        fb = obs.instrument_device_fn(
            jax.jit(lambda x: jnp.tanh(x) * 1.5), "dev.cache.b")
        fb(x)
        progs = device.programs()
        assert progs["dev.cache.a"]["cache"] == "miss", progs
        assert progs["dev.cache.b"]["cache"] == "hit", progs
        c = obs.metrics_snapshot()["counters"]
        assert c["device.compile_cache_misses"] >= 1
        assert c["device.compile_cache_hits"] >= 1
        spans = {e["attrs"]["program"]: e["attrs"]["cache"]
                 for e in obs.snapshot()["events"]
                 if e["name"] == "engine.compile"}
        assert spans == {"dev.cache.a": "miss", "dev.cache.b": "hit"}


# --------------------------------------------------- driver StepStats
class TestDriverStepStats:
    def test_first_ticket_carries_compiles(self):
        """With tracing on from construction, the first ticket's
        window reports the arm programs' compiles (n_compiles > 0,
        t_compile > 0); steady-state tickets report ~0.  Untraced
        runs keep zeros."""
        from uptune_tpu.driver import Tuner
        from uptune_tpu.workloads import (rosenbrock_objective,
                                          rosenbrock_space)
        obs.enable()
        t = Tuner(rosenbrock_space(2, -2.0, 2.0),
                  rosenbrock_objective(2), seed=0,
                  technique="DifferentialEvolution")
        first = t.step()
        later = t.step()
        res = t.result()
        t.close()
        assert first.n_compiles >= 3          # propose+commit+observe
        assert first.t_compile > 0
        assert later.n_compiles == 0 and later.t_compile == 0.0
        assert res.t_compile == pytest.approx(
            first.t_compile + later.t_compile)
        progs = device.programs()
        assert "driver.commit" in progs
        assert any(k.startswith("driver.propose.") for k in progs)


# ------------------------------------------------------ profiler dump
class TestDeviceTrace:
    def test_capture_and_export_reference(self, tmp_path):
        """start_trace/stop_trace wrap jax.profiler: the XPlane dump
        lands under the dir, and a Chrome-trace export written while
        the capture ran references it (otherData.device_trace) — the
        combined-Perfetto-view contract."""
        obs.enable()
        d = str(tmp_path / "devtrace")
        assert device.start_trace(d) == d
        assert device.start_trace(d) == d      # idempotent while active
        jax.block_until_ready(jnp.ones((64, 64)) @ jnp.ones((64, 64)))
        assert device.stop_trace() == d
        dumps = [f for root, _, files in os.walk(d) for f in files
                 if f.endswith(".xplane.pb")]
        assert dumps, "profiler dump missing"
        doc = obs.chrome_trace()
        assert doc["otherData"]["device_trace"] == d
        obs.validate_trace(doc)

    def test_env_gate(self, tmp_path, monkeypatch):
        monkeypatch.setenv("UT_DEVICE_TRACE", "off")
        assert device.maybe_trace_from_env() is None
        monkeypatch.delenv("UT_DEVICE_TRACE")
        assert device.maybe_trace_from_env() is None


# ----------------------------------------------------- top and report
class TestTopDevicePanel:
    def test_panel_renders_values_and_dashes(self):
        from uptune_tpu.obs import top
        s_empty = top.Sample(0.0, {}, {}, {})
        frame = top.render(None, s_empty, "x")
        dev_line = next(ln for ln in frame.splitlines()
                        if ln.startswith("device"))
        roof_line = next(ln for ln in frame.splitlines()
                         if ln.startswith("roofline"))
        assert "—" in dev_line and "—" in roof_line
        s = top.Sample(
            10.0,
            {"device.compiles": 4, "device.compile_cache_hits": 3,
             "device.compile_cache_misses": 1,
             "device.dispatches": 500},
            {"device.programs": 4,
             "device.achieved_flops_per_s": 2.2e12,
             "device.mxu_util": 0.008, "device.hbm_util": 0.41,
             "device.arith_intensity": 0.37},
            {"device.compile_ms": {"count": 4, "sum": 1234.5}},
            deltas={"device.dispatches": 100}, dt=2.0)
        frame = top.render(None, s, "x")
        assert "compiles 4 (1,234 ms)" in frame
        assert "cache hit/miss 3/1" in frame
        assert "dispatches/s 50.0" in frame
        assert "MXU 0.008000" in frame and "HBM 0.4100" in frame

    def test_json_frame_carries_device_family(self):
        """`ut top --json` frames are the raw counters/gauges — the
        device.* family rides through untouched."""
        from uptune_tpu.obs import top
        row = {"t": 1.0, "dt": 1.0,
               "counters": {"device.dispatches": 7},
               "deltas": {"device.dispatches": 7},
               "gauges": {"device.programs": 2}, "hists": {}}
        s = top.sample_from_row(row)
        assert s.counters["device.dispatches"] == 7
        assert s.gauges["device.programs"] == 2
        assert top.rates(None, s)["device.dispatches"] == 7.0


class TestReportDeviceSection:
    def _metrics_file(self, tmp_path, gauges, counters):
        p = tmp_path / "m.metrics.jsonl"
        row = {"t": 1.0, "dt": 1.0, "counters": counters,
               "deltas": dict(counters), "gauges": gauges,
               "hists": {"device.compile_ms":
                         {"count": 2, "sum": 321.0}}}
        p.write_text(json.dumps(row) + "\n")
        return str(p)

    def test_section_present_with_device_telemetry(self, tmp_path):
        from uptune_tpu.obs import report
        met = report.summarize_metrics(self._metrics_file(
            tmp_path,
            {"device.flops.engine.run": 1e9,
             "device.bytes.engine.run": 4e9,
             "device.arith_intensity.engine.run": 0.25,
             "device.compile_ms.engine.run": 321.0,
             "device.achieved_flops_per_s": 5e8,
             "device.mxu_util": 0.002},
            {"device.compiles": 2, "device.compile_cache_hits": 1,
             "device.compile_cache_misses": 1,
             "device.dispatches": 10}))
        dev = report.device_summary(met)
        assert dev["programs"]["engine.run"]["flops"] == 1e9
        assert dev["compile"]["compiles"] == 2
        assert dev["compile"]["compile_ms_total"] == 321.0
        assert dev["roofline"]["mxu_util"] == 0.002
        # both renderers carry the section (journal can be minimal)
        jp = tmp_path / "j.jsonl"
        jp.write_text(json.dumps(
            {"v": 1, "origin_unix": 0.0, "meta": {}}) + "\n")
        header, rows = obs.journal.read(str(jp))
        an = report.analyze(header, rows)
        md = report.render_markdown(an, met)
        assert "## Device & compile" in md
        assert "engine.run" in md
        html = report.render_html(an, met)
        assert "Device &amp; compile" in html
        assert "compile-cache hits" in html

    def test_section_absent_without_device_telemetry(self, tmp_path):
        from uptune_tpu.obs import report
        met = report.summarize_metrics(self._metrics_file(
            tmp_path, {"serve.batch_fill": 1.0}, {"serve.asks": 5}))
        assert report.device_summary(met) is None
        assert report.device_summary(None) is None
        jp = tmp_path / "j.jsonl"
        jp.write_text(json.dumps(
            {"v": 1, "origin_unix": 0.0, "meta": {}}) + "\n")
        header, rows = obs.journal.read(str(jp))
        md = report.render_markdown(report.analyze(header, rows), met)
        assert "Device & compile" not in md
