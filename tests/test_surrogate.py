"""Surrogate layer: GP + MLP-ensemble quality, acquisition functions, and
multivoting prune integration with the Tuner."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uptune_tpu.driver import Tuner
from uptune_tpu.space.params import FloatParam, PermParam
from uptune_tpu.space.spec import Space
from uptune_tpu.surrogate import SurrogateManager, gp, mlp
from uptune_tpu.workloads import (rosenbrock_device, rosenbrock_objective,
                                  rosenbrock_space)


def _train_data(n=256, f=4, seed=0):
    k = jax.random.PRNGKey(seed)
    x = jax.random.uniform(k, (n, f))
    y = ((x - 0.3) ** 2).sum(-1) + 0.05 * jnp.sin(10 * x[:, 0])
    return x, y


class TestGP:
    def test_fit_predict_interpolates(self):
        x, y = _train_data()
        st = gp.fit(x, y)
        mu, sd = gp.predict(st, x[:32])
        np.testing.assert_allclose(np.asarray(mu), np.asarray(y[:32]),
                                   atol=0.1)
        assert (np.asarray(sd) >= 0).all()

    def test_rank_correlation_on_heldout(self):
        x, y = _train_data(300)
        st = gp.fit(x[:256], y[:256])
        mu, _ = gp.predict(st, x[256:])
        got, want = np.asarray(mu), np.asarray(y[256:])
        # Spearman via rank correlation
        r1 = np.argsort(np.argsort(got)).astype(float)
        r2 = np.argsort(np.argsort(want)).astype(float)
        rho = np.corrcoef(r1, r2)[0, 1]
        assert rho > 0.8, rho

    def test_ei_prefers_promising(self):
        x, y = _train_data()
        st = gp.fit(x, y)
        good = jnp.full((1, 4), 0.3)   # near the optimum
        bad = jnp.full((1, 4), 0.95)
        ei = gp.expected_improvement(st, jnp.concatenate([good, bad]),
                                     jnp.min(y))
        assert float(ei[0]) >= float(ei[1])

    def test_nonfinite_targets_clamped(self):
        x, y = _train_data(64)
        y = y.at[0].set(jnp.inf)
        st = gp.fit(x, y)
        mu, _ = gp.predict(st, x[:8])
        assert np.isfinite(np.asarray(mu)).all()

    def test_subsample_keeps_best(self):
        x, y = _train_data(512)
        xs, ys = gp.subsample(jax.random.PRNGKey(0), x, y, 128)
        assert xs.shape == (128, 4)
        assert float(ys.min()) == float(y.min())


class TestMLP:
    def test_ensemble_fit_and_disagreement(self):
        x, y = _train_data(256)
        st = mlp.fit(jax.random.PRNGKey(0), x, y, n_members=4, steps=200)
        preds = mlp.predict_members(st, x[:64])
        assert preds.shape == (4, 64)
        mu, sd = mlp.predict(st, x[:64])
        err = float(jnp.abs(mu - y[:64]).mean())
        assert err < 0.3, err
        assert float(sd.mean()) > 0


class TestManager:
    def _space(self):
        return rosenbrock_space(2, -3.0, 3.0)

    def test_not_fitted_below_min_points(self):
        m = SurrogateManager(self._space(), "gp", min_points=64)
        m.observe(np.random.rand(10, 2), np.random.rand(10))
        assert not m.maybe_refit()
        assert m.keep_mask(self._space().random(
            jax.random.PRNGKey(0), 8)) is None

    @pytest.mark.parametrize("kind", ["gp", "mlp"])
    def test_prune_rejects_bad_keeps_good(self, kind):
        space = self._space()
        key = jax.random.PRNGKey(0)
        cands = space.random(key, 512)
        feats = np.asarray(space.features(cands))
        qor = np.asarray(rosenbrock_device(space.decode_scalars(cands.u)))
        m = SurrogateManager(space, kind, min_points=64, explore_frac=0.0,
                             n_members=4)
        m.observe(feats, qor)
        assert m.maybe_refit()
        probe = space.random(jax.random.PRNGKey(1), 256)
        keep = m.keep_mask(probe)
        pq = np.asarray(rosenbrock_device(space.decode_scalars(probe.u)))
        assert keep is not None and 0 < keep.sum() < len(keep)
        # kept candidates should be substantially better on average
        assert pq[keep].mean() < pq[~keep].mean()

    def test_explore_fraction_keeps_some(self):
        space = self._space()
        m = SurrogateManager(space, "gp", min_points=32, explore_frac=1.0)
        cands = space.random(jax.random.PRNGKey(0), 128)
        m.observe(np.asarray(space.features(cands)),
                  np.random.rand(128))
        m.maybe_refit()
        keep = m.keep_mask(space.random(jax.random.PRNGKey(1), 64))
        assert keep.all()  # explore_frac=1.0 keeps everything


@pytest.mark.slow
class TestTunerIntegration:
    @pytest.mark.parametrize("kind", ["gp", "mlp"])
    def test_tuner_with_surrogate_converges(self, kind):
        space = rosenbrock_space(2, -3.0, 3.0)
        t = Tuner(space, rosenbrock_objective(2), seed=3, surrogate=kind,
                  surrogate_opts=dict(min_points=96, refit_interval=96,
                                      n_members=3))
        res = t.run(test_limit=900)
        assert res.best_qor < 2.0, res.best_qor
        assert t.pruned_total > 0, "surrogate never pruned anything"
        # pruned candidates are not archived/evaluated
        assert res.evals <= 900 + 200


class TestMixedKernel:
    """Discrete-aware surrogate representation + product kernel
    (VERDICT r3 next-step #2): categorical lanes one-hot with Hamming
    semantics, numeric lanes snapped to their decoded grid."""

    def _space(self):
        from uptune_tpu.space.params import EnumParam, IntParam
        return Space(
            [EnumParam(f"f{i}", ("default", "on", "off")) for i in range(6)]
            + [IntParam("p0", 0, 100), IntParam("p1", 0, 10)])

    def test_transform_shapes_and_split(self):
        sp = self._space()
        assert sp.n_cat == 6
        assert sp.n_cont_features == 2
        key = jax.random.PRNGKey(0)
        cands = sp.random(key, 5)
        sf = sp.surrogate_transform(sp.features(cands))
        assert sf.shape == (5, sp.n_surrogate_features)
        assert sp.n_surrogate_features == 2 + 6 * 3

    def test_onehot_distance_is_hamming(self):
        sp = self._space()
        a = sp.from_configs([{**{f"f{i}": "default" for i in range(6)},
                              "p0": 50, "p1": 5}])
        b = sp.from_configs([{**{f"f{i}": "default" for i in range(6)},
                              "f0": "on", "f3": "off", "p0": 50, "p1": 5}])
        fa = sp.surrogate_transform(sp.features(a))
        fb = sp.surrogate_transform(sp.features(b))
        d2 = float(((fa - fb) ** 2).sum())
        # two flags differ -> squared distance exactly 2 (Hamming count)
        np.testing.assert_allclose(d2, 2.0, atol=1e-5)

    def test_numeric_lanes_snap_to_grid(self):
        sp = self._space()
        cands = sp.random(jax.random.PRNGKey(1), 64)
        sf = sp.surrogate_transform(sp.features(cands))
        # p1 has 11 codes: snapped unit values live on the 11-point
        # encode grid (code + 0.5)/11 — i.e. decoding them recovers
        # exact integers
        codes = np.asarray(sf[:, 1]) * 11.0 - 0.5
        np.testing.assert_allclose(codes, np.round(codes), atol=1e-4)
        assert len(np.unique(np.round(codes))) <= 11

    def test_mixed_gp_learns_flag_effect(self):
        """y depends on one flag + one int; the mixed kernel must rank a
        held-out set well despite 5 noise flags."""
        sp = self._space()
        rng = np.random.RandomState(0)
        cfgs = [{**{f"f{i}": rng.choice(["default", "on", "off"])
                    for i in range(6)},
                 "p0": int(rng.randint(101)), "p1": int(rng.randint(11))}
                for _ in range(120)]
        y = np.asarray([10.0 * (c["f2"] == "on") + 0.05 * c["p0"]
                        + 2.0 * (c["f4"] == "off") for c in cfgs],
                       np.float32)
        cands = sp.from_configs(cfgs)
        feats = sp.surrogate_transform(sp.features(cands))
        nc, ncat = sp.n_cont_features, sp.n_cat
        st = gp.fit_auto(feats[:96], jnp.asarray(y[:96]),
                         n_cont=nc, n_cat=ncat)
        mu, _ = gp.predict(st, feats[96:], n_cont=nc, n_cat=ncat)
        got, want = np.asarray(mu), y[96:]
        r1 = np.argsort(np.argsort(got)).astype(float)
        r2 = np.argsort(np.argsort(want)).astype(float)
        rho = np.corrcoef(r1, r2)[0, 1]
        assert rho > 0.8, rho

    def test_default_args_reproduce_pure_matern(self):
        """n_cont=None keeps the exact pre-mixed behavior."""
        x, y = _train_data()
        st_old = gp.fit(x, y)
        st_new = gp.fit(x, y, n_cont=None, n_cat=0)
        np.testing.assert_allclose(np.asarray(st_old.alpha),
                                   np.asarray(st_new.alpha), rtol=1e-6)

    def test_manager_pool_flip_moves_on_cat_space(self):
        """propose_pool on a categorical-heavy space emits novel
        candidates that are mostly small Hamming distances from the
        incumbent (flag flips), not uniform jumps."""
        sp = self._space()
        m = SurrogateManager(sp, "gp", min_points=16, refit_interval=16,
                             propose_batch=8, pool_mult=16, seed=0)
        rng = np.random.RandomState(1)
        cfgs = [{**{f"f{i}": rng.choice(["default", "on", "off"])
                    for i in range(6)},
                 "p0": int(rng.randint(101)), "p1": int(rng.randint(11))}
                for _ in range(32)]
        y = np.asarray([10.0 * (c["f2"] == "on") + 0.05 * c["p0"]
                        for c in cfgs], np.float32)
        cands = sp.from_configs(cfgs)
        m.observe(np.asarray(sp.features(cands)), y)
        assert m.maybe_refit()
        best_i = int(np.argmin(y))
        out = m.propose_pool(jax.random.PRNGKey(2),
                             cands.u[best_i], (), float(y[best_i]))
        assert out is not None and out.u.shape[0] == 8


class TestSurrogateActivityGuards:
    """Two measured guards (BENCHREPORT gcc-real analysis): the
    observation gate `min_model_points` (explicit knob, inert by
    default) and the run-budget `passive` rule the driver applies when
    the eval budget is smaller than the parameter count."""

    def _mgr(self, space, **kw):
        return SurrogateManager(space, "gp", min_points=16,
                                refit_interval=16, propose_batch=8,
                                pool_mult=16, seed=0, **kw)

    def _cat_space(self, n=40):
        from uptune_tpu.space.params import EnumParam
        return Space([EnumParam(f"f{i}", ("a", "b", "c"))
                      for i in range(n)])

    def test_observation_gate_suppresses_prune_and_pool(self):
        sp = self._cat_space()
        m = self._mgr(sp, min_model_points=40)
        rng = np.random.RandomState(0)
        cands = sp.random(jax.random.PRNGKey(0), 32)
        y = rng.rand(32).astype(np.float32)
        m.observe(np.asarray(sp.features(cands)), y)
        assert m.maybe_refit()          # it still fits...
        assert m.fitted
        assert m.keep_mask(cands) is None           # ...but won't veto
        assert m.propose_pool(jax.random.PRNGKey(1), cands.u[0], (),
                              1.0) is None          # ...or propose
        # past the gate both activate
        cands2 = sp.random(jax.random.PRNGKey(2), 32)
        m.observe(np.asarray(sp.features(cands2)),
                  rng.rand(32).astype(np.float32))
        m.maybe_refit()
        assert m.keep_mask(cands2) is not None
        assert m.propose_pool(jax.random.PRNGKey(3), cands2.u[0], (),
                              1.0) is not None

    def test_default_gate_is_inert(self):
        # gating on observations by default COSTS evals where guidance
        # from min_points already pays (gcc-options probe: 1553 gated
        # vs 1046.5 ungated median) — default must stay min_points
        m = self._mgr(self._cat_space(200))
        assert m.min_model_points == 16

    def test_budget_rule_selects_bandit_recipe(self):
        """r4 verdict #4: when the eval budget is below the parameter
        count and the plane CAN be bandit-arbitrated, the driver now
        applies the measured-best budget-constrained recipe (bandit
        arbitration, affordable non-parity pulls) instead of
        passivating."""
        import warnings

        sp = self._cat_space(40)

        def obj(cfgs):
            return [1.0 for _ in cfgs]

        t = Tuner(sp, obj, seed=0, surrogate="gp",
                  surrogate_opts={"min_points": 16, "propose_batch": 8})
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            t.run(test_limit=20)    # 20 < 40 scalar params
        t.close()
        assert not t.surrogate.passive
        assert t._surr_arm
        assert t.surrogate.arbitration == "bandit"
        assert t.surrogate.propose_batch == 8       # parity off
        assert any("BUDGET-CONSTRAINED" in str(x.message) for x in w)

    def test_budget_rule_passivates_without_plane(self):
        """With the proposal plane disabled (propose_batch=0) the
        budget-constrained recipe cannot engage; the rule falls back to
        passivation, the measured-safe default."""
        import warnings

        sp = self._cat_space(40)

        def obj(cfgs):
            return [1.0 for _ in cfgs]

        t = Tuner(sp, obj, seed=0, surrogate="gp",
                  surrogate_opts={"min_points": 16, "propose_batch": 0})
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            t.run(test_limit=20)    # 20 < 40 scalar params
        t.close()
        assert t.surrogate.passive
        assert not t._surr_arm
        assert any("PASSIVE" in str(x.message) for x in w)

    def test_budget_rule_respects_opt_out_and_big_budgets(self):
        sp = self._cat_space(40)

        def obj(cfgs):
            return [1.0 for _ in cfgs]

        t = Tuner(sp, obj, seed=0, surrogate="gp",
                  surrogate_opts={"min_points": 16,
                                  "auto_passive": False})
        t._apply_budget_rule(20)
        assert not t.surrogate.passive
        t.close()
        t2 = Tuner(sp, obj, seed=0, surrogate="gp",
                   surrogate_opts={"min_points": 16})
        t2._apply_budget_rule(4000)   # budget >> params: stays active
        assert not t2.surrogate.passive
        t2.close()

    def test_budget_rule_is_per_run(self):
        """A later large-budget run on the same tuner re-activates what
        the rule itself passivated (r4 review: the flag must not stick);
        user-set passive flags are left alone."""
        sp = self._cat_space(40)
        t = Tuner(sp, lambda cfgs: [1.0] * len(cfgs), seed=0,
                  surrogate="gp", surrogate_opts={"min_points": 16})
        t._apply_budget_rule(20)
        assert t.surrogate.passive
        t._apply_budget_rule(4000)
        assert not t.surrogate.passive      # rule-set flag cleared
        t.surrogate.passive = True          # user-set
        t._apply_budget_rule(4000)
        assert t.surrogate.passive          # left alone
        t.close()


def test_mixed_kernel_with_permutation_block():
    """Perm position lanes live in the CONTINUOUS block of the
    surrogate representation; a space with perms + enums + ints must
    fit, score, and pool-propose without shape drift."""
    from uptune_tpu.space.params import EnumParam, IntParam, PermParam
    sp = Space([PermParam("tour", items=tuple(range(6)))]
               + [EnumParam(f"f{i}", ("a", "b", "c")) for i in range(5)]
               + [IntParam("p", 0, 9)])
    assert sp.n_cat == 5
    assert sp.n_cont_features == 1 + 6      # int lane + 6 perm positions
    cands = sp.random(jax.random.PRNGKey(0), 48)
    feats = sp.surrogate_transform(sp.features(cands))
    assert feats.shape == (48, sp.n_surrogate_features)
    y = jnp.asarray(np.random.RandomState(0).rand(48), jnp.float32)
    st = gp.fit_auto(feats, y, n_cont=sp.n_cont_features, n_cat=sp.n_cat)
    mu, sd = gp.predict(st, feats[:8], sp.n_cont_features, sp.n_cat)
    assert np.isfinite(np.asarray(mu)).all()
    assert (np.asarray(sd) >= 0).all()
    m = SurrogateManager(sp, "gp", min_points=16, refit_interval=16,
                         propose_batch=4, pool_mult=8, seed=0)
    m.observe(np.asarray(sp.features(cands)), np.asarray(y))
    assert m.maybe_refit()
    out = m.propose_pool(jax.random.PRNGKey(1), cands.u[0],
                         tuple(p[0] for p in cands.perms), 0.5)
    assert out is not None and out.u.shape[0] == 4
    # proposed permutations are valid orderings
    for row in np.asarray(out.perms[0]):
        assert sorted(row.tolist()) == list(range(6))
