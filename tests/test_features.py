"""EDA report extractor tests on synthetic report files (the reference
parsers, report.py:122-174 + add/features.py:4-80, were exercised only
against licensed-tool output)."""
import textwrap

import pytest

from uptune_tpu.api import constraint as C
from uptune_tpu.api.features import (get_syn_features, get_timing,
                                     get_utilization, quartus, vhls)

VHLS_XML = textwrap.dedent("""\
    <profile>
      <ReportVersion><Version>2019.1</Version></ReportVersion>
      <UserAssignments>
        <ProductFamily>zynq</ProductFamily>
        <Part>xc7z020clg484-1</Part>
        <TopModelName>top_fn</TopModelName>
        <TargetClockPeriod>10.00</TargetClockPeriod>
        <unit>ns</unit>
      </UserAssignments>
      <PerformanceEstimates>
        <SummaryOfTimingAnalysis>
          <EstimatedClockPeriod>8.70</EstimatedClockPeriod>
        </SummaryOfTimingAnalysis>
        <SummaryOfOverallLatency>
          <Best-caseLatency>1000</Best-caseLatency>
          <Worst-caseLatency>2000</Worst-caseLatency>
          <Interval-min>1001</Interval-min>
          <Interval-max>2001</Interval-max>
        </SummaryOfOverallLatency>
      </PerformanceEstimates>
      <AreaEstimates>
        <Resources>
          <BRAM_18K>12</BRAM_18K><DSP48E>20</DSP48E>
          <FF>4001</FF><LUT>8002</LUT>
        </Resources>
        <AvailableResources>
          <BRAM_18K>280</BRAM_18K><DSP48E>220</DSP48E>
          <FF>106400</FF><LUT>53200</LUT>
        </AvailableResources>
      </AreaEstimates>
    </profile>
""")


@pytest.fixture(autouse=True)
def clean(monkeypatch, tmp_path):
    monkeypatch.delenv("UT_BEFORE_RUN_PROFILE", raising=False)
    monkeypatch.setenv("UT_WORK_DIR", str(tmp_path))
    C.REGISTRY.clear()
    from uptune_tpu.api.state import STATE
    STATE.reset()
    yield
    C.REGISTRY.clear()


class TestVhls:
    def test_parse(self, tmp_path):
        p = tmp_path / "csynth.xml"
        p.write_text(VHLS_XML)
        res = vhls(str(p))
        assert res["part"] == "xc7z020clg484-1"
        assert res["top"] == "top_fn"
        assert res["estimated_cp"] == pytest.approx(8.70)
        assert res["latency_max"] == 2000
        assert res["lut_used"] == 8002
        assert res["lut_util_pct"] == pytest.approx(15.04)
        assert res["dsp48e_used"] == 20

    def test_target_key(self, tmp_path):
        p = tmp_path / "csynth.xml"
        p.write_text(VHLS_XML)
        assert vhls(str(p), target="latency_min") == 1000

    def test_register_covariates(self, tmp_path):
        p = tmp_path / "csynth.xml"
        p.write_text(VHLS_XML)
        vhls(str(p), register=True)
        assert C.REGISTRY.nodes["vhls_lut_used"].value == 8002

    def test_missing_file(self):
        with pytest.raises(RuntimeError, match="csyn"):
            vhls("/nonexistent/report.xml")


def _write_quartus_reports(d, design="mm"):
    (d / f"{design}.sta.syn.summary").write_text(
        "Type  : setup\nSlack : -0.123\nTNS : -45,6\n")
    (d / f"{design}.syn.rpt").write_text(
        "; boundary_port ; 42 ;\n"
        "; fourteennm_ff ; 1,234 ;\n"
        "; Max LUT depth ; 7.50 ;\n")
    (d / f"{design}.fit.syn.summary").write_text(
        "Logic utilization (in ALMs) : 1,024 / 100,000\n"
        "Total pins : 12\n"
        "Total RAM Blocks : 3 / 99\n")


class TestQuartus:
    def test_low_level_parsers(self, tmp_path):
        _write_quartus_reports(tmp_path)
        slack, tns = get_timing("mm", str(tmp_path), "syn")
        assert slack == pytest.approx(-0.123)
        assert tns == pytest.approx(-456.0)
        syn = get_syn_features("mm", str(tmp_path))
        assert syn["boundary_port"] == 42
        assert syn["fourteennm_ff"] == 1234
        assert syn["Max LUT depth"] == pytest.approx(7.5)
        fit = get_utilization("mm", str(tmp_path), "syn")
        assert fit["Logic utilization (in ALMs)"] == 1024
        assert fit["Total pins"] == 12
        assert fit["Total RAM Blocks"] == 3

    def test_aggregate_and_register(self, tmp_path):
        _write_quartus_reports(tmp_path)
        vec = quartus("mm", str(tmp_path))
        assert vec["slack"] == pytest.approx(-0.123)
        assert vec["boundary_port"] == 42
        assert C.REGISTRY.nodes["Total pins"].value == 12

    def test_target_and_missing_files(self, tmp_path):
        _write_quartus_reports(tmp_path)
        assert quartus("mm", str(tmp_path),
                       target="Total pins", register=False) == 12
        # empty dir: everything missing -> empty vector, no raise
        empty = tmp_path / "empty"
        empty.mkdir()
        assert quartus("mm", str(empty), register=False) == {}
