"""Sharded GP acquisition scoring (parallel/surrogate_shard.py):
candidates sharded over a mesh axis, fitted GPState replicated.  Every
score kind must agree exactly with the single-device computation — the
shard is the same math on a slice."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uptune_tpu.parallel import make_mesh, sharded_gp_score
from uptune_tpu.surrogate import gp


def _fitted(n=96, f=6, seed=0):
    k = jax.random.PRNGKey(seed)
    x = jax.random.uniform(k, (n, f))
    y = ((x - 0.4) ** 2).sum(-1) + 0.1 * jnp.sin(8 * x[:, 0])
    return gp.fit_auto(x, y), x, y


@pytest.fixture(scope="class")
def fitted_env(request):
    """One GP fit + candidate batch for the whole class: the fit is the
    expensive part and every test reads it immutably."""
    request.cls.mesh = make_mesh(n_search=1, n_eval=8)
    request.cls.state, request.cls.x, request.cls.y = _fitted()
    kq = jax.random.PRNGKey(9)
    request.cls.feats = jax.random.uniform(kq, (256, 6))


@pytest.mark.usefixtures("fitted_env")
class TestShardedScore:
    """Four kind-variants of ONE sharded-scoring path.  Tier-1 keeps
    the lcb variant (it exercises both the mean and the variance
    machinery); the mean/ei/thompson siblings are slow-marked for
    suite-budget headroom (ISSUE 6 — tier-1 runs ~810s of the 870s
    timeout)."""

    @pytest.mark.slow
    def test_mean_matches_dense(self):
        got = sharded_gp_score(self.mesh, "eval", self.state,
                               self.feats, kind="mean")
        want, _ = gp.predict(self.state, self.feats)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_ei_matches_dense(self):
        best = float(jnp.min(self.y))
        got = sharded_gp_score(self.mesh, "eval", self.state,
                               self.feats, kind="ei", best_y=best)
        want = gp.expected_improvement(self.state, self.feats,
                                       jnp.asarray(best))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_lcb_matches_dense(self):
        got = sharded_gp_score(self.mesh, "eval", self.state,
                               self.feats, kind="lcb", beta=1.5)
        want = gp.lower_confidence_bound(self.state, self.feats, 1.5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_thompson_shards_draw_independently(self):
        got = np.asarray(sharded_gp_score(
            self.mesh, "eval", self.state, self.feats, kind="thompson",
            key=jax.random.PRNGKey(3)))
        assert np.isfinite(got).all()
        # per-shard key folding: shard slices must not repeat each
        # other's draws (identical slices would mean a replicated key)
        s = got.reshape(8, -1)
        for i in range(1, 8):
            assert not np.allclose(s[0], s[i])

    def test_validation(self):
        with pytest.raises(ValueError, match="divisible"):
            sharded_gp_score(self.mesh, "eval", self.state,
                             self.feats[:100], kind="mean")
        with pytest.raises(ValueError, match="best_y"):
            sharded_gp_score(self.mesh, "eval", self.state,
                             self.feats, kind="ei")
        with pytest.raises(ValueError, match="unknown score"):
            sharded_gp_score(self.mesh, "eval", self.state,
                             self.feats, kind="ucb")

    def test_under_jit_on_search_eval_mesh(self):
        """Composes under jit on a 2-axis mesh (the engine's mesh
        shape), scoring over the eval axis."""
        mesh = make_mesh(n_search=2, n_eval=4)
        best = float(jnp.min(self.y))

        @jax.jit
        def score(feats):
            return sharded_gp_score(mesh, "eval", self.state, feats,
                                    kind="ei", best_y=best)

        got = score(self.feats)
        want = gp.expected_improvement(self.state, self.feats,
                                       jnp.asarray(best))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)


class TestMixedKernelShard:
    @pytest.mark.slow   # suite-budget (ISSUE 8): sharded-vs-dense
    # mixed-kernel parity; dense mixed-kernel coverage (test_surrogate)
    # and the sharded lcb parity above stay tier-1
    def test_cat_split_matches_dense(self):
        """A mixed-kernel GPState must score identically sharded vs
        dense when the n_cont/n_cat split is passed through (r4 review
        finding)."""
        from uptune_tpu.space.params import EnumParam, FloatParam
        from uptune_tpu.space.spec import Space

        mesh = make_mesh(n_search=1, n_eval=8)
        sp = Space([FloatParam("a", 0, 1), FloatParam("b", 0, 1)]
                   + [EnumParam(f"f{i}", ("x", "y", "z"))
                      for i in range(4)])
        k = jax.random.PRNGKey(3)
        cands = sp.random(k, 96)
        feats = sp.surrogate_transform(sp.features(cands))
        y = feats[:, 0] * 2 + feats[:, 2] - feats[:, 5]
        nc, ncat = sp.n_cont_features, sp.n_cat
        st = gp.fit_auto(feats, y, n_cont=nc, n_cat=ncat)
        q = sp.surrogate_transform(sp.features(
            sp.random(jax.random.PRNGKey(4), 64)))
        want = gp.lower_confidence_bound(st, q, n_cont=nc, n_cat=ncat)
        got = sharded_gp_score(mesh, "eval", st, q, kind="lcb",
                               n_cont=nc, n_cat=ncat)
        # rtol 5e-5, not 1e-5: both sides are float32 (eps ~1.2e-7) and
        # the sharded path reassociates the 96-term kernel/solve
        # reductions, so O(sqrt(n)*eps) ~ 1e-6/op accumulation over the
        # Cholesky solve chain legitimately reaches ~2e-5 relative
        # (observed 1.9e-5 on CPU); 5e-5 still catches any real
        # math/split-plumbing error, which shows up orders of magnitude
        # larger
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=5e-5, atol=1e-6)
