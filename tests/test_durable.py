"""Crash-safe serving tests (ISSUE 15: serve/durable.py, obs/faults.py,
client auto-resume, wire hardening — docs/SERVING.md "Durability &
failover").

Coverage map:
* fault-injection registry: schedule determinism (at/every), error
  and delay actions, the UT_FAULTS grammar, unknown-point rejection,
  the disarmed one-flag-check no-op
* CheckpointLog: record round trip, torn-tail tolerance mid-record,
  version-gap truncation, close-record reaping
* duplicate tell replay idempotence on the offline group: epoch-id
  squash (in-flight and committed), incarnation-token rejection
* WireServer hardening: request-line cap, idle-read timeout
* TelemetryShipper reconnect jitter bounds
* ResultStore fsync knob resolution (arg > UT_STORE_FSYNC > config)
* `serve-durable*` config keys + flag precedence
* recovery lifecycle (one in-process server pair, compile-heavy so
  grouped in a single test): replay parity, the commit-vs-append
  SIGKILL window's bounded-loss contract (the lost tail epoch
  re-fills from the store memo), restore of a signature with more
  survivors than one group's slots, torn checkpoint tail
* client auto-resume across a server restart on the same port
* `bench.py --failover --quick` end-to-end smoke (tier-1, the ISSUE
  requirement — a real `ut serve --durable` child crashed by a
  deterministic UT_FAULTS schedule, recovered under the strict guard)
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from uptune_tpu.api import session as api_session  # noqa: E402
from uptune_tpu.obs import faults  # noqa: E402
from uptune_tpu.obs.ship import backoff_jitter  # noqa: E402
from uptune_tpu.serve.durable import (  # noqa: E402
    CheckpointLog, decode_raw, encode_raw)
from uptune_tpu.serve.wire import WireServer  # noqa: E402

DIMS = 2


def _space():
    from uptune_tpu.workloads import rosenbrock_space
    return rosenbrock_space(DIMS, -3.0, 3.0)


def _measure(cfg):
    x = np.array([cfg[f"x{i}"] for i in range(DIMS)])
    return float(np.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2
                        + (1 - x[:-1]) ** 2))


# ---------------------------------------------------------------------
class TestFaults:
    def setup_method(self):
        faults.disarm()

    def teardown_method(self):
        faults.disarm()

    def test_disarmed_is_a_noop(self):
        assert not faults.armed()
        for _ in range(3):
            faults.fire("wire.read")        # no error, no counting
        assert faults.hits("wire.read") == 0

    def test_unknown_point_rejected_eagerly(self):
        with pytest.raises(ValueError):
            faults.arm("wire.raed", "error", at=1)
        with pytest.raises(ValueError):
            faults.arm("wire.read", "explode", at=1)

    def test_error_schedule_is_hit_deterministic(self):
        faults.arm("store.record", "error", at=3)
        faults.fire("store.record")
        faults.fire("store.record")
        with pytest.raises(faults.FaultInjected):
            faults.fire("store.record")
        faults.fire("store.record")         # hit 4: past the schedule
        assert faults.hits("store.record") == 4
        assert faults.schedules()["store.record"][0]["fired"] == 1

    def test_every_schedule(self):
        faults.arm("pool.reap", "error", every=2)
        fired = 0
        for _ in range(6):
            try:
                faults.fire("pool.reap")
            except faults.FaultInjected:
                fired += 1
        assert fired == 3

    def test_delay_schedule_sleeps(self):
        faults.arm("wire.reply", "delay", at=1, param=0.05)
        t0 = time.perf_counter()
        faults.fire("wire.reply")
        assert time.perf_counter() - t0 >= 0.05

    def test_env_spec_grammar(self):
        rules = list(faults.parse_spec(
            "ckpt.append=crash@12,wire.read=delay@3:0.5,"
            "store.record=error%4"))
        assert rules == [("ckpt.append", "crash", 12, 0, None),
                         ("wire.read", "delay", 3, 0, 0.5),
                         ("store.record", "error", 0, 4, None)]
        n = faults.maybe_arm_from_env(
            env={"UT_FAULTS": "wire.read=error@1"})
        assert n == 1 and faults.armed()
        with pytest.raises(faults.FaultInjected):
            faults.fire("wire.read")

    def test_disarm_resets_flag_and_hits(self):
        faults.arm("wire.read", "error", at=99)
        faults.fire("wire.read")
        faults.disarm()
        assert not faults.armed()
        assert faults.hits("wire.read") == 0


# ---------------------------------------------------------------------
class TestCheckpointLog:
    def test_round_trip_and_nan_encoding(self, tmp_path):
        log = CheckpointLog(str(tmp_path / "ck"))
        raw = np.array([1.5, float("nan"), float("inf")], np.float32)
        enc = encode_raw(raw)
        assert enc == [1.5, None, None]
        dec = decode_raw(enc)
        assert dec[0] == 1.5 and dec[1] != dec[1] and dec[2] != dec[2]
        assert log.append("abc", {"ev": "open", "seed": 3})
        assert log.append("abc", {"ev": "commit", "v": 1, "raw": enc})
        recs = log.load("abc")
        assert [r["ev"] for r in recs] == ["open", "commit"]

    def test_torn_tail_mid_record_is_dropped(self, tmp_path):
        log = CheckpointLog(str(tmp_path / "ck"))
        log.append("s1", {"ev": "open"})
        log.append("s1", {"ev": "commit", "v": 1, "raw": [1.0]})
        # a crash mid-append leaves a partial final line
        with open(log.path_for("s1"), "ab") as f:
            f.write(b'{"ev": "commit", "v": 2, "raw": [2.0')
        bundles = dict(log.scan())
        b = bundles["s1"]
        assert b["open"] is not None and not b["closed"]
        assert [r["v"] for r in b["commits"]] == [1]

    def test_version_gap_truncates_replay(self, tmp_path):
        log = CheckpointLog(str(tmp_path / "ck"))
        log.append("s1", {"ev": "open"})
        for v in (1, 2, 4, 5):      # 3 missing: 4, 5 untrustworthy
            log.append("s1", {"ev": "commit", "v": v, "raw": []})
        b = dict(log.scan())["s1"]
        assert [r["v"] for r in b["commits"]] == [1, 2]

    def test_close_record_marks_reapable(self, tmp_path):
        log = CheckpointLog(str(tmp_path / "ck"))
        log.append("s1", {"ev": "open"})
        log.append("s1", {"ev": "close"})
        log.append("s2", {"ev": "open"})
        bundles = dict(log.scan())
        assert bundles["s1"]["closed"] and not bundles["s2"]["closed"]
        log.reap("s1")
        assert log.session_ids() == ["s2"]

    def test_fsync_knob_carried(self, tmp_path):
        assert CheckpointLog(str(tmp_path), fsync=True).fsync
        assert not CheckpointLog(str(tmp_path)).fsync


# ---------------------------------------------------------------------
class TestStoreFsyncKnob:
    def test_resolution_order(self, tmp_path, monkeypatch):
        from uptune_tpu.store.store import ResultStore
        sig = ["IntParam('i', 1, 4)"]
        # default: off
        st = ResultStore(str(tmp_path / "a"), sig, "cmd")
        assert st.fsync is False
        st.close()
        # env layer
        monkeypatch.setenv("UT_STORE_FSYNC", "1")
        st = ResultStore(str(tmp_path / "b"), sig, "cmd")
        assert st.fsync is True
        st.close()
        # explicit arg beats env
        st = ResultStore(str(tmp_path / "c"), sig, "cmd", fsync=False)
        assert st.fsync is False
        st.close()
        # ut.config layer (env unset)
        monkeypatch.delenv("UT_STORE_FSYNC")
        try:
            api_session.settings["store-fsync"] = True
            st = ResultStore(str(tmp_path / "d"), sig, "cmd")
            assert st.fsync is True
            # a synced append still lands as one complete line
            st.record({"i": 2}, 1.25)
            assert st.lookup({"i": 2})["qor"] == 1.25
            st.close()
        finally:
            api_session.reset_settings()

    def test_config_key_exists(self):
        assert "store-fsync" in api_session.DEFAULTS
        assert api_session.DEFAULTS["store-fsync"] is False


class TestDurableConfigKeys:
    def test_defaults_have_durable_keys(self):
        for k in ("serve-durable", "serve-durable-fsync"):
            assert k in api_session.DEFAULTS

    def test_flag_precedence(self):
        from uptune_tpu.serve.cli import build_parser, resolve_config
        import uptune_tpu as ut
        try:
            cfg = resolve_config(build_parser().parse_args([]))
            assert cfg["durable"] is None
            ut.config({"serve-durable": "/tmp/ck"})
            cfg = resolve_config(build_parser().parse_args([]))
            assert cfg["durable"] == "/tmp/ck"
            # bare --durable means 'on' (default location)
            cfg = resolve_config(build_parser().parse_args(
                ["--durable"]))
            assert cfg["durable"] == "on"
            cfg = resolve_config(build_parser().parse_args(
                ["--durable", "off", "--durable-fsync"]))
            assert cfg["durable"] == "off"
            assert cfg["durable_fsync"] is True
        finally:
            api_session.reset_settings()


# ---------------------------------------------------------------------
class TestShipperJitter:
    def test_jitter_bounds_and_spread(self):
        draws = [backoff_jitter(2.0) for _ in range(64)]
        assert all(1.0 <= d <= 2.0 for d in draws)
        # a lockstep herd would draw one constant; the spread is the
        # whole point of the satellite
        assert len({round(d, 6) for d in draws}) > 8


# ---------------------------------------------------------------------
class _PingServer(WireServer):
    WIRE_NAME = "ut-test-wire"

    def _op_ping(self, req):
        return {"t": 1}

    _OPS = {"ping": _op_ping}


class TestWireHardening:
    def test_oversized_line_gets_error_then_close(self):
        srv = _PingServer("127.0.0.1", 0)
        srv.max_line = 256
        srv.start()
        try:
            with socket.create_connection(("127.0.0.1", srv.port),
                                          timeout=5) as c:
                c.sendall(b'{"op": "ping", "pad": "'
                          + b"x" * 1024 + b'"}\n')
                f = c.makefile("rb")
                line = f.readline()
                resp = json.loads(line)
                assert resp["ok"] is False
                assert "exceeds" in resp["error"]
                # the connection is closed (unsyncable stream)
                assert f.readline() == b""
        finally:
            srv.stop()

    def test_idle_connection_times_out(self):
        srv = _PingServer("127.0.0.1", 0)
        srv.idle_timeout = 0.3
        srv.start()
        try:
            with socket.create_connection(("127.0.0.1", srv.port),
                                          timeout=5) as c:
                # send nothing: the reader thread must give up and
                # close instead of pinning forever
                c.settimeout(5.0)
                t0 = time.perf_counter()
                assert c.recv(64) == b""
                assert time.perf_counter() - t0 < 4.0
        finally:
            srv.stop()

    def test_truncated_reply_is_a_connection_loss(self):
        """A server dying mid-reply flushes a PARTIAL line; the
        client must classify it as a connection loss (the resume
        machinery's retryable class), not leak a JSONDecodeError."""
        from uptune_tpu.serve.client import (ConnectionLostError,
                                             SessionClient)
        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(1)
        port = lst.getsockname()[1]

        def half_reply():
            conn, _ = lst.accept()
            conn.makefile("rb").readline()
            conn.sendall(b'{"ok": tru')     # crash mid-flush
            conn.close()

        t = threading.Thread(target=half_reply, daemon=True)
        t.start()
        c = SessionClient("127.0.0.1", port, timeout=5)
        try:
            with pytest.raises(ConnectionLostError):
                c.ping()
            assert c._broken
        finally:
            c.close()
            lst.close()

    def test_live_connection_unaffected(self):
        srv = _PingServer("127.0.0.1", 0)
        srv.idle_timeout = 2.0
        srv.start()
        try:
            with socket.create_connection(("127.0.0.1", srv.port),
                                          timeout=5) as c:
                f = c.makefile("rwb")
                for _ in range(3):
                    f.write(b'{"op": "ping"}\n')
                    f.flush()
                    assert json.loads(f.readline())["ok"]
                    time.sleep(0.1)
        finally:
            srv.stop()


# ---------------------------------------------------------------------
class TestDuplicateTellIdempotence:
    """The resume protocol's squash rules on the offline single-slot
    group (one compile for the whole class)."""

    @pytest.fixture(scope="class")
    def group(self):
        from uptune_tpu.serve.group import SessionGroup
        return SessionGroup(_space(), 1)

    def test_offer_carries_epoch(self, group):
        s = group.join(seed=31)
        try:
            t = s.ask(1)[0]
            assert t.epoch == 0
        finally:
            s.close()

    def test_in_flight_duplicate_squashed(self, group):
        s = group.join(seed=32)
        try:
            t = s.ask(2)[0]
            r1 = s.tell(t.ticket, 1.5, epoch=t.epoch, incarn=s.incarn)
            assert "duplicate" not in r1
            r2 = s.tell(t.ticket, 1.5, epoch=t.epoch, incarn=s.incarn)
            assert r2["duplicate"] and not r2["committed"]
            # without the epoch tag the same replay stays a hard error
            from uptune_tpu.serve.session import StaleTicketError
            with pytest.raises(StaleTicketError):
                s.tell(t.ticket, 1.5)
        finally:
            s.close()

    def test_committed_duplicate_squashed(self, group):
        s = group.join(seed=33)
        try:
            first = None
            while s.version == 0:
                trials = s.ask(8)
                for t in trials:
                    if first is None:
                        first = t
                    s.tell(t.ticket, _measure(t.config))
            r = s.tell(first.ticket, 0.0, epoch=first.epoch,
                       incarn=s.incarn)
            assert r["duplicate"] and r["committed"]
            assert r["version"] == s.version
        finally:
            s.close()

    def test_stale_incarnation_rejected_not_misapplied(self, group):
        from uptune_tpu.serve.session import SessionRestoredError
        s = group.join(seed=34)
        try:
            t = s.ask(1)[0]
            # a ticket from a lost pre-crash incarnation: same id,
            # same epoch — must NOT apply to the live ticket
            with pytest.raises(SessionRestoredError):
                s.tell(t.ticket, 1.0, epoch=t.epoch, incarn="dead")
            # ...but a stale-incarnation duplicate of a DURABLY
            # committed epoch squashes cleanly
            r = s.tell(t.ticket, _measure(t.config), epoch=t.epoch,
                       incarn=s.incarn)
            while not r.get("committed"):
                trials = s.ask(8)
                if not trials:
                    continue
                for t2 in trials:
                    r = s.tell(t2.ticket, _measure(t2.config))
            r2 = s.tell(t.ticket, 9.9, epoch=t.epoch, incarn="dead")
            assert r2["duplicate"] and r2["committed"]
        finally:
            s.close()

    def test_mark_restored_offsets_ticket_ids(self, group):
        s = group.join(seed=35)
        try:
            t0 = s.ask(1)[0]
            s._mark_restored("abcd1234")
            assert s.incarn == "abcd1234"
            # drain the pending epoch, then check fresh ids are offset
            assert s.outstanding()
            for t in s.outstanding():
                s.tell(t.ticket, _measure(t.config))
            while s.version == 0:
                trials = s.ask(8)
                if not trials:
                    continue
                for t in trials:
                    s.tell(t.ticket, _measure(t.config))
            t1 = s.ask(1)[0]
            assert t1.ticket >= (1 << 20) > t0.ticket
        finally:
            s.close()


# ---------------------------------------------------------------------
@pytest.mark.slow
class TestRecoveryLifecycle:
    """The compile-heavy crash/replay edges, grouped into one module
    of work per server pair (`bench.py --failover --quick` covers the
    end-to-end kill in tier-1; these pin the unit semantics)."""

    def _drive(self, srv, sid, epochs, chunk=8):
        from_version = srv._sessions[sid].version
        while srv._sessions[sid].version < from_version + epochs:
            a = srv.handle({"op": "ask", "session": sid, "n": chunk})
            assert a["ok"], a
            if not a["trials"]:
                continue
            res = [{"ticket": t["ticket"],
                    "qor": _measure(t["config"]),
                    "epoch": t["epoch"]} for t in a["trials"]]
            r = srv.handle({"op": "tell", "session": sid,
                            "results": res, "incarn": a["incarn"]})
            assert r["ok"], r

    def test_recover_replay_parity_and_loss_bound(self, tmp_path):
        from uptune_tpu.serve import SessionServer
        from uptune_tpu.serve.session import LocalSession
        from uptune_tpu.exec.space_io import records_from_space
        records = records_from_space(_space())
        store = str(tmp_path / "store")
        # slots=1: THREE live sessions of one signature force the
        # recovering server to allocate three groups — the
        # no-free-slot restore edge
        srv = SessionServer(host="127.0.0.1", port=0, slots=1,
                            max_sessions=16, store_dir=store,
                            durable="on", work_dir=str(tmp_path))
        sids = {}
        for i, seed in enumerate((41, 42, 43)):
            r = srv.handle({"op": "open", "space": records,
                            "seed": seed, "program": f"life-{seed}",
                            "store": "off" if seed == 41 else "on"})
            assert r["ok"], r
            sids[seed] = r["session"]
            self._drive(srv, sids[seed], epochs=2)
        pre = {seed: srv.handle({"op": "best", "session": sid})
               for seed, sid in sids.items()}
        ckdir = srv.ckpt.root
        # simulate the commit-vs-append SIGKILL window for seed 43:
        # drop its LAST commit record (the append that never landed)
        path = srv.ckpt.path_for(sids[43])
        lines = open(path, "rb").read().splitlines(keepends=True)
        assert sum(b'"ev":"commit"' in ln for ln in lines) == 2
        with open(path, "wb") as f:
            f.writelines(lines[:-1])
        # and a torn tail for seed 42: partial record mid-write
        with open(srv.ckpt.path_for(sids[42]), "ab") as f:
            f.write(b'{"ev":"commit","v":3,"raw":[1.0')
        # no close, no stop: the crash

        srv2 = SessionServer(host="127.0.0.1", port=0, slots=1,
                             max_sessions=16, store_dir=store,
                             durable="on", work_dir=str(tmp_path))
        try:
            assert srv2.recovered == 3
            stats = srv2.handle({"op": "stats"})
            assert stats["durable"]["recovered"] == 3
            # three groups allocated for one signature
            assert sum(len(gs) for gs in srv2._groups.values()) >= 3

            # seeds 41/42: full restore, host state bitwise
            for seed in (41, 42):
                b = srv2.handle({"op": "best", "session": sids[seed]})
                assert b["qor"] == pre[seed]["qor"]
                assert b["config"] == pre[seed]["config"]
                assert b["version"] == 2
                assert b["tells"] == pre[seed]["tells"]

            # store-off session: continued proposal stream bitwise
            # equal to an uninterrupted offline sibling
            ls = LocalSession(_space(), seed=41)
            try:
                for _ in range(2):
                    done = False
                    while not done:
                        trials = ls.ask(8)
                        if not trials:
                            done = True
                            continue
                        for t in trials:
                            rr = ls.tell(t.ticket, _measure(t.config))
                            done = done or rr["committed"]
                a = srv2.handle({"op": "ask", "session": sids[41],
                                 "n": 500})
                want = [t.config for t in ls.ask(500)]
                assert [t["config"] for t in a["trials"]] == want
            finally:
                ls.close()

            # seed 43: the bounded-loss contract — restored one
            # version short (the un-appended commit), but its tells
            # were store-recorded before any reply, so re-driving the
            # epoch re-fills from the memo and lands on the SAME state
            b = srv2.handle({"op": "best", "session": sids[43]})
            assert b["version"] == 1
            self._drive(srv2, sids[43], epochs=1)
            b = srv2.handle({"op": "best", "session": sids[43]})
            assert b["version"] == 2
            assert b["qor"] == pre[43]["qor"]
            assert b["config"] == pre[43]["config"]

            # recovered segments keep extending: close reaps them
            for sid in sids.values():
                srv2.handle({"op": "close", "session": sid})
            assert srv2.ckpt.session_ids() == []
            assert os.path.isdir(ckdir)
        finally:
            srv2.stop()
        srv.stop()

    def test_client_auto_resume_across_restart(self, tmp_path):
        """A TCP client with auto_resume survives the server dying
        under it: reconnect+backoff+attach on the SAME port, reissue
        of outstanding tickets, duplicate-tell squash — and finishes
        with state equal to an uninterrupted run."""
        from uptune_tpu.serve import SessionServer, connect
        store = str(tmp_path / "store")
        srv = SessionServer(host="127.0.0.1", port=0, slots=2,
                            max_sessions=8, store_dir=store,
                            durable="on", work_dir=str(tmp_path))
        srv.start()
        port = srv.port
        c = connect(("127.0.0.1", port), timeout=30,
                    auto_resume=True, max_retries=40,
                    backoff_base=0.1, backoff_max=1.0)
        h = c.open_session(_space(), seed=51, program="resume")
        trials = h.ask(4)
        h.tell_many((t.ticket, _measure(t.config))
                    for t in trials[:2])
        # "crash": stop the server with tickets outstanding (durable
        # state survives; the in-flight epoch is at the store's mercy)
        srv.stop()
        resumed = {}

        def finish():
            try:
                while h.version < 2:
                    tr = h.ask(4)
                    if not tr:
                        time.sleep(0.02)
                        continue
                    h.tell_many((t.ticket, _measure(t.config))
                                for t in tr)
                resumed["best"] = h.best()
            except Exception as e:          # surfaced by the assert
                resumed["error"] = repr(e)

        worker = threading.Thread(target=finish, daemon=True)
        worker.start()
        time.sleep(0.5)
        srv2 = SessionServer(host="127.0.0.1", port=port, slots=2,
                             max_sessions=8, store_dir=store,
                             durable="on", work_dir=str(tmp_path))
        # the dead server's accepted sockets can hold the port in a
        # non-TIME_WAIT state for a moment (same-process restart only
        # — a real crash frees them with the process): bounded retry
        deadline = time.time() + 60
        while True:
            try:
                srv2.start()
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.3)
        try:
            worker.join(timeout=120)
            assert not worker.is_alive(), "client never resumed"
            assert "error" not in resumed, resumed
            assert c.reconnects >= 1
            assert resumed["best"]["version"] == 2
            from uptune_tpu.serve.session import LocalSession
            ls = LocalSession(_space(), seed=51)
            try:
                while ls.version < 2:
                    for t in ls.ask(4):
                        ls.tell(t.ticket, _measure(t.config))
                assert resumed["best"]["qor"] == ls.best()["qor"]
                assert resumed["best"]["config"] == ls.best()["config"]
            finally:
                ls.close()
        finally:
            c.close()
            srv2.stop()

    def test_orphan_ttl_sweeps_disconnected_durable_sessions(
            self, tmp_path):
        from uptune_tpu.serve import SessionServer, connect
        srv = SessionServer(host="127.0.0.1", port=0, slots=2,
                            max_sessions=8, store_dir="off",
                            durable=str(tmp_path / "ck"),
                            work_dir=str(tmp_path), orphan_ttl=0.2)
        srv.start()
        try:
            c = connect(("127.0.0.1", srv.port), timeout=30)
            h = c.open_session(_space(), seed=61, store=False)
            sid = h.id
            assert srv.n_sessions == 1

            # ownership transfer: a SECOND connection attaches, then
            # the FIRST dies — the stale owner must not restart the
            # orphan clock on a session its client re-homed
            c2 = connect(("127.0.0.1", srv.port), timeout=30)
            c2.attach_session(sid)
            c.close()
            deadline = time.time() + 3
            while time.time() < deadline and sid not in srv._orphans:
                time.sleep(0.05)
            time.sleep(0.4)         # well past orphan_ttl
            srv._sweep_orphans()
            assert srv.n_sessions == 1, \
                "stale owner's death orphaned a re-attached session"

            c2.close()  # the CURRENT owner disconnecting starts it
            deadline = time.time() + 5
            while srv.n_sessions and time.time() < deadline:
                time.sleep(0.05)
                srv._sweep_orphans()
            assert srv.n_sessions == 0
            # the swept session closed cleanly: segment reaped
            assert srv.ckpt.session_ids() == []
        finally:
            srv.stop()


# ---------------------------------------------------------------------
class TestFailoverBenchSmoke:
    def test_failover_bench_quick_smoke(self, tmp_path):
        """`bench.py --failover --quick` (the ISSUE 15 tier-1 smoke,
        ~21s on an idle box, the fleet-smoke precedent): a real
        `ut serve --durable` child crashed at a DETERMINISTIC
        checkpoint append (UT_FAULTS crash schedule), recovered
        in-process on the same port under the strict trace guard,
        auto-resume clients finishing with bitwise matched-seed
        parity and zero committed loss."""
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--failover", "--quick", "--cpu"],
            capture_output=True, text=True, env=env,
            cwd=str(tmp_path), timeout=420)
        assert r.returncode == 0, r.stdout + r.stderr
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["metric"] == "serve_failover_ok"
        assert out["value"] is True
        art = json.load(open(os.path.join(
            REPO, "BENCH_FAILOVER.quick.json")))
        assert art["phase2"]["parity_bitwise_ok"]
        assert art["phase2"]["zero_committed_loss"]
        assert art["phase2"]["trace_guard"]["clean"]
        assert art["phase2"]["crash_rc"] == 137
