"""Batched multi-instance engine (ISSUE 6): matched-seed parity with
sequential single-instance runs, the Pallas dedup merge vs the XLA
fallback, fused flat surrogate scoring, the on-device best-exchange
collective, shard_map scale-out over the instance axis, the tune_batch
library surface, strict trace-guard cleanliness, and the bench.py
--multi smoke.

Tier-1 budget discipline: compiles dominate these tests' cost, so the
rosenbrock runs share ONE module-scoped engine + compiled programs
(fixtures below), sizes stay tiny (2-d space, <=8 steps, 1<<9
histories), and every result consumed by several tests is computed
once."""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uptune_tpu.driver.history import History
from uptune_tpu.engine import (BatchedEngine, FusedEngine,
                               make_instance_mesh, surrogate_eval_fn)
from uptune_tpu.ops import dedup
from uptune_tpu.workloads import (random_tsp_distances, rosenbrock_device,
                                  rosenbrock_space, tsp_device, tsp_space)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KEY = jax.random.PRNGKey(7)
STEPS = 8

HIST_FIELDS = ("h0", "h1", "qor", "n", "age", "step", "dropped")


def _rb_obj(vals, perms):
    return rosenbrock_device(vals)


def _eq(a, b):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.fixture(scope="module")
def rb_eng():
    """One shared 2-d engine: 8 steps x 114 cands > 1<<9 capacity,
    so the matched-seed parity runs exercise EVICTION through both the
    per-instance cond predicate (sequential) and the batched engine's
    conservative batch-level gate."""
    return FusedEngine(rosenbrock_space(2, -3.0, 3.0), _rb_obj,
                       history_capacity=1 << 9)


@pytest.fixture(scope="module")
def seq_run(rb_eng):
    """The sequential single-instance program, compiled once."""
    return jax.jit(lambda s: rb_eng.run(s, STEPS))


@pytest.fixture(scope="module")
def batched4(rb_eng):
    """(engine, final state) of the shared N=4 batched run."""
    be = BatchedEngine(rb_eng, 4)
    return be, be.run(be.init(KEY), STEPS)


@pytest.fixture(scope="module")
def exchange4(rb_eng):
    """(engine, final state) of the N=4 portfolio run (exchange at
    steps 3 and 6)."""
    be = BatchedEngine(rb_eng, 4, exchange_every=3)
    return be, be.run(be.init(KEY), STEPS)


class TestBatchedParity:
    def test_n1_exact_parity(self, rb_eng, seq_run):
        """A 1-instance batched run IS the single engine: best config,
        best qor, history table and counters match BITWISE — including
        the eviction steps (capacity overflows at step 5)."""
        be = BatchedEngine(rb_eng, 1)
        sb = be.run(be.init(KEY), STEPS)
        ss = seq_run(rb_eng.init(be.instance_keys(KEY)[0]))
        _eq(sb.best.qor[0], ss.best.qor)
        _eq(sb.best.u[0], ss.best.u)
        _eq(sb.hist.h0[0], ss.hist.h0)
        _eq(sb.hist.qor[0], ss.hist.qor)
        _eq(sb.hist.dropped[0], ss.hist.dropped)
        _eq(sb.evals[0], ss.evals)
        _eq(sb.acqs[0], ss.acqs)

    def test_matched_seed_equivalence_n4(self, batched4, rb_eng,
                                         seq_run):
        """Without exchange, every instance's result equals the
        sequential single-instance run started from the same derived
        key — N independent tunes, one program (ISSUE 6 acceptance)."""
        be, s4 = batched4
        for i, k in enumerate(be.instance_keys(KEY)):
            si = seq_run(rb_eng.init(k))
            _eq(s4.best.qor[i], si.best.qor)
            _eq(s4.best.u[i], si.best.u)
            _eq(s4.evals[i], si.evals)
            _eq(s4.hist.dropped[i], si.hist.dropped)

    def test_perm_space_batched(self):
        n = 8
        dist = jnp.asarray(random_tsp_distances(n, seed=5))
        eng = FusedEngine(tsp_space(n),
                          lambda v, perms: tsp_device(perms[0], dist),
                          history_capacity=1 << 10)
        be = BatchedEngine(eng, 2)
        st = be.run(be.init(jax.random.PRNGKey(0)), 6)
        for cfg in be.best_configs(st):
            assert sorted(cfg["tour"]) == list(range(n))
        assert np.isfinite(be.best_qors(st)).all()

    def test_run_traced_per_instance_monotone(self, rb_eng):
        be = BatchedEngine(rb_eng, 2)
        _, traces = jax.jit(lambda s: be.run_traced(s, 4))(
            be.init(jax.random.PRNGKey(1)))
        tr = np.asarray(traces)
        assert tr.shape == (4, 2)
        assert (np.diff(tr, axis=0) <= 1e-9).all()

    def test_best_reporting(self, batched4):
        be, st = batched4
        qors = be.best_qors(st)
        cfg, q = be.best(st)
        i = int(np.argmin(qors))
        assert q == qors[i]
        assert cfg == be.best_config(st, i)
        assert len(be.best_configs(st)) == 4


class TestExchange:
    def test_exchange_propagates_best(self, exchange4, batched4):
        """Portfolio mode: after an exchange step every instance's
        incumbent equals the global best (the reference's epoch sync,
        on device) — and the cooperative global best is at least as
        good as the independent instances' (same seeds)."""
        _, sx = exchange4
        q = np.asarray(sx.best.qor)
        assert np.isfinite(q).all()
        np.testing.assert_allclose(q, q.min(), atol=0)
        _, si = batched4
        assert q.min() <= float(np.asarray(si.best.qor).min()) + 1e-6


class TestPallasDedupMerge:
    @staticmethod
    def _mk(rng, cap, b, n_live, sent_batch=8):
        h0 = np.sort(rng.randint(0, 2**31, n_live).astype(np.uint32))
        h0 = np.concatenate(
            [h0, np.full(cap - n_live, 0xFFFFFFFF, np.uint32)])
        h1 = rng.randint(0, 2**32, cap).astype(np.uint32)
        q = rng.randn(cap).astype(np.float32)
        q[n_live:] = np.inf
        age = np.concatenate(
            [rng.randint(0, 50, n_live),
             np.full(cap - n_live, -1)]).astype(np.int32)
        h0s = rng.randint(0, 2**31, b).astype(np.uint32)
        if n_live and b > 4:    # force history collisions
            h0s[:3] = h0[:3]
        if sent_batch and b > sent_batch:  # invalid (sentinel) rows
            h0s[-sent_batch:] = 0xFFFFFFFF
        h0s = np.sort(h0s)
        hist = tuple(jnp.asarray(a) for a in (h0, h1, q, age))
        new = tuple(jnp.asarray(a) for a in (
            h0s, rng.randint(0, 2**32, b).astype(np.uint32),
            rng.randn(b).astype(np.float32), np.full(b, 50, np.int32)))
        pos = (jnp.arange(b, dtype=jnp.int32)
               + jnp.searchsorted(hist[0], new[0],
                                  side="right").astype(jnp.int32))
        return hist, new, pos

    @pytest.mark.parametrize("cap,b,n_live", [
        (2048, 300, 1500),   # mid-fill, collisions, sentinel rows
        (2048, 2048, 2000),  # full-tile batch, near-full history
    ])
    def test_merge_parity(self, cap, b, n_live):
        """The Pallas kernel (interpret mode — the CPU parity harness,
        same as pallas_score) is BITWISE equal to the XLA
        gather+cumsum fallback."""
        rng = np.random.RandomState(cap + b)
        hist, new, pos = self._mk(rng, cap, b, n_live)
        outx = dedup.merge_rows_xla(hist, new, pos)
        outp = dedup.merge_rows_pallas(hist, new, pos, interpret=True)
        for name, a, p in zip(("h0", "h1", "qor", "age"), outx, outp):
            assert np.array_equal(np.asarray(a), np.asarray(p),
                                  equal_nan=True), name

    def test_history_insert_parity_with_eviction(self):
        """History.insert(merge_impl='pallas') == 'xla' across rounds
        that overflow capacity — the merge AND the (rewritten,
        sort-free) eviction agree."""
        cap = 2048
        hx, hp = History(cap, "xla"), History(cap, "pallas")
        stx, stp = hx.init(), hp.init()
        rng = np.random.RandomState(17)
        ins_x, ins_p = jax.jit(hx.insert), jax.jit(hp.insert)
        for _ in range(5):   # 5 * ~480 valid rows > cap => eviction
            hashes = jnp.asarray(
                rng.randint(0, 2**31, (600, 2)).astype(np.uint32))
            qor = jnp.asarray(rng.randn(600).astype(np.float32))
            valid = jnp.asarray(rng.rand(600) > 0.2)
            stx = ins_x(stx, hashes, qor, valid)
            stp = ins_p(stp, hashes, qor, valid)
        assert int(stx.dropped) > 0   # eviction actually exercised
        for name, a, p in zip(HIST_FIELDS, stx, stp):
            assert np.array_equal(np.asarray(a), np.asarray(p),
                                  equal_nan=True), name

    @pytest.mark.slow
    def test_batched_engine_pallas_merge_parity(self):
        """A whole batched engine run with merge_impl='pallas'
        (vmapped pallas_call, interpret mode) equals 'xla'.
        Slow-marked (suite budget): the kernel itself is bitwise
        parity-tested tier-1 by test_merge_parity and
        test_history_insert_parity_with_eviction; this adds only the
        vmapped-pallas_call engine cross-check."""
        key = jax.random.PRNGKey(9)
        states = []
        for impl in ("xla", "pallas"):
            eng = FusedEngine(rosenbrock_space(2, -3.0, 3.0), _rb_obj,
                              history_capacity=2048, merge_impl=impl)
            be = BatchedEngine(eng, 2)
            states.append(be.run(be.init(key), 4))
        _eq(states[0].best.qor, states[1].best.qor)
        _eq(states[0].hist.h0, states[1].hist.h0)
        _eq(states[0].evals, states[1].evals)

    def test_unsupported_shapes(self):
        assert not dedup.pallas_merge_supported(1000, 10)   # cap % TILE
        assert not dedup.pallas_merge_supported(4096, 4097)  # b > TILE
        rng = np.random.RandomState(0)
        hist, new, pos = self._mk(rng, 1024, 16, 100, sent_batch=0)
        with pytest.raises(ValueError):
            dedup.merge_rows_pallas(hist, new, pos, interpret=True)
        # merge_history falls back to xla off-TPU / on odd shapes
        out = dedup.merge_history(hist, new, impl="auto")
        ref = dedup.merge_rows_xla(hist, new, pos)
        for a, b in zip(out, ref):
            assert np.array_equal(np.asarray(a), np.asarray(b),
                                  equal_nan=True)


class TestFusedSurrogateScoring:
    @pytest.fixture(scope="class")
    def gp_fit(self):
        from uptune_tpu.surrogate import gp
        space = rosenbrock_space(3, -2.0, 2.0)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.rand(32, space.n_surrogate_features),
                        jnp.float32)
        y = jnp.asarray(rng.randn(32), jnp.float32)
        return space, gp.fit(x, y), y

    def test_score_flat_matches_predict(self, gp_fit):
        """gp.score_flat over a [I, B, F] stack == per-instance
        predict/EI/LCB: the fused one-dispatch scoring is the same
        model."""
        from uptune_tpu.surrogate import gp
        space, st, y = gp_fit
        rng = np.random.RandomState(1)
        xq = jnp.asarray(rng.rand(2, 16, space.n_surrogate_features),
                         jnp.float32)
        best = jnp.float32(float(y.min()))
        ei = gp.score_flat(st, xq, kind="ei", best_y=float(y.min()))
        lcb = gp.score_flat(st, xq, kind="lcb")
        mu = gp.score_flat(st, xq, kind="mean")
        for i in range(2):
            np.testing.assert_allclose(
                np.asarray(ei[i]),
                np.asarray(gp.expected_improvement(st, xq[i], best)),
                rtol=1e-6, atol=1e-8)
            np.testing.assert_allclose(
                np.asarray(lcb[i]),
                np.asarray(gp.lower_confidence_bound(st, xq[i])),
                rtol=1e-6, atol=1e-7)
            np.testing.assert_allclose(
                np.asarray(mu[i]),
                np.asarray(gp.predict(st, xq[i])[0]),
                rtol=1e-6, atol=1e-7)
        with pytest.raises(ValueError):
            gp.score_flat(st, xq, kind="ei")   # best_y required
        with pytest.raises(ValueError):
            gp.score_flat(st, xq, kind="nope")

    def test_surrogate_eval_fn_drives_batched_engine(self, gp_fit):
        """The fused GP eval_fn plugs into BatchedEngine: all
        instances' candidates score in one flat pass and the run is
        healthy."""
        space, st, y = gp_fit
        eng = FusedEngine(space, lambda v, p: jnp.zeros(v.shape[0]),
                          history_capacity=1 << 10)
        be = BatchedEngine(eng, 2)
        fn = surrogate_eval_fn(space, st, kind="ei",
                               best_y=float(y.min()))
        s = be.jit_run(3, fn, donate=False)(
            be.init(jax.random.PRNGKey(0)))
        assert np.isfinite(be.best_qors(s)).all()
        assert (np.asarray(s.evals) > 0).all()

    def test_surrogate_eval_fn_sense_orientation(self, gp_fit):
        """commit re-orients eval_fn output by the engine sign, so the
        helper must pre-apply the inverse for sense='max' — the raw
        outputs of the two senses are exact negations."""
        space, st, y = gp_fit
        cands = space.random(jax.random.PRNGKey(3), 8)
        lo = surrogate_eval_fn(space, st, kind="lcb")(cands)
        hi = surrogate_eval_fn(space, st, kind="lcb",
                               sense="max")(cands)
        np.testing.assert_array_equal(np.asarray(lo), -np.asarray(hi))


class TestFusedAcquisitionEngine:
    """ISSUE 19: the fused acquisition pipeline driving the engine —
    StatefulEval aux threading (publish never retraces), matched-seed
    route parity, and the propose+top-k programs."""

    @pytest.fixture(scope="class")
    def gp_fit(self):
        from uptune_tpu.surrogate import gp
        space = rosenbrock_space(3, -2.0, 2.0)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.rand(32, space.n_surrogate_features),
                        jnp.float32)
        y = jnp.asarray(rng.randn(32), jnp.float32)
        return space, gp.fit(x, y), y

    def _engine(self, space, n):
        eng = FusedEngine(space, lambda v, p: jnp.zeros(v.shape[0]),
                          history_capacity=1 << 10)
        return BatchedEngine(eng, n)

    def test_publish_refit_never_retraces(self, gp_fit):
        """Satellite 2 acceptance: the surrogate snapshot is a program
        ARGUMENT — publishing a refit re-dispatches the one compiled
        program with zero retraces under the strict guard."""
        from uptune_tpu.analysis import TraceGuard
        from uptune_tpu.engine import surrogate_aux
        from uptune_tpu.surrogate import gp
        space, st, y = gp_fit
        with TraceGuard(limit=1, strict=True) as guard:
            be = self._engine(space, 2)
            fn = surrogate_eval_fn(space, st, kind="ei",
                                   best_y=float(y.min()))
            run = be.jit_run(3, fn, donate=False)
            s0 = be.init(jax.random.PRNGKey(0))
            run(s0)
            st2 = gp.fit(jnp.asarray(st.x), y * 2.0)
            fn.publish(surrogate_aux(st2, best_y=float(y.min()) * 2.0,
                                     kind="ei"))
            run(s0)
        rep = guard.report()
        assert rep["traces"][
            "BatchedEngine.jit_run.<locals>._run"] == 1, rep

    @pytest.mark.parametrize("n_inst", [1, 4])
    def test_matched_seed_route_parity_e2e(self, gp_fit, monkeypatch,
                                           n_inst):
        """Tentpole acceptance: matched-seed whole runs with the fused
        pipeline pinned to the kernel-interpret route and to the XLA
        fallback are BITWISE identical (the engine scores the FLAT
        [N*B] batch, where the fallback stages the same per-tile
        computation)."""
        space, st, y = gp_fit

        def final(mode):
            monkeypatch.setenv("UT_PALLAS", mode)
            try:
                be = self._engine(space, n_inst)
                fn = surrogate_eval_fn(space, st, kind="ei",
                                       best_y=float(y.min()))
                return be, be.jit_run(3, fn, donate=False)(
                    be.init(jax.random.PRNGKey(1)))
            finally:
                monkeypatch.delenv("UT_PALLAS")

        be_i, s_i = final("interpret")
        be_x, s_x = final("off")
        _eq(s_i.best.qor, s_x.best.qor)
        _eq(s_i.best.u, s_x.best.u)
        _eq(s_i.evals, s_x.evals)

    def test_fused_matches_score_flat_staging(self, gp_fit):
        """impl='fused' vs the pre-fusion impl='score_flat' on the
        same candidates: same model, only fusion/FMA staging noise."""
        space, st, y = gp_fit
        cands = space.random(jax.random.PRNGKey(3), 64)
        args = dict(kind="ei", best_y=float(y.min()))
        a = surrogate_eval_fn(space, st, impl="fused", **args)(cands)
        b = surrogate_eval_fn(space, st, impl="score_flat",
                              **args)(cands)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=2e-6)
        with pytest.raises(ValueError):
            surrogate_eval_fn(space, st, impl="nope", **args)

    def test_jit_propose_topk_matches_full_scores(self, gp_fit):
        """Per-slot fused top-k == lax.top_k over that slot's full
        utility vector, on the SAME proposal epoch."""
        space, st, y = gp_fit
        acq = surrogate_eval_fn(space, st, kind="lcb")
        be = self._engine(space, 2)
        s0 = be.init(jax.random.PRNGKey(2))
        ts, cands, keys, vals, idx = be.jit_propose_topk(5, acq)(s0)
        ts2, cands2, keys2 = be.jit_propose_all()(s0)
        _eq(cands.u, cands2.u)          # same epoch
        from uptune_tpu.engine.fused import CandBatch
        for i in range(2):
            ci = CandBatch(cands.u[i],
                           tuple(p[i] for p in cands.perms))
            # eval orientation is engine-low-is-better: utilities are
            # the negation
            u = -np.asarray(acq(ci))
            rv, ri = jax.lax.top_k(jnp.asarray(u), 5)
            _eq(idx[i], ri)
            np.testing.assert_allclose(np.asarray(vals[i]),
                                       np.asarray(rv),
                                       rtol=1e-5, atol=2e-6)

    def test_jit_global_topk_replicated_and_sharded(self, gp_fit):
        """jit_global_topk returns every instance the SAME global
        winner set (exchange_topk is a full replication), and the
        mesh-sharded program selects the same candidates as the
        single-device vmap."""
        space, st, y = gp_fit
        acq = surrogate_eval_fn(space, st, kind="lcb")
        be = self._engine(space, 4)
        s0 = be.init(jax.random.PRNGKey(4))
        gv, gown, gidx = be.jit_global_topk(6, acq)(s0)
        assert gv.shape == (4, 6)
        for i in range(1, 4):           # replicated rows, bitwise
            _eq(gv[0], gv[i])
            _eq(gown[0], gown[i])
            _eq(gidx[0], gidx[i])
        eng = FusedEngine(space, lambda v, p: jnp.zeros(v.shape[0]),
                          history_capacity=1 << 10)
        bs = BatchedEngine(eng, 4, mesh=make_instance_mesh(2))
        sv, sown, sidx = bs.jit_global_topk(6, acq)(
            bs.init(jax.random.PRNGKey(4)))
        _eq(sown[0], gown[0])
        _eq(sidx[0], gidx[0])
        np.testing.assert_allclose(np.asarray(sv[0]),
                                   np.asarray(gv[0]),
                                   rtol=1e-5, atol=2e-6)

    def test_fused_engine_propose_topk(self, gp_fit):
        """FusedEngine.propose_topk returns the k best-by-acquisition
        rows of its own proposal epoch."""
        space, st, y = gp_fit
        acq = surrogate_eval_fn(space, st, kind="lcb")
        eng = FusedEngine(space, lambda v, p: jnp.zeros(v.shape[0]),
                          history_capacity=1 << 10)
        si = eng.init(jax.random.PRNGKey(5))
        nts, cands, key, vals, idx = eng.propose_topk(si, acq, 4)
        u = -np.asarray(acq(cands))
        rv, ri = jax.lax.top_k(jnp.asarray(u), 4)
        _eq(idx, ri)
        bad = surrogate_eval_fn(space, st, kind="lcb",
                                impl="score_flat")
        bad.topk = None
        with pytest.raises(ValueError):
            eng.propose_topk(si, bad, 4)


class TestShardMap:
    def test_sharded_equals_vmap(self, rb_eng, batched4):
        """shard_map over the instance mesh is semantically INVISIBLE:
        same per-instance results as the single-device vmap run (the
        shared batched4 fixture, same key and steps)."""
        bs = BatchedEngine(rb_eng, 4, mesh=make_instance_mesh(2))
        ss = bs.run(bs.init(KEY), STEPS)
        _, sv = batched4
        _eq(sv.best.qor, ss.best.qor)
        _eq(sv.best.u, ss.best.u)
        _eq(sv.evals, ss.evals)

    def test_sharded_exchange_equals_vmap_exchange(self, rb_eng,
                                                   exchange4):
        """The exchange collective spans the mesh axis AND the in-shard
        vmap axis — cooperative results match the unsharded portfolio
        bitwise."""
        bs = BatchedEngine(rb_eng, 4, exchange_every=3,
                           mesh=make_instance_mesh(2))
        ss = bs.run(bs.init(KEY), STEPS)
        _, sv = exchange4
        _eq(sv.best.qor, ss.best.qor)
        q = np.asarray(ss.best.qor)
        np.testing.assert_allclose(q, q.min(), atol=0)

    def test_indivisible_instances_raise(self, rb_eng):
        with pytest.raises(ValueError):
            BatchedEngine(rb_eng, 3, mesh=make_instance_mesh(2))


class TestTuneBatchAPI:
    def test_tune_batch_and_continue(self):
        import uptune_tpu as ut
        space = rosenbrock_space(2, -3.0, 3.0)
        res = ut.tune_batch(space, _rb_obj, n_instances=2, steps=4,
                            seed=0, history_capacity=1 << 10)
        assert len(res.best_configs) == 2
        assert res.best_qors.shape == (2,)
        assert res.best_qor == res.best_qors.min()
        assert set(res.best_config) == {"x0", "x1"}
        assert (res.acqs > 0).all() and (res.evals > 0).all()
        before = float(res.best_qors.min())
        # continuation through tune_batch(state=..., engine=...) must
        # NOT donate the caller's state (res.state stays readable) and
        # reuses the compiled program via the returned engine
        res2 = ut.tune_batch(space, _rb_obj, n_instances=2, steps=4,
                             seed=0, history_capacity=1 << 10,
                             state=res.state, engine=res.engine)
        assert float(np.asarray(res.state.best.qor).min()) == before
        assert float(res2.best_qors.min()) <= before + 1e-6
        with pytest.raises(ValueError):
            ut.tune_batch(space, _rb_obj, n_instances=3, steps=4,
                          engine=res.engine)

    def test_tune_batch_max_sense(self):
        import uptune_tpu as ut
        space = rosenbrock_space(2, -3.0, 3.0)
        res = ut.tune_batch(space,
                            lambda v, p: -rosenbrock_device(v),
                            n_instances=2, steps=5, sense="max",
                            history_capacity=1 << 10)
        assert res.best_qor > -0.5   # max of -rosenbrock -> ~0


class TestTraceGuardBatched:
    def test_whole_batched_run_traces_once(self):
        """ISSUE 6 acceptance: one compiled program for the whole
        batched run — repeated donated driving adds ZERO retraces
        under the strict guard."""
        from uptune_tpu.analysis import TraceGuard
        with TraceGuard(limit=1, strict=True) as guard:
            eng = FusedEngine(rosenbrock_space(2, -3.0, 3.0), _rb_obj,
                              history_capacity=1 << 10)
            be = BatchedEngine(eng, 2, exchange_every=2)
            run = be.jit_run(3)
            st = be.init(jax.random.PRNGKey(0))
            for _ in range(3):
                st = run(st)
        rep = guard.report()
        assert rep["traces"] == {
            "BatchedEngine.jit_run.<locals>._run": 1}, rep


class TestBenchMultiSmoke:
    def test_bench_multi_quick(self):
        """`bench.py --multi --quick --cpu` is the tier-1 smoke for the
        multi-instance bench path (ISSUE 6 CI satellite): one JSON
        line, the evidence artifact, and a clean strict trace-guard
        report."""
        env = {**os.environ, "PYTHONPATH": REPO,
               "UT_TRACE_GUARD": "strict"}
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--multi", "--quick", "--cpu"],
            capture_output=True, text=True, cwd=REPO, env=env,
            timeout=420)
        assert out.returncode == 0, out.stderr[-2000:]
        res = json.loads(out.stdout.strip().splitlines()[-1])
        assert res["metric"] == "multi_instance_agg_acqs_per_sec_per_chip"
        assert res["quick"] and res["platform"] == "cpu"
        assert res["n_instances"] >= 32
        assert res["value"] > 0
        assert res["speedup_vs_warm_sequential"] > 0
        # strict guard: every wrapper in the measured region compiled
        # exactly once
        assert res["retraces"]["excess"] == {}, res["retraces"]
        # the roofline fields are sourced from the shared obs.device
        # module since ISSUE 13 (bench.py owns no private peak table):
        # measured cost-model + memory-plan fields must be present
        ca = res["cost_analysis"]
        assert ca["total_flops"] and ca["total_bytes_accessed"]
        assert ca["flops_per_s"] and ca["bytes_per_s"]
        assert ca["arith_intensity"] is not None
        assert ca["peak_memory"]["temp_bytes"] >= 0
        assert ca["peak_memory"]["argument_bytes"] > 0
        assert "obs.device" in ca["source"] or \
            "obs/device" in ca["note"]
        # ISSUE 19: the fused acquisition pipeline A/B must be present
        # with measured rates on BOTH sides, the routing verdict, and
        # the kernel's static tile/VMEM roofline protocol fields
        fa = res["fused_acquire"]
        assert fa["route"] in ("pallas", "interpret", "xla")
        assert fa["agg_acq_per_s_fused"] > 0
        assert fa["agg_acq_per_s_unfused"] > 0
        assert fa["fused_speedup_vs_unfused"] > 0
        assert fa["topk_k"] >= 1 and fa["agg_acq_per_s_fused_topk"] > 0
        sch = fa["kernel_schema"]
        assert sch["tile_rows"] > 0 and sch["lanes"] > 0
        assert sch["k_lanes"] > 0 and sch["vmem_bytes"] > 0
        fca = fa["cost_analysis"]
        assert fca["total_flops"] and fca["flops_per_s"]
        assert fca["peak_memory"]["argument_bytes"] > 0
        path = os.path.join(REPO, "BENCH_MULTI.quick.json")
        assert os.path.exists(path)
        with open(path) as f:
            assert json.load(f)["n_instances"] == res["n_instances"]
