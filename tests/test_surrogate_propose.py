"""The surrogate PROPOSAL plane (surrogate/manager.py propose_pool +
driver/driver.py _acquire_surrogate): EI-maximizing batches from an
oversampled pool, interleaved with technique tickets.  This is the
TPU-native extension past the reference's filter-only surrogate role
(/root/reference/python/uptune/api.py:307-326 only ever prunes) and the
mechanism behind the iters-to-optimum north star (BENCHREPORT.md)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uptune_tpu.driver import Tuner
from uptune_tpu.space.params import FloatParam, IntParam, PermParam
from uptune_tpu.space.spec import Space
from uptune_tpu.surrogate import SurrogateManager
from uptune_tpu.workloads import (rosenbrock_device, rosenbrock_objective,
                                  rosenbrock_space)


def _fitted_manager(space, n=128, seed=0, **opts):
    m = SurrogateManager(space, "gp", min_points=32, explore_frac=0.0,
                         seed=seed, **opts)
    cands = space.random(jax.random.PRNGKey(seed), n)
    qor = np.asarray(rosenbrock_device(space.decode_scalars(cands.u)))
    m.observe(np.asarray(space.features(cands)), qor)
    assert m.maybe_refit()
    return m, cands, qor


class TestProposePool:
    def test_disabled_returns_none(self):
        space = rosenbrock_space(2, -3.0, 3.0)
        m, cands, _ = _fitted_manager(space)  # propose_batch defaults 0
        assert m.propose_pool(jax.random.PRNGKey(1), cands.u[0],
                              (), 1.0) is None

    def test_not_fitted_returns_none(self):
        space = rosenbrock_space(2, -3.0, 3.0)
        m = SurrogateManager(space, "gp", min_points=64, propose_batch=8)
        assert m.propose_pool(jax.random.PRNGKey(1),
                              jnp.zeros(2), (), 1.0) is None

    @pytest.mark.parametrize("kind", ["gp", "mlp"])
    @pytest.mark.parametrize("score", ["ei", "lcb"])
    def test_pool_batch_shape_and_validity(self, kind, score):
        space = rosenbrock_space(3, -3.0, 3.0)
        m = SurrogateManager(space, kind, min_points=32, n_members=3,
                             propose_batch=8, score=score, pool_mult=16)
        cands = space.random(jax.random.PRNGKey(0), 64)
        qor = np.asarray(rosenbrock_device(space.decode_scalars(cands.u)))
        m.observe(np.asarray(space.features(cands)), qor)
        assert m.maybe_refit()
        i = int(np.argmin(qor))
        out = m.propose_pool(jax.random.PRNGKey(1), cands.u[i], (),
                             float(qor[i]))
        assert out.batch == 8
        u = np.asarray(out.u)
        assert u.shape == (8, 3)
        assert (u >= 0.0).all() and (u <= 1.0).all()

    def test_pool_concentrates_near_optimum(self):
        """With a well-fit GP on rosenbrock, the EI-selected batch must be
        far better on average than uniform random candidates."""
        space = rosenbrock_space(2, -3.0, 3.0)
        m, cands, qor = _fitted_manager(space, n=256, propose_batch=16,
                                        score="ei", pool_mult=64)
        i = int(np.argmin(qor))
        out = m.propose_pool(jax.random.PRNGKey(2), cands.u[i], (),
                             float(qor[i]))
        picked = np.asarray(
            rosenbrock_device(space.decode_scalars(out.u)))
        rand = np.asarray(rosenbrock_device(space.decode_scalars(
            space.random(jax.random.PRNGKey(3), 512).u)))
        assert picked.mean() < rand.mean() / 2, (picked.mean(),
                                                 rand.mean())

    def test_pool_concentrates_on_flag_space(self):
        """gcc-options-shaped landscape: mostly-boolean lanes with
        additive effects.  The sparse-lane-resample rows must let EI
        find better-than-random candidates around the incumbent (dense
        Gaussian moves alone either round back to the incumbent or jump
        uniformly far on such spaces)."""
        from uptune_tpu.space.params import BoolParam
        rng = np.random.RandomState(0)
        space = Space([BoolParam(f"f{i}") for i in range(48)])
        w = rng.randn(48) * 0.5

        def qor_of(u):
            flags = np.round(np.asarray(u))
            return 5.0 + flags @ w

        m = SurrogateManager(space, "gp", min_points=48,
                             explore_frac=0.0, propose_batch=16,
                             score="ei", pool_mult=32)
        cands = space.random(jax.random.PRNGKey(0), 192)
        qor = qor_of(cands.u)
        m.observe(np.asarray(space.features(cands)), qor)
        assert m.maybe_refit()
        i = int(np.argmin(qor))
        out = m.propose_pool(jax.random.PRNGKey(1), cands.u[i], (),
                             float(qor[i]))
        picked = qor_of(out.u)
        rand = qor_of(space.random(jax.random.PRNGKey(2), 512).u)
        # picked batch must improve on random sampling AND contain
        # something at least as good as the incumbent's neighbourhood
        assert picked.mean() < rand.mean(), (picked.mean(), rand.mean())
        assert picked.min() <= qor[i], (picked.min(), qor[i])

    def test_pool_perm_rows_are_permutations(self):
        space = Space([FloatParam("a", 0, 1),
                       PermParam("p", tuple(range(7)))])
        m = SurrogateManager(space, "gp", min_points=16, propose_batch=8,
                             pool_mult=8)
        cands = space.random(jax.random.PRNGKey(0), 32)
        m.observe(np.asarray(space.features(cands)), np.arange(32.0))
        assert m.maybe_refit()
        out = m.propose_pool(jax.random.PRNGKey(1), cands.u[0],
                             tuple(p[0] for p in cands.perms), 0.5)
        pm = np.asarray(out.perms[0])
        want = np.arange(7)
        for row in pm:
            assert (np.sort(row) == want).all(), row


@pytest.mark.slow
class TestTunerSurrogateTickets:
    def _opts(self, **kw):
        o = dict(min_points=24, refit_interval=24, select="topk",
                 keep_frac=0.5, explore_frac=0.1, score="ei",
                 propose_batch=8, propose_every=2, pool_mult=16)
        o.update(kw)
        return o

    def test_surrogate_tickets_attributed_and_credit_free(self, tmp_path):
        import json
        space = rosenbrock_space(2, -3.0, 3.0)
        arch = str(tmp_path / "a.jsonl")
        t = Tuner(space, rosenbrock_objective(2), seed=5, surrogate="gp",
                  surrogate_opts=self._opts(), archive=arch)
        t.run(test_limit=250)
        t.close()
        assert "surrogate" in t.arm_stats, t.arm_stats
        # archive rows carry the 'surrogate' attribution
        techs = set()
        with open(arch) as f:
            f.readline()  # header
            for line in f:
                techs.add(json.loads(line)["tech"])
        assert "surrogate" in techs, techs
        # no bandit credit entry is ever created for the surrogate plane
        # (injected tickets bypass MetaTechnique.credit)
        from uptune_tpu.techniques.bandit import MetaTechnique
        assert isinstance(t.root, MetaTechnique)
        assert "surrogate" not in [a.name for a in t.root.techniques]

    def test_surrogate_proposals_dedup_against_history(self):
        space = Space([IntParam("i", 0, 15), IntParam("j", 0, 15)])
        t = Tuner(space, lambda cfgs: [c["i"] + c["j"] for c in cfgs],
                  seed=2, surrogate="gp",
                  surrogate_opts=self._opts(min_points=16,
                                            refit_interval=16))
        t.run(test_limit=256)  # space has 256 configs: full saturation
        # every evaluation was of a distinct config (dedup held across
        # technique AND surrogate tickets): with 256 total configs, any
        # repeat evaluation would overshoot the count
        assert t.evals <= 256

    def test_resume_warms_surrogate(self, tmp_path):
        """Archive replay must feed the surrogate training set — a
        resumed run's GP starts fitted, not cold (the reference's
        resume() replays into the DBs its surrogate trains from,
        api.py:341-363)."""
        space = rosenbrock_space(2, -2.048, 2.048)
        obj = rosenbrock_objective(2)
        arch = str(tmp_path / "a.jsonl")
        t = Tuner(space, obj, seed=3, surrogate="gp",
                  surrogate_opts=self._opts(), archive=arch)
        t.run(test_limit=80)
        t.close()
        t2 = Tuner(space, obj, seed=4, surrogate="gp",
                   surrogate_opts=self._opts(), archive=arch,
                   resume=True)
        assert t2.evals >= 80
        assert t2.surrogate.fitted, "surrogate cold after resume"
        # and the proposal plane engages on the very first acquisitions
        t2.run(test_limit=t2.evals + 40)
        assert "surrogate" in t2.arm_stats, t2.arm_stats
        t2.close()

    def test_faster_than_filter_only_on_fixed_seed(self):
        """The proposal plane must beat the filter-only surrogate config
        on a fixed seed (the BENCHREPORT improvement, in-miniature)."""
        space = rosenbrock_space(2, -2.048, 2.048)
        obj = rosenbrock_objective(2)

        def iters_to(t, thresh, budget):
            res = t.run(test_limit=budget, target=thresh)
            t.close()
            for i, v in enumerate(res.trace):
                if v <= thresh:
                    return i + 1
            return budget

        with_pool = Tuner(space, obj, seed=11, surrogate="gp",
                          surrogate_opts=self._opts())
        filter_only = Tuner(space, obj, seed=11, surrogate="gp",
                            surrogate_opts=self._opts(propose_batch=0))
        a = iters_to(with_pool, 0.1, 600)
        b = iters_to(filter_only, 0.1, 600)
        assert a <= b, (a, b)
