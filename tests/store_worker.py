"""Jax-free cooperative-store worker process for the two-process
loopback tests (tests/test_multihost.py TestTwoProcessCooperativeStore,
ISSUE 18).

Holds one RemoteStore client (store/remote.py) pointed at the shared
`ut store` server whose address arrives in argv, and exposes a tiny
wire surface of its own so the parent test can command records and
observe what the client sees — all over real localhost TCP, zero jax.
Prints ``PORT <n>`` once listening; exits when its stdin closes (the
parent's teardown signal — no signal races, no orphan on parent
death)."""
import sys


def main() -> int:
    addr, tag = sys.argv[1], sys.argv[2]

    from uptune_tpu.serve.wire import WireServer
    from uptune_tpu.store.remote import RemoteStore

    store = RemoteStore(addr, ["coop-loopback-spec"], "coop-loopback",
                        refresh_interval=0.0)

    class Worker(WireServer):
        WIRE_NAME = "ut-store-worker"

        def __init__(self) -> None:
            super().__init__("127.0.0.1", 0)
            self.foreign = 0

        def _op_ping(self, req: dict) -> dict:
            return {"role": "store-worker", "tag": tag}

        def _op_record(self, req: dict) -> dict:
            """Record n rows under this worker's tag and wait until
            every one of them is ACKED by the store server."""
            n = int(req.get("n", 1))
            keys = []
            for i in range(n):
                row = store.record({"w": tag, "i": i},
                                   float(req.get("base", 0.0)) + i)
                if row is not None:
                    keys.append(row["k"])
            shipped = store.flush_wait(20.0)
            return {"keys": keys, "shipped": shipped}

        def _op_sync(self, req: dict) -> dict:
            """Pull the server's delta feed and report what this
            client now knows — fresh rows are the sibling's."""
            merged = store.refresh()
            fresh = store.pop_fresh_rows()
            with self._lock:
                self.foreign += len(fresh)
            best = store.best_row()
            return {"merged": merged,
                    "fresh": [r["cfg"] for r in fresh],
                    "foreign_total": self.foreign,
                    "rows": len(store),
                    "best_qor": None if best is None else best["qor"]}

        def _op_lookup(self, req: dict) -> dict:
            row = store.lookup(dict(req["cfg"]))
            return {"row": row}

        def _op_stats(self, req: dict) -> dict:
            return {"stats": store.stats()}

        _OPS = {"ping": _op_ping, "record": _op_record,
                "sync": _op_sync, "lookup": _op_lookup,
                "stats": _op_stats}

    w = Worker().start()
    print(f"PORT {w.port}", flush=True)
    sys.stdin.read()            # parent closes stdin to stop us
    w.stop()
    store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
