"""Space encoding/decoding semantics, mirroring the reference's unit-value
contracts (manipulator.py:473-503, 651-836) on the flat device encoding."""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uptune_tpu.space import (
    BoolParam, CandBatch, EnumParam, FloatParam, IntParam, LogFloatParam,
    LogIntParam, PermParam, Pow2Param, ScheduleParam, Space, SwitchParam,
    infer_param,
)


def small_space():
    return Space([
        FloatParam("f", 0.0, 10.0),
        IntParam("i", 1, 9),
        LogIntParam("li", 1, 1024),
        LogFloatParam("lf", 0.001, 1000.0),
        Pow2Param("p2", 2, 256),
        BoolParam("b"),
        SwitchParam("sw", 5),
        EnumParam("e", options=("a", "b", "c")),
        PermParam("perm", items=(0, 1, 2, 3, 4)),
    ])


def test_shapes_and_masks():
    sp = small_space()
    assert sp.n_scalar == 8
    assert sp.perm_sizes == (5,)
    assert np.asarray(sp.complex_mask).tolist() == [
        False, False, False, False, False, True, True, True]


def test_decode_endpoints_and_rounding():
    sp = small_space()
    lo = sp.decode_scalars(jnp.zeros((1, 8)))[0]
    hi = sp.decode_scalars(jnp.ones((1, 8)))[0]
    np.testing.assert_allclose(lo[0], 0.0, atol=1e-5)       # float lo
    np.testing.assert_allclose(hi[0], 10.0, atol=1e-5)      # float hi
    assert lo[1] == 1 and hi[1] == 9                         # int clamped
    assert lo[2] == 1 and hi[2] == 1024                      # log int
    np.testing.assert_allclose(lo[3], 0.001, rtol=1e-3)      # log float lo
    np.testing.assert_allclose(hi[3], 1000.0, rtol=1e-3)     # log float hi
    assert lo[4] == 2 and hi[4] == 256                       # pow2 values
    assert lo[5] == 0 and hi[5] == 1                         # bool codes
    assert lo[6] == 0 and hi[6] == 4                         # switch codes
    assert lo[7] == 0 and hi[7] == 2                         # enum codes


def test_int_rounding_uniformity():
    # unit->int decode must cover endpoints with the same width as interior
    # values (the +-0.4999 widening of manipulator.py:477-480).
    sp = Space([IntParam("i", 0, 3)])
    u = jnp.linspace(0.0, 1.0, 4001)[:, None]
    vals = np.asarray(sp.decode_scalars(u))[:, 0]
    counts = [int((vals == v).sum()) for v in range(4)]
    assert min(counts) > 0.8 * max(counts), counts


def test_pow2_decode_is_power_of_two():
    sp = Space([Pow2Param("p", 4, 64)])
    u = jax.random.uniform(jax.random.PRNGKey(0), (256, 1))
    vals = np.asarray(sp.decode_scalars(u))[:, 0]
    assert set(np.unique(vals)) <= {4.0, 8.0, 16.0, 32.0, 64.0}


def test_encode_decode_roundtrip_configs():
    sp = small_space()
    cands = sp.random(jax.random.PRNGKey(1), 32)
    cfgs = sp.to_configs(cands)
    back = sp.from_configs(cfgs)
    cfgs2 = sp.to_configs(back)
    for a, b in zip(cfgs, cfgs2):
        for k in a:
            if isinstance(a[k], float):
                assert a[k] == pytest.approx(b[k], rel=1e-3), k
            else:
                assert a[k] == b[k], k


def test_random_perms_valid():
    sp = small_space()
    cands = sp.random(jax.random.PRNGKey(2), 64)
    pm = np.asarray(cands.perms[0])
    for row in pm:
        assert sorted(row.tolist()) == [0, 1, 2, 3, 4]
    # not all identical
    assert len({tuple(r) for r in pm.tolist()}) > 10


def test_hash_consistency_and_spread():
    sp = small_space()
    cands = sp.random(jax.random.PRNGKey(3), 128)
    h1 = np.asarray(sp.hash_batch(cands))
    h2 = np.asarray(sp.hash_batch(cands))
    np.testing.assert_array_equal(h1, h2)
    pairs = {tuple(r) for r in h1.tolist()}
    assert len(pairs) == 128  # no collisions in a random batch
    # configs that decode identically hash identically even if raw unit
    # values differ (integer lanes quantize)
    spi = Space([IntParam("i", 0, 3)])
    ca = CandBatch(jnp.array([[0.50], [0.52]]), ())
    ha = np.asarray(spi.hash_batch(ca))
    assert tuple(ha[0]) == tuple(ha[1])


def test_search_space_size():
    sp = Space([IntParam("i", 1, 9), BoolParam("b"),
                PermParam("p", items=tuple(range(5)))])
    assert sp.search_space_size() == 9 * 2 * math.factorial(5)


def test_schedule_param_normalize():
    # b depends on a; c depends on b (transitively on a)
    sp = Space([ScheduleParam("s", items=("a", "b", "c"),
                              deps=(("b", ("a",)), ("c", ("b",))))])
    cands = sp.random(jax.random.PRNGKey(4), 16)
    for cfg in sp.to_configs(cands):
        order = cfg["s"]
        assert order.index("a") < order.index("b") < order.index("c")


def test_infer_param():
    assert isinstance(infer_param("x", 3, (1, 9)), IntParam)
    assert isinstance(infer_param("x", 0.5, (0.0, 1.0)), FloatParam)
    assert isinstance(infer_param("x", True, (True, False)), BoolParam)
    e = infer_param("x", "a", ["a", "b"])
    assert isinstance(e, EnumParam) and e.options == ("a", "b")
    p = infer_param("x", [0, 1, 2], [0, 1, 2])
    assert isinstance(p, PermParam)


def test_seed_default():
    sp = small_space()
    cfgs = sp.to_configs(sp.seed_default(2))
    assert cfgs[0]["i"] == 1 and cfgs[0]["p2"] == 2
    assert cfgs[0]["perm"] == [0, 1, 2, 3, 4]
