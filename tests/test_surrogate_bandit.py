"""Surrogate arbitration='bandit': the proposal plane as a credit-earning
VIRTUAL ARM of the AUC bandit (techniques/bandit.py register_virtual_arm +
driver/driver.py _surrogate_ticket(credit=True)).

Where the scheduled plane fires every propose_every-th acquisition
unconditionally (and the run-budget rule can only switch it off
wholesale), bandit arbitration routes the decision through the same AUC
credit math that arbitrates technique arms (reference credit semantics:
/root/reference/python/uptune/opentuner/search/bandittechniques.py:116-146)
— pulls that stop producing new bests decay the arm's score and the
bandit starves it, per run, with no static threshold."""
import warnings

import jax
import numpy as np
import pytest

from uptune_tpu.driver import Tuner
from uptune_tpu.space.params import FloatParam
from uptune_tpu.space.spec import Space
from uptune_tpu.techniques.bandit import AUCBanditMeta, AUCBanditQueue
from uptune_tpu.workloads import rosenbrock_objective, rosenbrock_space


def _opts(**kw):
    o = dict(min_points=16, refit_interval=16, select="topk",
             keep_frac=0.5, explore_frac=0.1, score="ei",
             propose_batch=8, pool_mult=16, arbitration="bandit")
    o.update(kw)
    return o


class TestQueueVirtualArms:
    def test_add_key_starts_unpulled(self):
        q = AUCBanditQueue(["a", "b"], seed=0)
        for k in ("a", "b"):
            for v in (True, False):
                q.on_result(k, v)
        q.add_key("v")
        assert q.use_counts["v"] == 0
        assert q.bandit_score("v") == float("inf")
        assert q.ordered_keys()[0] == "v"

    def test_add_key_idempotent(self):
        q = AUCBanditQueue(["a"], seed=0)
        q.on_result("a", True)
        q.add_key("a")
        assert q.keys.count("a") == 1
        assert q.use_counts["a"] == 1

    def test_loser_arm_demoted(self):
        """An arm whose pulls never produce new bests must rank below an
        arm with wins once both have been tried."""
        q = AUCBanditQueue(["good", "bad"], seed=0)
        for _ in range(10):
            q.on_result("good", True)
            q.on_result("bad", False)
        assert q.bandit_score("good") > q.bandit_score("bad")
        assert q.ordered_keys()[0] == "good"

    def test_meta_register_virtual_arm(self):
        from uptune_tpu.techniques.base import get_root
        root = get_root(["AUCBanditMetaTechniqueA"])
        assert isinstance(root, AUCBanditMeta)
        root.register_virtual_arm("surrogate")
        assert "surrogate" in root.bandit.use_counts
        assert "surrogate" in root.ordered_names()
        # Technique-only callers never see the virtual arm
        assert all(t.name != "surrogate" for t in root.select_order())

    def test_virtual_arm_name_collision_raises(self):
        from uptune_tpu.techniques.base import get_root
        root = get_root(["AUCBanditMetaTechniqueA"])
        with pytest.raises(ValueError):
            root.register_virtual_arm("DifferentialEvolutionAlt")


class TestDriverWiring:
    def test_registers_virtual_arm(self):
        space = rosenbrock_space(2, -2.0, 2.0)
        t = Tuner(space, rosenbrock_objective(2), seed=0, surrogate="gp",
                  surrogate_opts=_opts())
        assert t._surr_arm
        assert "surrogate" in t.root.bandit.use_counts

    def test_non_bandit_root_falls_back_with_warning(self):
        space = rosenbrock_space(2, -2.0, 2.0)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            t = Tuner(space, rosenbrock_objective(2), seed=0,
                      technique="PureRandom", surrogate="gp",
                      surrogate_opts=_opts())
        assert not t._surr_arm
        assert any("bandit" in str(x.message) for x in w)

    def test_propose_batch_zero_falls_back(self):
        space = rosenbrock_space(2, -2.0, 2.0)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            t = Tuner(space, rosenbrock_objective(2), seed=0,
                      surrogate="gp",
                      surrogate_opts=_opts(propose_batch=0))
        assert not t._surr_arm
        assert any("bandit" in str(x.message) for x in w)

    def test_budget_constrained_recipe(self):
        """BUDGET_CONSTRAINED_OPTS (the measured gcc-real winner,
        BENCHREPORT 30-seed table) wires bandit arbitration with
        8-eval pulls and no passivation."""
        from uptune_tpu.calibrated import BUDGET_CONSTRAINED_OPTS
        space = Space([FloatParam(f"x{i}", 0, 1) for i in range(32)])
        t = Tuner(space, lambda cfgs: [0.0] * len(cfgs), seed=0,
                  surrogate="gp",
                  surrogate_opts=dict(BUDGET_CONSTRAINED_OPTS))
        assert t._surr_arm
        assert t.surrogate.propose_batch == 8   # parity off
        t._apply_budget_rule(test_limit=5)      # 5 << 32 params
        assert not t.surrogate.passive          # auto_passive off

    def test_budget_rule_applies_recipe_by_arbitration(self):
        """r4 verdict #4: on a small budget the rule applies the
        measured-best budget-constrained recipe.  An explicitly
        bandit-arbitrated plane is left exactly as the user configured
        it (including pull-size parity); a scheduled plane is switched
        to bandit arbitration with parity off — and switched BACK on a
        later large-budget run (the rule is per run)."""
        space = Space([FloatParam(f"x{i}", 0, 1) for i in range(32)])
        # explicit bandit arbitration: untouched
        t = Tuner(space, lambda cfgs: [0.0] * len(cfgs), seed=0,
                  surrogate="gp",
                  surrogate_opts=_opts(arbitration="bandit",
                                       auto_passive=True))
        raised = t.surrogate.propose_batch      # parity raised at init
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            t._apply_budget_rule(test_limit=5)  # 5 << 32 params
        assert not t.surrogate.passive
        assert t._surr_arm
        assert t.surrogate.propose_batch == raised
        # scheduled plane: switched to the recipe, then reverted
        t2 = Tuner(space, lambda cfgs: [0.0] * len(cfgs), seed=0,
                   surrogate="gp",
                   surrogate_opts=_opts(arbitration="schedule",
                                        auto_passive=True))
        assert not t2._surr_arm
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            t2._apply_budget_rule(test_limit=5)
        assert not t2.surrogate.passive
        assert t2._surr_arm
        assert t2.surrogate.arbitration == "bandit"
        assert t2.surrogate.propose_batch == 8      # parity off
        assert any("BUDGET-CONSTRAINED" in str(x.message) for x in w)
        t2._apply_budget_rule(test_limit=4000)      # per-run revert
        assert t2.surrogate.arbitration == "schedule"
        assert not t2._surr_arm

    def test_pull_size_parity(self):
        """Under bandit arbitration the pool batch is raised to the
        median technique-arm batch (pull-size parity); opting out or
        using the schedule leaves the configured batch alone."""
        space = rosenbrock_space(2, -2.0, 2.0)
        obj = rosenbrock_objective(2)
        t = Tuner(space, obj, seed=0, surrogate="gp",
                  surrogate_opts=_opts())
        bs = sorted(m.natural_batch(space) for m in t.members)
        assert t.surrogate.propose_batch == max(8, bs[len(bs) // 2])
        t2 = Tuner(space, obj, seed=0, surrogate="gp",
                   surrogate_opts=_opts(propose_batch_parity=False))
        assert t2.surrogate.propose_batch == 8
        t3 = Tuner(space, obj, seed=0, surrogate="gp",
                   surrogate_opts=_opts(arbitration="schedule"))
        assert t3.surrogate.propose_batch == 8


@pytest.mark.slow
class TestBanditArbitrationRuns:
    def test_pulls_match_credit_events(self):
        """Every surrogate ticket the bandit pulls must feed exactly one
        AUC event: arm_stats pulls == queue use_counts (no phantom
        pulls, no uncredited pulls)."""
        space = rosenbrock_space(2, -2.048, 2.048)
        t = Tuner(space, rosenbrock_objective(2), seed=7, surrogate="gp",
                  surrogate_opts=_opts())
        t.run(test_limit=300)
        pulls = t.arm_stats.get("surrogate", [0, 0, 0])[0]
        assert pulls > 0, t.arm_stats
        assert t.root.bandit.use_counts["surrogate"] == pulls

    def test_useless_plane_is_starved(self):
        """A proposal plane that only ever re-proposes the incumbent
        (saturated pool) must cost nothing: no ticket is ever opened
        (the walk falls through to technique arms, keeping the
        random-injection saturation escape reachable — r4 review), no
        credit events accrue, and the dry backoff bounds how often the
        pool is even scored."""
        space = rosenbrock_space(2, -2.048, 2.048)

        class SaturatedManager:
            arbitration = "bandit"
            propose_batch = 8
            propose_every = 1
            fitted = True
            passive = False
            auto_passive = False

            def observe(self, feats, qor):
                pass

            def maybe_refit(self):
                return False

            def keep_mask(self, cands, candidate_mask=None):
                return None

            def propose_pool(self, key, best_u, best_perms, best_y):
                # 8 copies of the incumbent: always fully duplicate
                import jax.numpy as jnp
                from uptune_tpu.space.spec import CandBatch
                u = jnp.tile(jnp.asarray(best_u)[None, :], (8, 1))
                return CandBatch(u, ())

            def prune(self, *a, **kw):
                return None

        t = Tuner(space, rosenbrock_objective(2), seed=9,
                  surrogate=SaturatedManager())
        assert t._surr_arm
        res = t.run(test_limit=200)
        # the run itself made progress through technique arms
        assert res.evals >= 100, res.evals
        # a saturated pool never opens a ticket: zero pulls, zero
        # credit events, zero evals attributed to the plane
        assert t.root.bandit.use_counts["surrogate"] == 0
        assert "surrogate" not in t.arm_stats
        assert t.root.bandit.exploitation_term("surrogate") == 0.0

    def test_helpful_plane_outscores_techniques(self):
        """On smooth rosenbrock the fitted GP plane produces new bests
        at a far higher rate than mutation arms — the bandit must
        learn to rank it first (the r4 design's whole point)."""
        space = rosenbrock_space(4, -2.048, 2.048)
        t = Tuner(space, rosenbrock_objective(4), seed=5, surrogate="gp",
                  surrogate_opts=_opts())
        t.run(test_limit=500)
        bq = t.root.bandit
        assert bq.use_counts["surrogate"] > 0
        others = [bq.bandit_score(k) for k in bq.keys if k != "surrogate"]
        assert bq.bandit_score("surrogate") > max(others), {
            k: bq.bandit_score(k) for k in bq.keys}
