"""Content-addressed results store (uptune_tpu/store/, docs/STORE.md):
key derivation, the append-only segment layout (incl. the two-process
atomic-append race), cache-hit serving through ProgramTuner (a repeated
identical tune re-executes nothing), resume-vs-store equivalence under
a counting evaluator, cross-tune warm start, multi-instance exchange,
and the `bench.py --cache --quick` smoke + strict trace-guard CLI run
that keep the serve path from rotting."""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import uptune_tpu
from uptune_tpu.api import constraint as C
from uptune_tpu.api import session
from uptune_tpu.exec.controller import ProgramTuner
from uptune_tpu.store import (ResultStore, canon_config, eval_signature,
                              scope_id, trial_key)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    uptune_tpu.__file__)))
ENV = {"PYTHONPATH": REPO}

SIG = ["IntParam('x', 0, 100)", "IntParam('y', 0, 100)"]

QUAD = textwrap.dedent("""
    import uptune_tpu as ut
    x = ut.tune(50, (0, 100), name="x")
    y = ut.tune(50, (0, 100), name="y")
    ut.target(float((x - 37) ** 2 + (y - 11) ** 2), "min")
""")

# counting evaluator: every REAL trial execution (not the profiling
# run) appends its config to an exec log — re-executions are visible
COUNTING = textwrap.dedent("""
    import os
    import uptune_tpu as ut
    x = ut.tune(50, (0, 100), name="x")
    y = ut.tune(50, (0, 100), name="y")
    if os.environ.get("UT_TUNE_START"):
        with open({log!r}, "a") as f:
            f.write(f"{{x}},{{y}}\\n")
    ut.target(float((x - 37) ** 2 + (y - 11) ** 2), "min")
""")


@pytest.fixture(autouse=True)
def clean_env(monkeypatch):
    for v in ("UT_BEFORE_RUN_PROFILE", "UT_TUNE_START", "BEST",
              "UT_WORK_DIR", "UT_TRACE_GUARD"):
        monkeypatch.delenv(v, raising=False)
    C.REGISTRY.clear()
    session.reset_settings()
    yield


def _mk(tmp_path, body, name="prog.py", **kw):
    p = tmp_path / name
    p.write_text(body)
    kw.setdefault("parallel", 2)
    kw.setdefault("env", ENV)
    kw.setdefault("runtime_limit", 30.0)
    return ProgramTuner([sys.executable, str(p)], str(tmp_path), **kw)


def _exec_lines(log):
    return [l for l in log.read_text().splitlines() if l.strip()] \
        if log.exists() else []


# ---------------------------------------------------------------------
class TestKeys:
    def test_key_stable_across_value_representations(self):
        sc = scope_id(SIG, eval_signature(["true"], 0))
        k1 = trial_key(sc, {"x": 3, "y": 0.5})
        k2 = trial_key(sc, {"y": np.float32(0.5).item(), "x": np.int64(3)})
        assert k1 == k2
        assert canon_config({"b": -0.0}) == canon_config({"b": 0.0})

    def test_key_sensitive_to_config_space_stage_command(self):
        es = eval_signature(["true"], 0)
        sc = scope_id(SIG, es)
        base = trial_key(sc, {"x": 1})
        assert trial_key(sc, {"x": 2}) != base
        assert trial_key(scope_id(SIG[:1], es), {"x": 1}) != base
        assert trial_key(scope_id(SIG, eval_signature(["true"], 1)),
                         {"x": 1}) != base
        assert trial_key(scope_id(SIG, eval_signature(["false"], 0)),
                         {"x": 1}) != base

    def test_command_is_content_addressed(self, tmp_path):
        """Editing a file argument changes the signature; moving the
        work dir (same content, different path) does not; the
        interpreter collapses to 'python'."""
        a = tmp_path / "a" / "prog.py"
        b = tmp_path / "b" / "prog.py"
        a.parent.mkdir()
        b.parent.mkdir()
        a.write_text("print(1)\n")
        b.write_text("print(1)\n")
        s_a = eval_signature([sys.executable, str(a)], 0)
        assert eval_signature([sys.executable, str(b)], 0) == s_a
        assert '"python"' in s_a and sys.executable not in s_a
        b.write_text("print(2)\n")
        assert eval_signature([sys.executable, str(b)], 0) != s_a

    def test_program_named_python_is_still_content_hashed(self, tmp_path):
        """Only the interpreter IDENTITY collapses: a tuned program
        that happens to be named python.py keeps its content hash, so
        editing it still invalidates its rows."""
        p = tmp_path / "python.py"
        p.write_text("print(1)\n")
        s1 = eval_signature([sys.executable, str(p)], 0)
        assert "file:python.py:" in s1
        p.write_text("print(2)\n")
        assert eval_signature([sys.executable, str(p)], 0) != s1

    def test_env_forks_the_scope_but_pythonpath_does_not(self):
        """Two tunes of one program under different build env measure
        different things (CFLAGS!) and must not share rows; PYTHONPATH
        is controller plumbing and must not fork the scope."""
        base = eval_signature(["true"], 0, env={"CFLAGS": "-O0"})
        assert eval_signature(["true"], 0, env={"CFLAGS": "-O3"}) != base
        assert eval_signature(
            ["true"], 0,
            env={"CFLAGS": "-O0", "PYTHONPATH": "/anywhere"}) == base


# ---------------------------------------------------------------------
class TestResultStore:
    def test_record_lookup_reopen_roundtrip(self, tmp_path):
        root = str(tmp_path / "store")
        with ResultStore(root, SIG, ["true"]) as st:
            assert st.lookup({"x": 1, "y": 2}) is None
            st.record({"x": 1, "y": 2}, 7.5, 0.25, u=[0.01, 0.02],
                      perms=[])
            row = st.lookup({"x": 1, "y": 2})
            assert row["qor"] == 7.5 and row["u"] == [0.01, 0.02]
        with ResultStore(root, SIG, ["true"]) as st2:
            assert st2.lookup({"x": 1, "y": 2})["qor"] == 7.5
            # different scope (other command) must not see the row
        with ResultStore(root, SIG, ["false"]) as st3:
            assert st3.lookup({"x": 1, "y": 2}) is None
            assert st3.scope_rows() == []

    def test_failures_recorded_not_served_and_upgraded(self, tmp_path):
        with ResultStore(str(tmp_path), SIG, ["true"]) as st:
            st.record({"x": 1}, None, 1.0)      # build failure
            assert st.lookup({"x": 1}) is None
            assert len(st) == 1                  # ...but bookkept
            st.record({"x": 1}, 3.0, 1.0)        # retry succeeded
            assert st.lookup({"x": 1})["qor"] == 3.0
            # idempotent re-record: a finite row is never replaced
            assert st.record({"x": 1}, 9.0, 1.0) is None
            assert st.lookup({"x": 1})["qor"] == 3.0

    def test_torn_tail_line_is_ignored_until_complete(self, tmp_path):
        root = str(tmp_path)
        st = ResultStore(root, SIG, ["true"])
        st.record({"x": 1}, 1.0)
        st.close()
        seg = [f for f in os.listdir(root) if f.startswith("seg-")][0]
        with open(os.path.join(root, seg), "a") as f:
            f.write('{"k": "torn')          # crashed mid-append
        st2 = ResultStore(root, SIG, ["true"])
        assert len(st2) == 1                 # torn row invisible
        assert st2.lookup({"x": 1})["qor"] == 1.0

    def test_compact_merges_and_truncates_own_segment(self, tmp_path):
        root = str(tmp_path)
        a = ResultStore(root, SIG, ["true"])
        for i in range(5):
            a.record({"x": i}, float(i))
        assert a.compact() == 5
        a.close()
        assert os.path.exists(os.path.join(root, "base.jsonl"))
        assert not [f for f in os.listdir(root) if f.startswith("seg-")]
        b = ResultStore(root, SIG, ["true"])
        assert len(b) == 5 and b.lookup({"x": 3})["qor"] == 3.0

    def test_best_row_respects_sense(self, tmp_path):
        with ResultStore(str(tmp_path), SIG, ["true"]) as st:
            st.record({"x": 1}, 5.0)
            st.record({"x": 2}, 2.0)
            st.record({"x": 3}, 9.0)
            assert st.best_row("min")["qor"] == 2.0
            assert st.best_row("max")["qor"] == 9.0


RACER = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    from uptune_tpu.store import ResultStore
    root, tag = sys.argv[1], int(sys.argv[2])
    st = ResultStore(root, {sig!r}, ["true"])
    for i in range(250):
        st.record({{"x": tag * 1000 + i}}, float(i), 0.01)
    st.close()
""")


class TestMultiInstance:
    def test_two_process_append_race(self, tmp_path):
        """The atomic-segment protocol: two processes hammering one
        store directory concurrently lose no rows and tear no lines."""
        root = str(tmp_path / "race")
        script = str(tmp_path / "racer.py")
        with open(script, "w") as f:
            f.write(RACER.format(repo=REPO, sig=SIG))
        procs = [subprocess.Popen([sys.executable, script, root, str(t)],
                                  env={**os.environ, **ENV})
                 for t in (1, 2)]
        for p in procs:
            assert p.wait(timeout=120) == 0
        st = ResultStore(root, SIG, ["true"])
        assert len(st) == 500
        assert st.lookup({"x": 1000})["qor"] == 0.0
        assert st.lookup({"x": 2249})["qor"] == 249.0

    def test_sibling_compact_does_not_blind_running_peers(self, tmp_path):
        """compact() replaces base.jsonl by rename and truncates the
        caller's own segment: a RUNNING peer's remembered byte offsets
        point into dead files, and must reset (inode/shrink check) so
        post-compact appends stay visible."""
        root = str(tmp_path)
        a = ResultStore(root, SIG, ["true"], refresh_interval=0.0)
        b = ResultStore(root, SIG, ["true"], refresh_interval=0.0)
        for i in range(10):
            a.record({"x": i}, float(i))
        b.refresh()
        assert len(b) == 10
        a.compact()                      # base replaced, seg-A deleted
        a.record({"x": 100}, 100.0)      # fresh seg-A, small file
        b.refresh()
        assert b.lookup({"x": 100})["qor"] == 100.0
        assert len(b) == 11

    def test_refresh_sees_sibling_appends(self, tmp_path):
        root = str(tmp_path)
        a = ResultStore(root, SIG, ["true"], refresh_interval=0.0)
        b = ResultStore(root, SIG, ["true"], refresh_interval=0.0)
        a.record({"x": 1}, 1.0)
        assert b.lookup({"x": 1}) is None     # not yet refreshed
        b.refresh()
        assert b.lookup({"x": 1})["qor"] == 1.0
        assert b.foreign_rows >= 1
        b.record({"x": 2}, 2.0)
        a.refresh()
        assert a.lookup({"x": 2})["qor"] == 2.0


# ---------------------------------------------------------------------
class TestControllerServe:
    def test_repeated_tune_eliminates_builds(self, tmp_path):
        """A repeated identical lockstep tune must re-execute NOTHING:
        run 2 serves every trial from the store (the BENCH_CACHE.json
        protocol), and the counting evaluator proves no config ever
        ran twice."""
        log = tmp_path / "execs.log"
        body = COUNTING.format(log=str(log))
        kw = dict(parallel=1, prefetch=0, test_limit=6, seed=0)
        pt1 = _mk(tmp_path, body, **kw)
        res1 = pt1.run()
        lines1 = _exec_lines(log)
        assert pt1.pool.launched == len(lines1) > 0
        pt2 = _mk(tmp_path, body, **kw)
        res2 = pt2.run()
        assert pt2.pool.launched == 0, "run 2 must build nothing"
        assert pt2.store_hits == res2.evals - 1  # seed came profiled
        assert _exec_lines(log) == lines1
        # identical stream: run 2's archive replays run 1's trials —
        # the same configs, served instead of built
        assert res2.best_qor == res1.best_qor
        rows = [json.loads(l) for l in
                open(tmp_path / "ut.archive.jsonl")][1:]
        cfgs = [json.dumps(r["cfg"], sort_keys=True) for r in rows]
        assert len(cfgs) == res1.evals + res2.evals
        assert set(cfgs) == {json.dumps(r["cfg"], sort_keys=True)
                             for r in rows[:res1.evals]}

    def test_store_off_disables(self, tmp_path):
        pt = _mk(tmp_path, QUAD, test_limit=4, seed=1, store_dir="off")
        pt.run()
        assert pt.store is None
        assert not (tmp_path / "ut.temp" / "store").exists()

    def test_resume_never_reexecutes_recorded_configs(self, tmp_path):
        """Kill-and-resume equivalence: the resumed run's archive is
        duplicate-free and the counting evaluator saw every config
        exactly once — archived rows are ingested into the store and
        history, so neither replay nor re-proposal builds again."""
        log = tmp_path / "execs.log"
        body = COUNTING.format(log=str(log))
        pt1 = _mk(tmp_path, body, parallel=1, test_limit=4, seed=4)
        pt1.run()
        n1 = len(_exec_lines(log))
        pt2 = _mk(tmp_path, body, parallel=1, test_limit=10, seed=4,
                  resume=True)
        res = pt2.run()
        assert res.evals == 10
        lines = _exec_lines(log)
        assert len(lines) == len(set(lines)), "a config ran twice"
        assert len(lines) == n1 + pt2.pool.launched
        rows = [json.loads(l) for l in
                open(tmp_path / "ut.archive.jsonl")][1:]
        cfgs = [json.dumps(r["cfg"], sort_keys=True) for r in rows]
        assert len(cfgs) == len(set(cfgs)) == 10

    @pytest.mark.slow
    def test_warm_start_from_sibling_work_dir(self, tmp_path):
        """A second tune in a DIFFERENT work dir sharing the store
        warm-starts: best-so-far at least as good as run 1's, recorded
        configs never re-proposed (budget goes to new configs only).
        Slow-marked for suite-budget headroom (ISSUE 6): the fast
        tier-1 siblings are TestSurrogateWarmStart (manager-level
        warm-start fit) and the preload/exchange serve tests."""
        wd1, wd2 = tmp_path / "a", tmp_path / "b"
        wd1.mkdir()
        wd2.mkdir()
        store = str(tmp_path / "shared-store")
        pt1 = _mk(wd1, QUAD, test_limit=6, seed=1, store_dir=store)
        res1 = pt1.run()
        pt2 = _mk(wd2, QUAD, test_limit=5, seed=1, store_dir=store,
                  warm_start=True)
        res2 = pt2.run()
        assert res2.best_qor <= res1.best_qor
        rows1 = [json.loads(l) for l in
                 open(wd1 / "ut.archive.jsonl")][1:]
        rows2 = [json.loads(l) for l in
                 open(wd2 / "ut.archive.jsonl")][1:]
        c1 = {json.dumps(r["cfg"], sort_keys=True) for r in rows1}
        c2 = {json.dumps(r["cfg"], sort_keys=True) for r in rows2}
        assert not (c1 & c2), "warm start re-measured a stored config"

    def test_exchange_propagates_concurrent_sibling_best(self, tmp_path):
        """Multi-instance exchange: while this instance tunes, a
        'sibling' (a second ResultStore handle on the same directory)
        appends the optimum.  The next refresh delta must inject it as
        an 'exchange' trial, served from the store — the new best
        propagates with zero build cost."""
        from uptune_tpu.driver.plugins import SearchHook
        store_root = str(tmp_path / "shared-store")
        state = {"pt": None, "planted": False}

        class Sibling(SearchHook):
            def on_start(self, tuner):
                # the controller opened its store just before building
                # the tuner: tighten the refresh cadence for the test
                state["pt"].store.refresh_interval = 0.0

            def on_result(self, tuner, trial, qor):
                if state["planted"]:
                    return
                state["planted"] = True
                pt = state["pt"]
                sib = ResultStore(
                    store_root, [repr(s) for s in pt.tuner.space.specs],
                    pt.command)
                sib.record({"x": 37, "y": 11}, 0.0, 0.5)  # the optimum
                sib.close()

        pt = _mk(tmp_path, QUAD, test_limit=8, seed=3,
                 store_dir=store_root, hooks=[Sibling()])
        state["pt"] = pt
        res = pt.run()
        assert res.best_qor == 0.0
        assert res.best_config == {"x": 37, "y": 11}
        rows = [json.loads(l) for l in
                open(tmp_path / "ut.archive.jsonl")][1:]
        ex = [r for r in rows if r["tech"] == "exchange"]
        assert len(ex) == 1 and ex[0]["qor"] == 0.0
        assert pt.exchange_injected == 1
        assert pt.store_hits >= 1   # the exchange trial was served


    def test_warm_start_respects_session_constraints(self, tmp_path):
        """Stored rows carry the RAW QoR; @ut.constraint must gate the
        warm-start preload exactly as it gates serve-time hits — a
        violating row must never become an unbeatable preloaded best
        (and the exchange plane must not keep re-injecting it)."""
        records = [{"name": "x", "type": "int", "default": 50,
                    "lo": 0, "hi": 100}]
        (tmp_path / "ut.params.json").write_text(json.dumps([records]))
        from uptune_tpu.exec.space_io import space_from_params
        sig = [repr(s) for s in space_from_params(records).specs]
        store_dir = str(tmp_path / "store")
        with ResultStore(store_dir, sig, ["true"]) as seedst:
            seedst.record({"x": 1}, 5.0, 0.1)    # raw best, VIOLATES
            seedst.record({"x": 2}, 30.0, 0.1)   # valid

        @uptune_tpu.constraint()
        def floor(qor, cfg):
            return qor > 20.0

        pt = ProgramTuner(["true"], str(tmp_path), parallel=1,
                          test_limit=2, seed=0, store_dir=store_dir,
                          warm_start=True, env=ENV, runtime_limit=10.0)
        res = pt.run()
        assert res.best_qor == 30.0, \
            "violating stored row leaked into best-so-far"
        assert pt.exchange_injected <= 1


# ---------------------------------------------------------------------
class TestTunerPreload:
    def test_preload_sets_best_without_counters(self):
        from uptune_tpu.driver import Tuner
        from uptune_tpu.workloads import rosenbrock_space
        space = rosenbrock_space(4, -3.0, 3.0)
        t = Tuner(space, None, seed=0)
        cands = space.random(__import__("jax").random.PRNGKey(7), 8)
        u = np.asarray(cands.u)
        qor = np.arange(8, dtype=np.float32) + 5.0
        n = t.preload(u, [np.asarray(p) for p in cands.perms], qor)
        assert n == 8
        assert float(t.best.qor) == 5.0
        assert t.evals == 0 and t.told == 0 and t.trace == []
        # preloaded rows are history-known: injecting one opens no trial
        cfg = space.to_configs(cands[np.asarray([0])])[0]
        assert t.inject([cfg]) == []
        # non-finite rows are dropped
        assert t.preload(u[:2], [np.asarray(p)[:2] for p in cands.perms],
                         [float("inf"), float("nan")]) == 0

    def test_preload_never_double_trains_surrogate(self):
        """Rows already in the dedup history (a --resume replay
        followed by a warm start over the same trials) must not be
        observed into the surrogate training set a second time."""
        import jax

        from uptune_tpu.driver import Tuner
        from uptune_tpu.workloads import rosenbrock_space
        space = rosenbrock_space(2, -3.0, 3.0)
        t = Tuner(space, None, seed=0, surrogate="gp",
                  surrogate_opts={"min_points": 64})
        cands = space.random(jax.random.PRNGKey(3), 8)
        u = np.asarray(cands.u)
        perms = [np.asarray(p) for p in cands.perms]
        qor = np.arange(8, dtype=np.float32)
        t.preload(u, perms, qor, refit=False)
        assert t.surrogate.n_points == 8
        t.preload(u, perms, qor, refit=False)
        assert t.surrogate.n_points == 8, "history dups re-observed"


# ---------------------------------------------------------------------
class TestSurrogateWarmStart:
    def test_manager_warm_start_fits_immediately(self):
        """SurrogateManager.warm_start (the library-mode ingestion
        hook): bulk rows + an immediate fit, ignoring the online
        refit_interval cadence."""
        import jax

        from uptune_tpu.surrogate.manager import SurrogateManager
        from uptune_tpu.workloads import rosenbrock_space
        space = rosenbrock_space(2, -3.0, 3.0)
        sm = SurrogateManager(space, "gp", min_points=8,
                              refit_interval=512)
        cands = space.random(jax.random.PRNGKey(0), 16)
        feats = np.asarray(space.features(cands))
        assert not sm.fitted
        assert sm.warm_start(feats, np.arange(16, dtype=np.float32))
        assert sm.fitted and sm.n_points == 16


# ---------------------------------------------------------------------
class TestEndToEndGates:
    def test_cache_bench_quick_smoke(self, tmp_path):
        """`bench.py --cache --quick` must keep producing its evidence
        JSON with full elimination on the lockstep repeat protocol —
        the cache path can't silently rot."""
        env = {**os.environ, **ENV}
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--cache",
             "--quick"], capture_output=True, text=True, env=env,
            cwd=str(tmp_path), timeout=420)
        assert r.returncode == 0, r.stdout + r.stderr
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["metric"] == "store_build_elimination"
        assert out["value"] >= 0.9
        assert out["run2"]["builds"] == 0
        assert os.path.exists(os.path.join(REPO,
                                           "BENCH_CACHE.quick.json"))

    def test_full_ut_run_strict_trace_guard_with_store(self, tmp_path):
        """Acceptance gate: a full `ut` CLI tune with the store enabled
        (default) AND span tracing on (`--trace`, ISSUE 7) passes
        UT_TRACE_GUARD=strict — neither the serve path nor the
        observability plane adds retraces, and the exported trace
        validates against the schema with the guard report merged into
        it (no separate stderr report when traced)."""
        prog = tmp_path / "prog.py"
        prog.write_text(QUAD)
        trace = tmp_path / "out_trace.json"
        env = {**os.environ, **ENV, "UT_TRACE_GUARD": "strict"}
        r = subprocess.run(
            [sys.executable, "-m", "uptune_tpu.cli", str(prog),
             "--test-limit", "6", "-pf", "2", "--trace", str(trace)],
            capture_output=True, text=True, env=env, cwd=str(tmp_path),
            timeout=420)
        assert r.returncode == 0, r.stdout + r.stderr
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["evals"] >= 6
        assert (tmp_path / "ut.temp" / "store").is_dir()
        from uptune_tpu import obs
        with open(trace) as f:
            doc = json.load(f)
        obs.validate_trace(doc)
        # the retrace report ships inside the export when tracing
        assert doc["otherData"]["trace_guard"]["excess"] == {}
        lanes = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert any(l.startswith("worker-") for l in lanes)
        assert (tmp_path / "out_trace.json.metrics.jsonl").is_file()
