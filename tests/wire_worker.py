"""Jax-free wire-kernel worker process for the loopback multi-process
tests (tests/test_multihost.py TestTwoProcessLoopback, ISSUE 17).

Speaks the repo wire protocol over real localhost TCP through the
asyncio wire kernel (serve/wire.py) without importing the engine: a
stand-in "host" holding a local best that the parent test routes work
onto through the consistent-hash Router.  Prints ``PORT <n>`` once
listening; exits when its stdin closes (the parent's teardown signal —
no signal races, no orphan on parent death)."""
import sys


def main() -> int:
    from uptune_tpu.serve.wire import WireServer

    class Worker(WireServer):
        WIRE_NAME = "ut-mh-worker"

        def __init__(self) -> None:
            super().__init__("127.0.0.1", 0)
            self.best = None
            self.tells = 0

        def _op_ping(self, req: dict) -> dict:
            return {"role": "loopback-worker"}

        def _op_tell(self, req: dict) -> dict:
            qor = float(req["qor"])
            with self._lock:
                self.tells += 1
                if self.best is None or qor < self.best:
                    self.best = qor
                return {"best": self.best, "tells": self.tells}

        def _op_best(self, req: dict) -> dict:
            with self._lock:
                return {"best": self.best, "tells": self.tells}

        _OPS = {"ping": _op_ping, "tell": _op_tell, "best": _op_best}

    w = Worker().start()
    print(f"PORT {w.port}", flush=True)
    sys.stdin.read()            # parent closes stdin to stop us
    w.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
