"""Search-quality observability (ISSUE 12): the tuning journal, the
online QualityMonitor and its exact offline replay, the stall /
miscalibration / failure detectors, the serve health op, `ut report`
rendering, `ut top --json`, and the committed example artifacts.

The acceptance spine: (1) online convergence/calibration gauges equal
an exact offline recomputation from the journal of the same
matched-seed run; (2) alerts fire on a synthetic stalled tune and a
deliberately miswired surrogate and stay silent on a healthy
rosenbrock run; (3) the committed example report renders from the
committed journal.  The tiny driver e2e here is the fast tier-1
sibling of the slow-marked `bench.py --report --quick` subprocess
smoke.
"""
import json
import os
import subprocess
import sys

import pytest

from uptune_tpu import obs
from uptune_tpu.obs import journal, quality
from uptune_tpu.obs import report as obs_report
from uptune_tpu.obs.quality import QualityConfig, SessionQuality

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_obs():
    yield
    journal.stop()
    obs.reset()


# ---------------------------------------------------------------- journal
class TestJournal:
    def test_disabled_is_noop(self, tmp_path):
        assert not journal.enabled()
        journal.emit("tell", gid=0)       # must not raise or write
        assert journal.path() is None

    def test_round_trip_header_and_rows(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        journal.start(p, meta={"k": "v"})
        journal.emit("snapshot", version=1, n_rows=8, bucket=16)
        journal.emit("step", step=1, arm="de", evaluated=2,
                     new_best=True, best=1.0, evals=2, src="technique",
                     batch=8, trials=2, dup=6, filtered=0, gids=[0, 1],
                     ok=[True, True], qors=[1.0, 2.0],
                     nb=[True, False], durs=[0.1, 0.1])
        journal.stop()
        header, rows = journal.read(p, strict=True)
        assert header["journal"] == journal.SCHEMA_VERSION
        assert header["meta"] == {"k": "v"}
        assert [r["ev"] for r in rows] == ["snapshot", "step"]
        assert rows[1]["qors"] == [1.0, 2.0] and rows[1]["t"] >= 0

    def test_torn_tail_tolerated_lenient_rejected_strict(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        journal.start(p)
        journal.emit("store_hit", gid=0, qor=1.0, dur=0.0)
        journal.stop()
        with open(p, "a") as f:
            f.write('{"ev": "store_hit", "gid": 1')  # torn final line
        header, rows = journal.read(p)
        assert len(rows) == 1
        # final-line tears are legal even in strict mode (crashed
        # writer); a mid-stream tear is not
        _, rows2 = journal.read(p, strict=True)
        assert len(rows2) == 1

    def test_strict_rejects_unknown_kind(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        journal.start(p)
        journal.emit("store_hit", gid=0, qor=1.0, dur=0.0)
        journal.stop()
        with open(p, "a") as f:
            f.write(json.dumps({"ev": "martian", "t": 0.0}) + "\n")
        with pytest.raises(ValueError, match="martian"):
            journal.read(p, strict=True)

    def test_sink_sees_rows_before_serialization(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        seen = []
        journal.add_sink(seen.append)
        try:
            journal.start(p)
            journal.emit("store_hit", gid=7, qor=1.0, dur=0.0)
        finally:
            journal.remove_sink(seen.append)
        assert seen and seen[0]["gid"] == 7

    def test_buffered_rows_flush_on_stop(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        journal.start(p)
        for i in range(10):      # below the flush threshold
            journal.emit("store_hit", gid=i, qor=1.0, dur=0.0)
        journal.stop()
        _, rows = journal.read(p, strict=True)
        assert len(rows) == 10


# ------------------------------------------------------- quality monitor
def _step(i, qor, best, new_best, ok=True, mu=None, sigma=None, **kw):
    """One synthetic single-trial step row (the journal packs per-trial
    outcomes as arrays on the ticket's step row)."""
    row = {"ev": "step", "t": float(i), "step": i, "arm": "de",
           "evaluated": 1, "withdrawn": False, "new_best": new_best,
           "best": best, "evals": i + 1, "gids": [i], "ok": [ok],
           "qors": [qor if ok else None], "nb": [new_best],
           "durs": [0.0], **kw}
    if mu is not None:
        row["mus"], row["sigmas"] = [mu], [sigma]
    return row


class TestQualityMonitor:
    def test_calibration_math_exact(self):
        # four joined rows with hand-checkable moments
        rows = [
            _step(0, 1.0, 1.0, True, mu=1.5, sigma=1.0),   # z = -0.5
            _step(1, 2.0, 1.0, False, mu=2.0, sigma=1.0),  # z = 0
            _step(2, 4.0, 1.0, False, mu=1.0, sigma=1.0),  # z = 3
            _step(3, 3.0, 1.0, False, mu=2.5, sigma=0.2),  # z = 2.5
        ]
        mon = quality.replay(rows)
        g = mon.gauges
        assert g["search.cal_rows"] == 4
        assert g["search.cal_mae"] == round((0.5 + 0 + 3 + 0.5) / 4, 6)
        assert g["search.cal_cover95"] == 0.5   # |z|<=1.96: rows 0, 1
        assert g["search.cal_cover50"] == 0.5
        # mus [1.5, 2, 1, 2.5] vs qors [1, 2, 4, 3]: imperfect ranking
        assert -1.0 <= g["search.cal_rank_corr"] < 1.0
        assert g["search.best_qor"] == 1.0
        assert g["search.tells_since_best"] == 3

    def test_stall_alert_fires_once_and_rearms(self):
        cfg = QualityConfig(stall_tells=5)
        rows = [_step(i, 2.0, 1.0, False) for i in range(8)]
        rows += [_step(8, 0.5, 0.5, True)]
        rows += [_step(9 + i, 2.0, 0.5, False) for i in range(6)]
        mon = quality.replay(rows, cfg)
        kinds = [a["kind"] for a in mon.alerts]
        assert kinds == ["stall", "stall"]      # one per episode
        assert mon.alerts[0]["tells_since_best"] == 5

    def test_miscalibration_alert_on_miswired_surrogate(self):
        # deliberately miswired: confident (sigma ~ 0) and wrong —
        # interval coverage collapses, the detector must fire
        cfg = QualityConfig(min_cal_rows=10)
        rows = [_step(i, float(i % 7), 0.0, i == 0,
                      mu=100.0, sigma=1e-6) for i in range(12)]
        mon = quality.replay(rows, cfg)
        kinds = [a["kind"] for a in mon.alerts]
        assert "miscalibration" in kinds
        assert mon.gauges["search.cal_cover95"] == 0.0

    def test_uselessly_wide_intervals_alert(self):
        # sigma ~1e9 wider than the actual error: coverage is perfect
        # but the intervals rank nothing — the median-|z| floor fires
        cfg = QualityConfig(min_cal_rows=10)
        rows = [_step(i, float(i % 7), 0.0, i == 0,
                      mu=3.0, sigma=1e9) for i in range(12)]
        mon = quality.replay(rows, cfg)
        assert any(a["kind"] == "miscalibration" for a in mon.alerts)
        assert mon.gauges["search.cal_cover50"] == 1.0
        assert mon.gauges["search.cal_med_abs_z"] < 1e-6

    def test_accurate_but_conservative_model_is_not_flagged(self):
        # honest accuracy with generous sigma: coverage ~100% yet the
        # errors are a meaningful fraction of the interval — healthy
        cfg = QualityConfig(min_cal_rows=10)
        rows = [_step(i, float(i % 7), 0.0, i == 0,
                      mu=float(i % 7) + 0.2, sigma=1.0)
                for i in range(12)]
        mon = quality.replay(rows, cfg)
        assert mon.alerts == []

    def test_failure_rate_alert(self):
        cfg = QualityConfig(fail_window=8, fail_rate_hi=0.5)
        rows = [_step(i, None, None, False, ok=False)
                for i in range(8)]
        mon = quality.replay(rows, cfg)
        assert [a["kind"] for a in mon.alerts] == ["failures"]
        assert mon.gauges["search.fail_rate"] == 1.0

    def test_healthy_stream_stays_silent(self):
        rows = []
        best = 10.0
        for i in range(60):
            q = 10.0 - 0.15 * i
            nb = q < best
            best = min(best, q)
            rows.append(_step(i, q, best, nb, mu=q + 0.1, sigma=1.0))
        mon = quality.replay(rows)
        assert mon.alerts == []
        assert mon.gauges["search.cal_cover95"] == 1.0

    def test_pull_and_arm_rates(self):
        rows = [
            {"ev": "step", "t": 1.0, "step": 1, "arm": "de",
             "evaluated": 4, "withdrawn": False, "new_best": True,
             "best": 1.0, "evals": 4, "src": "technique", "batch": 8,
             "trials": 4, "pruned": 2, "filtered": 0, "dup": 2},
            {"ev": "step", "t": 2.0, "step": 2, "arm": "pso",
             "evaluated": 4, "withdrawn": False, "new_best": False,
             "best": 1.0, "evals": 8, "src": "technique", "batch": 8,
             "trials": 4, "pruned": 2, "filtered": 0, "dup": 2},
            {"ev": "store_hit", "t": 3.0, "gid": 9, "qor": 1.0,
             "dur": 2.0},
        ]
        mon = quality.replay(rows)
        g = mon.gauges
        assert g["search.pulls"] == 2
        assert g["search.dup_rate"] == 0.25
        assert g["search.prune_rate"] == 0.25
        assert g["search.novel_rate"] == 0.5
        assert g["search.arm_evals_share.de"] == 0.5
        assert g["search.arm_best_share.de"] == 1.0

    def test_replay_survives_json_round_trip(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        mon = obs.start_journal(p)
        best = 5.0
        for i in range(40):
            q = 5.0 - 0.04 * i * (i % 3)
            nb = q < best
            best = min(best, q)
            journal.emit("step", step=i, arm="de", evaluated=1,
                         withdrawn=False, new_best=nb,
                         best=round(best, 6), evals=i + 1,
                         gids=[i], ok=[True], qors=[round(q, 6)],
                         nb=[nb], durs=[0.0],
                         mus=[round(q + 0.3, 6)], sigmas=[0.7])
        obs.stop_journal(mon)
        _, rows = journal.read(p, strict=True)
        assert quality.replay(rows).gauges == mon.gauges


# ---------------------------------------------------- driver e2e (tier-1)
@pytest.fixture(scope="module")
def driver_journal(tmp_path_factory):
    """One tiny matched-seed journaled tune shared by the e2e asserts:
    rosenbrock-2d, sync GP surrogate (deterministic), obs + journal on
    — the fast sibling of the slow bench.py --report smoke."""
    from uptune_tpu.driver import Tuner
    from uptune_tpu.workloads import rosenbrock_objective, \
        rosenbrock_space
    p = str(tmp_path_factory.mktemp("journal") / "run.journal.jsonl")
    obs.enable()
    mon = obs.start_journal(p, meta={"test": "driver_journal"})
    t = Tuner(rosenbrock_space(2, -2.048, 2.048),
              rosenbrock_objective(2), seed=0, surrogate="gp",
              surrogate_opts=dict(min_points=8, refit_interval=16,
                                  max_points=64, async_refit=False))
    t.run(test_limit=60)
    t.close()
    journal.flush()
    obs.stop_journal(mon)   # detaches + finalizes the cadence gauges
    online = dict(mon.gauges)
    metrics_gauges = obs.metrics_snapshot()["gauges"]
    alerts = list(mon.alerts)
    obs.reset()
    yield {"path": p, "online": online, "alerts": alerts,
           "metrics_gauges": metrics_gauges}


class TestDriverJournal:
    def test_online_gauges_match_offline_replay(self, driver_journal):
        """ISSUE 12 acceptance: the online gauges equal an EXACT
        offline recomputation from the journal file."""
        _, rows = journal.read(driver_journal["path"], strict=True)
        replayed = quality.replay(rows)
        assert replayed.gauges == driver_journal["online"]
        # and the published copies in the metrics registry agree
        pub = {k: v for k, v in driver_journal["metrics_gauges"].items()
               if k.startswith("search.")}
        assert pub == {k: v for k, v in replayed.gauges.items()
                       if k in pub}
        assert pub      # non-empty: publication actually happened

    def test_row_schema_and_calibration_join(self, driver_journal):
        _, rows = journal.read(driver_journal["path"], strict=True)
        kinds = {r["ev"] for r in rows}
        assert {"step", "snapshot"} <= kinds
        steps = [r for r in rows if r["ev"] == "step"]
        assert all({"arm", "evaluated", "new_best", "best",
                    "evals"} <= set(r) for r in steps)
        evaluated = [r for r in steps if r.get("qors")]
        assert evaluated
        for r in evaluated:
            n = len(r["qors"])
            # compact encoding: exactly one gid form; optional arrays
            # (ok/nb/durs at their defaults are omitted) match length
            assert ("gid0" in r) != ("gids" in r)
            for k in ("gids", "ok", "nb", "durs"):
                if k in r:
                    assert len(r[k]) == n
        # the GP fits at 8 points -> later steps carry mus/sigmas
        joined = [r for r in evaluated if "mus" in r]
        assert joined and all(
            len(r["mus"]) == len(r["sigmas"]) == len(r["qors"])
            and "pred_v" in r for r in joined)
        # pull verdicts ride the step rows (captured at ticket open)
        pulls = [r for r in steps if "batch" in r]
        assert pulls and all(
            r["src"] in ("technique", "surrogate", "injected",
                         "random")
            and r["batch"] >= r["trials"] + r["dup"] + r["pruned"]
            + r["filtered"] - 1 for r in pulls)

    def test_healthy_run_is_alert_free(self, driver_journal):
        """Acceptance: detectors stay silent on a healthy rosenbrock
        run (while the synthetic stalled/miswired streams above
        fire)."""
        assert driver_journal["alerts"] == []

    def test_report_renders_from_live_journal(self, driver_journal,
                                              tmp_path):
        html = obs_report.render(driver_journal["path"])
        assert "<svg" in html and "Calibration reliability" in html
        md = obs_report.render(driver_journal["path"], fmt="md")
        assert "## Arm attribution" in md
        # CLI surface: ut report -> file
        out = str(tmp_path / "r.html")
        assert obs_report.main([driver_journal["path"],
                                "-o", out]) == 0
        assert os.path.getsize(out) > 1000


# -------------------------------------------------------- serve health
class TestServeHealth:
    def _server(self):
        from uptune_tpu.serve.server import SessionServer
        return SessionServer(port=0, slots=4, store_dir="off")

    def _open(self, srv, seed=0):
        from uptune_tpu.exec.space_io import records_from_space
        from uptune_tpu.workloads import rosenbrock_space
        recs = records_from_space(rosenbrock_space(2, -3.0, 3.0))
        resp = srv.handle({"op": "open", "space": recs, "seed": seed})
        assert resp["ok"], resp
        return resp["session"]

    def test_health_op_per_session_and_rollup(self):
        srv = self._server()
        try:
            sid = self._open(srv)
            resp = srv.handle({"op": "health", "session": sid})
            assert resp["ok"] and resp["health"]["status"] == "cold"
            # drive tells: first improves, the rest stall
            qor = 1.0
            for _ in range(12):
                trials = srv.handle({"op": "ask", "session": sid,
                                     "n": 2})["trials"]
                for t in trials:
                    srv.handle({"op": "tell", "session": sid,
                                "ticket": t["ticket"], "qor": qor})
                    qor += 1.0          # strictly worse: no new best
            one = srv.handle({"op": "health", "session": sid,
                              "stall_tells": 8})["health"]
            assert one["status"] == "stalled"
            assert one["tells_since_best"] >= 8
            assert one["best_qor"] == 1.0
            ok = srv.handle({"op": "health", "session": sid})["health"]
            assert ok["status"] == "ok"     # default threshold: quiet
            roll = srv.handle({"op": "health", "stall_tells": 8})
            assert roll["ok"] and roll["sessions"] == 1
            assert roll["by_status"] == {"stalled": 1}
            assert roll["health"][0]["session"] == sid
        finally:
            srv.stop()
            obs.reset()

    def test_failing_session_and_bad_threshold(self):
        srv = self._server()
        try:
            sid = self._open(srv)
            told = 0
            while told < SessionQuality.FAIL_WINDOW:
                trials = srv.handle({"op": "ask", "session": sid,
                                     "n": 4})["trials"]
                for t in trials:
                    srv.handle({"op": "tell", "session": sid,
                                "ticket": t["ticket"], "qor": None})
                    told += 1
            h = srv.handle({"op": "health", "session": sid})["health"]
            assert h["status"] == "failing" and h["fail_rate"] == 1.0
            bad = srv.handle({"op": "health", "stall_tells": "x"})
            assert not bad["ok"]
            unknown = srv.handle({"op": "health", "session": "nope"})
            assert not unknown["ok"]
        finally:
            srv.stop()
            obs.reset()

    def test_local_session_health_and_journal_rows(self, tmp_path):
        from uptune_tpu.serve.session import LocalSession
        from uptune_tpu.workloads import rosenbrock_space
        p = str(tmp_path / "serve.journal.jsonl")
        mon = obs.start_journal(p)
        with LocalSession(rosenbrock_space(2, -3.0, 3.0), seed=1) as s:
            for _ in range(3):
                for t in s.ask(2):
                    s.tell(t.ticket, 1.25)
            h = s.health()
            assert h["status"] == "ok" and h["tells"] == 6
        obs.stop_journal(mon)
        _, rows = journal.read(p, strict=True)
        st = [r for r in rows if r["ev"] == "serve_tell"]
        assert len(st) == 6
        assert all(r["ok"] and r["qor"] == 1.25 for r in st)
        assert sum(r["new_best"] for r in st) == 1


# ----------------------------------------------------------- ut top
class TestTopJson:
    def _row(self):
        return {"t": 100.0, "dt": 1.0, "pid": 1,
                "counters": {"driver.asks": 10, "search.alerts": 1},
                "deltas": {"driver.asks": 5},
                "gauges": {"search.best_qor": 1.5,
                           "search.cal_cover95": 0.9},
                "hists": {}}

    def test_json_once_frame(self, tmp_path, capsys):
        from uptune_tpu.obs import top
        p = str(tmp_path / "m.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps(self._row()) + "\n")
        assert top.main(["--metrics", p, "--once", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["gauges"]["search.best_qor"] == 1.5
        assert doc["rates"]["driver.asks"] == 5.0
        assert doc["source"] == p

    def test_json_requires_once(self, tmp_path):
        from uptune_tpu.obs import top
        with pytest.raises(SystemExit):
            top.main(["--metrics", "x", "--json"])

    def test_search_panel_renders(self):
        from uptune_tpu.obs import top
        cur = top.sample_from_row(self._row())
        frame = top.render(None, cur, "test")
        assert "search" in frame and "best 1.5" in frame
        assert "cover95 0.90" in frame


# ------------------------------------------------- committed artifacts
class TestCommittedExamples:
    JOURNAL = os.path.join(REPO, "exp_archives",
                           "obs_journal_example.jsonl")
    REPORT = os.path.join(REPO, "exp_archives",
                          "obs_report_example.html")

    def test_journal_example_schema_valid(self):
        header, rows = journal.read(self.JOURNAL, strict=True)
        assert header["journal"] == journal.SCHEMA_VERSION
        steps = [r for r in rows if r["ev"] == "step"]
        assert sum(len(r.get("qors") or ()) for r in steps) >= 100
        assert any("mus" in r for r in steps)
        mon = quality.replay(rows)
        assert mon.alerts == []             # the example is healthy
        assert mon.gauges["search.cal_rows"] > 0

    def test_report_renders_from_committed_journal(self):
        """Acceptance: the committed report is exactly what rendering
        the committed journal produces (the renderer is deterministic
        given the journal)."""
        html = obs_report.render(self.JOURNAL)
        with open(self.REPORT) as f:
            committed = f.read()
        assert html == committed
        assert "<svg" in html and "No alerts fired." in html


# ----------------------------------------------- pool reap journal rows
class TestFeatureInterm:
    def test_reap_reads_covars_and_interm(self, tmp_path):
        from uptune_tpu.api.report import COVARS_FILE, FEATURES_FILE
        from uptune_tpu.exec.pool import WorkerPool

        class _FakeSlot:
            sandbox = str(tmp_path)

        class _FakeTrial:
            gid = 42

        with open(tmp_path / COVARS_FILE, "w") as f:
            json.dump({"cores": 8}, f)
        with open(tmp_path / FEATURES_FILE, "w") as f:
            json.dump([[0, [1.0, 2.0]]], f)
        p = str(tmp_path / "j.jsonl")
        journal.start(p)
        WorkerPool._journal_child_rows(_FakeSlot(), _FakeTrial())
        journal.stop()
        _, rows = journal.read(p, strict=True)
        by = {r["ev"]: r for r in rows}
        assert by["feature"]["covars"] == {"cores": 8}
        assert by["feature"]["gid"] == 42
        assert by["interm"]["feats"] == [1.0, 2.0]


# --------------------------------------------------- slow e2e sibling
@pytest.mark.slow
def test_bench_report_smoke_subprocess():
    """The heavy sibling: `python bench.py --report --quick` end to
    end in a fresh process (its fast tier-1 siblings are the driver
    e2e + render tests above)."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--report",
         "--quick"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO}, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    doc = json.loads(r.stdout.strip().splitlines()[-1])
    assert doc["value"] == 1.0 and doc["alerts"] == []
