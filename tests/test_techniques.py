"""Technique-layer tests: every registered technique must run jitted
propose/observe cycles with valid outputs, and the core optimizers must
actually optimize (the reference has no such tests — SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uptune_tpu.space import params as P
from uptune_tpu.space.spec import Space
from uptune_tpu.techniques import base as tb
from uptune_tpu.techniques.bandit import AUCBanditQueue, MetaTechnique


def mixed_space():
    return Space([
        P.FloatParam("x", -5, 5), P.FloatParam("y", -5, 5),
        P.IntParam("n", 0, 10), P.EnumParam("e", options=("a", "b", "c")),
        P.PermParam("p", items=tuple(range(8))),
    ])


def sphere_space(d=4):
    return Space([P.FloatParam(f"x{i}", -3, 3) for i in range(d)])


def sphere_qor(space, cands):
    v = space.decode_scalars(cands.u)
    return jnp.sum(v * v, axis=-1)


def run_technique(t, space, qor_fn, steps, seed=0):
    """Drive one technique with jitted step functions; returns best qor."""
    key = jax.random.PRNGKey(seed)
    k_init, k_run = jax.random.split(key)
    state = t.init_state(space, k_init)
    best = tb.Best.empty(space)
    propose = jax.jit(lambda st, k, b: t.propose(space, st, k, b))
    observe = jax.jit(lambda st, c, q, b: t.observe(space, st, c, q, b))
    for i in range(steps):
        kk = jax.random.fold_in(k_run, i)
        state, cands = propose(state, kk, best)
        qor = qor_fn(space, cands)
        best = best.update(cands, qor)
        state = observe(state, cands, qor, best)
    return best


@pytest.fixture(scope="module")
def space():
    return mixed_space()


def base_techniques():
    return [tb.get_technique(n) for n in tb.all_technique_names()
            if not isinstance(tb.get_technique(n), MetaTechnique)]


@pytest.mark.parametrize("t", base_techniques(), ids=lambda t: t.name)
def test_technique_valid_outputs(t, space):
    """Every technique emits batches of the declared size with in-range
    unit lanes and valid permutations, under jit."""
    if not t.supports(space):
        pytest.skip("unsupported space")
    key = jax.random.PRNGKey(1)
    state = t.init_state(space, key)
    best = tb.Best.empty(space)
    propose = jax.jit(lambda st, k, b: t.propose(space, st, k, b))
    observe = jax.jit(lambda st, c, q, b: t.observe(space, st, c, q, b))
    for i in range(2):
        state, cands = propose(state, jax.random.fold_in(key, i), best)
        n = t.natural_batch(space)
        assert cands.u.shape == (n, space.n_scalar)
        u = np.asarray(cands.u)
        assert np.all(u >= 0.0) and np.all(u <= 1.0)
        for pm, size in zip(cands.perms, space.perm_sizes):
            pm = np.asarray(pm)
            assert pm.shape == (n, size)
            assert np.all(np.sort(pm, axis=1) == np.arange(size)), t.name
        qor = sphere_qor(space, cands) + 0.1 * jnp.arange(n)
        best = best.update(cands, qor)
        state = observe(state, cands, qor, best)
    assert np.isfinite(float(best.qor))


@pytest.mark.parametrize("name,steps,target", [
    ("CMAES", 30, 1e-3),
    ("DifferentialEvolution", 40, 0.05),
    ("NormalGreedyMutation10", 60, 0.05),
    ("PatternSearch", 60, 0.05),
    ("RandomNelderMead", 60, 0.1),
    ("RandomTorczon", 60, 0.1),
    ("pso-OX1", 40, 0.1),
    ("PseudoAnnealingSearch", 80, 0.5),
    ("UniformGreedyMutation10", 80, 0.5),
])
def test_optimizes_sphere(name, steps, target):
    """Core techniques descend on a 4-d sphere well below random-search
    level (random best after comparable budget is ~0.1-0.5)."""
    space = sphere_space(4)
    t = tb.get_technique(name)
    best = run_technique(t, space, sphere_qor, steps)
    assert float(best.qor) < target, (name, float(best.qor))


def test_de_population_replacement():
    """DE replaces members only when the candidate improves them."""
    from uptune_tpu.techniques.de import DifferentialEvolution
    space = sphere_space(3)
    t = DifferentialEvolution(population_size=8, name="de-test")
    key = jax.random.PRNGKey(0)
    state = t.init_state(space, key)
    best = tb.Best.empty(space)
    state, cands = t.propose(space, state, key, best)
    qor = sphere_qor(space, cands)
    best = best.update(cands, qor)
    state = t.observe(space, state, cands, qor, best)
    assert np.all(np.isfinite(np.asarray(state.qor)))
    # worse candidates never replace
    state2, cands2 = t.propose(space, state, jax.random.fold_in(key, 1), best)
    bad = jnp.full((8,), 1e9)
    state3 = t.observe(space, state2, cands2, bad, best)
    np.testing.assert_array_equal(np.asarray(state3.pop.u),
                                  np.asarray(state2.pop.u))


def test_auc_bandit_queue_matches_slow_formula():
    """Fast incremental AUC credit == the reference's O(n) formula
    (bandittechniques.py:96-131)."""
    rng = np.random.RandomState(0)
    q = AUCBanditQueue(["a", "b", "c"], window=50)
    hist = []
    for i in range(300):
        k = ["a", "b", "c"][rng.randint(3)]
        v = bool(rng.rand() < 0.3)
        q.on_result(k, v)
        hist.append((k, v))
        hist = hist[-50:]
        for key in ("a", "b", "c"):
            score, pos = 0.0, 0
            for kk, vv in hist:
                if kk == key:
                    pos += 1
                    if vv:
                        score += pos
            slow = score * 2.0 / (pos * (pos + 1.0)) if pos else 0.0
            assert abs(q.exploitation_term(key) - slow) < 1e-9


def test_bandit_prefers_productive_arm():
    q = AUCBanditQueue(["good", "bad"], seed=3)
    for i in range(60):
        q.on_result("good", i % 2 == 0)
        q.on_result("bad", False)
    assert q.ordered_keys()[0] == "good"


def test_portfolios_resolve():
    root = tb.get_root(None)
    assert isinstance(root, MetaTechnique)
    assert root.name == "AUCBanditMetaTechniqueA"
    assert [t.name for t in root.techniques] == [
        "DifferentialEvolutionAlt", "UniformGreedyMutation",
        "NormalGreedyMutation", "RandomNelderMead"]
    multi = tb.get_root(["PureRandom", "PatternSearch"])
    assert isinstance(multi, MetaTechnique)
    order1 = [t.name for t in multi.select_order()]
    order2 = [t.name for t in multi.select_order()]
    assert order1 != order2  # round robin rotates


def test_registry_has_recycling_and_roundrobin():
    names = tb.all_technique_names()
    assert "RecyclingMetaTechnique" in names
    assert "RoundRobinMetaSearchTechnique" in names
    assert len(names) >= 45, len(names)


def test_cmaes_in_driver_and_space_support():
    """CMA-ES (beyond-reference arm) integrates with the batched driver
    and declines permutation spaces."""
    from uptune_tpu.driver.driver import Tuner
    from uptune_tpu.workloads import rosenbrock_objective, rosenbrock_space

    t = tb.get_technique("CMAES")
    assert not t.supports(mixed_space())     # has a perm block
    space = rosenbrock_space(2, -3.0, 3.0)
    tuner = Tuner(space, rosenbrock_objective(2), seed=3,
                  technique="CMAES")
    res = tuner.run(test_limit=600)
    tuner.close()
    assert res.best_qor < 0.05, res.best_qor


def test_recycling_meta_restarts_fire_and_converge():
    """The restart-meta recycles members whose window-best lags the global
    best, and still descends on rosenbrock (metatechniques.py:89-180)."""
    from uptune_tpu.driver.driver import Tuner
    from uptune_tpu.workloads import rosenbrock_objective, rosenbrock_space

    space = rosenbrock_space(2, -3.0, 3.0)
    t = Tuner(space, rosenbrock_objective(2), seed=7,
              technique="RecyclingMetaTechnique")
    # shrink the window so recycling happens well within the budget
    t.root.window = 4
    res = t.run(test_limit=500)
    assert t.root.restart_count > 0, "no member was ever recycled"
    assert res.best_qor < 5.0, res.best_qor
    # restarted members keep proposing (their state re-initialized, not
    # removed): every member still has a live device state
    assert set(t._tstates) >= {m.name for m in t.members}
    t.close()


def test_recycling_meta_spares_fresh_members():
    """A member is only judged after completing a full window (the
    reference's old_best_results guard)."""
    from uptune_tpu.techniques.bandit import RecyclingMeta
    from uptune_tpu.techniques.purerandom import PureRandom
    m = RecyclingMeta([PureRandom(name="a"), PureRandom(name="b")],
                      name="rm", window=2)
    # first window: b is clearly worst, but has no previous window yet
    m.credit("a", True, step_best=1.0, global_best=1.0)
    m.credit("b", False, step_best=50.0, global_best=1.0)
    assert m.poll_restart() == []
    # second window: b lags the global best again -> restart queued
    m.credit("a", False, step_best=2.0, global_best=1.0)
    m.credit("b", False, step_best=60.0, global_best=1.0)
    assert m.poll_restart() == ["b"]
    assert m.restart_count == 1


def test_restart_not_undone_by_stale_inflight_ticket():
    """A ticket opened before a member restart must not write its
    pre-restart state snapshot back when it finalizes later (async
    ask/tell can hold several tickets for the same member in flight)."""
    from uptune_tpu.driver.driver import Tuner
    from uptune_tpu.techniques.bandit import RecyclingMeta
    from uptune_tpu.techniques.purerandom import PureRandom
    from uptune_tpu.workloads import rosenbrock_space

    space = rosenbrock_space(2, -3.0, 3.0)
    meta = RecyclingMeta([PureRandom(name="pr")], name="rm", window=1)
    t = Tuner(space, technique=meta)
    name = t.members[0].name

    # round 1: establish a strong global best (prev window for 'pr')
    for tr in t.ask(min_trials=1):
        t.tell(tr, 0.0)
    # two tickets in flight for the same member
    batch_a = t.ask(min_trials=1)
    batch_b = t.ask(min_trials=1)
    # resolving A (worse than global best) triggers the recycle
    for tr in batch_a:
        t.tell(tr, 10.0)
    assert t.root.restart_count >= 1
    assert t._tgen[name] == t.root.restart_count
    fresh = t._tstates[name]
    # resolving stale B must NOT overwrite the re-initialized state
    for tr in batch_b:
        t.tell(tr, 20.0)
    restarts_after_b = t.root.restart_count
    if t._tgen[name] == restarts_after_b:
        # B itself may trigger another recycle (window=1); only when no
        # newer restart superseded it can we check the guard directly
        assert t._tstates[name] is fresh, \
            "stale ticket reverted the restart"
    t.close()


def test_permutation_space_only():
    """Techniques that support pure-permutation spaces handle them; tsp-like
    objective improves under GA/PSO."""
    space = Space([P.PermParam("tour", items=tuple(range(10)))])
    coords = np.random.RandomState(0).rand(10, 2)

    def tour_len(space_, cands):
        pts = jnp.asarray(coords)[cands.perms[0]]
        d = jnp.linalg.norm(pts - jnp.roll(pts, 1, axis=1), axis=-1)
        return jnp.sum(d, axis=-1)

    t = tb.get_technique("ga-PMX")
    best_ga = run_technique(t, space, tour_len, 40)
    rnd = run_technique(tb.get_technique("PureRandom"), space, tour_len, 5)
    assert float(best_ga.qor) <= float(rnd.qor) * 1.05
    assert not tb.get_technique("RandomNelderMead").supports(space)


def test_legacy_two_arg_credit_meta_still_works():
    """A user MetaTechnique subclass written against the pre-r3 2-arg
    credit() signature must not crash the driver: the signature is
    inspected ONCE at construction (a FutureWarning — visible under
    default filters, unlike DeprecationWarning) and the driver falls
    back to the legacy call (ADVICE r3).  A TypeError raised INSIDE a
    modern credit() must still propagate."""
    import warnings

    from uptune_tpu.driver.driver import Tuner
    from uptune_tpu.techniques.bandit import MetaTechnique
    from uptune_tpu.techniques.purerandom import PureRandom
    from uptune_tpu.workloads import rosenbrock_objective, rosenbrock_space

    class LegacyMeta(MetaTechnique):
        def __init__(self):
            super().__init__([PureRandom(name="a"), PureRandom(name="b")],
                             name="legacy")
            self.calls = 0

        def select_order(self):
            return list(self.techniques)

        def credit(self, name, was_new_best):  # old signature, no kwargs
            self.calls += 1

    space = rosenbrock_space(2, -3.0, 3.0)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        t = Tuner(space, rosenbrock_objective(2), seed=11,
                  technique=LegacyMeta())
    assert any(issubclass(x.category, FutureWarning) for x in w)
    res = t.run(test_limit=60)
    t.close()
    assert t.root.calls > 0
    assert res.best_qor < float("inf")

    class BuggyModernMeta(MetaTechnique):
        def __init__(self):
            super().__init__([PureRandom(name="a")], name="buggy")

        def select_order(self):
            return list(self.techniques)

        def credit(self, name, was_new_best, step_best=None,
                   global_best=None):
            raise TypeError("bug inside a modern credit()")

    t2 = Tuner(space, rosenbrock_objective(2), seed=12,
               technique=BuggyModernMeta())
    with pytest.raises(TypeError, match="bug inside"):
        t2.run(test_limit=60)
    t2.close()


def test_experimental_label():
    """AUCBanditMetaTechniqueTPU measured 1.62x behind portfolio A at 30
    matched seeds (AB_PORTFOLIO.md); it stays registered but must carry
    the [experimental] tag the CLI listing surfaces (r4 verdict #6)."""
    from uptune_tpu.techniques.base import is_experimental
    assert is_experimental("AUCBanditMetaTechniqueTPU")
    assert not is_experimental("AUCBanditMetaTechniqueA")
