"""Distributed observability (ISSUE 10, docs/OBSERVABILITY.md):
subprocess trace sidecars merged at reap, the `ut-trace merge` shard
joiner with clock-offset alignment, the metrics flight recorder's
timeline (writer thread vs scrape losing nothing), Prometheus text
exposition, `ut top` rendering, and graceful SIGINT/atexit telemetry
flushing.  The serve-plane halves (wire ctx propagation, Prometheus
scrape op) live in tests/test_serve.py beside the shared server
fixture.

Budget note: everything here is in-process and sub-second except the
@slow real-subprocess e2e at the bottom — each slow test keeps a cheap
tier-1 sibling (the simulated-sidecar merge, the committed merged
artifact)."""
import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import pytest

import uptune_tpu
from uptune_tpu import obs
from uptune_tpu.obs import flight, merge, sidecar, top

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    uptune_tpu.__file__)))
ENV = {"PYTHONPATH": REPO}


@pytest.fixture(autouse=True)
def obs_clean():
    obs.reset()
    yield
    obs.reset()


# ---------------------------------------------------------------- core
class TestCoreAdditions:
    def test_span_ids_unique_and_pid_tagged(self):
        ids = {obs.new_span_id() for _ in range(64)}
        assert len(ids) == 64
        assert all(i.startswith(f"{os.getpid():x}-") for i in ids)

    def test_emit_at_places_events_on_explicit_ts(self):
        obs.enable()
        obs.emit_at("a", 1.5, 0.25, "lane-x", {"k": 1})
        obs.emit_at("b", 2.0)                       # instant, own lane
        evs = obs.snapshot()["events"]
        a = next(e for e in evs if e["name"] == "a")
        assert (a["ts"], a["dur"], a["track"]) == (1.5, 0.25, "lane-x")
        b = next(e for e in evs if e["name"] == "b")
        assert b["dur"] is None and b["track"] == "MainThread"

    def test_emit_at_disabled_is_inert(self):
        obs.emit_at("a", 1.0, 1.0, "lane")
        obs.enable()
        assert obs.snapshot()["events"] == []


# ------------------------------------------------------------- sidecar
class TestSidecar:
    def test_dump_read_roundtrip_and_merge_alignment(self, tmp_path):
        """The tier-1 sibling of the @slow subprocess e2e: a simulated
        child dumps its rings to a sidecar; a 'driver' (same process,
        fresh enable cycle) merges them onto a worker lane with the
        clock offset applied, and the file is consumed."""
        path = str(tmp_path / sidecar.SIDECAR_FILE)
        obs.enable()
        with obs.span("child.load_proposal"):
            pass
        obs.event("child.target", qor=1.25)
        child_origin = obs.trace_origin_unix()
        sidecar.dump(path)
        header, events = sidecar.read(path)
        assert header["sidecar"] == 1
        assert header["origin_unix"] == child_origin
        names = {e["name"] for e in events}
        assert {"child.load_proposal", "child.target",
                "child.run"} <= names

        obs.enable()                    # the "driver" side: new origin
        n = sidecar.merge_into(path, "worker-3")
        assert n == len(events)
        assert not os.path.exists(path), "consumed sidecar must go"
        evs = obs.snapshot()["events"]
        merged = [e for e in evs if e["name"].startswith("child.")]
        assert {e["track"] for e in merged} == {"worker-3"}
        # clock alignment: child events recorded BEFORE the driver's
        # enable() land at negative trace time, never at raw child time
        offset = child_origin - obs.trace_origin_unix()
        tgt = next(e for e in merged if e["name"] == "child.target")
        src = next(e for e in events if e["name"] == "child.target")
        assert abs(tgt["ts"] - (src["ts"] + offset)) < 1e-9
        assert tgt["attrs"]["qor"] == 1.25

    def test_read_tolerates_garbage_and_torn_tails(self, tmp_path):
        p = tmp_path / "x.jsonl"
        assert sidecar.read(str(p)) is None             # missing
        p.write_text("")
        assert sidecar.read(str(p)) is None             # empty
        p.write_text('{"not": "a sidecar"}\n')
        assert sidecar.read(str(p)) is None             # wrong header
        p.write_text('{"sidecar": 1, "origin_unix": 5.0}\n'
                     '{"name": "a", "ts": 0.1, "dur": null}\n'
                     '{"name": "b", "ts"')               # torn tail
        header, events = sidecar.read(str(p))
        assert [e["name"] for e in events] == ["a"]

    def test_merge_into_disabled_or_missing_is_zero(self, tmp_path):
        assert sidecar.merge_into(str(tmp_path / "nope"), "w") == 0
        obs.enable()
        assert sidecar.merge_into(str(tmp_path / "nope"), "w") == 0

    def test_maybe_init_child_env_gate(self, tmp_path, monkeypatch):
        monkeypatch.delenv(sidecar.SIDECAR_ENV, raising=False)
        assert sidecar.maybe_init_child() is None
        assert not obs.enabled()
        path = str(tmp_path / "sc.jsonl")
        monkeypatch.setenv(sidecar.SIDECAR_ENV, path)
        assert sidecar.maybe_init_child() == path
        assert obs.enabled()
        # idempotent: re-init (protocol state reset) doesn't stack
        assert sidecar.maybe_init_child() == path


# ----------------------------------------------------- flight recorder
class TestFlightRecorder:
    def test_timeline_rows_lose_nothing_under_concurrency(self,
                                                          tmp_path):
        """ISSUE 10 satellite: the writer thread snapshots windows
        while worker threads hammer the registry — the sum of per-row
        deltas equals the final counters exactly (the lock makes every
        row a consistent cut), and histogram window counts add up."""
        obs.enable()
        path = str(tmp_path / "m.metrics.jsonl")
        rec = flight.start(path, interval=0.02)
        n_threads, per = 4, 300
        start = threading.Barrier(n_threads)

        def writer(k):
            start.wait()
            for i in range(per):
                obs.count("t.counter")
                obs.observe("t.hist", float(i))

        ts = [threading.Thread(target=writer, args=(k,))
              for k in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        rec.stop()
        rec.stop()                          # idempotent
        rows = [json.loads(l) for l in open(path)]
        assert len(rows) >= 1
        assert rows[-1]["final"] is True
        total = n_threads * per
        assert sum(r["deltas"].get("t.counter", 0) for r in rows) \
            == total
        assert rows[-1]["counters"]["t.counter"] == total
        assert sum(r["hists"].get("t.hist", {}).get("window_count", 0)
                   for r in rows) == total
        # rows carry their window length for rate computation
        assert all(r["dt"] >= 0 for r in rows)

    def test_rotation_caps_the_file(self, tmp_path):
        obs.enable()
        path = str(tmp_path / "r.metrics.jsonl")
        rec = flight.FlightRecorder(path, interval=60, max_rows=5)
        rec.start()
        for _ in range(12):
            obs.count("c")
            rec._write_row()
        rec.stop()
        assert rec.rotations == 2
        assert os.path.exists(path + ".1")
        kept = sum(1 for _ in open(path)) + sum(
            1 for _ in open(path + ".1"))
        assert kept <= 11                   # bounded, not unbounded

    def test_finish_settles_recorder_not_legacy_row(self, tmp_path):
        """obs.finish on a traced run with a recorder stops it (final
        row) instead of appending the legacy one-shot snapshot — and a
        second finish (clean exit after a signal flush) appends
        nothing more."""
        obs.enable()
        trace = str(tmp_path / "t.json")
        obs.start_flight_recorder(trace, interval=60)
        obs.count("x")
        obs.finish(trace)
        rows = [json.loads(l)
                for l in open(trace + ".metrics.jsonl")]
        assert rows[-1]["final"] is True
        n = len(rows)
        obs.finish(trace)
        rows2 = [json.loads(l)
                 for l in open(trace + ".metrics.jsonl")]
        assert len(rows2) == n
        obs.validate_trace(json.load(open(trace)))

    def test_window_snapshot_cursor_math(self):
        obs.enable()
        obs.count("a", 3)
        obs.observe("h", 1.0)
        row, cur = obs.window_snapshot(None)
        assert row["deltas"]["a"] == 3
        assert row["hists"]["h"]["window_count"] == 1
        obs.count("a", 2)
        row2, _ = obs.window_snapshot(cur)
        assert row2["deltas"]["a"] == 2
        assert row2["counters"]["a"] == 5
        assert row2["hists"]["h"]["window_count"] == 0
        assert "p50" not in row2["hists"]["h"]


# ---------------------------------------------------------- prometheus
class TestPrometheus:
    def test_exposition_families(self):
        obs.enable()
        obs.count("serve.asks", 7)
        obs.gauge("pool.utilization", 0.5)
        for v in (1.0, 2.0, 3.0):
            obs.observe("serve.ask_ms", v)
        text = obs.prometheus_text()
        assert "# TYPE ut_serve_asks counter\nut_serve_asks 7" in text
        assert "# TYPE ut_pool_utilization gauge" in text
        assert 'ut_serve_ask_ms{quantile="0.5"} 2' in text
        assert "ut_serve_ask_ms_count 3" in text
        assert "ut_serve_ask_ms_sum 6" in text

    def test_name_sanitization(self):
        obs.enable()
        obs.count("weird.name-with:chars/2", 1)
        text = obs.prometheus_text()
        assert "ut_weird_name_with_chars_2 1" in text


# --------------------------------------------------------------- merge
def _make_shard(tmp_path, name, process, origin, events):
    """A normalized chrome shard written through the real exporter
    pipeline would share this process's clock; build documents by hand
    instead so distinct origins (distinct fake hosts) are testable."""
    doc = {"traceEvents": [
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "MainThread"}}] + events,
        "otherData": {"process": process, "origin_unix": origin}}
    path = str(tmp_path / name)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


class TestMerge:
    def test_merge_aligns_clocks_and_namespaces_pids(self, tmp_path):
        a = _make_shard(tmp_path, "a.json", "proc-a", 1000.0, [
            {"ph": "X", "pid": 1, "tid": 1, "name": "s", "ts": 0.0,
             "dur": 1e6}])
        b = _make_shard(tmp_path, "b.json", "proc-b", 1002.5, [
            {"ph": "X", "pid": 1, "tid": 1, "name": "s", "ts": 0.0,
             "dur": 1e6}])
        out = str(tmp_path / "merged.json")
        doc = merge.merge_files([a, b], out=out)
        obs.validate_trace(doc)
        obs.validate_trace(json.load(open(out)))
        procs = {e["args"]["name"]: e["pid"]
                 for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert set(procs) == {"proc-a", "proc-b"}
        assert len(set(procs.values())) == 2
        xs = {e["pid"]: e["ts"] for e in doc["traceEvents"]
              if e["ph"] == "X"}
        # shard b's span is shifted by its 2.5 s clock offset
        assert xs[procs["proc-b"]] - xs[procs["proc-a"]] == \
            pytest.approx(2.5e6)
        man = doc["otherData"]["merged"]
        assert [s["offset_s"] for s in man] == [0.0, 2.5]

    def test_merge_accepts_sidecar_shards(self, tmp_path):
        obs.enable()
        obs.event("child.target", qor=2.0)
        sc = str(tmp_path / "sc.jsonl")
        sidecar.dump(sc)
        a = _make_shard(tmp_path, "a.json", "driver",
                        obs.trace_origin_unix(), [
                            {"ph": "i", "pid": 1, "tid": 1, "name": "e",
                             "ts": 0.0, "s": "t"}])
        doc = merge.merge_shards([merge.load_shard(a),
                                  merge.load_shard(sc)])
        obs.validate_trace(doc)
        names = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert any(n.startswith("worker-child") for n in names)

    def test_client_server_joins_annotated(self, tmp_path):
        cli = _make_shard(tmp_path, "c.json", "client", 1000.0, [
            {"ph": "X", "pid": 1, "tid": 1, "name": "client.request",
             "ts": 0.0, "dur": 5000.0, "args": {"ctx": "abc-1",
                                                "op": "ask"}}])
        srv = _make_shard(tmp_path, "s.json", "server", 1000.0, [
            {"ph": "X", "pid": 1, "tid": 1, "name": "serve.handle",
             "ts": 1000.0, "dur": 2000.0, "args": {"parent": "abc-1",
                                                   "op": "ask"}}])
        doc = merge.merge_shards([merge.load_shard(cli),
                                  merge.load_shard(srv)])
        assert doc["otherData"]["joins"] == 1
        req = next(e for e in doc["traceEvents"]
                   if e.get("name") == "client.request")
        assert req["args"]["server_ms"] == 2.0
        assert req["args"]["wire_ms"] == 3.0

    def test_cli_merge_and_validate(self, tmp_path, capsys):
        a = _make_shard(tmp_path, "a.json", "p1", 1.0, [])
        out = str(tmp_path / "m.json")
        assert merge.main(["merge", "-o", out, a]) == 0
        assert merge.main(["validate", out]) == 0
        bad = tmp_path / "bad.json"
        bad.write_text('{"traceEvents": [{"ph": "X"}]}')
        assert merge.main(["validate", str(bad)]) == 1
        assert merge.main(["merge", "-o", out,
                           str(tmp_path / "missing.json")]) == 1

    def test_committed_merged_artifact_is_valid(self):
        """ISSUE 10 acceptance: the checked-in merged example (bench.py
        --obs phase 4) spans >= 3 distinct processes — driver, worker
        child, serve server/client — passes validate_trace, and has at
        least one annotated client/server join."""
        path = os.path.join(REPO, "exp_archives",
                            "obs_trace_merged_example.json")
        with open(path) as f:
            doc = json.load(f)
        obs.validate_trace(doc)
        procs = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert len(procs) >= 3
        roles = {p.split()[0] for p in procs}
        assert {"ut-driver", "ut-serve", "ut-client"} <= roles
        assert any(r.startswith("worker-child") for r in roles)
        assert doc["otherData"]["joins"] >= 1
        joined = [e for e in doc["traceEvents"]
                  if e.get("name") == "client.request"
                  and "wire_ms" in e.get("args", {})]
        assert joined


# ----------------------------------------------------------------- top
class TestTop:
    def _rows(self):
        return [
            {"t": 100.0, "dt": 1.0, "counters": {"serve.asks": 50},
             "deltas": {"serve.asks": 50}, "gauges": {}, "hists": {}},
            {"t": 101.0, "dt": 1.0,
             "counters": {"serve.asks": 175, "store.hits": 30,
                          "store.misses": 10},
             "deltas": {"serve.asks": 125, "store.hits": 30,
                        "store.misses": 10},
             "gauges": {"serve.sessions.active": 12,
                        "serve.batch_fill": 0.875,
                        "pool.utilization": 0.66},
             "hists": {"serve.ask_ms": {"count": 175, "p50": 0.4,
                                        "p95": 1.2}}},
        ]

    def test_render_shows_vitals_and_rates(self):
        r1, r2 = (top.sample_from_row(r) for r in self._rows())
        frame = top.render(r1, r2, "test-source")
        assert "test-source" in frame
        assert "sessions 12" in frame
        assert "batch fill 0.88" in frame
        assert "asks/s 125.0" in frame          # deltas/dt, exact
        assert "ask p50/p95 0.40/1.20 ms" in frame
        assert "hit-rate 75.0%" in frame

    def test_render_missing_families_degrade_to_dash(self):
        cur = top.Sample(100.0, {}, {}, {})
        frame = top.render(None, cur, "empty")
        assert "—" in frame                     # never a KeyError

    def test_rates_fall_back_to_poll_diffs(self):
        p = top.Sample(100.0, {"serve.asks": 10}, {}, {})
        c = top.Sample(102.0, {"serve.asks": 30}, {}, {})
        assert top.rates(p, c)["serve.asks"] == pytest.approx(10.0)
        assert top.rates(None, c) == {}

    def test_once_over_metrics_file(self, tmp_path, capsys):
        path = tmp_path / "m.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in self._rows())
                        + "\n{\"torn")
        assert top.main(["--metrics", str(path), "--once"]) == 0
        out = capsys.readouterr().out
        assert "asks/s 125.0" in out
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert top.main(["--metrics", str(empty), "--once"]) == 1

    def test_ut_cli_dispatches_top(self):
        from uptune_tpu import cli
        with pytest.raises(SystemExit) as e:
            cli.main(["top", "--help"])
        assert e.value.code == 0


# ------------------------------------------------------------ flushing
class TestExitFlush:
    def test_flush_all_writes_trace_and_final_row(self, tmp_path):
        """Tier-1 sibling of the @slow SIGINT e2e: the registered
        flush writes a valid trace + stops the recorder, tagging the
        reason; re-entry is guarded."""
        obs.enable()
        trace = str(tmp_path / "t.json")
        obs.install_exit_flush(trace, extra={"process": "test"})
        obs.start_flight_recorder(trace, interval=60)
        obs.count("x")
        obs._flush_all("signal:2")
        doc = json.load(open(trace))
        obs.validate_trace(doc)
        assert doc["otherData"]["flushed_on"] == "signal:2"
        assert doc["otherData"]["process"] == "test"
        rows = [json.loads(l) for l in open(trace + ".metrics.jsonl")]
        assert rows[-1]["final"] is True


# ------------------------------------------------------- slow e2e pair
@pytest.mark.slow
class TestSubprocessE2E:
    PROG = textwrap.dedent("""
        import uptune_tpu as ut
        x = ut.tune(50, (0, 100), name="x")
        y = ut.tune(50, (0, 100), name="y")
        ut.target(float((x - 37) ** 2 + (y - 11) ** 2), "min")
    """)

    def test_child_sidecar_spans_merge_onto_worker_lane(self, tmp_path):
        """Real subprocess trials: the traced driver's worker lanes
        carry the children's own child.* spans, clock-aligned inside
        their pool.build windows, and the consumed sidecars are gone
        (tier-1 sibling: TestSidecar.test_dump_read_roundtrip...)."""
        from uptune_tpu.exec.controller import ProgramTuner
        prog = tmp_path / "prog.py"
        prog.write_text(self.PROG)
        obs.enable()
        pt = ProgramTuner([sys.executable, str(prog)], str(tmp_path),
                          parallel=1, prefetch=0, test_limit=3, seed=0,
                          store_dir="off", env=ENV, runtime_limit=60.0)
        pt.run()
        evs = obs.snapshot()["events"]
        child = [e for e in evs if e["name"].startswith("child.")]
        assert {e["track"] for e in child} == {"worker-0"}
        assert {"child.run", "child.target",
                "child.load_proposal"} <= {e["name"] for e in child}
        builds = {(e["attrs"] or {}).get("gid"): e for e in evs
                  if e["name"] == "pool.build"}
        for e in child:
            b = builds[(e["attrs"] or {}).get("gid")]
            assert b["ts"] - 0.1 <= e["ts"] <= b["ts"] + b["dur"] + 0.1
        # every sidecar was consumed at reap
        temp = tmp_path / "ut.temp"
        assert not list(temp.glob("temp.*/" + sidecar.SIDECAR_FILE))
        from uptune_tpu.obs import metrics as m
        assert m.snapshot()["counters"]["pool.sidecar_events"] >= 3

    def test_sigint_flushes_truncated_telemetry(self, tmp_path):
        """An interrupted `ut` run (the satellite): SIGINT mid-tune
        still leaves a validate_trace-clean trace and a metrics
        timeline ending in a final row (tier-1 sibling:
        TestExitFlush)."""
        prog = tmp_path / "prog.py"
        prog.write_text(self.PROG)
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        env.pop("UT_TRACE_GUARD", None)
        p = subprocess.Popen(
            [sys.executable, "-m", "uptune_tpu.cli", str(prog),
             "--test-limit", "500", "-pf", "1", "--store", "off",
             "--trace", "t.json", "--metrics-interval", "0.2"],
            cwd=str(tmp_path), env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        deadline = time.time() + 300
        archive = tmp_path / "ut.archive.jsonl"
        while time.time() < deadline and not archive.exists():
            time.sleep(0.3)
        assert archive.exists(), "tune never got under way"
        time.sleep(1.0)
        p.send_signal(signal.SIGINT)
        out, _ = p.communicate(timeout=120)
        assert p.returncode != 0            # it WAS interrupted
        doc = json.load(open(tmp_path / "t.json"))
        obs.validate_trace(doc)
        assert doc["otherData"]["flushed_on"] in (
            "signal:2", "atexit"), out
        rows = [json.loads(l)
                for l in open(tmp_path / "t.json.metrics.jsonl")]
        assert rows and rows[-1]["final"] is True
