"""Multi-host bootstrap + REAL multi-process DCN tests.

TestConfig/TestMesh verify the config-resolution layer and the mesh
layout contract on the virtual 8-device CPU platform.  TestTwoProcess
(SURVEY §4's multi-host requirement; VERDICT r2 next-step #4) spawns two
actual `jax.distributed` processes, builds the hybrid mesh, runs sharded
engine steps with the cross-host best-exchange collective, and asserts
both processes computed the same global best."""
import os
import socket
import subprocess
import sys

import pytest

jax = pytest.importorskip("jax")

from uptune_tpu.parallel import (distributed_config,  # noqa: E402
                                 is_coordinator, make_multihost_mesh)


class TestConfig:
    def test_single_process_defaults(self, monkeypatch):
        for v in ("UT_COORDINATOR", "UT_NUM_PROCESSES", "UT_PROCESS_ID"):
            monkeypatch.delenv(v, raising=False)
        cfg = distributed_config()
        assert cfg == {"coordinator_address": None, "num_processes": 1,
                       "process_id": 0}

    def test_env_layer(self, monkeypatch):
        monkeypatch.setenv("UT_COORDINATOR", "10.0.0.1:1234")
        monkeypatch.setenv("UT_NUM_PROCESSES", "4")
        monkeypatch.setenv("UT_PROCESS_ID", "2")
        cfg = distributed_config()
        assert cfg["coordinator_address"] == "10.0.0.1:1234"
        assert cfg["num_processes"] == 4 and cfg["process_id"] == 2

    def test_args_beat_env(self, monkeypatch):
        monkeypatch.setenv("UT_NUM_PROCESSES", "4")
        monkeypatch.setenv("UT_COORDINATOR", "env:1")
        cfg = distributed_config("arg:2", 8, 7)
        assert cfg["coordinator_address"] == "arg:2"
        assert cfg["num_processes"] == 8 and cfg["process_id"] == 7

    def test_validation(self, monkeypatch):
        for v in ("UT_COORDINATOR", "UT_NUM_PROCESSES", "UT_PROCESS_ID"):
            monkeypatch.delenv(v, raising=False)
        with pytest.raises(ValueError, match="coordinator"):
            distributed_config(num_processes=2)
        with pytest.raises(ValueError, match="outside"):
            distributed_config("h:1", 2, 5)
        with pytest.raises(ValueError, match=">= 1"):
            distributed_config(num_processes=0)


@pytest.mark.slow
class TestTwoProcess:
    def test_distributed_best_exchange(self, tmp_path):
        """2 jax.distributed CPU processes × 2 devices: initialize() for
        real, hybrid mesh, 25 sharded steps, identical global best."""
        port = _free_port()
        env_base = {k: v for k, v in os.environ.items()
                    if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        worker = os.path.join(os.path.dirname(__file__),
                              "multihost_worker.py")
        procs = []
        for pid in range(2):
            env = dict(
                env_base,
                JAX_PLATFORMS="cpu",
                UT_COORDINATOR=f"localhost:{port}",
                UT_NUM_PROCESSES="2",
                UT_PROCESS_ID=str(pid),
            )
            procs.append(subprocess.Popen(
                [sys.executable, worker], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        outs = []
        for p in procs:
            try:
                out, err = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("multihost worker hung")
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(out)
        bests = []
        for out in outs:
            line = next(ln for ln in out.splitlines()
                        if ln.startswith("UT_MH "))
            bests.append(line.split("global_best=")[1].split()[0])
        # both processes computed the identical global best
        assert bests[0] == bests[1], outs
        # exactly one coordinator
        coords = [("coord=True" in o) for o in outs]
        assert sorted(coords) == [False, True], outs


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestMesh:
    def test_layout(self):
        mesh = make_multihost_mesh(n_eval_per_host=2)
        n = len(jax.devices())
        assert dict(mesh.shape) == {"search": n // 2, "eval": 2}
        # eval rows are contiguous device ids (the ICI-island contract)
        ids = [[d.id for d in row] for row in mesh.devices]
        for row in ids:
            assert row == sorted(row)
            assert row[1] == row[0] + 1

    def test_indivisible(self):
        with pytest.raises(ValueError, match="divid"):
            make_multihost_mesh(n_eval_per_host=3)

    def test_eval_wider_than_host_rejected(self):
        n = len(jax.devices())
        with pytest.raises(ValueError, match="divid"):
            make_multihost_mesh(n_eval_per_host=n * 2)

    def test_coordinator_predicate(self):
        assert is_coordinator() is True   # single-process run
