"""Multi-host bootstrap + REAL multi-process DCN tests.

TestConfig/TestMesh verify the config-resolution layer and the mesh
layout contract on the virtual 8-device CPU platform.  TestTwoProcess
(SURVEY §4's multi-host requirement; VERDICT r2 next-step #4) spawns two
actual `jax.distributed` processes, builds the hybrid mesh, runs sharded
engine steps with the cross-host best-exchange collective, and asserts
both processes computed the same global best."""
import json
import os
import socket
import subprocess
import sys

import pytest

jax = pytest.importorskip("jax")

from uptune_tpu.parallel import (distributed_config,  # noqa: E402
                                 is_coordinator, make_multihost_mesh)

# some jax builds cannot run REAL multi-process collectives on the CPU
# backend ("Multiprocess computations aren't implemented ..."): a
# capability gap in the environment, not a regression in this repo —
# detect it from the worker's own failure and skip cleanly instead of
# failing the suite (CHANGES.md PR 8 noted the drift)
_MULTIPROC_UNSUPPORTED = (
    "Multiprocess computations aren't implemented",
    "multi-process deployments are not supported on the CPU backend",
)


def _skip_if_multiproc_unsupported(rc: int, out: str, err: str) -> None:
    if rc != 0 and any(m in out + err for m in _MULTIPROC_UNSUPPORTED):
        pytest.skip("this jax build's CPU backend does not implement "
                    "multi-process collectives (environment "
                    "capability, not a repo regression)")


class TestConfig:
    def test_single_process_defaults(self, monkeypatch):
        for v in ("UT_COORDINATOR", "UT_NUM_PROCESSES", "UT_PROCESS_ID"):
            monkeypatch.delenv(v, raising=False)
        cfg = distributed_config()
        assert cfg == {"coordinator_address": None, "num_processes": 1,
                       "process_id": 0}

    def test_env_layer(self, monkeypatch):
        monkeypatch.setenv("UT_COORDINATOR", "10.0.0.1:1234")
        monkeypatch.setenv("UT_NUM_PROCESSES", "4")
        monkeypatch.setenv("UT_PROCESS_ID", "2")
        cfg = distributed_config()
        assert cfg["coordinator_address"] == "10.0.0.1:1234"
        assert cfg["num_processes"] == 4 and cfg["process_id"] == 2

    def test_args_beat_env(self, monkeypatch):
        monkeypatch.setenv("UT_NUM_PROCESSES", "4")
        monkeypatch.setenv("UT_COORDINATOR", "env:1")
        cfg = distributed_config("arg:2", 8, 7)
        assert cfg["coordinator_address"] == "arg:2"
        assert cfg["num_processes"] == 8 and cfg["process_id"] == 7

    def test_validation(self, monkeypatch):
        for v in ("UT_COORDINATOR", "UT_NUM_PROCESSES", "UT_PROCESS_ID"):
            monkeypatch.delenv(v, raising=False)
        with pytest.raises(ValueError, match="coordinator"):
            distributed_config(num_processes=2)
        with pytest.raises(ValueError, match="outside"):
            distributed_config("h:1", 2, 5)
        with pytest.raises(ValueError, match=">= 1"):
            distributed_config(num_processes=0)


@pytest.mark.slow
class TestTwoProcess:
    def test_distributed_best_exchange(self, tmp_path):
        """2 jax.distributed CPU processes × 2 devices: initialize() for
        real, hybrid mesh, 25 sharded steps, identical global best."""
        port = _free_port()
        env_base = {k: v for k, v in os.environ.items()
                    if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        worker = os.path.join(os.path.dirname(__file__),
                              "multihost_worker.py")
        procs = []
        for pid in range(2):
            env = dict(
                env_base,
                JAX_PLATFORMS="cpu",
                UT_COORDINATOR=f"localhost:{port}",
                UT_NUM_PROCESSES="2",
                UT_PROCESS_ID=str(pid),
            )
            procs.append(subprocess.Popen(
                [sys.executable, worker], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        outs = []
        for p in procs:
            try:
                out, err = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("multihost worker hung")
            _skip_if_multiproc_unsupported(p.returncode, out, err)
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(out)
        bests = []
        for out in outs:
            line = next(ln for ln in out.splitlines()
                        if ln.startswith("UT_MH "))
            bests.append(line.split("global_best=")[1].split()[0])
        # both processes computed the identical global best
        assert bests[0] == bests[1], outs
        # exactly one coordinator
        coords = [("coord=True" in o) for o in outs]
        assert sorted(coords) == [False, True], outs


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_workers(n, port, extra_env, worker=None):
    env_base = {k: v for k, v in os.environ.items()
                if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    worker = worker or os.path.join(os.path.dirname(__file__),
                                    "multihost_worker.py")
    procs = []
    for pid in range(n):
        env = dict(
            env_base,
            JAX_PLATFORMS="cpu",
            UT_COORDINATOR=f"localhost:{port}",
            UT_NUM_PROCESSES=str(n),
            UT_PROCESS_ID=str(pid),
            **{k: (v.format(pid=pid) if isinstance(v, str) else v)
               for k, v in extra_env.items()},
        )
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    return procs


@pytest.mark.slow
class TestFourProcessElastic:
    """VERDICT r3 next-step #5: 4 jax.distributed processes × 2 devices
    with per-process seed offsets (uneven best trajectories), a
    checkpointed best, a SIGKILLed worker mid-phase (pod preemption —
    the TPU failure model is job-level restart, not MPI-style membership
    change), and a resumed 4-process job that restores the checkpoint
    and never regresses past it."""

    def test_kill_and_resume_over_dcn(self, tmp_path):
        import time as _time
        ckpt = str(tmp_path / "best.json")

        # phase A: clean 4-proc run, uneven seeds, writes the checkpoint
        procs = _spawn_workers(4, _free_port(), {"UT_MH_CKPT": ckpt})
        outs = _communicate_all(procs, timeout=600)
        bests = set()
        coords = 0
        for out in outs:
            line = next(ln for ln in out.splitlines()
                        if ln.startswith("UT_MH "))
            bests.add(line.split("global_best=")[1].split()[0])
            coords += "coord=True" in line
        assert len(bests) == 1, outs     # all 4 agree after exchange
        assert coords == 1, outs         # exactly one coordinator
        assert os.path.exists(ckpt)
        import json as _json
        with open(ckpt) as f:
            saved = _json.load(f)
        assert saved["qor"] < 1.0

        # phase B: same job, long-running; SIGKILL one worker mid-phase,
        # then tear down the rest (the job dies as a unit — preemption)
        beacon = str(tmp_path / "started_{pid}.txt")
        procs = _spawn_workers(4, _free_port(), {
            "UT_MH_STEPS": "4000",
            "UT_MH_START_FILE": beacon,
        })
        deadline = _time.time() + 420
        while _time.time() < deadline and not all(
                os.path.exists(beacon.format(pid=p)) for p in range(4)):
            _time.sleep(0.5)
        assert all(os.path.exists(beacon.format(pid=p))
                   for p in range(4)), "phase B never got under way"
        procs[2].kill()                       # the preempted host
        rc2 = procs[2].wait(timeout=60)
        assert rc2 != 0
        for p in procs:                       # job-level teardown
            p.kill()
            p.wait(timeout=60)

        # phase C: restart the whole 4-proc job with resume: it restores
        # the phase-A best and must end at-or-below it, all agreeing
        procs = _spawn_workers(4, _free_port(), {
            "UT_MH_CKPT": ckpt, "UT_MH_RESUME": "1"})
        outs = _communicate_all(procs, timeout=600)
        finals = set()
        for out in outs:
            line = next(ln for ln in out.splitlines()
                        if ln.startswith("UT_MH "))
            assert f"restored={saved['qor']:.9f}" in line, line
            finals.add(float(line.split("global_best=")[1].split()[0]))
        assert len(finals) == 1
        assert finals.pop() <= saved["qor"] + 1e-9


def _communicate_all(procs, timeout):
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker hung")
        _skip_if_multiproc_unsupported(p.returncode, out, err)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
        outs.append(out)
    return outs


class TestMesh:
    def test_layout(self):
        mesh = make_multihost_mesh(n_eval_per_host=2)
        n = len(jax.devices())
        assert dict(mesh.shape) == {"search": n // 2, "eval": 2}
        # eval rows are contiguous device ids (the ICI-island contract)
        ids = [[d.id for d in row] for row in mesh.devices]
        for row in ids:
            assert row == sorted(row)
            assert row[1] == row[0] + 1

    def test_indivisible(self):
        with pytest.raises(ValueError, match="divid"):
            make_multihost_mesh(n_eval_per_host=3)

    def test_eval_wider_than_host_rejected(self):
        n = len(jax.devices())
        with pytest.raises(ValueError, match="divid"):
            make_multihost_mesh(n_eval_per_host=n * 2)

    def test_coordinator_predicate(self):
        assert is_coordinator() is True   # single-process run


class TestLauncher:
    def test_num_hosts_spawns_prefixed_children(self, capsys):
        """`ut --num-hosts 2 ...` runs the same command in 2 local
        processes with the UT_* distributed env wired (the cluster
        provisioning analogue, cluster/config.yaml)."""
        from uptune_tpu.cli import main as cli_main
        rc = cli_main(["--num-hosts", "2", "--list-techniques"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "[h0] PureRandom" in out
        assert "[h1] PureRandom" in out

    def test_child_does_not_relaunch(self, monkeypatch, capsys):
        """A child (UT_PROCESS_ID set) must run the command itself, not
        fork another fleet."""
        monkeypatch.setenv("UT_PROCESS_ID", "0")
        from uptune_tpu.cli import main as cli_main
        rc = cli_main(["--num-hosts", "2", "--list-techniques"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "[h0]" not in out and "PureRandom" in out


class TestTwoProcessLoopback:
    """The wire-kernel loopback sibling of TestTwoProcess (ISSUE 17):
    the jax builds on this box may not implement CPU multi-process
    collectives, which skips the real DCN cases above — this covers
    the two-process wiring that IS this repo's code (serve/wire.py
    asyncio kernel + serve/router.py consistent-hash placement) over
    real localhost TCP with zero jax in the workers, so it runs in
    tier-1 unconditionally."""

    N_KEYS = 48

    @staticmethod
    def _req(port, payload):
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=10) as s:
            f = s.makefile("rwb")
            f.write((json.dumps(payload) + "\n").encode())
            f.flush()
            return json.loads(f.readline())

    def test_routed_tells_across_two_workers(self, tmp_path):
        from uptune_tpu.utils.pypath import child_pythonpath
        worker = os.path.join(os.path.dirname(__file__),
                              "wire_worker.py")
        env = dict(os.environ, PYTHONPATH=child_pythonpath())
        procs = [subprocess.Popen(
            [sys.executable, worker], stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env)
            for _ in range(2)]
        from uptune_tpu.serve.router import Router
        router = None
        try:
            ports = []
            for p in procs:
                line = p.stdout.readline().strip()
                assert line.startswith("PORT "), line
                ports.append(int(line.split()[1]))
            router = Router(shards=0, work_dir=str(tmp_path),
                            supervise_interval=30.0).start()
            by_name = {}
            for port in ports:
                by_name[router.register("127.0.0.1", port)] = port

            # route every key through the router's own TCP port, tell
            # its qor to the owning worker, and re-look-up afterwards:
            # placement must be a pure function of the key
            qors = {f"loop-{i}": ((i * 37) % 101) / 10.0
                    for i in range(self.N_KEYS)}
            owners = {}
            for key, qor in qors.items():
                r = self._req(router.port, {"op": "route", "key": key})
                assert r["ok"], r
                owners[key] = r["shard"]
                t = self._req(by_name[r["shard"]],
                              {"op": "tell", "qor": qor})
                assert t["ok"], t
            for key in qors:
                r = self._req(router.port, {"op": "route", "key": key})
                assert r["shard"] == owners[key]

            # both workers took real traffic, nothing was lost, and
            # the per-worker minima compose to the global minimum
            assert len(set(owners.values())) == 2, owners
            bests = {}
            tells = 0
            for name, port in by_name.items():
                b = self._req(port, {"op": "best"})
                tells += b["tells"]
                bests[name] = b["best"]
            assert tells == self.N_KEYS
            for name in by_name:
                want = min(q for k, q in qors.items()
                           if owners[k] == name)
                assert bests[name] == want
            assert min(bests.values()) == min(qors.values())
        finally:
            if router is not None:
                router.stop()
            for p in procs:
                if p.stdin:
                    p.stdin.close()     # the worker's exit signal
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()


class TestTwoProcessCooperativeStore:
    """The cooperative-store loopback sibling of TestTwoProcessLoopback
    (ISSUE 18): two jax-free worker processes, each holding a
    RemoteStore client, cooperate through one StoreServer over real
    localhost TCP — record/ack, cross-worker delta feeds, and the
    shared-memo lookup that IS the fabric's reason to exist.  Runs in
    tier-1 unconditionally."""

    @staticmethod
    def _req(port, payload):
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=10) as s:
            f = s.makefile("rwb")
            f.write((json.dumps(payload) + "\n").encode())
            f.flush()
            resp = json.loads(f.readline())
        assert resp.get("ok"), resp
        return resp

    def test_two_workers_share_one_store(self, tmp_path):
        from uptune_tpu.store.server import StoreServer
        from uptune_tpu.utils.pypath import child_pythonpath
        srv = StoreServer("127.0.0.1", 0,
                          str(tmp_path / "store")).start()
        worker = os.path.join(os.path.dirname(__file__),
                              "store_worker.py")
        env = dict(os.environ, PYTHONPATH=child_pythonpath())
        addr = f"tcp://127.0.0.1:{srv.port}"
        procs = [subprocess.Popen(
            [sys.executable, worker, addr, tag],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=env)
            for tag in ("a", "b")]
        try:
            ports = []
            for p in procs:
                line = p.stdout.readline().strip()
                assert line.startswith("PORT "), line
                ports.append(int(line.split()[1]))
            pa, pb = ports

            # A records 10 acked rows; B's delta pull sees exactly
            # those 10 as FOREIGN fresh rows (elite-migration feed)
            ra = self._req(pa, {"op": "record", "n": 10, "base": 5.0})
            assert len(ra["keys"]) == 10 and ra["shipped"]
            sb = self._req(pb, {"op": "sync"})
            assert sb["merged"] == 10 and len(sb["fresh"]) == 10
            assert all(c["w"] == "a" for c in sb["fresh"])
            assert sb["best_qor"] == 5.0

            # B records 4; A sees only B's 4 (its own never echo back)
            rb = self._req(pb, {"op": "record", "n": 4, "base": 1.0})
            assert len(rb["keys"]) == 4 and rb["shipped"]
            sa = self._req(pa, {"op": "sync"})
            assert len(sa["fresh"]) == 4
            assert all(c["w"] == "b" for c in sa["fresh"])
            assert sa["rows"] == 14 and sa["best_qor"] == 1.0

            # the cross-tenant memo: A serves B's measurement by key
            la = self._req(pa, {"op": "lookup",
                                "cfg": {"w": "b", "i": 2}})
            assert la["row"] is not None and la["row"]["qor"] == 3.0

            # a second sync is quiet — the delta cursor advanced
            assert self._req(pb, {"op": "sync"})["merged"] == 0
            st = self._req(pa, {"op": "stats"})["stats"]["remote"]
            assert st["connected"] and st["acked"] == 10
            assert st["dropped"] == 0
            with srv._lock:
                assert srv.recorded == 14 and srv.dups == 0
        finally:
            srv.stop()
            for p in procs:
                if p.stdin:
                    p.stdin.close()     # the worker's exit signal
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()


@pytest.mark.slow
class TestLauncherTune:
    def test_two_replica_program_tune(self, tmp_path):
        """`ut --num-hosts 2 prog.py`: replicas diverge (per-replica
        seed), write separate archives/bests (no shared-file races on
        one work_dir — slot sandboxes are namespaced per replica), and
        the launcher promotes the winner to best.json (r4 review: the
        plumbing-only test missed all of this)."""
        import json as _json
        import shutil

        src = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "samples", "rosenbrock",
            "rosenbrock.py")
        prog = tmp_path / "rosenbrock.py"
        shutil.copy(src, prog)
        env = dict(os.environ)
        env.pop("UT_PROCESS_ID", None)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, "-m", "uptune_tpu.cli", str(prog),
             "--num-hosts", "2", "--test-limit", "20"],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=str(tmp_path))
        assert r.returncode == 0, r.stdout + r.stderr
        assert "best across 2 replicas" in r.stdout
        arch0 = tmp_path / "ut.archive.jsonl"
        arch1 = tmp_path / "ut.archive.h1.jsonl"
        assert arch0.exists() and arch1.exists()
        best = _json.load(open(tmp_path / "best.json"))
        bests = [best["qor"]]
        if (tmp_path / "best.h1.json").exists():
            bests.append(_json.load(open(tmp_path / "best.h1.json"))["qor"])
        # the promoted best.json is the min across replica bests
        assert best["qor"] == min(bests)
