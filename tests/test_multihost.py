"""Multi-host bootstrap helpers.  Real DCN needs multiple processes;
here we verify the config resolution/validation layer and the mesh
layout contract on the virtual 8-device CPU platform (full sharded
execution is covered by tests/test_engine.py and the driver's
dryrun_multichip)."""
import pytest

jax = pytest.importorskip("jax")

from uptune_tpu.parallel import (distributed_config,  # noqa: E402
                                 is_coordinator, make_multihost_mesh)


class TestConfig:
    def test_single_process_defaults(self, monkeypatch):
        for v in ("UT_COORDINATOR", "UT_NUM_PROCESSES", "UT_PROCESS_ID"):
            monkeypatch.delenv(v, raising=False)
        cfg = distributed_config()
        assert cfg == {"coordinator_address": None, "num_processes": 1,
                       "process_id": 0}

    def test_env_layer(self, monkeypatch):
        monkeypatch.setenv("UT_COORDINATOR", "10.0.0.1:1234")
        monkeypatch.setenv("UT_NUM_PROCESSES", "4")
        monkeypatch.setenv("UT_PROCESS_ID", "2")
        cfg = distributed_config()
        assert cfg["coordinator_address"] == "10.0.0.1:1234"
        assert cfg["num_processes"] == 4 and cfg["process_id"] == 2

    def test_args_beat_env(self, monkeypatch):
        monkeypatch.setenv("UT_NUM_PROCESSES", "4")
        monkeypatch.setenv("UT_COORDINATOR", "env:1")
        cfg = distributed_config("arg:2", 8, 7)
        assert cfg["coordinator_address"] == "arg:2"
        assert cfg["num_processes"] == 8 and cfg["process_id"] == 7

    def test_validation(self, monkeypatch):
        for v in ("UT_COORDINATOR", "UT_NUM_PROCESSES", "UT_PROCESS_ID"):
            monkeypatch.delenv(v, raising=False)
        with pytest.raises(ValueError, match="coordinator"):
            distributed_config(num_processes=2)
        with pytest.raises(ValueError, match="outside"):
            distributed_config("h:1", 2, 5)
        with pytest.raises(ValueError, match=">= 1"):
            distributed_config(num_processes=0)


class TestMesh:
    def test_layout(self):
        mesh = make_multihost_mesh(n_eval_per_host=2)
        n = len(jax.devices())
        assert dict(mesh.shape) == {"search": n // 2, "eval": 2}
        # eval rows are contiguous device ids (the ICI-island contract)
        ids = [[d.id for d in row] for row in mesh.devices]
        for row in ids:
            assert row == sorted(row)
            assert row[1] == row[0] + 1

    def test_indivisible(self):
        with pytest.raises(ValueError, match="divid"):
            make_multihost_mesh(n_eval_per_host=3)

    def test_eval_wider_than_host_rejected(self):
        n = len(jax.devices())
        with pytest.raises(ValueError, match="divid"):
            make_multihost_mesh(n_eval_per_host=n * 2)

    def test_coordinator_predicate(self):
        assert is_coordinator() is True   # single-process run
