"""Offline stats / technique-attribution tests: the archive alone must
answer "which technique found the best" (VERDICT round-1 weak #6; the
reference's equivalent is SQL over the requestor column,
opentuner/utils/stats.py)."""
import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from uptune_tpu.driver.driver import Tuner  # noqa: E402
from uptune_tpu.space.params import FloatParam  # noqa: E402
from uptune_tpu.space.spec import Space  # noqa: E402
from uptune_tpu.utils.stats import (ArchiveTail, convergence, follow,  # noqa: E402
                                    load_archive, main, render_table,
                                    technique_report, write_csv)


@pytest.fixture(scope="module")
def archive(tmp_path_factory):
    """A real tuning run's archive (portfolio => several techniques)."""
    path = str(tmp_path_factory.mktemp("arch") / "ut.archive.jsonl")
    space = Space([FloatParam(f"x{i}", -2.0, 2.0) for i in range(3)])

    def obj(cfgs):
        return [sum(c[f"x{i}"] ** 2 for i in range(3)) for c in cfgs]

    t = Tuner(space, obj, seed=0, archive=path)
    t.run(test_limit=200)
    t.close()
    return path


class TestLoadAndReport:
    def test_load_skips_header(self, archive):
        rows = load_archive(archive)
        assert rows and all("space_sig" not in r for r in rows)
        assert all("tech" in r and "qor" in r for r in rows)

    def test_attribution_complete(self, archive):
        rows = load_archive(archive)
        rep = technique_report(rows)
        assert sum(st["evals"] for st in rep.values()) == len(rows)
        # exactly one technique found the global best
        finders = [t for t, st in rep.items() if st["found_global_best"]]
        assert len(finders) == 1
        st = rep[finders[0]]
        assert st["global_best_at"] is not None
        assert rows[st["global_best_at"]]["tech"] == finders[0]
        gbest = min(float(r["qor"]) for r in rows
                    if np.isfinite(r["qor"]))
        assert st["best_qor"] == pytest.approx(gbest)

    def test_multiple_techniques_pulled(self, archive):
        rep = technique_report(load_archive(archive))
        assert len(rep) >= 2   # the portfolio really rotated arms

    def test_sense_max(self):
        rows = [{"tech": "a", "qor": 5.0, "best": True, "time": 0.1},
                {"tech": "b", "qor": 9.0, "best": True, "time": 0.1}]
        rep = technique_report(rows, sense="max")
        assert rep["b"]["found_global_best"]
        assert rep["b"]["best_qor"] == 9.0

    def test_failures_counted(self):
        rows = [{"tech": "a", "qor": float("inf"), "best": False,
                 "time": 0.0},
                {"tech": "a", "qor": 1.0, "best": True, "time": 0.0}]
        rep = technique_report(rows)
        assert rep["a"]["failures"] == 1 and rep["a"]["evals"] == 2


class TestConvergenceAndOutputs:
    def test_convergence_monotone(self, archive):
        conv = convergence(load_archive(archive))
        for tech, pts in conv.items():
            vals = [v for _, v in pts]
            assert vals == sorted(vals, reverse=True) or \
                all(b <= a for a, b in zip(vals, vals[1:]))

    def test_csv(self, archive, tmp_path):
        out = tmp_path / "conv.csv"
        write_csv(load_archive(archive), str(out))
        lines = out.read_text().strip().splitlines()
        assert lines[0] == "technique,eval_index,best_so_far"
        assert len(lines) > 1

    def test_render_table(self, archive):
        text = render_table(technique_report(load_archive(archive)))
        assert "technique" in text and "*" in text

    def test_cli(self, archive, tmp_path, capsys):
        csv = tmp_path / "c.csv"
        rc = main([archive, "--csv", str(csv), "--json"])
        assert rc == 0
        out = capsys.readouterr().out
        rep = json.loads(out)
        assert any(st["found_global_best"] for st in rep.values())
        assert csv.exists()

    def test_cli_empty(self, tmp_path):
        p = tmp_path / "empty.jsonl"
        p.write_text("")
        assert main([str(p)]) == 1


class TestFollow:
    """The during-run live view (reference: the decouple dashboard,
    async_task_scheduler.py:148-209)."""

    @staticmethod
    def _row(i, tech="t", qor=1.0, best=False):
        return json.dumps({"gid": i, "tech": tech, "time": 0.01,
                           "cfg": {}, "u": [], "perms": [],
                           "qor": qor, "best": best}) + "\n"

    def test_tail_reads_incrementally(self, tmp_path):
        p = tmp_path / "a.jsonl"
        p.write_text(json.dumps({"space_sig": "x"}) + "\n"
                     + self._row(0, qor=5.0, best=True))
        tail = ArchiveTail(str(p))
        first = tail.read_new()
        assert len(first) == 1            # header filtered
        assert tail.read_new() == []      # no growth -> no rows
        with open(p, "a") as f:
            f.write(self._row(1, tech="u", qor=3.0, best=True))
        second = tail.read_new()
        assert len(second) == 1 and second[0]["tech"] == "u"

    def test_tail_buffers_partial_lines(self, tmp_path):
        p = tmp_path / "a.jsonl"
        full = self._row(0, qor=2.0)
        p.write_text(full[:10])           # writer mid-line
        tail = ArchiveTail(str(p))
        assert tail.read_new() == []
        with open(p, "a") as f:
            f.write(full[10:])
        assert len(tail.read_new()) == 1

    def test_tail_resets_on_rotation(self, tmp_path):
        p = tmp_path / "a.jsonl"
        p.write_text(self._row(0) + self._row(1))
        tail = ArchiveTail(str(p))
        assert len(tail.read_new()) == 2
        p.write_text(self._row(7, tech="fresh"))   # shrank: rotated
        rows = tail.read_new()
        assert len(rows) == 1 and rows[0]["tech"] == "fresh"

    def test_follow_renders_live_view(self, tmp_path, capsys):
        p = tmp_path / "a.jsonl"
        p.write_text(self._row(0, tech="DE", qor=4.0, best=True)
                     + self._row(1, tech="DE", qor=9.0))
        rc = follow(str(p), interval=0.01, max_polls=3)
        assert rc == 0
        out = capsys.readouterr().out
        assert "evals=2" in out and "best=4" in out and "DE" in out
