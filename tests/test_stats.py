"""Offline stats / technique-attribution tests: the archive alone must
answer "which technique found the best" (VERDICT round-1 weak #6; the
reference's equivalent is SQL over the requestor column,
opentuner/utils/stats.py)."""
import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from uptune_tpu.driver.driver import Tuner  # noqa: E402
from uptune_tpu.space.params import FloatParam  # noqa: E402
from uptune_tpu.space.spec import Space  # noqa: E402
from uptune_tpu.utils.stats import (ArchiveTail, convergence, follow,  # noqa: E402
                                    load_archive, main, render_table,
                                    technique_report, write_csv)


@pytest.fixture(scope="module")
def archive(tmp_path_factory):
    """A real tuning run's archive (portfolio => several techniques)."""
    path = str(tmp_path_factory.mktemp("arch") / "ut.archive.jsonl")
    space = Space([FloatParam(f"x{i}", -2.0, 2.0) for i in range(3)])

    def obj(cfgs):
        return [sum(c[f"x{i}"] ** 2 for i in range(3)) for c in cfgs]

    t = Tuner(space, obj, seed=0, archive=path)
    t.run(test_limit=200)
    t.close()
    return path


class TestLoadAndReport:
    def test_load_skips_header(self, archive):
        rows = load_archive(archive)
        assert rows and all("space_sig" not in r for r in rows)
        assert all("tech" in r and "qor" in r for r in rows)

    def test_attribution_complete(self, archive):
        rows = load_archive(archive)
        rep = technique_report(rows)
        assert sum(st["evals"] for st in rep.values()) == len(rows)
        # exactly one technique found the global best
        finders = [t for t, st in rep.items() if st["found_global_best"]]
        assert len(finders) == 1
        st = rep[finders[0]]
        assert st["global_best_at"] is not None
        assert rows[st["global_best_at"]]["tech"] == finders[0]
        gbest = min(float(r["qor"]) for r in rows
                    if np.isfinite(r["qor"]))
        assert st["best_qor"] == pytest.approx(gbest)

    def test_multiple_techniques_pulled(self, archive):
        rep = technique_report(load_archive(archive))
        assert len(rep) >= 2   # the portfolio really rotated arms

    def test_sense_max(self):
        rows = [{"tech": "a", "qor": 5.0, "best": True, "time": 0.1},
                {"tech": "b", "qor": 9.0, "best": True, "time": 0.1}]
        rep = technique_report(rows, sense="max")
        assert rep["b"]["found_global_best"]
        assert rep["b"]["best_qor"] == 9.0

    def test_failures_counted(self):
        rows = [{"tech": "a", "qor": float("inf"), "best": False,
                 "time": 0.0},
                {"tech": "a", "qor": 1.0, "best": True, "time": 0.0}]
        rep = technique_report(rows)
        assert rep["a"]["failures"] == 1 and rep["a"]["evals"] == 2


class TestConvergenceAndOutputs:
    def test_convergence_monotone(self, archive):
        conv = convergence(load_archive(archive))
        for tech, pts in conv.items():
            vals = [v for _, v in pts]
            assert vals == sorted(vals, reverse=True) or \
                all(b <= a for a, b in zip(vals, vals[1:]))

    def test_csv(self, archive, tmp_path):
        out = tmp_path / "conv.csv"
        write_csv(load_archive(archive), str(out))
        lines = out.read_text().strip().splitlines()
        assert lines[0] == "technique,eval_index,best_so_far"
        assert len(lines) > 1

    def test_render_table(self, archive):
        text = render_table(technique_report(load_archive(archive)))
        assert "technique" in text and "*" in text

    def test_cli(self, archive, tmp_path, capsys):
        csv = tmp_path / "c.csv"
        rc = main([archive, "--csv", str(csv), "--json"])
        assert rc == 0
        out = capsys.readouterr().out
        rep = json.loads(out)
        assert any(st["found_global_best"] for st in rep.values())
        assert csv.exists()

    def test_cli_empty(self, tmp_path):
        p = tmp_path / "empty.jsonl"
        p.write_text("")
        assert main([str(p)]) == 1


class TestFollow:
    """The during-run live view (reference: the decouple dashboard,
    async_task_scheduler.py:148-209)."""

    @staticmethod
    def _row(i, tech="t", qor=1.0, best=False):
        return json.dumps({"gid": i, "tech": tech, "time": 0.01,
                           "cfg": {}, "u": [], "perms": [],
                           "qor": qor, "best": best}) + "\n"

    def test_tail_reads_incrementally(self, tmp_path):
        p = tmp_path / "a.jsonl"
        p.write_text(json.dumps({"space_sig": "x"}) + "\n"
                     + self._row(0, qor=5.0, best=True))
        tail = ArchiveTail(str(p))
        first = tail.read_new()
        assert len(first) == 1            # header filtered
        assert tail.read_new() == []      # no growth -> no rows
        with open(p, "a") as f:
            f.write(self._row(1, tech="u", qor=3.0, best=True))
        second = tail.read_new()
        assert len(second) == 1 and second[0]["tech"] == "u"

    def test_tail_buffers_partial_lines(self, tmp_path):
        p = tmp_path / "a.jsonl"
        full = self._row(0, qor=2.0)
        p.write_text(full[:10])           # writer mid-line
        tail = ArchiveTail(str(p))
        assert tail.read_new() == []
        with open(p, "a") as f:
            f.write(full[10:])
        assert len(tail.read_new()) == 1

    def test_tail_resets_on_rotation(self, tmp_path):
        p = tmp_path / "a.jsonl"
        p.write_text(self._row(0) + self._row(1))
        tail = ArchiveTail(str(p))
        assert len(tail.read_new()) == 2
        p.write_text(self._row(7, tech="fresh"))   # shrank: rotated
        rows = tail.read_new()
        assert len(rows) == 1 and rows[0]["tech"] == "fresh"

    def test_follow_renders_live_view(self, tmp_path, capsys):
        p = tmp_path / "a.jsonl"
        p.write_text(self._row(0, tech="DE", qor=4.0, best=True)
                     + self._row(1, tech="DE", qor=9.0))
        rc = follow(str(p), interval=0.01, max_polls=3)
        assert rc == 0
        out = capsys.readouterr().out
        assert "evals=2" in out and "best=4" in out and "DE" in out


class TestCompareMode:
    """Cross-run technique comparison (stats_matplotlib.py equivalent,
    VERDICT r3 next-step #6): multiple archives -> per-technique median
    best-so-far."""

    def _mk(self, tmp_path, name, rows):
        p = tmp_path / name
        with open(p, "w") as f:
            f.write(json.dumps({"space_sig": "x"}) + "\n")
            for r in rows:
                f.write(json.dumps(r) + "\n")
        return str(p)

    @staticmethod
    def _row(tech, qor):
        return {"tech": tech, "qor": qor, "time": 0.0}

    def test_median_across_runs(self, tmp_path):
        from uptune_tpu.utils.stats import compare_convergence
        a = [self._row("t", 10.0), self._row("t", 4.0)]
        b = [self._row("t", 8.0), self._row("t", 6.0)]
        c = [self._row("t", 2.0), self._row("t", 9.0)]
        conv = compare_convergence([a, b, c])
        pts = dict((int(i), v) for i, v in conv["t"])
        # at eval 0 best-so-fars are 10/8/2 -> median 8;
        # at eval 1 they are 4/6/2 -> median 4
        assert pts[0] == 8.0
        assert pts[1] == 4.0

    def test_technique_absent_from_one_run(self, tmp_path):
        from uptune_tpu.utils.stats import compare_convergence
        a = [self._row("t", 5.0), self._row("u", 3.0)]
        b = [self._row("t", 7.0), self._row("t", 1.0)]
        conv = compare_convergence([a, b])
        assert "u" in conv    # present in only one run still plotted
        assert conv["u"][0][1] == 3.0

    def test_cli_multi_archive(self, tmp_path, capsys):
        from uptune_tpu.utils.stats import main as stats_main
        p1 = self._mk(tmp_path, "a.jsonl",
                      [self._row("t", 5.0), self._row("u", 3.0)])
        p2 = self._mk(tmp_path, "b.jsonl",
                      [self._row("t", 2.0)])
        csv = tmp_path / "cmp.csv"
        rc = stats_main([p1, p2, "--csv", str(csv)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "cross-run comparison over 2 archives" in out
        assert "median_best_so_far" in csv.read_text()

    def test_follow_accumulator_matches_full_recompute(self, archive):
        """The incremental fold must reproduce technique_report exactly
        (VERDICT r3 weak #6), fed in uneven chunks."""
        from uptune_tpu.utils.stats import FollowAccumulator
        rows = load_archive(archive)
        acc = FollowAccumulator("min")
        i = 0
        for sz in (1, 7, 31, 64, 1000):
            acc.update(rows[i:i + sz])
            i += sz
        acc.update(rows[i:])
        full = technique_report(rows)
        assert acc.snapshot() == full


def test_compact_archive_dedups_and_guards(tmp_path):
    """--compact keeps the header + first row per config, and ABORTS if
    the archive grows mid-compaction (a live tuner appending would keep
    writing to the replaced inode — rows would vanish silently)."""
    from uptune_tpu.utils.stats import compact_archive
    p = tmp_path / "a.jsonl"
    rows = [{"gid": 0, "tech": "t", "qor": 1.0, "u": [0.1], "perms": []},
            {"gid": 1, "tech": "t", "qor": 2.0, "u": [0.2], "perms": []},
            {"gid": 2, "tech": "u", "qor": 1.0, "u": [0.1], "perms": []}]
    with open(p, "w") as f:
        f.write(json.dumps({"space_sig": "s"}) + "\n")
        for r in rows:
            f.write(json.dumps(r) + "\n")
        f.write('{"torn')
    st = compact_archive(str(p))
    assert st == {"rows_before": 3, "rows_after": 2}
    kept = [json.loads(l) for l in open(p)]
    assert "space_sig" in kept[0]
    assert [r["gid"] for r in kept[1:]] == [0, 1]  # first dup wins

    # live-writer guard: grow the file between read and replace by
    # monkeypatching getsize is overkill — simulate with an appender
    import os as _os
    import uptune_tpu.utils.stats as stats_mod
    real_getsize = _os.path.getsize
    calls = {"n": 0}

    def growing(path_):
        calls["n"] += 1
        return real_getsize(path_) + (0 if calls["n"] == 1 else 64)

    stats_mod.os.path.getsize = growing
    try:
        with pytest.raises(RuntimeError, match="grew while compacting"):
            compact_archive(str(p))
    finally:
        stats_mod.os.path.getsize = real_getsize
    # aborted compaction left the archive untouched
    assert [json.loads(l) for l in open(p)] == kept


def test_compare_convergence_carries_finished_runs_forward():
    """A short (target-hit) run keeps contributing its final best to
    later grid points — the median best-so-far must never regress when
    a run ends (r4 review finding)."""
    from uptune_tpu.utils.stats import compare_convergence
    short = [{"tech": "t", "qor": 1.0}]
    long_ = [{"tech": "t", "qor": 100.0} for _ in range(50)]
    conv = compare_convergence([short, long_])
    vals = [v for _, v in conv["t"]]
    # median of (1.0 carried, 100.0) stays 50.5 to the end — no jump up
    assert all(abs(v - 50.5) < 1e-9 for v in vals), vals
    assert vals == sorted(vals, reverse=True) or len(set(vals)) == 1


def test_compacted_archive_preserves_eval_budget(tmp_path):
    """Resume after --compact must not shrink evals/told: the dropped
    duplicate rows' budget would otherwise be re-spent in real
    evaluations (r4 review finding)."""
    from uptune_tpu.utils.stats import compact_archive
    space = Space([FloatParam("x", -1.0, 1.0)])

    def obj(cfgs):
        return [c["x"] ** 2 for c in cfgs]

    arch = str(tmp_path / "a.jsonl")
    t = Tuner(space, obj, seed=0, archive=arch)
    t.run(test_limit=300)
    evals0, best0 = t.evals, t.result().best_qor
    t.close()
    st = compact_archive(arch)
    assert st["rows_before"] >= st["rows_after"]
    t2 = Tuner(space, obj, seed=1, archive=arch, resume=True)
    assert t2.evals == evals0, (t2.evals, evals0)
    assert abs(t2.result().best_qor - best0) < 1e-9
    # a second compaction accumulates the counter instead of resetting
    t2.close()
    st2 = compact_archive(arch)
    t3 = Tuner(space, obj, seed=2, archive=arch, resume=True)
    assert t3.evals >= evals0
    t3.close()
