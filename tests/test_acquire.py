"""Fused acquisition pipeline (ops/acquire.py) and the shared
UT_PALLAS routing knob (ops/routing.py) — ISSUE 19 tier-1.

Parity contract (established empirically; docs/PERF.md):

* interpret route vs XLA-fallback route on the FLAT batch is BITWISE
  for every kind and for top-k (values and indices): the fallback runs
  the same per-tile utility function under lax.map over identical
  tiles, so both routes stage identical computations.
* kind='mean' is additionally bitwise against the materialized
  unfused reference (same dot staging).
* 'ei'/'lcb' differ from the MATERIALIZED reference only by XLA
  fusion/FMA context (~2e-7): asserted allclose, with top-k INDEX
  equality (selection-identical) rather than value-bitwise.
* vmapped comparisons are allclose + index equality: batching changes
  the gemm reduction shapes, so cross-route bitwise is not promised.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from uptune_tpu.api import session
from uptune_tpu.ops import acquire, routing
from uptune_tpu.surrogate import gp


# ---------------------------------------------------------------- routing
class TestRoutingKnob:
    def test_decide_modes(self):
        # off: XLA at any size
        assert routing.decide(1 << 20, mode="off") == routing.XLA
        # interpret: kernel-in-interpret at any size
        assert routing.decide(1, mode="interpret") == routing.INTERPRET
        # auto off-TPU: interpret past min_rows iff cpu_ok
        assert routing.decide(4096, min_rows=4096,
                              mode="auto") == routing.INTERPRET
        assert routing.decide(4095, min_rows=4096,
                              mode="auto") == routing.XLA
        assert routing.decide(4096, min_rows=4096, cpu_ok=False,
                              mode="auto") == routing.XLA
        # unsupported shapes always fall back
        assert routing.decide(1 << 20, supported=False,
                              mode="interpret") == routing.XLA

    def test_env_knob_and_config_precedence(self, monkeypatch):
        monkeypatch.delenv("UT_PALLAS", raising=False)
        session.reset_settings()
        assert routing.pallas_mode() == "auto"
        session.config({"pallas": "off"})
        try:
            assert routing.pallas_mode() == "off"
            # env wins over ut.config
            monkeypatch.setenv("UT_PALLAS", "interpret")
            assert routing.pallas_mode() == "interpret"
        finally:
            session.reset_settings()

    def test_bad_values_raise(self, monkeypatch):
        monkeypatch.setenv("UT_PALLAS", "fast")
        with pytest.raises(ValueError):
            routing.pallas_mode()
        monkeypatch.delenv("UT_PALLAS", raising=False)
        session.reset_settings()
        session.config({"pallas": "sometimes"})   # keys checked here
        try:
            with pytest.raises(ValueError):
                routing.pallas_mode()             # values at read time
        finally:
            session.reset_settings()

    def test_interpret_flag(self):
        assert routing.interpret_flag(routing.INTERPRET) is True
        assert routing.interpret_flag(routing.PALLAS) is False


# ---------------------------------------------------------------- fixtures
def _dense_state():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(48, 6), jnp.float32)
    y = jnp.asarray(rng.randn(48), jnp.float32)
    st = gp.precompute_kinv(gp.fit(x, y))
    return st, float(np.asarray(y).min()), None, 0


def _mixed_state():
    rng = np.random.RandomState(1)
    n_cont, n_cat, K = 3, 4, 3
    codes = rng.randint(K, size=(56, n_cat))
    oh = np.zeros((56, n_cat, K), np.float32)
    np.put_along_axis(oh, codes[:, :, None], 1.0, axis=2)
    x = np.concatenate(
        [rng.rand(56, n_cont).astype(np.float32),
         oh.reshape(56, -1) / np.sqrt(2)], axis=1)
    y = (x[:, 0] + 2.0 * (codes[:, 1] == 0)
         + 0.1 * rng.randn(56)).astype(np.float32)
    st = gp.precompute_kinv(gp.fit(
        jnp.asarray(x), jnp.asarray(y), 0.4, 1e-2,
        n_cont=n_cont, n_cat=n_cat, ls_cat=0.2))
    return st, float(y.min()), n_cont, n_cat


@pytest.fixture(scope="module", params=["dense", "mixed"])
def fitted(request):
    st, best, nc, ncat = (_dense_state() if request.param == "dense"
                          else _mixed_state())
    rng = np.random.RandomState(2)
    xq = jnp.asarray(rng.rand(200, st.x.shape[1]), jnp.float32)
    return st, best, nc, ncat, xq


def _kw(kind, best):
    return {"kind": kind, "best_y": best if kind == "ei" else None}


# ---------------------------------------------------------------- parity
class TestFlatParity:
    @pytest.mark.parametrize("kind", acquire.KINDS)
    def test_interpret_equals_xla_bitwise(self, fitted, kind):
        st, best, nc, ncat, xq = fitted
        u_i = acquire.acquire_scores(st, xq, n_cont=nc, n_cat=ncat,
                                     route=routing.INTERPRET,
                                     **_kw(kind, best))
        u_x = acquire.acquire_scores(st, xq, n_cont=nc, n_cat=ncat,
                                     route=routing.XLA,
                                     **_kw(kind, best))
        np.testing.assert_array_equal(np.asarray(u_i), np.asarray(u_x))

    def test_mean_bitwise_vs_materialized_ref(self, fitted):
        st, best, nc, ncat, xq = fitted
        ref = acquire.acquire_scores_ref(st, xq, kind="mean",
                                         n_cont=nc, n_cat=ncat)
        for route in (routing.INTERPRET, routing.XLA):
            got = acquire.acquire_scores(st, xq, kind="mean",
                                         n_cont=nc, n_cat=ncat,
                                         route=route)
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(ref))

    @pytest.mark.parametrize("kind", ["ei", "lcb"])
    def test_ei_lcb_close_to_ref_and_selection_identical(
            self, fitted, kind):
        """ei/lcb vs the MATERIALIZED pipeline: only FMA/fusion noise
        (<=~2e-7), and the fused top-k picks the same candidates."""
        st, best, nc, ncat, xq = fitted
        ref = acquire.acquire_scores_ref(st, xq, n_cont=nc, n_cat=ncat,
                                         **_kw(kind, best))
        got = acquire.acquire_scores(st, xq, n_cont=nc, n_cat=ncat,
                                     route=routing.INTERPRET,
                                     **_kw(kind, best))
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=2e-6)
        _, ri = acquire.acquire_topk_ref(st, xq, 7, n_cont=nc,
                                         n_cat=ncat, **_kw(kind, best))
        _, gi = acquire.acquire_topk(st, xq, 7, n_cont=nc, n_cat=ncat,
                                     route=routing.INTERPRET,
                                     **_kw(kind, best))
        np.testing.assert_array_equal(np.asarray(gi), np.asarray(ri))

    def test_utilities_orientation(self, fitted):
        """'mean' utilities are exactly -posterior-mean: descending
        utility = ascending predicted QoR."""
        st, best, nc, ncat, xq = fitted
        u = acquire.acquire_scores(st, xq, kind="mean", n_cont=nc,
                                   n_cat=ncat, route=routing.XLA)
        mu, _ = gp.predict(st, xq, nc, ncat) if nc is not None else \
            gp.predict(st, xq)
        # predict solves through the Cholesky (different staging):
        # same tolerance as the pallas_score-vs-predict tests
        np.testing.assert_allclose(np.asarray(u), -np.asarray(mu),
                                   rtol=1e-4, atol=1e-5)


class TestTopK:
    @pytest.mark.parametrize("k", [1, 5, 160])
    def test_topk_interpret_equals_xla_bitwise(self, fitted, k):
        st, best, nc, ncat, xq = fitted
        vi, ii = acquire.acquire_topk(st, xq, min(k, xq.shape[0]),
                                      kind="ei", best_y=best,
                                      n_cont=nc, n_cat=ncat,
                                      route=routing.INTERPRET)
        vx, ix = acquire.acquire_topk(st, xq, min(k, xq.shape[0]),
                                      kind="ei", best_y=best,
                                      n_cont=nc, n_cat=ncat,
                                      route=routing.XLA)
        np.testing.assert_array_equal(np.asarray(vi), np.asarray(vx))
        np.testing.assert_array_equal(np.asarray(ii), np.asarray(ix))

    def test_topk_matches_global_topk_semantics(self, fitted):
        """(vals, idx) == lax.top_k over the full utility vector —
        descending values, ties to the LOWEST flat index."""
        st, best, nc, ncat, xq = fitted
        u = acquire.acquire_scores(st, xq, kind="lcb", n_cont=nc,
                                   n_cat=ncat, route=routing.INTERPRET)
        rv, ri = jax.lax.top_k(u, 9)
        gv, gi = acquire.acquire_topk(st, xq, 9, kind="lcb",
                                      n_cont=nc, n_cat=ncat,
                                      route=routing.INTERPRET)
        np.testing.assert_array_equal(np.asarray(gv), np.asarray(rv))
        np.testing.assert_array_equal(np.asarray(gi), np.asarray(ri))

    def test_topk_ties_take_lowest_index(self):
        """Duplicated query rows produce exactly equal utilities; the
        streaming selection must resolve ties like lax.top_k (lowest
        global index), not arbitrarily per tile."""
        st, best, nc, ncat = _dense_state()
        rng = np.random.RandomState(3)
        base = rng.rand(4, 6).astype(np.float32)
        xq = jnp.asarray(np.tile(base, (8, 1)))      # each row x8
        _, idx = acquire.acquire_topk(st, xq, 8, kind="mean",
                                      route=routing.INTERPRET)
        u = acquire.acquire_scores_ref(st, xq, kind="mean")
        _, ref_idx = jax.lax.top_k(u, 8)
        np.testing.assert_array_equal(np.asarray(idx),
                                      np.asarray(ref_idx))

    @pytest.mark.slow
    def test_topk_spill_k_beyond_tile_width(self):
        """k > TILE (per-tile selection saturates at TILE winners and
        the cross-tile merge must recover the global set): bitwise
        interpret==xla and exact vs the materialized global top-k.
        Slow-marked (~15s: a 3-tile interpret-mode kernel); the k <=
        TILE merge path stays tier-1 above."""
        st, best, nc, ncat = _dense_state()
        rng = np.random.RandomState(4)
        xq = jnp.asarray(rng.rand(2500, 6), jnp.float32)
        k = 1200
        vi, ii = acquire.acquire_topk(st, xq, k, kind="lcb",
                                      route=routing.INTERPRET)
        vx, ix = acquire.acquire_topk(st, xq, k, kind="lcb",
                                      route=routing.XLA)
        np.testing.assert_array_equal(np.asarray(vi), np.asarray(vx))
        np.testing.assert_array_equal(np.asarray(ii), np.asarray(ix))
        u = acquire.acquire_scores_ref(st, xq, kind="lcb")
        rv, ri = jax.lax.top_k(u, k)
        np.testing.assert_allclose(np.asarray(vi), np.asarray(rv),
                                   rtol=1e-5, atol=2e-6)

    def test_k_validation(self):
        st, best, *_ = _dense_state()
        xq = jnp.zeros((16, 6), jnp.float32)
        with pytest.raises(ValueError):
            acquire.acquire_topk(st, xq, 0)
        with pytest.raises(ValueError):
            acquire.acquire_topk(st, xq, 17)
        with pytest.raises(ValueError):
            acquire.acquire_scores(st, xq, kind="ei")   # best_y
        with pytest.raises(ValueError):
            acquire.acquire_scores(st, xq, kind="nope")


# ---------------------------------------------------------------- batched
class TestBatchedParity:
    def test_vmapped_routes_agree(self, fitted):
        """vmap over an instance axis: both routes select the same
        candidates per instance (values allclose; batching changes
        gemm shapes, so bitwise is out of contract here)."""
        st, best, nc, ncat, xq = fitted
        stack = xq[:192].reshape(2, 96, -1)

        def tk(route):
            return jax.vmap(lambda q: acquire.acquire_topk(
                st, q, 6, kind="ei", best_y=best, n_cont=nc,
                n_cat=ncat, route=route))(stack)

        vi, ii = tk(routing.INTERPRET)
        vx, ix = tk(routing.XLA)
        np.testing.assert_array_equal(np.asarray(ii), np.asarray(ix))
        np.testing.assert_allclose(np.asarray(vi), np.asarray(vx),
                                   rtol=1e-5, atol=2e-6)

    def test_shard_mapped_equals_vmapped(self):
        """shard_map over the instance mesh wrapping the vmapped fused
        top-k is semantically invisible (same selections as plain
        vmap on one device)."""
        from jax.sharding import PartitionSpec as P

        from uptune_tpu.engine import make_instance_mesh
        from uptune_tpu.parallel.sharded import shard_map

        st, best, nc, ncat = _dense_state()
        rng = np.random.RandomState(5)
        stack = jnp.asarray(rng.rand(4, 64, 6), jnp.float32)

        def local(qs):
            return jax.vmap(lambda q: acquire.acquire_topk(
                st, q, 5, kind="lcb", route=routing.XLA))(qs)

        mesh = make_instance_mesh(2)
        sharded = jax.jit(shard_map(
            local, mesh=mesh, in_specs=(P("idev"),),
            out_specs=P("idev"), check_rep=False))
        vv, vs = jax.jit(local)(stack), sharded(stack)
        np.testing.assert_array_equal(np.asarray(vv[1]),
                                      np.asarray(vs[1]))
        np.testing.assert_allclose(np.asarray(vv[0]),
                                   np.asarray(vs[0]),
                                   rtol=1e-5, atol=2e-6)


# ---------------------------------------------------------------- schema
class TestKernelSchema:
    def test_fields_and_vmem_budget(self):
        sch = acquire.kernel_schema(1024, 16, kind="ei", k=64)
        assert sch["tile_rows"] == acquire.TILE
        assert sch["lanes"] == acquire.LANES
        assert sch["k_lanes"] == 128            # ceil(64 -> KLANES)
        assert sch["min_rows_auto"] == acquire.MIN_ROWS
        # VMEM residency stays inside a v4/v5 core's ~16 MB budget at
        # the documented worst-case protocol shape (docs/PERF.md)
        assert sch["vmem_bytes"] < 16 * 1024 * 1024
        # mean drops the kinv/w blocks
        assert acquire.kernel_schema(1024, 16, kind="mean",
                                     k=0)["vmem_bytes"] < \
            sch["vmem_bytes"]
