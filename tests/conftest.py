"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding paths are exercised without TPU hardware (the driver separately
dry-runs `__graft_entry__.dryrun_multichip`).  The guard also drops the
axon TPU-tunnel backend factory, which otherwise dials a (possibly
wedged) tunnel during backends() initialization and hangs the suite."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from uptune_tpu.utils.platform_guard import force_cpu  # noqa: E402

force_cpu(8)
