"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding paths are exercised without TPU hardware (the driver separately
dry-runs `__graft_entry__.dryrun_multichip`)."""
import os

# Force, not setdefault: the machine environment pre-sets the experimental
# axon TPU-tunnel platform, which must never be touched from the test suite.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon plugin (injected via sitecustomize on this image) registers a
# backend factory whose PJRT client dials a TPU tunnel during backends()
# initialization — even under JAX_PLATFORMS=cpu — and hangs the whole
# suite if the tunnel is wedged.  Drop the factory before any backend is
# initialized; tests are CPU-only by design.
from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_threefry_partitionable", True)
