"""Unified observability plane (uptune_tpu/obs/, docs/OBSERVABILITY.md):
ring-buffer correctness under concurrent writers, the disabled-path
zero-event guarantee, Chrome trace-event schema round-trip, the
committed example artifact, and the ISSUE 7 structural acceptance
criteria — background refit spans OVERLAP driver dispatch spans, and
store-hit tickets BYPASS the worker build lanes (asserted on recorded
events, not by eyeball)."""
import json
import os
import sys
import textwrap
import threading
import time

import pytest

import uptune_tpu
from uptune_tpu import obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    uptune_tpu.__file__)))
ENV = {"PYTHONPATH": REPO}


@pytest.fixture(autouse=True)
def obs_clean():
    obs.reset()
    yield
    obs.reset()


# ------------------------------------------------------------- core
class TestCore:
    def test_disabled_path_records_nothing(self):
        """The zero-event assertion: with tracing off, every API entry
        is inert — and span() hands back ONE shared singleton (no
        allocation on the hot path)."""
        assert not obs.enabled()
        s1 = obs.span("a", k=1)
        s2 = obs.device_span("b")
        assert s1 is s2, "disabled span must be the shared no-op"
        with s1:
            pass
        obs.event("e", x=2)
        obs.complete_span("c", t0=0.0, dur=1.0, track="worker-0")
        obs.count("n")
        obs.gauge("g", 3)
        obs.observe("h", 4.0)
        snap = obs.snapshot()
        assert snap["events"] == []
        m = obs.metrics_snapshot()
        assert m["counters"] == {} and m["gauges"] == {} \
            and m["hists"] == {}

    def test_span_event_metrics_roundtrip(self):
        obs.enable()
        with obs.span("ticket.propose", arm="de") as sp:
            sp.set(rows=3)
        obs.event("ticket.open", gid=7)
        obs.complete_span("pool.build", t0=time.perf_counter(),
                          dur=0.5, track="worker-1", gid=7)
        obs.count("store.hits", 2)
        obs.gauge("prefetch.depth", 5)
        obs.observe("store.serve_ms", 0.7)
        evs = obs.snapshot()["events"]
        by = {e["name"]: e for e in evs}
        assert by["ticket.propose"]["dur"] >= 0
        assert by["ticket.propose"]["attrs"] == {"arm": "de", "rows": 3}
        assert by["ticket.open"]["dur"] is None
        assert by["pool.build"]["track"] == "worker-1"
        m = obs.metrics_snapshot()
        assert m["counters"]["store.hits"] == 2
        assert m["gauges"]["prefetch.depth"] == 5
        assert m["hists"]["store.serve_ms"]["count"] == 1

    def test_ring_wraps_and_counts_drops(self):
        obs.enable(capacity=8)
        for i in range(20):
            obs.event("e", i=i)
        snap = obs.snapshot()
        assert len(snap["events"]) == 8
        # oldest overwritten: only the last 8 survive, in order
        assert [e["attrs"]["i"] for e in snap["events"]] == \
            list(range(12, 20))
        assert sum(snap["dropped"].values()) == 12

    def test_concurrent_writers_lose_nothing(self):
        """Driver + refit-thread + pool shape: N threads record into
        their own rings concurrently; every event survives intact, in
        per-thread order, with no cross-thread interleaving damage."""
        obs.enable(capacity=4096)
        n_threads, per = 4, 1000
        start = threading.Barrier(n_threads + 1)

        def writer(tid):
            start.wait()
            for i in range(per):
                obs.event("w", tid=tid, i=i)

        ts = [threading.Thread(target=writer, args=(k,),
                               name=f"obs-writer-{k}")
              for k in range(n_threads)]
        for t in ts:
            t.start()
        start.wait()
        for i in range(per):
            obs.event("w", tid=-1, i=i)
        for t in ts:
            t.join()
        snap = obs.snapshot()
        assert sum(snap["dropped"].values()) == 0
        seen = {}
        for e in snap["events"]:
            a = e["attrs"]
            seen.setdefault(a["tid"], []).append(a["i"])
        assert set(seen) == {-1, 0, 1, 2, 3}
        for tid, idxs in seen.items():
            assert idxs == list(range(per)), \
                f"thread {tid} lost or reordered events"
        # per-thread timestamps are monotonic (each ring is
        # single-writer, so order == record order)
        by_track = {}
        for e in snap["events"]:
            by_track.setdefault(e["track"], []).append(e["ts"])
        for track, tss in by_track.items():
            assert tss == sorted(tss), f"{track} timestamps regressed"

    def test_enable_cycle_isolates_runs(self):
        """A thread surviving an enable() cycle (the refit worker
        shape) must re-register: its old ring is never exported, its
        new records are."""
        obs.enable()
        done1 = threading.Event()
        go2 = threading.Event()
        done2 = threading.Event()

        def worker():
            obs.event("old", run=1)
            done1.set()
            go2.wait(5)
            obs.event("new", run=2)
            done2.set()

        t = threading.Thread(target=worker, name="survivor")
        t.start()
        done1.wait(5)
        obs.enable()        # second run: clears rings, bumps epoch
        go2.set()
        done2.wait(5)
        t.join(5)
        evs = obs.snapshot()["events"]
        assert [e["name"] for e in evs] == ["new"]


# ------------------------------------------------------------ export
class TestExport:
    def _populate(self):
        obs.enable()
        with obs.span("ticket.propose", arm="de"):
            pass
        obs.event("ticket.finalize", step=1)
        obs.complete_span("pool.build", t0=time.perf_counter(),
                          dur=0.25, track="worker-0", gid=3)

        def bg():
            with obs.span("surrogate.fit", background=True):
                pass

        t = threading.Thread(target=bg, name="ut-surrogate-refit_0")
        t.start()
        t.join()
        obs.count("store.hits")
        obs.observe("store.serve_ms", 0.8)

    def test_trace_schema_roundtrip(self, tmp_path):
        self._populate()
        path = str(tmp_path / "trace.json")
        obs.write_trace(path, extra={"note": "test"})
        obs.write_metrics_jsonl(path + ".metrics.jsonl")
        with open(path) as f:
            doc = json.load(f)          # the round trip
        obs.validate_trace(doc)
        lanes = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert {"MainThread", "worker-0",
                "ut-surrogate-refit_0"} <= lanes
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        build = next(e for e in xs if e["name"] == "pool.build")
        assert abs(build["dur"] - 250_000) < 1_000   # µs
        assert doc["otherData"]["note"] == "test"
        assert doc["otherData"]["metrics"]["counters"][
            "store.hits"] == 1
        row = json.loads(
            open(path + ".metrics.jsonl").readline())
        assert row["counters"]["store.hits"] == 1
        assert row["hists"]["store.serve_ms"]["count"] == 1

    def test_validate_rejects_malformed(self):
        with pytest.raises(ValueError):
            obs.validate_trace({"events": []})
        with pytest.raises(ValueError):
            obs.validate_trace({"traceEvents": [
                {"ph": "X", "pid": 1, "tid": 1, "name": "a",
                 "ts": 0.0}]})        # X without dur
        with pytest.raises(ValueError):
            obs.validate_trace({"traceEvents": [
                {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
                 "args": {"name": "t"}},
                {"ph": "i", "pid": 1, "tid": 2, "name": "a",
                 "ts": 0.0, "s": "t"}]})   # tid 2 unnamed

    def test_committed_example_trace_validates(self):
        """The checked-in Perfetto artifact (bench.py --obs phase 3)
        must satisfy the schema contract and actually show the async
        shape: a refit-worker lane distinct from the driver lane, with
        fit spans on it."""
        path = os.path.join(REPO, "exp_archives",
                            "obs_trace_example.json")
        with open(path) as f:
            doc = json.load(f)
        obs.validate_trace(doc)
        name_of = {e["tid"]: e["args"]["name"]
                   for e in doc["traceEvents"]
                   if e["ph"] == "M" and e["name"] == "thread_name"}
        assert len(set(name_of.values())) >= 2
        fit_lanes = {name_of[e["tid"]] for e in doc["traceEvents"]
                     if e["ph"] == "X" and e["name"] == "surrogate.fit"}
        assert any(l != "MainThread" for l in fit_lanes)

    def test_text_summary_mentions_spans_and_drops(self):
        self._populate()
        s = obs.text_summary()
        assert "pool.build" in s and "store.hits" in s


# -------------------------------------------------- structural gates
def _overlaps(a, b):
    return a["ts"] < b["ts"] + b["dur"] and b["ts"] < a["ts"] + a["dur"]


class TestStructural:
    def test_refit_spans_overlap_dispatch(self):
        """ISSUE 7 acceptance: a traced async tune must SHOW the
        overlap the async surrogate plane claims — a background
        surrogate.fit span on the refit-worker lane intersecting
        driver-lane ticket spans in time."""
        from uptune_tpu.driver import Tuner
        from uptune_tpu.workloads import (rosenbrock_objective,
                                          rosenbrock_space)
        obs.enable()
        obj = rosenbrock_objective(2)
        tuner = Tuner(rosenbrock_space(2, -2.048, 2.048), None, seed=0,
                      surrogate="gp",
                      surrogate_opts={"min_points": 8,
                                      "refit_interval": 8,
                                      "max_points": 64,
                                      "async_refit": True})
        done = 0
        while done < 48:
            for tr in tuner.ask(min_trials=1):
                tuner.tell(tr, float(obj([tr.config])[0]))
                done += 1
        tuner.close()   # drains the background worker
        evs = obs.snapshot()["events"]
        fits = [e for e in evs if e["name"] == "surrogate.fit"
                and (e["attrs"] or {}).get("background")]
        assert fits, "no background fit ran — protocol broken"
        assert all(e["track"] != "MainThread" for e in fits)
        driver = [e for e in evs
                  if e["track"] == "MainThread"
                  and e["dur"] is not None
                  and e["name"].startswith("ticket.")]
        assert driver
        assert any(_overlaps(f, d) for f in fits for d in driver), \
            "refit never overlapped driver dispatch — the async " \
            "plane's whole claim"

    def test_store_hits_bypass_build_lane(self, tmp_path):
        """ISSUE 7 acceptance: store-hit tickets must never appear on
        a worker build lane.  Run 1 populates the store (untraced);
        run 2 (traced, larger budget) serves the replayed prefix from
        the store and builds only novel configs — the recorded events
        prove the bypass: serve gids and build gids are disjoint,
        both non-empty."""
        from uptune_tpu.exec.controller import ProgramTuner
        prog = tmp_path / "prog.py"
        prog.write_text(textwrap.dedent("""
            import uptune_tpu as ut
            x = ut.tune(50, (0, 100), name="x")
            y = ut.tune(50, (0, 100), name="y")
            ut.target(float((x - 37) ** 2 + (y - 11) ** 2), "min")
        """))

        def mk(limit):
            return ProgramTuner([sys.executable, str(prog)],
                                str(tmp_path), parallel=1, prefetch=0,
                                test_limit=limit, seed=0, env=ENV,
                                runtime_limit=30.0)

        mk(5).run()
        obs.enable()
        pt2 = mk(10)
        pt2.run()
        assert pt2.store_hits > 0
        assert pt2.pool.launched > 0
        evs = obs.snapshot()["events"]
        served = {(e["attrs"] or {}).get("gid") for e in evs
                  if e["name"] == "store.serve_hit"}
        built = {(e["attrs"] or {}).get("gid") for e in evs
                 if e["name"] == "pool.build"}
        assert served and built
        assert all(e["track"] == "store" for e in evs
                   if e["name"] == "store.serve_hit")
        assert not (served & built), \
            f"gids {served & built} were served AND built"
        assert len(served) == pt2.store_hits
        assert len(built) == pt2.pool.launched


class TestGuardMerge:
    def test_retrace_events_land_on_timeline(self):
        """The TraceGuard report is part of the obs export now: every
        jit trace inside a guard is an instant event, and excess ones
        are flagged on the event itself."""
        import jax
        import jax.numpy as jnp

        from uptune_tpu.analysis.trace_guard import TraceGuard
        obs.enable()
        with TraceGuard(limit=1, name="t"):
            @jax.jit
            def f(x):
                return x + 1

            f(jnp.ones(2))
            f(jnp.ones(3))   # retrace (new shape) -> excess
        evs = [e for e in obs.snapshot()["events"]
               if e["name"] == "jit.trace"]
        assert len(evs) == 2
        assert [e["attrs"]["excess"] for e in evs] == [False, True]
        assert obs.metrics_snapshot()["counters"]["jit.traces"] == 2
