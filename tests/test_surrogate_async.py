"""Async surrogate plane (ISSUE 5): versioned snapshot protocol,
background refit, incremental rank-1 Cholesky extension, sync/async
parity at matched watermarks, mid-refit abandon + resume replay, and
strict trace-guard cleanliness of the incremental path.

Sizes are deliberately tiny (hyper_fit=False where the sweep is not the
subject) — the suite budget is tight (ROADMAP tier-1)."""
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from uptune_tpu.driver import Tuner  # noqa: E402
from uptune_tpu.surrogate import gp  # noqa: E402
from uptune_tpu.surrogate.manager import SurrogateManager  # noqa: E402
from uptune_tpu.workloads import (rosenbrock_device,  # noqa: E402
                                  rosenbrock_objective, rosenbrock_space)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {"JAX_PLATFORMS": "cpu"}

SOPTS = {"min_points": 16, "refit_interval": 16, "max_points": 64,
         "propose_batch": 8, "propose_every": 2, "hyper_fit": False}


def _space():
    return rosenbrock_space(2, -3.0, 3.0)


def _feed(m, space, n, seed):
    cands = space.random(jax.random.PRNGKey(seed), n)
    feats = np.asarray(space.features(cands))
    qor = np.asarray(rosenbrock_device(space.decode_scalars(cands.u)))
    m.observe(feats, qor)
    return feats, qor


# ------------------------------------------------------------- gp.extend
class TestExtend:
    def _fitted(self, n, bucket, with_kinv, ls=0.4, noise=1e-2):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.rand(n + 6, 5), jnp.float32)
        y = jnp.asarray(rng.randn(n + 6), jnp.float32)
        x0, y0, m0 = gp.pad_train(x[:n], y[:n], bucket)
        st = gp.fit(x0, y0, lengthscale=ls, noise=noise, mask=m0)
        if with_kinv:
            st = gp.precompute_kinv(st)
        return st, x, y

    @pytest.mark.parametrize("with_kinv", [False, True])
    def test_extend_matches_full_refit_at_fixed_hypers(self, with_kinv):
        """Rank-1 extension is EXACT conditioning: predictions (and the
        premasked K^-1) match a from-scratch fit on the extended set
        with the same hyperparameters and standardization moments."""
        st, x, y = self._fitted(20, 32, with_kinv)
        mean, std = st.y_mean, st.y_std
        for i in range(20, 24):
            st = gp.extend(st, x[i], y[i], jnp.int32(i))
        # reference: full factorization over 24 rows, with the 20-row
        # standardization frozen (what extend keeps by design)
        x1, y1, m1 = gp.pad_train(x[:24], y[:24], 32)
        yn = (y1 - mean) / std * m1
        k = gp._mask_adjust(gp._matern52(x1, x1, jnp.float32(0.4)),
                            jnp.float32(1e-2), m1)
        chol = jnp.linalg.cholesky(k)
        alpha = jax.scipy.linalg.cho_solve((chol, True), yn)
        ref = gp.GPState(x1, alpha, chol, mean, std, jnp.float32(0.4),
                         jnp.float32(1e-2), m1, 1.0)
        xq = jnp.asarray(np.random.RandomState(1).rand(16, 5),
                         jnp.float32)
        mu1, sd1 = gp.predict(st, xq)
        mu2, sd2 = gp.predict(ref, xq)
        np.testing.assert_allclose(np.asarray(mu1), np.asarray(mu2),
                                   atol=1e-4)
        np.testing.assert_allclose(np.asarray(sd1), np.asarray(sd2),
                                   atol=1e-4)
        if with_kinv:
            ref = gp.precompute_kinv(ref)
            np.testing.assert_allclose(np.asarray(st.kinv),
                                       np.asarray(ref.kinv), atol=1e-3)

    def test_extend_leaves_other_rows_untouched(self):
        """Padded-row decoupling makes the update local: every factor
        row except `slot` is bit-identical after an extension."""
        st, x, y = self._fitted(20, 32, False)
        st2 = gp.extend(st, x[20], y[20], jnp.int32(20))
        before = np.asarray(st.chol)
        after = np.asarray(st2.chol)
        rows = np.ones(32, bool)
        rows[20] = False
        np.testing.assert_array_equal(before[rows], after[rows])
        assert float(st2.mask[20]) == 1.0 and float(st.mask[20]) == 0.0


# ------------------------------------------------- manager snapshot plane
class TestSnapshotPlane:
    def test_incremental_keeps_watermark_current(self):
        space = _space()
        m = SurrogateManager(space, "gp", **SOPTS)
        _feed(m, space, 32, 0)
        assert m.maybe_refit()          # sync full fit published
        v = m.snapshot_version
        assert v >= 1 and m.refit_lag_rows == 0
        _feed(m, space, 5, 1)           # below cadence
        assert not m.maybe_refit()      # no FULL fit ...
        assert m.incr_updates == 5      # ... but rows folded in
        assert m.refit_lag_rows == 0
        assert m.snapshot_version == v + 1

    def test_async_submit_publish_poll(self):
        space = _space()
        m = SurrogateManager(space, "gp", async_refit=True, **SOPTS)
        _feed(m, space, 32, 0)
        assert not m.maybe_refit()      # submitted, not yet published
        assert m.drain(60.0)
        assert m.fitted and m.refits == 1 and m.t_refit_bg_total > 0
        # blocking accumulators untouched: nothing ran on this thread
        assert m.t_refit_total == 0.0

    def test_concurrent_reads_never_see_half_published_snapshot(self):
        """Hook-injected slow fit: while the background worker is held
        mid-fit, scoring reads keep returning the COMPLETE previous
        snapshot (same version, consistent threshold); the new version
        appears only after the worker finishes."""
        space = _space()
        m = SurrogateManager(space, "gp", async_refit=True, **SOPTS)
        _feed(m, space, 32, 0)
        assert m.maybe_refit() is False
        assert m.drain(60.0) and m.fitted
        v1 = m.snapshot_version
        probe = space.random(jax.random.PRNGKey(9), 16)

        gate = threading.Event()
        orig = dict(m._fit_jit)

        def gated(fn):
            def slow_fit(*a):
                gate.wait(30.0)
                return fn(*a)
            return slow_fit

        m._fit_jit = {k: gated(v) for k, v in orig.items()}
        _feed(m, space, 20, 1)          # past the cadence
        m.maybe_refit()                 # submits the gated fit
        assert m._refit_future is not None
        seen = set()
        for _ in range(5):
            snap = m._snap
            seen.add(snap.version)
            assert snap.threshold is not None
            assert m.keep_mask(probe) is not None
        # increments may have bumped the version, but nothing from the
        # gated full fit leaked out
        assert m.refits == 1 and not m._refit_future.done()
        gate.set()
        assert m.drain(60.0)
        assert m.refits == 2 and m.snapshot_version > max(seen) >= v1
        m._fit_jit = orig

    def test_background_failure_warns_and_retries(self):
        space = _space()
        m = SurrogateManager(space, "gp", async_refit=True, **SOPTS)
        _feed(m, space, 32, 0)
        orig = dict(m._fit_jit)

        def boom(*a):
            raise RuntimeError("boom")

        m._fit_jit = {k: boom for k in orig}
        m.maybe_refit()
        with pytest.warns(RuntimeWarning, match="background surrogate "
                                               "refit failed"):
            m.drain(60.0)
        assert not m.fitted
        m._fit_jit = orig
        assert not m.maybe_refit()      # resubmits (cadence re-armed)
        assert m.drain(60.0) and m.fitted

    def test_force_refit_is_sync_under_async(self):
        """PR 4 warm-start semantics: preload/warm_start must come back
        with the model READY, even with the async plane on."""
        space = _space()
        m = SurrogateManager(space, "gp", async_refit=True, **SOPTS)
        cands = space.random(jax.random.PRNGKey(0), 24)
        feats = np.asarray(space.features(cands))
        assert m.warm_start(feats, np.arange(24, dtype=np.float32))
        assert m.fitted and m.t_refit_last > 0


# -------------------------------------------------------- driver parity
class TestDriverParity:
    def _run(self, async_on, steps=12, drain=True):
        space = _space()
        t = Tuner(space, rosenbrock_objective(2), seed=0,
                  surrogate="gp",
                  surrogate_opts={**SOPTS, "async_refit": async_on})
        seq = []
        for _ in range(steps):
            st = t.step()
            if async_on and drain \
                    and t.surrogate._refit_future is not None:
                # the watermark barrier: publication lands exactly
                # where the sync fit would have, before the next
                # acquisition reads the snapshot.  Only when a fit is
                # actually in flight — an unconditional extra
                # maybe_refit() would fold a second capped extension
                # batch this tick, which the sync run doesn't do
                assert t.surrogate.drain(120.0)
                t.surrogate.maybe_refit()
            seq.append((st.technique, st.batch, st.evaluated,
                        round(st.best_qor, 9)))
        res = t.result()
        lag = t.surrogate.refit_lag_rows
        t.close()
        return seq, res, lag

    def test_async_equals_sync_at_matched_watermarks(self):
        s_off, r_off, lag_off = self._run(False)
        s_on, r_on, lag_on = self._run(True)
        assert s_off == s_on
        assert r_off.trace == r_on.trace
        assert r_off.best_qor == r_on.best_qor
        # identical watermarks too: the same rows are conditioned in
        # at the same points in both modes
        assert lag_on == lag_off
        # the async run never blocked the tell path on a full fit
        assert r_on.t_refit < r_off.t_refit or r_off.t_refit == 0.0

    def test_stepstats_carry_surrogate_fields(self):
        space = _space()
        t = Tuner(space, rosenbrock_objective(2), seed=0,
                  surrogate="gp", surrogate_opts=dict(SOPTS))
        seen_version = 0
        for _ in range(8):
            st = t.step()
            assert st.refit_lag_rows >= 0 and st.t_refit >= 0.0
            seen_version = max(seen_version, st.snapshot_version)
        res = t.result()
        t.close()
        assert seen_version >= 1          # a fit happened and was seen
        assert res.t_refit > 0.0          # sync mode blocked on it


# ------------------------------------------------- resume / kill safety
class TestResumeSafety:
    def test_midrefit_abandon_then_resume_replays_exactly(self, tmp_path):
        """A tuner abandoned with a background refit still in flight
        (the mid-refit kill) must leave an archive that replays
        exactly: the refit plane never touches archive/history rows."""
        space = _space()
        arch = str(tmp_path / "a.jsonl")
        t = Tuner(space, rosenbrock_objective(2), seed=0, archive=arch,
                  surrogate="gp",
                  surrogate_opts={**SOPTS, "async_refit": True})
        for _ in range(6):
            t.step()
        # a refit is (or was) in flight; simulate the kill: flush the
        # archive (the OS would have the written rows) and DROP the
        # tuner without close()/drain()
        t._flush_archive()
        evals, best = t.evals, t.result().best_qor
        del t

        t2 = Tuner(space, rosenbrock_objective(2), seed=0, archive=arch,
                   resume=True, surrogate="gp",
                   surrogate_opts={**SOPTS, "async_refit": True})
        assert t2.evals == evals
        assert t2.result().best_qor == pytest.approx(best)
        # resume routed the ingest refit through the async plane: the
        # call returned without blocking, and the fit lands in the
        # background (drain proves it completes)
        assert t2.surrogate.drain(120.0)
        assert t2.surrogate.fitted
        t2.close()

    def test_preload_refits_synchronously_with_async_plane(self):
        """PR 4 store warm-start: preload(refit=True) must return with
        the surrogate fitted even when async_refit is on."""
        space = _space()
        t = Tuner(space, rosenbrock_objective(2), seed=0,
                  surrogate="gp",
                  surrogate_opts={**SOPTS, "async_refit": True})
        rng = np.random.RandomState(0)
        u = rng.rand(24, space.n_scalar).astype(np.float32)
        qor = rng.rand(24).astype(np.float32)
        assert t.preload(u, [], qor) == 24
        assert t.surrogate.fitted       # no drain needed: forced sync
        t.close()


# ----------------------------------------------------------- trace guard
class TestTraceGuard:
    def test_incremental_updates_add_no_retrace_churn(self):
        """Strict guard over full fits at TWO buckets plus incremental
        extensions at both: the per-bucket extension wrappers (built
        up-front in __init__) each trace exactly once, and no wrapper
        is rebuilt after tracing."""
        from uptune_tpu.analysis.trace_guard import TraceGuard
        space = _space()
        with TraceGuard(strict=True, name="surrogate-async") as tg:
            m = SurrogateManager(space, "gp", min_points=8,
                                 refit_interval=8, max_points=64,
                                 hyper_fit=False)
            _feed(m, space, 8, 0)
            assert m.maybe_refit()           # bucket 16 (8 + headroom)
            _feed(m, space, 3, 1)
            m.maybe_refit()                  # extend @ bucket 16
            _feed(m, space, 8, 2)
            assert m.maybe_refit()           # bucket 32
            _feed(m, space, 3, 3)
            m.maybe_refit()                  # extend @ bucket 32
        assert m.incr_updates >= 6
        assert not tg.excess(), tg.report()


# ------------------------------------------------------------ bench smoke
class TestBenchSmoke:
    @pytest.mark.slow
    def test_surrogate_bench_quick_smoke(self):
        """`bench.py --surrogate --quick` must keep producing its
        evidence JSON: refit windows observed in both modes, the async
        tell path cheaper inside them, and search quality sane.

        Slow-marked (ISSUE 7 suite-budget reclaim: ~27s, the single
        most expensive tier-1 test): the async plane's FUNCTIONALITY
        keeps dense tier-1 coverage right here (driver sync/async
        parity, snapshot atomicity, extend exactness, resume safety),
        and the bench-script seam keeps tier-1 smokes via `--cache`
        and `--multi --quick` — this 3-run latency protocol adds
        wiring coverage only."""
        env = {**os.environ, **ENV}
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--surrogate", "--quick"], capture_output=True, text=True,
            env=env, cwd=REPO, timeout=540)
        assert r.returncode == 0, r.stdout + r.stderr
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["metric"] == "surrogate_async_refit_window_p95_speedup"
        assert out["sync"]["warm_refit_windows"] >= 3
        assert out["async"]["warm_refit_windows"] >= 3
        assert out["value"] is not None and out["value"] > 1.0
        assert out["refit_overlap_fraction"] > 0.5
        assert os.path.exists(
            os.path.join(REPO, "BENCH_SURROGATE.quick.json"))
