"""Fleet telemetry (ISSUE 14, docs/OBSERVABILITY.md "Fleet
telemetry"): the serve/wire.py service kernel the session server was
rebased onto, the TelemetryShipper's bounded never-blocking queue +
reconnect/backoff, the hub's rollup semantics (counter-sum exactness,
gauge last-writes, labeled-approximate percentiles), ack-before-reply
timeline durability under K concurrent shippers with kill -9-style
disconnects, timeline rotation + restart replay, the `ut top`
multi-metrics/--fleet satellites, the flight-recorder rotate-depth
satellite, the serve health `limit=` satellite, and `ut report`'s
multi-source rendering.

Budget note: everything here is socket/thread-level and sub-second —
no engine, no compiles; the real multi-process fleet e2e lives in
`bench.py --fleet` (its --quick smoke is the tier-1 subprocess
check)."""
import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

import uptune_tpu
from uptune_tpu import obs
from uptune_tpu.obs import flight, ship, top
from uptune_tpu.obs import hub as hub_mod
from uptune_tpu.obs.hub import TelemetryHub, fleet_rollup
from uptune_tpu.serve.wire import RequestError, WireServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    uptune_tpu.__file__)))


@pytest.fixture(autouse=True)
def obs_clean():
    obs.reset()
    yield
    ship.stop()
    obs.reset()


def _wire_request(port, payload, keep=False):
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    f = s.makefile("rwb")
    f.write(json.dumps(payload).encode() + b"\n")
    f.flush()
    resp = json.loads(f.readline())
    if keep:
        return resp, (s, f)
    f.close()
    s.close()
    return resp


# ------------------------------------------------------- wire kernel
class _EchoServer(WireServer):
    WIRE_NAME = "ut-test-echo"

    def __init__(self):
        super().__init__("127.0.0.1", 0)
        self.reaped = []

    def _op_echo(self, req):
        return {"echo": req.get("value")}

    def _op_boom(self, req):
        raise RuntimeError("kaboom")

    def _op_bad(self, req):
        raise RequestError("bad field")

    _OPS = {"echo": _op_echo, "boom": _op_boom, "bad": _op_bad}

    def _conn_opened(self, conn, addr):
        return {"seen": 0}

    def _on_response(self, state, req, resp):
        state["seen"] += 1

    def _conn_closed(self, state):
        self.reaped.append(state["seen"])


class TestWireKernel:
    def test_dispatch_and_error_walls(self):
        srv = _EchoServer()
        out = srv.handle({"op": "echo", "value": 7, "id": "x"})
        assert out == {"ok": True, "echo": 7, "id": "x"}
        out = srv.handle({"op": "bad"})
        assert not out["ok"] and out["error"] == "bad field"
        out = srv.handle({"op": "boom"})
        assert not out["ok"] and "internal" in out["error"]
        out = srv.handle({"op": "nope"})
        assert not out["ok"] and "unknown op" in out["error"]
        out = srv.handle({"op": ["not", "hashable"]})
        assert not out["ok"] and "unknown op" in out["error"]
        assert not srv.handle(["not a dict"])["ok"]

    def test_tcp_loop_and_conn_reaping_hooks(self):
        with _EchoServer() as srv:
            resp, (s, f) = _wire_request(srv.port,
                                         {"op": "echo", "value": 1},
                                         keep=True)
            assert resp["ok"] and resp["echo"] == 1
            # a bad-JSON line is answered, not fatal, and never
            # reaches the response hook
            f.write(b"this is not json\n")
            f.flush()
            assert not json.loads(f.readline())["ok"]
            f.write(json.dumps({"op": "echo", "value": 2}).encode()
                    + b"\n")
            f.flush()
            assert json.loads(f.readline())["echo"] == 2
            f.close()       # makefile holds its own socket ref
            s.close()
            deadline = time.time() + 5
            while not srv.reaped and time.time() < deadline:
                time.sleep(0.01)
        assert srv.reaped == [2]    # 2 parsed requests, 1 bad line

    def test_session_server_is_a_wire_server(self):
        from uptune_tpu.serve.server import SessionServer
        assert issubclass(SessionServer, WireServer)
        srv = SessionServer(port=0)     # not started: no sockets
        assert srv.handle({"op": "ping"})["ok"]
        assert srv.WIRE_NAME == "ut-serve"


# ------------------------------------------------- shipper mechanics
class TestShipper:
    def test_bounded_queue_drops_oldest_with_accounting(self):
        obs.enable()
        sh = ship.TelemetryShipper("127.0.0.1:1", role="t",
                                   queue_max=4)
        for i in range(10):
            assert sh.offer("journal", {"i": i})
        assert sh.dropped == 6
        with sh._qlock:
            kept = [item["row"]["i"] for item in sh._q]
        assert kept == [6, 7, 8, 9]     # oldest shed, newest kept
        from uptune_tpu.obs import metrics as metrics_mod
        assert metrics_mod.counter_value("ship.dropped") == 6

    def test_offer_refused_after_stop(self):
        sh = ship.TelemetryShipper("127.0.0.1:1", role="t")
        sh._stop.set()
        assert not sh.offer("journal", {})

    def test_reconnect_with_backoff_flaky_listener(self, tmp_path):
        """Hook-gated flaky hub: refuses the first 2 connections
        (hello never answered), then behaves.  The shipper must
        retry with backoff and deliver everything it queued —
        nothing acked is lost, and the early failures are counted."""
        refusals = {"left": 2}
        gate_lock = threading.Lock()

        class FlakyHub(TelemetryHub):
            def _op_hello(self, req):
                with gate_lock:
                    if refusals["left"] > 0:
                        refusals["left"] -= 1
                        raise RequestError("not yet")
                return TelemetryHub._op_hello(self, req)

            # the dispatch table binds functions, not names — a
            # subclass overriding an op must re-map it
            _OPS = {**TelemetryHub._OPS, "hello": _op_hello}

        with FlakyHub(port=0, timeline=str(tmp_path / "tl.jsonl")) \
                as hub:
            obs.enable()
            sh = ship.TelemetryShipper(
                f"127.0.0.1:{hub.port}", role="flaky-test",
                interval=0.05, backoff_base=0.02, backoff_max=0.1)
            sh.start()
            obs.count("test.counter", 5)
            deadline = time.time() + 10
            while sh.acked == 0 and time.time() < deadline:
                time.sleep(0.02)
            sh.stop()
            assert refusals["left"] == 0
            assert sh.failures >= 2         # the gated refusals
            assert sh.connects >= 1
            assert sh.acked > 0
            src = next(iter(hub._sources.values()))
            assert src.last_window["counters"]["test.counter"] == 5

    def test_exactness_contract_vs_flight_recorder(self, tmp_path):
        """The unit half of BENCH_FLEET's exactness contract: after a
        clean stop, the hub's last window for a source equals the
        source's own final flight-recorder row, counter for
        counter."""
        mpath = str(tmp_path / "m.jsonl")
        with TelemetryHub(port=0,
                          timeline=str(tmp_path / "tl.jsonl")) as hub:
            obs.enable()
            rec = flight.start(mpath, interval=0.05)
            sh = ship.TelemetryShipper(f"127.0.0.1:{hub.port}",
                                       role="exact", interval=0.05)
            sh.start()
            for i in range(137):
                obs.count("driver.asks")
                if i % 3 == 0:
                    obs.observe("serve.ask_ms", 0.1 * i)
            obs.gauge("pool.busy", 2)
            time.sleep(0.15)
            sh.stop()
            rec.stop()
            src = next(iter(hub._sources.values()))
            hub_counters = src.last_window["counters"]
            final = [json.loads(line)
                     for line in open(mpath)][-1]
            assert final.get("final") is True
            assert hub_counters == final["counters"]
            assert src.last_window["gauges"] == final["gauges"]
            assert src.final_seen

    def test_final_window_cut_when_stop_lands_in_backoff(self):
        """stop() arriving while the loop sits in its reconnect
        backoff must still cut a final=true terminal window (it ends
        up queued for the unreachable hub, but a hub that came back
        during the last drain would receive it)."""
        obs.enable()
        sh = ship.TelemetryShipper("127.0.0.1:1", role="t",
                                   interval=0.02, backoff_base=5.0,
                                   connect_timeout=0.2)
        sh.start()
        deadline = time.time() + 10
        while sh.failures == 0 and time.time() < deadline:
            time.sleep(0.01)
        sh.stop(timeout=10)     # lands inside the 5 s backoff wait
        with sh._qlock:
            items = list(sh._q) + (sh._pending or [])
        finals = [i for i in items
                  if i["kind"] == "window" and i["row"].get("final")]
        assert finals, "terminal window lost its final flag"

    def test_env_wiring_role_suffix(self, tmp_path):
        with TelemetryHub(port=0, timeline=None) as hub:
            env = {"UT_TELEMETRY": f"127.0.0.1:{hub.port}",
                   "UT_PROCESS_ID": "3"}
            sh = ship.maybe_ship_from_env(role="ut-driver", env=env)
            assert sh is not None
            assert sh.source["role"] == "ut-driver.h3"
            sh.stop()
            assert ship.maybe_ship_from_env(env={}) is None
            assert ship.maybe_ship_from_env(
                env={"UT_TELEMETRY": "off"}) is None


# ------------------------------------------------------- hub rollup
class TestFleetRollup:
    def test_counter_sums_exact_gauges_last_write(self):
        rows = [
            ("a", {"t": 10.0, "dt": 1.0,
                   "counters": {"x": 5, "y": 1.5},
                   "deltas": {"x": 2}, "gauges": {"g": 1}, "hists": {}}),
            ("b", {"t": 11.0, "dt": 0.5,
                   "counters": {"x": 7},
                   "deltas": {"x": 3}, "gauges": {"g": 9}, "hists": {}}),
        ]
        roll = fleet_rollup(rows)
        assert roll["counters"] == {"x": 12, "y": 1.5}
        assert roll["deltas"] == {"x": 5}
        assert roll["gauges"]["g"] == 9     # newest t wins
        assert roll["dt"] == 1.0
        assert roll["per_source"] == ["a", "b"]

    def test_hist_percentiles_weighted_and_labeled_approx(self):
        rows = [
            ("a", {"t": 1, "dt": 1, "counters": {}, "deltas": {},
                   "gauges": {},
                   "hists": {"h": {"count": 10, "sum": 10.0,
                                   "window_count": 10,
                                   "window_sum": 10.0,
                                   "p50": 1.0, "p95": 2.0}}}),
            ("b", {"t": 1, "dt": 1, "counters": {}, "deltas": {},
                   "gauges": {},
                   "hists": {"h": {"count": 30, "sum": 90.0,
                                   "window_count": 30,
                                   "window_sum": 90.0,
                                   "p50": 3.0, "p95": 4.0}}}),
        ]
        h = fleet_rollup(rows)["hists"]["h"]
        assert h["count"] == 40 and h["sum"] == 100.0
        assert h["window_count"] == 40
        assert h["p50"] == pytest.approx(2.5)   # (10*1 + 30*3) / 40
        assert h["p95"] == pytest.approx(3.5)
        assert h["approx"] is True


def _ship_req(role, rows, host="hx", pid=1):
    return {"op": "ship",
            "source": {"host": host, "pid": pid, "role": role},
            "rows": rows}


def _win(t, counters, final=False, **kw):
    row = {"t": t, "dt": 1.0, "counters": counters, "deltas": {},
           "gauges": {}, "hists": {}, **kw}
    if final:
        row["final"] = True
    return {"kind": "window", "row": row}


class TestHubOps:
    def test_ship_metrics_sources_roundtrip(self, tmp_path):
        hub = TelemetryHub(port=0, timeline=str(tmp_path / "t.jsonl"))
        assert hub.handle(_ship_req("r1", [
            _win(1.0, {"driver.asks": 10}),
            {"kind": "journal", "row": {"ev": "step", "t": 0.1}},
        ]))["acked"] == 2
        assert hub.handle(_ship_req("r2", [
            _win(2.0, {"driver.asks": 32}), ], pid=2))["acked"] == 1
        m = hub.handle({"op": "metrics"})
        assert m["sources"] == 2
        assert m["metrics"]["counters"]["driver.asks"] == 42
        rows = hub.handle({"op": "sources"})["rows"]
        assert [r["role"] for r in rows] == ["r1", "r2"]
        r1 = next(r for r in rows if r["role"] == "r1")
        assert r1["journal_rows"] == 1 and r1["windows"] == 1
        hub.stop()

    def test_timeline_durable_before_ack(self, tmp_path):
        """Ack-implies-durable: when handle() returns ok, the rows are
        already flushed to the fleet timeline."""
        tl = str(tmp_path / "t.jsonl")
        hub = TelemetryHub(port=0, timeline=tl)
        hub.handle(_ship_req("r1", [_win(1.0, {"c": 1})]))
        lines = [json.loads(x) for x in open(tl)]
        assert lines[0]["fleet"] == 1       # header
        assert lines[1]["src"] == "hx:1:r1"
        assert lines[1]["row"]["counters"] == {"c": 1}
        hub.stop()

    def test_health_worst_first_stale_and_limit(self, tmp_path):
        hub = TelemetryHub(port=0, timeline=None, stale_s=0.5)
        hub.handle(_ship_req("quiet", [_win(1.0, {})]))
        hub.handle(_ship_req("healthy", [_win(1.0, {})], pid=2))
        hub.handle(_ship_req("sick", [
            {"kind": "health",
             "row": {"t": 1.0, "sessions": 3,
                     "by_status": {"failing": 1, "ok": 2}}}], pid=3))
        # age the quiet source past the staleness bar
        hub._sources[("hx", "1", "quiet")].last_unix -= 10
        out = hub.handle({"op": "health"})
        assert out["ok"]
        statuses = [r["status"] for r in out["health"]]
        assert statuses[0] == "failing"     # worst first
        assert "stale" in statuses
        assert out["by_status"]["failing"] == 1
        # bounded payload: limit= honored and validated
        out = hub.handle({"op": "health", "limit": 1})
        assert len(out["health"]) == 1 and out["truncated"]
        assert out["health"][0]["status"] == "failing"
        assert not hub.handle({"op": "health", "limit": 0})["ok"]
        assert not hub.handle({"op": "health", "limit": 99999})["ok"]
        assert not hub.handle({"op": "health", "limit": "x"})["ok"]
        hub.stop()

    def test_health_poll_races_active_shippers(self):
        """A health poll must never leak an internal error while ship
        batches mutate per-source state (the alerts deque) — rows are
        built under the hub lock."""
        hub = TelemetryHub(port=0, timeline=None)
        stop = threading.Event()

        def pound():
            i = 0
            while not stop.is_set():
                hub.handle(_ship_req("noisy", [
                    {"kind": "alert", "row": {"kind": "stall",
                                              "t": float(i)}}]))
                i += 1

        t = threading.Thread(target=pound)
        t.start()
        try:
            for _ in range(300):
                out = hub.handle({"op": "health"})
                assert out["ok"], out
        finally:
            stop.set()
            t.join(5)
        assert out["health"][0]["status"] == "stalled"
        hub.stop()

    def test_timeline_rotation_and_restart_replay(self, tmp_path):
        tl = str(tmp_path / "t.jsonl")
        hub = TelemetryHub(port=0, timeline=tl, timeline_rows=3,
                           timeline_rotate=2)
        for i in range(8):
            hub.handle(_ship_req("r1", [_win(float(i),
                                             {"c": i + 1})]))
        hub.stop()
        assert hub.timeline_rotations >= 2
        assert os.path.exists(tl + ".1") and os.path.exists(tl + ".2")
        assert not os.path.exists(tl + ".3")    # depth respected
        # chain reads oldest-first across generations
        rows = [r for r in flight.read_chain(tl) if "src" in r]
        assert [r["row"]["counters"]["c"] for r in rows] == \
            list(range(1, 9))
        # a restarted hub replays the chain and serves the old view
        hub2 = TelemetryHub(port=0, timeline=tl, timeline_rows=100)
        m = hub2.handle({"op": "metrics"})
        assert m["sources"] == 1
        assert m["metrics"]["counters"]["c"] == 8   # last window
        src = next(iter(hub2._sources.values()))
        assert src.meta.get("replayed")
        hub2.stop()


# ------------------------------------- concurrency + kill durability
class TestHubConcurrency:
    def test_k_shippers_with_kill9_disconnects_lose_nothing_acked(
            self, tmp_path):
        """K concurrent wire writers, half of which abort their
        socket mid-stream without any goodbye (the kill -9 shape):
        every batch that was ACKED must be present in the fleet
        timeline; un-acked in-flight batches are the only loss."""
        tl = str(tmp_path / "t.jsonl")
        acked = [0] * 6
        with TelemetryHub(port=0, timeline=tl) as hub:
            def run(k):
                s = socket.create_connection(
                    ("127.0.0.1", hub.port), timeout=10)
                f = s.makefile("rwb")
                for b in range(10):
                    req = _ship_req(f"w{k}", [
                        _win(float(b), {"n": b + 1})], pid=100 + k)
                    f.write(json.dumps(req).encode() + b"\n")
                    f.flush()
                    resp = json.loads(f.readline())
                    assert resp["ok"]
                    acked[k] += 1
                    if k % 2 == 0 and b == 4:
                        # kill -9 shape: abort, no close handshake
                        s.close()
                        return
                f.close()
                s.close()

            threads = [threading.Thread(target=run, args=(k,))
                       for k in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(10)
        rows = [json.loads(x) for x in open(tl)][1:]
        by_src = {}
        for r in rows:
            by_src[r["src"]] = by_src.get(r["src"], 0) + 1
        for k in range(6):
            assert by_src.get(f"hx:{100 + k}:w{k}", 0) == acked[k]
        assert sum(acked) == sum(by_src.values())

    def test_threaded_shippers_concurrent_rollup_consistent(self):
        """K real TelemetryShippers against one hub: the fleet
        counter rollup equals the sum of each source's final
        registry... but shippers in one process share ONE metrics
        registry, so this asserts the rollup = per-source last
        windows sum (structural) and that every shipper's final
        window arrived."""
        with TelemetryHub(port=0, timeline=None) as hub:
            obs.enable()
            obs.count("shared.counter", 10)
            shippers = [ship.TelemetryShipper(
                f"127.0.0.1:{hub.port}", role=f"s{k}", interval=0.05)
                for k in range(4)]
            for sh in shippers:
                sh.start()
            time.sleep(0.2)
            for sh in shippers:
                sh.stop()
            assert len(hub._sources) == 4
            for src in hub._sources.values():
                assert src.final_seen
                assert src.last_window["counters"][
                    "shared.counter"] == 10
            m = hub.handle({"op": "metrics"})["metrics"]
            assert m["counters"]["shared.counter"] == 40


# ------------------------------------------- flight rotate satellite
class TestFlightRotateDepth:
    def test_rotate_files_shifts_chain(self, tmp_path):
        p = str(tmp_path / "f.jsonl")
        for gen, text in ((2, "old"), (1, "mid")):
            with open(f"{p}.{gen}", "w") as f:
                f.write(text + "\n")
        with open(p, "w") as f:
            f.write("new\n")
        flight.rotate_files(p, 3)
        assert open(f"{p}.3").read().strip() == "old"
        assert open(f"{p}.2").read().strip() == "mid"
        assert open(f"{p}.1").read().strip() == "new"
        assert not os.path.exists(p)
        # depth 1 = historical behavior: .1 only
        with open(p, "w") as f:
            f.write("newer\n")
        flight.rotate_files(p, 1)
        assert open(f"{p}.1").read().strip() == "newer"

    def test_recorder_honors_rotate_depth(self, tmp_path):
        obs.enable()
        p = str(tmp_path / "m.jsonl")
        rec = flight.FlightRecorder(p, interval=60, max_rows=2,
                                    rotate=3)
        rec.start()
        for _ in range(7):
            rec._write_row()
        rec.stop()
        chain = flight.chain(p)
        assert chain[-1] == p and len(chain) >= 3
        rows = flight.read_chain(p)
        # rows survive across generations in write order
        pids = [r["pid"] for r in rows if "pid" in r]
        assert len(pids) == len(rows) and len(rows) >= 6

    def test_top_last_rows_crosses_rotation_boundary(self, tmp_path):
        p = str(tmp_path / "m.jsonl")
        with open(p + ".1", "w") as f:
            f.write(json.dumps({"t": 1.0, "counters": {"a": 1}}) + "\n")
            f.write(json.dumps({"t": 2.0, "counters": {"a": 2}}) + "\n")
        with open(p, "w") as f:
            f.write(json.dumps({"t": 3.0, "counters": {"a": 3}}) + "\n")
        rows = top.last_rows(p, 3)
        assert [r["counters"]["a"] for r in rows] == [1, 2, 3]


# --------------------------------------------------- top satellites
class TestTopFleet:
    def _write_metrics(self, path, asks, t=None, gauges=None):
        with open(path, "w") as f:
            f.write(json.dumps({
                "t": t or time.time(), "dt": 1.0,
                "counters": {"driver.asks": asks},
                "deltas": {"driver.asks": asks},
                "gauges": gauges or {}, "hists": {}}) + "\n")

    def test_multi_metrics_glob_fleet_rolled_frame(self, tmp_path,
                                                   capsys):
        self._write_metrics(str(tmp_path / "m.jsonl"), 100)
        self._write_metrics(str(tmp_path / "m.h1.jsonl"), 50)
        rc = top.main(["--metrics", str(tmp_path / "m*.jsonl"),
                       "--once", "--json", "--fleet"])
        assert rc == 0
        frame = json.loads(capsys.readouterr().out)
        assert frame["counters"]["driver.asks"] == 150
        assert frame["meta"]["sources"] == 2
        labels = {s["source"] for s in frame["sources"]}
        assert labels == {"m.jsonl", "m.h1.jsonl"}

    def test_single_metrics_path_unchanged(self, tmp_path, capsys):
        p = str(tmp_path / "m.jsonl")
        self._write_metrics(p, 7)
        rc = top.main(["--metrics", p, "--once", "--json"])
        assert rc == 0
        frame = json.loads(capsys.readouterr().out)
        assert frame["counters"]["driver.asks"] == 7
        assert "sources" not in frame

    def test_top_addr_hub_renders_fleet(self, capsys):
        with TelemetryHub(port=0, timeline=None) as hub:
            hub.handle(_ship_req("r1", [_win(
                1.0, {"serve.asks": 42},
                gauges={"serve.sessions.active": 2})]))
            rc = top.main(["--addr", f"127.0.0.1:{hub.port}",
                           "--once", "--fleet"])
            out = capsys.readouterr().out
        assert rc == 0
        assert "sources   (1)" in out
        assert "hx:1:r1" in out

    def test_render_fleet_lines_stale_first(self):
        lines = top.fleet_lines([
            {"source": "b", "age_s": 0.1, "rates": {}, "stale": False},
            {"source": "a", "age_s": 60.0, "rates": {}, "stale": True},
        ])
        assert "(2)" in lines[0]
        assert "a" in lines[1] and "STALE" in lines[1]


# ------------------------------------------- serve health limit (sat)
class _FakeSession:
    def __init__(self, sid, status):
        self.id = sid
        self._status = status

    def health(self, **kw):
        return {"session": self.id, "status": self._status}


class TestServeHealthLimit:
    def _server(self):
        from uptune_tpu.serve.server import SessionServer
        return SessionServer(port=0)    # not started: no sockets

    def test_limit_bounds_and_default(self):
        srv = self._server()
        srv._sessions = {f"s{i}": _FakeSession(f"s{i}", "ok")
                         for i in range(70)}
        out = srv.handle({"op": "health"})
        assert out["ok"] and len(out["health"]) == 64
        assert out["truncated"] and out["sessions"] == 70
        out = srv.handle({"op": "health", "limit": 70})
        assert len(out["health"]) == 70 and not out["truncated"]
        out = srv.handle({"op": "health", "limit": 2})
        assert len(out["health"]) == 2 and out["truncated"]
        for bad in (0, -3, 4096, "x"):
            assert not srv.handle({"op": "health",
                                   "limit": bad})["ok"]

    def test_worst_first_survives_truncation(self):
        srv = self._server()
        srv._sessions = {"a": _FakeSession("a", "ok"),
                         "b": _FakeSession("b", "failing"),
                         "c": _FakeSession("c", "stalled")}
        out = srv.handle({"op": "health", "limit": 2})
        assert [r["status"] for r in out["health"]] == \
            ["failing", "stalled"]


# ------------------------------------------------ report multi-source
def _write_journal(path, qors, arm="de"):
    from uptune_tpu.obs import journal
    with open(path, "w") as f:
        f.write(json.dumps({"journal": journal.SCHEMA_VERSION,
                            "origin_unix": 1.0, "pid": 1,
                            "meta": {}}) + "\n")
        best = None
        for i, q in enumerate(qors):
            nb = best is None or q < best
            best = q if nb else best
            f.write(json.dumps({
                "ev": "step", "t": 0.1 * i, "arm": arm, "src": "arm",
                "batch": 1, "trials": 1, "dup": 0, "qors": [q],
                "nb": [nb], "gid0": i, "best": best}) + "\n")


class TestReportMultiSource:
    def test_multiple_journals_render_per_source(self, tmp_path,
                                                 capsys):
        from uptune_tpu.obs import report
        j1 = str(tmp_path / "a.h0.jsonl")
        j2 = str(tmp_path / "a.h1.jsonl")
        _write_journal(j1, [5.0, 3.0, 4.0])
        _write_journal(j2, [9.0, 2.0], arm="pso")
        rc = report.main([str(tmp_path / "a.h*.jsonl"),
                          "--format", "md", "-o", "-"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "## Sources" in out
        assert "## Source: a.h0.jsonl" in out
        assert "## Source: a.h1.jsonl" in out
        assert "pso" in out and "de" in out

    def test_fleet_timeline_detected_and_split(self, tmp_path,
                                               capsys):
        from uptune_tpu.obs import report
        tl = str(tmp_path / "ut.fleet.jsonl")
        hub = TelemetryHub(port=0, timeline=tl)
        hub.handle(_ship_req("driver.h0", [
            {"kind": "journal",
             "row": {"ev": "step", "t": 0.1, "arm": "de",
                     "qors": [1.0], "nb": [True], "gid0": 0,
                     "best": 1.0}},
            _win(1.0, {"driver.asks": 4}),
        ]))
        hub.handle(_ship_req("driver.h1", [
            {"kind": "journal",
             "row": {"ev": "step", "t": 0.2, "arm": "pso",
                     "qors": [2.0], "nb": [True], "gid0": 0,
                     "best": 2.0}}], pid=2))
        hub.stop()
        sources = report.read_sources([tl])
        assert [s[0] for s in sources] == ["hx:1:driver.h0",
                                           "hx:2:driver.h1"]
        rc = report.main([tl, "--format", "md", "-o", "-"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "hx:1:driver.h0" in out and "hx:2:driver.h1" in out
        # html renders self-contained too
        html = report.render_multi(sources, fmt="html")
        assert "ut report — fleet" in html and "driver.h0" in html

    def test_single_journal_unchanged(self, tmp_path, capsys):
        from uptune_tpu.obs import report
        j = str(tmp_path / "j.jsonl")
        _write_journal(j, [5.0, 3.0])
        rc = report.main([j, "--format", "md", "-o", "-"])
        out = capsys.readouterr().out
        assert rc == 0
        assert out.startswith("# ut report")
        assert "## Sources" not in out


# --------------------------------------------------- bench smoke
class TestFleetBenchSmoke:
    def test_bench_fleet_quick(self, tmp_path):
        """The tier-1 fleet e2e: 4 real processes (2 driver replicas,
        1 `ut serve`, the bench client) shipping to one hub, the
        exactness contract and the >= 0.95x shipper bar asserted by
        the bench itself (rc != 0 on any failure)."""
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
                   UT_TRACE="", UT_JOURNAL="", UT_TELEMETRY="")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--fleet", "--quick"],
            cwd=str(tmp_path), env=env, capture_output=True,
            text=True, timeout=560)
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
        line = [ln for ln in r.stdout.splitlines()
                if ln.startswith("{")][-1]
        out = json.loads(line)
        assert out["value"] is True
        art = json.load(open(os.path.join(REPO,
                                          "BENCH_FLEET.quick.json")))
        assert art["phase2"]["all_sources_exact"]
        assert art["phase2"]["fleet_counter_sum_exact"]
        assert art["phase2"]["processes"] == 4
