"""Search-hook observer tests (the reference's SearchPlugin +
display plugins, search/plugin.py:26-153)."""
import json

import pytest

jax = pytest.importorskip("jax")

from uptune_tpu.driver.driver import Tuner  # noqa: E402
from uptune_tpu.driver.plugins import (FileDisplay, LogDisplay,  # noqa: E402
                                       SearchHook)
from uptune_tpu.space.params import FloatParam  # noqa: E402
from uptune_tpu.space.spec import Space  # noqa: E402


def _space():
    return Space([FloatParam("x", -2.0, 2.0), FloatParam("y", -2.0, 2.0)])


def _obj(cfgs):
    return [c["x"] ** 2 + c["y"] ** 2 for c in cfgs]


class Recorder(SearchHook):
    def __init__(self):
        self.events = []

    def on_start(self, tuner):
        self.events.append(("start",))

    def on_result(self, tuner, trial, qor):
        self.events.append(("result", trial.gid, qor))

    def on_step(self, tuner, stats):
        self.events.append(("step", stats.technique))

    def on_new_best(self, tuner, config, qor):
        self.events.append(("best", qor))

    def on_finish(self, tuner, result):
        self.events.append(("finish", result.evals))


class TestHooks:
    def test_lifecycle_and_counts(self):
        rec = Recorder()
        t = Tuner(_space(), _obj, seed=0, hooks=[rec])
        res = t.run(test_limit=100)
        t.close()
        kinds = [e[0] for e in rec.events]
        assert kinds[0] == "start" and kinds[-1] == "finish"
        assert kinds.count("result") == res.evals
        assert kinds.count("step") == res.steps
        assert "best" in kinds
        # best events are monotone improving
        bests = [e[1] for e in rec.events if e[0] == "best"]
        assert bests == sorted(bests, reverse=True)
        assert rec.events[-1] == ("finish", res.evals)

    def test_failing_hook_does_not_kill_run(self):
        class Bomb(SearchHook):
            def on_step(self, tuner, stats):
                raise RuntimeError("boom")

        t = Tuner(_space(), _obj, seed=0, hooks=[Bomb()])
        res = t.run(test_limit=60)
        t.close()
        assert res.evals >= 60

    def test_failure_qor_reported_as_none(self):
        rec = Recorder()

        def obj(cfgs):
            return [float("nan") for _ in cfgs]

        t = Tuner(_space(), obj, seed=0, hooks=[rec])
        t.step()
        t.close()
        results = [e for e in rec.events if e[0] == "result"]
        assert results and all(e[2] is None for e in results)


class TestDisplays:
    def test_log_display(self, capsys):
        import sys
        t = Tuner(_space(), _obj, seed=0,
                  hooks=[LogDisplay(interval=0.0, out=sys.stdout)])
        t.run(test_limit=80)
        t.close()
        out = capsys.readouterr().out
        assert "NEW BEST" in out and "evals=" in out

    def test_file_display(self, tmp_path):
        p = tmp_path / "best.log"
        t = Tuner(_space(), _obj, seed=0, hooks=[FileDisplay(str(p))])
        res = t.run(test_limit=80)
        t.close()
        rows = [json.loads(l) for l in p.read_text().splitlines()]
        assert rows
        assert rows[-1]["qor"] == pytest.approx(res.best_qor)
        qs = [r["qor"] for r in rows]
        assert qs == sorted(qs, reverse=True)
