"""End-to-end tests of the intrusive API protocol: DEFAULT / ANALYSIS /
TUNE / BEST modes, ut.target flush + breakpoints, session best round
trip, and the constraint registry.

Spec: /root/reference/python/uptune/template/types.py:57-138,
report.py:45-103, api.py:52-65.
"""
import json
import os

import pytest

import uptune_tpu as ut
from uptune_tpu.api import constraint as C
from uptune_tpu.api import session
from uptune_tpu.api.state import STATE

MODE_VARS = ("UT_BEFORE_RUN_PROFILE", "UT_TUNE_START", "BEST", "UPTUNE",
             "UT_CURR_INDEX", "UT_CURR_STAGE", "UT_GLOBAL_ID",
             "UT_WORK_DIR", "UT_MULTI_STAGE_SAMPLE", "EZTUNING")


@pytest.fixture(autouse=True)
def clean_env(tmp_path, monkeypatch):
    for v in MODE_VARS:
        monkeypatch.delenv(v, raising=False)
    monkeypatch.setenv("UT_WORK_DIR", str(tmp_path))
    C.REGISTRY.clear()
    session.reset_settings()
    STATE.reset()
    yield tmp_path
    STATE.reset()


def _script(x_default=3):
    """A reference-style tuned program body; returns (x, y, flag)."""
    x = ut.tune(x_default, (1, 9), name="x")
    y = ut.tune(0.5, (0.0, 2.0))          # unnamed -> positional binding
    flag = ut.tune(True)
    return x, y, flag


def test_default_mode_returns_defaults():
    assert _script() == (3, 0.5, True)


def test_analysis_flushes_params_and_default_qor(clean_env, monkeypatch):
    monkeypatch.setenv("UT_BEFORE_RUN_PROFILE", "On")
    STATE.reset()
    x, y, flag = _script()
    assert (x, y, flag) == (3, 0.5, True)
    ut.target(x + y, "min")
    params = json.load(open(clean_env / "ut.params.json"))
    assert len(params) == 1 and len(params[0]) == 3
    assert params[0][0]["name"] == "x" and params[0][0]["type"] == "int"
    assert params[0][1]["type"] == "float"
    assert params[0][2]["type"] == "bool"
    dq = json.load(open(clean_env / "ut.default_qor.json"))
    assert dq["qor"] == 3.5 and dq["trend"] == "min"


def _write_protocol_files(work, cfg, params=None):
    os.makedirs(work / "configs", exist_ok=True)
    with open(work / "configs" / "ut.dr_stage0_index0.json", "w") as f:
        json.dump(cfg, f)
    if params is not None:
        with open(work / "ut.params.json", "w") as f:
            json.dump(params, f)


def test_tune_mode_serves_proposal_by_name_and_position(
        clean_env, monkeypatch):
    params = [[{"name": "x", "type": "int", "default": 3, "lo": 1, "hi": 9},
               {"name": "v0_1", "type": "float", "default": 0.5,
                "lo": 0.0, "hi": 2.0},
               {"name": "v0_2", "type": "bool", "default": True}]]
    _write_protocol_files(
        clean_env, {"x": 7, "v0_1": 1.25, "v0_2": False}, params)
    monkeypatch.setenv("UT_TUNE_START", "True")
    monkeypatch.setenv("UT_CURR_INDEX", "0")
    STATE.reset()
    assert _script() == (7, 1.25, False)
    ut.target(1.0, "min")
    rows = json.load(open(clean_env / "ut.qor_stage0.json"))
    assert rows == [[0, 1.0, "min"]]


def test_tune_mode_missing_proposal_falls_back_to_defaults(
        clean_env, monkeypatch):
    monkeypatch.setenv("UT_TUNE_START", "True")
    STATE.reset()
    assert _script() == (3, 0.5, True)


def test_best_mode_applies_best_with_positional_binding(
        clean_env, monkeypatch):
    session.write_best({"x": 9, "v0_1": 1.75, "v0_2": False}, 0.125,
                       work_dir=str(clean_env))
    with open(clean_env / "ut.params.json", "w") as f:
        json.dump([[{"name": "x"}, {"name": "v0_1"}, {"name": "v0_2"}]], f)
    monkeypatch.setenv("BEST", "True")
    STATE.reset()
    # unnamed calls must bind positionally in BEST mode too (ADVICE r1)
    assert _script() == (9, 1.75, False)
    cfg, qor = ut.get_best()
    assert cfg["x"] == 9 and qor == 0.125


def test_init_apply_best_switches_mode(clean_env, monkeypatch):
    session.write_best({"x": 4}, 1.0, work_dir=str(clean_env))
    ut.init(apply_best=True)
    assert os.environ["UPTUNE"] == "True"
    assert STATE.mode == "best"
    assert ut.tune(3, (1, 9), name="x") == 4


def test_multistage_analysis_two_targets(clean_env, monkeypatch):
    monkeypatch.setenv("UT_BEFORE_RUN_PROFILE", "On")
    STATE.reset()
    ut.tune(3, (1, 9), name="a")
    ut.target(1.0, "min")             # stage 0 boundary
    ut.tune(0.5, (0.0, 1.0), name="b")
    ut.target(2.0, "min")             # stage 1 boundary
    params = json.load(open(clean_env / "ut.params.json"))
    assert len(params) == 2
    assert params[0][0]["name"] == "a" and params[1][0]["name"] == "b"


def test_multistage_tune_breakpoint_exits(clean_env, monkeypatch):
    params = [[{"name": "a", "type": "int", "default": 3, "lo": 1,
                "hi": 9}],
              [{"name": "b", "type": "float", "default": 0.5, "lo": 0.0,
                "hi": 1.0}]]
    _write_protocol_files(clean_env, {"a": 5}, params)
    monkeypatch.setenv("UT_TUNE_START", "True")
    monkeypatch.setenv("UT_CURR_STAGE", "0")
    STATE.reset()
    assert ut.tune(3, (1, 9), name="a") == 5
    with pytest.raises(SystemExit):
        ut.target(1.5, "min")         # tuned stage -> write + exit
    rows = json.load(open(clean_env / "ut.qor_stage0.json"))
    assert rows == [[0, 1.5, "min"]]


def test_save_decorator_reports_qor(clean_env, monkeypatch):
    monkeypatch.setenv("UT_TUNE_START", "True")
    STATE.reset()

    @ut.save("max")
    def objective():
        return 42.0

    assert objective() == 42.0
    rows = json.load(open(clean_env / "ut.qor_stage0.json"))
    assert rows == [[0, 42.0, "max"]]


def test_feature_and_register(clean_env, monkeypatch):
    monkeypatch.setenv("UT_BEFORE_RUN_PROFILE", "On")
    STATE.reset()
    ut.feature(8, "cores")
    covars = json.load(open(clean_env / "covars.json"))
    assert covars == {"cores": 8}
    assert int(ut.vars.cores) == 8
    # VarNode usable as a tune() bound
    assert ut.tune(5, (2, int(ut.vars.cores))) == 5


def test_rules_and_constraints_enforced():
    @ut.rule()
    def no_both(cfg):
        return not (cfg["a"] and cfg["b"])

    @ut.constraint()
    def sane(qor, cfg):
        return qor < 100

    assert C.REGISTRY.check_config({"a": True, "b": False})
    assert not C.REGISTRY.check_config({"a": True, "b": True})
    assert C.REGISTRY.check_qor(5.0, {})
    assert not C.REGISTRY.check_qor(500.0, {})


def test_config_validation():
    s = ut.config({"test-limit": 50})
    assert s["test-limit"] == 50
    with pytest.raises(KeyError):
        ut.config({"bogus": 1})


def test_every_declared_export_resolves():
    import uptune_tpu
    for name in uptune_tpu._LAZY:
        assert getattr(uptune_tpu, name) is not None


def test_best_mode_accepts_reference_list_payload(clean_env, monkeypatch):
    # the reference writes best.json as [cfg, qor] (api.py:146-149)
    with open(clean_env / "best.json", "w") as f:
        json.dump([{"x": 6}, 0.5], f)
    monkeypatch.setenv("BEST", "True")
    STATE.reset()
    assert ut.tune(3, (1, 9), name="x") == 6


def test_best_mode_malformed_payload_falls_back(clean_env, monkeypatch):
    with open(clean_env / "best.json", "w") as f:
        json.dump("garbage", f)
    monkeypatch.setenv("BEST", "True")
    STATE.reset()
    assert ut.tune(3, (1, 9), name="x") == 3


def test_feature_registers_vars_in_tune_mode(clean_env, monkeypatch):
    monkeypatch.setenv("UT_TUNE_START", "True")
    STATE.reset()
    ut.feature(16, "cores")
    assert int(ut.vars.cores) == 16  # bound must resolve during trials


def test_best_mode_multistage_positional_binding(clean_env, monkeypatch):
    # unnamed params in stage >= 1 must bind after target() advances the
    # stage counter in BEST mode
    session.write_best({"a": 5, "v1_0": 0.75}, 1.0,
                       work_dir=str(clean_env))
    with open(clean_env / "ut.params.json", "w") as f:
        json.dump([[{"name": "a"}], [{"name": "v1_0"}]], f)
    monkeypatch.setenv("BEST", "True")
    STATE.reset()
    assert ut.tune(3, (1, 9)) == 5        # stage 0, positional
    ut.target(1.0, "min")                 # stage boundary
    assert ut.tune(0.5, (0.0, 1.0)) == 0.75  # stage 1, positional


def test_interm_writes_marker_and_features(clean_env, monkeypatch):
    monkeypatch.setenv("UT_BEFORE_RUN_PROFILE", "On")
    STATE.reset()
    ut.interm([1.0, 2.0], shape=2)
    assert (clean_env / "ut.interim_features.json").exists()
    feats = json.load(open(clean_env / "ut.features.json"))
    assert feats == [[-1, [1.0, 2.0]]]
