"""Fused on-device engine + sharded multi-chip engine tests (8 virtual CPU
devices via conftest)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uptune_tpu.engine import FusedEngine, default_arms
from uptune_tpu.parallel import ShardedEngine, make_mesh
from uptune_tpu.workloads import (
    random_tsp_distances, rosenbrock_device, rosenbrock_space, tsp_device,
    tsp_space)


def _rb_obj(vals, perms):
    return rosenbrock_device(vals)


class TestFusedEngine:
    def test_rosenbrock_converges_on_device(self):
        space = rosenbrock_space(2, -3.0, 3.0)
        eng = FusedEngine(space, _rb_obj)
        state = eng.init(jax.random.PRNGKey(0))
        state = jax.jit(lambda s: eng.run(s, 100))(state)
        assert eng.best_qor(state) < 0.5
        assert int(state.acqs) == 100 * eng.total_batch
        assert int(state.evals) <= int(state.acqs)

    def test_jit_run_donates_state(self):
        """jit_run (the bench/drive entry) donates the EngineState:
        history + technique buffers update in place — the caller must
        rebind and never reuse the donated input."""
        space = rosenbrock_space(2, -3.0, 3.0)
        eng = FusedEngine(space, _rb_obj, history_capacity=1 << 10)
        s0 = eng.init(jax.random.PRNGKey(0))
        run = eng.jit_run(5)
        s1 = run(s0)
        assert s0.hist.h0.is_deleted()
        assert s0.best.u.is_deleted()
        assert np.isfinite(eng.best_qor(s1))
        # rebound state keeps working across repeated donated calls
        s2 = run(s1)
        assert int(s2.acqs) == 10 * eng.total_batch
        # and donate=False keeps the input alive (debug/compare runs)
        s3 = eng.init(jax.random.PRNGKey(1))
        _ = eng.jit_run(2, donate=False)(s3)
        assert not s3.hist.h0.is_deleted()

    def test_trace_monotone(self):
        space = rosenbrock_space(2, -3.0, 3.0)
        eng = FusedEngine(space, _rb_obj)
        state = eng.init(jax.random.PRNGKey(1))
        _, trace = jax.jit(lambda s: eng.run_traced(s, 50))(state)
        tr = np.asarray(trace)
        assert (np.diff(tr) <= 1e-9).all()

    def test_max_sense(self):
        space = rosenbrock_space(2, -3.0, 3.0)
        eng = FusedEngine(space, lambda v, p: -rosenbrock_device(v),
                          sense="max")
        state = eng.init(jax.random.PRNGKey(2))
        state = jax.jit(lambda s: eng.run(s, 60))(state)
        assert eng.best_qor(state) > -0.5  # max of -rosenbrock -> ~0

    def test_perm_space(self):
        n = 12
        dist = jnp.asarray(random_tsp_distances(n, seed=2))
        space = tsp_space(n)
        eng = FusedEngine(space, lambda v, perms: tsp_device(perms[0], dist))
        state = eng.init(jax.random.PRNGKey(3))
        state = jax.jit(lambda s: eng.run(s, 80))(state)
        cfg = eng.best_config(state)
        assert sorted(cfg["tour"]) == list(range(n))
        # random tours on 12 cities average ~6.2; search must beat them well
        assert eng.best_qor(state) < 4.5

    def test_arm_stats_accumulate(self):
        space = rosenbrock_space(2, -3.0, 3.0)
        eng = FusedEngine(space, _rb_obj)
        state = eng.init(jax.random.PRNGKey(4))
        state = jax.jit(lambda s: eng.run(s, 20))(state)
        assert (np.asarray(state.arm_pulls) == 20).all()
        assert int(np.asarray(state.arm_hits).sum()) >= 1

    def test_scaled_arms(self):
        space = rosenbrock_space(4, -5.0, 5.0)
        eng = FusedEngine(space, _rb_obj, arms=default_arms(scale=8))
        assert eng.total_batch >= 8 * (30 + 32 + 32)
        state = eng.init(jax.random.PRNGKey(5))
        state = jax.jit(lambda s: eng.run(s, 10))(state)
        assert np.isfinite(eng.best_qor(state))


@pytest.mark.slow
class TestShardedEngine:
    def test_mesh_8_devices(self):
        assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"

    def test_sharded_run_matches_convergence(self):
        space = rosenbrock_space(2, -3.0, 3.0)
        eng = FusedEngine(space, _rb_obj)
        mesh = make_mesh(n_search=4, n_eval=2)
        sh = ShardedEngine(eng, mesh)
        state = sh.init(jax.random.PRNGKey(0))
        state = sh.run(state, 60)
        cfg, qor = sh.best(state)
        assert qor < 0.5, qor
        # best exchange: every replica's best must equal the global best
        qors = np.asarray(state.best.qor)
        assert np.allclose(qors, qors.min(), atol=1e-6)

    def test_search_only_mesh(self):
        space = rosenbrock_space(2, -3.0, 3.0)
        eng = FusedEngine(space, _rb_obj)
        sh = ShardedEngine(eng, make_mesh(n_search=8, n_eval=1))
        state = sh.init(jax.random.PRNGKey(1))
        state = sh.run(state, 40)
        _, qor = sh.best(state)
        assert qor < 1.0

    def test_eval_sharding_equivalence(self):
        # same seed: eval-sharded run must equal unsharded run bitwise-ish
        space = rosenbrock_space(2, -3.0, 3.0)
        eng = FusedEngine(space, _rb_obj, dedup=False)
        sh1 = ShardedEngine(eng, make_mesh(n_search=1, n_eval=1))
        sh4 = ShardedEngine(eng, make_mesh(n_search=1, n_eval=4))
        s1 = sh1.run(sh1.init(jax.random.PRNGKey(7)), 25)
        s4 = sh4.run(sh4.init(jax.random.PRNGKey(7)), 25)
        np.testing.assert_allclose(
            np.asarray(s1.best.qor), np.asarray(s4.best.qor), rtol=1e-5)

    def test_perm_space_sharded(self):
        n = 8
        dist = jnp.asarray(random_tsp_distances(n, seed=1))
        space = tsp_space(n)
        eng = FusedEngine(space, lambda v, perms: tsp_device(perms[0], dist))
        sh = ShardedEngine(eng, make_mesh(n_search=4, n_eval=2))
        state = sh.init(jax.random.PRNGKey(2))
        state = sh.run(state, 40)
        cfg, qor = sh.best(state)
        assert sorted(cfg["tour"]) == list(range(n))


class TestShardedSemanticEquivalence:
    """r4 verdict next-step #5: upgrade multichip evidence from 'runs'
    to 'equivalent'.  With one search replica, eval-axis sharding must
    be semantically INVISIBLE: the full best trajectory of the sharded
    engine over >=50 steps — dedup ON, the production configuration —
    equals the single-device engine's under identical seeds.  (With
    n_search > 1 replicas intentionally diverge: independent RNG
    streams + best exchange is a different, multi-start semantics —
    covered by test_sharded_run_matches_convergence.)"""

    def _trajectory(self, runner, init_state, chunks=10, chunk=6):
        state, traj = init_state, []
        for _ in range(chunks):
            state = runner(state, chunk)
            traj.append(float(np.asarray(state.best.qor).min()))
        return state, traj

    @staticmethod
    def _padded_engine(space, obj, div=8):
        """default arms padded so any eval-axis split divides the batch
        (same recipe as __graft_entry__._flagship)."""
        from uptune_tpu.techniques.purerandom import PureRandom
        arms = default_arms(1)
        pad = (-sum(t.natural_batch(space) for t in arms)) % div
        if pad:
            arms.append(PureRandom(batch=pad))
        return FusedEngine(space, obj, arms=arms)

    @pytest.mark.slow
    def test_trajectory_equivalence_60_steps(self):
        # ~10s; slow-marked for tier-1 headroom (ISSUE 5).  The gate
        # itself stays tier-1 through the perm-space sibling below and
        # the driver's separate __graft_entry__.dryrun_multichip run
        space = rosenbrock_space(3, -3.0, 3.0)
        eng = self._padded_engine(space, _rb_obj)  # dedup ON (default)
        key = jax.random.PRNGKey(11)

        # single device: plain engine.run via jit.  ShardedEngine.init
        # derives replica keys via split(key, n_search), so the
        # apples-to-apples single-device run starts from the SAME
        # derived key, not the raw one
        run1 = jax.jit(lambda s, n: eng.run(s, n), static_argnums=1)
        s1, t1 = self._trajectory(
            run1, eng.init(jax.random.split(key, 1)[0]))

        # eval-sharded across 4 devices, same key
        sh = ShardedEngine(eng, make_mesh(n_search=1, n_eval=4))
        s4, t4 = self._trajectory(sh.run, sh.init(key))

        assert len(t1) == len(t4) == 10          # 60 steps total
        np.testing.assert_allclose(t1, t4, rtol=1e-5, atol=1e-6)
        # the final incumbent CONFIG matches too, not just its QoR
        np.testing.assert_allclose(
            np.asarray(s1.best.u),
            np.asarray(jax.tree.map(lambda x: x[0], s4.best).u),
            rtol=1e-5, atol=1e-6)

    def test_perm_space_trajectory_equivalence(self):
        n = 8
        dist = jnp.asarray(random_tsp_distances(n, seed=3))
        space = tsp_space(n)
        eng = FusedEngine(space,
                          lambda v, perms: tsp_device(perms[0], dist))
        key = jax.random.PRNGKey(13)
        run1 = jax.jit(lambda s, k: eng.run(s, k), static_argnums=1)
        _, t1 = self._trajectory(
            run1, eng.init(jax.random.split(key, 1)[0]),
            chunks=8, chunk=8)
        sh = ShardedEngine(eng, make_mesh(n_search=1, n_eval=2))
        _, t2 = self._trajectory(sh.run, sh.init(key), chunks=8, chunk=8)
        np.testing.assert_allclose(t1, t2, rtol=1e-5, atol=1e-6)

    def test_surrogate_refit_under_mesh_equivalence(self):
        """A GP refit on the sharded run's history, EI-scored over the
        whole mesh, must equal the single-device fit+score (the
        sharded surrogate plane is the same model, just spread)."""
        from uptune_tpu.parallel import sharded_gp_score
        from uptune_tpu.surrogate import gp

        space = rosenbrock_space(3, -3.0, 3.0)
        eng = self._padded_engine(space, _rb_obj)
        sh = ShardedEngine(eng, make_mesh(n_search=1, n_eval=4))
        state = sh.run(sh.init(jax.random.PRNGKey(17)), 50)

        rng = np.random.RandomState(17)
        feats = jnp.asarray(rng.rand(96, space.n_features), jnp.float32)
        ys = jnp.asarray(rng.randn(96), jnp.float32)
        st = gp.fit_auto(feats, ys)
        pool = jnp.asarray(rng.rand(64, space.n_features), jnp.float32)
        best_y = float(np.asarray(ys).min())
        mesh = make_mesh(n_search=1, n_eval=8)
        ei_sharded = sharded_gp_score(mesh, "eval", st, pool, kind="ei",
                                      best_y=best_y)
        ei_single = gp.expected_improvement(st, pool,
                                            jnp.float32(best_y))
        np.testing.assert_allclose(np.asarray(ei_sharded),
                                   np.asarray(ei_single),
                                   rtol=1e-4, atol=1e-6)
        # and the engine state it ran beside is healthy
        assert np.isfinite(np.asarray(state.best.qor)).all()
