"""Sharded front tier (ISSUE 17, docs/SERVING.md "Sharded front
tier"): the consistent-hash ring's determinism / balance / minimal
movement, the canonical routing key, the Router's op surface over
registered external shards (no subprocesses), the real-TCP redirect
protocol through SessionClient (re-homing, probe-based attach, the
redirect-loop bound), and the `bench.py --serve-sharded --quick`
tier-1 smoke.

Budget note: everything except the bench smoke is socket/thread-level
— no engine, no jax compiles.  Router(shards=0) + register() keeps
the supervisor away from real `ut serve` children entirely; the only
spawned processes live in the subprocess smoke."""
import json
import os
import subprocess
import sys

import pytest

import uptune_tpu
from uptune_tpu import obs
from uptune_tpu.serve import router as router_mod
from uptune_tpu.serve.client import ServeError, SessionClient
from uptune_tpu.serve.router import HashRing, Router, routing_key
from uptune_tpu.serve.wire import RequestError, WireServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    uptune_tpu.__file__)))


@pytest.fixture(autouse=True)
def obs_clean():
    obs.reset()
    yield
    obs.reset()


def _keys(n):
    return [f"key-{i}" for i in range(n)]


# ------------------------------------------------------- hash ring
class TestHashRing:
    def test_lookup_deterministic_and_order_independent(self):
        a, b = HashRing(), HashRing()
        for name in ("s0", "s1", "s2", "s3"):
            a.add(name)
        for name in ("s3", "s1", "s0", "s2"):
            b.add(name)
        for k in _keys(200):
            assert a.lookup(k) == b.lookup(k)
        # and stable across repeated lookups
        assert [a.lookup(k) for k in _keys(50)] == \
               [a.lookup(k) for k in _keys(50)]

    def test_balance(self):
        ring = HashRing()
        for i in range(4):
            ring.add(f"s{i}")
        counts = {f"s{i}": 0 for i in range(4)}
        for k in _keys(2000):
            counts[ring.lookup(k)] += 1
        # 64 vnodes/shard: no shard should own a wildly skewed share
        for name, n in counts.items():
            assert 0.10 < n / 2000 < 0.45, (name, counts)

    def test_add_moves_only_toward_new_node(self):
        ring = HashRing()
        for i in range(4):
            ring.add(f"s{i}")
        before = {k: ring.lookup(k) for k in _keys(1000)}
        ring.add("s4")
        moved = 0
        for k, owner in before.items():
            now = ring.lookup(k)
            if now != owner:
                moved += 1
                # consistent hashing's defining property: adding a
                # node only steals keys FOR that node — no key moves
                # between two preexisting shards
                assert now == "s4", (k, owner, now)
        assert 0 < moved / 1000 < 0.45      # ~1/5 expected

    def test_remove_moves_only_owned_keys(self):
        ring = HashRing()
        for i in range(4):
            ring.add(f"s{i}")
        before = {k: ring.lookup(k) for k in _keys(1000)}
        ring.remove("s2")
        for k, owner in before.items():
            now = ring.lookup(k)
            if owner == "s2":
                assert now != "s2"
            else:
                assert now == owner, (k, owner, now)

    def test_empty_and_membership(self):
        ring = HashRing()
        assert ring.lookup("anything") is None
        assert len(ring) == 0 and ring.nodes == []
        ring.add("s0")
        ring.add("s0")              # idempotent
        assert len(ring) == 1 and ring.nodes == ["s0"]
        ring.remove("nope")         # unknown: no-op
        ring.remove("s0")
        assert ring.lookup("anything") is None


# ----------------------------------------------------- routing key
class TestRoutingKey:
    def test_canonical_and_distinct(self):
        recs = [{"name": "x0", "type": "float", "lo": -1.0, "hi": 1.0}]
        same = [{"hi": 1.0, "lo": -1.0, "type": "float", "name": "x0"}]
        other = [{"name": "x0", "type": "float", "lo": -2.0,
                  "hi": 1.0}]
        k = routing_key(recs)
        assert k == routing_key(recs) == routing_key(same)
        assert k != routing_key(other)
        assert len(k) == 40 and int(k, 16) >= 0     # hex sha1


# ------------------------------------------- router ops (no procs)
def _recs(i):
    return [{"name": "x0", "type": "float", "lo": -1.0 - i,
             "hi": 1.0 + i}]


class TestRouterOps:
    @pytest.fixture()
    def router(self, tmp_path):
        r = Router(shards=0, work_dir=str(tmp_path))
        # three registered externals on dead ports: routing/bookkeeping
        # ops never dial them, and the attach probe treats a refused
        # connection as "not here"
        for i in range(3):
            r.register("127.0.0.1", 1, name=f"s{i}")
        return r

    def test_ping(self, router):
        out = router.handle({"op": "ping"})
        assert out["ok"] and out["role"] == "router"
        assert out["shards"] == 3 and out["sessions"] == 0

    def test_open_needs_space(self, router):
        for bad in ({}, {"space": []}, {"space": "x"}):
            out = router.handle({"op": "open", **bad})
            assert not out["ok"] and "space" in out["error"]

    def test_open_redirect_consistent_with_route(self, router):
        a = router.handle({"op": "open", "space": _recs(0)})
        b = router.handle({"op": "open", "space": _recs(0)})
        want = router.handle({"op": "route", "space": _recs(0)})
        assert a["ok"] and a["redirect"] == b["redirect"] == \
            want["addr"]
        assert a["shard"] == want["shard"]

    def test_distinct_spaces_spread(self, router):
        shards = {router.handle({"op": "open",
                                 "space": _recs(i)})["shard"]
                  for i in range(12)}
        assert len(shards) >= 2, shards

    def test_open_remembers_sid_for_attach(self, router):
        out = router.handle({"op": "open", "space": _recs(1),
                             "session": "sid-abc"})
        att = router.handle({"op": "attach", "session": "sid-abc"})
        assert att["ok"] and att["shard"] == out["shard"]
        assert att["redirect"] == out["redirect"]

    def test_attach_unknown_probes_then_fails(self, router):
        out = router.handle({"op": "attach", "session": "nope"})
        assert not out["ok"] and "unknown session" in out["error"]
        out = router.handle({"op": "attach"})
        assert not out["ok"] and "session" in out["error"]

    def test_route_needs_key_or_space(self, router):
        byk = router.handle({"op": "route",
                             "key": routing_key(_recs(2))})
        bys = router.handle({"op": "route", "space": _recs(2)})
        assert byk["ok"] and byk["shard"] == bys["shard"]
        out = router.handle({"op": "route"})
        assert not out["ok"] and "key" in out["error"]

    def test_resolve_multi_signature(self, router):
        """ISSUE 20: many spaces (or precomputed keys) map to their
        owning shards in ONE round trip, with element-wise error
        rows — one malformed entry never discards its siblings."""
        out = router.handle({"op": "resolve",
                             "spaces": [_recs(0), _recs(1),
                                        "bad", []]})
        assert out["ok"]
        rows = out["resolved"]
        assert len(rows) == 4
        want = router.handle({"op": "route", "space": _recs(0)})
        assert rows[0]["shard"] == want["shard"]
        assert rows[0]["addr"] == want["addr"]
        assert rows[0]["key"] == routing_key(_recs(0))[:12]
        assert "error" in rows[2] and "error" in rows[3]
        # the keys form agrees with the spaces form
        byk = router.handle({"op": "resolve",
                             "keys": [routing_key(_recs(1))]})
        assert byk["ok"]
        assert byk["resolved"][0]["shard"] == rows[1]["shard"]

    def test_resolve_validation_and_cap(self, router):
        for bad in ({}, {"spaces": "x"}, {"keys": 3}):
            out = router.handle({"op": "resolve", **bad})
            assert not out["ok"], bad
        router.MAX_RESOLVE = 2
        try:
            out = router.handle({"op": "resolve",
                                 "keys": ["a", "b", "c"]})
            assert not out["ok"] and "capped" in out["error"]
        finally:
            del router.MAX_RESOLVE

    def test_batch_frame_inherited_from_kernel(self, router):
        """`ut route` speaks multi-op frames with no op-table change
        (the ISSUE 20 kernel seam): ping + route + resolve in one
        frame, ordered replies."""
        out = router.handle({"op": "batch", "ops": [
            {"op": "ping"},
            {"op": "route", "space": _recs(0)},
            {"op": "resolve", "keys": [routing_key(_recs(1))]}]})
        assert out["ok"] and out["n"] == 3 and out["failed"] == 0
        assert out["replies"][0]["role"] == "router"
        assert out["replies"][1]["shard"]
        assert out["replies"][2]["resolved"][0]["shard"]

    def test_shards_rows_sorted(self, router):
        out = router.handle({"op": "shards"})
        assert out["ok"] and out["target"] == 3
        names = [r["name"] for r in out["shards"]]
        assert names == sorted(names) == ["s0", "s1", "s2"]
        row = out["shards"][0]
        assert row["managed"] is False and row["ready"] is True

    def test_scale_validation(self, router):
        out = router.handle({"op": "scale"})
        assert not out["ok"] and "shards" in out["error"]
        out = router.handle({"op": "scale", "shards": 100})
        assert not out["ok"] and "[0, 64]" in out["error"]
        # scale DOWN never spawns; the drain is the supervisor's job
        out = router.handle({"op": "scale", "shards": 1})
        assert out["ok"] and out["target"] == 1
        assert out["live"] == 3 and out["spawned"] == []

    def test_register_bumps_target(self, tmp_path):
        r = Router(shards=0, work_dir=str(tmp_path))
        assert r._target == 0
        r.register("127.0.0.1", 1)
        r.register("127.0.0.1", 2)
        # without the bump the supervisor's converge step would drain
        # the externals it was just handed
        assert r._target == 2

    def test_autoscale_policy(self, tmp_path, monkeypatch):
        """Load-driven targeting: hot mean-sessions/shard raises the
        target one step per cooldown window, an idle tier lowers it,
        both bounded — the supervisor's converge step then does the
        actual spawning/draining."""
        r = Router(shards=0, work_dir=str(tmp_path),
                   autoscale=(1.0, 6.0), autoscale_bounds=(2, 4))
        for i in range(3):
            r.register("127.0.0.1", 1, name=f"s{i}")
        assert r._target == 3
        vals = [10.0, 10.0, 10.0]
        monkeypatch.setattr(r.hub, "gauge_values",
                            lambda key: list(vals))
        r._autoscale()
        assert r._target == 4
        # cooldown: one decision must settle before the next
        r._autoscale()
        assert r._target == 4
        r._scale_hold = 0.0
        r._autoscale()  # still hot but already at the upper bound
        assert r._target == 4
        # idle tier sheds one per window, floored at the lower bound
        vals[:] = [0.0, 0.0, 0.0]
        for _ in range(5):
            r._scale_hold = 0.0
            r._autoscale()
        assert r._target == 2
        # no live gauge windows yet (cold hub): never adjusts
        r._scale_hold = 0.0
        monkeypatch.setattr(r.hub, "gauge_values", lambda key: [])
        r._autoscale()
        assert r._target == 2

    def test_session_map_cap(self, router, monkeypatch):
        monkeypatch.setattr(router_mod, "SESSION_MAP_CAP", 4)
        for i in range(10):
            router._remember(f"sid-{i}", "s0")
        assert len(router._sessions) == 4
        # newest placements survive the eviction
        assert "sid-9" in router._sessions

    def test_metrics_empty_hub(self, router):
        out = router.handle({"op": "metrics"})
        assert out["ok"] and out["shards"] == 3
        assert out["sessions"] == 0 and "metrics" in out

    def test_top_renders_router_scrape(self, router):
        """`ut top --addr <router>`: the router's metrics op serves
        the hub rollup in the scrape shape sample_from_scrape /
        render already consume — no top.py special-casing."""
        from uptune_tpu.obs import top
        resp = router.handle({"op": "metrics"})
        cur = top.sample_from_scrape(resp)
        out = top.render(None, cur, source="router", width=72)
        assert "serve" in out and "sessions" in out

    def test_stats_shape(self, router):
        out = router.handle({"op": "stats"})
        assert out["ok"] and out["kills"] == 0
        assert out["restarts"] == 0 and out["sessions_mapped"] == 0
        assert [r["name"] for r in out["shards"]] == \
            ["s0", "s1", "s2"]


# ------------------------------------------------ TCP redirect e2e
class FakeShard(WireServer):
    """A session-server stand-in speaking just enough of the protocol
    for redirect tests: open mints a session, attach finds it, stats
    exposes the `session_ids` registry the router's probe reads."""

    WIRE_NAME = "ut-test-shard"

    def __init__(self):
        super().__init__("127.0.0.1", 0)
        self.sessions = {}
        self.opens = 0

    def _op_ping(self, req: dict) -> dict:
        return {"role": "fake-shard"}

    def _op_open(self, req: dict) -> dict:
        with self._lock:
            self.opens += 1
            sid = req.get("session") or f"fs{self.port}-{self.opens}"
            self.sessions[sid] = True
        return {"session": sid, "version": 0, "incarn": "i0"}

    def _op_attach(self, req: dict) -> dict:
        sid = req.get("session")
        with self._lock:
            known = sid in self.sessions
        if not known:
            raise RequestError(f"unknown session: {sid}")
        return {"session": sid, "version": 0, "incarn": "i0"}

    def _op_stats(self, req: dict) -> dict:
        with self._lock:
            out = {"n_sessions": len(self.sessions)}
            if req.get("sessions"):
                out["session_ids"] = sorted(self.sessions)
        return out

    _OPS = {"ping": _op_ping, "open": _op_open,
            "attach": _op_attach, "stats": _op_stats}


class TestRedirectTCP:
    def test_open_and_attach_redirect_rehome(self, tmp_path):
        shards = [FakeShard().start() for _ in range(2)]
        r = Router(shards=0, work_dir=str(tmp_path),
                   supervise_interval=30.0).start()
        try:
            for sh in shards:
                r.register("127.0.0.1", sh.port)
            recs = _recs(0)
            want = r.handle({"op": "route", "space": recs})
            c = SessionClient("127.0.0.1", r.port, timeout=10)
            h = c.open_session(recs, seed=1)
            # one hop: the client now talks to the owning shard
            assert c.redirects == 1
            assert f"{c.host}:{c.port}" == want["addr"]
            owner = next(sh for sh in shards if sh.port == c.port)
            assert h.id in owner.sessions

            # a FRESH client attaches through the router: the sid was
            # shard-minted (never seen by the router), so the probe
            # path finds it via the shards' session registries
            c2 = SessionClient("127.0.0.1", r.port, timeout=10)
            h2 = c2.attach_session(h.id)
            assert c2.redirects == 1 and c2.port == c.port
            assert h2.id == h.id

            with pytest.raises(ServeError, match="unknown session"):
                SessionClient("127.0.0.1", r.port,
                              timeout=10).request("attach",
                                                  session="nope")
            c.close()
            c2.close()
        finally:
            r.stop()
            for sh in shards:
                sh.stop()

    def test_redirect_loop_bounded(self, tmp_path):
        # a router registered as its own shard redirects forever; the
        # client must give up at MAX_REDIRECTS, not spin
        r = Router(shards=0, work_dir=str(tmp_path),
                   supervise_interval=30.0).start()
        try:
            r.register("127.0.0.1", r.port, name="s0")
            c = SessionClient("127.0.0.1", r.port, timeout=10)
            with pytest.raises(ServeError, match="redirect limit"):
                c.open_session(_recs(0), seed=1)
            assert c.redirects == SessionClient.MAX_REDIRECTS
            c.close()
        finally:
            r.stop()


# --------------------------------------------------- tier-1 smoke
class TestShardedBenchSmoke:
    def test_sharded_bench_quick_smoke(self, tmp_path):
        """`bench.py --serve-sharded --quick` (the ISSUE 17 tier-1
        smoke): a real Router over real `ut serve --durable` shard
        children on localhost TCP, K walked 1->2, then a
        DETERMINISTIC route.kill SIGKILL mid-drive with same-port
        respawn — auto-resume clients finish with bitwise
        matched-seed parity and zero acked committed loss.
        Throughput is recorded, never gated (co-tenant noise)."""
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--serve-sharded", "--quick", "--cpu"],
            capture_output=True, text=True, env=env,
            cwd=str(tmp_path), timeout=840)
        assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-4000:]
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["metric"] == "serve_sharded_ok"
        assert out["value"] is True
        art = json.load(open(os.path.join(
            REPO, "BENCH_SERVE_SHARDED.quick.json")))
        assert art["phase2"]["parity_bitwise_ok"]
        assert art["phase2"]["zero_committed_loss"]
        assert art["phase2"]["acked_committed_monotone"]
        assert art["phase2"]["kills"] == 1
        assert art["phase2"]["restarts"] >= 1
        assert art["phase2"]["trace_guard"]["clean"]
        assert art["phase1"]["agg_asks_per_s"]
