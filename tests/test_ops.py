"""Operator kernel semantics: numeric unit-space ops and permutation
crossovers, property-tested against the reference's documented behavior
(manipulator.py:505-542, 1048-1357)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uptune_tpu.ops import numeric, perm


def valid_perm_rows(pm):
    pm = np.asarray(pm)
    n = pm.shape[1]
    return all(sorted(r.tolist()) == list(range(n)) for r in pm)


# ---------------- numeric ----------------

def test_reflect_unit():
    v = jnp.array([-0.25, 0.0, 0.5, 1.0, 1.25, 1.9])
    out = np.asarray(numeric.reflect_unit(v))
    np.testing.assert_allclose(out, [0.25, 0.0, 0.5, 1.0, 0.75, 0.1],
                               atol=1e-6)
    assert ((out >= 0) & (out <= 1)).all()


def test_normal_mutation_bounds_and_masks():
    key = jax.random.PRNGKey(0)
    u = jnp.full((64, 6), 0.5)
    cm = jnp.array([False, False, False, True, True, True])
    out = numeric.normal_mutation(key, u, 0.1, cm)
    out_np = np.asarray(out)
    assert ((out_np >= 0) & (out_np <= 1)).all()
    # complex lanes are uniform redraws: spread over [0,1], not near 0.5
    assert out_np[:, 3:].std() > 0.2
    # primitive lanes stay near 0.5 with sigma=0.1
    assert abs(out_np[:, :3].mean() - 0.5) < 0.05
    # with a mask, unmasked lanes unchanged
    m = jnp.zeros((64, 6), bool).at[:, 0].set(True)
    out2 = np.asarray(numeric.normal_mutation(key, u, 0.1, cm, mask=m))
    np.testing.assert_array_equal(out2[:, 1:], 0.5)
    assert (out2[:, 0] != 0.5).any()


def test_set_linear_primitive_and_complex():
    key = jax.random.PRNGKey(1)
    B, D = 16, 4
    cm = jnp.array([False, False, True, True])
    ua = jnp.full((B, D), 0.2)
    ub = jnp.full((B, D), 0.6)
    uc = jnp.full((B, D), 0.4)
    # codes equal on lane 2, differ on lane 3
    eq = jnp.tile(jnp.array([True, True, True, False]), (B, 1))
    out = np.asarray(numeric.set_linear(
        key, ua, ub, uc, 1.0, 0.5, -0.5, cm, eq))
    # primitive: 0.2 + 0.5*(0.6-0.4) = 0.3
    np.testing.assert_allclose(out[:, :2], 0.3, atol=1e-6)
    # complex equal codes: copy ua
    np.testing.assert_allclose(out[:, 2], 0.2, atol=1e-6)
    # complex differing codes: random redraw (not a constant)
    assert out[:, 3].std() > 0.05


def test_set_linear_clips():
    key = jax.random.PRNGKey(2)
    one = jnp.ones((4, 2))
    cm = jnp.zeros(2, bool)
    eq = jnp.ones((4, 2), bool)
    out = np.asarray(numeric.set_linear(key, one, one, one * 0.0,
                                        1.0, 1.0, -0.0, cm, eq))
    assert (out <= 1.0).all()


def test_swarm_moves_toward_best():
    key = jax.random.PRNGKey(3)
    u = jnp.full((256, 2), 0.1)
    best = jnp.full((256, 2), 0.9)
    vel = jnp.zeros((256, 2))
    cm = jnp.zeros(2, bool)
    bm = jnp.zeros(2, bool)
    out, v = numeric.swarm(key, u, best, best, vel, cm, bm)
    assert np.asarray(out).mean() > 0.15  # moved toward 0.9 on average
    assert np.asarray(v).mean() > 0


def test_swarm_complex_lanes_mix_parents():
    # SWITCH/ENUM lanes must stochastically pick among current/local/global
    # values — never snap to the unit endpoints (which would make middle
    # options unreachable).
    key = jax.random.PRNGKey(9)
    u = jnp.full((512, 2), 0.5)
    loc = jnp.full((512, 2), 0.3)
    glob = jnp.full((512, 2), 0.7)
    cm = jnp.array([True, True])
    bm = jnp.array([True, False])  # lane 0 bool, lane 1 enum-like
    out, _ = numeric.swarm(key, u, loc, glob, jnp.zeros((512, 2)), cm, bm)
    o = np.asarray(out)
    assert set(np.unique(o[:, 0]).tolist()) <= {0.0, 1.0}  # bool coin
    uniq = np.unique(o[:, 1])
    assert all(min(abs(float(v) - t) for t in (0.3, 0.5, 0.7)) < 1e-6
               for v in uniq)
    assert len(uniq) == 3                                  # all parents reachable


# ---------------- permutation ----------------

N = 8


def rand_perms(key, b=32, n=N):
    return jax.vmap(lambda k: jax.random.permutation(k, n))(
        jax.random.split(key, b)).astype(jnp.int32)


def test_shuffle_and_swap_valid():
    key = jax.random.PRNGKey(4)
    pm = rand_perms(key)
    assert valid_perm_rows(perm.shuffle_batch(key, pm))
    out = perm.random_swap_batch(key, pm)
    assert valid_perm_rows(out)
    # exactly 0 or 2 positions differ per row
    diff = (np.asarray(out) != np.asarray(pm)).sum(axis=1)
    assert set(diff.tolist()) <= {0, 2}


def test_random_invert():
    key = jax.random.PRNGKey(5)
    pm = rand_perms(key)
    out = perm.random_invert_batch(key, pm, 3)
    assert valid_perm_rows(out)
    diff = (np.asarray(out) != np.asarray(pm)).sum(axis=1)
    assert diff.max() <= 3


def test_small_random_change_matches_reference_bubble():
    # reference: iterate i=1..n-1, swap (i-1, i) with prob p on the *updated*
    # list (manipulator.py:1067-1080)
    key = jax.random.PRNGKey(6)
    p0 = jnp.arange(N, dtype=jnp.int32)
    out = perm.small_random_change(key, p0, 1.0)  # always swap
    # with p=1 element 0 bubbles to the end
    assert np.asarray(out).tolist() == [1, 2, 3, 4, 5, 6, 7, 0]


@pytest.mark.parametrize("name", ["PX", "PMX", "CX", "OX1", "OX3"])
def test_crossovers_produce_valid_perms(name):
    key = jax.random.PRNGKey(7)
    k1, k2, k3 = jax.random.split(key, 3)
    p1 = rand_perms(k1)
    p2 = rand_perms(k2)
    fn = getattr(perm, f"cross_{name.lower()}_batch")
    out = fn(k3, p1, p2, 3)
    assert valid_perm_rows(out)


def test_px_semantics():
    # head of p1 (up to some cut c in [2, n]) reordered to p2's order
    # (ascending here); tail keeps p1's order
    p1 = np.array([3, 1, 0, 2, 4, 7, 6, 5])
    p2 = jnp.arange(N, dtype=jnp.int32)
    seen_cuts = set()
    for seed in range(16):
        out = np.asarray(perm.cross_px(
            jax.random.PRNGKey(seed), jnp.asarray(p1, jnp.int32), p2))
        assert sorted(out.tolist()) == list(range(N))
        # the result must equal sorted(p1[:c]) + p1[c:] for some c in [2, n]
        matches = [c for c in range(2, N + 1)
                   if out.tolist() == sorted(p1[:c].tolist()) + p1[c:].tolist()]
        assert matches, out
        seen_cuts.add(matches[0])
    assert len(seen_cuts) > 1  # cut point actually varies


def test_pmx_segment_copied():
    key = jax.random.PRNGKey(8)
    p1 = jnp.arange(N, dtype=jnp.int32)
    p2 = jnp.array([7, 6, 5, 4, 3, 2, 1, 0], jnp.int32)
    out = np.asarray(perm.cross_pmx(key, p1, p2, 3))
    # some window of length 3 must equal p2's window at the same positions
    found = any(np.array_equal(out[r:r + 3], np.asarray(p2)[r:r + 3])
                for r in range(N - 2))
    assert found and sorted(out.tolist()) == list(range(N))


def test_cx_takes_cycle_from_p2():
    p1 = jnp.array([1, 2, 3, 0, 4, 5, 6, 7], jnp.int32)  # cycle (0 1 2 3)
    p2 = jnp.arange(N, dtype=jnp.int32)
    out = np.asarray(perm.cross_cx(jax.random.PRNGKey(0), p1, p2))
    assert sorted(out.tolist()) == list(range(N))
    # positions on the chosen cycle take p2's values, others keep p1's;
    # since p1 differs from p2 only on the 4-cycle, out is one of the two
    assert (np.array_equal(out, np.asarray(p1)) or
            np.array_equal(out, np.asarray(p2)))


def test_ox1_inserts_p2_window_in_order():
    p1 = jnp.arange(N, dtype=jnp.int32)
    p2 = jnp.array([7, 6, 5, 4, 3, 2, 1, 0], jnp.int32)
    out = np.asarray(perm.cross_ox1(jax.random.PRNGKey(1), p1, p2, 3))
    assert sorted(out.tolist()) == list(range(N))
    # a length-3 descending run from p2 must appear contiguously
    runs = [out[i:i + 3] for i in range(N - 2)]
    assert any((r[0] - 1 == r[1]) and (r[1] - 1 == r[2]) for r in runs)


def test_toposort_batch():
    # item1 requires item0 earlier; item2 requires item1
    dep = jnp.array([[0, 0, 0], [1, 0, 0], [0, 1, 0]], dtype=bool)
    pm = jnp.array([[2, 1, 0], [0, 1, 2], [1, 0, 2]], jnp.int32)
    out = np.asarray(perm.toposort_batch(pm, dep))
    for row in out:
        assert row.tolist() == [0, 1, 2]
    # stability: with no deps, order preserved
    nodep = jnp.zeros((3, 3), bool)
    out2 = np.asarray(perm.toposort_batch(pm, nodep))
    np.testing.assert_array_equal(out2, np.asarray(pm))
