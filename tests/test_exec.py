"""Black-box evaluation plane tests: subprocess measurement, sandboxed
worker pool with timeout kill + dead-worker replacement, and the
ProgramTuner end-to-end loop (the reference's api.py:399-594 +
src/single_stage.py semantics)."""
import json
import os
import sys
import textwrap
import time

import pytest

import uptune_tpu
from uptune_tpu.api import constraint as C
from uptune_tpu.api import session
from uptune_tpu.exec import (ProgramTuner, WorkerPool, call_program,
                             default_config, space_from_params)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    uptune_tpu.__file__)))
ENV = {"PYTHONPATH": REPO}

QUAD_PROG = textwrap.dedent("""
    import uptune_tpu as ut
    x = ut.tune(50, (0, 100), name="x")
    y = ut.tune(50, (0, 100), name="y")
    ut.target(float((x - 37) ** 2 + (y - 11) ** 2), "min")
""")

SLOW_PROG = textwrap.dedent("""
    import time
    import uptune_tpu as ut
    x = ut.tune(80, (0, 100), name="x")
    if x < 50:
        time.sleep(60)          # hangs; must be killed by the pool
    ut.target(float(abs(x - 75)), "min")
""")


@pytest.fixture(autouse=True)
def clean_registry(monkeypatch):
    for v in ("UT_BEFORE_RUN_PROFILE", "UT_TUNE_START", "BEST",
              "UT_WORK_DIR"):
        monkeypatch.delenv(v, raising=False)
    C.REGISTRY.clear()
    session.reset_settings()
    yield


def _write(tmp_path, body, name="prog.py"):
    p = tmp_path / name
    p.write_text(body)
    return str(p)


# ---------------------------------------------------------------------
class TestCallProgram:
    def test_basic_capture(self):
        res = call_program([sys.executable, "-c", "print('hi')"])
        assert res["returncode"] == 0 and res["stdout"].strip() == "hi"
        assert not res["timeout"]

    def test_timeout_kills_process_group(self):
        # child spawns a grandchild; both must die within the limit
        code = ("import subprocess, sys, time; "
                "subprocess.Popen([sys.executable, '-c', "
                "'import time; time.sleep(60)']); time.sleep(60)")
        t0 = time.time()
        res = call_program([sys.executable, "-c", code], limit=1.0)
        assert res["timeout"] and time.time() - t0 < 10

    def test_failure_rc(self):
        res = call_program([sys.executable, "-c", "raise SystemExit(3)"])
        assert res["returncode"] == 3


# ---------------------------------------------------------------------
class TestSpaceIO:
    def test_round_trip(self):
        recs = [
            {"name": "i", "type": "int", "default": 3, "lo": 1, "hi": 9},
            {"name": "f", "type": "float", "default": 0.5, "lo": 0.0,
             "hi": 2.0},
            {"name": "b", "type": "bool", "default": True},
            {"name": "e", "type": "enum", "default": "-O2",
             "options": ["-O1", "-O2", "-O3"]},
            {"name": "p", "type": "perm", "default": [0, 1, 2],
             "items": [0, 1, 2]},
        ]
        space = space_from_params(recs)
        assert len(space) == 5
        dflt = default_config(recs)
        assert dflt == {"i": 3, "f": 0.5, "b": True, "e": "-O2",
                        "p": [0, 1, 2]}
        cands = space.from_configs([dflt])
        cfg = space.to_configs(cands)[0]
        assert cfg["i"] == 3 and cfg["e"] == "-O2"
        assert list(cfg["p"]) == [0, 1, 2]


# ---------------------------------------------------------------------
def _mk_tuner(tmp_path, body, **kw):
    prog = _write(tmp_path, body)
    kw.setdefault("parallel", 2)
    kw.setdefault("env", ENV)
    kw.setdefault("runtime_limit", 30.0)
    return ProgramTuner([sys.executable, prog], str(tmp_path), **kw)


class TestProgramTuner:
    def test_analysis_discovers_space(self, tmp_path):
        pt = _mk_tuner(tmp_path, QUAD_PROG)
        params = pt.analyze()
        assert [r["name"] for r in params[0]] == ["x", "y"]
        assert pt.sense == "min"
        # default (50,50): (13)^2 + (39)^2
        assert pt.default_qor == 13 ** 2 + 39 ** 2

    @pytest.mark.slow   # suite-budget (ISSUE 8): the driver e2e is
    # also covered tier-1 by test_store's full `ut` CLI strict-guard
    # run (superset: CLI + store + trace) and this class's faster
    # constraint/budget/timeout/prefetch cases
    def test_end_to_end_tunes_and_persists_best(self, tmp_path):
        pt = _mk_tuner(tmp_path, QUAD_PROG, test_limit=40, seed=1)
        res = pt.run()
        assert res.evals >= 40
        # must improve on the default config's QoR
        assert res.best_qor < 13 ** 2 + 39 ** 2
        assert 0 <= res.best_config["x"] <= 100
        # best.json round trip
        cfg, qor = uptune_tpu.get_best(str(tmp_path))
        assert qor == res.best_qor
        # archive carries technique attribution incl. the seed trial
        rows = [json.loads(l) for l in
                open(tmp_path / "ut.archive.jsonl")][1:]
        assert rows[0]["tech"] == "seed"
        assert all("tech" in r for r in rows)
        assert len({r["tech"] for r in rows}) >= 1

    def test_budget_not_overrun_by_wide_tickets(self, tmp_path):
        """--test-limit N must launch ~N trials even while a whole
        technique batch (e.g. a 30-member DE population) is in flight:
        round-2 regression — the evals-based gate only advanced when a
        full ticket resolved, so limit=25 ran 50+ subprocesses."""
        pt = _mk_tuner(tmp_path, QUAD_PROG, test_limit=10, seed=2)
        res = pt.run()
        assert res.evals <= 10 + pt.parallel, res.evals
        assert pt.pool.launched <= 10 + pt.parallel

    def test_timeout_kill_and_worker_replacement(self, tmp_path):
        # 24 trials over a space where ~half hang: the budget is now
        # enforced per-trial (told-gated), so the limit must be wide
        # enough that some x < 50 trial is actually launched
        pt = _mk_tuner(tmp_path, SLOW_PROG, test_limit=24, seed=3,
                       runtime_limit=1.0)
        t0 = time.time()
        res = pt.run()
        took = time.time() - t0
        # some trials (x < 50) hung and were killed + replaced
        assert pt.pool.replaced >= 1
        assert res.evals >= 8
        assert took < 120
        # the survivors still tuned toward x=75
        assert res.best_qor <= abs(80 - 75)  # at least the default

    @pytest.mark.slow
    def test_rules_restrict_search_space(self, tmp_path):
        """Slow-marked (ISSUE 7 suite-budget reclaim: ~12s of
        subprocess builds); the driver-level filter mechanics keep the
        fast in-process sibling below, and the registry logic stays
        tier-1 in test_api::test_rules_and_constraints_enforced."""
        @uptune_tpu.rule()
        def x_small(cfg):
            return cfg["x"] <= 20

        pt = _mk_tuner(tmp_path, QUAD_PROG, test_limit=20, seed=5)
        res = pt.run()
        rows = [json.loads(l) for l in
                open(tmp_path / "ut.archive.jsonl")][1:]
        evaluated = [r for r in rows if r["tech"] != "seed"]
        assert evaluated and all(r["cfg"]["x"] <= 20 for r in evaluated)
        assert pt.tuner.filtered_total > 0

    def test_config_filter_restricts_library_tuner(self):
        """Fast sibling of the e2e rule test above: the SAME
        config_filter path (_open_ticket drops rejected rows before
        they become trials; filtered_total counts them) on an
        in-process Tuner — no subprocesses."""
        from uptune_tpu.driver import Tuner
        from uptune_tpu.exec.space_io import space_from_params
        space = space_from_params(
            [{"name": "x", "type": "int", "default": 50,
              "lo": 0, "hi": 100}])
        t = Tuner(space, lambda cfgs: [abs(c["x"] - 10.0)
                                       for c in cfgs],
                  seed=5, config_filter=lambda c: c["x"] <= 20)
        res = t.run(test_limit=30)
        assert t.filtered_total > 0
        assert res.evals > 0
        assert res.best_config["x"] <= 20

    def test_constraint_marks_violations_failed(self, tmp_path):
        @uptune_tpu.constraint()
        def qor_cap(qor, cfg):
            return qor < 500.0

        pt = _mk_tuner(tmp_path, QUAD_PROG, test_limit=20, seed=7)
        res = pt.run()
        assert res.best_qor < 500.0

    def test_custom_model_proposals_are_injected(self, tmp_path):
        @uptune_tpu.model("oracle")
        def oracle(history, space):
            return {"x": 37, "y": 11}   # the optimum

        pt = _mk_tuner(tmp_path, QUAD_PROG, test_limit=12, seed=9)
        res = pt.run()
        assert res.best_qor == 0.0
        rows = [json.loads(l) for l in
                open(tmp_path / "ut.archive.jsonl")][1:]
        assert any(r["tech"] == "oracle" for r in rows)

    def test_prefetch_overlaps_and_keeps_budget(self, tmp_path):
        """Async ticket prefetch (default: one pool width of lookahead)
        must keep the per-trial budget exact and record driver-plane
        timing; speculative cancels after a new best are bounded by
        what was queued."""
        pt = _mk_tuner(tmp_path, QUAD_PROG, test_limit=12, seed=11)
        assert pt.prefetch == pt.parallel  # default depth
        res = pt.run()
        assert res.evals <= 12 + pt.parallel
        assert pt.pool.launched <= 12 + pt.parallel
        # the tuner measured its own plane: propose happened, and
        # tickets spent wall-clock waiting on subprocess builds
        assert res.t_propose > 0.0
        assert res.t_eval_wait > 0.0
        assert pt.spec_cancelled >= 0
        assert 0.0 < pt.pool.utilization() <= 1.0
        # cancelled speculative trials never reach the archive
        rows = [json.loads(l) for l in
                open(tmp_path / "ut.archive.jsonl")][1:]
        assert len(rows) == res.evals

    def test_prefetch_zero_is_lockstep(self, tmp_path):
        """prefetch=0 restores propose-only-when-a-slot-is-free."""
        pt = _mk_tuner(tmp_path, QUAD_PROG, test_limit=8, seed=13,
                       prefetch=0)
        res = pt.run()
        assert res.evals <= 8 + pt.parallel
        assert pt.spec_cancelled == 0  # nothing speculative to cancel
        assert res.best_qor < 13 ** 2 + 39 ** 2

    def test_params_reuse_skips_analysis(self, tmp_path):
        prog = _write(tmp_path, QUAD_PROG)
        with open(tmp_path / "ut.params.json", "w") as f:
            json.dump([[{"name": "x", "type": "int", "default": 50,
                         "lo": 0, "hi": 100},
                        {"name": "y", "type": "int", "default": 50,
                         "lo": 0, "hi": 100}]], f)
        pt = ProgramTuner([sys.executable, prog], str(tmp_path),
                          parallel=2, env=ENV, runtime_limit=30.0)
        params = pt.analyze()   # must NOT re-run the program
        assert params[0][0]["name"] == "x"
        assert pt.default_qor is None  # no profiling run happened


# ---------------------------------------------------------------------
class TestWorkerPoolSandbox:
    def test_sandboxes_isolate_and_symlink(self, tmp_path):
        _write(tmp_path, QUAD_PROG)
        (tmp_path / "data.txt").write_text("shared")
        with open(tmp_path / "ut.params.json", "w") as f:
            json.dump([[{"name": "x", "type": "int", "default": 1,
                         "lo": 0, "hi": 9}]], f)
        pool = WorkerPool("true", str(tmp_path), 2)
        pool.start()
        for i in range(2):
            sb = tmp_path / "ut.temp" / f"temp.{i}"
            assert (sb / "prog.py").is_symlink()
            assert (sb / "data.txt").read_text() == "shared"
            # params copied, not symlinked: per-sandbox protocol state
            assert (sb / "ut.params.json").is_file()
            assert not (sb / "ut.params.json").is_symlink()
        pool.shutdown()
