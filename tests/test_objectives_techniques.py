"""Composite objectives + the round-2 technique-registry additions
(BanditMutation, ComposableDE, generate_bandit_technique)."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")

from uptune_tpu.driver.objectives import (MaximizeAccuracy,  # noqa: E402
                                          MaximizeAccuracyMinimizeSize,
                                          MinimizeTime,
                                          ThresholdAccuracyMinimizeTime,
                                          get_objective)
from uptune_tpu.space.params import (FloatParam, IntParam,  # noqa: E402
                                     PermParam)
from uptune_tpu.space.spec import Space  # noqa: E402
from uptune_tpu.techniques.banditmutation import (  # noqa: E402
    BanditMutation, ComposableDE, generate_bandit_technique)
from uptune_tpu.techniques.base import (all_technique_names,  # noqa: E402
                                        get_technique)


def _space(with_perm=False):
    specs = [FloatParam(f"x{i}", -2.0, 2.0) for i in range(4)]
    specs.append(IntParam("n", 0, 20))
    if with_perm:
        specs.append(PermParam("p", tuple(range(8))))
    return Space(specs)


class TestObjectives:
    def test_minimize_time_order(self):
        o = MinimizeTime()
        assert o({"time": 1.0}) < o({"time": 2.0})

    def test_maximize_accuracy_order(self):
        o = MaximizeAccuracy()
        assert o({"accuracy": 0.9}) < o({"accuracy": 0.5})

    def test_acc_dominates_size(self):
        o = MaximizeAccuracyMinimizeSize()
        hi_acc_big = o({"accuracy": 0.9, "size": 5000.0})
        lo_acc_small = o({"accuracy": 0.8, "size": 1.0})
        assert hi_acc_big < lo_acc_small

    def test_size_breaks_accuracy_ties(self):
        o = MaximizeAccuracyMinimizeSize()
        assert o({"accuracy": 0.9, "size": 10.0}) < \
            o({"accuracy": 0.9, "size": 20.0})

    def test_threshold_partitions(self):
        o = ThresholdAccuracyMinimizeTime(target=0.95)
        above_slow = o({"accuracy": 0.96, "time": 1e5})
        below_fast = o({"accuracy": 0.94, "time": 0.001})
        assert above_slow < below_fast
        # above threshold: pure time order
        assert o({"accuracy": 0.99, "time": 1.0}) < \
            o({"accuracy": 0.95, "time": 2.0})
        # below threshold: closer to target is better
        assert o({"accuracy": 0.94, "time": 1.0}) < \
            o({"accuracy": 0.5, "time": 1.0})

    def test_nonfinite_is_inf(self):
        assert MinimizeTime()({"time": float("nan")}) == float("inf")
        # composites must rank ANY non-finite metric as failure too
        o = MaximizeAccuracyMinimizeSize()
        assert o({"accuracy": float("nan"), "size": 1.0}) == float("inf")
        assert o({"accuracy": float("inf"), "size": 1.0}) == float("inf")
        t = ThresholdAccuracyMinimizeTime(target=0.9)
        assert t({"accuracy": 0.99, "time": float("nan")}) == float("inf")

    def test_get_objective(self):
        o = get_objective("ThresholdAccuracyMinimizeTime", target=0.9)
        assert isinstance(o, ThresholdAccuracyMinimizeTime)
        with pytest.raises(KeyError):
            get_objective("Nope")

    def test_missing_metric_message(self):
        with pytest.raises(KeyError, match="accuracy"):
            MaximizeAccuracy()({"time": 1.0})


class TestRegistryAdditions:
    def test_registered(self):
        names = all_technique_names()
        for n in ("AUCBanditMutationTechnique", "ComposableDiffEvolution",
                  "ComposableDiffEvolutionCX"):
            assert n in names, n

    def test_bandit_mutation_converges_on_sphere(self):
        from uptune_tpu.driver.driver import Tuner
        space = _space()

        def obj(cfgs):
            return [sum(c[f"x{i}"] ** 2 for i in range(4)) + 0.01 * c["n"]
                    for c in cfgs]

        t = Tuner(space, obj, technique="AUCBanditMutationTechnique",
                  seed=0)
        res = t.run(test_limit=800)
        t.close()
        assert res.best_qor < 0.3, res.best_qor

    def test_bandit_mutation_credit_moves(self):
        space = _space()
        bm = BanditMutation(batch=16)
        key = jax.random.PRNGKey(0)
        st = bm.init_state(space, key)
        from uptune_tpu.techniques.base import Best
        best = Best.empty(space)
        st, cands = jax.jit(
            lambda s, k, b: bm.propose(space, s, k, b))(st, key, best)
        assert cands.batch == 16
        qor = jax.numpy.linspace(0.0, 1.0, 16)
        best = best.update(cands, qor)
        st2 = jax.jit(
            lambda s, c, q, b: bm.observe(space, s, c, q, b))(
            st, cands, qor, best)
        assert not np.allclose(np.asarray(st2.credit),
                               np.asarray(st.credit))

    def test_composable_de_perm_validity(self):
        space = _space(with_perm=True)
        t = ComposableDE("CX")
        key = jax.random.PRNGKey(1)
        st = t.init_state(space, key)
        from uptune_tpu.techniques.base import Best
        best = Best.empty(space)
        for i in range(3):
            key, k = jax.random.split(key)
            st, cands = t.propose(space, st, k, best)
            p = np.asarray(cands.perms[0])
            assert (np.sort(p, 1) == np.arange(8)).all()
            qor = jax.numpy.asarray(
                np.random.RandomState(i).rand(cands.batch), dtype="float32")
            best = best.update(cands, qor)
            st = t.observe(space, st, cands, qor, best)

    def test_generate_bandit_deterministic(self):
        a = generate_bandit_technique(7)
        b = generate_bandit_technique(7)
        assert [t.name for t in a.techniques] == \
            [t.name for t in b.techniques]
        c = generate_bandit_technique(8)
        assert [t.name for t in a.techniques] != \
            [t.name for t in c.techniques] or len(a.techniques) != \
            len(c.techniques)

    @pytest.mark.slow
    def test_generated_portfolio_tunes(self):
        """Slow-marked for suite-budget headroom (ISSUE 10, ~12 s —
        a 400-trial generated-portfolio tune): generation validity
        keeps tier-1 coverage via test_generate_bandit_deterministic,
        the bandit-mutation convergence/credit tests, and the
        composable-operator tests in this file."""
        from uptune_tpu.driver.driver import Tuner
        space = _space()

        def obj(cfgs):
            return [sum(c[f"x{i}"] ** 2 for i in range(4)) for c in cfgs]

        t = Tuner(space, obj, technique=generate_bandit_technique(3),
                  seed=1)
        res = t.run(test_limit=400)
        t.close()
        assert res.best_qor < 1.0
