"""ut-lint: fixture-proven true positives/negatives per rule, the
suppression syntax, reporters, the trace guard, and the repo-clean gate
that wires `scripts/lint.sh` into tier-1.

Fixture snippets are linted as strings (lint_source) — no files, no
jax import on the static side.  The trace-guard tests run real jit
under the CPU platform forced by conftest.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from uptune_tpu.analysis import lint_source
from uptune_tpu.analysis.reporters import format_json, format_sarif, \
    format_text

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fixture(src):
    """Dedent a triple-quoted fixture and drop its leading blank line
    so asserted line numbers match what the snippet reads like."""
    return textwrap.dedent(src).lstrip("\n")


def active(src, rule=None):
    """Non-suppressed findings for a dedented fixture snippet."""
    fs = lint_source("fixture.py", fixture(src))
    assert not any(f.rule == "E000" for f in fs), \
        f"fixture failed to parse: {fs}"
    fs = [f for f in fs if not f.suppressed]
    if rule is not None:
        fs = [f for f in fs if f.rule == rule]
    return fs


def suppressed(src, rule):
    fs = lint_source("fixture.py", fixture(src))
    return [f for f in fs if f.suppressed and f.rule == rule]


# ---------------------------------------------------------------- R001
class TestHostSync:
    def test_positive_float_cast(self):
        fs = active("""
            import jax

            @jax.jit
            def f(x):
                return float(x) + 1.0
        """, "R001")
        assert len(fs) == 1 and fs[0].line == 5

    def test_positive_item_in_scan_body(self):
        fs = active("""
            import jax

            def outer(xs):
                def body(carry, x):
                    return carry + x.item(), None
                return jax.lax.scan(body, 0.0, xs)
        """, "R001")
        assert len(fs) == 1

    def test_positive_np_asarray(self):
        fs = active("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(x).sum()
        """, "R001")
        assert len(fs) == 1

    def test_negative_static_math_and_host_fn(self):
        # float() on a closure constant under jit, and float() on a
        # traced-looking value in a NON-jitted function: both fine
        fs = active("""
            import jax
            import numpy as np

            D = 16

            @jax.jit
            def f(x):
                scale = float(np.log2(D))
                return x * scale

            def report(x):
                return float(x)
        """, "R001")
        assert fs == []

    def test_negative_shape_metadata(self):
        fs = active("""
            import jax

            @jax.jit
            def f(x):
                return x * float(x.shape[0])
        """, "R001")
        assert fs == []

    def test_suppressed(self):
        src = """
            import jax

            @jax.jit
            def f(x):
                return float(x)  # ut-lint: disable=R001
        """
        assert active(src, "R001") == []
        assert len(suppressed(src, "R001")) == 1


# ---------------------------------------------------------------- R002
class TestKeyReuse:
    def test_positive_straight_line(self):
        fs = active("""
            import jax

            def f(key):
                a = jax.random.uniform(key, (3,))
                b = jax.random.normal(key, (3,))
                return a + b
        """, "R002")
        assert len(fs) == 1 and fs[0].line == 5

    def test_positive_loop_without_split(self):
        fs = active("""
            import jax

            def f(key):
                out = []
                for _ in range(4):
                    out.append(jax.random.uniform(key, (2,)))
                return out
        """, "R002")
        assert len(fs) == 1

    def test_positive_inline_prngkey(self):
        fs = active("""
            import jax

            def f():
                return jax.random.uniform(jax.random.PRNGKey(0), (2,))
        """, "R002")
        assert len(fs) == 1

    def test_negative_split_idiom(self):
        fs = active("""
            import jax

            def f(key):
                key, k1 = jax.random.split(key)
                a = jax.random.uniform(k1, (3,))
                key, k2 = jax.random.split(key)
                return a + jax.random.normal(k2, (3,))
        """, "R002")
        assert fs == []

    def test_negative_branches_are_exclusive(self):
        fs = active("""
            import jax

            def f(key, flag):
                if flag:
                    return jax.random.uniform(key, (2,))
                else:
                    return jax.random.normal(key, (2,))
        """, "R002")
        assert fs == []

    def test_positive_comprehension_reuse(self):
        # same hazard as the for-loop form: every comprehension
        # iteration replays the same key
        fs = active("""
            import jax

            def f(key):
                return [jax.random.uniform(key, (2,))
                        for _ in range(3)]
        """, "R002")
        assert len(fs) == 1

    def test_negative_split_in_comprehension(self):
        # the standard idiom: each iteration binds a FRESH child key
        fs = active("""
            import jax

            def f(key):
                return [jax.random.uniform(k, (2,))
                        for k in jax.random.split(key, 3)]
        """, "R002")
        assert fs == []

    def test_negative_fold_in_loop(self):
        fs = active("""
            import jax

            def f(key):
                return [jax.random.uniform(jax.random.fold_in(key, i),
                                           (2,))
                        for i in range(3)]
        """, "R002")
        # fold_in derives decorrelated streams; the inline consumer is
        # fold_in's RESULT, not a constant PRNGKey
        assert fs == []

    def test_negative_self_attr_rebind_in_loop(self):
        fs = active("""
            import jax

            class T:
                def f(self):
                    ks = []
                    for _ in range(3):
                        self.key, k = jax.random.split(self.key)
                        ks.append(jax.random.uniform(k, (2,)))
                    return ks
        """, "R002")
        assert fs == []

    def test_suppressed(self):
        src = """
            import jax

            def f():
                k = 0
                # ut-lint: disable-next=R002
                return jax.random.uniform(jax.random.PRNGKey(0), (2,))
        """
        assert active(src, "R002") == []
        assert len(suppressed(src, "R002")) == 1


# ---------------------------------------------------------------- R003
class TestTracedControlFlow:
    def test_positive_if_on_param(self):
        fs = active("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """, "R003")
        assert len(fs) == 1 and fs[0].line == 5

    def test_positive_while_on_jnp(self):
        fs = active("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                while jnp.any(x > 0):
                    x = x - 1
                return x
        """, "R003")
        assert len(fs) == 1

    def test_negative_none_check_and_shape(self):
        fs = active("""
            import jax

            @jax.jit
            def f(x, eval_fn=None):
                if eval_fn is None:
                    x = x * 2
                while x.ndim < 4:
                    x = x[None]
                return x
        """, "R003")
        assert fs == []

    def test_negative_static_config(self):
        fs = active("""
            import jax

            class T:
                def __init__(self, dedup):
                    self.dedup = dedup

                def step(self, state):
                    def body(s, _):
                        if self.dedup:
                            s = s + 1
                        return s, None
                    return jax.lax.scan(body, state, None, length=3)
        """, "R003")
        assert fs == []

    def test_suppressed(self):
        src = """
            import jax

            @jax.jit
            def f(x):
                if x > 0:  # ut-lint: disable=R003
                    return x
                return -x
        """
        assert active(src, "R003") == []
        assert len(suppressed(src, "R003")) == 1


# ---------------------------------------------------------------- R004
class TestSideEffects:
    def test_positive_print(self):
        fs = active("""
            import jax

            @jax.jit
            def f(x):
                print(x)
                return x
        """, "R004")
        assert len(fs) == 1

    def test_positive_global_and_open(self):
        fs = active("""
            import jax

            @jax.jit
            def f(x):
                global COUNT
                COUNT = COUNT + 1
                with open("log.txt", "a") as fh:
                    fh.write("step")
                return x
        """, "R004")
        assert len(fs) == 2

    def test_negative_host_side_print(self):
        fs = active("""
            import jax

            @jax.jit
            def f(x):
                return x * 2

            def drive(x):
                y = f(x)
                print(y)
                return y
        """, "R004")
        assert fs == []

    def test_suppressed(self):
        src = """
            import jax

            @jax.jit
            def f(x):
                print("tracing f")  # ut-lint: disable=R004
                return x
        """
        assert active(src, "R004") == []
        assert len(suppressed(src, "R004")) == 1


# ---------------------------------------------------------------- R005
class TestRetraceChurn:
    def test_positive_jit_in_loop(self):
        fs = active("""
            import jax

            def f(xs):
                out = []
                for x in xs:
                    g = jax.jit(lambda v: v + 1)
                    out.append(g(x))
                return out
        """, "R005")
        assert len(fs) == 1

    def test_positive_immediate_invocation(self):
        fs = active("""
            import jax

            def f(x):
                return jax.jit(lambda v: v * 2)(x)
        """, "R005")
        assert len(fs) == 1

    def test_negative_parameterized_decorator(self):
        # `@jax.jit(donate_argnums=0)` is definition-time jitting, not
        # wrapper churn
        fs = active("""
            import jax

            @jax.jit(donate_argnums=0)
            def f(x):
                return x * 2

            def outer(xs):
                @jax.jit(donate_argnums=0)
                def g(x):
                    return x + 1
                return [g(x) for x in xs]
        """, "R005")
        assert fs == []

    def test_negative_module_level_and_keyed_cache(self):
        fs = active("""
            import jax

            def _impl(v):
                return v + 1

            g = jax.jit(_impl)

            class T:
                def __init__(self, fns):
                    self._jit = {}
                    for name, fn in fns.items():
                        self._jit[name] = jax.jit(fn)
        """, "R005")
        assert fs == []

    def test_suppressed(self):
        src = """
            import jax

            def f(x):
                return jax.jit(lambda v: v * 2)(x)  # ut-lint: disable=R005
        """
        assert active(src, "R005") == []
        assert len(suppressed(src, "R005")) == 1


# ------------------------------------------------------------ engine
class TestEngine:
    def test_disable_all(self):
        fs = active("""
            import jax

            @jax.jit
            def f(x):
                print(float(x))  # ut-lint: disable=all
                return x
        """)
        assert fs == []

    def test_syntax_error_is_reported_not_raised(self):
        fs = lint_source("broken.py", "def f(:\n")
        assert len(fs) == 1 and fs[0].rule == "E000"

    def test_reporters(self):
        fs = lint_source("fixture.py", fixture("""
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """))
        txt = format_text(fs)
        assert "R001" in txt and "fixture.py:5" in txt
        doc = json.loads(format_json(fs))
        assert doc["summary"]["total"] == 1
        assert doc["findings"][0]["rule"] == "R001"
        sarif = json.loads(format_sarif(fs))
        assert sarif["version"] == "2.1.0"
        run = sarif["runs"][0]
        assert run["results"][0]["ruleId"] == "R001"
        ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert {"R001", "R002", "R003", "R004", "R005"} <= ids

    def test_identical_findings_get_distinct_fingerprints(self):
        # a NEW hazard textually identical to a baselined one must NOT
        # inherit its fingerprint (it would be silently grandfathered)
        fs = active("""
            import jax

            @jax.jit
            def f(x):
                return float(x)

            @jax.jit
            def g(x):
                return float(x)
        """, "R001")
        assert len(fs) == 2
        assert fs[0].fingerprint() != fs[1].fingerprint()

    def test_cli_baseline_grandfathers(self, tmp_path):
        bad = tmp_path / "mod.py"
        bad.write_text(textwrap.dedent("""
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """))
        base = tmp_path / "baseline.json"
        env = {**os.environ, "PYTHONPATH": REPO}
        common = [sys.executable, "-m", "uptune_tpu.analysis", str(bad)]
        r = subprocess.run(common, capture_output=True, text=True,
                           env=env, cwd=str(tmp_path))
        assert r.returncode == 1, r.stdout + r.stderr
        r = subprocess.run(common + ["--write-baseline", str(base)],
                           capture_output=True, text=True, env=env,
                           cwd=str(tmp_path))
        assert r.returncode == 0, r.stdout + r.stderr
        r = subprocess.run(common + ["--baseline", str(base)],
                           capture_output=True, text=True, env=env,
                           cwd=str(tmp_path))
        assert r.returncode == 0, r.stdout + r.stderr

    def test_parse_errors_are_never_grandfathered(self, tmp_path):
        bad = tmp_path / "broken.py"
        bad.write_text("def f(:\n")
        base = tmp_path / "baseline.json"
        env = {**os.environ, "PYTHONPATH": REPO}
        common = [sys.executable, "-m", "uptune_tpu.analysis", str(bad)]
        r = subprocess.run(common + ["--write-baseline", str(base)],
                           capture_output=True, text=True, env=env,
                           cwd=str(tmp_path))
        assert "refusing to baseline" in r.stderr
        assert json.loads(base.read_text())["fingerprints"] == []
        r = subprocess.run(common + ["--baseline", str(base)],
                           capture_output=True, text=True, env=env,
                           cwd=str(tmp_path))
        assert r.returncode == 1, r.stdout + r.stderr
        assert "E000" in r.stdout


# ------------------------------------------------------- trace guard
class TestTraceGuard:
    def test_counts_retraces_and_warns(self):
        import jax
        import jax.numpy as jnp

        from uptune_tpu.analysis import TraceGuard
        with pytest.warns(RuntimeWarning, match="unexpected recompile"):
            with TraceGuard(limit=1) as tg:
                @jax.jit
                def f(x):
                    return x * 2.0
                f(jnp.ones((3,)))
                f(jnp.ones((3,)))    # cache hit: no new trace
                f(jnp.ones((4,)))    # new shape: retrace
        label = next(iter(tg.counts))
        assert tg.counts[label] == 2
        assert tg.excess() == {label: 2}
        assert tg.report()["limit"] == 1

    def test_within_budget_is_silent(self):
        import jax
        import jax.numpy as jnp
        import warnings

        from uptune_tpu.analysis import TraceGuard
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            with TraceGuard(limit=1) as tg:
                @jax.jit
                def f(x):
                    return x + 1.0
                f(jnp.ones((3,)))
                f(jnp.ones((3,)))
        assert list(tg.counts.values()) == [1]

    def test_detects_rebuilt_wrapper_churn(self):
        # every wrapper traces once, but rebuilding one per call is a
        # fresh compile each time — the R005 hazard, caught dynamically
        import jax
        import jax.numpy as jnp

        from uptune_tpu.analysis import TraceGuard
        with pytest.warns(RuntimeWarning, match="rebuilt after trace"):
            with TraceGuard(limit=1) as tg:
                def impl(x):
                    return x * 2.0
                for _ in range(4):
                    jax.jit(impl)(jnp.ones((2,)))
        rb = tg.report()["rebuilds"]
        assert list(rb.values()) == [3]
        assert all(v == 1 for v in tg.counts.values())

    def test_wrapper_fleet_built_upfront_is_clean(self):
        # N wrappers from one code object, all built BEFORE anything
        # runs (the driver's per-technique jit loop): not churn
        import jax
        import jax.numpy as jnp
        import warnings

        from uptune_tpu.analysis import TraceGuard
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            with TraceGuard(limit=1) as tg:
                fns = [jax.jit(lambda x, s=float(i): x * s)
                       for i in range(4)]
                for fn in fns:
                    fn(jnp.ones((2,)))
        assert tg.rebuilds == {}
        assert all(v == 1 for v in tg.counts.values())

    def test_strict_raises_and_restores_jit(self):
        import jax
        import jax.numpy as jnp

        from uptune_tpu.analysis import RetraceError, TraceGuard
        orig = jax.jit
        with pytest.raises(RetraceError):
            with TraceGuard(limit=0, strict=True):
                @jax.jit
                def f(x):
                    return x - 1.0
                f(jnp.ones((2,)))
        assert jax.jit is orig


# ------------------------------------------------------- repo gate
@pytest.mark.parametrize("package", ["store", "surrogate", "engine",
                                     "ops", "obs", "serve"])
def test_package_suppression_free(package):
    """Packages on the correctness-critical fast path must be finding-
    AND suppression-free: no '# ut-lint: disable' escape hatch, no
    baseline.  store/ decides whether a build is SKIPPED (cache
    correctness, ISSUE 4) and since ISSUE 18 carries the cooperative
    search fabric — server.py, whose ack-after-durable append IS the
    zero-acked-loss contract, and remote.py, whose write-behind
    flusher sits on every cooperating tuner's tell path; surrogate/
    now runs a concurrent background
    refit thread (ISSUE 5) — a silenced host-sync or retrace hazard
    there would hide a stall on the very path this PR moved off the
    driver; engine/ and ops/ carry the fused/batched acquisition loop
    and its Pallas kernels (ISSUE 6; since ISSUE 19 ops/acquire.py
    fuses surrogate score + acquisition + top-k into one device
    program on the propose path, routed by ops/routing.py's UT_PALLAS
    knob) — a silenced hazard there would
    invalidate every BENCH_* headline measured through them; obs/ is
    instrumentation living INSIDE every hot path (ISSUE 7; the
    ISSUE 10 distributed-obs modules — sidecar, flight recorder,
    merge, top — the ISSUE 12 search-quality modules — journal,
    quality, report — the ISSUE 13 device-telemetry module —
    device.py, wrapping every engine/driver device program — and the
    ISSUE 14 fleet-telemetry modules — ship.py, whose offer() sits on
    the driver/serve hot paths, and hub.py, the collector every
    process reports into — live in the same package and inherit the
    rule)
    — a silenced hazard there would tax or skew the measurements it
    exists to make, and the ISSUE 15 fault-injection registry
    faults.py sits permanently inside the wire/checkpoint/store/pool
    seams; serve/ multiplexes every tenant onto three shared
    compiled programs (ISSUE 8) — a silenced retrace or host-sync
    hazard there stalls ALL sessions at once, since ISSUE 14 its
    wire.py service kernel carries EVERY wire-speaking plane (session
    server + telemetry hub) — rebuilt in ISSUE 17 as one asyncio
    event loop over a bounded worker pool, where a lock held across a
    blocking call stalls the whole connection plane — since ISSUE 15
    its durable.py write-ahead checkpoint plane carries the
    zero-committed-loss contract, and since ISSUE 17 its router.py
    sharded front tier (supervisor thread + session map) fronts every
    shard.  lint.sh enforces the same in the
    pre-commit gate."""
    r = subprocess.run(
        [sys.executable, "-m", "uptune_tpu.analysis",
         os.path.join(REPO, "uptune_tpu", package),
         "--format", "json", "--show-suppressed"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "PYTHONPATH": REPO})
    doc = json.loads(r.stdout)
    assert doc["findings"] == [], doc["findings"]
    assert r.returncode == 0, r.stdout + r.stderr


def test_repo_clean():
    """scripts/lint.sh (the pre-commit gate) must pass on the tree:
    zero non-suppressed ut-lint findings in uptune_tpu/."""
    r = subprocess.run(["bash", os.path.join(REPO, "scripts", "lint.sh")],
                       capture_output=True, text=True, cwd=REPO,
                       env={**os.environ, "PYTHONPATH": REPO,
                            "PYTHON": sys.executable})
    assert r.returncode == 0, (
        f"ut-lint found new hazards:\n{r.stdout}\n{r.stderr}")
