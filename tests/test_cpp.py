"""C++ client tests: compile the header-only client with g++ and (a) run
its self-contained unit-test binary, (b) tune the demo workload
end-to-end through the subprocess evaluation plane — the test the
reference never had (its C++ API was an unfinished skeleton,
/root/reference/src/uptune.h:14-47, with only a default-mode assertion,
tests/cpp/test_basic.cc:5-8)."""
import os
import shutil
import subprocess
import sys

import pytest

import uptune_tpu
from uptune_tpu.api import constraint as C
from uptune_tpu.api import session
from uptune_tpu.exec import ProgramTuner

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    uptune_tpu.__file__)))
CPP = os.path.join(REPO, "cpp")

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no g++ in environment")


@pytest.fixture(autouse=True)
def clean_registry(monkeypatch):
    for v in ("UT_BEFORE_RUN_PROFILE", "UT_TUNE_START", "BEST",
              "UT_WORK_DIR"):
        monkeypatch.delenv(v, raising=False)
    C.REGISTRY.clear()
    session.reset_settings()
    yield


def _compile(src: str, out: str) -> str:
    subprocess.run(
        ["g++", "-std=c++11", "-O2", "-Wall", "-Wextra", "-Werror",
         "-I", os.path.join(CPP, "include"), "-o", out, src],
        check=True, capture_output=True, text=True)
    return out


@pytest.fixture(scope="module")
def binaries(tmp_path_factory):
    d = tmp_path_factory.mktemp("cppbin")
    return {
        "tests": _compile(os.path.join(CPP, "tests", "test_client.cc"),
                          str(d / "uptune_tests")),
        "demo": _compile(os.path.join(CPP, "demo", "demo_tune.cc"),
                         str(d / "demo_tune")),
    }


def test_unit_suite(binaries, tmp_path):
    res = subprocess.run([binaries["tests"]], capture_output=True,
                         text=True, cwd=str(tmp_path), timeout=60)
    assert res.returncode == 0, res.stderr
    assert "all phases passed" in res.stdout


def test_demo_default_mode(binaries, tmp_path):
    res = subprocess.run([binaries["demo"]], capture_output=True,
                         text=True, cwd=str(tmp_path), timeout=60)
    assert res.returncode == 0
    assert "block=16" in res.stdout and "cost=7.4" in res.stdout


@pytest.mark.slow   # suite-budget (ISSUE 8): the 60-trial tuned run;
# the C++ unit suite + default-mode demo stay tier-1
def test_demo_tuned_end_to_end(binaries, tmp_path):
    """Analysis discovers the 4-param space from the binary; 60 trials
    across 2 workers must beat the default cost (7.4) decisively."""
    work = tmp_path / "w"
    work.mkdir()
    pt = ProgramTuner([binaries["demo"]], str(work), parallel=2,
                      test_limit=60, runtime_limit=30.0, seed=3)
    params = pt.analyze()
    assert [r["name"] for r in params[0]] == [
        "block", "alpha", "unroll", "opt"]
    assert pt.default_qor == pytest.approx(7.4)
    res = pt.run()
    assert res.evals >= 40
    assert res.best_qor < 3.0          # default is 7.4; optimum is 0
    assert set(res.best_config) == {"block", "alpha", "unroll", "opt"}
    # best.json applies back through the C++ BEST mode
    env = dict(os.environ, BEST="True", UT_WORK_DIR=str(work))
    out = subprocess.run([binaries["demo"]], capture_output=True,
                         text=True, env=env, cwd=str(work), timeout=60)
    assert out.returncode == 0
    blk = int(out.stdout.split("block=")[1].split()[0])
    assert blk == res.best_config["block"]
