"""Concurrency pass: fixture-proven positives/negatives/suppressions
for R101-R106, the LockGuard runtime sanitizer, the --changed CLI, and
the repo-wide concurrency-clean gate.

Static fixtures lint as strings (lint_source) — no files, no jax.  The
LockGuard tests run real threads but never import jax; the strict
smoke drives the actual jax-free serving/store primitives (checkpoint
log, result store, wire server) under an installed strict guard.
"""
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from uptune_tpu.analysis import lint_source
from uptune_tpu.analysis.lock_guard import (LockGuard, LockOrderError,
                                            lock_guard_from_env)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fixture(src):
    return textwrap.dedent(src).lstrip("\n")


def active(src, rule=None):
    fs = lint_source("fixture.py", fixture(src))
    assert not any(f.rule == "E000" for f in fs), \
        f"fixture failed to parse: {fs}"
    fs = [f for f in fs if not f.suppressed]
    if rule is not None:
        fs = [f for f in fs if f.rule == rule]
    return fs


def suppressed(src, rule):
    fs = lint_source("fixture.py", fixture(src))
    return [f for f in fs if f.suppressed and f.rule == rule]


# ---------------------------------------------------------------- R101
class TestLockOrderInversion:
    def test_positive_both_sites_flagged(self):
        fs = active("""
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            return 1

                def two(self):
                    with self._b:
                        with self._a:
                            return 2
        """, "R101")
        # one finding per direction's nesting site
        assert len(fs) == 2
        assert {f.line for f in fs} == {10, 15}
        assert all("inversion" in f.message for f in fs)

    def test_negative_consistent_order(self):
        fs = active("""
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            return 1

                def two(self):
                    with self._a:
                        with self._b:
                            return 2
        """, "R101")
        assert fs == []

    def test_suppressed(self):
        src = """
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:  # ut-lint: disable=R101
                            return 1

                def two(self):
                    with self._b:
                        with self._a:  # ut-lint: disable=R101
                            return 2
        """
        assert active(src, "R101") == []
        assert len(suppressed(src, "R101")) == 2


# ---------------------------------------------------------------- R102
class TestBlockingUnderLock:
    def test_positive_fsync(self):
        fs = active("""
            import os
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def flush(self, fd):
                    with self._lock:
                        os.fsync(fd)
        """, "R102")
        assert len(fs) == 1 and fs[0].line == 10
        assert "os.fsync" in fs[0].message

    def test_positive_socket_and_sleep(self):
        fs = active("""
            import time
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def send(self, sock, data):
                    with self._lock:
                        sock.sendall(data)
                        time.sleep(0.1)
        """, "R102")
        assert len(fs) == 2

    def test_positive_transitive_intra_class(self):
        # the store's record -> _append -> fsync seam
        fs = active("""
            import os
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()

                def _append(self, fd, data):
                    os.write(fd, data)
                    os.fsync(fd)

                def record(self, fd, data):
                    with self._lock:
                        self._append(fd, data)
        """, "R102")
        assert len(fs) == 1 and fs[0].line == 14
        assert "_append" in fs[0].message

    def test_negative_outside_lock_and_buffered_write(self):
        # snapshot-under-lock / block-outside, and buffered writes
        # under a lock (the append discipline) are both fine
        fs = active("""
            import os
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def flush(self, fd, f, data):
                    with self._lock:
                        os.write(fd, data)
                        f.write(data)
                        f.flush()
                    os.fsync(fd)
        """, "R102")
        assert fs == []

    def test_suppressed(self):
        src = """
            import os
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def flush(self, fd):
                    with self._lock:
                        os.fsync(fd)  # ut-lint: disable=R102
        """
        assert active(src, "R102") == []
        assert len(suppressed(src, "R102")) == 1


# ---------------------------------------------------------------- R103
class TestUnguardedSharedField:
    SRC = """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def bump(self):
                with self._lock:
                    self._n += 1

            def _run(self):
                {access}

            def stop(self):
                self._t.join()
    """

    def test_positive_bare_access_in_thread_entry(self):
        fs = active(self.SRC.format(access="self._n = 0"), "R103")
        assert len(fs) == 1 and fs[0].line == 15
        assert "_n" in fs[0].message

    def test_negative_locked_access_in_thread_entry(self):
        fs = active("""
            import threading

            class Pump:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def bump(self):
                    with self._lock:
                        self._n += 1

                def _run(self):
                    with self._lock:
                        self._n = 0

                def stop(self):
                    self._t.join()
        """, "R103")
        assert fs == []

    def test_negative_no_threads(self):
        fs = active("""
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1

                def peek(self):
                    return self._n
        """, "R103")
        assert fs == []

    def test_suppressed(self):
        src = self.SRC.format(
            access="self._n = 0  # ut-lint: disable=R103")
        assert active(src, "R103") == []
        assert len(suppressed(src, "R103")) == 1


# ---------------------------------------------------------------- R104
class TestAckBeforeDurable:
    def test_positive_commit_acked_without_drain(self):
        fs = active("""
            from uptune_tpu.serve import durable

            class H:
                def _drain_ckpt(self, sid):
                    pass

                def op_tell(self, st, sid):
                    self.state._commit()
                    return {"committed": True}
        """, "R104")
        assert len(fs) == 1 and fs[0].line == 8

    def test_negative_drain_after_commit(self):
        fs = active("""
            from uptune_tpu.serve import durable

            class H:
                def _drain_ckpt(self, sid):
                    pass

                def op_tell(self, st, sid):
                    self.state._commit()
                    self._drain_ckpt(sid)
                    return {"committed": True}
        """, "R104")
        assert fs == []

    def test_negative_out_of_scope_module(self):
        # no durable import and no drain seam: commit+return is not a
        # serving ack path
        fs = active("""
            class Repo:
                def save(self, txn):
                    txn._commit()
                    return True
        """, "R104")
        assert fs == []

    def test_suppressed(self):
        src = """
            from uptune_tpu.serve import durable

            class H:
                def _drain_ckpt(self, sid):
                    pass

                def op_tell(self, st, sid):
                    self.state._commit()  # ut-lint: disable=R104
                    return {"committed": True}
        """
        assert active(src, "R104") == []
        assert len(suppressed(src, "R104")) == 1

    # -- split-phase `*_locked` appliers (ISSUE 20) -------------------
    # A `*_locked` method that commits is the under-lock half of a
    # split-phase tell: exempt itself, but calls to it ARE commits, so
    # the drain obligation lands on every caller.

    def test_negative_locked_half_callers_drain(self):
        # the tell/tell_many shape: one drain per batch, after the
        # locked applier, before the reply
        fs = active("""
            from uptune_tpu.serve import durable

            class S:
                def _drain_ckpt(self):
                    pass

                def _tell_locked(self, ticket, qor):
                    self._commit()
                    return {"committed": True}

                def tell(self, ticket, qor):
                    with self.group.lock:
                        res = self._tell_locked(ticket, qor)
                    self._drain_ckpt()
                    return res

                def tell_many(self, rows):
                    out = []
                    with self.group.lock:
                        for t, q in rows:
                            out.append(self._tell_locked(t, q))
                    self._drain_ckpt()
                    return out
        """, "R104")
        assert fs == []

    def test_positive_locked_half_caller_skips_drain(self):
        # a caller that acks without draining is flagged AT THE CALL
        # SITE — the hazard the per-function scan alone cannot see
        src = """
            from uptune_tpu.serve import durable

            class S:
                def _drain_ckpt(self):
                    pass

                def _tell_locked(self, ticket, qor):
                    self._commit()
                    return {"committed": True}

                def tell(self, ticket, qor):
                    with self.group.lock:
                        res = self._tell_locked(ticket, qor)
                    return res
        """
        fs = active(src, "R104")
        assert len(fs) == 1 and fs[0].line == 13

    def test_positive_locked_suffix_without_commit_not_exempt(self):
        # the suffix alone is not a pass: a non-committing `*_locked`
        # helper is no carrier, and a plain method that commits and
        # acks still fires even if a `*_locked` name exists nearby
        fs = active("""
            from uptune_tpu.serve import durable

            class S:
                def _drain_ckpt(self):
                    pass

                def _peek_locked(self):
                    return self.version

                def op_tell(self, st):
                    self.state._commit()
                    return {"committed": True}
        """, "R104")
        assert len(fs) == 1 and fs[0].line == 11


# ---------------------------------------------------------------- R105
class TestThreadWithoutJoin:
    def test_positive_untracked_start(self):
        fs = active("""
            import threading

            def kick(fn):
                threading.Thread(target=fn, daemon=True).start()
        """, "R105")
        assert len(fs) == 1 and fs[0].line == 4

    def test_positive_container_never_joined(self):
        fs = active("""
            import threading

            class Pool:
                def __init__(self):
                    self._threads = []

                def spawn(self, fn):
                    self._threads.append(
                        threading.Thread(target=fn, daemon=True))
        """, "R105")
        assert len(fs) == 1

    def test_negative_joined_via_container(self):
        fs = active("""
            import threading

            class Pool:
                def __init__(self):
                    self._threads = []

                def spawn(self, fn):
                    t = threading.Thread(target=fn, daemon=True)
                    self._threads.append(t)
                    t.start()

                def stop(self):
                    for t in list(self._threads):
                        t.join(timeout=2.0)
        """, "R105")
        assert fs == []

    def test_negative_direct_join(self):
        fs = active("""
            import threading

            def run(fn):
                t = threading.Thread(target=fn)
                t.start()
                t.join()
        """, "R105")
        assert fs == []

    def test_suppressed(self):
        src = """
            import threading

            def kick(fn):
                # fire-and-forget by design: dies with the process
                threading.Thread(  # ut-lint: disable=R105
                    target=fn, daemon=True).start()
        """
        assert active(src, "R105") == []
        assert len(suppressed(src, "R105")) == 1


# ---------------------------------------------------------------- R106
class TestConditionWaitNoPredicate:
    def test_positive_bare_wait(self):
        fs = active("""
            import threading

            class Q:
                def __init__(self):
                    self._cv = threading.Condition()

                def get(self):
                    with self._cv:
                        self._cv.wait()
                        return 1
        """, "R106")
        assert len(fs) == 1 and fs[0].line == 9

    def test_negative_while_predicate(self):
        fs = active("""
            import threading

            class Q:
                def __init__(self):
                    self._cv = threading.Condition()
                    self._items = []

                def get(self):
                    with self._cv:
                        while not self._items:
                            self._cv.wait()
                        return self._items.pop()
        """, "R106")
        assert fs == []

    def test_negative_wait_for_and_event(self):
        fs = active("""
            import threading

            class Q:
                def __init__(self):
                    self._cv = threading.Condition()
                    self._ev = threading.Event()
                    self._items = []

                def get(self):
                    with self._cv:
                        self._cv.wait_for(lambda: self._items)
                    self._ev.wait()
        """, "R106")
        assert fs == []

    def test_suppressed(self):
        src = """
            import threading

            class Q:
                def __init__(self):
                    self._cv = threading.Condition()

                def get(self):
                    with self._cv:
                        self._cv.wait()  # ut-lint: disable=R106
                        return 1
        """
        assert active(src, "R106") == []
        assert len(suppressed(src, "R106")) == 1


# ----------------------------------------------------------- LockGuard
class TestLockGuard:
    def test_clean_nesting_no_findings(self):
        with LockGuard(strict=True, name="t-clean") as g:
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
            with a:
                with b:
                    pass
        assert g.ok()
        rep = g.report()
        assert rep["cycles"] == [] and rep["acquires"] >= 4

    def test_cycle_detected_sequential_interleave(self):
        # AB then BA run to completion on separate threads: the order
        # graph accumulates across time, so the cycle is detected with
        # no actual deadlock.  Locks MUST be allocated on separate
        # lines — the guard keys identity by allocation site
        g = LockGuard(name="t-cycle").install()
        try:
            a = threading.Lock()
            b = threading.Lock()

            def ab():
                with a:
                    with b:
                        pass

            def ba():
                with b:
                    with a:
                        pass

            t = threading.Thread(target=ab)
            t.start()
            t.join()
            t = threading.Thread(target=ba)
            t.start()
            t.join()
        finally:
            g.uninstall()
        rep = g.report()
        assert len(rep["cycles"]) == 1
        assert not g.ok()

    def test_strict_raises_on_exit(self):
        with pytest.raises(LockOrderError, match="cycle"):
            with LockGuard(strict=True, name="t-strict") as g:
                a = threading.Lock()
                b = threading.Lock()
                with a:
                    with b:
                        pass
                with b:
                    with a:
                        pass
        assert not g.ok()

    def test_warn_mode_warns_not_raises(self):
        with pytest.warns(RuntimeWarning, match="cycle"):
            with LockGuard(strict=False, name="t-warn"):
                a = threading.Lock()
                b = threading.Lock()
                with a:
                    with b:
                        pass
                with b:
                    with a:
                        pass

    def test_held_too_long(self):
        with pytest.raises(LockOrderError, match="held-too-long"):
            with LockGuard(strict=True, held_ms=5.0, name="t-held") as g:
                lk = threading.Lock()
                with lk:
                    time.sleep(0.02)
        assert g.report()["held_too_long"]
        assert g.report()["held_max_ms"] >= 5.0

    def test_rlock_reentrancy_and_condition(self):
        with LockGuard(strict=True, name="t-rlock") as g:
            r = threading.RLock()
            with r:
                with r:         # reentrant: outermost-only reporting
                    pass
            cv = threading.Condition()
            hit = []

            def waiter():
                with cv:
                    while not hit:
                        cv.wait(timeout=5.0)

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.05)
            with cv:
                hit.append(1)
                cv.notify_all()
            t.join(timeout=5.0)
            assert not t.is_alive()
        assert g.ok()

    def test_env_gating(self, monkeypatch):
        monkeypatch.delenv("UT_LOCK_GUARD", raising=False)
        g = lock_guard_from_env()
        assert not g.enabled
        g.install()     # inert: must not patch
        assert threading.Lock is not g and not g._active
        monkeypatch.setenv("UT_LOCK_GUARD", "strict")
        g = lock_guard_from_env()
        assert g.enabled and g.strict
        monkeypatch.setenv("UT_LOCK_GUARD", "warn")
        monkeypatch.setenv("UT_LOCK_GUARD_MS", "250")
        g = lock_guard_from_env()
        assert g.enabled and not g.strict and g.held_ms == 250.0

    def test_uninstall_restores_factories(self):
        orig_lock, orig_rlock = threading.Lock, threading.RLock
        g = LockGuard(name="t-restore").install()
        assert threading.Lock is not orig_lock
        g.uninstall()
        assert threading.Lock is orig_lock
        assert threading.RLock is orig_rlock


class TestLockGuardStrictSmoke:
    """Strict guard over the real jax-free serving/store primitives:
    zero findings expected — this is the cheap in-suite proxy for the
    `bench.py --serve --quick` acceptance run."""

    def test_durable_store_wire_clean(self, tmp_path):
        from uptune_tpu.serve.durable import CheckpointLog
        from uptune_tpu.serve.wire import WireServer
        from uptune_tpu.store.store import ResultStore

        class Ping(WireServer):
            WIRE_NAME = "t-ping"

            def _op_ping(self, req):
                return {"pong": True}
            _OPS = {"ping": _op_ping}

        with LockGuard(strict=True, name="t-smoke") as g:
            ckpt = CheckpointLog(str(tmp_path / "ckpt"), fsync=True)
            assert ckpt.append("s1", {"ev": "open", "v": 0})
            assert ckpt.append("s1", {"ev": "commit", "v": 1})

            st = ResultStore(str(tmp_path / "store"),
                             ["x:int:0:8"], "true", fsync=True)
            for i in range(4):
                st.record({"x": i}, qor=float(i))
            assert st.lookup({"x": 2}) is not None
            st.compact()
            assert st.lookup({"x": 2}) is not None

            srv = Ping(host="127.0.0.1", port=0).start()
            import socket
            with socket.create_connection(
                    ("127.0.0.1", srv.port), timeout=5) as c:
                f = c.makefile("rwb")
                f.write(b'{"op": "ping"}\n')
                f.flush()
                resp = json.loads(f.readline())
                assert resp["ok"] and resp["pong"]
            srv.stop()
        assert g.ok(), g.report()
        assert g.report()["acquires"] > 0


# ------------------------------------------------------------- changed
class TestChangedScoping:
    def _git(self, cwd, *args):
        return subprocess.run(["git", *args], cwd=cwd,
                              capture_output=True, text=True)

    def test_changed_lints_only_dirty_files(self, tmp_path):
        if self._git(tmp_path, "init", "-q").returncode != 0:
            pytest.skip("git unavailable")
        self._git(tmp_path, "config", "user.email", "t@t")
        self._git(tmp_path, "config", "user.name", "t")
        clean = tmp_path / "clean.py"
        dirty = tmp_path / "dirty.py"
        bad = ("import threading\n\n"
               "def kick(fn):\n"
               "    threading.Thread(target=fn).start()\n")
        clean.write_text(bad)    # committed hazard: out of scope
        dirty.write_text("x = 1\n")
        self._git(tmp_path, "add", ".")
        self._git(tmp_path, "commit", "-q", "-m", "seed")
        dirty.write_text(bad)    # NEW hazard in the diff
        r = subprocess.run(
            [sys.executable, "-m", "uptune_tpu.analysis", ".",
             "--changed", "--select", "R105"],
            cwd=tmp_path, capture_output=True, text=True,
            env=dict(os.environ, PYTHONPATH=REPO))
        assert r.returncode == 1, r.stdout + r.stderr
        assert "dirty.py" in r.stdout
        assert "clean.py" not in r.stdout

    def test_changed_falls_back_without_git(self, tmp_path):
        f = tmp_path / "mod.py"
        f.write_text("import threading\n\n"
                     "def kick(fn):\n"
                     "    threading.Thread(target=fn).start()\n")
        env = dict(os.environ, PYTHONPATH=REPO,
                   GIT_DIR=str(tmp_path / "no-such-repo"))
        r = subprocess.run(
            [sys.executable, "-m", "uptune_tpu.analysis", ".",
             "--changed", "--select", "R105"],
            cwd=tmp_path, capture_output=True, text=True, env=env)
        # full-lint fallback still finds the hazard
        assert r.returncode == 1, r.stdout + r.stderr
        assert "falling back to full lint" in r.stderr


# ----------------------------------------------------------- repo gate
class TestRepoConcurrencyClean:
    def test_repo_clean_under_concurrency_rules(self):
        """The concurrency pass holds repo-wide with zero unsuppressed
        findings (the R101-R106 half of scripts/lint.sh)."""
        r = subprocess.run(
            [sys.executable, "-m", "uptune_tpu.analysis",
             "uptune_tpu/", "bench.py", "scripts/",
             "--select", "R101,R102,R103,R104,R105,R106"],
            cwd=REPO, capture_output=True, text=True)
        assert r.returncode == 0, r.stdout + r.stderr
