"""The fused on-device tuning engine: the whole acquisition loop as ONE
jitted XLA program.

The host driver (uptune_tpu.driver) replays the reference's controller
semantics for *black-box* objectives where each evaluation is an external
build (the reference's only regime, `/root/reference/python/uptune/
api.py:399-594`).  For cheap / on-device objectives — analytic functions,
surrogate models, batched simulators — crossing the host boundary per step
throws away the TPU's throughput.  This engine keeps everything on device:

    propose (all arms) -> concat -> dedup vs history -> evaluate ->
    observe (each arm its slice) -> best exchange -> repeat under lax.scan

Every arm proposes its natural batch each step (static shapes; the
"sequential bandit picks one arm" control flow of the reference,
bandittechniques.py:150-266, becomes per-arm credit *attribution* instead
of arm gating — all arms run, the AUC stats are still tracked in-device
and determine nothing but reporting + the host driver's arm choice).
This is the north-star path: ~10^4-10^5 candidate acquisitions/sec/chip.
"""
from __future__ import annotations

from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import obs
from ..driver.history import History, HistState, dup_source
from ..space.spec import CandBatch, Space, concat_cands
from ..techniques.base import Best, Technique, get_technique

# objective over decoded values: (vals [B, D] f32, perms tuple [B, s_k]) -> [B]
DeviceObjective = Callable[[jax.Array, Tuple[jax.Array, ...]], jax.Array]


class EngineState(NamedTuple):
    tstates: Tuple    # per-arm technique states
    best: Best
    hist: HistState
    key: jax.Array
    evals: jax.Array          # scalar i32: novel evaluations so far
    acqs: jax.Array           # scalar i32: total candidates processed
    arm_pulls: jax.Array      # [n_arms] i32
    arm_hits: jax.Array       # [n_arms] i32: steps where arm held new best


def default_arms(scale: int = 1) -> List[Technique]:
    """The AUCBanditMetaTechniqueA portfolio members
    (bandittechniques.py:273-278), with populations scaled for device
    throughput (`scale` multiplies every arm's batch)."""
    from ..techniques.de import DifferentialEvolution
    from ..techniques.evolutionary import GreedyMutation
    from ..techniques.simplex import NelderMead

    return [
        DifferentialEvolution(population_size=30 * scale, cr=0.2,
                              name="DifferentialEvolutionAlt"),
        GreedyMutation(batch=32 * scale, name="UniformGreedyMutation"),
        GreedyMutation(batch=32 * scale, sigma=0.1, mutation_rate=0.3,
                       name="NormalGreedyMutation"),
        NelderMead(init_style="random", name="RandomNelderMead"),
    ]


class FusedEngine:
    """space + arms + on-device objective -> (init, step, run)."""

    def __init__(self, space: Space, objective: DeviceObjective,
                 arms: Optional[Sequence[Technique]] = None,
                 history_capacity: int = 1 << 15, dedup: bool = True,
                 sense: str = "min", merge_impl: str = "auto"):
        assert sense in ("min", "max")
        self.space = space
        self.sign = 1.0 if sense == "min" else -1.0
        self.objective = objective
        if arms is None:
            arms = default_arms()
        elif isinstance(arms, (list, tuple)) and arms and isinstance(
                arms[0], str):
            arms = [get_technique(n) for n in arms]
        self.arms: List[Technique] = [t for t in arms if t.supports(space)]
        if not self.arms:
            raise ValueError("no arm supports this space")
        self.batches = [t.natural_batch(space) for t in self.arms]
        self.total_batch = sum(self.batches)
        self.history = History(history_capacity, merge_impl=merge_impl)
        self.dedup = dedup

    # ------------------------------------------------------------------
    def init(self, key: jax.Array) -> EngineState:
        keys = jax.random.split(key, len(self.arms) + 1)
        tstates = tuple(t.init_state(self.space, k)
                        for t, k in zip(self.arms, keys[:-1]))
        n = len(self.arms)
        return EngineState(
            tstates, Best.empty(self.space), self.history.init(), keys[-1],
            jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
            jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32))

    # ------------------------------------------------------------------
    def propose(self, state: EngineState):
        """The proposal half of one step: every arm emits its batch and
        the batches concatenate (pure; jit/vmap-able).  Returns
        `(new_tstates, cands, key)` for `commit()` — the split exists so
        the batched multi-instance engine can vmap proposal, evaluate
        ALL instances' candidates in one flat fused scoring pass, and
        vmap the commit, instead of dispatching per instance."""
        space = self.space
        key, *karms = jax.random.split(state.key, len(self.arms) + 1)

        new_tstates = []
        cands_list = []
        for t, st, k in zip(self.arms, state.tstates, karms):
            st2, c = t.propose(space, st, k, state.best)
            new_tstates.append(st2)
            cands_list.append(c)
        cands = (concat_cands(cands_list) if len(cands_list) > 1
                 else cands_list[0])
        return tuple(new_tstates), cands, key

    def propose_topk(self, state: EngineState, acq, k: int):
        """Propose one epoch and keep only the k rows the fused
        acquisition pipeline ranks best (pure; jit-able).  `acq` is a
        `StatefulEval` from `surrogate_eval_fn(..., impl="fused")` —
        its `.topk` runs surrogate score, acquisition transform and
        top-k selection in one device program over the proposal batch
        (ops/acquire.py).  Returns `(new_tstates, cands, key, vals,
        idx)` with `vals`/`idx` the [k] acquisition utilities and
        candidate rows; the caller gathers `cands[idx]` (or feeds the
        indices to a measurement queue) instead of materialising
        per-row scores."""
        if acq.topk is None:
            raise ValueError("acq has no topk (need impl='fused')")
        new_tstates, cands, key = self.propose(state)
        vals, idx = acq.topk(cands, acq.aux, k)
        return new_tstates, cands, key, vals, idx

    # ------------------------------------------------------------------
    def step(self, state: EngineState, eval_fn=None,
             exchange=None) -> EngineState:
        """One fused acquisition step (pure; jit/scan-able).

        `eval_fn(cands) -> qor` overrides the plain objective call (the
        sharded engine injects a batch-sharded evaluator); `exchange(best)
        -> best` is the cross-replica best-exchange collective (the
        epoch-wise `sync` of the reference's multi-instance search,
        opentuner/api.py:87-104) — identity when absent."""
        new_tstates, cands, key = self.propose(state)
        if eval_fn is None:
            raw = self.objective(
                self.space.decode_scalars(cands.u), cands.perms)
        else:
            raw = eval_fn(cands)
        return self.commit(state, new_tstates, cands, raw, key, exchange)

    # ------------------------------------------------------------------
    def commit(self, state: EngineState, new_tstates, cands: CandBatch,
               raw: jax.Array, key: jax.Array,
               exchange=None, evict_pred=None) -> EngineState:
        """The commit half of one step: orient + clean the measured QoR,
        dedup against history, fold the batch into the best, attribute
        per-arm credit, and run every arm's observe.  `raw` is the
        UN-oriented objective value for `cands` (propose()'s output);
        `evict_pred` forwards to History.insert (the batched engine's
        unbatched eviction gate)."""
        qor = self.sign * raw
        qor = jnp.where(jnp.isfinite(qor), qor, jnp.inf).astype(jnp.float32)

        if self.dedup:
            hashes = self.space.hash_batch(cands)
            found, known = self.history.contains(state.hist, hashes)
            src = dup_source(hashes)
            first = src == jnp.arange(hashes.shape[0])
            novel = first & ~found
            hist = self.history.insert(state.hist, hashes, qor, novel,
                                       evict_pred=evict_pred)
            n_new = novel.sum().astype(jnp.int32)
        else:
            hist = state.hist
            n_new = jnp.asarray(cands.batch, jnp.int32)

        # per-arm best attribution + observe
        prev_best = state.best.qor
        best = state.best.update(cands, qor)
        if exchange is not None:
            best = exchange(best)
        off = 0
        arm_hits = state.arm_hits
        tstates_out = []
        step_min = jnp.min(qor)
        for i, (t, st2, b) in enumerate(
                zip(self.arms, new_tstates, self.batches)):
            sl = slice(off, off + b)
            cq = qor[sl]
            arm_best = jnp.min(cq)
            hit = (arm_best < prev_best) & (arm_best <= step_min)
            arm_hits = arm_hits.at[i].add(hit.astype(jnp.int32))
            tstates_out.append(
                t.observe(self.space, st2, cands[sl], cq, best))
            off += b

        return EngineState(
            tuple(tstates_out), best, hist, key,
            state.evals + n_new,
            state.acqs + jnp.asarray(cands.batch, jnp.int32),
            state.arm_pulls + 1, arm_hits)

    # ------------------------------------------------------------------
    def run(self, state: EngineState, n_steps: int, eval_fn=None,
            exchange=None) -> EngineState:
        """n_steps fused steps under lax.scan (ONE compiled program)."""
        def body(s, _):
            return self.step(s, eval_fn, exchange), None
        out, _ = jax.lax.scan(body, state, None, length=n_steps)
        return out

    def jit_run(self, n_steps: int, eval_fn=None, exchange=None,
                donate: bool = True):
        """jax.jit-wrapped run(): the preferred entry for repeated
        driving.  With donate=True (default) the EngineState argument is
        DONATED — the multi-MB history buffers are updated in place
        instead of copied on every call, and the caller must rebind
        (`state = run(state)`) and never touch the donated input again.
        Returns the jitted callable (supports .lower(state) for AOT
        compile + cost analysis, as bench.py uses)."""
        def _run(s):
            return self.run(s, n_steps, eval_fn, exchange)
        fn = jax.jit(_run, donate_argnums=(0,) if donate else ())
        # each dispatch of the fused step loop is one span on the
        # caller's lane (and a jax.profiler.TraceAnnotation, so a
        # captured XLA profile lines up with the host trace); a traced
        # run also harvests the program's XLA cost/memory analysis at
        # compile time (obs.device, docs/OBSERVABILITY.md)
        return obs.instrument_device_fn(fn, "engine.run",
                                        steps=n_steps, donate=donate)

    def run_traced(self, state: EngineState,
                   n_steps: int) -> Tuple[EngineState, jax.Array]:
        """Like run() but also returns the best-so-far trace [n_steps]
        (user orientation)."""
        def body(s, _):
            s = self.step(s)
            return s, self.sign * s.best.qor
        return jax.lax.scan(body, state, None, length=n_steps)

    def best_config(self, state: EngineState):
        return self.space.to_configs(state.best.as_batch(1))[0]

    def best_qor(self, state: EngineState) -> float:
        # intentional host sync: this is the reporting boundary, called
        # once after run() — never from inside the fused/scanned step.
        # R001 does not fire here (best_qor is not jit-reachable), and
        # engine/ is suppression-free (scripts/lint.sh), so no pragma:
        # a future caller that pulls this into a traced path will be
        # flagged loudly instead of silently waived
        return float(self.sign * state.best.qor)
