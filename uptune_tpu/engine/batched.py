"""Batched multi-instance engine: N independent tunes as ONE program.

The reference scales search by launching many OpenTuner *processes*
that exchange results through SQLite/CSV archives (PAPER.md L4/L5).
On a TPU that shape wastes the chip: BENCH_TPU.json records the fused
single-instance engine at MXU util 6e-06 / HBM util 9e-4 — ~0.0001%
of a v5 lite, because one tune's batches are tiny next to the
hardware.  This module stacks `EngineState` along a leading INSTANCE
axis and runs the whole portfolio-of-portfolios as one vmapped,
donate-in-place program:

* **N independent tunes** of the same space signature (or N seeds of
  one tune): `jax.vmap` over `FusedEngine.propose`/`commit`, one
  compiled program, ONE trace under `UT_TRACE_GUARD=strict`, per-
  instance RNG streams / technique states / dedup histories — the
  device-resident analogue of the reference's per-instance DBs.
* **Fused scoring**: the evaluation between the two vmapped halves is
  NOT vmapped — all instances' candidates flatten to one [N*B] batch
  and score in a single dispatch (for surrogate objectives this turns
  N small GP scoring matmuls into one MXU-filling [N*B, train] pass —
  `surrogate_eval_fn` / gp.score_flat).
* **Periodic on-device best-exchange** across the instance axis
  (`exchange_every=k`): the multi-start portfolio becomes cooperative,
  reusing the sharded engine's lexicographic pmin + one-hot psum
  collective over the vmap axis name (the epoch-wise `sync` of the
  reference's multi-instance search, opentuner/api.py:87-104).
* **shard_map scale-out**: with an instance mesh the same step runs
  per-device over local instances, and the exchange collective spans
  both the mesh axis and the in-device vmap axis.

`bench.py --multi` measures the aggregate acquisition throughput and
writes BENCH_MULTI.json; `uptune_tpu.tune_batch` is the library
surface.
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import obs
from ..space.spec import CandBatch
from ..techniques.base import Best
from .fused import EngineState, FusedEngine

# axis names: the in-program vmap axis over instances, and the device
# mesh axis shard_map splits the instance axis over
VMAP_AXIS = "inst"
MESH_AXIS = "idev"


def _strong(tree):
    """Strip weak_type from every array leaf: technique init states
    carry weak-typed python-constant leaves, which become strong after
    one run — without this the second jit_run call on a rebound state
    would RETRACE (driver.py learned the same lesson in PR 2; the
    strict trace guard holds this engine to one trace per wrapper)."""
    return jax.tree.map(
        lambda x: (x + jnp.zeros((), x.dtype)
                   if getattr(x, "weak_type", False) else x), tree)


def make_instance_mesh(n_devices: Optional[int] = None,
                       devices=None) -> Mesh:
    """1-D ('idev',) mesh over the first n_devices local devices."""
    devices = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (MESH_AXIS,))


def exchange_best(best: Best, axes) -> Best:
    """Global-best broadcast over the named axes (vmap instance axis
    and/or mesh axis): lexicographic (qor, instance-rank) argmin, then
    a one-hot psum broadcast — ShardedEngine._exchange generalized to
    arbitrary axis-name tuples."""
    axes = tuple(axes)
    qmin = jax.lax.pmin(best.qor, axes)
    rank = jnp.asarray(0, jnp.int32)
    for ax in axes:  # row-major rank over the axis product
        rank = rank * jax.lax.psum(1, ax) + jax.lax.axis_index(ax)
    big = jnp.asarray(1 << 30, jnp.int32)
    winner = jax.lax.pmin(
        jnp.where(best.qor == qmin, rank, big), axes)
    i_am = (rank == winner) & jnp.isfinite(qmin)
    u = jax.lax.psum(jnp.where(i_am, best.u, 0.0), axes)
    perms = tuple(jax.lax.psum(jnp.where(i_am, p, 0), axes)
                  for p in best.perms)
    # keep the local best when nothing finite exists yet
    return Best(
        jnp.where(jnp.isfinite(qmin), u, best.u),
        tuple(jnp.where(jnp.isfinite(qmin), p, lp)
              for p, lp in zip(perms, best.perms)),
        qmin)


class StatefulEval:
    """An eval_fn whose learned state rides as a PROGRAM ARGUMENT.

    The pre-ISSUE-19 pattern — a fresh closure over `gp_state` per
    surrogate snapshot — retraced the whole fused-propose program on
    every publish (`jit_run` memoizes by eval_fn object identity, and
    the captured state was baked into the jaxpr as a constant).  Here
    the pure function `fn(cands, aux)` is built ONCE per engine/config
    and the snapshot pytree lives in `.aux`: `jit_run` threads the
    CURRENT `.aux` as a (non-donated) argument on every dispatch, so a
    publish is one attribute rebind — same structure/shapes, zero
    retrace under UT_TRACE_GUARD=strict (the tier-1 regression).

    `topk(cands, aux, k) -> (vals [k], idx [k])` is the fused
    score+acquisition+top-k companion (ops/acquire) the slot programs
    vmap per instance; `__call__` keeps the legacy eager contract
    (scores the batch against the CURRENT aux)."""
    __slots__ = ("fn", "topk", "aux")

    def __init__(self, fn, aux, topk=None):
        self.fn, self.aux, self.topk = fn, aux, topk

    def __call__(self, cands: CandBatch) -> jax.Array:
        return self.fn(cands, self.aux)

    def publish(self, aux) -> None:
        """Swap in a new snapshot.  The aux pytree MUST keep the same
        structure and shapes (same train-size bucket, K^-1 attached
        consistently) — that is what makes this retrace-free."""
        self.aux = aux


def surrogate_aux(gp_state, best_y=None, kind: str = "ei"):
    """The aux pytree for `surrogate_eval_fn` programs: (GPState with
    the premasked K^-1 attached for variance kinds, best-so-far as a
    traced f32 scalar).  Build the refit's aux with the SAME kind and
    train-size bucket and publish via `ev.publish(surrogate_aux(...))`."""
    from ..surrogate import gp as gp_mod
    if kind != "mean" and gp_state.kinv is None:
        gp_state = gp_mod.precompute_kinv(gp_state)
    return (gp_state,
            jnp.asarray(0.0 if best_y is None else best_y, jnp.float32))


def surrogate_eval_fn(space, gp_state, kind: str = "ei",
                      best_y=None, beta: float = 2.0,
                      n_cont: Optional[int] = None, n_cat: int = 0,
                      sense: str = "min", impl: str = "fused"):
    """A flat-batch eval_fn scoring candidates against a fitted
    GPState so that the ENGINE prefers: low posterior mean ('mean'),
    high expected improvement ('ei'), or low mu - beta*sd ('lcb').
    Because BatchedEngine evaluates the FLATTENED [N*B] batch, all
    instances share one scoring pass — and with impl='fused' (default)
    that pass is the ISSUE-19 fused acquisition pipeline
    (`ops/acquire`): cross-kernel, moments, and the acquisition
    transform in ONE device program (Pallas kernel / XLA fallback per
    `ops/routing.py`), no [N*B, train] or [N*B] HBM intermediates.
    impl='score_flat' keeps the pre-fusion `gp.score_flat` staging
    (the A/B comparator).

    Returns a `StatefulEval`: the GP snapshot and best-so-far ride in
    `.aux` as program arguments — publish a refit with
    `ev.publish(surrogate_aux(new_state, new_best, kind))` and no
    compiled program retraces.

    `sense` MUST match the engine's: eval_fn output is re-oriented by
    commit (`qor = sign * raw` — the eval_fn slot carries USER-level
    values), so this helper pre-applies the inverse.  The model is
    assumed fitted on engine-oriented (minimized) QoR, as the driver
    trains it."""
    assert sense in ("min", "max"), sense
    if impl not in ("fused", "score_flat"):
        raise ValueError(f"unknown impl {impl!r}")
    if kind == "ei" and best_y is None:
        raise ValueError("kind='ei' needs best_y")
    sgn = 1.0 if sense == "min" else -1.0
    from ..ops import acquire as acq_mod
    from ..surrogate import gp as gp_mod

    def _feats(cands: CandBatch) -> jax.Array:
        return space.surrogate_transform(space.features(cands))

    def fn(cands: CandBatch, aux) -> jax.Array:
        st, by = aux
        if impl == "fused":
            u = acq_mod.acquire_scores(
                st, _feats(cands), kind=kind,
                best_y=by if kind == "ei" else None,
                beta=beta, n_cont=n_cont, n_cat=n_cat)
            # utilities are higher-is-better; IEEE negation is exact,
            # so sense orientation stays bitwise-symmetric
            return sgn * (-u)
        s = gp_mod.score_flat(
            st, _feats(cands), kind=kind,
            best_y=by if kind == "ei" else None,
            beta=beta, n_cont=n_cont, n_cat=n_cat)
        return sgn * (-s if kind == "ei" else s)

    def topk(cands: CandBatch, aux, k: int):
        st, by = aux
        return acq_mod.acquire_topk(
            st, _feats(cands), k, kind=kind,
            best_y=by if kind == "ei" else None,
            beta=beta, n_cont=n_cont, n_cat=n_cat)

    return StatefulEval(fn, surrogate_aux(gp_state, best_y, kind),
                        topk=topk)


def exchange_topk(vals: jax.Array, idx: jax.Array, axes, k: int):
    """Portfolio-wide top-k across the named instance axes (vmap
    and/or mesh — the exchange_best axis contract): every instance
    contributes its local fused top-k (vals [k] desc, idx [k]), a
    one-hot-style scatter + psum assembles the [n_total, k] pool in
    row-major rank order, and one lax.top_k over the flattened pool
    broadcasts the SAME global winners to every instance.  Ties
    resolve by (rank, local rank) — the flat-pool lowest-index order.
    Returns (vals [k], owner rank [k] i32, local idx [k] i32)."""
    axes = tuple(axes)
    n_total, rank = 1, jnp.asarray(0, jnp.int32)
    for ax in axes:  # row-major rank, exactly as exchange_best
        sz = jax.lax.psum(1, ax)
        n_total, rank = n_total * sz, rank * sz + jax.lax.axis_index(ax)
    gv = jax.lax.psum(
        jnp.zeros((n_total, k), vals.dtype).at[rank].set(vals), axes)
    gi = jax.lax.psum(
        jnp.zeros((n_total, k), jnp.int32).at[rank].set(
            idx.astype(jnp.int32)), axes)
    v, pos = jax.lax.top_k(gv.reshape(-1), k)
    return v, (pos // k).astype(jnp.int32), gi.reshape(-1)[pos]


class BatchedEngine:
    """A FusedEngine vectorized over a leading instance axis.

    n_instances independent searches (same Space + arms => same
    compiled step) run as one program; `exchange_every=k` turns
    multi-start into a cooperative portfolio (on-device best exchange
    every k steps); `mesh` (a ('idev',) Mesh) shards the instance axis
    across devices with shard_map."""

    def __init__(self, engine: FusedEngine, n_instances: int,
                 exchange_every: int = 0, mesh: Optional[Mesh] = None):
        if n_instances < 1:
            raise ValueError(f"n_instances must be >= 1: {n_instances}")
        self.engine = engine
        self.n_instances = int(n_instances)
        self.exchange_every = int(exchange_every)
        self.mesh = mesh
        if mesh is not None:
            n_dev = mesh.shape[MESH_AXIS]
            if self.n_instances % n_dev:
                raise ValueError(
                    f"n_instances {n_instances} not divisible by "
                    f"mesh axis size {n_dev}")
        self._compiled: dict = {}

    # -- state management ---------------------------------------------------
    def instance_keys(self, key: jax.Array) -> jax.Array:
        """The per-instance PRNG keys init() derives — exposed so
        matched-seed sequential runs can start FusedEngine.init from
        the exact same streams."""
        return jax.random.split(key, self.n_instances)

    def init(self, key: jax.Array) -> EngineState:
        """Stacked per-instance EngineStates ([n_instances] leading
        axis), placed on the mesh when sharded."""
        state = _strong(jax.vmap(self.engine.init)(self.instance_keys(key)))
        if self.mesh is not None:
            sharding = NamedSharding(self.mesh, P(MESH_AXIS))
            state = jax.tree.map(
                lambda x: jax.device_put(x, sharding), state)
        return state

    # -- the batched step ---------------------------------------------------
    def _eval_flat(self, flat: CandBatch) -> jax.Array:
        eng = self.engine
        return eng.objective(eng.space.decode_scalars(flat.u), flat.perms)

    def _step(self, state: EngineState, t: jax.Array, axes,
              eval_fn=None) -> EngineState:
        """propose (vmapped) -> score (ONE flat fused dispatch) ->
        commit (vmapped, with the optional exchange collective)."""
        eng = self.engine
        tstates, cands, keys = jax.vmap(eng.propose)(state)
        i_local, b = cands.u.shape[0], cands.u.shape[1]
        flat = CandBatch(
            cands.u.reshape(i_local * b, -1),
            tuple(p.reshape(i_local * b, p.shape[-1])
                  for p in cands.perms))
        raw = (eval_fn or self._eval_flat)(flat).reshape(i_local, b)

        # batch-level eviction gate, computed OUTSIDE the vmap so the
        # insert cond keeps a real (unbatched) predicate: a batched
        # predicate lowers cond to select and the evict branch would
        # run every step for every instance (identity or not).
        # Conservative (any instance COULD overflow) is exact in
        # effect: evict at overflow 0 is the identity.
        evict_pred = None
        if eng.dedup:
            evict_pred = jnp.any(
                state.hist.n + b > eng.history.capacity)

        exchange = None
        if self.exchange_every > 0:
            k = self.exchange_every

            def exchange(best):
                ex = exchange_best(best, axes)
                do = (t + 1) % k == 0
                return jax.tree.map(
                    lambda a, bs: jnp.where(do, a, bs), ex, best)

        def commit(s, ts, c, q, kk):
            return eng.commit(s, ts, c, q, kk, exchange=exchange,
                              evict_pred=evict_pred)

        return jax.vmap(commit, axis_name=VMAP_AXIS)(
            state, tstates, cands, raw, keys)

    def _run_local(self, state: EngineState, n_steps: int, axes,
                   eval_fn=None) -> EngineState:
        def body(s, t):
            return self._step(s, t, axes, eval_fn), None
        out, _ = jax.lax.scan(
            body, state, jnp.arange(n_steps, dtype=jnp.int32))
        return out

    # -- compiled entries ---------------------------------------------------
    def jit_run(self, n_steps: int, eval_fn=None, donate: bool = True):
        """The jitted n_steps program (memoized per (n_steps, donate,
        eval_fn) so repeated driving never retraces).  donate=True
        updates the stacked histories/technique states in place — the
        caller must rebind and never reuse the donated input.

        A plain-callable `eval_fn` is part of the memo key by OBJECT
        IDENTITY (same contract as jax.jit): pass the SAME callable
        across calls — re-wrapping a fresh closure per call recompiles
        each time.  A `StatefulEval` is keyed by its pure `.fn` and its
        `.aux` snapshot is threaded as a non-donated program ARGUMENT,
        read at call time: publishing a refit (`.publish(...)`, same
        pytree structure/shapes) re-dispatches the one compiled program
        and NEVER retraces (the UT_TRACE_GUARD=strict regression)."""
        stateful = isinstance(eval_fn, StatefulEval)
        sig = (n_steps, donate, eval_fn.fn if stateful else eval_fn)
        fn = self._compiled.get(sig)
        if fn is not None:
            return fn
        if stateful:
            if self.mesh is None:
                def _run(s, aux):
                    return self._run_local(
                        s, n_steps, (VMAP_AXIS,),
                        lambda c: eval_fn.fn(c, aux))
            else:
                from ..parallel.sharded import shard_map

                def _local(s, aux):
                    return self._run_local(
                        s, n_steps, (MESH_AXIS, VMAP_AXIS),
                        lambda c: eval_fn.fn(c, aux))

                # aux is replicated (P() prefix spec): every shard
                # scores against the same snapshot
                _run = shard_map(_local, mesh=self.mesh,
                                 in_specs=(P(MESH_AXIS), P()),
                                 out_specs=P(MESH_AXIS), check_rep=False)
            inst = obs.instrument_device_fn(
                jax.jit(_run, donate_argnums=(0,) if donate else ()),
                "engine.batched_run", steps=n_steps,
                n_instances=self.n_instances, donate=donate)

            def fn(state, aux=None):
                return inst(state, _strong(
                    eval_fn.aux if aux is None else aux))
            fn.lower = inst.lower  # AOT/bench: pass aux explicitly
            self._compiled[sig] = fn
            return fn
        if self.mesh is None:
            def _run(s):
                return self._run_local(s, n_steps, (VMAP_AXIS,), eval_fn)
        else:
            from ..parallel.sharded import shard_map

            def _run_l(s):
                return self._run_local(s, n_steps,
                                       (MESH_AXIS, VMAP_AXIS), eval_fn)

            _run = shard_map(_run_l, mesh=self.mesh,
                             in_specs=(P(MESH_AXIS),),
                             out_specs=P(MESH_AXIS), check_rep=False)
        fn = obs.instrument_device_fn(
            jax.jit(_run, donate_argnums=(0,) if donate else ()),
            "engine.batched_run", steps=n_steps,
            n_instances=self.n_instances, donate=donate)
        self._compiled[sig] = fn
        return fn

    def run(self, state: EngineState, n_steps: int,
            eval_fn=None) -> EngineState:
        """Non-donating convenience entry (tests / interactive use)."""
        return self.jit_run(n_steps, eval_fn, donate=False)(state)

    def run_traced(self, state: EngineState, n_steps: int
                   ) -> Tuple[EngineState, jax.Array]:
        """Like run() but also returns the per-instance best-so-far
        trace [n_steps, n_instances] in USER orientation.  Unsharded
        only (a scan output's per-step collective layout under
        shard_map is not worth the complexity for an orientation
        tool)."""
        if self.mesh is not None:
            raise ValueError("run_traced is unsharded-only")
        sign = self.engine.sign

        def body(s, t):
            s = self._step(s, t, (VMAP_AXIS,))
            return s, sign * s.best.qor

        return jax.lax.scan(
            body, state, jnp.arange(n_steps, dtype=jnp.int32))

    # -- slot primitives (the serving plane's join/ask/tell seam) -----------
    # A session server (uptune_tpu/serve, docs/SERVING.md) multiplexes
    # many ask/tell tenants onto ONE stacked EngineState: proposal
    # generation is vmapped across every slot in one dispatch, while
    # join (init_slot), leave (slot reuse via init_slot) and tell
    # (commit_slot) touch a single instance row.  All three programs
    # take the slot index as a TRACED scalar and are memoized like
    # jit_run, so a group compiles each exactly once — sessions joining
    # and leaving NEVER retrace the batched program (the strict
    # trace-guard contract BENCH_SERVE.json is held to).

    def jit_propose_all(self):
        """Jitted vmap(propose) over the stacked state ->
        (stacked new tstates, stacked CandBatch [n, B, ...], stacked
        keys).  Pure read of the state (nothing is donated): the state
        advances only when a slot's measured batch commits, so one
        proposal epoch can be re-derived — identically — for any slot
        that has not moved since (propose is deterministic in the
        state)."""
        fn = self._compiled.get("propose_all")
        if self.mesh is not None:
            # a sharded group would put every tenant's ask on a
            # cross-device dispatch; the serving plane scales by
            # allocating more in-device groups instead
            raise ValueError("slot primitives are unsharded-only")
        if fn is None:
            def _propose_all(s):
                return jax.vmap(self.engine.propose)(s)
            fn = self._compiled["propose_all"] = obs.instrument_device_fn(
                jax.jit(_propose_all), "engine.propose_all",
                n_instances=self.n_instances)
        return fn

    def jit_propose_topk(self, k: int, acq):
        """Jitted (state, aux) -> (tstates, cands, keys, vals [n, k],
        idx [n, k]): one proposal epoch plus the fused per-slot top-k
        (StatefulEval `acq` with a `.topk` — surrogate_eval_fn
        impl="fused") in a single dispatch.  The serving plane uses
        this to hand each tenant only its k best-by-acquisition rows
        instead of the full proposal batch.  Memoized by (k, acq.fn);
        aux (the surrogate snapshot) is a program argument, so refits
        published via acq.publish never retrace.  Unsharded-only, like
        every slot primitive."""
        if self.mesh is not None:
            raise ValueError("slot primitives are unsharded-only")
        if acq.topk is None:
            raise ValueError("acq has no topk (need impl='fused')")
        sig = ("propose_topk", k, acq.fn)
        fn = self._compiled.get(sig)
        if fn is not None:
            return fn

        def _propose_topk(s, aux):
            tstates, cands, keys = jax.vmap(self.engine.propose)(s)
            vals, idx = jax.vmap(lambda c: acq.topk(c, aux, k))(cands)
            return tstates, cands, keys, vals, idx

        inst = obs.instrument_device_fn(
            jax.jit(_propose_topk), "engine.propose_topk", k=k,
            n_instances=self.n_instances)

        def fn(state, aux=None):
            return inst(state, _strong(acq.aux if aux is None else aux))
        fn.lower = inst.lower
        self._compiled[sig] = fn
        return fn

    def jit_global_topk(self, k: int, acq):
        """Jitted (state, aux) -> (vals, owner, idx), each [n_local, k]
        with IDENTICAL rows: one proposal epoch, the fused per-instance
        top-k, then the exchange_topk collective merging the [n*B]
        global candidate pool's k best across the vmap (and, when
        sharded, mesh) instance axes.  `owner` is the flattened
        row-major instance rank that proposed each winner and `idx` its
        row within that instance's batch.  Memoized by (k, acq.fn);
        aux is a replicated program argument (no retrace on publish)."""
        if acq.topk is None:
            raise ValueError("acq has no topk (need impl='fused')")
        sig = ("global_topk", k, acq.fn)
        fn = self._compiled.get(sig)
        if fn is not None:
            return fn
        axes = ((VMAP_AXIS,) if self.mesh is None
                else (MESH_AXIS, VMAP_AXIS))

        def _local(s, aux):
            def one(si):
                _, cands, _ = self.engine.propose(si)
                vals, idx = acq.topk(cands, aux, k)
                # The per-instance tops are returned alongside the
                # exchange result (and sliced off in the host wrapper):
                # keeping them live as program outputs pins the
                # collective's operands to committed buffers.  With only
                # the exchanged [k] arrays as outputs, the emulated
                # multi-CPU-device backend (forced virtual devices) has
                # been observed to feed the all-reduce stale operand
                # rows — values absent from any instance's score vector
                # — at mesh=2 with 2 instances per shard; any
                # observation of vals/idx (outputs, debug.print)
                # restores the correct result, and optimization_barrier
                # alone does not.
                return vals, idx, exchange_topk(vals, idx, axes, k)
            return jax.vmap(one, axis_name=VMAP_AXIS)(s)

        if self.mesh is None:
            _prog = _local
        else:
            from ..parallel.sharded import shard_map
            _prog = shard_map(_local, mesh=self.mesh,
                              in_specs=(P(MESH_AXIS), P()),
                              out_specs=P(MESH_AXIS), check_rep=False)
        inst = obs.instrument_device_fn(
            jax.jit(_prog), "engine.global_topk", k=k,
            n_instances=self.n_instances)

        def fn(state, aux=None):
            _, _, ex = inst(state,
                            _strong(acq.aux if aux is None else aux))
            return ex
        fn.lower = inst.lower
        self._compiled[sig] = fn
        return fn

    def jit_init_slot(self):
        """Jitted (state, i, key) -> state with slot i re-initialized
        from `key` — session join (and slot REUSE after a leave: the
        departed tenant's rows are simply overwritten).  The stacked
        state is donated and updated in place; `i` and `key` are traced,
        so every join dispatches the same compiled program."""
        fn = self._compiled.get("init_slot")
        if self.mesh is not None:
            raise ValueError("slot primitives are unsharded-only")
        if fn is None:
            def _init_slot(s, i, key):
                fresh = _strong(self.engine.init(key))
                return jax.tree.map(lambda a, b: a.at[i].set(b), s, fresh)
            fn = self._compiled["init_slot"] = obs.instrument_device_fn(
                jax.jit(_init_slot, donate_argnums=(0,)),
                "engine.init_slot")
        return fn

    def jit_commit_slot(self):
        """Jitted (state, tstates, cands, keys, raw, i) -> state with
        slot i's pending proposal epoch committed: `tstates`/`cands`/
        `keys` are jit_propose_all outputs (STACKED — the slot is
        sliced inside the program, so the host never tree-maps per
        leaf), `raw` is the [B] un-oriented measured QoR for slot i's
        candidate rows.  The stacked state is donated; only row i
        changes.  No exchange collective runs here: server sessions are
        independent tenants, and cross-tenant coupling belongs to the
        shared results store, not the engine state."""
        fn = self._compiled.get("commit_slot")
        if self.mesh is not None:
            raise ValueError("slot primitives are unsharded-only")
        if fn is None:
            def _commit_slot(s, tstates, cands, keys, raw, i):
                at = lambda t: jax.tree.map(lambda x: x[i], t)  # noqa: E731
                new_i = self.engine.commit(
                    at(s), at(tstates), at(cands), raw, keys[i])
                return jax.tree.map(lambda a, b: a.at[i].set(b), s, new_i)
            fn = self._compiled["commit_slot"] = obs.instrument_device_fn(
                jax.jit(_commit_slot, donate_argnums=(0,)),
                "engine.commit_slot")
        return fn

    # -- host-side results --------------------------------------------------
    def best_qors(self, state: EngineState) -> np.ndarray:
        """[n_instances] per-instance best QoR in USER orientation
        (host sync: the reporting boundary, never jit-reachable)."""
        return self.engine.sign * np.asarray(state.best.qor)

    def best_config(self, state: EngineState, i: int) -> dict:
        """Instance i's incumbent configuration."""
        best = jax.tree.map(lambda x: x[i], state.best)
        return self.engine.space.to_configs(best.as_batch(1))[0]

    def best_configs(self, state: EngineState) -> List[dict]:
        return self.engine.space.to_configs(
            CandBatch(state.best.u, state.best.perms))

    def best(self, state: EngineState) -> Tuple[dict, float]:
        """(config, qor) of the globally best instance."""
        qors = self.best_qors(state)
        i = int(np.argmin(self.engine.sign * qors))
        return self.best_config(state, i), float(qors[i])
