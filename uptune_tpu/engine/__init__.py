from .fused import DeviceObjective, EngineState, FusedEngine, default_arms  # noqa: F401
