from .batched import (BatchedEngine, exchange_best,  # noqa: F401
                      make_instance_mesh, surrogate_eval_fn)
from .fused import DeviceObjective, EngineState, FusedEngine, default_arms  # noqa: F401
