from .batched import (BatchedEngine, StatefulEval,  # noqa: F401
                      exchange_best, exchange_topk,
                      make_instance_mesh, surrogate_aux,
                      surrogate_eval_fn)
from .fused import DeviceObjective, EngineState, FusedEngine, default_arms  # noqa: F401
