"""QuickEst: fast QoR estimation from early-stage features.

TPU-native port of the reference's offline estimator pipeline
(`/root/reference/python/uptune/quickest/`: `train.py:500` train,
`test.py:188` test / `test.py:227` predict(feats, target='LUT_impl'),
`preprocess.py:56`), which predicts post-implementation FPGA
resource/timing (LUT/FF/DSP/BRAM, slack) from early HLS report features
using lasso + XGBoost per target with a stacked linear head.

Here the per-target model is: JAX L1 linear model (ISTA) for feature
selection -> MLP ensemble (uptune_tpu.surrogate.mlp) on the selected
features -> a stacked combination of the linear and MLP heads fit on a
validation split — all jitted, persisted as npz+json.
"""
from .analyze import (analyze, feature_importance, hls_scores,
                      learning_curve, rrse, scores)
from .hlsreport import discover_operations, extract, scrape_checkpoint
from .pipeline import (QuickEst, load_csv, predict, preprocess, test,
                       train)

__all__ = ["QuickEst", "preprocess", "train", "test", "predict",
           "load_csv", "analyze", "scores", "hls_scores",
           "learning_curve", "feature_importance", "rrse",
           "extract", "discover_operations", "scrape_checkpoint"]
