"""QuickEst estimator pipeline (see package docstring).

Reference behavior being matched (file:line into /root/reference/python/
uptune/quickest/):
* per-target model zoo with lasso + tree regressor (`train.py:190-320`
  train_models) -> here lasso (JAX ISTA) + MLP ensemble;
* model assembly: a linear head over member predictions fit on held-out
  data (`train.py:321-500` assemble_models / model_weights);
* feature selection by lasso coefficients (`train.py:369-402`
  select_features);
* metrics: R2 and relative absolute error per target
  (`test.py:91-186` test_models);
* persistence: a model database keyed by target (`train.py` pickles ->
  here a directory of npz + json, no pickle).
"""
from __future__ import annotations

import csv
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


# ---------------------------------------------------------------- data
def load_csv(path: str, target_cols: Sequence[str]
             ) -> Tuple[np.ndarray, np.ndarray, List[str], List[str]]:
    """Load a feature CSV (header row; numeric cells; non-numeric cells
    become NaN -> imputed by preprocess).  Returns
    (X, Y, feature_names, target_names)."""
    with open(path, newline="") as f:
        rows = [r for r in csv.reader(f) if r]   # skip blank lines
    if not rows or len(rows) < 2:
        raise ValueError(f"{path}: need a header row + data rows")
    header = [h.strip() for h in rows[0]]
    missing = [t for t in target_cols if t not in header]
    if missing:
        raise ValueError(f"{path}: target columns {missing} not in header")
    t_idx = [header.index(t) for t in target_cols]
    f_idx = [i for i in range(len(header)) if i not in t_idx]

    def num(cell: str) -> float:
        try:
            return float(cell)
        except ValueError:
            return float("nan")

    data = np.asarray([[num(c) for c in r] for r in rows[1:]], np.float32)
    return (data[:, f_idx], data[:, t_idx],
            [header[i] for i in f_idx], [header[i] for i in t_idx])


def preprocess(x: np.ndarray, *, impute: bool = True,
               drop_constant: bool = True
               ) -> Tuple[np.ndarray, Dict[str, list]]:
    """Column-median imputation + constant-column drop (the reference's
    preprocess.py:56-200 cleanup, minus its workload-specific renames).
    Returns (X_clean, meta) where meta['kept'] indexes original columns
    — apply the same meta to inference-time features via
    `apply_preprocess`."""
    x = np.asarray(x, np.float32).copy()
    finite = np.isfinite(x)
    # column-safe median: all-NaN columns (e.g. a text path column from
    # an extractor CSV) impute to 0 without numpy's All-NaN warning
    med = np.zeros(x.shape[1], np.float32)
    for j in range(x.shape[1]):
        col = x[finite[:, j], j]
        if col.size:
            med[j] = np.median(col)
    if impute:
        bad = ~np.isfinite(x)
        x[bad] = np.broadcast_to(med, x.shape)[bad]
    kept = list(range(x.shape[1]))
    if drop_constant:
        keep = x.std(0) > 1e-12
        kept = [i for i in range(x.shape[1]) if keep[i]]
        x = x[:, keep]
    return x, {"kept": kept, "median": med.tolist()}


def apply_preprocess(x: np.ndarray, meta: Dict[str, list]) -> np.ndarray:
    x = np.asarray(x, np.float32).copy()
    med = np.asarray(meta["median"], np.float32)
    bad = ~np.isfinite(x)
    x[bad] = np.broadcast_to(med, x.shape)[bad]
    return x[:, meta["kept"]]


# ------------------------------------------------------------- metrics
def r2_score(y: np.ndarray, pred: np.ndarray) -> float:
    ss_res = float(((y - pred) ** 2).sum())
    ss_tot = float(((y - y.mean()) ** 2).sum())
    return 1.0 - ss_res / max(ss_tot, 1e-12)


def rae(y: np.ndarray, pred: np.ndarray) -> float:
    """Relative absolute error (the reference's headline metric)."""
    return float(np.abs(y - pred).sum() /
                 max(np.abs(y - y.mean()).sum(), 1e-12))


# ---------------------------------------------------------- JAX models
def _lasso_fit(x, y, lam: float, steps: int = 500):
    """L1 linear regression by ISTA on standardized inputs; returns
    (w, b) in standardized space.  One jitted lax.scan."""
    import jax
    import jax.numpy as jnp

    n, f = x.shape
    lr = 1.0 / max(float(np.linalg.norm(x, 2) ** 2 / n), 1e-8)

    def body(wb, _):
        w, b = wb
        pred = x @ w + b
        g_w = (x.T @ (pred - y)) / n
        g_b = jnp.mean(pred - y)
        w = w - lr * g_w
        w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - lr * lam, 0.0)
        return (w, b - lr * g_b), None

    (w, b), _ = jax.lax.scan(
        body, (jnp.zeros(f), jnp.asarray(0.0)), None, length=steps)
    return w, b


class _TargetModel:
    """lasso feature-selection -> MLP ensemble -> stacked head, for one
    target column."""

    def __init__(self, lam: float = 0.02, top_k: int = 32,
                 n_members: int = 4, mlp_steps: int = 400, seed: int = 0):
        self.lam = lam
        self.top_k = top_k
        self.n_members = n_members
        self.mlp_steps = mlp_steps
        self.seed = seed
        self.sel: Optional[np.ndarray] = None
        self.w = self.b = None            # lasso head (standardized)
        self.x_mean = self.x_std = None
        self.y_mean = self.y_std = None
        self.mlp_state = None
        self.stack = (0.5, 0.5, 0.0)      # (w_linear, w_mlp, bias)

    def fit(self, x: np.ndarray, y: np.ndarray) -> "_TargetModel":
        import jax
        import jax.numpy as jnp

        from ..surrogate import mlp as mlp_mod

        n = x.shape[0]
        if n < 16:
            raise ValueError(
                f"QuickEst needs >= 16 training rows, got {n}")
        self.x_mean = x.mean(0)
        self.x_std = np.maximum(x.std(0), 1e-8)
        self.y_mean = float(y.mean())
        self.y_std = float(max(y.std(), 1e-8))
        xs = (x - self.x_mean) / self.x_std
        ys = (y - self.y_mean) / self.y_std

        # both members train on `tr` only, so the `va` tail is genuinely
        # held out for the stacking weights (the reference assembles on
        # held-out data too, train.py:321-500)
        n_val = max(4, n // 5)
        tr = slice(0, n - n_val)
        va = slice(n - n_val, n)

        w, b = _lasso_fit(jnp.asarray(xs[tr]), jnp.asarray(ys[tr]),
                          self.lam)
        self.w, self.b = np.asarray(w), float(b)
        order = np.argsort(-np.abs(self.w))
        k = min(self.top_k, xs.shape[1])
        sel = order[:k]
        sel = sel[np.abs(self.w[sel]) > 1e-6]
        if len(sel) == 0:
            sel = order[:1]
        self.sel = np.sort(sel)

        # intentional seed-derived key: a QuickEst model is a pure
        # function of (training data, seed) — refits on the same rows
        # must reproduce bit-identically, so there is no stored key to
        # split
        self.mlp_state = mlp_mod.fit(
            jax.random.PRNGKey(self.seed),
            jnp.asarray(xs[tr][:, self.sel]),
            jnp.asarray(ys[tr]), n_members=self.n_members,
            steps=self.mlp_steps)
        lin_va = xs[va] @ self.w + self.b
        mlp_va, _ = mlp_mod.predict(self.mlp_state,
                                    jnp.asarray(xs[va][:, self.sel]))
        mlp_va = np.asarray(mlp_va)
        a = np.stack([lin_va, mlp_va, np.ones_like(lin_va)], 1)
        coef, *_ = np.linalg.lstsq(a, ys[va], rcond=None)
        self.stack = tuple(float(c) for c in coef)
        return self

    def predict(self, x: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        from ..surrogate import mlp as mlp_mod

        xs = (np.asarray(x, np.float32) - self.x_mean) / self.x_std
        lin = xs @ self.w + self.b
        mlpp, _ = mlp_mod.predict(self.mlp_state,
                                  jnp.asarray(xs[:, self.sel]))
        wl, wm, c = self.stack
        ys = wl * lin + wm * np.asarray(mlpp) + c
        return ys * self.y_std + self.y_mean

    # ------------------------------------------------------ persistence
    def state_arrays(self) -> Dict[str, np.ndarray]:
        import jax

        out = {"w": self.w, "b": np.asarray([self.b]), "sel": self.sel,
               "x_mean": self.x_mean, "x_std": self.x_std,
               "y_ms": np.asarray([self.y_mean, self.y_std]),
               "stack": np.asarray(self.stack)}
        leaves = jax.tree.leaves(self.mlp_state)
        for i, leaf in enumerate(leaves):
            out[f"mlp_{i}"] = np.asarray(leaf)
        return out

    def load_arrays(self, arrs: Dict[str, np.ndarray]) -> "_TargetModel":
        from ..surrogate.mlp import MLPEnsembleState

        self.w = arrs["w"]
        self.b = float(arrs["b"][0])
        self.sel = arrs["sel"]
        self.x_mean, self.x_std = arrs["x_mean"], arrs["x_std"]
        self.y_mean, self.y_std = (float(arrs["y_ms"][0]),
                                   float(arrs["y_ms"][1]))
        self.stack = tuple(float(v) for v in arrs["stack"])
        n_layers = len([k for k in arrs if k.startswith("mlp_")])
        leaves = [arrs[f"mlp_{i}"] for i in range(n_layers)]
        # reconstruct the pytree structure: params is a tuple of (w, b)
        # layer pairs with leading ensemble axis, then 4 scalar stats
        n_params = n_layers - 4
        params = tuple((leaves[i], leaves[i + 1])
                       for i in range(0, n_params, 2))
        self.mlp_state = MLPEnsembleState(params, *leaves[n_params:])
        return self


class QuickEst:
    """Multi-target QoR estimator (the reference's model database keyed
    by target name, e.g. 'LUT_impl')."""

    def __init__(self, **model_opts):
        self.model_opts = model_opts
        self.models: Dict[str, _TargetModel] = {}
        self.pre_meta: Optional[Dict[str, list]] = None
        self.feature_names: Optional[List[str]] = None

    def fit(self, x: np.ndarray, y: np.ndarray,
            target_names: Sequence[str],
            feature_names: Optional[Sequence[str]] = None) -> "QuickEst":
        y = np.asarray(y, np.float32)
        if y.ndim == 1:
            y = y[:, None]
        assert y.shape[1] == len(target_names)
        x, self.pre_meta = preprocess(x)
        self.feature_names = (list(feature_names)
                              if feature_names is not None else None)
        opts = dict(self.model_opts)
        base_seed = opts.pop("seed", 0)
        for j, name in enumerate(target_names):
            self.models[name] = _TargetModel(
                seed=base_seed + j, **opts).fit(x, y[:, j])
        return self

    def predict(self, feats: np.ndarray,
                target: str = "LUT_impl") -> np.ndarray:
        """Match test.py:227 predict(feats, target='LUT_impl')."""
        if target not in self.models:
            raise KeyError(
                f"no model for target {target!r}; have "
                f"{sorted(self.models)}")
        feats = np.atleast_2d(np.asarray(feats, np.float32))
        return self.models[target].predict(
            apply_preprocess(feats, self.pre_meta))

    def score(self, x: np.ndarray, y: np.ndarray,
              target_names: Sequence[str]) -> Dict[str, Dict[str, float]]:
        y = np.asarray(y, np.float32)
        if y.ndim == 1:
            y = y[:, None]
        out = {}
        for j, name in enumerate(target_names):
            pred = self.predict(x, name)
            out[name] = {"r2": r2_score(y[:, j], pred),
                         "rae": rae(y[:, j], pred)}
        return out

    # ------------------------------------------------------ persistence
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        meta = {"targets": sorted(self.models),
                "pre_meta": self.pre_meta,
                "feature_names": self.feature_names,
                "model_opts": self.model_opts}
        with open(os.path.join(path, "quickest.json"), "w") as f:
            json.dump(meta, f)
        for name, m in self.models.items():
            np.savez(os.path.join(path, f"target_{name}.npz"),
                     **m.state_arrays())

    @classmethod
    def load(cls, path: str) -> "QuickEst":
        with open(os.path.join(path, "quickest.json")) as f:
            meta = json.load(f)
        est = cls(**meta["model_opts"])
        est.pre_meta = meta["pre_meta"]
        est.feature_names = meta["feature_names"]
        for name in meta["targets"]:
            arrs = dict(np.load(os.path.join(path, f"target_{name}.npz")))
            est.models[name] = _TargetModel(
                **meta["model_opts"]).load_arrays(arrs)
        return est


# ------------------------------------------------- module-level facade
_DEFAULT_DIR = "quickest_models"


def train(x: np.ndarray, y: np.ndarray, target_names: Sequence[str],
          save_dir: Optional[str] = _DEFAULT_DIR,
          feature_names: Optional[Sequence[str]] = None,
          **model_opts) -> QuickEst:
    """Train + persist (the reference's `train()` CLI, train.py:500).
    Pass `feature_names` so downstream feature-importance reports name
    real features instead of positional f{i} placeholders."""
    est = QuickEst(**model_opts).fit(x, y, target_names,
                                     feature_names=feature_names)
    if save_dir:
        est.save(save_dir)
    return est


def test(x: np.ndarray, y: np.ndarray, target_names: Sequence[str],
         model_dir: str = _DEFAULT_DIR) -> Dict[str, Dict[str, float]]:
    """Score a persisted model DB (test.py:188)."""
    return QuickEst.load(model_dir).score(x, y, target_names)


def predict(feats: np.ndarray, target: str = "LUT_impl",
            model_dir: str = _DEFAULT_DIR) -> np.ndarray:
    """One-shot prediction from the persisted model DB (test.py:227)."""
    return QuickEst.load(model_dir).predict(feats, target)
