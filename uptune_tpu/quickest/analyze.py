"""QuickEst analysis stage: learning curves, model scores, feature
importance — the research loop that answers "how much data does the
estimator need and which features matter".

Reference: `/root/reference/python/uptune/quickest/analyze.py` —
`analyze_learning_curve` (:417-495, per-target train/test RRSE as the
training-set prefix grows), `analyze_scores` (:242-291, per-model
RAE/R2/RRSE tables written as CSVs), `analyze_feature_importance`
(:149-198, per-target lasso |coef| / tree split-weight tables),
`analyze_scores_hls` (:293-333, the no-model baseline scoring each early
HLS feature directly against its matching target), dispatched by the
`analyze()` CLI switch (:498).  The reference re-fits sklearn
Lasso/XGBoost per curve point; here each point re-fits the JAX
lasso->MLP->stack target model of `pipeline._TargetModel` with the same
hyperparameters, so the curve reflects the estimator actually shipped.

All outputs are plain dicts plus optional CSV files (no pandas/pickle);
plotting is delegated to the caller or `save_plots` (matplotlib gated).
"""
from __future__ import annotations

import csv
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from .pipeline import (QuickEst, _TargetModel, apply_preprocess,
                       preprocess, r2_score, rae)


def rrse(y: np.ndarray, pred: np.ndarray) -> float:
    """Root relative squared error (analyze.py:219-228), the
    reference's learning-curve metric."""
    num = float(((y - pred) ** 2).sum())
    den = float(((y - y.mean()) ** 2).sum())
    return float(np.sqrt(num / max(den, 1e-12)))


def scores(est: QuickEst, x: np.ndarray, y: np.ndarray,
           target_names: Sequence[str],
           save_dir: Optional[str] = None
           ) -> Dict[str, Dict[str, float]]:
    """Per-target RAE/R2/RRSE of a fitted estimator on held-out data
    (analyze_scores, analyze.py:242-291)."""
    y = np.asarray(y, np.float32)
    if y.ndim == 1:
        y = y[:, None]
    out: Dict[str, Dict[str, float]] = {}
    for j, name in enumerate(target_names):
        pred = est.predict(x, name)
        out[name] = {"RAE": rae(y[:, j], pred),
                     "R2": r2_score(y[:, j], pred),
                     "RRSE": rrse(y[:, j], pred)}
    if save_dir:
        _write_table(os.path.join(save_dir, "scores.csv"),
                     ["target", "RAE", "R2", "RRSE"],
                     [[n, m["RAE"], m["R2"], m["RRSE"]]
                      for n, m in out.items()])
    return out


def hls_scores(x: np.ndarray, y: np.ndarray,
               pairs: Sequence[tuple],
               feature_names: Sequence[str],
               target_names: Sequence[str],
               save_dir: Optional[str] = None
               ) -> Dict[str, Dict[str, float]]:
    """The no-model baseline (analyze_scores_hls, analyze.py:293-333):
    score an early HLS feature DIRECTLY as the prediction of its
    post-implementation counterpart — the floor any learned estimator
    must beat.  `pairs` maps (feature_name, target_name); the result is
    keyed by (feature, target) so two early features scored against the
    same target both survive (the reference emits one row per pair)."""
    x = np.atleast_2d(np.asarray(x, np.float32))
    y = np.atleast_2d(np.asarray(y, np.float32))
    out: Dict[tuple, Dict[str, float]] = {}
    for feat, tgt in pairs:
        fi = list(feature_names).index(feat)
        ti = list(target_names).index(tgt)
        fx, ty = x[:, fi], y[:, ti]
        out[(feat, tgt)] = {"feature": feat, "target": tgt,
                            "RAE": rae(ty, fx),
                            "R2": r2_score(ty, fx), "RRSE": rrse(ty, fx)}
    if save_dir:
        _write_table(os.path.join(save_dir, "scores_hls.csv"),
                     ["target", "feature", "RAE", "R2", "RRSE"],
                     [[m["target"], m["feature"], m["RAE"], m["R2"],
                       m["RRSE"]] for m in out.values()])
    return out


def learning_curve(x_train: np.ndarray, y_train: np.ndarray,
                   x_test: np.ndarray, y_test: np.ndarray,
                   target_names: Sequence[str],
                   points: int = 8,
                   save_dir: Optional[str] = None,
                   **model_opts) -> Dict[str, Dict[str, list]]:
    """Train/test RRSE per target as the training prefix grows
    (analyze_learning_curve, analyze.py:417-495: prefixes from ~15% of
    the data up to all of it).  Answers the QuickEst research question:
    how many implementation runs must be collected before the estimator
    is trustworthy?"""
    y_train = np.asarray(y_train, np.float32)
    y_test = np.asarray(y_test, np.float32)
    if y_train.ndim == 1:
        y_train = y_train[:, None]
    if y_test.ndim == 1:
        y_test = y_test[:, None]
    n = x_train.shape[0]
    lo = max(16, int(round(n * 0.15)))   # _TargetModel floor is 16 rows
    if lo >= n:
        raise ValueError(f"need > {lo} training rows for a curve, got {n}")
    nums = sorted({int(v) for v in np.linspace(lo, n, points)})
    xt_clean, meta = preprocess(x_train)
    xe_clean = apply_preprocess(x_test, meta)
    base_seed = model_opts.pop("seed", 0)

    out: Dict[str, Dict[str, list]] = {}
    for j, name in enumerate(target_names):
        tr_scores, te_scores = [], []
        for num in nums:
            m = _TargetModel(seed=base_seed + j, **model_opts).fit(
                xt_clean[:num], y_train[:num, j])
            tr_scores.append(rrse(y_train[:num, j],
                                  m.predict(xt_clean[:num])))
            te_scores.append(rrse(y_test[:, j], m.predict(xe_clean)))
        out[name] = {"nums": nums, "train": tr_scores, "test": te_scores}
    if save_dir:
        rows = [[name, num, tr, te]
                for name, d in out.items()
                for num, tr, te in zip(d["nums"], d["train"], d["test"])]
        _write_table(os.path.join(save_dir, "learning_curve.csv"),
                     ["target", "train_rows", "rrse_train", "rrse_test"],
                     rows)
    return out


def feature_importance(est: QuickEst,
                       save_dir: Optional[str] = None
                       ) -> Dict[str, Dict[str, float]]:
    """Per-target normalized |lasso coefficient| over the preprocessed
    feature set, plus which features the MLP stage actually consumes
    (analyze_feature_importance, analyze.py:149-198 — lasso weights and
    tree split-weights; our second stage's 'importance' is membership in
    the lasso-selected set)."""
    out: Dict[str, Dict[str, float]] = {}
    kept = est.pre_meta["kept"] if est.pre_meta else None
    for name, m in est.models.items():
        w = np.abs(np.asarray(m.w, np.float64))
        total = w.sum() or 1.0
        fn = _kept_names(est.feature_names, kept, len(w))
        imp = {fn[i]: float(w[i] / total) for i in range(len(w))}
        out[name] = dict(sorted(imp.items(), key=lambda kv: -kv[1]))
        out[name]["__selected__"] = [fn[i] for i in m.sel]  # type: ignore
    if save_dir:
        feats = sorted({f for d in out.values()
                        for f in d if f != "__selected__"})
        rows = [[f] + [out[t].get(f, 0.0) for t in est.models]
                for f in feats]
        _write_table(os.path.join(save_dir, "feature_importance.csv"),
                     ["feature"] + list(est.models), rows)
    return out


def analyze(func: str = "scores", **kwargs):
    """Dispatch façade mirroring the reference CLI's -f switch
    (analyze.py:498 + the abbreviation table at :49-60)."""
    table = {
        "sc": scores, "scores": scores, "score": scores,
        "schls": hls_scores, "score_hls": hls_scores, "hls": hls_scores,
        "lc": learning_curve, "learning_curve": learning_curve,
        "fi": feature_importance, "feature_importance": feature_importance,
    }
    if func not in table:
        raise ValueError(
            f"unknown analysis {func!r}; known: {sorted(table)}")
    return table[func](**kwargs)


def _kept_names(feature_names: Optional[Sequence[str]],
                kept: Optional[Sequence[int]], n: int) -> List[str]:
    if feature_names is None:
        return [f"f{i}" for i in range(n)]
    if kept is None:
        return list(feature_names)[:n]
    return [feature_names[i] for i in kept]


def _write_table(path: str, header: Sequence[str], rows) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(header)
        for r in rows:
            w.writerow(r)
