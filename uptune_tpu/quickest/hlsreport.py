"""HLS-report feature extractor: design directories -> QuickEst CSV.

The reference front-end walks LegUp HLS output trees and scrapes early
(pre-implementation) features plus post-fit targets into the feature CSV
the estimator trains on (`/root/reference/python/uptune/quickest/extract/
LegUp/funcs.py:270-447` ExtractData/ExtractData_file; the name lists at
funcs.py:154-267).  This module provides the same capability as a
declarative parse table driving one generic scraper — stdlib-only, so it
runs on hosts without the EDA tools installed.

Layout expectations (funcs.py:283-289): a design directory contains one
subdirectory per clock-period checkpoint matching ``*CP_<n>``; each holds
the HLS reports (``scheduling.legup.rpt``, ``resources.legup.rpt``,
``timingReport.legup.rpt``, ``*.v``) and, once implementation ran, the
fit report (``top.fit.rpt``) whose numbers are the prediction TARGETS.

Emitted CSV schema (funcs.py:274-281): ``Design_Path, Design_Index,
Device_Index, <early features...>, <operation counts...>, <targets...>``
— directly loadable by `uptune_tpu.quickest.load_csv` with
``target_cols=TARGETS`` (drop the path column first or let preprocess
impute the non-numeric cells away).
"""
from __future__ import annotations

import csv
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# early scheduling/resource features (funcs.py:154-163)
BASE_FEATURES = [
    "Registers", "DSP Elements", "Combinational", "RAM Elements",
    "Logic Elements", "Clock Period", "Delay_of_path_max",
    "Delay_of_path_min", "Delay_of_path_mean", "Delay_of_path_med",
]

# operation-mix counts (funcs.py:165-244 lists ~80 of these; the set is
# design-suite-dependent, so discover_operations() can mine the actual
# names from a tree instead of hardcoding the reference's suite)
DEFAULT_OPERATIONS = [
    "signed_add_8", "signed_add_16", "signed_add_32", "signed_add_64",
    "signed_subtract_32", "signed_multiply_32", "signed_divide_32",
    "signed_comp_eq_8", "signed_comp_eq_32", "signed_comp_eq_64",
    "signed_comp_eq_mux_32", "signed_comp_lt_32", "signed_comp_gt_32",
    "shift_ll_32", "shift_rl_32", "bitwise_AND_32", "bitwise_OR_32",
    "bitwise_XOR_32", "mux_2_32", "reg_32",
]

# post-implementation targets (funcs.py:246-267)
TARGETS = [
    "Registers_used", "DSP_blocks_used", "ALUT_used",
    "Block_memory_bits_used", "RAM_blocks_used",
]

_CP_DIR = re.compile(r"^.*?CP_[0-9]+$")

# fit-report rows: line marker -> [(field, group)] over '; N / M' or '; N '
_FIT_ROWS: List[Tuple[str, List[Tuple[str, int]]]] = [
    ("; Total registers", [("Registers_used", 1)]),
    ("; Total block memory bits", [("Block_memory_bits_used", 1),
                                   ("Total_Block_memory_bits", 2)]),
    ("; Total RAM Blocks", [("RAM_blocks_used", 1),
                            ("Total_RAM_blocks", 2)]),
    ("; Total DSP Blocks", [("DSP_blocks_used", 1),
                            ("Total_DSP_blocks", 2)]),
    ("; Combinational ALUT usage for logic", [("ALUT_for_logic", 1)]),
    ("; Combinational ALUT usage for route-throughs",
     [("ALUT_for_route-throughs", 1)]),
    ("; Memory ALUT usage", [("ALUT_for_memory", 1)]),
]
_FIT_NUM = re.compile(r"; ([0-9,]+)(?: / ([0-9,]+))?")


def _to_int(txt: str) -> int:
    return int(txt.replace(",", ""))


def scrape_checkpoint(path: str,
                      operations: Sequence[str]) -> Dict[str, object]:
    """Scrape one ``*CP_<n>`` checkpoint directory into a flat record
    (missing reports simply leave their fields absent; operation counts
    default to 0 as in funcs.py:308-310)."""
    rec: Dict[str, object] = {op: 0 for op in operations}

    p = os.path.join(path, "scheduling.legup.rpt")
    if os.path.exists(p):
        with open(p, errors="replace") as f:
            for line in f:
                if "Clock period constraint" in line:
                    m = re.search(r": (.+)ns", line)
                    if m:
                        rec["Clock Period"] = float(m.group(1))
                    break

    p = os.path.join(path, "resources.legup.rpt")
    if os.path.exists(p):
        with open(p, errors="replace") as f:
            for line in f:
                for name in ("Logic Elements", "Combinational",
                             "Registers", "DSP Elements"):
                    if name in line:
                        # first number only: real report lines carry
                        # trailing text ('Registers: 450 / 114480 (12%)')
                        m = re.search(r": ([0-9,]+)", line)
                        if m:
                            rec[name] = _to_int(m.group(1))
                m = re.search(r'Operation "(.+)" x ([0-9,]+)', line)
                if m and m.group(1) in rec:
                    rec[m.group(1)] = _to_int(m.group(2))

    p = os.path.join(path, "timingReport.legup.rpt")
    if os.path.exists(p):
        delays: List[float] = []
        with open(p, errors="replace") as f:
            for line in f:
                m = re.search(r"-Delay of path:([0-9,.]+) ns-", line)
                if m:
                    delays.append(float(m.group(1).replace(",", "")))
        if delays:
            delays.sort()
            n = len(delays)
            med = (delays[n // 2] if n % 2 else
                   0.5 * (delays[n // 2 - 1] + delays[n // 2]))
            rec.update({"Delay_of_path_max": delays[-1],
                        "Delay_of_path_min": delays[0],
                        "Delay_of_path_mean": sum(delays) / n,
                        "Delay_of_path_med": med})
        else:
            rec.update({k: 0 for k in (
                "Delay_of_path_max", "Delay_of_path_min",
                "Delay_of_path_mean", "Delay_of_path_med")})

    # first match across the (sorted, deterministic) .v files wins;
    # generated netlists can be MBs, so stop at the first hit
    for fn in sorted(os.listdir(path)):
        if os.path.splitext(fn)[1] != ".v" or "RAM Elements" in rec:
            continue
        with open(os.path.join(path, fn), errors="replace") as f:
            for line in f:
                m = re.search(
                    r"// Number of RAM elements: ([0-9,]+)", line)
                if m:
                    rec["RAM Elements"] = _to_int(m.group(1))
                    break

    p = os.path.join(path, "top.fit.rpt")
    if os.path.exists(p):
        with open(p, errors="replace") as f:
            for line in f:
                for marker, fields in _FIT_ROWS:
                    if marker in line:
                        m = _FIT_NUM.search(line)
                        if m:
                            for field, g in fields:
                                if m.group(g) is not None:
                                    rec[field] = _to_int(m.group(g))
        aluts = [rec.get(k) for k in ("ALUT_for_logic",
                                      "ALUT_for_route-throughs",
                                      "ALUT_for_memory")]
        if any(a is not None for a in aluts):
            rec["ALUT_used"] = sum(a or 0 for a in aluts)
    return rec


def discover_operations(design_dirs: Iterable[str]) -> List[str]:
    """Mine the operation names actually present in a tree (the
    reference's WhatFeatures pass, funcs.py:454-470) so the CSV schema
    matches the design suite instead of a hardcoded list."""
    ops = set()
    for d in design_dirs:
        for cp in _iter_checkpoints(d):
            p = os.path.join(cp, "resources.legup.rpt")
            if not os.path.exists(p):
                continue
            with open(p, errors="replace") as f:
                for line in f:
                    m = re.search(r'Operation "(.+)" x ', line)
                    if m:
                        ops.add(m.group(1))
    return sorted(ops)


def _iter_checkpoints(design_dir: str) -> List[str]:
    if not os.path.isdir(design_dir):
        return []
    return sorted(os.path.join(design_dir, y)
                  for y in os.listdir(design_dir)
                  if _CP_DIR.match(y)
                  and os.path.isdir(os.path.join(design_dir, y)))


def extract(design_dirs: Sequence[str], out_csv: str,
            operations: Optional[Sequence[str]] = None,
            targets: Sequence[str] = tuple(TARGETS),
            require_targets: bool = True) -> int:
    """Walk design directories and write the QuickEst feature CSV;
    returns the number of data rows written.

    A checkpoint row is emitted only when every REQUESTED target was
    actually scraped (funcs.py:438-439 skips rows whose implementation
    never ran; here the gate follows the caller's `targets` so custom
    target sets aren't silently judged by the reference's two fields)
    unless ``require_targets=False`` (inference-time extraction, where
    the targets are what the estimator will predict)."""
    if operations is None:
        operations = discover_operations(design_dirs) or DEFAULT_OPERATIONS
    feat_cols = BASE_FEATURES + list(operations)
    header = (["Design_Path", "Design_Index", "Device_Index"]
              + feat_cols + list(targets))
    rows = 0
    with open(out_csv, "w", newline="") as out:
        w = csv.writer(out)
        w.writerow(header)
        for di, d in enumerate(design_dirs):
            for cp in _iter_checkpoints(d):
                rec = scrape_checkpoint(cp, operations)
                if require_targets and not all(t in rec for t in targets):
                    continue
                w.writerow([os.path.abspath(cp), di, 0]
                           + [rec.get(c, "") for c in feat_cols]
                           + [rec.get(t, "") for t in targets])
                rows += 1
    return rows
