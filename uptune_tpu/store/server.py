"""The networked ResultStore server (ISSUE 18): one crash-safe
process holding the shared results table for a cooperating fleet.

`ResultStore` already exchanges rows between instances — but only
through a shared filesystem (each instance re-scans the directory's
segments).  The reference synchronized its fleet through one global
SQLite database every `MpiController` worker published into (PAPER.md
L1/L4); this module is the TPU-native equivalent over the repo's own
seams: a `StoreServer` on the serve/wire.py kernel speaking

* ``hello``  — client announces itself (+ optional scope): returns the
  server incarnation token and the scope's row count,
* ``lookup`` — one content key -> its finite row (the memo read),
* ``record`` — one row in, durably appended, THEN acked.  Duplicate
  keys are acked as ``dup`` without an append — the content-key dedup
  that makes a reconnecting client's write-behind replay idempotent,
* ``delta``  — the `pop_fresh_rows` feed generalized over the wire:
  rows appended after a client-held cursor, filtered to the requested
  scope and excluding the requester's own rows,
* ``best`` / ``stats`` / ``metrics`` / ``health`` — incumbent query,
  accounting, the `ut top --addr` scrape, and the hub's worst-first
  fold entry (``by_status``, the PR 14 rollup shape).

Durability is the CheckpointLog write discipline (serve/durable.py):
one complete JSON line per accepted row via a single ``O_APPEND``
write — the ack is sent only after the append returns, so a SIGKILL
can never lose an acked row (page-cache durable; ``--fsync`` extends
that to power loss).  Restart replays the log torn-tail-tolerantly: a
partial tail line (the append the crash interrupted) ends the usable
prefix.  ``faults.fire("rstore.append")`` sits inside the append so
`bench.py --store-remote` can kill the server at a deterministic
append and prove the contract.
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from .. import obs
from ..obs import faults
from ..serve.wire import RequestError, WireServer
from .store import _finite

log = logging.getLogger("uptune_tpu")

__all__ = ["StoreServer", "main", "LOG_FILE", "DELTA_MAX"]

LOG_FILE = "rows.jsonl"         # the server's single durable log
DELTA_MAX = 512                 # rows per delta reply (clients loop)

# the row fields a record op may carry — anything else is dropped so
# one client cannot bloat every sibling's delta feed with junk
_ROW_FIELDS = ("k", "scope", "cfg", "qor", "dur", "t", "src", "u",
               "perms")


class StoreServer(WireServer):
    """One shared results table behind a TCP port.

    The table is rebuilt from the durable log on construction
    (torn-tail-tolerant, exactly the segment-load rule ResultStore
    applies to its shards), so a SIGKILLed server restarted on the
    same directory serves every row it ever acked.  ``incarn`` is a
    fresh token per construction: delta cursors are positions in THIS
    incarnation's append order, and a client presenting a stale
    incarnation is restarted from 0 (its local table dedups the
    re-read)."""

    WIRE_NAME = "ut-store"

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 root: Optional[str] = None, *, fsync: bool = False):
        super().__init__(host, port)
        self.root = os.path.abspath(root or os.path.join(
            os.getcwd(), "ut.store"))
        os.makedirs(self.root, exist_ok=True)
        self.fsync = bool(fsync)
        self.log_path = os.path.join(self.root, LOG_FILE)
        self.incarn = f"{os.getpid():d}-{os.urandom(4).hex()}"
        # _lock (WireServer's RLock) guards the table + counters;
        # _io_lock is the fd-lifecycle leaf lock (the ResultStore
        # discipline: acquire order _lock -> _io_lock, never reverse)
        self._io_lock = threading.Lock()
        self._fd: Optional[int] = None
        self._rows: Dict[str, Dict[str, Any]] = {}
        self._seq: List[str] = []      # keys in durable append order
        self.hits = 0
        self.misses = 0
        self.recorded = 0              # rows accepted this incarnation
        self.dups = 0                  # idempotent re-records acked
        self.appends = 0               # durable appends this incarnation
        self.append_errors = 0
        self.replayed = 0              # rows recovered from the log
        self.torn_tail = False
        self._clients = 0
        # a store server is a serving process: the scrape op (and the
        # hub's fold) always has data
        if not obs.enabled():
            obs.enable()
        self._replay()

    # -- durability ----------------------------------------------------
    def _replay(self) -> None:
        """Rebuild table + append order from the durable log.  The
        CheckpointLog load rule: only COMPLETE lines count, and a bad
        line mid-file ends the usable prefix (bytes after a torn
        append are one interrupted write's debris, not data)."""
        try:
            with open(self.log_path, "rb") as f:
                buf = f.read()
        except OSError:
            return
        for line in buf.split(b"\n"):
            if not line.strip():
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                self.torn_tail = True
                break
            if isinstance(row, dict) and isinstance(row.get("k"), str):
                self._merge(row)
                self.replayed += 1
        if self.replayed or self.torn_tail:
            log.info("[%s] replayed %d row(s) from %s%s",
                     self.WIRE_NAME, self.replayed, self.log_path,
                     " (torn tail dropped)" if self.torn_tail else "")

    def _merge(self, row: Dict[str, Any]) -> bool:
        """First-finite-wins merge (caller holds ``_lock`` or is the
        single-threaded replay).  Returns True when the row changed
        the table."""
        k = row["k"]
        cur = self._rows.get(k)
        if cur is not None and (_finite(cur.get("qor"))
                                or not _finite(row.get("qor"))):
            return False
        self._rows[k] = row
        if cur is None:
            self._seq.append(k)
        return True

    def _append_durable(self, row: Dict[str, Any]) -> None:
        """One row -> one complete O_APPEND line, flushed before the
        caller acks (serve/durable.py's ack-after-durable discipline).
        The fault point fires INSIDE the append window so an armed
        crash lands exactly where the loss bound is contested."""
        data = (json.dumps(row, separators=(",", ":"),
                           allow_nan=False) + "\n").encode()
        with self._io_lock:
            faults.fire("rstore.append")
            if self._fd is None:
                self._fd = os.open(
                    self.log_path,
                    os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            os.write(self._fd, data)   # one write = one atomic line
            fd = os.dup(self._fd) if self.fsync else None
        if fd is not None:
            # the power-loss barrier runs outside the lock on a dup'd
            # fd (the ResultStore R102 rule): the row is on disk when
            # the ack goes out either way
            try:
                os.fsync(fd)
            finally:
                os.close(fd)

    # -- ops -----------------------------------------------------------
    def _op_hello(self, req: dict) -> dict:
        scope = req.get("scope")
        with self._lock:
            rows = (sum(1 for r in self._rows.values()
                        if r.get("scope") == scope)
                    if isinstance(scope, str) else len(self._rows))
        return {"role": "ut-store", "incarn": self.incarn,
                "rows": rows, "started_unix": self.started_unix}

    def _op_lookup(self, req: dict) -> dict:
        k = req.get("k")
        if not isinstance(k, str):
            raise RequestError("lookup needs a string 'k'")
        with self._lock:
            row = self._rows.get(k)
            if row is not None and _finite(row.get("qor")):
                self.hits += 1
                obs.count("store.hits")
                return {"row": row}
            self.misses += 1
            obs.count("store.misses")
            return {"row": None}

    def _op_record(self, req: dict) -> dict:
        raw = req.get("row")
        if not isinstance(raw, dict) or not isinstance(raw.get("k"),
                                                       str) \
                or not isinstance(raw.get("scope"), str) \
                or not isinstance(raw.get("cfg"), dict):
            raise RequestError(
                "record needs a row object with k/scope/cfg")
        qor = raw.get("qor")
        if qor is not None:
            try:
                qor = float(qor)
            except (TypeError, ValueError):
                raise RequestError(f"row qor must be a number or "
                                   f"null: {qor!r}")
            if not _finite(qor):
                qor = None
        row = {f: raw[f] for f in _ROW_FIELDS if f in raw}
        row["qor"] = qor
        with self._lock:
            cur = self._rows.get(row["k"])
            if cur is not None and (_finite(cur.get("qor"))
                                    or not _finite(qor)):
                # content-key dedup: a write-behind replay after
                # reconnect re-sends its in-flight rows — ack, never
                # re-append (idempotency is the client's durability)
                self.dups += 1
                return {"acked": True, "dup": True}
        # the durable append runs OUTSIDE _lock (lookups must not
        # queue behind disk); ack-after-durable means the table insert
        # and the ack both happen only after the append returned.  Two
        # racers on one fresh key may both append — duplicate log
        # lines merge away on replay, exactly like duplicate segment
        # rows in ResultStore
        try:
            self._append_durable(row)
        except OSError:
            with self._lock:
                self.append_errors += 1
            raise
        with self._lock:
            self.appends += 1
            obs.count("rstore.appends")
            if self._merge(row):
                self.recorded += 1
                obs.count("store.recorded")
                return {"acked": True, "dup": False}
            self.dups += 1
            return {"acked": True, "dup": True}

    def _op_delta(self, req: dict) -> dict:
        scope = req.get("scope")
        if not isinstance(scope, str):
            raise RequestError("delta needs a string 'scope'")
        src = req.get("src")
        try:
            cursor = int(req.get("cursor", 0))
        except (TypeError, ValueError):
            raise RequestError(
                f"cursor must be an integer: {req.get('cursor')!r}")
        if req.get("incarn") not in (None, self.incarn):
            # the client's cursor indexes a DEAD incarnation's append
            # order: restart it (its local table dedups the re-read)
            cursor = 0
        cursor = max(0, cursor)
        out: List[Dict[str, Any]] = []
        with self._lock:
            total = len(self._seq)
            while cursor < total and len(out) < DELTA_MAX:
                r = self._rows.get(self._seq[cursor])
                cursor += 1
                if r is not None and r.get("scope") == scope \
                        and r.get("src") != src \
                        and _finite(r.get("qor")):
                    out.append(r)
            more = cursor < total
        return {"rows": out, "cursor": cursor, "more": more,
                "incarn": self.incarn}

    def _op_best(self, req: dict) -> dict:
        scope = req.get("scope")
        if not isinstance(scope, str):
            raise RequestError("best needs a string 'scope'")
        sense = str(req.get("sense", "min"))
        pick = min if sense != "max" else max
        with self._lock:
            rows = [r for r in self._rows.values()
                    if r.get("scope") == scope
                    and _finite(r.get("qor"))]
        if not rows:
            return {"row": None}
        return {"row": pick(rows, key=lambda r: float(r["qor"]))}

    def _op_stats(self, req: dict) -> dict:
        with self._lock:
            scopes = len({r.get("scope") for r in self._rows.values()})
            return {"rows": len(self._rows), "scopes": scopes,
                    "hits": self.hits, "misses": self.misses,
                    "recorded": self.recorded, "dups": self.dups,
                    "appends": self.appends,
                    "append_errors": self.append_errors,
                    "replayed": self.replayed,
                    "torn_tail": self.torn_tail,
                    "clients": self._clients, "incarn": self.incarn,
                    "root": self.root, "fsync": self.fsync}

    def _op_metrics(self, req: dict) -> dict:
        """The `ut top --addr` scrape — the session server's payload
        shape (top.sample_from_scrape), carrying the store.* counters
        plus rstore.appends for the acked-append gauge."""
        fmt = str(req.get("format", "json")).lower()
        with self._lock:
            clients = self._clients
        out: Dict[str, Any] = {
            "sessions": clients,
            "uptime_s": round(time.time() - self.started_unix, 3)}
        if fmt == "prometheus":
            out["metrics_text"] = obs.prometheus_text()
        elif fmt == "json":
            out["metrics"] = obs.metrics_snapshot()
        else:
            raise RequestError(
                f"metrics format must be json|prometheus: {fmt!r}")
        return out

    def _op_health(self, req: dict) -> dict:
        """The hub's fold entry (obs/hub.py adopts the worst
        ``by_status`` verdict of a shipped health rollup): ``failing``
        when durable appends error, ``cold`` while the table is empty,
        ``ok`` otherwise."""
        with self._lock:
            if self.append_errors:
                status = "failing"
            elif not self._rows:
                status = "cold"
            else:
                status = "ok"
            return {"role": "ut-store", "status": status,
                    "by_status": {status: max(1, self._clients)},
                    "rows": len(self._rows),
                    "clients": self._clients,
                    "appends": self.appends,
                    "append_errors": self.append_errors}

    def _op_ping(self, req: dict) -> dict:
        return {"role": "ut-store", "t": time.time()}

    _OPS = {"hello": _op_hello, "lookup": _op_lookup,
            "record": _op_record, "delta": _op_delta,
            "best": _op_best, "stats": _op_stats,
            "metrics": _op_metrics, "health": _op_health,
            "ping": _op_ping}

    # -- connection accounting (the WireServer reaping seam) -----------
    def _conn_opened(self, conn, addr) -> Any:
        with self._lock:
            self._clients += 1
        return True

    def _conn_closed(self, state: Any) -> None:
        if state:
            with self._lock:
                self._clients -= 1

    def _listen_banner(self) -> str:
        return (f" (store root {self.root}, {len(self._rows)} row(s)"
                f"{', fsync' if self.fsync else ''})")

    def stop(self) -> None:
        super().stop()
        with self._io_lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None


def main(argv: Optional[List[str]] = None) -> int:
    """``ut store`` — run a store server (docs/STORE.md "Remote
    store")."""
    p = argparse.ArgumentParser(
        prog="ut store",
        description="networked results-store server: tuning processes "
                    "started with --store tcp://HOST:PORT share one "
                    "results table, exchange new-bests, and pool "
                    "surrogate evidence through it")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8791,
                   help="TCP port (0 = ephemeral, printed once bound)")
    p.add_argument("--dir", default=None, metavar="DIR",
                   help="durable log directory (default ut.store under "
                        "the cwd); restart on the same directory "
                        "replays every acked row")
    p.add_argument("--fsync", action="store_true",
                   help="fsync each append (power-loss durability; "
                        "SIGKILL durability needs no fsync)")
    p.add_argument("--telemetry", default=None, metavar="HOST:PORT",
                   help="ship metrics/health to a `ut hub` collector "
                        "under the ut-store role")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname)s %(message)s")
    # the bench's deterministic-crash seam (same as `ut serve`)
    faults.maybe_arm_from_env()
    srv = StoreServer(args.host, args.port, args.dir,
                      fsync=args.fsync)
    shipper = None
    if args.telemetry:
        shipper = obs.ship.start(
            args.telemetry, role="ut-store",
            health_provider=lambda: srv._op_health({}))
    srv.start()
    print(f"PORT {srv.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        log.info("[%s] shutting down", srv.WIRE_NAME)
    finally:
        if shipper is not None:
            shipper.stop()
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
