"""Canonical content-addressed keys for the trial results store.

A stored QoR may only be served for a trial that would measure the same
thing: the key binds together (1) the structural space signature (the
same `repr(spec)` list the jsonl archive header carries — any change to
names/kinds/bounds invalidates position-indexed replay), (2) the
materialized config dict, and (3) the evaluation signature — what would
actually run: the command with file arguments replaced by their CONTENT
hash (so editing the tuned program invalidates its cached QoRs even if
the path is unchanged, and moving a work dir does NOT invalidate them
even though the absolute path changed) plus the pipeline stage index.

The reference's SQLite results database keys on (configuration hash)
inside a per-program database file (`/root/reference/python/uptune/
api.py` SQLAlchemy sync); content-addressing the eval side lets one
store directory safely hold results for many programs/spaces at once.
"""
from __future__ import annotations

import hashlib
import json
import os
import sys
from typing import Any, Dict, Optional, Sequence

# content hashes of command file arguments, keyed by (path, mtime, size)
# so repeated store opens don't re-read a multi-MB interpreter binary
_FILE_HASH_CACHE: Dict[tuple, str] = {}


def _norm_value(v: Any) -> Any:
    """JSON-stable form of one config value: numpy scalars unwrapped,
    tuples listified, floats kept as floats (json repr of a python
    float is deterministic)."""
    if hasattr(v, "item") and not isinstance(v, (str, bytes)):
        try:
            v = v.item()
        except (AttributeError, TypeError, ValueError):
            pass
    if isinstance(v, (list, tuple)):
        return [_norm_value(x) for x in v]
    if isinstance(v, bool) or v is None or isinstance(v, (int, str)):
        return v
    if isinstance(v, float):
        # canonical: -0.0 == 0.0 must not fork the key
        return v + 0.0
    return repr(v)


def canon_config(cfg: Dict[str, Any]) -> str:
    """Canonical JSON text of a config dict (sorted keys, normalized
    scalar types) — the per-trial part of the key."""
    return json.dumps({k: _norm_value(v) for k, v in cfg.items()},
                      sort_keys=True, separators=(",", ":"))


def _hash_file(path: str) -> str:
    st = os.stat(path)
    ck = (os.path.abspath(path), st.st_mtime_ns, st.st_size)
    h = _FILE_HASH_CACHE.get(ck)
    if h is None:
        d = hashlib.sha1()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                d.update(chunk)
        h = d.hexdigest()[:16]
        _FILE_HASH_CACHE[ck] = h
    return h


def _norm_command_arg(arg: Any) -> str:
    """One command element in content-addressed form.

    * THE running interpreter (``sys.executable``, compared by
      realpath) collapses to ``"python"`` — results must survive venv
      moves and micro-version bumps; the tuned program itself is what
      defines the measurement.  Only the interpreter identity check
      triggers this: a tuned program that happens to be NAMED
      ``python.py`` is still content-hashed;
    * any other existing file becomes ``file:<basename>:<sha1[:16]>``
      of its CONTENT, so editing the program (or a build script passed
      as an argument) invalidates its recorded QoRs;
    * everything else (flags, literals) is kept verbatim.
    """
    if not isinstance(arg, str):
        return repr(arg)
    if os.path.isfile(arg):
        try:
            if os.path.realpath(arg) == os.path.realpath(sys.executable):
                return "python"
        except OSError:
            pass
        base = os.path.basename(arg)
        try:
            return f"file:{base}:{_hash_file(arg)}"
        except OSError:
            return arg
    return arg


def eval_signature(command, stage: int = 0,
                   extra_files: Optional[Sequence[str]] = None,
                   env: Optional[Dict[str, str]] = None) -> str:
    """Canonical signature of what an evaluation runs: the normalized
    command, the stage index, the content hashes of any extra inputs
    that shape the measurement (e.g. a template source whose rendered
    copy is what actually executes), and the extra ENVIRONMENT the
    trials run under — two tunes of one program with different env
    (say CFLAGS) measure different things and must not share rows.
    PYTHONPATH is excluded: the controller wires it for child imports
    (machine-local path plumbing, like the interpreter location), so
    keeping it would fork the scope per checkout without changing the
    measurement."""
    cmd = ([command] if isinstance(command, str) else list(command))
    sig = {"cmd": [_norm_command_arg(a) for a in cmd], "stage": int(stage)}
    extras = sorted(os.path.basename(p) + ":" + _hash_file(p)
                    for p in (extra_files or []) if os.path.isfile(p))
    if extras:
        sig["extra"] = extras
    env = {k: v for k, v in (env or {}).items() if k != "PYTHONPATH"}
    if env:
        sig["env"] = {str(k): str(v) for k, v in sorted(env.items())}
    return json.dumps(sig, sort_keys=True, separators=(",", ":"))


def scope_id(space_sig: Sequence[str], eval_sig: str) -> str:
    """One hex id for a (space, evaluation) pair.  Every stored row
    carries it, so one store directory holds many programs' results and
    warm-start only ingests rows measured by THIS measurement."""
    d = hashlib.sha1()
    for s in space_sig:
        d.update(s.encode())
        d.update(b"\n")
    d.update(eval_sig.encode())
    return d.hexdigest()[:20]


def trial_key(scope: str, cfg: Dict[str, Any]) -> str:
    """The content address of one trial: scope + canonical config."""
    d = hashlib.sha1()
    d.update(scope.encode())
    d.update(b"\n")
    d.update(canon_config(cfg).encode())
    return d.hexdigest()
