"""RemoteStore: the networked client half of the cooperative search
fabric (ISSUE 18) — drop-in for `ResultStore` wherever one plugs in
today, speaking the store/server.py wire ops over one TCP connection.

Design rule: the tuning loop must NEVER block on the network.  A
`record()` is a local-table insert plus a bounded enqueue; one daemon
flusher thread owns the socket and ships queued rows batch-wise,
ack-gated, with reconnect backoff — the TelemetryShipper discipline
(obs/ship.py) applied to result rows:

* bounded queue sheds the OLDEST rows with explicit ``dropped``
  accounting (newest rows carry the most evidence),
* in-flight rows stay owned by the flusher until the server acks them,
  so a connection death mid-batch replays them after reconnect — the
  server's content-key dedup makes that replay idempotent,
* a dead server degrades the store to local-only (lookups/exchange
  serve the local table; queued rows wait) instead of stalling tells,
  and a recovered server drains the backlog transparently.

Reads are local-first: `lookup()` consults the in-memory table (rows
pulled from the server plus everything recorded locally) and only pays
one wire round-trip on a miss while connected.  `refresh()` is the
``delta`` op — the `pop_fresh_rows` fresh-foreign contract holds
exactly: rows pulled during the INITIAL open sync are a previous run's
results (warm start's job), only rows arriving after open feed the
exchange plane.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import obs
from ..obs.ship import backoff_jitter
from ..utils.net import reject_self_connect
from .keys import eval_signature, scope_id, trial_key
from .store import _finite

log = logging.getLogger("uptune_tpu")

__all__ = ["RemoteStore", "parse_addr", "QUEUE_MAX", "BATCH_MAX"]

QUEUE_MAX = 1024                # bounded write-behind (rows)
BATCH_MAX = 64                  # rows shipped per flush pass
BACKOFF_BASE = 0.25
BACKOFF_MAX = 5.0
CONNECT_TIMEOUT = 3.0
OP_TIMEOUT = 10.0


def parse_addr(addr: str) -> Tuple[str, int]:
    """``tcp://HOST:PORT`` (or bare ``HOST:PORT``) -> (host, port)."""
    a = str(addr).strip()
    if a.startswith("tcp://"):
        a = a[len("tcp://"):]
    host, sep, ptxt = a.rpartition(":")
    if not sep or not host or "/" in host:
        raise ValueError(
            f"store address must be tcp://HOST:PORT: {addr!r}")
    try:
        port = int(ptxt)
    except ValueError:
        raise ValueError(
            f"store address port is not a number: {addr!r}")
    if not 1 <= port <= 65535:
        raise ValueError(f"store address port out of range: {addr!r}")
    return host, port


class RemoteStore:
    """One process's handle on a shared `StoreServer` — the
    `ResultStore` public surface (lookup/record/refresh/scope_rows/
    best_row/pop_fresh_rows/stats/close) over TCP with local
    write-behind.

    Lock order: ``_lock`` (table + counters) -> ``_qlock`` (queue
    leaf); ``_wire_lock`` serializes socket use and is NEVER held
    while ``_lock`` is wanted (wire I/O happens with the table lock
    released, so a slow server cannot stall a lookup)."""

    def __init__(self, addr: str, space_sig: Sequence[str], command,
                 *, stage: int = 0,
                 extra_files: Optional[Sequence[str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 refresh_interval: float = 2.0,
                 fsync: Optional[bool] = None,
                 queue_max: int = QUEUE_MAX,
                 batch_max: int = BATCH_MAX,
                 connect_timeout: float = CONNECT_TIMEOUT,
                 op_timeout: float = OP_TIMEOUT,
                 backoff_base: float = BACKOFF_BASE,
                 backoff_max: float = BACKOFF_MAX):
        del fsync   # durability is the SERVER's contract (--fsync there)
        self.addr = str(addr)
        self.host, self.port = parse_addr(addr)
        self.eval_sig = eval_signature(command, stage,
                                       extra_files=extra_files, env=env)
        self.scope = scope_id(list(space_sig), self.eval_sig)
        self.refresh_interval = float(refresh_interval)
        self.instance = f"{os.getpid():d}-{os.urandom(4).hex()}"
        self.queue_max = int(queue_max)
        self.batch_max = int(batch_max)
        self.connect_timeout = float(connect_timeout)
        self.op_timeout = float(op_timeout)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self._lock = threading.RLock()
        self._qlock = threading.Lock()
        self._wire_lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._file = None
        self._rid = 0
        self._rows: Dict[str, Dict[str, Any]] = {}
        self._fresh_foreign: set = set()
        self._queue: List[Dict[str, Any]] = []
        self._pending: List[Dict[str, Any]] = []   # flusher-owned batch
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._cursor = 0
        self._incarn: Optional[str] = None
        self._last_refresh = 0.0
        self._closed = False
        self.hits = 0
        self.misses = 0
        self.recorded = 0
        self.foreign_rows = 0
        self.dropped = 0            # write-behind rows shed (bounded queue)
        self.acked = 0              # rows the server durably acked
        self.connects = 0
        self.failures = 0
        # open: one dial attempt; a dead server at open is LOUD (the
        # user asked for a shared store and is getting local-only) but
        # never fatal — the flusher keeps retrying in the background
        try:
            with self._wire_lock:
                self._connect()
            self._initial_sync()
        except (OSError, ValueError) as e:
            log.warning(
                "[ut] remote store %s unreachable at open (%s): "
                "degrading to local-only; queued rows will ship if the "
                "server comes back", self.addr, e)
        self._flusher = threading.Thread(
            target=self._loop, name="ut-rstore-flush", daemon=True)
        self._flusher.start()

    # -- wire ----------------------------------------------------------
    @property
    def connected(self) -> bool:
        return self._sock is not None

    def _connect(self) -> None:
        """Dial + hello (caller holds ``_wire_lock`` or is __init__
        before the flusher starts)."""
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout)
        try:
            # the localhost-ephemeral-port self-connect hazard the
            # serve client and shipper already guard against (PR 15)
            reject_self_connect(sock, f"store {self.addr}")
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(self.op_timeout)
            f = sock.makefile("rwb")
            self._sock, self._file = sock, f
            resp = self._request({"op": "hello",
                                  "client": self.instance,
                                  "scope": self.scope})
            incarn = resp.get("incarn")
            with self._lock:
                if incarn != self._incarn:
                    # new server incarnation: our delta cursor indexes
                    # a dead append order — restart it (the local
                    # table dedups the re-pull)
                    self._cursor = 0
                    self._incarn = incarn
                self.connects += 1
        except BaseException:
            self._sock = self._file = None
            try:
                sock.close()
            except OSError:
                pass
            raise
        obs.count("rstore.connects")

    def _drop_conn(self) -> None:
        sock, self._sock, self._file = self._sock, None, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _request(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """One request/response on the live connection (caller holds
        ``_wire_lock`` or is __init__).  Raises OSError on any
        transport or protocol failure so every caller's degrade path
        is uniform."""
        if self._file is None:
            raise OSError("remote store not connected")
        self._rid += 1
        payload = dict(payload, id=self._rid)
        try:
            self._file.write(json.dumps(
                payload, separators=(",", ":")).encode() + b"\n")
            self._file.flush()
            line = self._file.readline()
        except (OSError, ValueError) as e:
            raise OSError(f"remote store I/O failed: {e}")
        if not line:
            raise OSError("remote store closed the connection")
        try:
            resp = json.loads(line)
        except json.JSONDecodeError as e:
            raise OSError(f"remote store sent a malformed reply: {e}")
        if not isinstance(resp, dict) or not resp.get("ok"):
            err = resp.get("error") if isinstance(resp, dict) else line
            raise OSError(f"remote store refused "
                          f"{payload.get('op')}: {err}")
        return resp

    def _initial_sync(self) -> None:
        """Pull the scope's existing rows at open.  These are a
        previous run's results: merged as NON-fresh so warm start sees
        them via `scope_rows()` but the exchange plane does not re-pull
        history as migration (the ResultStore ``_loading`` rule)."""
        n = self._pull_delta(fresh=False)
        if n:
            log.info("[ut] remote store %s: synced %d existing row(s)",
                     self.addr, n)

    def _pull_delta(self, fresh: bool) -> int:
        """Loop the ``delta`` op until drained (caller must NOT hold
        ``_lock``; takes ``_wire_lock``)."""
        total = 0
        with self._wire_lock:
            if self._sock is None:
                return 0
            more = True
            while more:
                with self._lock:
                    cur, inc = self._cursor, self._incarn
                resp = self._request({"op": "delta", "scope": self.scope,
                                      "cursor": cur, "incarn": inc,
                                      "src": self.instance})
                rows = resp.get("rows") or []
                with self._lock:
                    self._cursor = int(resp.get("cursor", cur))
                    self._incarn = resp.get("incarn", inc)
                    for row in rows:
                        if self._merge_foreign(row, fresh):
                            total += 1
                more = bool(resp.get("more")) and bool(rows)
        return total

    def _merge_foreign(self, row: Any, fresh: bool) -> bool:
        """First-finite-wins merge of a server row (caller holds
        ``_lock``)."""
        if not isinstance(row, dict):
            return False
        k = row.get("k")
        if not isinstance(k, str):
            return False
        cur = self._rows.get(k)
        if cur is not None and (_finite(cur.get("qor"))
                                or not _finite(row.get("qor"))):
            return False
        self._rows[k] = row
        self.foreign_rows += 1
        if fresh:
            self._fresh_foreign.add(k)
        return True

    # -- ResultStore surface: reads ------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def __bool__(self) -> bool:
        # An open-but-empty store must stay truthy: ``if store:`` call
        # sites would otherwise never record the first row.
        return True

    def lookup(self, cfg: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Local table first; one wire lookup on a local miss while
        connected (a foreign sibling may have measured this config
        since the last delta pull)."""
        k = trial_key(self.scope, cfg)
        with self._lock:
            row = self._rows.get(k)
            if row is not None and _finite(row.get("qor")):
                self.hits += 1
                obs.count("store.hits")
                return row
        if self._sock is not None:
            try:
                with self._wire_lock:
                    if self._sock is not None:
                        resp = self._request({"op": "lookup", "k": k})
                        row = resp.get("row")
                    else:
                        row = None
            except OSError:
                with self._wire_lock:
                    self._drop_conn()
                row = None
            if isinstance(row, dict) and _finite(row.get("qor")):
                with self._lock:
                    # remote hit: cache it, NOT fresh (a served memo
                    # is not an elite-migration event)
                    self._merge_foreign(row, fresh=False)
                    self.hits += 1
                    obs.count("store.hits")
                return row
        with self._lock:
            self.misses += 1
            obs.count("store.misses")
            return None

    def scope_rows(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r for r in self._rows.values()
                    if r.get("scope") == self.scope
                    and _finite(r.get("qor"))]

    def best_row(self, sense: str = "min") -> Optional[Dict[str, Any]]:
        rows = self.scope_rows()
        if not rows:
            return None
        pick = min if sense == "min" else max
        return pick(rows, key=lambda r: float(r["qor"]))

    def pop_fresh_rows(self) -> List[Dict[str, Any]]:
        """Finite in-scope rows pulled from the server since open (the
        elite-migration feed); consuming clears the set."""
        with self._lock:
            if not self._fresh_foreign:
                return []
            keys, self._fresh_foreign = self._fresh_foreign, set()
            out = []
            for k in keys:
                r = self._rows.get(k)
                if r is not None and r.get("scope") == self.scope \
                        and _finite(r.get("qor")):
                    out.append(r)
            return out

    def refresh(self) -> int:
        """Pull the server's delta feed (reconnect handled by the
        flusher, not here — refresh on a dead connection is a cheap
        no-op, never a dial)."""
        self._last_refresh = time.monotonic()
        try:
            with obs.span("store.refresh") as sp:
                n = self._pull_delta(fresh=True)
                sp.set(rows=n)
            return n
        except OSError as e:
            with self._wire_lock:
                self._drop_conn()
            log.debug("[ut] remote store %s refresh failed: %s",
                      self.addr, e)
            return 0

    def maybe_refresh(self) -> int:
        if time.monotonic() - self._last_refresh < self.refresh_interval:
            return 0
        return self.refresh()

    # -- ResultStore surface: writes -----------------------------------
    def record(self, cfg: Dict[str, Any], qor: Optional[float],
               dur: float = 0.0, *, u: Optional[Sequence[float]] = None,
               perms: Optional[Sequence[Sequence[int]]] = None,
               source: str = "") -> Optional[Dict[str, Any]]:
        """Local-table insert + bounded enqueue; NEVER dials or blocks
        on the wire (the tell path's latency contract).  Returns the
        row, or None on idempotent re-records — the ResultStore
        contract exactly."""
        with self._lock:
            k = trial_key(self.scope, cfg)
            cur = self._rows.get(k)
            if cur is not None and (_finite(cur.get("qor"))
                                    or not _finite(qor)):
                return None
            row: Dict[str, Any] = {
                "k": k, "scope": self.scope, "cfg": cfg,
                "qor": (float(qor) if _finite(qor) else None),
                "dur": round(float(dur), 6), "t": round(time.time(), 3),
                "src": source or self.instance,
            }
            if u is not None:
                row["u"] = [float(x) for x in u]
            if perms is not None:
                row["perms"] = [[int(i) for i in p] for p in perms]
            self._rows[k] = row
            self.recorded += 1
            obs.count("store.recorded")
        self._offer(row)
        return row

    def _offer(self, row: Dict[str, Any]) -> None:
        """Bounded enqueue under the queue leaf lock, shedding the
        OLDEST row when full (the shipper's drop rule: newest evidence
        wins) with explicit accounting."""
        with self._qlock:
            self._queue.append(row)
            while len(self._queue) > self.queue_max:
                self._queue.pop(0)
                self.dropped += 1
                obs.count("rstore.client_dropped")
        self._wake.set()

    def ingest_archive(self, path: str) -> int:
        """Replay a driver jsonl trial archive through record() (rows
        ship to the server like any other)."""
        n = 0
        try:
            with open(path, "rb") as f:
                for line in f:
                    if not line.endswith(b"\n"):
                        break   # torn tail
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break
                    if "cfg" not in rec:
                        continue   # space_sig header row
                    if self.record(rec["cfg"], rec.get("qor"),
                                   rec.get("time", 0.0),
                                   u=rec.get("u"), perms=rec.get("perms"),
                                   source="archive") is not None:
                        n += 1
        except OSError:
            return n
        return n

    # -- flusher -------------------------------------------------------
    def _loop(self) -> None:
        """The write-behind daemon (obs/ship.py discipline): wait for
        work or the poll tick, reconnect with jittered exponential
        backoff, ship ``_pending`` (retried-before-new, ack-gated)."""
        backoff = self.backoff_base
        while True:
            # event-driven with a 0.2s poll floor: a record() wakes the
            # flusher immediately, so sibling processes see new rows at
            # wire latency, not at the poll tick (what makes a tight
            # elite-migration cadence real instead of aspirational)
            self._wake.wait(0.2)
            stopping = self._stop.is_set()
            self._wake.clear()
            with self._qlock:
                have = bool(self._queue) or bool(self._pending)
            if have:
                with self._wire_lock:
                    dead = self._sock is None
                if dead:
                    try:
                        with self._wire_lock:
                            if self._sock is None:
                                self._connect()
                        backoff = self.backoff_base
                        log.info("[ut] remote store %s reconnected",
                                 self.addr)
                    except (OSError, ValueError):
                        with self._lock:
                            self.failures += 1
                        if stopping:
                            break   # terminal: server still dead
                        self._stop.wait(backoff_jitter(backoff))
                        backoff = min(backoff * 2, self.backoff_max)
                        continue
                try:
                    self._flush()
                    backoff = self.backoff_base
                except OSError as e:
                    with self._wire_lock:
                        self._drop_conn()
                    with self._lock:
                        self.failures += 1
                    log.debug("[ut] remote store %s flush failed: %s",
                              self.addr, e)
                    if not stopping:
                        self._stop.wait(backoff_jitter(backoff))
                        backoff = min(backoff * 2, self.backoff_max)
            if stopping:
                # final cut AFTER a flush attempt: rows queued before
                # close() had their chance to ship
                break

    def _flush(self) -> None:
        """Ship up to batch_max rows, ack-gated.  ``_pending`` is
        flusher-owned: rows move queue -> pending under ``_qlock``,
        leave pending only on server ack, and survive a connection
        death for replay after reconnect (the server's content-key
        dedup absorbs re-sends)."""
        while True:
            with self._qlock:
                take = self.batch_max - len(self._pending)
                if take > 0 and self._queue:
                    self._pending.extend(self._queue[:take])
                    del self._queue[:take]
                batch = list(self._pending)
            if not batch:
                return
            with self._wire_lock:
                if self._sock is None:
                    raise OSError("remote store not connected")
                for row in batch:
                    resp = self._request({"op": "record", "row": row})
                    if not resp.get("acked"):
                        raise OSError(
                            f"remote store did not ack row {row['k']}")
                    with self._qlock:
                        # ack-gated removal: identity, not equality —
                        # the queue may hold a same-key retry row
                        self._pending = [r for r in self._pending
                                         if r is not row]
                    with self._lock:
                        self.acked += 1
                    obs.count("rstore.client_acked")

    # -- lifecycle -----------------------------------------------------
    def flush_wait(self, timeout: float = 5.0) -> bool:
        """Best-effort wait until the write-behind queue drains (tests
        and orderly shutdowns; the tuning loop never calls this)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._qlock:
                if not self._queue and not self._pending:
                    return True
            self._wake.set()
            time.sleep(0.02)
        return False

    def compact(self) -> int:
        """Server-side storage is one log; nothing to compact from the
        client.  Returns the local row count for parity."""
        return len(self)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._wake.set()
        self._flusher.join(timeout=self.op_timeout)
        with self._wire_lock:
            self._drop_conn()
        with self._qlock:
            left = len(self._queue) + len(self._pending)
        if left:
            log.warning("[ut] remote store %s closed with %d unshipped "
                        "row(s) (server unreachable)", self.addr, left)

    def __enter__(self) -> "RemoteStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> Dict[str, Any]:
        addr, scope = self.addr, self.scope   # immutable after __init__
        connected = self.connected
        with self._qlock:
            queued = len(self._queue) + len(self._pending)
        with self._lock:
            return {"rows": len(self._rows), "hits": self.hits,
                    "misses": self.misses, "recorded": self.recorded,
                    "foreign_rows": self.foreign_rows,
                    "scope": scope,
                    "remote": {"addr": addr,
                               "connected": connected,
                               "queued": queued,
                               "dropped": self.dropped,
                               "acked": self.acked,
                               "connects": self.connects,
                               "failures": self.failures}}
