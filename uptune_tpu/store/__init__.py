"""Content-addressed trial results store (store.py) and its key
derivation (keys.py): cache hits instead of repeated external builds,
cross-tune warm starts, and multi-instance best-exchange over one
shared directory.  See docs/STORE.md."""
from .keys import (canon_config, eval_signature, scope_id,  # noqa: F401
                   trial_key)
from .store import ResultStore  # noqa: F401

__all__ = ["ResultStore", "canon_config", "eval_signature", "scope_id",
           "trial_key"]
