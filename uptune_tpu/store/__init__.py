"""Content-addressed trial results store (store.py), its key
derivation (keys.py), and the networked cooperative-store plane
(server.py + remote.py): cache hits instead of repeated external
builds, cross-tune warm starts, and multi-instance best-exchange over
one shared directory OR one shared TCP store server.  See
docs/STORE.md."""
from .keys import (canon_config, eval_signature, scope_id,  # noqa: F401
                   trial_key)
from .store import ResultStore  # noqa: F401

__all__ = ["ResultStore", "canon_config", "eval_signature", "scope_id",
           "trial_key", "is_remote_addr", "open_store"]


def is_remote_addr(base) -> bool:
    """True when a store base names a store SERVER (``tcp://...``)
    rather than a directory."""
    return isinstance(base, str) and base.startswith("tcp://")


def open_store(base, space_sig, command, **kw):
    """The one store factory every plug-in site routes through: a
    ``tcp://HOST:PORT`` base opens a `RemoteStore` on the cooperative
    store server, anything else a filesystem `ResultStore` on that
    directory.  Keyword arguments are the shared constructor surface
    (stage/extra_files/env/refresh_interval/fsync)."""
    if is_remote_addr(base):
        from .remote import RemoteStore   # lazy: keeps dir-store imports lean
        return RemoteStore(base, space_sig, command, **kw)
    return ResultStore(base, space_sig, command, **kw)
